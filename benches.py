"""Benchmark configs 1/2/4/5 from BASELINE.md, invoked by
``bench.py --config <name>``; config 3 (bert_base, the driver default)
lives in bench.py itself.

Every config follows bench.py's honesty contract: slope timing with a
host-readback barrier (the axon tunnel's ``block_until_ready`` can
acknowledge before remote execution completes — see bench.py), median
slope across trials, and ``mfu <= 1.0`` asserts wherever an MFU is
computed. The reference publishes no numeric
baselines (BASELINE.md), so ``vs_baseline`` is MFU/0.40 where an MFU
target applies and 1.0 (self-referential) for the throughput-only
configs.

Analog of the reference's config-driven op benchmark harness
(/root/reference/paddle/fluid/operators/benchmark/op_tester.cc — there a
config file picks the op; here --config picks the model-level workload).
"""

import numpy as np

from bench import (_assert_sane_mfu, _emit, _peak_flops, _read_back,
                   _timed_steps)

CONFIGS = {}


def config(name):
    def deco(fn):
        CONFIGS[name] = fn
        return fn
    return deco


def run_config(name: str, on_tpu: bool, batch=None) -> None:
    if name not in CONFIGS:
        raise SystemExit(
            f"unknown bench config {name!r}; available: "
            f"{['bert_base'] + sorted(CONFIGS)}")
    import inspect
    fn = CONFIGS[name]
    if batch is None:
        fn(on_tpu)
        return
    if "batch_override" not in inspect.signature(fn).parameters:
        raise SystemExit(
            f"config {name!r} does not support --batch; it would run at "
            f"its hardcoded batch while reporting yours (honesty "
            f"contract: refuse rather than mislead)")
    fn(on_tpu, batch_override=batch)


@config("mnist_lenet")
def bench_mnist_lenet(on_tpu):
    """BASELINE config 1: eager dygraph LeNet training — exercises the
    tape engine, nn, optimizer end-to-end (throughput, no MFU target)."""
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import to_tensor
    from paddle1_tpu.vision.models.lenet import LeNet

    batch = 64 if on_tpu else 16
    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    rng = np.random.default_rng(0)
    # inputs staged to device once (eager mode re-dispatches every op
    # through the relay already; re-uploading the pixels per step would
    # add relay bandwidth on top — see bench_resnet50_dp)
    xt = to_tensor(rng.standard_normal(
        (batch, 1, 28, 28)).astype(np.float32))
    yt = to_tensor(rng.integers(0, 10, (batch,)).astype(np.int64))

    def step():
        out = model(xt)
        loss = paddle.nn.functional.cross_entropy(out, yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    _read_back(step())  # warmup, flushed to completion
    n_steps = 20 if on_tpu else 3
    times, loss = _timed_steps(step, n_steps)
    import statistics
    dt = statistics.median(times)
    _emit("mnist_lenet_eager_samples_per_sec", batch / dt, "samples/s", 1.0,
          {"batch": batch, "steps": n_steps,
           "step_ms_median": round(dt * 1e3, 2),
           "loss": float(loss.numpy()), "mode": "eager"})


@config("resnet50_dp")
def bench_resnet50_dp(on_tpu, batch_override=None):
    """BASELINE config 2: ResNet-50 data-parallel over all local devices
    (compiled engine; GSPMD inserts the grad all-reduce over ICI)."""
    import jax
    import statistics
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh
    from paddle1_tpu.vision.models.resnet import resnet50

    devs = jax.devices()
    img = 224 if on_tpu else 32
    # batch_override is the GLOBAL batch (same meaning as bert_base's
    # --batch); it must divide the device count
    if batch_override is not None and batch_override % len(devs):
        raise SystemExit(f"--batch {batch_override} not divisible by "
                         f"{len(devs)} devices")
    per_dev = (32 if on_tpu else 2) if batch_override is None \
        else batch_override // len(devs)
    batch = per_dev * len(devs)

    model = resnet50()
    # lr kept small: the bench replays ONE batch, where the ImageNet lr
    # schedule diverges; the timing is lr-independent
    opt = paddle.optimizer.Momentum(learning_rate=1e-3, momentum=0.9,
                                    parameters=model.parameters())

    def loss_fn(m, b):
        out = m(Tensor(b["x"]))
        return paddle.nn.functional.cross_entropy(out, Tensor(b["y"]))

    mesh = build_mesh(dp=len(devs), devices=devs)
    engine = ParallelEngine(model, opt, loss_fn, mesh=mesh,
                            amp_dtype="bfloat16" if on_tpu else None)
    rng = np.random.default_rng(0)

    # Batches are PRE-STAGED on device and cycled — measuring the
    # training step, not the relay's host->device bandwidth. The r5
    # profiler trace (chip_results/conv_probe_trace.txt) showed the
    # 19.3 MB float32 image batch costs ~575 ms/step through the axon
    # tunnel while the compiled step itself runs in ~15 ms: that relay
    # artifact — not conv throughput — was the whole "conv MFU mystery"
    # of rounds 3-4. Real training feeds from the DataLoader's
    # device-prefetch path (io/dataloader.py), which overlaps uploads
    # with compute; cycling staged batches is the single-chip analog.
    def mk():
        return {"x": rng.standard_normal(
                    (batch, 3, img, img)).astype(np.float32),
                "y": rng.integers(0, 1000, (batch,)).astype(np.int64)}
    staged = [engine.shard_batch(mk()) for _ in range(2)]
    it = {"i": 0}

    def step():
        it["i"] += 1
        return engine.step(staged[it["i"] % len(staged)])

    _read_back(step())  # compile, flushed to completion
    times, loss = _timed_steps(step, 10 if on_tpu else 3)
    dt = statistics.median(times)

    # ResNet-50 @224 fwd ≈ 4.1e9 FLOPs/sample (2×MACs); bwd ≈ 2× fwd
    flops_sample = 4.1e9 * (img / 224.0) ** 2 * 3.0
    mfu = (flops_sample * batch / dt) / (_peak_flops(devs[0]) * len(devs))
    detail = {"batch": batch, "img": img, "devices": len(devs),
              "step_ms_median": round(dt * 1e3, 2), "mfu": round(mfu, 4),
              "amp": "bfloat16" if on_tpu else "none",
              "input": "device-staged (2-batch cycle; see docstring)",
              "loss": float(loss)}
    _assert_sane_mfu(mfu, detail, step_fn=step)
    _emit("resnet50_dp_samples_per_sec", batch / dt, "samples/s",
          mfu / 0.40, detail)


@config("ernie_sharded")
def bench_ernie_sharded(on_tpu):
    """BASELINE config 4: ERNIE-1.5B-class training with ZeRO-2 sharding
    (reduce-scatter over ICI). Published memory math
    (tools/memory_math.py): full depth needs ~28 GiB (f32 masters +
    Adam moments + grads + bf16 copy) — a single 16-GiB v5e cannot hold
    it; ZeRO-2 fits it from 4 chips (~14.4 GiB/chip). On one device
    this measures the LARGEST DEPTH THAT FITS: 10 of 24 layers at full
    width (~12.9 GiB peak) — per-layer compute identical to full scale,
    so full-depth throughput projects as value × (proxy step FLOPs /
    full step FLOPs) with the same MFU; the detail dict carries that
    projection. With >= 4 devices the full depth runs sharded; the
    full-scale sharded compile path is validated on the virtual
    8-device mesh by tests/test_parallel_engine.py, test_sharding_remat
    and __graft_entry__.py."""
    import jax
    import statistics
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh
    from paddle1_tpu.text.models import (BertForPretraining,
                                         BertPretrainingCriterion,
                                         apply_megatron_sharding,
                                         ernie_1p5b)

    devs = jax.devices()
    n = len(devs)
    # memory math (tools/memory_math.py): 24 layers fit from 4 chips
    # under ZeRO-2; one chip holds at most 10 full-width layers
    layers = 24 if n >= 4 else (10 if on_tpu else 6)
    seq = 512 if on_tpu else 64
    per_dev = 4 if on_tpu else 1
    batch = per_dev * n

    enc = ernie_1p5b(num_hidden_layers=layers,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0,
                     **({} if on_tpu else
                        {"hidden_size": 256, "num_attention_heads": 4,
                         "intermediate_size": 1024, "vocab_size": 1024}))
    model = BertForPretraining(enc)
    crit = BertPretrainingCriterion(enc.vocab_size)
    if n > 1:
        apply_megatron_sharding(model)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    def loss_fn(m, b):
        scores, rel = m(Tensor(b["ids"]))
        return crit(scores, rel, Tensor(b["mlm"]), Tensor(b["nsp"]))

    mesh = build_mesh(dp=1, sharding=n, devices=devs)
    engine = ParallelEngine(model, opt, loss_fn, mesh=mesh, zero_stage=2,
                            amp_dtype="bfloat16" if on_tpu else None)
    rng = np.random.default_rng(0)
    v = enc.vocab_size
    b = {"ids": rng.integers(1, v, (batch, seq)).astype(np.int32),
         "mlm": rng.integers(0, v, (batch, seq)).astype(np.int32),
         "nsp": rng.integers(0, 2, (batch,)).astype(np.int32)}

    _read_back(engine.step(b))  # compile, flushed to completion
    times, loss = _timed_steps(lambda: engine.step(b), 10 if on_tpu else 2)
    dt = statistics.median(times)

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    attn = 12 * layers * batch * seq * seq * enc.hidden_size
    flops_step = 6 * n_params * batch * seq + attn
    mfu = (flops_step / dt) / (_peak_flops(devs[0]) * n)
    detail = {"batch": batch, "seq": seq, "layers": layers,
              "params": n_params, "devices": n, "zero_stage": 2,
              "step_ms_median": round(dt * 1e3, 2), "mfu": round(mfu, 4),
              "proxy": layers != 24, "loss": float(loss)}
    if layers != 24 and on_tpu:
        # proxy basis (tools/memory_math.py): same width => same MFU;
        # full-depth samples/s = measured × FLOPs(proxy)/FLOPs(24L).
        # Per-layer param count inlined (NOT imported) so an import
        # problem can never eat the measurement before the JSON emits.
        H, I = enc.hidden_size, enc.intermediate_size
        per_layer = (4 * H * H + 4 * H) + (H * I + I + I * H + H) + 4 * H
        full_n = n_params + (24 - layers) * per_layer
        attn24 = 12 * 24 * batch * seq * seq * H
        flops_full = 6 * full_n * batch * seq + attn24
        detail["proxy_basis"] = ("largest depth fitting 16GiB "
                                 "(tools/memory_math.py)")
        detail["projected_full_depth_samples_per_sec"] = round(
            (batch / dt) * flops_step / flops_full, 2)
    _assert_sane_mfu(mfu, detail,
                     step_fn=lambda: engine.step(b))
    _emit("ernie_1p5b_zero2_samples_per_sec", batch / dt, "samples/s",
          mfu / 0.40, detail)


@config("yolov3_infer")
def bench_yolov3_infer(on_tpu):
    """BASELINE config 5: PP-YOLO-class detection inference — conv stack
    jitted on device; box decode + NMS measured separately (they run
    host-side at deploy time, matching the reference's split)."""
    import jax
    import statistics
    import time
    import paddle1_tpu as paddle
    from paddle1_tpu.autograd import engine as ag
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.vision.models.yolo import yolov3

    batch = 8 if on_tpu else 1
    img = 416 if on_tpu else 128
    model = yolov3(num_classes=80)
    model.eval()
    params = model.functional_state()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, 3, img, img)).astype(np.float32)

    import jax.numpy as jnp

    @jax.jit
    def fwd(params, x):
        with ag.no_grad(), model.load_functional_state(params):
            return [o.data for o in model(Tensor(x))]

    # stage the input on device once: the 16.6 MB float32 batch costs
    # ~500 ms/step through the axon relay vs ~ms of compute (the r5
    # trace root-cause — see bench_resnet50_dp); deploy-time serving
    # keeps a device-resident input buffer the same way
    xd = jnp.asarray(x)
    _read_back(fwd(params, xd))  # compile, flushed
    times, outs = _timed_steps(lambda: fwd(params, xd),
                               20 if on_tpu else 3)
    dt = statistics.median(times)

    img_size = np.tile([[img, img]], (batch, 1)).astype(np.int32)  # [B,2]
    with ag.no_grad():
        # warm pass first: deploy-time serving is steady-state, and the
        # eager decode/NMS ops compile per shape on first touch
        model.postprocess([Tensor(o) for o in outs], Tensor(img_size))
        t0 = time.perf_counter()
        results = model.postprocess([Tensor(o) for o in outs],
                                    Tensor(img_size))
        post_ms = (time.perf_counter() - t0) * 1e3

    _emit("yolov3_infer_images_per_sec", batch / dt, "images/s", 1.0,
          {"batch": batch, "img": img,
           "step_ms_median": round(dt * 1e3, 2),
           "postprocess_ms_per_batch": round(post_ms, 2),
           "detections_img0": int(np.asarray(
               results[0][0].numpy()).shape[0]) if results else 0})


@config("allreduce_busbw")
def bench_allreduce_busbw(on_tpu, batch_override=None):
    """BASELINE primary metric's fleet half: allreduce bus bandwidth.

    Payload sweep of in-graph ``psum`` over every visible device
    (nccl-tests conventions: algbw = per-rank payload / time,
    busbw = algbw * 2(n-1)/n — the wire traffic of a ring). On one
    chip there is no ICI to measure: the run still executes (the
    numbers are the on-device reduction path) but is loudly marked
    ``blocked: single-chip``. On the virtual CPU mesh this smokes the
    full multi-device path; real numbers land whenever multi-chip
    hardware exists."""
    import statistics
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))
    sizes_mb = [1, 4, 16, 64] if on_tpu else [1, 4]
    if batch_override:  # --batch reinterprets as max payload MB
        sizes_mb = [m for m in sizes_mb if m <= batch_override] \
            or [batch_override]
    sweep = []
    for mb in sizes_mb:
        elems = mb * (1 << 20) // 4
        x = jax.device_put(
            jnp.ones((n, elems), jnp.float32),
            NamedSharding(mesh, P("x", None)))

        @jax.jit
        def allreduce(v):
            return shard_map(
                lambda s: jax.lax.psum(s, "x") * (1.0 / n),
                mesh=mesh, in_specs=P("x", None),
                out_specs=P("x", None))(v)

        state = {"x": x}

        def step_fn():
            state["x"] = allreduce(state["x"])  # chained dependency
            return state["x"]

        _read_back(allreduce(x))  # compile outside the timing
        try:
            times, _ = _timed_steps(step_fn, 8 if on_tpu else 4)
            dt = statistics.median(times)
        except AssertionError:
            if n > 1:
                raise
            # single chip: psum over one device is (near) a no-op, so
            # the slope degenerates; time plain calls instead — the run
            # is marked `blocked: single-chip` below regardless
            import time as _time
            best = None
            for _ in range(10):
                t0 = _time.perf_counter()
                _read_back(step_fn())
                best = min(best or 1e9, _time.perf_counter() - t0)
            dt = best
        payload = elems * 4  # bytes per rank
        algbw = payload / dt
        busbw = algbw * (2 * (n - 1) / n)
        sweep.append({"payload_mb": mb,
                      "time_us": round(dt * 1e6, 1),
                      "algbw_gbps": round(algbw / 1e9, 3),
                      "busbw_gbps": round(busbw / 1e9, 3)})
    best = max(s["busbw_gbps"] for s in sweep)
    detail = {"device": str(devs[0].device_kind
                            if hasattr(devs[0], "device_kind")
                            else devs[0].platform),
              "n_devices": n, "sweep": sweep,
              "convention": "nccl-tests: busbw = algbw * 2(n-1)/n"}
    if n == 1:
        detail["blocked"] = ("single-chip: no ICI to measure — busbw "
                             "is 0 by the ring formula; sweep times "
                             "are the on-device reduction path only")
    _emit("fleet_allreduce_busbw", best, "GB/s", 1.0, detail)
