"""Serving runtime (ISSUE 4): dynamic micro-batching inference server —
shape-bucketed executables, batcher parity, admission control,
deadlines, chaos-driven shed paths, graceful SIGTERM drain, metrics.

Fast cases ride tier-1; the loaded smoke (p99 bound) and the
subprocess/Supervisor SIGTERM drains are slow-marked (CI's serving
lane runs them, like --elastic)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core import chaos, health
from paddle1_tpu.core.flags import flags_guard
from paddle1_tpu.serving import (DeadlineExceeded, InferenceEngine,
                                 Server, ServerClosed, ServerOverloaded,
                                 ServingMetrics, resolve_buckets)


@pytest.fixture(autouse=True)
def _isolate():
    health.reset()
    chaos.reset()
    yield
    health.reset()
    chaos.reset()


def _mlp(seed=0, din=8, dout=4):
    paddle.seed(seed)
    m = paddle.nn.Sequential(paddle.nn.Linear(din, 16), paddle.nn.ReLU(),
                             paddle.nn.Linear(16, dout))
    m.eval()
    return m


def _eager(model, x):
    from paddle1_tpu.core.tensor import to_tensor
    return np.asarray(model(to_tensor(x)).numpy())


class TestMetrics:
    def test_counter_histogram_snapshot(self):
        m = ServingMetrics()
        m.counter("requests_total").inc()
        m.counter("requests_total").inc(2)
        h = m.histogram("queue_ms")
        for v in range(100):
            h.observe(float(v))
        m.record_response(3)
        snap = m.snapshot()
        assert snap["counters"]["requests_total"] == 3
        s = snap["histograms"]["queue_ms"]
        assert s["count"] == 100 and s["max"] == 99.0
        assert 48 <= s["p50"] <= 51 and 97 <= s["p99"] <= 99
        assert snap["qps"] > 0
        text = m.render_text()
        assert "p1t_serving_requests_total 3" in text
        assert "p1t_serving_queue_ms_p99" in text

    def test_histogram_empty(self):
        h = ServingMetrics().histogram("x")
        assert h.percentile(99) == 0.0
        assert h.summary()["count"] == 0

    def test_render_text_prometheus_exposition(self):
        """Snapshot of the exposition format (ISSUE 7 satellite): a
        histogram exports as a Prometheus summary — TYPE header,
        quantile-labeled gauges, and RAW monotone _sum/_count series so
        rate(..._sum[1m]) / rate(..._count[1m]) works — plus the legacy
        stat gauges for existing scrapers."""
        m = ServingMetrics()
        m.counter("requests_total").inc(7)
        h = m.histogram("e2e_ms")
        h.observe(1.5)
        h.observe(2.25)
        text = m.render_text()
        lines = text.splitlines()
        assert "p1t_serving_requests_total 7" in lines
        assert "# TYPE p1t_serving_e2e_ms summary" in lines
        assert 'p1t_serving_e2e_ms{quantile="0.5"} 1.5' in lines
        assert 'p1t_serving_e2e_ms{quantile="0.95"} 2.25' in lines
        assert 'p1t_serving_e2e_ms{quantile="0.99"} 2.25' in lines
        # raw, unrounded totals (repr of the float sum, exact int count)
        assert "p1t_serving_e2e_ms_sum 3.75" in lines
        assert "p1t_serving_e2e_ms_count 2" in lines
        # legacy gauge lines survive for existing scrapers
        assert any(l.startswith("p1t_serving_e2e_ms_p99 ")
                   for l in lines)
        assert any(l.startswith("p1t_serving_e2e_ms_max ")
                   for l in lines)
        # the raw sum must not be the 4-digit-rounded summary value
        h2 = ServingMetrics()
        hh = h2.histogram("t")
        for _ in range(3):
            hh.observe(0.1)  # 0.30000000000000004 raw
        assert f"p1t_serving_t_sum {repr(0.1 + 0.1 + 0.1)}" \
            in h2.render_text()

    def test_generation_metrics_exposition(self):
        """ISSUE 9 satellite, extending the PR 7 format snapshot: the
        generation counters/gauge/histogram export — a gauge gets a
        ``# TYPE ... gauge`` header and a plain sample line, the
        per-request tokens_per_s rides the summary format, and
        tokens_generated_total is an ordinary counter line."""
        m = ServingMetrics()
        m.counter("tokens_generated_total").inc(37)
        m.gauge("slot_occupancy").set(0.75)
        m.histogram("tokens_per_s").observe(120.0)
        m.histogram("tokens_per_s").observe(80.0)
        lines = m.render_text().splitlines()
        assert "p1t_serving_tokens_generated_total 37" in lines
        assert "# TYPE p1t_serving_slot_occupancy gauge" in lines
        assert "p1t_serving_slot_occupancy 0.75" in lines
        assert "# TYPE p1t_serving_tokens_per_s summary" in lines
        assert "p1t_serving_tokens_per_s_count 2" in lines
        assert "p1t_serving_tokens_per_s_sum 200.0" in lines
        # snapshot carries the gauge; labeled multi-child pages drop
        # the TYPE header but keep the labeled sample (PR 7 rule)
        assert m.snapshot()["gauges"]["slot_occupancy"] == 0.75
        labeled = m.render_text(label=("version", "v2"),
                                type_headers=False)
        assert 'p1t_serving_slot_occupancy{version="v2"} 0.75' \
            in labeled.splitlines()
        assert "# TYPE p1t_serving_slot_occupancy gauge" not in labeled

    def test_gauges_merge_worst_child(self):
        from paddle1_tpu.serving import merge_snapshots
        a, b = ServingMetrics(), ServingMetrics()
        a.gauge("slot_occupancy").set(0.25)
        b.gauge("slot_occupancy").set(0.9)
        a.counter("tokens_generated_total").inc(10)
        b.counter("tokens_generated_total").inc(5)
        agg = merge_snapshots([a.snapshot(), b.snapshot()])
        assert agg["gauges"]["slot_occupancy"] == 0.9
        assert agg["counters"]["tokens_generated_total"] == 15


class TestBuckets:
    def test_auto_powers_of_two(self):
        assert resolve_buckets(None, 16) == (1, 2, 4, 8, 16)
        assert resolve_buckets(None, 12) == (1, 2, 4, 8, 12)

    def test_explicit_and_flag(self):
        assert resolve_buckets((8, 1, 4, 4), None) == (1, 4, 8)
        with flags_guard(serve_buckets="2,6"):
            assert resolve_buckets(None, None) == (2, 6)
        with pytest.raises(Exception, match="comma-separated"):
            with flags_guard(serve_buckets="2,six"):
                resolve_buckets(None, None)

    def test_bucket_for_and_oversize(self):
        eng = InferenceEngine(lambda x: x, buckets=(1, 4, 8))
        assert eng.bucket_for(1) == 1
        assert eng.bucket_for(3) == 4
        assert eng.bucket_for(8) == 8
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="largest bucket"):
            eng.bucket_for(9)


class TestInferenceEngine:
    def test_ragged_parity_and_one_compile_per_bucket(self):
        model = _mlp(0)
        eng = InferenceEngine(model, buckets=(1, 4, 8),
                              input_specs=[((8,), "float32")])
        rng = np.random.default_rng(0)
        for rows in (1, 3, 5, 8, 3, 5, 1):  # repeats hit warm buckets
            x = rng.standard_normal((rows, 8)).astype(np.float32)
            out = eng.infer([x])[0]
            assert out.shape == (rows, 4)
            np.testing.assert_allclose(out, _eager(model, x), rtol=1e-5,
                                       atol=1e-6)
        # buckets touched: 1 (rows 1), 4 (rows 3), 8 (rows 5, 8) —
        # exactly one compile each despite 7 dispatches
        assert eng.compile_counts == {1: 1, 4: 1, 8: 1}
        assert sum(eng.dispatch_counts.values()) == 7
        assert eng.cache_stats()["misses"] == 3

    def test_warmup_precompiles_every_bucket(self):
        eng = InferenceEngine(_mlp(1), buckets=(1, 2, 4),
                              input_specs=[((8,), "float32")])
        assert eng.warm_up() == 3
        assert eng.compile_counts == {1: 1, 2: 1, 4: 1}
        x = np.zeros((2, 8), np.float32)
        eng.infer([x])
        assert eng.compile_counts[2] == 1  # served warm, no recompile

    def test_retrace_guard_warns_once_on_new_inner_sig(self):
        import warnings
        eng = InferenceEngine(lambda x: x * 2, buckets=(1, 4))
        eng.infer([np.zeros((1, 8), np.float32)])
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            eng.infer([np.zeros((1, 9), np.float32)])   # new inner dim
            eng.infer([np.zeros((1, 10), np.float32)])  # third sig
        msgs = [r for r in rec if "retracing" in str(r.message)]
        assert len(msgs) == 1  # warn-once (jit_retrace_warn idiom)

    def test_pad_rows_do_not_leak(self):
        # zero padding must never change the real rows' outputs
        model = _mlp(2)
        eng = InferenceEngine(model, buckets=(8,))
        x = np.random.default_rng(1).standard_normal((3, 8)).astype(
            np.float32)
        np.testing.assert_allclose(eng.infer([x])[0], _eager(model, x),
                                   rtol=1e-5, atol=1e-6)


class TestServerBatching:
    def test_mixed_size_parity_across_ragged_boundaries(self):
        model = _mlp(3)
        srv = Server(model, max_batch=8, buckets=(1, 4, 8),
                     batch_timeout_ms=5, queue_depth=64).start()
        rng = np.random.default_rng(2)
        reqs = [rng.standard_normal((rows, 8)).astype(np.float32)
                for rows in (1, 3, 1, 2, 5, 1, 1, 4, 2, 1)]  # 21 rows
        futs = [srv.submit(r) for r in reqs]
        for r, f in zip(reqs, futs):
            out = f.result(timeout=30)
            assert out.shape == (r.shape[0], 4)
            np.testing.assert_allclose(out, _eager(model, r), rtol=1e-5,
                                       atol=1e-6)
        rep = srv.drain()
        assert rep["accepted"] == 10 and rep["completed"] == 10
        assert rep["unaccounted"] == 0
        snap = srv.metrics.snapshot()
        occ = snap["histograms"]["batch_occupancy"]
        assert 0 < occ["max"] <= 1.0
        assert snap["counters"]["batches_total"] <= 10  # coalesced

    def test_full_batch_vs_timeout_flush_paths(self):
        with flags_guard(serve_chaos_slow_s=0.4):
            chaos.configure("serve_slow_step@1")
            srv = Server(_mlp(4), max_batch=4, buckets=(1, 4),
                         batch_timeout_ms=10, queue_depth=64).start()
            x = np.zeros((1, 8), np.float32)
            first = srv.submit(x)          # batch 1: stalled by chaos
            time.sleep(0.1)                # batcher is inside the stall
            futs = [srv.submit(x) for _ in range(4)]  # queue a FULL batch
            first.result(timeout=30)
            for f in futs:
                f.result(timeout=30)
            # one more after the burst: flushes on the timeout path
            srv.submit(x).result(timeout=30)
            snap = srv.metrics.snapshot()["counters"]
            srv.drain()
        assert snap["batches_full_total"] >= 1
        assert snap["batches_timeout_total"] >= 1
        assert chaos.counts().get("serve_slow_step") >= 1

    def test_incompatible_signature_splits_batch(self):
        model_in8 = _mlp(5)
        srv = Server(model_in8, max_batch=8, buckets=(8,),
                     batch_timeout_ms=20, queue_depth=64).start()
        a = np.zeros((1, 8), np.float32)
        b = np.ones((2, 8), np.float64)  # same rank, new dtype → new sig
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # expected retrace warning
            fa, fb = srv.submit(a), srv.submit(b)
            fa.result(timeout=30)
            fb.result(timeout=30)
        rep = srv.drain()
        assert rep["batches"] == 2 and rep["unaccounted"] == 0


class TestAdmissionControl:
    def test_overload_sheds_typed(self):
        with flags_guard(serve_chaos_slow_s=0.5):
            chaos.configure("serve_slow_step@1")
            srv = Server(_mlp(6), max_batch=1, buckets=(1,),
                         batch_timeout_ms=0, queue_depth=2).start()
            x = np.zeros((1, 8), np.float32)
            first = srv.submit(x)     # picked up, stalled in dispatch
            time.sleep(0.1)
            q1, q2 = srv.submit(x), srv.submit(x)  # fill the queue
            with pytest.raises(ServerOverloaded):
                srv.submit(x)
            snap = srv.metrics.snapshot()["counters"]
            assert snap["shed_total"] == 1
            for f in (first, q1, q2):
                f.result(timeout=30)
            rep = srv.drain()
        # sheds are NOT accepted: accounting stays exact
        assert rep["accepted"] == 3 and rep["completed"] == 3
        assert rep["unaccounted"] == 0

    def test_deadline_expiry_via_slow_step_chaos(self):
        """The serve_slow_step@N chaos point proving the deadline/shed
        path: the stalled dispatch ages queued requests past their
        deadline; they fail typed, never dispatched, all accounted."""
        with flags_guard(serve_chaos_slow_s=0.5):
            chaos.configure("serve_slow_step@1")
            srv = Server(_mlp(7), max_batch=4, buckets=(1, 4),
                         batch_timeout_ms=5, queue_depth=64).start()
            x = np.zeros((1, 8), np.float32)
            first = srv.submit(x)  # its dispatch stalls 0.5s
            time.sleep(0.1)
            doomed = [srv.submit(x, deadline_ms=100) for _ in range(2)]
            assert first.result(timeout=30).shape == (1, 4)
            for f in doomed:
                with pytest.raises(DeadlineExceeded, match="never"):
                    f.result(timeout=30)
            rep = srv.drain()
        assert rep["deadline_failed"] == 2
        assert rep["accepted"] == 3
        assert rep["completed"] == 1 and rep["unaccounted"] == 0

    def test_result_timeout_typed_on_wedged_batch(self):
        """ISSUE 7 satellite: a reader blocking on a wedged batch must
        not wait forever — result(timeout=...) raises the typed
        DeadlineExceeded. The request itself stays in flight (first-
        wins), so a later read succeeds and the books still balance."""
        with flags_guard(serve_chaos_slow_s=1.0):
            chaos.configure("serve_slow_step@1")
            srv = Server(_mlp(21), max_batch=1, buckets=(1,),
                         batch_timeout_ms=0, queue_depth=8).start()
            x = np.zeros((1, 8), np.float32)
            fut = srv.submit(x)   # its dispatch stalls 1s
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded, match="still in "
                               "flight"):
                fut.result(timeout=0.1)
            assert time.monotonic() - t0 < 0.9  # didn't ride the stall
            # the request was NOT cancelled: it completes and accounts
            assert fut.result(timeout=30).shape == (1, 4)
            rep = srv.drain()
        assert rep["accepted"] == 1 and rep["completed"] == 1
        assert rep["unaccounted"] == 0

    def test_submit_validation(self):
        srv = Server(_mlp(8), max_batch=4, buckets=(4,),
                     batch_timeout_ms=1).start()
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="split"):
            srv.submit(np.zeros((5, 8), np.float32))
        with pytest.raises(InvalidArgumentError, match="batch dim"):
            srv.submit(np.float32(3.0))
        srv.drain()

    def test_prebuilt_engine_rejects_unappliable_kwargs(self):
        eng = InferenceEngine(_mlp(8), buckets=(1, 4))
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="pre-built"):
            Server(eng, buckets=(1, 2))
        with pytest.raises(InvalidArgumentError, match="pre-built"):
            Server(eng, input_specs=[((8,), "float32")])
        srv = Server(eng, max_batch=4)  # compatible kwargs still fine
        assert srv.engine is eng and eng.metrics is srv.metrics

    def test_submit_drain_race_accounting(self):
        """Submits hammering a server while it drains must never leave
        unaccounted != 0: the admission lock pairs the accepted count
        with the enqueue, so a drain's snapshot can't land between
        them. (Pre-fix this raced ~1/LOTS into accepted=completed+1.)"""
        eng = InferenceEngine(_mlp(13), buckets=(4,))
        x = np.zeros((1, 8), np.float32)
        for _ in range(8):
            srv = Server(eng, max_batch=4, batch_timeout_ms=1,
                         queue_depth=64).start()
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    try:
                        srv.submit(x)
                    except (ServerClosed, ServerOverloaded):
                        return

            ts = [threading.Thread(target=hammer) for _ in range(4)]
            for t in ts:
                t.start()
            time.sleep(0.02)
            rep = srv.drain()
            stop.set()
            for t in ts:
                t.join()
            assert rep["unaccounted"] == 0, rep

    def test_mismatched_multi_input_rejected_before_enqueue(self):
        """One malformed multi-input request must fail at submit(),
        not poison the micro-batch it would have been coalesced into."""
        srv = Server(lambda x, y: x + y, max_batch=4, buckets=(4,),
                     batch_timeout_ms=5).start()
        from paddle1_tpu.core.errors import InvalidArgumentError
        good = (np.ones((2, 4), np.float32), np.ones((2, 4), np.float32))
        f0 = srv.submit(*good)
        with pytest.raises(InvalidArgumentError, match="share the batch"):
            srv.submit(np.ones((2, 4), np.float32),
                       np.ones((3, 4), np.float32))
        with pytest.raises(InvalidArgumentError, match="share the batch"):
            srv.submit(np.ones((2, 4), np.float32), np.float32(1.0))
        f1 = srv.submit(*good)  # innocents keep flowing
        np.testing.assert_allclose(f0.result(timeout=30), 2.0)
        np.testing.assert_allclose(f1.result(timeout=30), 2.0)
        rep = srv.drain()
        assert rep["accepted"] == 2 and rep["unaccounted"] == 0


class TestDrain:
    def test_drain_under_load_accounts_every_request(self):
        srv = Server(_mlp(9), max_batch=4, buckets=(1, 4),
                     batch_timeout_ms=5, queue_depth=128).start()
        x = np.zeros((1, 8), np.float32)
        futs = [srv.submit(x) for _ in range(24)]
        health.request_drain()  # programmatic SIGTERM equivalent
        rep = srv.wait(poll_s=0.01, timeout=30)
        assert rep["drained"] is True
        # the no-silent-drops contract: every accepted request resolved
        assert all(f.done() for f in futs)
        assert rep["accepted"] == 24
        assert rep["completed"] + rep["deadline_failed"] + \
            rep["errors"] == 24
        assert rep["unaccounted"] == 0
        for f in futs:
            assert f.result(timeout=1).shape == (1, 4)

    def test_submit_after_drain_is_typed(self):
        srv = Server(_mlp(10), buckets=(1,), batch_timeout_ms=1).start()
        srv.drain()
        with pytest.raises(ServerClosed):
            srv.submit(np.zeros((1, 8), np.float32))

    def test_batcher_death_latches_drain_and_reports_fatal(self,
                                                           monkeypatch):
        """A dead batcher must not leave a healthy-looking zombie:
        wait() returns instead of polling forever, drain() reports the
        fatal, and submit() fails typed."""
        srv = Server(_mlp(10), buckets=(1,), batch_timeout_ms=1).start()
        from paddle1_tpu.serving import batcher as batcher_mod
        real = batcher_mod.core_health

        class _BrokenHealth:  # only the BATCHER's binding is replaced
            @staticmethod
            def beat():
                raise RuntimeError("beat broke")
            report_unhealthy = staticmethod(real.report_unhealthy)
        monkeypatch.setattr(batcher_mod, "core_health", _BrokenHealth)
        rep = srv.wait(poll_s=0.01, timeout=30)  # returns via the latch
        assert rep["fatal"] is not None and "beat broke" in rep["fatal"]
        with pytest.raises(ServerClosed):
            srv.submit(np.zeros((1, 8), np.float32))

    def test_drain_timeout_fails_inflight_typed(self):
        """drain() on a WEDGED dispatch resolves the popped-but-
        unresolved futures typed — no client hangs forever on a future
        whose batch never completed."""
        from paddle1_tpu.core.errors import PreconditionNotMetError
        with flags_guard(serve_chaos_slow_s=1.5):
            chaos.configure("serve_slow_step@1")
            srv = Server(_mlp(10), buckets=(1,),
                         batch_timeout_ms=1).start()
            fut = srv.submit(np.zeros((1, 8), np.float32))
            time.sleep(0.15)  # batcher pops it and stalls in dispatch
            rep = srv.drain(timeout=0.2)
        assert rep["drained"] is False
        with pytest.raises(PreconditionNotMetError, match="timed out"):
            fut.result(timeout=1)
        assert rep["unaccounted"] == 0  # failed typed, not dropped
        # let the stalled thread unwedge before the next test
        srv._batcher.join(timeout=5)

    def test_sigterm_handler_installed_once_across_restarts(self):
        """Restart-after-drain must not stack a new SIGTERM closure per
        cycle (each SIGTERM would re-run the drain chain N times)."""
        import signal
        prev = signal.getsignal(signal.SIGTERM)
        try:
            srv = Server(_mlp(10), buckets=(1,), batch_timeout_ms=1)
            srv.start()
            h1 = signal.getsignal(signal.SIGTERM)
            srv.drain()
            srv.start()
            assert signal.getsignal(signal.SIGTERM) is h1
            srv.drain()
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_restart_after_drain_serves_again(self):
        """start() is restartable: a drained server reopened with
        start() admits and completes requests (model-reload flow)."""
        srv = Server(_mlp(10), buckets=(1,), batch_timeout_ms=1).start()
        x = np.zeros((1, 8), np.float32)
        assert srv.infer(x, timeout=30).shape == (1, 4)
        srv.drain()
        srv.start()
        assert srv.running
        assert srv.infer(x, timeout=30).shape == (1, 4)
        rep = srv.drain()
        assert rep["unaccounted"] == 0

    def test_context_manager_drains(self):
        with Server(_mlp(11), buckets=(1, 2),
                    batch_timeout_ms=1) as srv:
            out = srv.infer(np.zeros((1, 8), np.float32), timeout=30)
            assert out.shape == (1, 4)
        assert not srv.running


class TestPredictorServe:
    @pytest.fixture(scope="class")
    def artifact(self, tmp_path_factory):
        from paddle1_tpu.jit import InputSpec
        model = _mlp(12)
        base = str(tmp_path_factory.mktemp("srv") / "m")
        paddle.jit.save(model, base,
                        input_spec=[InputSpec([4, 8], "float32", "x")])
        return base

    def test_serve_matches_run_and_buckets_at_export_batch(self,
                                                           artifact):
        from paddle1_tpu import inference
        pred = inference.create_predictor(
            inference.Config(artifact + ".pdmodel"))
        x = np.random.default_rng(3).standard_normal((4, 8)).astype(
            np.float32)
        ref = pred.run([x])[0]
        srv = pred.serve(batch_timeout_ms=5, warmup=True).start()
        # the exported artifact fixes the batch: one bucket, = export B
        assert srv.engine.buckets == (4,)
        futs = [srv.submit(x[i:i + 1]) for i in range(4)]
        got = np.concatenate([f.result(timeout=30) for f in futs])
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
        rep = srv.drain()
        assert rep["compile_counts"] == {4: 1}

    def test_conflicting_buckets_on_fixed_artifact_typed(self, artifact):
        """Explicit buckets that disagree with the export batch fail
        typed at construction, not deep inside jax.export at dispatch."""
        from paddle1_tpu import inference
        from paddle1_tpu.core.errors import InvalidArgumentError
        pred = inference.create_predictor(
            inference.Config(artifact + ".pdmodel"))
        with pytest.raises(InvalidArgumentError, match="exported at"):
            pred.serve(buckets=(1, 16))
        with pytest.raises(InvalidArgumentError, match="exported at"):
            pred.serve(max_batch=8)
        # matching override is fine
        assert pred.serve(buckets=(4,)).engine.buckets == (4,)

    def test_predictor_subclass_routes_through_adapter(self, artifact):
        """isinstance, not a class-name string: a Predictor SUBCLASS
        must still unwrap the artifact (export-pinned bucket, sidecar
        specs) instead of dying as 'not a Layer or callable'."""
        from paddle1_tpu import inference

        class AuditedPredictor(inference.Predictor):
            pass

        pred = AuditedPredictor(inference.Config(artifact + ".pdmodel"))
        srv = Server(pred, batch_timeout_ms=5)
        assert srv.engine.buckets == (4,)
        srv.start()
        x = np.random.default_rng(4).standard_normal((4, 8)).astype(
            np.float32)
        ref = pred.run([x])[0]
        np.testing.assert_allclose(srv.infer(x[:1], timeout=30),
                                   ref[:1], rtol=1e-6, atol=1e-6)
        srv.drain()

    def test_quantized_predictor_teaches(self, artifact):
        from paddle1_tpu import inference
        from paddle1_tpu.core.errors import UnimplementedError
        cfg = inference.Config(artifact + ".pdmodel")
        cfg.enable_quantized_inference()
        pred = inference.create_predictor(cfg)
        with pytest.raises(UnimplementedError, match="fp32"):
            pred.serve()


class TestPredictorTypedErrors:
    """Satellite: unfilled-handle failures are typed and teach, instead
    of a bare KeyError/RuntimeError."""

    def test_run_with_unfilled_handle(self, tmp_path):
        from paddle1_tpu import inference
        from paddle1_tpu.jit import InputSpec
        from paddle1_tpu.core.errors import PreconditionNotMetError
        base = str(tmp_path / "m")
        paddle.jit.save(_mlp(13), base,
                        input_spec=[InputSpec([2, 8], "float32", "x")])
        pred = inference.create_predictor(
            inference.Config(base + ".pdmodel"))
        with pytest.raises(PreconditionNotMetError,
                           match="never filled"):
            pred.run()
        # reshape() alone is metadata — copy_to_cpu says so
        h = pred.get_input_handle("x")
        h.reshape([2, 8])
        with pytest.raises(PreconditionNotMetError,
                           match="copy_from_cpu"):
            h.copy_to_cpu()
        from paddle1_tpu.core.errors import NotFoundError
        with pytest.raises(NotFoundError):
            pred.get_input_handle("nope")
        # filled handles still work end to end
        x = np.zeros((2, 8), np.float32)
        h.copy_from_cpu(x)
        assert pred.run()[0].shape == (2, 4)


class TestBNServing:
    """Satellite: a model whose BN stats were learned entirely under the
    compiled trainer serves EVAL with those stats (functionalized
    running-stat updates), not with init stats."""

    def test_compiled_training_feeds_eval_serving(self):
        import jax
        from paddle1_tpu.core.tensor import Tensor
        from paddle1_tpu.distributed import ParallelEngine, build_mesh
        paddle.seed(14)
        m = paddle.nn.Sequential(paddle.nn.Linear(8, 6),
                                 paddle.nn.BatchNorm1D(6),
                                 paddle.nn.Linear(6, 4))
        m.train()
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        loss_fn = lambda mm, b: \
            ((mm(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()
        eng = ParallelEngine(m, opt, loss_fn,
                             mesh=build_mesh(dp=1,
                                             devices=jax.devices()[:1]))
        rng = np.random.default_rng(4)
        import warnings
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            for _ in range(3):
                # biased inputs so running mean must move off init 0
                eng.step({"x": (rng.standard_normal((16, 8)) + 3.0)
                          .astype(np.float32),
                          "y": rng.standard_normal((16, 4))
                          .astype(np.float32)})
        # functionalized: no warn-and-skip under the framework engine
        assert not [r for r in rec if "SKIPPED" in str(r.message)]
        eng.sync_model()
        mean = np.asarray(m[1]._mean.numpy())
        assert np.abs(mean).max() > 0.1  # stats genuinely learned
        # eval serving consumes the learned stats
        m.eval()
        srv = Server(m, buckets=(1, 4), batch_timeout_ms=1).start()
        x = (rng.standard_normal((2, 8)) + 3.0).astype(np.float32)
        out = srv.infer(x, timeout=30)
        np.testing.assert_allclose(out, _eager(m, x), rtol=1e-5,
                                   atol=1e-6)
        srv.drain()


_SIGTERM_WORKER = textwrap.dedent('''
    """Loaded serving worker: drains cleanly on SIGTERM, exits 0."""
    import json, sys, threading
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle1_tpu as paddle
    from paddle1_tpu.serving import (Server, ServerClosed,
                                     ServerOverloaded)

    paddle.seed(0)
    m = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                             paddle.nn.Linear(16, 4))
    m.eval()
    srv = Server(m, max_batch=4, buckets=(1, 4), batch_timeout_ms=5,
                 queue_depth=256).start()
    results = {"ok": 0, "typed_fail": 0}
    lock = threading.Lock()

    def client():
        x = np.zeros((1, 8), np.float32)
        while True:
            try:
                srv.submit(x).result(timeout=30)
                with lock:
                    results["ok"] += 1
            except (ServerClosed, ServerOverloaded):
                return  # draining/shed: stop submitting
            except Exception:
                with lock:
                    results["typed_fail"] += 1

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    print("READY", flush=True)
    report = srv.wait(poll_s=0.02)   # returns after SIGTERM -> drain
    for t in threads:
        t.join(timeout=10)
    report["client_ok"] = results["ok"]
    report["client_typed_fail"] = results["typed_fail"]
    print("REPORT " + json.dumps(report), flush=True)
    sys.exit(0 if report["unaccounted"] == 0 and report["drained"]
             else 3)
''')


def _run_sigterm_worker(tmp_path, supervised: bool):
    script = tmp_path / "worker.py"
    script.write_text(_SIGTERM_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
    env.update({"PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"})
    if supervised:
        from paddle1_tpu.distributed import Supervisor
        sup = Supervisor(policy="fail_fast",
                         heartbeat_dir=str(tmp_path / "hb"),
                         poll_s=0.1, grace_s=5.0)
        log = str(tmp_path / "workerlog.0")
        sup.add_worker(0, [sys.executable, "-u", str(script)], env=env,
                       log_path=log)
        sup.start()
        # wait for the worker to be serving, then SIGTERM it mid-load
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            if os.path.exists(log) and "READY" in open(log).read():
                break
            time.sleep(0.05)
        else:
            raise AssertionError("worker never became ready")
        time.sleep(0.3)  # let the clients build up load
        w = sup._workers[0]
        w.proc.send_signal(signal.SIGTERM)
        rc = sup.run()
        out = open(log).read()
        return rc, out
    proc = subprocess.Popen([sys.executable, "-u", str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, env=env, text=True)
    line = proc.stdout.readline()
    assert "READY" in line, line
    time.sleep(0.3)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    return proc.returncode, "READY\n" + out


class TestSigtermDrain:
    @pytest.mark.slow  # subprocess + jax import ~12s; the in-process
    # drain-under-load test covers the accounting contract in-tier
    def test_standalone_sigterm_drains_cleanly(self, tmp_path):
        """Acceptance: SIGTERM during a loaded run — every accepted
        request completes or fails typed, none silently dropped, clean
        exit."""
        rc, out = _run_sigterm_worker(tmp_path, supervised=False)
        assert rc == 0, out[-2000:]
        rep = json.loads(out.split("REPORT ", 1)[1].splitlines()[0])
        assert rep["drained"] is True and rep["unaccounted"] == 0
        assert rep["accepted"] == rep["completed"] + \
            rep["deadline_failed"] + rep["errors"]
        assert rep["client_typed_fail"] == 0
        assert rep["client_ok"] >= 1  # it really was loaded

    @pytest.mark.slow
    def test_supervised_sigterm_clean_exit(self, tmp_path):
        """Acceptance: the Supervisor sees a clean exit (rc 0) from a
        SIGTERM'd serving worker — serving workers are supervisable
        with the PR 3 machinery."""
        rc, out = _run_sigterm_worker(tmp_path, supervised=True)
        assert rc == 0, out[-2000:]
        assert "REPORT" in out
        rep = json.loads(out.split("REPORT ", 1)[1].splitlines()[0])
        assert rep["drained"] is True and rep["unaccounted"] == 0


@pytest.mark.slow
class TestServingSmoke:
    def test_concurrent_low_load_p99_and_zero_sheds(self):
        """CI serving smoke: concurrent client threads at low load —
        p99 under a generous CPU bound, zero sheds."""
        srv = Server(_mlp(15), max_batch=8, buckets=(1, 4, 8),
                     batch_timeout_ms=2, queue_depth=256,
                     warmup=False).start()
        srv.engine.warm_up(example=[np.zeros((1, 8), np.float32)])
        n_per, n_cli = 50, 4
        errs = []

        def client(i):
            rng = np.random.default_rng(i)
            for _ in range(n_per):
                x = rng.standard_normal((1, 8)).astype(np.float32)
                try:
                    out = srv.submit(x).result(timeout=30)
                    assert out.shape == (1, 4)
                except Exception as e:
                    errs.append(e)
                time.sleep(0.002)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_cli)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        rep = srv.drain()
        assert not errs, errs[:3]
        assert rep["shed"] == 0
        assert rep["accepted"] == n_per * n_cli
        assert rep["completed"] == n_per * n_cli
        p99 = srv.metrics.histogram("e2e_ms").percentile(99)
        assert 0 < p99 < 1000, p99  # generous CPU bound, loud if wild
