"""distribution / auto-checkpoint / sysconfig / onnx-shim coverage."""

import os
import tempfile
import unittest

import numpy as np
import pytest

import paddle1_tpu as paddle


class TestDistribution(unittest.TestCase):
    def test_normal(self):
        d = paddle.distribution.Normal(0.0, 1.0)
        s = d.sample([2000])
        self.assertLess(abs(float(s.numpy().mean())), 0.15)
        lp = d.log_prob(paddle.to_tensor(0.0))
        self.assertAlmostEqual(float(lp), -0.9189385, places=5)
        ent = d.entropy()
        self.assertAlmostEqual(float(ent), 1.4189385, places=5)
        kl = d.kl_divergence(paddle.distribution.Normal(0.0, 2.0))
        self.assertGreater(float(kl), 0.0)

    def test_uniform(self):
        d = paddle.distribution.Uniform(1.0, 3.0)
        s = d.sample([1000]).numpy()
        self.assertTrue((s >= 1.0).all() and (s < 3.0).all())
        self.assertAlmostEqual(float(d.entropy()), np.log(2.0), places=5)
        self.assertAlmostEqual(float(d.log_prob(paddle.to_tensor(2.0))),
                               -np.log(2.0), places=5)
        self.assertEqual(float(d.log_prob(paddle.to_tensor(5.0))),
                         -np.inf)

    def test_categorical(self):
        logits = paddle.to_tensor(np.log(np.array([0.7, 0.2, 0.1],
                                                  np.float32)))
        d = paddle.distribution.Categorical(logits)
        s = d.sample([4000]).numpy()
        self.assertAlmostEqual((s == 0).mean(), 0.7, delta=0.06)
        lp = d.log_prob(paddle.to_tensor(np.array([0], np.int64)))
        self.assertAlmostEqual(float(lp), np.log(0.7), places=4)
        ent = float(d.entropy())
        self.assertAlmostEqual(ent, -(0.7 * np.log(0.7) + 0.2 * np.log(0.2)
                                      + 0.1 * np.log(0.1)), places=4)


class TestAutoCheckpoint(unittest.TestCase):
    def test_resume_cycle(self):
        from paddle1_tpu.incubate import train_epoch_range
        from paddle1_tpu.vision.models import LeNet
        with tempfile.TemporaryDirectory() as d:
            m = LeNet()
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=m.parameters())
            seen = []
            for epoch in train_epoch_range(5, m, opt, name="t",
                                           checkpoint_dir=d):
                seen.append(epoch)
                if epoch == 2:
                    # simulated crash DURING epoch 2 (before its snapshot):
                    # epochs 0-1 are durable, epoch 2 must re-run
                    break
            self.assertEqual(seen, [0, 1, 2])
            # "restart": fresh objects, same dir → resumes at epoch 2
            m2 = LeNet()
            opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                        parameters=m2.parameters())
            seen2 = list(train_epoch_range(5, m2, opt2, name="t",
                                           checkpoint_dir=d))
            self.assertEqual(seen2, [2, 3, 4])
            # weights restored from snapshot
            a = m.state_dict()["features.0.weight"].numpy()
            b = m2.state_dict()["features.0.weight"].numpy()
            np.testing.assert_array_equal(a, b)

    def test_no_dir_passthrough(self):
        from paddle1_tpu.incubate import train_epoch_range
        os.environ.pop("PADDLE_CHECKPOINT_DIR", None)
        self.assertEqual(list(train_epoch_range(3)), [0, 1, 2])


class TestMisc(unittest.TestCase):
    def test_sysconfig(self):
        self.assertTrue(os.path.isdir(paddle.sysconfig.get_include()))
        self.assertTrue(os.path.isdir(paddle.sysconfig.get_lib()))

    def test_onnx_export_raises_for_onnx_suffix(self):
        from paddle1_tpu.vision.models import LeNet
        with self.assertRaises(NotImplementedError):
            paddle.onnx.export(LeNet(), "/tmp/x.onnx")


class TestPre20TopLevelCompat:
    """r3 namespace sweep vs reference python/paddle/__init__.py: the
    pre-2.0 top-level names old scripts touch."""

    def test_reader_pipeline(self):
        import paddle1_tpu as paddle

        def train():
            for i in range(10):
                yield np.float32([i]), i % 2

        r = paddle.batch(paddle.reader.shuffle(train, buf_size=4), 4)
        batches = list(r())
        assert [len(b) for b in batches] == [4, 4, 2]
        r2 = paddle.batch(train, 4, drop_last=True)
        assert [len(b) for b in list(r2())] == [4, 4]
        # decorators compose
        fn = paddle.reader.firstn(paddle.reader.cache(train), 3)
        assert len(list(fn())) == 3
        m = paddle.reader.map_readers(lambda s: s[1], train)
        assert list(m()) == [i % 2 for i in range(10)]

    def test_flags_and_modes(self):
        import paddle1_tpu as paddle
        # the real device probe (False on the CPU test sim, True on chip)
        assert isinstance(paddle.is_compiled_with_tpu(), (bool, np.bool_))
        assert not paddle.is_compiled_with_cuda()
        assert paddle.in_dygraph_mode() and paddle.in_dynamic_mode()
        assert paddle.get_cudnn_version() is None

    def test_tensor_utilities(self):
        import paddle1_tpu as paddle
        x = paddle.to_tensor(np.arange(12).reshape(3, 4))
        assert int(paddle.rank(x).numpy()) == 2
        assert paddle.tolist(paddle.to_tensor(np.array([1, 2]))) == [1, 2]
        assert not bool(paddle.is_empty(x).numpy())
        np.testing.assert_array_equal(
            paddle.reverse(paddle.to_tensor(np.array([1, 2, 3])),
                           0).numpy(), [3, 2, 1])
        np.testing.assert_array_equal(
            paddle.crop_tensor(x, shape=[2, 2],
                               offsets=[1, 1]).numpy(),
            [[5, 6], [9, 10]])

    def test_aliases_and_places(self):
        import paddle1_tpu as paddle
        assert paddle.VarBase is paddle.Tensor
        assert paddle.CUDAPlace is paddle.TPUPlace
        with pytest.raises(RuntimeError, match="TPU build"):
            paddle.NPUPlace(0)
        p = paddle.create_parameter([2, 3])
        assert p.shape == [2, 3]
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)


class TestBoundedDifferentiableWhile(unittest.TestCase):
    """static.nn.while_loop(max_iter=N): bounded lax.scan lowering —
    the differentiable form of the traced while (VERDICT r3 weak #8:
    a traced-bound while was forward-only)."""

    def test_matches_unbounded_result(self):
        import jax.numpy as jnp
        from paddle1_tpu import static
        from paddle1_tpu.core.tensor import to_tensor

        def run(**kw):
            i0 = to_tensor(np.int32(0))
            s0 = to_tensor(np.float32(0.0))
            i, s = static.nn.while_loop(
                lambda i, s: to_tensor((i.data < 5)),
                lambda i, s: (to_tensor(i.data + 1),
                              to_tensor(s.data + 2.0)),
                [i0, s0], **kw)
            return int(i.numpy()), float(s.numpy())

        self.assertEqual(run(), (5, 10.0))
        self.assertEqual(run(max_iter=8), (5, 10.0))  # freezes after 5

    def test_bounded_form_is_differentiable(self):
        import jax
        import jax.numpy as jnp
        from paddle1_tpu import static

        def loss(x):
            # s = x * 3 via three loop iterations, then squared
            def cond(i, s):
                return i < 3

            def body(i, s):
                return i + 1, s + x

            from paddle1_tpu.core.tensor import to_tensor
            i, s = static.nn.while_loop(
                cond, body, [jnp.int32(0), jnp.zeros(())], max_iter=5)
            s = s.data if hasattr(s, "data") else s
            return (s * s).sum()

        g = jax.grad(loss)(jnp.float32(2.0))
        # d/dx (3x)^2 = 18x = 36
        self.assertAlmostEqual(float(g), 36.0, places=4)

    def test_unbounded_form_still_forward_only(self):
        import jax
        import jax.numpy as jnp
        from paddle1_tpu import static

        def loss(x):
            i, s = static.nn.while_loop(
                lambda i, s: i < 3,
                lambda i, s: (i + 1, s + x),
                [jnp.int32(0), jnp.zeros(())])
            s = s.data if hasattr(s, "data") else s
            return (s * s).sum()

        # forward works; reverse mode specifically is what fails
        self.assertAlmostEqual(float(loss(jnp.float32(2.0))), 36.0,
                               places=4)
        with self.assertRaises(ValueError) as cm:
            jax.grad(loss)(jnp.float32(2.0))
        self.assertIn("while", str(cm.exception).lower())

    def test_bounded_grad_survives_unsafe_frozen_body(self):
        """Double-where regression: the dead body evaluation after
        termination (here x/(3-i) hitting i=3 -> x/0) must not poison
        the gradient with NaN."""
        import jax
        import jax.numpy as jnp
        from paddle1_tpu import static

        def loss(x):
            def cond(i, s):
                return i < 3

            def body(i, s):
                return i + 1, s + x / (3.0 - i.astype(jnp.float32))

            i, s = static.nn.while_loop(cond, body,
                                        [jnp.int32(0), jnp.zeros(())],
                                        max_iter=5)
            s = s.data if hasattr(s, "data") else s
            return s

        v = float(loss(jnp.float32(2.0)))
        self.assertAlmostEqual(v, 2 * (1 / 3 + 1 / 2 + 1.0), places=4)
        g = float(jax.grad(loss)(jnp.float32(2.0)))
        self.assertAlmostEqual(g, 1 / 3 + 1 / 2 + 1.0, places=4)

    def test_bounded_body_arity_mismatch_raises(self):
        import jax.numpy as jnp
        from paddle1_tpu import static
        with self.assertRaises(TypeError):
            static.nn.while_loop(
                lambda i, s: i < 2,
                lambda i, s: (i + 1, s, s),   # 3 outputs for 2 vars
                [jnp.int32(0), jnp.zeros(())], max_iter=4)

    def test_zero_iteration_loop_grad_clean(self):
        """cond false on entry: the body (x/0 on the initial state)
        must never execute, so both value and grad stay finite."""
        import jax
        import jax.numpy as jnp
        from paddle1_tpu import static

        def loss(x):
            i, s = static.nn.while_loop(
                lambda i, s: i < 0,
                lambda i, s: (i + 1,
                              s + x / (0.0 - i.astype(jnp.float32))),
                [jnp.int32(0), jnp.zeros(())], max_iter=3)
            s = s.data if hasattr(s, "data") else s
            return s

        self.assertEqual(float(loss(jnp.float32(2.0))), 0.0)
        self.assertEqual(float(jax.grad(loss)(jnp.float32(2.0))), 0.0)
