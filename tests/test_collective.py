"""Tests for paddle1_tpu.distributed.collective — the simulated-mesh test
backend promised by that module's docstring.

Two modes, mirroring the module's two faces:

* **SPMD trace**: every collective under ``shard_map`` over the virtual
  8-device CPU mesh (conftest.py), checking the real multi-device lowering
  numerically — including ReduceOp.PROD's log-magnitude/sign/zero handling
  and the Megatron fwd/bwd pairs (_c_identity/_mp_allreduce).
* **Eager group mode**: world-size-1 no-ops, group bookkeeping, send/recv
  pairing, barrier/wait (reference test_collective_base.py:34,124 roles).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

import paddle1_tpu.distributed.collective as C
from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.core.tensor import Tensor, to_tensor

N = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= N, "conftest must provision the 8-device CPU mesh"
    return Mesh(np.array(devs[:N]), ("x",))


def _per_rank(shape=(N, 4), seed=0, signed=True):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal(shape).astype(np.float32)
    if not signed:
        a = np.abs(a) + 0.1
    return jnp.asarray(a)


def _run(mesh, fn, x, in_spec=P("x"), out_spec=P("x")):
    """shard_map fn over the 'x' axis; fn sees this rank's shard."""
    return shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                     out_specs=out_spec)(x)


class TestAllReduceTrace:
    def test_sum(self, mesh):
        x = _per_rank()

        def f(xs):
            t = Tensor(xs[0])
            C.all_reduce(t, op=C.ReduceOp.SUM, group="x")
            return t.data[None]

        out = _run(mesh, f, x)
        np.testing.assert_allclose(
            np.asarray(out), np.broadcast_to(np.asarray(x).sum(0), x.shape),
            rtol=1e-5, atol=1e-5)

    def test_max_min_avg(self, mesh):
        x = _per_rank(seed=1)
        for op, ref in ((C.ReduceOp.MAX, np.asarray(x).max(0)),
                        (C.ReduceOp.MIN, np.asarray(x).min(0)),
                        (C.ReduceOp.AVG, np.asarray(x).mean(0))):
            def f(xs):
                t = Tensor(xs[0])
                C.all_reduce(t, op=op, group="x")
                return t.data[None]

            out = _run(mesh, f, x)
            np.testing.assert_allclose(np.asarray(out)[0], ref,
                                       rtol=1e-5, atol=1e-5)

    def test_prod_signs(self, mesh):
        # mixed signs: even/odd negative counts per column
        x = np.ones((N, 4), np.float32) * 2.0
        x[0, 0] = -2.0                    # one negative → negative product
        x[0, 1] = -2.0
        x[1, 1] = -2.0                    # two negatives → positive
        x = jnp.asarray(x)

        def f(xs):
            t = Tensor(xs[0])
            C.all_reduce(t, op=C.ReduceOp.PROD, group="x")
            return t.data[None]

        out = np.asarray(_run(mesh, f, x))[0]
        np.testing.assert_allclose(out, np.asarray(x).prod(0), rtol=1e-4)
        assert out[0] < 0 and out[1] > 0

    def test_prod_zero(self, mesh):
        x = np.full((N, 3), 1.5, np.float32)
        x[3, 2] = 0.0                     # any zero → exact 0, not -inf/nan

        def f(xs):
            t = Tensor(xs[0])
            C.all_reduce(t, op=C.ReduceOp.PROD, group="x")
            return t.data[None]

        out = np.asarray(_run(mesh, f, jnp.asarray(x)))[0]
        np.testing.assert_allclose(out, np.asarray(x).prod(0), rtol=1e-4,
                                   atol=1e-7)
        assert out[2] == 0.0 and np.isfinite(out).all()


class TestRootedTrace:
    def test_reduce_masks_non_dst(self, mesh):
        x = _per_rank(seed=2)

        def f(xs):
            t = Tensor(xs[0])
            C.reduce(t, dst=3, op=C.ReduceOp.SUM, group="x")
            return t.data[None]

        out = np.asarray(_run(mesh, f, x))
        ref = np.asarray(x)
        np.testing.assert_allclose(out[3], ref.sum(0), rtol=1e-5, atol=1e-5)
        for r in range(N):
            if r != 3:
                np.testing.assert_allclose(out[r], ref[r], rtol=1e-6)

    def test_broadcast(self, mesh):
        x = _per_rank(seed=3)

        def f(xs):
            t = Tensor(xs[0])
            C.broadcast(t, src=5, group="x")
            return t.data[None]

        out = np.asarray(_run(mesh, f, x))
        for r in range(N):
            np.testing.assert_allclose(out[r], np.asarray(x)[5], rtol=1e-6)

    def test_scatter(self, mesh):
        x = _per_rank(shape=(N, N, 2), seed=4)  # per-rank list of N chunks

        def f(xs):
            chunks = [Tensor(xs[0, i]) for i in range(N)]
            t = Tensor(jnp.zeros_like(xs[0, 0]))
            C.scatter(t, chunks, src=2, group="x")
            return t.data[None]

        out = np.asarray(_run(mesh, f, x))
        for r in range(N):
            # each rank ends with chunk r of src-rank-2's list
            np.testing.assert_allclose(out[r], np.asarray(x)[2, r],
                                       rtol=1e-6)


class TestGatherScatterTrace:
    def test_all_gather_stacked_and_list(self, mesh):
        x = _per_rank(shape=(N, 3), seed=5)

        def f(xs):
            lst = []
            stacked = C.all_gather(lst, Tensor(xs[0]), group="x")
            assert len(lst) == N
            return stacked.data[None]

        out = np.asarray(_run(mesh, f, x))
        for r in range(N):
            np.testing.assert_allclose(out[r], np.asarray(x), rtol=1e-6)

    def test_reduce_scatter(self, mesh):
        x = _per_rank(shape=(N, N * 2), seed=6)  # each rank holds [N*2]

        def f(xs):
            t = Tensor(jnp.zeros((2,), jnp.float32))
            C.reduce_scatter(t, Tensor(xs[0]), group="x")
            return t.data[None]

        out = np.asarray(_run(mesh, f, x))
        ref = np.asarray(x).sum(0).reshape(N, 2)
        for r in range(N):
            np.testing.assert_allclose(out[r], ref[r], rtol=1e-5, atol=1e-5)

    def test_reduce_scatter_list_input(self, mesh):
        x = _per_rank(shape=(N, N, 2), seed=7)

        def f(xs):
            parts = [Tensor(xs[0, i]) for i in range(N)]
            t = Tensor(jnp.zeros((2,), jnp.float32))
            C.reduce_scatter(t, parts, group="x")
            return t.data[None]

        out = np.asarray(_run(mesh, f, x))
        ref = np.asarray(x).sum(0)  # [N, 2]
        for r in range(N):
            np.testing.assert_allclose(out[r], ref[r], rtol=1e-5, atol=1e-5)

    def test_alltoall(self, mesh):
        x = _per_rank(shape=(N, N, 2), seed=8)  # rank r sends x[r, j] to j

        def f(xs):
            outs = []
            C.alltoall([Tensor(xs[0, i]) for i in range(N)], outs,
                       group="x")
            assert len(outs) == N
            return jnp.stack([o.data for o in outs])[None]

        out = np.asarray(_run(mesh, f, x))
        ref = np.asarray(x)
        for r in range(N):
            for j in range(N):
                np.testing.assert_allclose(out[r, j], ref[j, r], rtol=1e-6)

    def test_all_to_all_alias(self):
        assert C.all_to_all is C.alltoall


class TestMegatronPairsTrace:
    def test_c_identity_fwd_bwd(self, mesh):
        x = _per_rank(shape=(N, 4), seed=9)

        def loss(xs):
            y = C._c_identity(Tensor(xs), group="x")
            return jnp.sum(y.data)

        def f(xs):
            v = loss(xs[0])
            g = jax.grad(loss)(xs[0])
            return v[None], g[None]

        val, grad = shard_map(f, mesh=mesh, in_specs=(P("x"),),
                              out_specs=(P("x"), P("x")))(x)
        # fwd identity: per-rank sum of own shard
        np.testing.assert_allclose(np.asarray(val),
                                   np.asarray(x).sum(-1), rtol=1e-5)
        # bwd psum: each grad element = N (sum of ones across ranks)
        np.testing.assert_allclose(np.asarray(grad),
                                   np.full((N, 4), float(N)), rtol=1e-6)

    def test_mp_allreduce_fwd_bwd(self, mesh):
        x = _per_rank(shape=(N, 4), seed=10)

        def loss(xs):
            y = C._mp_allreduce(Tensor(xs), group="x")
            return jnp.sum(y.data)

        def f(xs):
            v = loss(xs[0])
            g = jax.grad(loss)(xs[0])
            return v[None], g[None]

        val, grad = shard_map(f, mesh=mesh, in_specs=(P("x"),),
                              out_specs=(P("x"), P("x")))(x)
        # fwd psum: every rank's loss = total sum
        np.testing.assert_allclose(np.asarray(val),
                                   np.full(N, np.asarray(x).sum()),
                                   rtol=1e-4)
        # bwd identity: grads are ones (no double-psum)
        np.testing.assert_allclose(np.asarray(grad),
                                   np.ones((N, 4)), rtol=1e-6)

    def test_c_concat(self, mesh):
        x = _per_rank(shape=(N, 2, 3), seed=11)

        def f(xs):
            return C._c_concat(Tensor(xs[0]), group="x").data[None]

        out = np.asarray(_run(mesh, f, x))
        ref = np.concatenate([np.asarray(x)[r] for r in range(N)], axis=-1)
        for r in range(N):
            np.testing.assert_allclose(out[r], ref, rtol=1e-6)

    def test_c_split(self, mesh):
        x = jnp.broadcast_to(_per_rank(shape=(2, N * 3), seed=12),
                             (N, 2, N * 3))

        def f(xs):
            return C._c_split(Tensor(xs[0]), group="x").data[None]

        out = np.asarray(_run(mesh, f, x))
        full = np.asarray(x)[0]
        for r in range(N):
            np.testing.assert_allclose(out[r], full[:, r * 3:(r + 1) * 3],
                                       rtol=1e-6)

    def test_c_split_indivisible_raises(self, mesh):
        x = jnp.ones((N, 2, N * 3 + 1), jnp.float32)

        def f(xs):
            return C._c_split(Tensor(xs[0]), group="x").data[None]

        with pytest.raises(InvalidArgumentError):
            _run(mesh, f, x)

    def test_split_guards(self, mesh):
        with pytest.raises(InvalidArgumentError):
            C.split(to_tensor(np.ones((4, 8), np.float32)), N, axis=0)
        with pytest.raises(InvalidArgumentError):
            C.split(to_tensor(np.ones((4, 8), np.float32)), 3, axis=-1)

    def test_round_trip_identity_concat_split(self, mesh):
        """c_split(c_concat(x)) == x — the column↔row parallel seam."""
        x = _per_rank(shape=(N, 2, 4), seed=13)

        def f(xs):
            y = C._c_concat(Tensor(xs[0]), group="x")
            z = C._c_split(y, group="x")
            return z.data[None]

        out = np.asarray(_run(mesh, f, x))
        np.testing.assert_allclose(out, np.asarray(x), rtol=1e-6)


class TestEagerGroupMode:
    def setup_method(self, _):
        C.destroy_process_group()

    def test_world_size_1_noops(self):
        t = to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        ref = np.asarray(t.numpy()).copy()
        C.all_reduce(t)
        C.broadcast(t, src=0)
        C.reduce(t, dst=0)
        np.testing.assert_allclose(np.asarray(t.numpy()), ref)
        lst = []
        stacked = C.all_gather(lst, t)
        assert len(lst) == 1 and stacked.shape[0] == 1
        np.testing.assert_allclose(np.asarray(lst[0].numpy()), ref)

    def test_group_bookkeeping(self):
        assert not C.is_initialized()
        g0 = C.get_group(0)
        assert C.is_initialized()
        assert g0.world_size == C.get_world_size() == 1
        assert C.get_rank() == 0 and C.get_rank(g0) == 0
        g = C.new_group([0])
        assert g.id >= 1 and g.nranks == 1
        assert g.get_group_rank(0) == 0 and g.get_group_rank(7) == -1
        assert C.get_group(g.id) is g
        assert "Group(" in repr(g)
        C.destroy_process_group(g)
        from paddle1_tpu.core.errors import PreconditionNotMetError
        with pytest.raises(PreconditionNotMetError):
            C.get_group(g.id)
        C.destroy_process_group()
        assert not C.is_initialized()

    def test_send_recv_pairing(self):
        src = to_tensor(np.array([1.0, 2.0], np.float32))
        C.send(src, dst=0)
        dst = to_tensor(np.zeros(2, np.float32))
        C.recv(dst, src=0)
        np.testing.assert_allclose(np.asarray(dst.numpy()), [1.0, 2.0])
        # empty buffer: recv leaves tensor untouched
        dst2 = to_tensor(np.full(2, 7.0, np.float32))
        C.recv(dst2, src=0)
        np.testing.assert_allclose(np.asarray(dst2.numpy()), [7.0, 7.0])

    def test_isend_irecv_work(self):
        w = C.isend(to_tensor(np.ones(2, np.float32)), dst=0)
        assert w.is_completed() and w.wait() is None
        w2 = C.irecv(to_tensor(np.zeros(2, np.float32)), src=0)
        assert w2.is_completed()

    def test_barrier_and_wait(self):
        C.barrier()          # single process: returns without error
        C.wait(to_tensor(np.ones(2, np.float32)))

    def test_all_gather_object(self):
        objs = []
        C.all_gather_object(objs, {"k": 1})
        assert objs == [{"k": 1}]

    def test_reduce_op_constants(self):
        assert (C.ReduceOp.SUM, C.ReduceOp.MAX, C.ReduceOp.MIN,
                C.ReduceOp.PROD, C.ReduceOp.AVG) == (0, 1, 2, 3, 4)


class TestHierarchicalAllReduce:
    """Functional two-level collective (VERDICT r3 missing #5; reference
    hierarchical_allreduce strategy)."""

    def test_matches_flat_psum_on_2x4_mesh(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle1_tpu.distributed.collective import (
            hierarchical_all_reduce)
        devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("dcn", "ici"))
        x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)

        @jax.jit
        def hier(v):
            return shard_map(
                lambda s: hierarchical_all_reduce(s, "ici", "dcn"),
                mesh=mesh, in_specs=P(("dcn", "ici")),
                out_specs=P(("dcn", "ici")))(v)

        @jax.jit
        def flat(v):
            return shard_map(
                lambda s: jax.lax.psum(jax.lax.psum(s, "ici"), "dcn"),
                mesh=mesh, in_specs=P(("dcn", "ici")),
                out_specs=P(("dcn", "ici")))(v)

        np.testing.assert_allclose(np.asarray(hier(x)),
                                   np.asarray(flat(x)), rtol=1e-6)

    def test_non_divisible_falls_back(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle1_tpu.distributed.collective import (
            hierarchical_all_reduce)
        devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("dcn", "ici"))
        x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)

        @jax.jit
        def hier(v):
            # local shard dim0 = 1 per device over the batch, then the
            # collective sees a [1,3] shard: 1 % 4 != 0 -> flat path
            return shard_map(
                lambda s: hierarchical_all_reduce(s, "ici", "dcn"),
                mesh=mesh, in_specs=P(("dcn", "ici")),
                out_specs=P(("dcn", "ici")))(v)

        expect = np.tile(x.sum(axis=0, keepdims=True) * 0 + x.sum(0),
                         (8, 1))
        np.testing.assert_allclose(np.asarray(hier(x)), expect,
                                   rtol=1e-6)
