"""Inference deployment surface (VERDICT r2 task 9): Config/Predictor over
the jit.save artifact, plus the C ABI (embedded-interpreter capi.cc) —
reference paddle_api.h:85-301 and inference/capi/."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import to_tensor


@pytest.fixture(scope="module")
def lenet_artifact(tmp_path_factory):
    from paddle1_tpu.jit import InputSpec, save
    from paddle1_tpu.vision.models.lenet import LeNet
    d = tmp_path_factory.mktemp("export")
    base = str(d / "lenet")
    model = LeNet()
    model.eval()
    save(model, base,
         input_spec=[InputSpec([4, 1, 28, 28], "float32", name="image")])
    x = np.random.default_rng(0).standard_normal(
        (4, 1, 28, 28)).astype(np.float32)
    ref = np.asarray(model(to_tensor(x)).numpy())
    return base, x, ref


class TestConfigPredictor:
    def test_config_surface(self, lenet_artifact):
        base, _, _ = lenet_artifact
        from paddle1_tpu.inference import Config
        cfg = Config(base + ".pdmodel")
        assert cfg.model_program_path().endswith(".pdmodel")
        assert cfg.params_file_path().endswith(".pdiparams")
        cfg.disable_gpu()
        assert not cfg.use_gpu()
        cfg.enable_use_gpu(100, 0)
        assert cfg.use_gpu() and cfg.gpu_device_id() == 0
        cfg.switch_ir_optim(True)
        cfg.enable_memory_optim()
        cfg.set_cpu_math_library_num_threads(4)
        assert cfg.cpu_math_library_num_threads() == 4
        s = cfg.summary()
        assert "model file" in s and "device" in s

    def test_config_model_dir_form(self, lenet_artifact):
        base, _, _ = lenet_artifact
        from paddle1_tpu.inference import Config
        cfg = Config(os.path.dirname(base))
        assert cfg.model_program_path() == base + ".pdmodel"

    def test_predictor_run_positional(self, lenet_artifact):
        base, x, ref = lenet_artifact
        from paddle1_tpu.inference import Config, create_predictor
        pred = create_predictor(Config(base + ".pdmodel"))
        assert pred.get_input_names() == ["image"]
        outs = pred.run([x])
        np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-5)

    def test_predictor_zero_copy_handles(self, lenet_artifact):
        base, x, ref = lenet_artifact
        from paddle1_tpu.inference import Config, create_predictor
        pred = create_predictor(Config(base + ".pdmodel"))
        h = pred.get_input_handle("image")
        h.reshape([4, 1, 28, 28])
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        np.testing.assert_allclose(out.copy_to_cpu(), ref, rtol=1e-5,
                                   atol=1e-5)
        assert out.shape() == [4, 10]

    def test_no_sidecar_fallback_input_count(self, lenet_artifact,
                                             tmp_path):
        """Review finding: without the .pdconfig sidecar (pre-sidecar
        artifacts), the input count must come from in_tree minus param
        leaves — not one phantom input per parameter."""
        import shutil
        base, x, ref = lenet_artifact
        for ext in (".pdmodel", ".pdiparams"):
            shutil.copy(base + ext, str(tmp_path / ("old" + ext)))
        from paddle1_tpu.inference import Config, create_predictor
        pred = create_predictor(Config(str(tmp_path / "old.pdmodel")))
        assert pred.get_input_names() == ["input_0"]
        outs = pred.run([x])
        np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-5)

    def test_missing_model_raises(self):
        from paddle1_tpu.inference import Config, Predictor
        with pytest.raises(FileNotFoundError):
            Predictor(Config("/tmp/definitely_missing_model.pdmodel"))

    def test_unknown_input_name(self, lenet_artifact):
        base, _, _ = lenet_artifact
        from paddle1_tpu.inference import Config, create_predictor
        pred = create_predictor(Config(base + ".pdmodel"))
        with pytest.raises(KeyError):
            pred.get_input_handle("nope")


C_DRIVER = textwrap.dedent(r"""
    #include <stdio.h>
    #include <stdint.h>
    #include <stdlib.h>
    #include <dlfcn.h>

    typedef void* (*create_fn)(const char*, const char*);
    typedef int (*run_fn)(void*, const float**, const int64_t*,
                          const int*, int, int, float*, int64_t,
                          int64_t*, int*);
    typedef void (*destroy_fn)(void*);
    typedef const char* (*err_fn)(void);

    int main(int argc, char** argv) {
      /* argv: 1=libpaddle1_capi.so 2=model_base 3=input.bin 4=output.bin */
      void* so = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
      if (!so) { fprintf(stderr, "dlopen: %s\n", dlerror()); return 2; }
      create_fn create = (create_fn)dlsym(so, "p1_predictor_create");
      run_fn run = (run_fn)dlsym(so, "p1_predictor_run_f32");
      destroy_fn destroy = (destroy_fn)dlsym(so, "p1_predictor_destroy");
      err_fn lasterr = (err_fn)dlsym(so, "p1_last_error");
      if (!create || !run || !destroy) { fprintf(stderr, "dlsym\n"); return 2; }

      void* h = create(argv[2], "cpu");
      if (!h) { fprintf(stderr, "create: %s\n", lasterr()); return 3; }

      float* in = (float*)malloc(4 * 1 * 28 * 28 * sizeof(float));
      FILE* f = fopen(argv[3], "rb");
      fread(in, sizeof(float), 4 * 28 * 28, f);
      fclose(f);

      int64_t shape[4] = {4, 1, 28, 28};
      int ndims = 4;
      const float* ins[1] = {in};
      float out[40];
      int64_t out_shape[8];
      int out_rank = 8;
      int rc = run(h, ins, shape, &ndims, 1, 0, out, 40, out_shape,
                   &out_rank);
      if (rc != 0) { fprintf(stderr, "run: %s\n", lasterr()); return 4; }
      if (out_rank != 2 || out_shape[0] != 4 || out_shape[1] != 10) {
        fprintf(stderr, "bad shape %d\n", out_rank); return 5;
      }
      FILE* g = fopen(argv[4], "wb");
      fwrite(out, sizeof(float), 40, g);
      fclose(g);
      destroy(h);
      printf("C-OK\n");
      return 0;
    }
""")


class TestCAPI:
    def test_c_level_smoke(self, lenet_artifact, tmp_path):
        """Build libpaddle1_capi.so, compile a pure-C driver, load the
        exported LeNet from C, run, and compare with the Python result."""
        base, x, ref = lenet_artifact
        from paddle1_tpu.core.native import build_capi
        so = build_capi()
        if so is None:
            pytest.skip("toolchain cannot build the capi .so")

        csrc = tmp_path / "driver.c"
        csrc.write_text(C_DRIVER)
        exe = tmp_path / "driver"
        comp = subprocess.run(["gcc", str(csrc), "-o", str(exe), "-ldl"],
                              capture_output=True)
        assert comp.returncode == 0, comp.stderr.decode()

        inp = tmp_path / "input.bin"
        outp = tmp_path / "output.bin"
        x.astype(np.float32).tofile(inp)

        env = dict(os.environ)
        # the embedded interpreter must find the repo and run on CPU with
        # no hardware-backend hook (same recipe as __graft_entry__.py)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = {k: v for k, v in env.items()
               if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))}
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run([str(exe), so, base, str(inp), str(outp)],
                           capture_output=True, timeout=300, env=env)
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode())
        assert b"C-OK" in r.stdout
        got = np.fromfile(outp, np.float32).reshape(4, 10)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


class TestQuantizedInference:
    """Weight-only int8/bf16 predictor mode (VERDICT r3 missing #8;
    reference mkldnn_quantizer.cc role, TPU-native form)."""

    def _artifact(self, tmp_path):
        import paddle1_tpu as paddle
        from paddle1_tpu.jit import InputSpec
        paddle.seed(0)
        model = paddle.nn.Sequential(
            paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
            paddle.nn.Linear(16, 4))
        path = str(tmp_path / "q/model")
        paddle.jit.save(model, path,
                        input_spec=[InputSpec([2, 8], "float32", "x")])
        return model, path

    def test_int8_weight_only_close_to_fp32(self, tmp_path):
        from paddle1_tpu import inference
        model, path = self._artifact(tmp_path)
        x = np.random.default_rng(0).standard_normal((2, 8)).astype(
            np.float32)

        cfg = inference.Config(path + ".pdmodel")
        ref = inference.create_predictor(cfg).run([x])[0]

        qcfg = inference.Config(path + ".pdmodel")
        qcfg.enable_quantized_inference()  # int8 default
        assert qcfg.precision_mode() == inference.PrecisionType.Int8
        out = inference.create_predictor(qcfg).run([x])[0]
        assert out.shape == ref.shape
        # int8 weight-only: small quantization error, same prediction
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err
        assert not np.allclose(out, ref)  # actually quantized

    def test_bf16_mode_runs(self, tmp_path):
        from paddle1_tpu import inference
        _, path = self._artifact(tmp_path)
        cfg = inference.Config(path + ".pdmodel")
        cfg.enable_quantized_inference(
            inference.PrecisionType.Bfloat16)
        out = inference.create_predictor(cfg).run(
            [np.ones((2, 8), np.float32)])[0]
        assert out.shape == (2, 4)

    def test_bad_precision_rejected(self):
        from paddle1_tpu import inference
        cfg = inference.Config()
        with pytest.raises(ValueError, match="Int8"):
            cfg.enable_quantized_inference(
                inference.PrecisionType.Half)
