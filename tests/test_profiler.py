"""Profiler: host spans, aggregation table, chrome trace export
(reference fluid/tests test_profiler.py)."""

import json
import os
import tempfile
import unittest

import numpy as np

import paddle1_tpu as paddle
from paddle1_tpu import profiler as prof


class TestProfiler(unittest.TestCase):
    def test_spans_and_export(self):
        prof.start_profiler()
        with prof.RecordEvent("outer"):
            x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
            y = paddle.matmul(x, x)
            _ = y.numpy()
        rows = prof.stop_profiler()
        names = [r[0] for r in rows]
        self.assertIn("outer", names)
        self.assertIn("matmul", names)  # eager dispatch auto-instrumented

    def test_chrome_trace_format(self):
        prof.start_profiler()
        with prof.RecordEvent("evt"):
            pass
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.json")
            prof.stop_profiler(profile_path=path)
            with open(path) as f:
                trace = json.load(f)
            self.assertIn("traceEvents", trace)
            evts = [e for e in trace["traceEvents"] if e["name"] == "evt"]
            self.assertEqual(len(evts), 1)
            self.assertEqual(evts[0]["ph"], "X")

    def test_disabled_is_noop(self):
        prof.reset_profiler()
        with prof.RecordEvent("nope"):
            pass
        rows = prof.stop_profiler()
        self.assertEqual(rows, [])

    def test_context_manager(self):
        with prof.profiler():
            x = paddle.to_tensor(np.ones((4, 4), np.float32))
            (x + x).numpy()
        # re-entrant: second use works
        with prof.profiler():
            pass
