"""Fault-tolerant generative serving (ISSUE 17): GenerationFleet with
bit-identical mid-stream failover and KV-pressure preemption.

The acceptance contracts pinned here:

* the streaming wire contract is exactly-once: per-token frames carry a
  monotone absolute sequence number, the client accepts a token iff it
  is the next expected index, duplicates (failover replays re-sending
  history) drop silently, and a gap means a desynced sender;
* a stream resumed from ``prompt + tokens already emitted`` with the
  same seed continues BIT-identically to the uninterrupted run —
  greedy AND sampled (the RNG key schedule is a pure function of
  (seed, token index), so the chain re-advances exactly);
* a replica SIGKILLed mid-stream (chaos ``gen_replica_kill``) loses no
  accepted stream: every in-flight stream fails over to a survivor and
  finishes identical to a single-process reference, with zero
  client-visible failures and ``unaccounted == 0`` at drain;
* a WEDGED replica (token plane frozen, heartbeats still flowing —
  chaos ``gen_replica_hang``) is caught by the fleet's stream-silence
  deadline, not the supervisor's hang timeout, and its streams migrate;
* KV pressure preempts lowest-priority streams (pages released the
  same tick, stream parked) and re-admits them bit-identically instead
  of surfacing :class:`KVPoolExhausted`;
* a rolling deploy migrates live streams by replay (no retry budget
  charged) and a failed canary rolls back with the old fleet intact;
* :class:`TokenStream` resolves ``cancel()`` vs ``result()`` vs
  mid-stream ``DeadlineExceeded`` first-wins — exactly one terminal
  state, always consistent with the raised type (ISSUE 17 satellite).
"""

import os
import textwrap
import threading
import time

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core import chaos, health
from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.serving import (CausalLM, DeadlineExceeded, DeployFailed,
                                 FleetStream, GenerationEngine,
                                 GenerationFleet, GenerationServer,
                                 ServerClosed, ServerOverloaded,
                                 StreamCancelled, StreamFailed, TokenStream)

VOCAB, MAX_SEQ, SLOTS, PS = 32, 64, 4, 8


@pytest.fixture(autouse=True)
def _isolate():
    health.reset()
    chaos.reset()
    yield
    health.reset()
    chaos.reset()


@pytest.fixture(scope="module")
def lm():
    paddle.seed(7)
    return CausalLM(vocab_size=VOCAB, d_model=16, nhead=2,
                    dim_feedforward=32, num_layers=2, max_seq=MAX_SEQ)


# ---------------------------------------------------------------------------
# FleetStream: the exactly-once receive contract


class TestFleetStreamContract:
    def test_in_order_frames_accumulate(self):
        st = FleetStream()
        assert st._feed(0, [3, 1]) == "ok"
        assert st._feed(2, [4]) == "ok"
        assert st.tokens == [3, 1, 4]

    def test_duplicate_frames_drop(self):
        """A failover replay re-sending delivered history is a no-op."""
        st = FleetStream()
        assert st._feed(0, [3, 1]) == "ok"
        assert st._feed(0, [3, 1]) == "dup"
        assert st._feed(1, [1]) == "dup"
        assert st.tokens == [3, 1]

    def test_partial_overlap_appends_only_the_fresh_suffix(self):
        """A frame straddling the delivered boundary contributes only
        the unseen tail — token i is delivered exactly once."""
        st = FleetStream()
        assert st._feed(0, [3, 1]) == "ok"
        assert st._feed(1, [1, 4, 5]) == "ok"
        assert st.tokens == [3, 1, 4, 5]

    def test_gap_means_desynced_sender(self):
        st = FleetStream()
        assert st._feed(0, [3]) == "ok"
        assert st._feed(2, [9]) == "gap"
        assert st.tokens == [3]  # the gap frame contributed nothing

    def test_finish_is_first_wins(self):
        st = FleetStream()
        assert st._finish("eos") is True
        assert st._finish("error", RuntimeError("late")) is False
        assert st.finish_reason == "eos"
        assert st.result() == []

    def test_frames_after_finish_are_dups(self):
        st = FleetStream()
        st._feed(0, [3])
        st._finish("length")
        assert st._feed(1, [4]) == "dup"
        assert st.result() == [3]

    def test_typed_error_surfaces_after_buffered_tokens(self):
        st = FleetStream()
        st._feed(0, [3, 1])
        st._finish("failed", StreamFailed("budget exhausted"))
        got = []
        with pytest.raises(StreamFailed):
            for tok in st:
                got.append(tok)
        assert got == [3, 1]          # everything delivered first
        assert st.tokens == [3, 1]    # partials stay readable

    def test_cancelled_iteration_is_clean_stop(self):
        st = FleetStream()
        st._feed(0, [3])
        st._finish("cancelled", StreamCancelled("x"))
        assert list(st) == [3]        # no raise on iteration
        with pytest.raises(StreamCancelled):
            st.result()               # result() stays typed

    def test_result_reader_deadline_keeps_stream_accounted(self):
        st = FleetStream()
        with pytest.raises(DeadlineExceeded, match="reader deadline"):
            st.result(timeout=0.05)
        assert not st.done()

    def test_cancel_invokes_fleet_hook_once(self):
        st = FleetStream()
        calls = []
        st._cancel_cb = calls.append
        st.cancel()
        st.cancel()
        assert len(calls) == 1


# ---------------------------------------------------------------------------
# streaming wire frames


class TestStreamWireFrames:
    def test_stream_frames_round_trip(self):
        """Token and end frames survive the framed protocol intact —
        including the end frame's token COUNT, which must not collide
        with the frame header's array-count slot ``n`` (send_msg owns
        ``n``; a clean 12-token close must not arrive as count=0 and
        masquerade as a lost-frame failover)."""
        import socket
        from paddle1_tpu.serving import wire
        a, b = socket.socketpair()
        try:
            wire.send_stream_tokens(a, 7, 3, [11, 12])
            wire.send_stream_end(a, 7, 12, "length")
            wire.send_stream_end(a, 8, 2, "error",
                                 etype="KVPoolExhausted", msg="full")
            h1, arrs = wire.recv_msg(b)
            assert h1["kind"] == wire.STREAM_TOKENS
            assert (h1["id"], h1["seq"], h1["toks"]) == (7, 3, [11, 12])
            assert arrs == []
            h2, _ = wire.recv_msg(b)
            assert h2["kind"] == wire.STREAM_END
            assert (h2["id"], h2["count"], h2["reason"]) == \
                (7, 12, "length")
            h3, _ = wire.recv_msg(b)
            assert (h3["count"], h3["etype"], h3["msg"]) == \
                (2, "KVPoolExhausted", "full")
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# fleet admission (no subprocesses: the queue/seed/shed plane)


class TestFleetAdmission:
    def test_submit_before_start_is_closed(self):
        fleet = GenerationFleet("x.py:make_model", replicas=2)
        with pytest.raises(ServerClosed, match="not admitting"):
            fleet.submit([1, 2, 3])

    def test_zero_replicas_rejected(self):
        with pytest.raises(InvalidArgumentError, match=">= 1 replica"):
            GenerationFleet("x.py:make_model", replicas=0)

    def test_seeds_are_minted_fleet_side(self):
        """A submit without a seed still gets one pinned at admission:
        failover replay is only bit-identical on the original seed, so
        the fleet — which owns the replay — must own the seed too."""
        fleet = GenerationFleet("x.py:make_model", replicas=1)
        fleet._accepting = True   # admission plane only; no replicas
        fleet.submit([1, 2, 3])
        fleet.submit([1, 2, 3])
        seeds = [r.seed for r in fleet._live.values()]
        assert len(set(seeds)) == 2
        assert all(isinstance(s, int) for s in seeds)

    def test_queue_depth_sheds_typed(self):
        fleet = GenerationFleet("x.py:make_model", replicas=1,
                                queue_depth=1)
        fleet._accepting = True
        fleet.submit([1, 2, 3])
        with pytest.raises(ServerOverloaded, match="stream shed"):
            fleet.submit([4, 5, 6])
        snap = fleet.metrics.snapshot()["counters"]
        assert snap["gen_fleet_shed_total"] == 1

    def test_invalid_args_are_typed(self):
        fleet = GenerationFleet("x.py:make_model", replicas=1)
        fleet._accepting = True
        with pytest.raises(InvalidArgumentError, match=">= 1 prompt"):
            fleet.submit([])
        with pytest.raises(InvalidArgumentError, match="max_new_tokens"):
            fleet.submit([1], max_new_tokens=0)


# ---------------------------------------------------------------------------
# resume replay parity: the mechanism failover/preemption both ride


class TestResumeReplayParity:
    """``submit(..., resume_tokens=emitted, seed=s)`` continues the
    stream bit-identically from the next token index — the foundation
    of mid-stream failover AND preempt/park re-admission."""

    @pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.9, 8)])
    def test_resume_continues_bit_identical(self, lm, temperature,
                                            top_k):
        srv = GenerationServer(lm, slots=SLOTS, max_seq=MAX_SEQ,
                               prefill_buckets=(8, 24)).start()
        try:
            prompt = [5, 9, 2, 7]
            ref = srv.generate(prompt, max_new_tokens=12,
                               temperature=temperature, top_k=top_k,
                               seed=11)
            assert len(ref) >= 2
            for cut in (1, len(ref) // 2, len(ref) - 1):
                st = srv.submit(prompt, max_new_tokens=12,
                                temperature=temperature, top_k=top_k,
                                seed=11, resume_tokens=ref[:cut])
                assert st.result(timeout=60) == ref[cut:], cut
        finally:
            rep = srv.drain()
        assert rep["unaccounted"] == 0

    def test_resume_without_seed_is_typed(self, lm):
        srv = GenerationServer(lm, slots=SLOTS, max_seq=MAX_SEQ,
                               prefill_buckets=(8, 24)).start()
        try:
            with pytest.raises(InvalidArgumentError,
                               match="original seed"):
                srv.submit([1, 2], max_new_tokens=8,
                           resume_tokens=[3])
        finally:
            srv.drain()


# ---------------------------------------------------------------------------
# KV-pressure preemption (in-process: chaos squats the page pool)


class TestKVPressurePreemption:
    def test_low_priority_parks_and_readmits_bit_identical(self, lm):
        """Chaos claims every free page mid-decode; with preemption on,
        the faulting low-priority stream parks (pages released) and
        re-admits by replay — output identical to a pressure-free run,
        KVPoolExhausted never client-visible."""
        prompts = [[3, 1, 4, 1], [5, 9, 2, 6], [8, 2, 8, 1]]
        seeds = [21, 22, 23]

        def run(pressure):
            chaos.reset()
            if pressure:
                chaos.configure("gen_page_pressure@3")
            eng = GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                                   prefill_buckets=(8, 24), paged=True,
                                   page_size=PS, pages=16,
                                   prefix_cache=0)
            srv = GenerationServer(eng, preempt=True).start()
            try:
                streams = [
                    srv.submit(p, max_new_tokens=16,
                               temperature=0.7, top_k=6, seed=s,
                               priority=(0 if i == 0 else 2))
                    for i, (p, s) in enumerate(zip(prompts, seeds))]
                outs = [st.result(timeout=120) for st in streams]
            finally:
                rep = srv.drain()
            assert rep["unaccounted"] == 0, rep
            assert rep["kv_pages_owed"] == 0, rep
            return outs, srv.metrics.snapshot()["counters"]

        ref, _ = run(pressure=False)
        got, counters = run(pressure=True)
        assert got == ref
        assert counters.get("gen_preemptions_total", 0) >= 1, counters
        assert counters.get("gen_preempt_readmits_total", 0) >= 1


# ---------------------------------------------------------------------------
# chaos: the generation-fleet injection points


class TestGenFleetChaosPoints:
    def test_spec_grammar(self):
        chaos.configure("gen_replica_kill@3:1,gen_replica_hang@5,"
                        "gen_page_pressure@2")
        assert chaos.enabled()
        with pytest.raises(ValueError, match="unknown chaos point"):
            chaos.configure("gen_replica_explode@1")
        with pytest.raises(ValueError, match="occurrence must be >= 1"):
            chaos.configure("gen_replica_kill@0")

    def test_frame_counter_and_rank_qualifier(self):
        chaos.configure("gen_replica_kill@2:0,gen_replica_hang@3:1")
        assert chaos.check_gen_replica(0) is None          # frame 1
        assert chaos.check_gen_replica(0) == \
            chaos.GEN_REPLICA_KILL                          # frame 2, rank 0
        assert chaos.check_gen_replica(0) is None          # frame 3: rank 0
        chaos.configure("gen_replica_hang@2")
        chaos.check_gen_replica(5)
        assert chaos.check_gen_replica(7) == \
            chaos.GEN_REPLICA_HANG  # unqualified: any rank's Nth frame

    def test_kill_outranks_hang_on_the_same_frame(self):
        chaos.configure("gen_replica_kill@1,gen_replica_hang@1")
        assert chaos.check_gen_replica(0) == chaos.GEN_REPLICA_KILL


# ---------------------------------------------------------------------------
# satellite: TokenStream cancel/result/deadline races resolve first-wins


class TestTokenStreamRaces:
    def test_finish_race_is_first_wins_and_consistent(self):
        """Two racers slam terminal states onto one stream; exactly one
        wins and ``result()`` raises the matching type — never a
        mixed state (reason says cancelled, raise says deadline)."""
        for _ in range(200):
            st = TokenStream(8)
            st._put(3)
            barrier = threading.Barrier(3)

            def deadline():
                barrier.wait()
                st._finish("deadline", DeadlineExceeded("racer"))

            def cancel():
                barrier.wait()
                st.cancel()
                st._finish("cancelled", StreamCancelled("racer"))

            ts = [threading.Thread(target=deadline),
                  threading.Thread(target=cancel)]
            for t in ts:
                t.start()
            barrier.wait()
            for t in ts:
                t.join()
            assert st.finish_reason in ("deadline", "cancelled")
            expect = (DeadlineExceeded if st.finish_reason == "deadline"
                      else StreamCancelled)
            with pytest.raises(expect):
                st.result()
            assert st.tokens == [3]  # partials survive either outcome

    def test_hammer_cancel_vs_result_vs_midstream_deadline(self, lm):
        """8 rounds of live streams with racing readers/cancellers and
        tight deadlines: every stream lands in exactly one terminal
        state consistent with what its reader observed, and the server
        ledger balances (nothing double-resolved, nothing leaked)."""
        eng = GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                               prefill_buckets=(8, 24), paged=True,
                               page_size=PS, pages=24)
        srv = GenerationServer(eng).start()
        outcomes = []

        def read(st, slot_out):
            try:
                slot_out.append(("done", st.result(timeout=30)))
            except BaseException as e:  # noqa: broad-except — recorded
                slot_out.append(("exc", e))

        try:
            for rnd in range(8):
                streams, results = [], []
                for i in range(4):
                    dl = 40.0 if i % 2 == 0 else None
                    streams.append(srv.submit(
                        [1 + rnd, 2 + i, 3, 4], max_new_tokens=24,
                        temperature=0.5, top_k=4, seed=100 + rnd * 8 + i,
                        deadline_ms=dl))
                    results.append([])
                readers = [threading.Thread(target=read, args=(st, out))
                           for st, out in zip(streams, results)]
                for t in readers:
                    t.start()
                time.sleep(0.005 * (rnd % 4))
                for st in streams[2:]:
                    st.cancel()
                for t in readers:
                    t.join()
                for st, out in zip(streams, results):
                    kind, val = out[0]
                    outcomes.append(st.finish_reason)
                    assert st.done()
                    if kind == "done":
                        assert st.finish_reason in ("eos", "length")
                        assert val == st.tokens
                    elif isinstance(val, StreamCancelled):
                        assert st.finish_reason == "cancelled"
                    elif isinstance(val, DeadlineExceeded):
                        # mid-stream wall deadline (reader timeout was
                        # generous, so it cannot be the reader's)
                        assert st.finish_reason in ("deadline", "budget")
                    else:  # pragma: no cover - unexpected type = fail
                        raise AssertionError(repr(val))
        finally:
            rep = srv.drain()
        assert rep["unaccounted"] == 0, (rep, outcomes)
        assert rep["tokens_owed"] == 0
        assert rep["kv_pages_owed"] == 0
        assert rep["fatal"] is None


# ---------------------------------------------------------------------------
# slow: the replica-subprocess matrix


FACTORY = textwrap.dedent("""\
    def make_model(arg):
        if arg == "boom":
            raise RuntimeError("factory boom")
        # SAME weights for every version tag: a hot-swap migration
        # replays streams on the new replicas, and the continuation is
        # only bit-identical when v2 serves the identical model
        import paddle1_tpu as paddle
        paddle.seed(0)
        return paddle.serving.CausalLM(
            vocab_size=32, d_model=16, nhead=2, dim_feedforward=32,
            num_layers=2, max_seq=64)
""")

GEN_CONFIG = {"slots": 4, "max_seq": 64, "prefill_buckets": [8, 24],
              "warmup": True}


def _make_genfleet(tmp_path, n=2, chaos_spec=None, **kw):
    factory = tmp_path / "factory.py"
    factory.write_text(FACTORY)
    kw.setdefault("version", "v1")
    kw.setdefault("hang_timeout", 60.0)
    kw.setdefault("poll_s", 0.1)
    kw.setdefault("ready_timeout_s", 180.0)
    kw.setdefault("stream_timeout_ms", 60000.0)
    for k, v in GEN_CONFIG.items():
        kw.setdefault(k, v)
    env = kw.pop("env", {})
    env.setdefault("JAX_PLATFORMS", "cpu")
    return GenerationFleet(f"{factory}:make_model", replicas=n, env=env,
                           work_dir=str(tmp_path / "genfleet"),
                           chaos_spec=chaos_spec, **kw)


def _reference(specs):
    """Uninterrupted single-process tokens for the FACTORY model."""
    paddle.seed(0)
    lm = CausalLM(vocab_size=32, d_model=16, nhead=2,
                  dim_feedforward=32, num_layers=2, max_seq=64)
    srv = GenerationServer(lm, slots=4, max_seq=64,
                           prefill_buckets=(8, 24)).start()
    try:
        return [srv.generate(s["prompt"],
                             max_new_tokens=s["max_new"],
                             temperature=s.get("temperature", 0.0),
                             top_k=s.get("top_k", 0),
                             seed=s["seed"])
                for s in specs]
    finally:
        srv.drain()


def _specs(n, max_new=12):
    """Half greedy, half sampled — failover parity must hold for both."""
    out = []
    for i in range(n):
        s = {"prompt": [2 + i, 7, 1 + (i % 3), 9], "max_new": max_new,
             "seed": 50 + i}
        if i % 2:
            s.update(temperature=0.8, top_k=8)
        out.append(s)
    return out


@pytest.mark.slow
class TestGenFleetSubprocessMatrix:
    def test_kill_mid_stream_failover_bit_identical(self, tmp_path):
        """SIGKILL replicas on their 10th token frame: every accepted
        stream fails over and completes IDENTICAL to the uninterrupted
        reference — greedy and sampled — with zero client-visible
        failures and a balanced ledger. (72 frames over 3 replicas:
        the pigeonhole guarantees at least one kill fires mid-stream;
        restarted lives replay chaos-free.)"""
        specs = _specs(6)
        ref = _reference(specs)
        fleet = _make_genfleet(tmp_path, n=3, retry_max=5,
                               streams_per_replica=2,
                               chaos_spec="gen_replica_kill@10")
        fleet.start()
        try:
            streams = [fleet.submit(s["prompt"],
                                    max_new_tokens=s["max_new"],
                                    temperature=s.get("temperature", 0.0),
                                    top_k=s.get("top_k", 0),
                                    seed=s["seed"])
                       for s in specs]
            outs = [st.result(timeout=300) for st in streams]
        finally:
            rep = fleet.drain()
        assert outs == ref
        assert rep["unaccounted"] == 0, rep
        assert rep["completed"] == len(specs)
        assert rep["errors"] == 0 and rep["stream_failed"] == 0
        assert rep["failovers"] >= 1, rep
        assert rep["replica_restarts"] >= 1, rep
        # one compiled decode signature per replica process, across
        # failover replays (resume prefill rides the prompt buckets)
        for rank, info in rep["replicas"].items():
            assert info["decode_compiles"] <= 1, rep["replicas"]

    def test_wedged_stream_caught_by_silence_deadline(self, tmp_path):
        """gen_replica_hang freezes the token plane while heartbeats
        keep flowing: only the fleet's wedged-stream transport deadline
        can catch it. The wedged rank restarts and its streams finish
        bit-identically elsewhere."""
        specs = _specs(4)
        ref = _reference(specs)
        fleet = _make_genfleet(tmp_path, n=2, retry_max=5,
                               streams_per_replica=2,
                               stream_timeout_ms=3000.0,
                               chaos_spec="gen_replica_hang@8")
        fleet.start()
        try:
            streams = [fleet.submit(s["prompt"],
                                    max_new_tokens=s["max_new"],
                                    temperature=s.get("temperature", 0.0),
                                    top_k=s.get("top_k", 0),
                                    seed=s["seed"])
                       for s in specs]
            outs = [st.result(timeout=300) for st in streams]
        finally:
            rep = fleet.drain()
            snap = fleet.metrics.snapshot()["counters"]
        assert outs == ref
        assert rep["unaccounted"] == 0, rep
        assert rep["errors"] == 0 and rep["stream_failed"] == 0
        assert snap.get("gen_fleet_replica_wedged_total", 0) >= 1, snap
        assert rep["replica_restarts"] >= 1, rep

    def test_preempt_readmit_under_page_pressure(self, tmp_path):
        """A tight page pool + concurrent mixed-priority streams: the
        replica preempts/parks instead of failing, and every stream —
        preempted included — finishes identical to a roomy
        single-process run. KVPoolExhausted is unreachable for admitted
        streams."""
        specs = _specs(4, max_new=16)
        ref = _reference(specs)  # roomy: no paging pressure at all
        # pages=12 → 11 usable: warm-up's max_seq-bucket prefill needs
        # ceil(63/8)=8 pages (must fit), but 4 concurrent 20-token
        # streams want 4*3=12 — admission pressure is guaranteed
        fleet = _make_genfleet(tmp_path, n=1, paged=True, page_size=8,
                               pages=12, prefix_cache=0, preempt=True,
                               streams_per_replica=4)
        fleet.start()
        try:
            streams = [fleet.submit(s["prompt"],
                                    max_new_tokens=s["max_new"],
                                    temperature=s.get("temperature", 0.0),
                                    top_k=s.get("top_k", 0),
                                    seed=s["seed"],
                                    priority=i % 3)
                       for i, s in enumerate(specs)]
            outs = [st.result(timeout=300) for st in streams]
        finally:
            rep = fleet.drain()
        assert outs == ref
        assert rep["unaccounted"] == 0, rep
        assert rep["errors"] == 0 and rep["stream_failed"] == 0
        info = rep["replicas"].get(0)
        if info is not None and info.get("pool"):
            assert info["pool"]["pages_in_use"] == 0, info

    def test_hot_swap_migrates_live_streams_bit_identical(self,
                                                          tmp_path):
        """deploy() under live streams: each retiring replica's
        in-flight streams migrate by replay onto the new version (same
        weights) and finish bit-identically; no retry budget charged,
        zero drops. Decode is chaos-slowed so streams straddle the
        swap."""
        specs = _specs(4, max_new=48)
        ref = _reference(specs)
        slow = ",".join(f"gen_slow_step@{i}" for i in range(1, 600))
        fleet = _make_genfleet(
            tmp_path, n=2, streams_per_replica=2, chaos_spec=slow,
            env={"JAX_PLATFORMS": "cpu",
                 "FLAGS_serve_chaos_slow_s": "0.4"})
        fleet.start()
        try:
            streams = [fleet.submit(s["prompt"],
                                    max_new_tokens=s["max_new"],
                                    temperature=s.get("temperature", 0.0),
                                    top_k=s.get("top_k", 0),
                                    seed=s["seed"])
                       for s in specs]
            time.sleep(1.0)  # let the streams start emitting
            out = fleet.deploy(fleet.model_spec, "v2",
                               canary_prompt=[1, 2, 3])
            assert out["rolled"] == 2
            outs = [st.result(timeout=300) for st in streams]
        finally:
            rep = fleet.drain()
        assert outs == ref
        assert fleet.version == "v2"
        assert rep["unaccounted"] == 0, rep
        assert rep["errors"] == 0 and rep["stream_failed"] == 0
        assert rep["migrations"] >= 1, rep
        assert rep["deploys"] == 1
        for info in rep["replicas"].values():
            assert info["version"] == "v2", rep["replicas"]

    def test_failed_canary_rolls_back_with_fleet_intact(self, tmp_path):
        fleet = _make_genfleet(tmp_path, n=1)
        fleet.start()
        try:
            before = fleet.generate([4, 2, 1], max_new_tokens=6,
                                    seed=9, timeout=120)
            with pytest.raises(DeployFailed, match="never became"):
                fleet.deploy(fleet.model_spec, "v2", model_arg="boom",
                             ready_timeout_s=25.0)
            assert fleet.version == "v1"
            after = fleet.generate([4, 2, 1], max_new_tokens=6,
                                   seed=9, timeout=120)
            assert after == before  # the old fleet kept serving
        finally:
            rep = fleet.drain()
        assert rep["unaccounted"] == 0, rep
        snap = fleet.metrics.snapshot()["counters"]
        assert snap.get("gen_fleet_rollbacks_total", 0) == 1

    def test_scale_to_migrates_streams_bit_identical(self, tmp_path):
        """ISSUE 18: replica count is the generative fleet's slot/page
        actuator. Scale-out under live chaos-slowed streams adds
        capacity without touching them; the scale-in that follows
        drains its rank's in-flight streams by bit-identical replay —
        both transitions counted and the ledger balanced."""
        specs = _specs(4, max_new=32)
        ref = _reference(specs)
        slow = ",".join(f"gen_slow_step@{i}" for i in range(1, 400))
        fleet = _make_genfleet(
            tmp_path, n=2, streams_per_replica=2, chaos_spec=slow,
            env={"JAX_PLATFORMS": "cpu",
                 "FLAGS_serve_chaos_slow_s": "0.3"})
        fleet.start()
        try:
            streams = [fleet.submit(s["prompt"],
                                    max_new_tokens=s["max_new"],
                                    temperature=s.get("temperature",
                                                      0.0),
                                    top_k=s.get("top_k", 0),
                                    seed=s["seed"])
                       for s in specs]
            time.sleep(1.0)  # streams are mid-decode when we scale
            up = fleet.scale_to(3, reason="autoscale out")
            assert up["from"] == 2 and len(up["added"]) == 1
            assert fleet.ready_replicas() == 3
            down = fleet.scale_to(2, reason="autoscale in")
            assert down["retired"] == [2]
            outs = [st.result(timeout=300) for st in streams]
        finally:
            rep = fleet.drain()
        assert outs == ref
        assert rep["unaccounted"] == 0, rep
        assert rep["errors"] == 0 and rep["stream_failed"] == 0
        snap = fleet.metrics.snapshot()["counters"]
        assert snap["scale_out_total"] == 1
        assert snap["scale_in_total"] == 1
