"""nn layer correctness: shapes, gradients, state_dict, hooks."""

import numpy as np

import paddle1_tpu as paddle
from paddle1_tpu import nn
from op_test import OpTest

F = nn.functional


class TestLinearConv(OpTest):
    def test_linear_matches_manual(self):
        paddle.seed(0)
        lin = nn.Linear(4, 3)
        x = np.random.randn(2, 4).astype(np.float32)
        out = lin(paddle.to_tensor(x))
        ref = x @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)

    def test_conv2d_matches_torch_semantics(self):
        # reference semantics: NCHW, weight [out,in,kh,kw]
        import jax
        paddle.seed(0)
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = np.random.randn(2, 3, 16, 16).astype(np.float32)
        out = conv(paddle.to_tensor(x))
        assert out.shape == [2, 8, 8, 8]
        out.sum().backward()
        assert conv.weight.grad is not None
        assert conv.bias.grad is not None

    def test_conv_grad_numeric(self):
        w = np.random.randn(2, 1, 3, 3).astype(np.float32) * 0.5
        x = np.random.randn(1, 1, 6, 6).astype(np.float32)
        self.check_grad(
            lambda xi, wi: F.conv2d(xi, wi, padding=1),
            [x, w], grad_input_idx=(0, 1), delta=1e-2, rtol=5e-2, atol=5e-3)

    def test_conv2d_transpose_shape(self):
        deconv = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1)
        x = paddle.to_tensor(np.random.randn(1, 4, 8, 8).astype(np.float32))
        out = deconv(x)
        assert out.shape == [1, 2, 15, 15], out.shape

    def test_depthwise_groups(self):
        conv = nn.Conv2D(4, 4, 3, groups=4, padding=1)
        x = paddle.to_tensor(np.random.randn(1, 4, 5, 5).astype(np.float32))
        assert conv(x).shape == [1, 4, 5, 5]


class TestNorms(OpTest):
    def test_layer_norm_stats(self):
        ln = nn.LayerNorm(16)
        x = np.random.randn(4, 16).astype(np.float32) * 3 + 1
        out = ln(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out.mean(-1), np.zeros(4), atol=1e-5)
        np.testing.assert_allclose(out.std(-1), np.ones(4), atol=1e-2)

    def test_batch_norm_train_and_eval(self):
        bn = nn.BatchNorm2D(3)
        x = np.random.randn(8, 3, 4, 4).astype(np.float32) * 2 + 5
        bn.train()
        y = bn(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(y.mean((0, 2, 3)), np.zeros(3), atol=1e-4)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), np.zeros(3))
        bn.eval()
        y2 = bn(paddle.to_tensor(x))
        assert y2.shape == [8, 3, 4, 4]

    def test_group_norm(self):
        gn = nn.GroupNorm(2, 4)
        x = paddle.to_tensor(np.random.randn(2, 4, 5, 5).astype(np.float32))
        assert gn(x).shape == [2, 4, 5, 5]

    def test_rms_norm(self):
        rn = nn.RMSNorm(8)
        x = paddle.to_tensor(np.random.randn(3, 8).astype(np.float32))
        out = rn(x)
        assert out.shape == [3, 8]


class TestActivationsPooling(OpTest):
    def test_activations(self):
        x = np.linspace(-3, 3, 13).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(F.relu(t).numpy(), np.maximum(x, 0))
        np.testing.assert_allclose(F.sigmoid(t).numpy(),
                                   1 / (1 + np.exp(-x)), rtol=1e-5)
        np.testing.assert_allclose(
            F.softmax(paddle.to_tensor(x.reshape(1, -1))).numpy().sum(),
            1.0, rtol=1e-5)
        self.check_grad(F.gelu, [np.random.randn(5).astype(np.float32)])

    def test_pools(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = F.max_pool2d(paddle.to_tensor(x), 2, 2)
        np.testing.assert_array_equal(out.numpy().reshape(2, 2),
                                      [[5, 7], [13, 15]])
        out = F.avg_pool2d(paddle.to_tensor(x), 2, 2)
        np.testing.assert_allclose(out.numpy().reshape(2, 2),
                                   [[2.5, 4.5], [10.5, 12.5]])

    def test_adaptive_pool(self):
        x = paddle.to_tensor(np.random.randn(1, 2, 7, 7).astype(np.float32))
        out = F.adaptive_avg_pool2d(x, 3)
        assert out.shape == [1, 2, 3, 3]


class TestEmbeddingDropout(OpTest):
    def test_embedding_lookup_and_grad(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 1]], np.int64))
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        out.sum().backward()
        g = emb.weight.grad.numpy()
        # row 1 used twice
        np.testing.assert_allclose(g[1], 2 * np.ones(4))
        np.testing.assert_allclose(g[5], np.zeros(4))

    def test_dropout_modes(self):
        paddle.seed(7)
        x = paddle.to_tensor(np.ones((1000,), np.float32))
        out = F.dropout(x, p=0.5, training=True)
        kept = out.numpy()
        frac = (kept != 0).mean()
        assert 0.4 < frac < 0.6
        np.testing.assert_allclose(kept[kept != 0], 2.0, rtol=1e-6)
        out_eval = F.dropout(x, p=0.5, training=False)
        np.testing.assert_allclose(out_eval.numpy(), 1.0)


class TestRNN(OpTest):
    def test_lstm_shapes(self):
        lstm = nn.LSTM(8, 16, num_layers=2)
        x = paddle.to_tensor(np.random.randn(4, 5, 8).astype(np.float32))
        out, (h, c) = lstm(x)
        assert out.shape == [4, 5, 16]
        assert h.shape == [2, 4, 16]
        assert c.shape == [2, 4, 16]
        out.sum().backward()
        assert lstm.weight_ih_l0.grad is not None

    def test_bidirectional_gru(self):
        gru = nn.GRU(4, 6, direction="bidirect")
        x = paddle.to_tensor(np.random.randn(2, 3, 4).astype(np.float32))
        out, h = gru(x)
        assert out.shape == [2, 3, 12]
        assert h.shape == [2, 2, 6]

    def test_lstm_cell(self):
        cell = nn.LSTMCell(4, 8)
        x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
        out, (h, c) = cell(x)
        assert out.shape == [2, 8]


class TestTransformer(OpTest):
    def test_mha_forward_backward(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.to_tensor(np.random.randn(2, 6, 16).astype(np.float32))
        out = mha(x, x, x)
        assert out.shape == [2, 6, 16]
        out.sum().backward()
        assert mha.q_proj.weight.grad is not None

    def test_encoder_layer(self):
        enc_layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(enc_layer, 2)
        x = paddle.to_tensor(np.random.randn(2, 5, 16).astype(np.float32))
        out = enc(x)
        assert out.shape == [2, 5, 16]

    def test_full_transformer(self):
        model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32,
                               dropout=0.0)
        src = paddle.to_tensor(np.random.randn(2, 4, 16).astype(np.float32))
        tgt = paddle.to_tensor(np.random.randn(2, 3, 16).astype(np.float32))
        out = model(src, tgt)
        assert out.shape == [2, 3, 16]


class TestLayerProtocol(OpTest):
    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = net.state_dict()
        assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}
        net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        net2.set_state_dict(sd)
        for (k1, v1), (k2, v2) in zip(sorted(net.state_dict().items()),
                                      sorted(net2.state_dict().items())):
            np.testing.assert_allclose(v1.numpy(), v2.numpy())

    def test_forward_hooks(self):
        lin = nn.Linear(2, 2)
        calls = []
        h = lin.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        lin(paddle.to_tensor(np.zeros((1, 2), np.float32)))
        assert calls == [1]
        h.remove()
        lin(paddle.to_tensor(np.zeros((1, 2), np.float32)))
        assert calls == [1]

    def test_train_eval_propagates(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        net.eval()
        assert not net[1].training
        net.train()
        assert net[1].training

    def test_named_parameters_unique(self):
        shared = nn.Linear(3, 3)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = shared
                self.b = shared

            def forward(self, x):
                return self.b(self.a(x))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert len(names) == 2  # shared params counted once


class TestLosses(OpTest):
    def test_cross_entropy_matches_manual(self):
        logits = np.random.randn(4, 5).astype(np.float32)
        labels = np.array([0, 2, 1, 4], np.int64)
        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels))
        # manual
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(out.item(), ref, rtol=1e-5)

    def test_mse_and_l1(self):
        a = np.random.randn(6).astype(np.float32)
        b = np.random.randn(6).astype(np.float32)
        np.testing.assert_allclose(
            F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b)).item(),
            ((a - b) ** 2).mean(), rtol=1e-5)
        np.testing.assert_allclose(
            F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b)).item(),
            np.abs(a - b).mean(), rtol=1e-5)

    def test_bce_with_logits_stable(self):
        x = np.array([100.0, -100.0, 0.0], np.float32)
        y = np.array([1.0, 0.0, 1.0], np.float32)
        out = F.binary_cross_entropy_with_logits(
            paddle.to_tensor(x), paddle.to_tensor(y))
        assert np.isfinite(out.item())

    def test_ignore_index(self):
        logits = np.random.randn(3, 4).astype(np.float32)
        labels = np.array([1, -100, 2], np.int64)
        out = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels), ignore_index=-100)
        mask = labels != -100
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(3), np.clip(labels, 0, None)])[mask].mean()
        np.testing.assert_allclose(out.item(), ref, rtol=1e-4)


class TestParitySweepNN:
    """r3 nn-surface parity sweep: hsigmoid_loss/HSigmoidLoss, diag_embed,
    elu_, RNN base classes (reference nn/functional/loss.py:312,
    nn/functional/extension.py diag_embed, nn/layer/rnn.py:134,844)."""

    def test_hsigmoid_is_a_distribution(self):
        # the binary-tree path losses must define a normalized
        # distribution: sum_l exp(-loss(l)) == 1 for any x
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(0)
        C, D = 11, 6
        x = paddle.to_tensor(rng.standard_normal((1, D)).astype(np.float32))
        w = paddle.to_tensor(rng.standard_normal((C - 1, D))
                             .astype(np.float32))
        b = paddle.to_tensor(rng.standard_normal((C - 1,))
                             .astype(np.float32))
        probs = []
        for label in range(C):
            l = paddle.to_tensor(np.array([label]))
            loss = F.hsigmoid_loss(x, l, C, w, bias=b)
            probs.append(np.exp(-float(loss.numpy()[0, 0])))
        np.testing.assert_allclose(sum(probs), 1.0, rtol=1e-5)

    def test_hsigmoid_layer_trains(self):
        import paddle1_tpu as paddle
        rng = np.random.default_rng(1)
        hs = paddle.nn.HSigmoidLoss(4, 6)
        opt = paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=hs.parameters())
        x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
        y = paddle.to_tensor(np.arange(8, dtype=np.int64) % 6)
        first = None
        for _ in range(30):
            loss = hs(x, y).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss.numpy())
        assert float(loss.numpy()) < first * 0.7

    def test_hsigmoid_custom_path(self):
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(rng.standard_normal((2, 3)).astype(np.float32))
        w = paddle.to_tensor(rng.standard_normal((4, 3)).astype(np.float32))
        lab = paddle.to_tensor(np.array([0, 1]))
        table = paddle.to_tensor(np.array([[0, 1, -1], [0, 2, 3]],
                                          np.int64))
        code = paddle.to_tensor(np.array([[1.0, 0.0, 0.0],
                                          [0.0, 1.0, 1.0]], np.float32))
        loss = F.hsigmoid_loss(x, lab, 5, w, path_table=table,
                               path_code=code)
        assert loss.shape == [2, 1]
        assert np.isfinite(loss.numpy()).all()

    def test_diag_embed(self):
        import paddle1_tpu.nn.functional as F
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        d = F.diag_embed(x)
        assert d.shape == [2, 3, 3]
        np.testing.assert_allclose(np.asarray(d.numpy())[1],
                                   np.diag([3.0, 4.0, 5.0]))
        off = F.diag_embed(x, offset=1)
        assert off.shape == [2, 4, 4]
        np.testing.assert_allclose(np.asarray(off.numpy())[0],
                                   np.diag([0.0, 1.0, 2.0], k=1))

    def test_elu_inplace(self):
        import paddle1_tpu.nn.functional as F
        t = paddle.to_tensor(np.float32([-1.0, 2.0]))
        out = F.elu_(t)
        assert out is t
        np.testing.assert_allclose(t.numpy(), [np.expm1(-1.0), 2.0],
                                   rtol=1e-6)

    def test_rnn_base_classes_exported(self):
        assert isinstance(paddle.nn.LSTM(4, 8), paddle.nn.RNNBase)
        assert issubclass(paddle.nn.LSTMCell, paddle.nn.RNNCellBase)


class TestConvNHWCInternal(OpTest):
    """conv_nhwc flag (BASELINE conv-throughput candidate fix): the
    NHWC-internal path must be numerically identical to the NCHW path,
    forward and backward."""

    def test_flag_path_matches_nchw(self):
        import numpy as np
        from paddle1_tpu.core import flags as core_flags
        from paddle1_tpu.core.tensor import to_tensor
        import paddle1_tpu.nn.functional as F

        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
        w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)
        b = rng.standard_normal((4,)).astype(np.float32)

        def run():
            xt = to_tensor(x)
            xt.stop_gradient = False
            out = F.conv2d(xt, to_tensor(w), to_tensor(b), stride=2,
                           padding=1)
            out.sum().backward()
            return np.asarray(out.numpy()), np.asarray(xt.grad.numpy())

        with core_flags.flags_guard(conv_nhwc="never"):
            o1, g1 = run()
        with core_flags.flags_guard(conv_nhwc="always"):
            o2, g2 = run()
        np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-5)

    def test_grouped_conv_flag_path(self):
        import numpy as np
        from paddle1_tpu.core import flags as core_flags
        from paddle1_tpu.core.tensor import to_tensor
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(1)
        x = rng.standard_normal((1, 4, 6, 6)).astype(np.float32)
        w = rng.standard_normal((8, 2, 3, 3)).astype(np.float32)
        with core_flags.flags_guard(conv_nhwc="never"):
            o1 = np.asarray(F.conv2d(to_tensor(x), to_tensor(w),
                                     groups=2, padding=1).numpy())
        with core_flags.flags_guard(conv_nhwc="always"):
            o2 = np.asarray(F.conv2d(to_tensor(x), to_tensor(w),
                                     groups=2, padding=1).numpy())
        np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5)

    def test_pool_flag_path_matches_nchw(self):
        # r5: pools joined the channels-last region (NCHW reduce_window
        # measured ~100x slower on chip — chip_results/conv_probe2.txt)
        import numpy as np
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import to_tensor
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(2)
        x = rng.standard_normal((2, 3, 9, 9)).astype(np.float32)
        for fn, kw in [(F.max_pool2d, dict(kernel_size=3, stride=2,
                                           padding=1)),
                       (F.max_pool2d, dict(kernel_size=2, stride=2,
                                           ceil_mode=True)),
                       (F.avg_pool2d, dict(kernel_size=3, stride=2,
                                           padding=1, exclusive=True)),
                       (F.avg_pool2d, dict(kernel_size=3, stride=3,
                                           exclusive=False)),
                       (F.adaptive_avg_pool2d, dict(output_size=3))]:
            def run():
                xt = to_tensor(x)
                xt.stop_gradient = False
                out = fn(xt, **kw)
                out.sum().backward()
                return (np.asarray(out.numpy()),
                        np.asarray(xt.grad.numpy()))
            with flags_guard(conv_nhwc="never"):
                o1, g1 = run()
            with flags_guard(conv_nhwc="always"):
                o2, g2 = run()
            np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"{fn.__name__} {kw}")
            np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"{fn.__name__} {kw} grad")

    def test_batch_norm_flag_path_matches_nchw(self):
        import numpy as np
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import to_tensor
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(3)
        x = rng.standard_normal((4, 5, 6, 6)).astype(np.float32)
        w = rng.standard_normal((5,)).astype(np.float32)
        b = rng.standard_normal((5,)).astype(np.float32)
        m = rng.standard_normal((5,)).astype(np.float32)
        v = rng.standard_normal((5,)).astype(np.float32) ** 2 + 0.5
        for training in (False, True):
            def run():
                xt = to_tensor(x)
                xt.stop_gradient = False
                out = F.batch_norm(xt, to_tensor(m.copy()),
                                   to_tensor(v.copy()), to_tensor(w),
                                   to_tensor(b), training=training)
                out.sum().backward()
                return (np.asarray(out.numpy()),
                        np.asarray(xt.grad.numpy()))
            with flags_guard(conv_nhwc="never"):
                o1, g1 = run()
            with flags_guard(conv_nhwc="always"):
                o2, g2 = run()
            np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"training={training}")
            np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"training={training} grad")

    def test_conv_1d_3d_flag_path_matches(self):
        # r5: the channels-last region generalized beyond 2-D — same
        # physics (channel dim must be the lane dim on this backend)
        import numpy as np
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import to_tensor
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(5)
        cases = [
            (F.conv1d, rng.standard_normal((2, 3, 12)),
             rng.standard_normal((5, 3, 3)), dict(stride=2, padding=1)),
            (F.conv1d, rng.standard_normal((1, 4, 10)),
             rng.standard_normal((8, 2, 3)), dict(groups=2, padding=1)),
            (F.conv3d, rng.standard_normal((2, 3, 5, 6, 6)),
             rng.standard_normal((4, 3, 3, 3, 3)),
             dict(stride=2, padding=1)),
            (F.conv3d, rng.standard_normal((1, 4, 4, 5, 5)),
             rng.standard_normal((8, 2, 3, 3, 3)),
             dict(groups=2, padding=1, dilation=1)),
        ]
        for fn, x, w, kw in cases:
            x = x.astype(np.float32)
            w = (w * 0.3).astype(np.float32)

            def run():
                xt = to_tensor(x)
                xt.stop_gradient = False
                out = fn(xt, to_tensor(w), **kw)
                out.sum().backward()
                return (np.asarray(out.numpy()),
                        np.asarray(xt.grad.numpy()))
            with flags_guard(conv_nhwc="never"):
                o1, g1 = run()
            with flags_guard(conv_nhwc="always"):
                o2, g2 = run()
            np.testing.assert_allclose(o1, o2, rtol=2e-5, atol=2e-5,
                                       err_msg=f"{fn.__name__} {kw}")
            np.testing.assert_allclose(g1, g2, rtol=2e-5, atol=2e-5,
                                       err_msg=f"{fn.__name__} {kw} grad")

    def test_conv_transpose_flag_path_matches(self):
        import numpy as np
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import to_tensor
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(6)
        cases = [
            (F.conv1d_transpose, rng.standard_normal((2, 4, 8)),
             rng.standard_normal((4, 3, 3)), dict(stride=2, padding=1)),
            (F.conv2d_transpose, rng.standard_normal((2, 4, 6, 6)),
             rng.standard_normal((4, 3, 3, 3)),
             dict(stride=2, padding=1, output_padding=1)),
            (F.conv2d_transpose, rng.standard_normal((1, 4, 5, 5)),
             rng.standard_normal((4, 2, 3, 3)), dict(groups=2)),
            (F.conv3d_transpose, rng.standard_normal((1, 3, 4, 4, 4)),
             rng.standard_normal((3, 2, 3, 3, 3)),
             dict(stride=2, padding=1)),
        ]
        for fn, x, w, kw in cases:
            x = x.astype(np.float32)
            w = (w * 0.3).astype(np.float32)

            def run():
                xt = to_tensor(x)
                xt.stop_gradient = False
                out = fn(xt, to_tensor(w), **kw)
                out.sum().backward()
                return (np.asarray(out.numpy()),
                        np.asarray(xt.grad.numpy()))
            with flags_guard(conv_nhwc="never"):
                o1, g1 = run()
            with flags_guard(conv_nhwc="always"):
                o2, g2 = run()
            np.testing.assert_allclose(o1, o2, rtol=2e-5, atol=2e-5,
                                       err_msg=f"{fn.__name__} {kw}")
            np.testing.assert_allclose(g1, g2, rtol=2e-5, atol=2e-5,
                                       err_msg=f"{fn.__name__} {kw} grad")

    def test_pool_1d_3d_and_bn_ranks_flag_path(self):
        import numpy as np
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import to_tensor
        import paddle1_tpu.nn.functional as F
        rng = np.random.default_rng(7)
        pool_cases = [
            (F.max_pool1d, rng.standard_normal((2, 3, 11)),
             dict(kernel_size=3, stride=2, padding=1)),
            (F.avg_pool1d, rng.standard_normal((2, 3, 10)),
             dict(kernel_size=2, stride=2)),
            (F.max_pool3d, rng.standard_normal((2, 3, 6, 7, 7)),
             dict(kernel_size=2, stride=2, ceil_mode=True)),
            (F.avg_pool3d, rng.standard_normal((2, 3, 6, 6, 6)),
             dict(kernel_size=3, stride=2, padding=1)),
        ]
        for fn, x, kw in pool_cases:
            x = x.astype(np.float32)

            def run():
                xt = to_tensor(x)
                xt.stop_gradient = False
                out = fn(xt, **kw)
                out.sum().backward()
                return (np.asarray(out.numpy()),
                        np.asarray(xt.grad.numpy()))
            with flags_guard(conv_nhwc="never"):
                o1, g1 = run()
            with flags_guard(conv_nhwc="always"):
                o2, g2 = run()
            np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"{fn.__name__} {kw}")
            np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"{fn.__name__} {kw} grad")
        # batch norm at 3-D (NCL) and 5-D (NCDHW)
        for shape in [(4, 5, 7), (2, 5, 3, 4, 4)]:
            x = rng.standard_normal(shape).astype(np.float32)
            w = rng.standard_normal((5,)).astype(np.float32)
            b = rng.standard_normal((5,)).astype(np.float32)

            def run():
                xt = to_tensor(x)
                xt.stop_gradient = False
                out = F.batch_norm(xt, to_tensor(np.zeros(5, np.float32)),
                                   to_tensor(np.ones(5, np.float32)),
                                   to_tensor(w), to_tensor(b),
                                   training=True)
                out.sum().backward()
                return (np.asarray(out.numpy()),
                        np.asarray(xt.grad.numpy()))
            with flags_guard(conv_nhwc="never"):
                o1, g1 = run()
            with flags_guard(conv_nhwc="always"):
                o2, g2 = run()
            np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"bn {shape}")
            np.testing.assert_allclose(g1, g2, rtol=1e-5, atol=1e-5,
                                       err_msg=f"bn {shape} grad")

    def test_small_cnn_end_to_end_flag_path(self):
        # conv+bn+pool+residual+fc: the full channels-last region in one
        # model, forward and parameter gradients identical to NCHW
        import numpy as np
        import paddle1_tpu as paddle
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import Tensor

        def build_and_step(seed):
            np.random.seed(seed)
            paddle.seed(seed)
            m = paddle.nn.Sequential(
                paddle.nn.Conv2D(3, 8, 3, padding=1),
                paddle.nn.BatchNorm2D(8),
                paddle.nn.ReLU(),
                paddle.nn.MaxPool2D(2, 2),
                paddle.nn.Conv2D(8, 8, 3, padding=1),
                paddle.nn.AdaptiveAvgPool2D(1),
                paddle.nn.Flatten(),
                paddle.nn.Linear(8, 4))
            rng = np.random.default_rng(0)
            x = Tensor(rng.standard_normal((2, 3, 12, 12))
                       .astype(np.float32))
            y = Tensor(rng.integers(0, 4, (2,)).astype(np.int64))
            loss = paddle.nn.functional.cross_entropy(m(x), y)
            loss.backward()
            return (float(np.asarray(loss.data)),
                    [np.asarray(p.grad.numpy()) for p in m.parameters()
                     if p.grad is not None])
        with flags_guard(conv_nhwc="never"):
            l1, g1 = build_and_step(7)
        with flags_guard(conv_nhwc="always"):
            l2, g2 = build_and_step(7)
        assert abs(l1 - l2) < 1e-5, (l1, l2)
        assert len(g1) == len(g2) and len(g1) > 0
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestConvBlockLayoutStability(OpTest):
    """ISSUE 15: a conv -> BN -> act -> pool residual block must stay
    layout-stable end to end in the channels-last region — only the
    stem/head boundary transposes survive XLA's cancellation, and the
    fused-BN Pallas path (NHWC-native) adds ZERO transposes of its own.
    This is the CPU-measurable face of the ~15% copy/layout overhead in
    chip_results/resnet_trace_b32.txt."""

    def _block_hlo_counts(self, fused):
        import warnings
        import jax.numpy as jnp
        import numpy as np
        import paddle1_tpu as paddle
        import paddle1_tpu.nn.functional as F
        from bench_utils import compiled_hlo_layout_census
        from paddle1_tpu.autograd import engine as ae
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import Tensor

        paddle.seed(0)
        conv1 = paddle.nn.Conv2D(64, 64, 3, padding=1, bias_attr=False)
        bn1 = paddle.nn.BatchNorm2D(64)
        conv2 = paddle.nn.Conv2D(64, 64, 3, padding=1, bias_attr=False)
        bn2 = paddle.nn.BatchNorm2D(64)
        pool = paddle.nn.MaxPool2D(2, 2)

        def block(xa):
            with ae.no_grad():
                x = Tensor(xa)
                h = F.relu(bn1(conv1(x)))
                h = F.fused_batch_norm_act(
                    conv2(h), bn2._mean, bn2._variance, bn2.weight,
                    bn2.bias, training=True, act="relu", residual=x)
                return pool(h).data

        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((8, 64, 16, 16))
                        .astype(np.float32))
        with flags_guard(conv_nhwc="always", fused_bn=fused), \
                warnings.catch_warnings():
            warnings.simplefilter("ignore")  # traced-stat warn-and-skip
            census = compiled_hlo_layout_census(block, x)
        return census["transposes"], census["copies"]

    def test_residual_block_transpose_free_interior(self):
        tr_xla, cp_xla = self._block_hlo_counts("never")
        tr_fused, _ = self._block_hlo_counts("always")
        # stem input + head output only: conv/BN/act/pool boundaries
        # all cancel. 3 allows one residual-edge transpose on some XLA
        # versions; the pre-fix layout-churn trace showed dozens.
        assert tr_xla <= 3, f"XLA path grew interior transposes: {tr_xla}"
        # the copy census is only meaningful on the non-interpreted
        # path (interpret-mode pallas emulation uses host copies)
        assert cp_xla <= 3, f"XLA path grew interior copies: {cp_xla}"
        # the fused kernel is NHWC-native: selecting it must not add a
        # single transpose anywhere in the compiled block
        assert tr_fused <= tr_xla, (tr_fused, tr_xla)


class TestSyncBatchNorm(OpTest):
    """Cross-replica BN (reference sync_batch_norm_op): stats psum'd
    over dp must equal GLOBAL-batch BN, in both layouts of the
    channels-last region (r5)."""

    def _run(self, conv_nhwc):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.core.tensor import Tensor
        from paddle1_tpu.distributed.env import spmd_axes

        devs = jax.devices()[:4]
        mesh = Mesh(np.asarray(devs), ("data",))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 3, 4, 4)).astype(np.float32) * 2 + 1

        paddle.seed(0)
        sbn = nn.SyncBatchNorm(3)
        w = sbn.weight.data
        b = sbn.bias.data

        def shard_fn(xs, w, b):
            with spmd_axes(dp="data"), flags_guard(conv_nhwc=conv_nhwc):
                y, = (sbn(Tensor(xs)).data,)
            return y

        y = jax.jit(shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P("data"), P(), P()),
            out_specs=P("data")))(jnp.asarray(x), w, b)

        # global-batch reference
        mean = x.mean(axis=(0, 2, 3), keepdims=True)
        var = x.var(axis=(0, 2, 3), keepdims=True)
        want = (x - mean) / np.sqrt(var + sbn._epsilon)
        want = want * np.asarray(w).reshape(1, -1, 1, 1) + \
            np.asarray(b).reshape(1, -1, 1, 1)
        np.testing.assert_allclose(np.asarray(y), want, rtol=2e-4,
                                   atol=2e-4)

    def test_matches_global_bn_nchw_path(self):
        self._run("never")

    def test_matches_global_bn_channels_last_region(self):
        self._run("always")
