"""In-graph pipeline schedule over the pp axis: forward + grads must match
sequential stage execution (reference SectionWorker semantics, compiled)."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from paddle1_tpu.distributed.pipeline import (pipeline_apply,
                                              stack_stage_params)

D = 8


def _stages(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [{"w": jnp.asarray(rng.standard_normal((D, D), np.float32) * .3),
             "b": jnp.asarray(rng.standard_normal((D,), np.float32) * .1)}
            for _ in range(n)]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _seq(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


class TestInGraphPipeline(unittest.TestCase):
    def setUp(self):
        self.mesh = Mesh(np.array(jax.devices()[:4]), ("pp",))
        self.per_stage = _stages(4)
        self.stacked = stack_stage_params(self.per_stage)
        self.f = shard_map(
            lambda sp, mi: pipeline_apply(_stage_fn, sp, mi, "pp"),
            mesh=self.mesh, in_specs=(P("pp"), P()), out_specs=P())

    def test_forward_matches_sequential(self):
        rng = np.random.default_rng(1)
        micro = jnp.asarray(rng.standard_normal((6, 2, D), np.float32))
        out = self.f(self.stacked, micro)
        ref = _seq(self.per_stage, micro)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_single_microbatch(self):
        rng = np.random.default_rng(2)
        micro = jnp.asarray(rng.standard_normal((1, 2, D), np.float32))
        out = self.f(self.stacked, micro)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_seq(self.per_stage, micro)),
                                   atol=1e-6)

    def test_grads_match_sequential(self):
        rng = np.random.default_rng(3)
        micro = jnp.asarray(rng.standard_normal((4, 2, D), np.float32))

        gp = jax.grad(lambda sp: jnp.sum(self.f(sp, micro) ** 2))(
            self.stacked)
        gr = stack_stage_params(jax.grad(
            lambda st: jnp.sum(_seq(st, micro) ** 2))(self.per_stage))
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gr[k]),
                                       atol=5e-5)

    def test_jit_compiles_once(self):
        rng = np.random.default_rng(4)
        micro = jnp.asarray(rng.standard_normal((4, 2, D), np.float32))
        jf = jax.jit(self.f)
        a = jf(self.stacked, micro)
        b = jf(self.stacked, micro)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
