"""Live world-resize with checkpoint resharding (PR 6).

Three layers under test, cheapest first:

* **topology** — MeshDescriptor round-trip, plan_resize's
  data-axes-only policy and its teaching errors;
* **checkpoint** — the manifest-driven shard remap: save on one mesh,
  restore onto a bigger/smaller one (shrink AND grow, uneven divisors,
  optimizer-moment trees, scalar/replicated leaves), typed ReshardError
  when the saved topology cannot be expressed at the new world size;
* **sampler/loader** — the elastic DistributedBatchSampler's
  world-size-invariant global stream and cursor remap across a resize;
* **supervisor** — shrink-and-continue on worker loss, grow on
  request_resize, floors/budgets (plain-stdlib beater workers, same
  pattern as test_launch).

The end-to-end 8→6→8 chaos parity gate is ``bench.py --elastic-resize``
(CI); the fast cases here keep the tier-1 suite honest without paying a
jax-subprocess import per test.
"""

import os
import signal
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle1_tpu.distributed import checkpoint as ckpt_mod
from paddle1_tpu.distributed.checkpoint import (CheckpointManager,
                                                CheckpointCorruptError,
                                                tree_mesh_descriptor)
from paddle1_tpu.distributed.topology import (MeshDescriptor, ReshardError,
                                              build_mesh,
                                              ensure_reshardable,
                                              mesh_descriptor, plan_resize)
from paddle1_tpu.io import DataLoader, DistributedBatchSampler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(n, **degrees):
    degrees = degrees or {"sharding": n}
    return build_mesh(devices=jax.devices()[:n], **degrees)


def _sharded(mesh, arr, *axes):
    return jax.device_put(arr, NamedSharding(mesh, P(*axes)))


class TestMeshDescriptor:
    def test_round_trip_and_digest(self):
        m = _mesh(8)
        d = mesh_descriptor(m)
        assert d.device_count == 8
        assert d.degree("sharding") == 8 and d.degree("mp") == 1
        back = MeshDescriptor.from_meta(d.as_meta())
        assert back == d
        assert back.digest() == d.digest()

    def test_equality_ignores_size_one_axes(self):
        a = MeshDescriptor(axes={"dp": 4, "mp": 1}, device_count=4)
        b = MeshDescriptor(axes={"dp": 4}, device_count=4)
        assert a == b
        assert MeshDescriptor.from_meta({"bogus": 1}) is None
        assert MeshDescriptor.from_meta(None) is None

    def test_manifest_meta_round_trip(self, tmp_path):
        """The PR 5 meta sanitizer learns the descriptor type: topology
        meta rides the manifest without the typed-key-path error."""
        state = {"w": np.arange(6, dtype=np.float32)}
        d = str(tmp_path / "ck")
        os.makedirs(d)
        ckpt_mod.write_manifest(d, state,
                                meta={"mesh": mesh_descriptor(_mesh(8)),
                                      "step": 3})
        doc = ckpt_mod.read_manifest(d)
        back = MeshDescriptor.from_meta(doc["meta"]["mesh"])
        assert back == mesh_descriptor(_mesh(8))
        assert ckpt_mod.manifest_mesh(d) == back

    def test_sanitizer_still_rejects_foreign_types(self, tmp_path):
        d = str(tmp_path / "ck")
        os.makedirs(d)
        with pytest.raises(CheckpointCorruptError, match=r"meta\.bad"):
            ckpt_mod.write_manifest(d, {"w": np.zeros(2)},
                                    meta={"bad": object()})


class TestPlanResize:
    def test_dp_scales(self):
        d = MeshDescriptor(axes={"dp": 8}, device_count=8)
        assert plan_resize(d, 6)["dp"] == 6
        assert plan_resize(d, 6)["sharding"] == 1

    def test_sharding_scales_when_dp_one(self):
        d = MeshDescriptor(axes={"sharding": 8}, device_count=8)
        got = plan_resize(d, 6)
        assert got["sharding"] == 6 and got["dp"] == 1

    def test_model_axes_preserved(self):
        d = MeshDescriptor(axes={"dp": 4, "mp": 2}, device_count=8)
        got = plan_resize(d, 6)
        assert got == {"dp": 3, "sharding": 1, "mp": 2, "pp": 1, "sp": 1}

    def test_mp_not_divisible_teaches(self):
        d = MeshDescriptor(axes={"dp": 2, "mp": 4}, device_count=8)
        with pytest.raises(ReshardError, match="multiple of 4"):
            plan_resize(d, 6)

    def test_both_data_axes_keep_zero_degree(self):
        d = MeshDescriptor(axes={"dp": 2, "sharding": 2}, device_count=4)
        got = plan_resize(d, 8)
        assert got["sharding"] == 2 and got["dp"] == 4
        with pytest.raises(ReshardError, match="multiple of"):
            plan_resize(d, 3)

    def test_ensure_reshardable(self):
        eight = mesh_descriptor(_mesh(8))
        six = mesh_descriptor(_mesh(6))
        assert ensure_reshardable(eight, eight) is False
        assert ensure_reshardable(None, six) is False  # pre-elastic ckpt
        assert ensure_reshardable(eight, six) is True
        mp2 = mesh_descriptor(_mesh(8, mp=2, sharding=4))
        with pytest.raises(ReshardError, match="mp="):
            ensure_reshardable(mp2, six)


class TestShardRemap:
    """save_sharded/load_sharded's resharding load path: old-shard →
    new-shard slices through orbax against the target shardings."""

    def _state(self, mesh):
        # params + an AdamW-shaped slot tree: moments shard like their
        # param, plus a replicated bias and a scalar step count
        w = np.arange(48 * 16, dtype=np.float32).reshape(48, 16)
        b = np.arange(4, dtype=np.float32)
        return {
            "params": {"w": _sharded(mesh, w, "sharding"),
                       "b": _sharded(mesh, b)},
            "opt": {"m": _sharded(mesh, w * 0.5, "sharding"),
                    "v": _sharded(mesh, w * 0.25, "sharding"),
                    "count": _sharded(mesh, np.float32(7))},
        }

    def _roundtrip(self, tmp_path, n_from, n_to):
        from paddle1_tpu.distributed.sharding_specs import describe_layout
        src = self._state(_mesh(n_from))
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(5, src, meta={"mesh": mesh_descriptor(_mesh(n_from))})
        target = self._state(_mesh(n_to))
        restored, step = mgr.restore(target)
        assert step == 5
        for path in (("params", "w"), ("params", "b"), ("opt", "m"),
                     ("opt", "v"), ("opt", "count")):
            want = src[path[0]][path[1]]
            got = restored[path[0]][path[1]]
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
            # the restored leaf LANDS in the new world's sharding
            assert got.sharding.mesh.devices.size == n_to
        # the layouts really changed world: params + both AdamW moments
        # are sharded over the new degree, scalars stay replicated
        layout = describe_layout(restored)
        for key in ("['params']['w']", "['opt']['m']", "['opt']['v']"):
            assert "sharding" in layout[key], layout
        assert layout["['opt']['count']"] == "PartitionSpec()"
        return restored

    def test_shrink_8_to_6(self, tmp_path):
        self._roundtrip(tmp_path, 8, 6)

    def test_grow_6_to_8(self, tmp_path):
        self._roundtrip(tmp_path, 6, 8)

    def test_uneven_divisor_falls_back_to_replicated(self, tmp_path):
        """48 % 5 != 0: at the new world the spec machinery
        (zero_shard_spec) leaves a non-divisible dim replicated — the
        remap must deliver a SHARDED-at-8 leaf into a REPLICATED-at-5
        target (and the reverse) bit-identically."""
        w = np.arange(48 * 16, dtype=np.float32).reshape(48, 16)
        src = {"w": _sharded(_mesh(8), w, "sharding")}
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, src, meta={"mesh": mesh_descriptor(_mesh(8))})
        target = {"w": _sharded(_mesh(5), np.zeros_like(w))}  # replicated
        restored, _ = mgr.restore(target)
        np.testing.assert_array_equal(np.asarray(restored["w"]), w)
        assert restored["w"].sharding.mesh.devices.size == 5

        # and back up: replicated-at-5 → sharded-at-6
        mgr2 = CheckpointManager(str(tmp_path / "ck2"))
        mgr2.save(1, {"w": _sharded(_mesh(5), w)},
                  meta={"mesh": mesh_descriptor(_mesh(5))})
        target = {"w": _sharded(_mesh(6), np.zeros_like(w), "sharding")}
        restored, _ = mgr2.restore(target)
        np.testing.assert_array_equal(np.asarray(restored["w"]), w)

    def test_mp_resize_refused_with_teaching_error(self, tmp_path):
        mesh_mp = _mesh(8, mp=2, sharding=4)
        src = self._state(mesh_mp)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(3, src, meta={"mesh": mesh_descriptor(mesh_mp)})
        target = self._state(_mesh(6))
        with pytest.raises(ReshardError, match="mp="):
            mgr.restore(target)

    def test_pre_elastic_checkpoint_still_restores(self, tmp_path):
        """No mesh meta (pre-PR6 checkpoint): the remap is skipped, the
        plain orbax restore still lands in the target shardings."""
        src = self._state(_mesh(8))
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(2, src)  # no meta["mesh"]
        restored, _ = mgr.restore(self._state(_mesh(6)))
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["w"]),
            np.asarray(src["params"]["w"]))

    def test_tree_mesh_descriptor(self):
        st = self._state(_mesh(6))
        assert tree_mesh_descriptor(st) == mesh_descriptor(_mesh(6))
        assert tree_mesh_descriptor({"x": 3}) is None


class _Range:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i], np.float32)


class TestElasticSampler:
    def test_global_stream_invariant_across_worlds(self):
        ds = _Range(96)
        streams = {}
        for w in (1, 2, 4, 8):
            s = DistributedBatchSampler(ds, batch_size=48 // w,
                                        num_replicas=w, rank="all",
                                        shuffle=True, elastic=True)
            streams[w] = list(s)
        for w in (2, 4, 8):
            assert streams[w] == streams[1]

    def test_rank_chunks_concatenate_to_global(self):
        ds = _Range(96)
        world = 4
        ranks = [list(DistributedBatchSampler(
            ds, batch_size=12, num_replicas=world, rank=r,
            shuffle=True, elastic=True)) for r in range(world)]
        glob = list(DistributedBatchSampler(
            ds, batch_size=12, num_replicas=world, rank="all",
            shuffle=True, elastic=True))
        for j, gb in enumerate(glob):
            assert sum((ranks[r][j] for r in range(world)), []) == gb

    def test_strided_default_layout_unchanged(self):
        ds = _Range(20)
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                    rank=1, shuffle=False)
        assert list(s) == [[1, 3], [5, 7], [9, 11], [13, 15], [17, 19]]

    def test_rank_all_requires_elastic(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="elastic"):
            DistributedBatchSampler(_Range(8), batch_size=2,
                                    num_replicas=2, rank="all")

    def test_strided_state_refuses_world_change(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        old = DistributedBatchSampler(_Range(32), batch_size=4,
                                      num_replicas=8, rank=0)
        new = DistributedBatchSampler(_Range(32), batch_size=4,
                                      num_replicas=4, rank=0)
        with pytest.raises(InvalidArgumentError, match="elastic=True"):
            new.set_state_dict(old.state_dict())

    def test_layout_mismatch_refused_even_at_same_world(self):
        """elastic and strided order samples differently, so state must
        never cross layouts — even when the rank/batch arithmetic
        matches (8x6 == 8x6)."""
        from paddle1_tpu.core.errors import InvalidArgumentError
        el = DistributedBatchSampler(_Range(96), batch_size=6,
                                     num_replicas=8, rank=0, elastic=True)
        st = DistributedBatchSampler(_Range(96), batch_size=6,
                                     num_replicas=8, rank=0)
        with pytest.raises(InvalidArgumentError, match="elastic=True"):
            st.set_state_dict(el.state_dict())
        with pytest.raises(InvalidArgumentError, match="elastic=False"):
            el.set_state_dict(st.state_dict())

    def test_elastic_state_requires_fixed_global_batch(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        old = DistributedBatchSampler(_Range(96), batch_size=6,
                                      num_replicas=8, rank="all",
                                      elastic=True)
        bad = DistributedBatchSampler(_Range(96), batch_size=6,
                                      num_replicas=6, rank="all",
                                      elastic=True)
        with pytest.raises(InvalidArgumentError, match="global"):
            bad.set_state_dict(old.state_dict())

    def test_loader_cursor_remaps_across_resize(self):
        """The tentpole data contract: consume c global batches at
        world 8, checkpoint the loader, restore at world 6 — the stream
        continues exactly where it left off (no sample dropped or
        consumed twice), because the cursor counts GLOBAL batches."""
        ds = _Range(30 * 48)

        def make_loader(w):
            s = DistributedBatchSampler(ds, batch_size=48 // w,
                                        num_replicas=w, rank="all",
                                        shuffle=True, elastic=True)
            return DataLoader(ds, batch_sampler=s)

        ref = [np.asarray(b.data).tolist()
               for b in list(make_loader(8))[:10]]

        loader8 = make_loader(8)
        it = iter(loader8)
        first4 = [np.asarray(next(it).data).tolist() for _ in range(4)]
        state = loader8.state_dict()
        assert first4 == ref[:4]

        loader6 = make_loader(6)
        loader6.set_state_dict(state)
        it6 = iter(loader6)
        rest = [np.asarray(next(it6).data).tolist() for _ in range(6)]
        assert rest == ref[4:10]

    def test_epoch_seed_world_invariant(self):
        ds = _Range(96)
        a = DistributedBatchSampler(ds, batch_size=12, num_replicas=4,
                                    rank="all", shuffle=True, elastic=True)
        b = DistributedBatchSampler(ds, batch_size=24, num_replicas=2,
                                    rank="all", shuffle=True, elastic=True)
        a.set_epoch(3), b.set_epoch(3)
        assert list(a) == list(b)
        b.set_epoch(4)
        assert list(a) != list(b)


# -- supervisor resize (plain-stdlib beater workers) -------------------------

ELASTIC_BEATER = textwrap.dedent("""
    import os, signal, sys, time
    hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM",
                os.environ.get("PADDLE_ELASTIC_WORLD", "1")))
    inc = int(os.environ["PADDLE_FT_WORKER_INCARNATION"])
    trace = os.environ["TRACE_FILE"]
    def note(kind):
        with open(trace, "a") as f:
            f.write(f"{kind} rank={rank} world={world} inc={inc}\\n")
    note("spawn")
    def on_term(s, fr):   # the drain: "checkpoint" and exit clean
        note("drain")
        sys.exit(0)
    signal.signal(signal.SIGTERM, on_term)
    die = os.environ.get("DIE_RANK")
    for i in range(400):
        os.utime(hb, None)
        if die is not None and rank == int(die) and inc == 0 and i == 5:
            os.kill(os.getpid(), signal.SIGKILL)
        if inc > 0 and i >= 10:
            break    # post-resize lives finish quickly
        time.sleep(0.02)
    note("done")
""")


def _resize_sup(tmp_path, nworkers, **kw):
    from paddle1_tpu.distributed import Supervisor
    kw.setdefault("policy", "resize")
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("grace_s", 3.0)
    kw.setdefault("hang_timeout", 10.0)
    kw.setdefault("heartbeat_dir", str(tmp_path / "hb"))
    extra_env = kw.pop("extra_env", {})
    sup = Supervisor(**kw)
    w = tmp_path / "worker.py"
    w.write_text(ELASTIC_BEATER)
    for r in range(nworkers):
        env = dict(os.environ, PADDLE_TRAINER_ID=str(r),
                   PADDLE_TRAINERS_NUM=str(nworkers),
                   TRACE_FILE=str(tmp_path / "trace"), **extra_env)
        sup.add_worker(r, [sys.executable, "-u", str(w)], env=env)
    return sup


def _trace(tmp_path):
    p = tmp_path / "trace"
    return p.read_text().splitlines() if p.exists() else []


class TestSupervisorResize:
    def test_worker_loss_shrinks_and_continues(self, tmp_path):
        """The tentpole: losing a rank of a 3-worker world drains the
        survivors and relaunches the fleet at world 2 with rewritten
        coordinates; the job then completes (rc 0)."""
        sup = _resize_sup(tmp_path, 3, extra_env={"DIE_RANK": "1"})
        assert sup.run() == 0
        assert [(r["from"], r["to"]) for r in sup.report.resizes] == \
            [(3, 2)]
        assert sup.report.world_size == 2
        tr = _trace(tmp_path)
        # survivors drained before relaunch
        assert any(t.startswith("drain rank=0 world=3") for t in tr)
        assert any(t.startswith("drain rank=2 world=3") for t in tr)
        # relaunched fleet: ranks 0..1 at world 2, incarnation 1, and
        # the dropped rank 2 never spawns again
        assert any(t == "spawn rank=0 world=2 inc=1" for t in tr)
        assert any(t == "spawn rank=1 world=2 inc=1" for t in tr)
        assert not any(t.startswith("spawn rank=2 world=2") for t in tr)

    def test_restart_policy_multiworld_routes_to_resize(self, tmp_path):
        """The PR 3 dead end, replaced: ft_supervise=restart with a
        multi-worker world no longer warns-and-relaunches a rank that
        cannot rejoin — it shrinks-and-continues."""
        sup = _resize_sup(tmp_path, 2, policy="restart",
                          extra_env={"DIE_RANK": "0"})
        assert sup.run() == 0
        assert [(r["from"], r["to"]) for r in sup.report.resizes] == \
            [(2, 1)]
        assert sup.report.total_restarts == 0  # resize, not restart

    @pytest.mark.slow  # tier-1 budget: the two cases above cover the
    # shrink paths; these variants ride the CI elastic-resize step
    def test_grow_on_request_clones_new_ranks(self, tmp_path):
        sup = _resize_sup(tmp_path, 2)
        rc_box = {}
        t = threading.Thread(target=lambda: rc_box.update(rc=sup.run()))
        t.start()
        time.sleep(0.4)  # let the fleet spawn and beat
        sup.request_resize(3, "capacity added")
        t.join(timeout=30)
        assert not t.is_alive() and rc_box["rc"] == 0
        assert [(r["from"], r["to"]) for r in sup.report.resizes] == \
            [(2, 3)]
        tr = _trace(tmp_path)
        assert any(t_ == "spawn rank=2 world=3 inc=1" for t_ in tr)

    @pytest.mark.slow  # see test_grow_on_request_clones_new_ranks
    def test_shrink_below_min_world_fails_pod(self, tmp_path):
        sup = _resize_sup(tmp_path, 2, min_world=2,
                          extra_env={"DIE_RANK": "1"})
        assert sup.run() != 0
        assert sup.report.resizes == []

    @pytest.mark.slow  # see test_grow_on_request_clones_new_ranks
    def test_resize_budget_exhausted_fails_pod(self, tmp_path):
        sup = _resize_sup(tmp_path, 3, max_resizes=0,
                          extra_env={"DIE_RANK": "1"})
        assert sup.run() != 0
        assert sup.report.resizes == []

    @pytest.mark.slow  # see test_grow_on_request_clones_new_ranks
    def test_explicit_request_below_floor_is_refused_not_fatal(
            self, tmp_path):
        sup = _resize_sup(tmp_path, 2, min_world=2)
        rc_box = {}
        t = threading.Thread(target=lambda: rc_box.update(rc=sup.run()))
        t.start()
        time.sleep(0.3)
        sup.request_resize(1, "operator fat-finger")
        # the request is refused; the healthy fleet must still finish
        deadline = time.time() + 30
        while time.time() < deadline and t.is_alive():
            time.sleep(0.1)
        # workers at inc 0 run ~8s; drain them to finish the test fast
        if t.is_alive():
            sup.request_resize(2, "noop")
            t.join(timeout=30)
        assert rc_box.get("rc") == 0
        assert all((r["from"], r["to"]) != (2, 1)
                   for r in sup.report.resizes)


class TestResizeRefusedTyped:
    """ISSUE 18 satellite: refusals are a typed result + counter, not
    a stderr string — the autoscaler backs off on `reason`."""

    def _counters(self):
        from paddle1_tpu.obs import registry as obs_registry
        return obs_registry.process_registry().snapshot()["counters"]

    def test_below_floor_refused_typed(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import (
            Supervisor, ResizeRefused, RESIZE_BELOW_FLOOR)
        sup = Supervisor(policy="resize", world_size=4, min_world=2,
                         heartbeat_dir=str(tmp_path / "hb"))
        before = self._counters().get("ft_resize_refusals_total", 0)
        r = sup.request_resize(1, "scale-in")
        assert isinstance(r, ResizeRefused)
        assert r.reason == RESIZE_BELOW_FLOOR
        assert r.requested == 1 and r.limit == 2
        assert sup._resize_request is None  # refused, never queued
        assert sup.report.resize_refusals == [
            {"requested": 1, "reason": RESIZE_BELOW_FLOOR, "limit": 2}]
        assert sup.report.as_dict()["resize_refusals"]
        after = self._counters()
        assert after.get("ft_resize_refusals_total", 0) == before + 1
        assert after.get("ft_resize_refused_below_floor_total", 0) >= 1

    def test_budget_exhausted_refused_typed(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import (
            Supervisor, ResizeRefused, RESIZE_BUDGET_EXHAUSTED)
        sup = Supervisor(policy="resize", world_size=4, min_world=1,
                         max_resizes=0,
                         heartbeat_dir=str(tmp_path / "hb"))
        r = sup.request_resize(6, "scale-out")
        assert isinstance(r, ResizeRefused)
        assert r.reason == RESIZE_BUDGET_EXHAUSTED
        assert r.requested == 6 and r.limit == 0
        assert sup._resize_request is None
        assert self._counters().get(
            "ft_resize_refused_budget_exhausted_total", 0) >= 1

    def test_accepted_and_noop_requests_return_none(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import Supervisor
        sup = Supervisor(policy="resize", world_size=4, min_world=2,
                         max_resizes=2,
                         heartbeat_dir=str(tmp_path / "hb"))
        assert sup.request_resize(3, "scale-in") is None
        assert sup._resize_request == (3, "scale-in")
        # a same-size request is a no-op, not a refusal — even with
        # the budget spent
        sup.max_resizes = 0
        assert sup.request_resize(4, "noop") is None


@pytest.mark.slow
class TestElasticResizeParity:
    def test_live_resize_8_6_8_parity(self):
        """The acceptance gate: 8→6→8 mid-run under worker_kill chaos,
        1e-6 final-param parity vs the uninterrupted fixed-global-batch
        run, resharding restores in both resized lives, exactly-once
        accounting across the graceful resize."""
        sys.path.insert(0, REPO)
        from bench import bench_elastic_resize
        bench_elastic_resize(on_tpu=False)
