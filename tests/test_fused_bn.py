"""Fused batch-norm Pallas kernel tests (ISSUE 15) — interpret mode on
CPU exercises the same kernel code the TPU executes, the flash-attention
discipline. Parity matrix: fwd + bwd, fp32 + bf16, train + eval,
with/without residual-add and relu, kernel path vs the XLA lowering;
plus the flag gating, the SyncBatchNorm local-stats reuse, the
collect_stat_updates functionalization, and the eval-mode
no-copy/no-retrace regressions (ISSUE 15 satellite 6)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle1_tpu as paddle
import paddle1_tpu.nn.functional as F
from paddle1_tpu.core.flags import flags_guard
from paddle1_tpu.core.tensor import Tensor, to_tensor


def _data(rows_shape=(4, 8, 8), c=64, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    n, h, w = rows_shape
    x = (rng.standard_normal((n, c, h, w)) * 2 + 1).astype(dtype)
    g = rng.standard_normal((c,)).astype(np.float32)
    b = rng.standard_normal((c,)).astype(np.float32)
    m = rng.standard_normal((c,)).astype(np.float32)
    v = (rng.standard_normal((c,)).astype(np.float32)) ** 2 + 0.5
    res = rng.standard_normal((n, c, h, w)).astype(dtype)
    return x, g, b, m, v, res


class TestKernelSupported:
    def test_supported_matrix(self):
        from paddle1_tpu.ops.pallas import fused_bn as pbn
        assert pbn.supported((256, 64))
        assert pbn.supported((4, 8, 8, 64))          # rows = 256
        assert not pbn.supported((256, 63))          # lane-unfriendly C
        assert not pbn.supported((7, 64))            # rows don't tile
        assert not pbn.supported((64,))              # no row dim
        # 16-bit compute needs a sublane-aligned row block
        assert pbn.supported((256, 64), jnp.bfloat16)

    def test_bad_act_typed(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        from paddle1_tpu.ops.pallas import fused_bn as pbn
        x = jnp.ones((64, 8), jnp.float32)
        with pytest.raises(InvalidArgumentError):
            pbn.fused_bn_train(x, jnp.ones(8), jnp.zeros(8), 1e-5,
                               act="gelu")
        with pytest.raises(InvalidArgumentError):
            F.fused_batch_norm_act(
                to_tensor(np.ones((2, 8, 4, 4), np.float32)),
                to_tensor(np.zeros(8, np.float32)),
                to_tensor(np.ones(8, np.float32)),
                to_tensor(np.ones(8, np.float32)),
                to_tensor(np.zeros(8, np.float32)), act="gelu")

    def test_requires_affine_and_matching_residual(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        x = to_tensor(np.ones((2, 8, 4, 4), np.float32))
        m = to_tensor(np.zeros(8, np.float32))
        v = to_tensor(np.ones(8, np.float32))
        with pytest.raises(InvalidArgumentError):
            F.fused_batch_norm_act(x, m, v, None, None)
        with pytest.raises(InvalidArgumentError):
            F.fused_batch_norm_act(
                x, m, v, to_tensor(np.ones(8, np.float32)),
                to_tensor(np.zeros(8, np.float32)),
                residual=to_tensor(np.ones((2, 8, 4, 2), np.float32)))


class TestFusedBnParity:
    """Kernel path vs XLA lowering through the public functional, tape
    backward included — the acceptance matrix."""

    def _run(self, fused, training, act, use_res, dtype, bwd="always"):
        x, g, b, m0, v0, res = _data(dtype=dtype)
        xt = to_tensor(x)
        xt.stop_gradient = False
        rt = to_tensor(res)
        rt.stop_gradient = False
        m = to_tensor(m0.copy())
        v = to_tensor(v0.copy())
        gw = to_tensor(g)
        gw.stop_gradient = False
        bw = to_tensor(b)
        bw.stop_gradient = False
        with flags_guard(conv_nhwc="always", fused_bn=fused,
                         fused_bn_bwd=bwd):
            if act == "identity" and not use_res:
                out = F.batch_norm(xt, m, v, gw, bw, training=training)
            else:
                out = F.fused_batch_norm_act(
                    xt, m, v, gw, bw, training=training, act=act,
                    residual=rt if use_res else None)
            if np.dtype(dtype).itemsize == 2:
                # normalize output-dtype semantics: the XLA lowering
                # promotes a bf16 input to f32 through the f32 buffers
                # where the kernel stays bf16-native — pin both paths
                # to bf16 so forward AND cotangent see one rounding
                out = out.astype("bfloat16")
            # non-uniform cotangent: a plain .sum() makes dgamma a pure
            # cancellation (sum of xhat ~ 0) and the comparison noise
            cot = to_tensor(np.random.default_rng(7).standard_normal(
                out.shape).astype(np.float32))
            (out.astype("float32") * cot).sum().backward()
        outs = [np.asarray(out.astype("float32").numpy()),
                np.asarray(xt.grad.astype("float32").numpy()),
                np.asarray(gw.grad.numpy()), np.asarray(bw.grad.numpy()),
                np.asarray(m.numpy()), np.asarray(v.numpy())]
        if use_res:
            outs.append(np.asarray(rt.grad.astype("float32").numpy()))
        return outs

    @pytest.mark.parametrize("training", [False, True])
    @pytest.mark.parametrize("act", ["identity", "relu"])
    @pytest.mark.parametrize("use_res", [False, True])
    def test_fp32_matrix(self, training, act, use_res):
        want = self._run("never", training, act, use_res, np.float32)
        got = self._run("always", training, act, use_res, np.float32)
        for i, (a, b) in enumerate(zip(got, want)):
            np.testing.assert_allclose(
                a, b, rtol=2e-5, atol=2e-5,
                err_msg=f"out {i} training={training} act={act} "
                        f"res={use_res}")

    @pytest.mark.parametrize("training", [False, True])
    def test_fp32_xla_backward_arm(self, training):
        # fused forward + XLA composition backward: the on-chip
        # ablation arm must agree with both the kernel backward and
        # the plain lowering
        want = self._run("never", training, "relu", True, np.float32)
        got = self._run("always", training, "relu", True, np.float32,
                        bwd="never")
        for i, (a, b) in enumerate(zip(got, want)):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5,
                                       err_msg=f"out {i}")

    @pytest.mark.parametrize("training", [False, True])
    @pytest.mark.parametrize("use_res", [False, True])
    def test_bf16_matrix(self, training, use_res):
        import ml_dtypes
        dt = np.dtype(ml_dtypes.bfloat16)
        # identity act for the bf16 GRAD matrix: a 1-ulp bf16
        # difference in the normalized value flips the relu mask on
        # knife-edge elements, turning the comparison into mask noise
        # (relu itself is covered at fp32 and by the forward check)
        want = self._run("never", training, "identity", use_res, dt)
        got = self._run("always", training, "identity", use_res, dt)
        # the kernel accumulates stats in f32 where the XLA lowering
        # reduces in bf16, so train-mode tolerance is bf16 resolution
        for i, (a, b) in enumerate(zip(got, want)):
            np.testing.assert_allclose(
                a, b, rtol=3e-2, atol=3e-2,
                err_msg=f"out {i} training={training} res={use_res}")
        # relu forward at bf16: outputs agree within bf16 resolution
        wf = self._run("never", training, "relu", use_res, dt)[0]
        gf = self._run("always", training, "relu", use_res, dt)[0]
        np.testing.assert_allclose(gf, wf, rtol=3e-2, atol=3e-2)

    def test_running_stats_update_parity(self):
        x, g, b, m0, v0, _ = _data()
        updates = {}
        for fused in ("never", "always"):
            m = to_tensor(m0.copy())
            v = to_tensor(v0.copy())
            with flags_guard(conv_nhwc="always", fused_bn=fused):
                F.batch_norm(to_tensor(x), m, v, to_tensor(g),
                             to_tensor(b), training=True, momentum=0.8)
            updates[fused] = (np.asarray(m.numpy()), np.asarray(v.numpy()))
        np.testing.assert_allclose(updates["never"][0],
                                   updates["always"][0], rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(updates["never"][1],
                                   updates["always"][1], rtol=1e-5,
                                   atol=1e-6)
        assert np.abs(updates["never"][0] - m0).max() > 1e-3  # did move

    def test_unsupported_shape_falls_back(self):
        # C=63 can't take the kernel: the flag path must silently use
        # the XLA lowering and still be correct
        rng = np.random.default_rng(3)
        x = rng.standard_normal((2, 63, 4, 4)).astype(np.float32)
        g = rng.standard_normal(63).astype(np.float32)
        b = rng.standard_normal(63).astype(np.float32)
        outs = {}
        for fused in ("never", "always"):
            with flags_guard(conv_nhwc="always", fused_bn=fused):
                outs[fused] = np.asarray(F.batch_norm(
                    to_tensor(x), to_tensor(np.zeros(63, np.float32)),
                    to_tensor(np.ones(63, np.float32)), to_tensor(g),
                    to_tensor(b), training=True).numpy())
        np.testing.assert_allclose(outs["never"], outs["always"],
                                   rtol=1e-5, atol=1e-6)

    def test_auto_threshold_crossover(self):
        # fused_bn=auto applies the fused_bn_auto_mb crossover; on CPU
        # auto additionally resolves to the XLA path (flag_active), so
        # probe the resolution helper directly
        from paddle1_tpu.nn.functional.norm import fused_bn_active
        big = (1024, 1024, 64)    # 256 MiB of f32
        small = (8, 8, 64)
        with flags_guard(fused_bn="always"):
            assert fused_bn_active(big, jnp.float32)
            assert fused_bn_active(small, jnp.float32)  # always bypasses
        with flags_guard(fused_bn="never"):
            assert not fused_bn_active(big, jnp.float32)
        if jax.default_backend() != "tpu":
            with flags_guard(fused_bn="auto"):
                assert not fused_bn_active(big, jnp.float32)


class TestCompiledTrainerIntegration:
    """The fused path under ParallelEngine: functionalized running
    stats, one trace, loss parity with the XLA lowering."""

    def _train(self, fused, k=3):
        from paddle1_tpu.distributed import ParallelEngine, build_mesh
        paddle.seed(0)
        np.random.seed(0)
        model = paddle.nn.Sequential(
            paddle.nn.Conv2D(3, 16, 3, padding=1, bias_attr=False),
            paddle.nn.BatchNorm2D(16),
            paddle.nn.ReLU(),
            paddle.nn.AdaptiveAvgPool2D(1),
            paddle.nn.Flatten(),
            paddle.nn.Linear(16, 4))
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=model.parameters())
        loss_fn = lambda m, b: \
            ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()
        mesh = build_mesh(dp=1, devices=jax.devices()[:1])
        rng = np.random.default_rng(0)
        batches = [
            {"x": rng.standard_normal((8, 3, 16, 16)).astype(np.float32),
             "y": rng.standard_normal((8, 4)).astype(np.float32)}
            for _ in range(k)]
        with flags_guard(conv_nhwc="always", fused_bn=fused,
                         fused_bn_bwd=fused):
            eng = ParallelEngine(model, opt, loss_fn, mesh=mesh)
            losses = [float(eng.step(b)) for b in batches]
            many = [float(l) for l in eng.step_many(batches)]
            eng.sync_model()
        stats = {k2: np.asarray(v.data)
                 for k2, v in model.state_dict().items()
                 if "_mean" in k2 or "_variance" in k2}
        return losses + many, stats, eng.trace_count

    def test_engine_parity_and_stat_functionalization(self):
        l1, s1, t1 = self._train("never")
        l2, s2, t2 = self._train("always")
        np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-6)
        for k in s1:
            np.testing.assert_allclose(s1[k], s2[k], rtol=1e-5,
                                       atol=1e-6)
            # running stats actually moved under the compiled step
            init = 0.0 if "_mean" in k else 1.0
            assert np.abs(s1[k] - init).max() > 1e-4, k
        assert t2 == t1  # fused path adds no retraces

    def test_collector_records_fused_stats(self):
        from paddle1_tpu.nn.functional.norm import collect_stat_updates
        x, g, b, m0, v0, _ = _data()
        with flags_guard(conv_nhwc="always", fused_bn="always"):
            with collect_stat_updates() as sink:
                def step(xa):
                    m = to_tensor(m0.copy())
                    v = to_tensor(v0.copy())
                    return F.batch_norm(to_tensor(xa), m, v,
                                        to_tensor(g), to_tensor(b),
                                        training=True).data
                jax.jit(step)(jnp.asarray(x))
        assert len(sink) == 1
        assert sink[0].momentum == 0.9


class TestSyncBatchNormFused:
    """SyncBatchNorm reuses the kernel's local-stats pass and keeps its
    cross-replica psum. Pallas calls carry no shard_map replication
    rule, so the fused variant runs under check_rep=False (any Pallas
    kernel does); grads go through the engine discipline (tape off,
    outer jax.grad)."""

    def _run(self, fused):
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle1_tpu import nn
        from paddle1_tpu.distributed.env import spmd_axes
        from paddle1_tpu.autograd import engine as ae

        devs = jax.devices()[:4]
        mesh = Mesh(np.asarray(devs), ("data",))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 64, 4, 4)).astype(np.float32) * 2 + 1
        paddle.seed(0)
        sbn = nn.SyncBatchNorm(64)
        w, b = sbn.weight.data, sbn.bias.data

        def shard_fn(xs, w, b):
            with ae.no_grad(), spmd_axes(dp="data"), \
                    flags_guard(conv_nhwc="always", fused_bn=fused,
                                fused_bn_bwd=fused):
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    return sbn(Tensor(xs)).data

        mapped = shard_map(shard_fn, mesh=mesh,
                           in_specs=(P("data"), P(), P()),
                           out_specs=P("data"), check_rep=False)
        y = jax.jit(mapped)(jnp.asarray(x), w, b)
        grads = jax.grad(lambda xs, w, b: (mapped(xs, w, b) ** 2).sum(),
                         argnums=(0, 1, 2))(jnp.asarray(x), w, b)
        return np.asarray(y), [np.asarray(g) for g in grads], sbn

    def test_matches_global_bn_and_xla_path(self):
        y, grads, sbn = self._run("always")
        # global-batch reference
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 64, 4, 4)).astype(np.float32) * 2 + 1
        mean = x.mean(axis=(0, 2, 3), keepdims=True)
        var = x.var(axis=(0, 2, 3), keepdims=True)
        want = (x - mean) / np.sqrt(var + sbn._epsilon)
        np.testing.assert_allclose(y, want, rtol=2e-4, atol=2e-4)
        # and bit-for-bit-level parity with the XLA lowering
        y2, grads2, _ = self._run("never")
        np.testing.assert_allclose(y, y2, rtol=1e-5, atol=1e-5)
        for a, b in zip(grads, grads2):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


class TestEvalHotPathRegressions:
    """ISSUE 15 satellite 6: eval-mode BN must not defensively copy the
    running-stat buffers per call, round-trip the host per step, or
    retrace under repeated calls."""

    def _model(self):
        paddle.seed(0)
        m = paddle.nn.Sequential(
            paddle.nn.Conv2D(3, 16, 3, padding=1, bias_attr=False),
            paddle.nn.BatchNorm2D(16),
            paddle.nn.ReLU(),
            paddle.nn.Conv2D(16, 16, 3, padding=1, bias_attr=False),
            paddle.nn.BatchNorm2D(16))
        m.eval()
        return m

    def test_eval_no_buffer_copy_and_no_host_round_trip(self):
        m = self._model()
        bn = m[1]
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((2, 16, 8, 8)).astype(np.float32))
        mean_arr = bn._mean.data
        var_arr = bn._variance.data
        bn(Tensor(x))  # settle lazy constants (cached epsilon scalar)
        # the buffers ride straight through: same device arrays (no
        # defensive copy per call), and an eval BN forward moves
        # NOTHING host<->device once inputs are device-resident — the
        # per-call epsilon-constant transfer was the satellite-6 audit
        # finding, fixed by the cached weak-typed scalar
        with jax.transfer_guard("disallow"):
            bn(Tensor(x))
        assert bn._mean.data is mean_arr
        assert bn._variance.data is var_arr

    def test_running_stat_blend_no_host_round_trip(self):
        # the eager running-stat blend stays on device (momentum
        # scalars are cached). The train-mode FORWARD cannot be fully
        # transfer-free under the eager tape — jax's own jvp rules
        # (e.g. rsqrt's coefficient) lift fresh scalar constants per
        # linearize — but the compiled-trainer path runs the whole
        # step in-jit, where constants fold (TestCompiledTrainer...)
        from paddle1_tpu.nn.functional.norm import _update_running_stats
        m = to_tensor(np.zeros(16, np.float32))
        v = to_tensor(np.ones(16, np.float32))
        mean = to_tensor(np.full(16, 0.5, np.float32))
        var = to_tensor(np.full(16, 2.0, np.float32))
        _update_running_stats(m, v, mean, var, 0.9, "test")  # warm
        before = m.data
        with jax.transfer_guard("disallow"):
            _update_running_stats(m, v, mean, var, 0.9, "test")
        assert m.data is not before  # blended, on device

    def test_eval_forward_compiles_once(self):
        m = self._model()
        traces = [0]

        def fwd(xa):
            traces[0] += 1
            from paddle1_tpu.autograd import engine as ae
            with ae.no_grad():
                return m(Tensor(xa)).data

        j = jax.jit(fwd)
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((2, 3, 8, 8)).astype(np.float32))
        a = j(x)
        b = j(x)
        assert traces[0] == 1
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_eval_dispatch_count_stable(self):
        # BN-heavy eager eval: the per-forward op dispatch count must
        # not grow call over call (no per-step host work accreting)
        from paddle1_tpu.autograd import engine as ae
        m = self._model()
        x = Tensor(jnp.asarray(
            np.random.default_rng(0).standard_normal((2, 3, 8, 8))
            .astype(np.float32)))
        m(x)
        orig = ae._apply_impl
        seen = []
        try:
            def probe(*a, **k):
                seen.append(a[0])
                return orig(*a, **k)
            ae._apply_impl = probe
            m(x)
            first = len(seen)
            seen.clear()
            m(x)
            second = len(seen)
        finally:
            ae._apply_impl = orig
        assert first == second and first > 0
