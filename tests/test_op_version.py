"""Op-version / artifact compat registry (VERDICT r3 missing #7;
reference op_version_registry.h): jit.save artifacts carry versions,
loaders refuse newer-runtime artifacts and warn across semantic
changes."""

import json
import warnings

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.framework import op_version as opv
from paddle1_tpu.jit import InputSpec


def _saved_model(tmp_path):
    model = paddle.nn.Linear(4, 2)
    path = str(tmp_path / "m/linear")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec([1, 4], "float32", "x")])
    return model, path


class TestRegistry:
    def test_versions_monotonic(self):
        assert opv.op_version("flash_attention") >= 2
        assert opv.op_version("never_registered_op") == 1
        with pytest.raises(ValueError, match="backwards"):
            opv.register_op_version("flash_attention", 1)

    def test_snapshot_shape(self):
        snap = opv.snapshot()
        assert snap["format_version"] == opv.FORMAT_VERSION
        assert "flash_attention" in snap["op_versions"]
        assert snap["framework_version"]


class TestArtifactCompat:
    def test_roundtrip_embeds_and_passes(self, tmp_path):
        model, path = _saved_model(tmp_path)
        cfg = json.load(open(path + ".pdconfig"))
        assert cfg["compat"]["format_version"] == opv.FORMAT_VERSION
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # clean load: no warnings
            loaded = paddle.jit.load(path)
        x = np.ones((1, 4), np.float32)
        np.testing.assert_allclose(
            np.asarray(loaded(paddle.to_tensor(x)).numpy()),
            np.asarray(model(paddle.to_tensor(x)).numpy()), rtol=1e-6)

    def test_newer_format_refuses(self, tmp_path):
        _, path = _saved_model(tmp_path)
        cfg = json.load(open(path + ".pdconfig"))
        cfg["compat"]["format_version"] = opv.FORMAT_VERSION + 1
        json.dump(cfg, open(path + ".pdconfig", "w"))
        with pytest.raises(opv.OpVersionError, match="upgrade"):
            paddle.jit.load(path)

    def test_newer_op_version_refuses(self, tmp_path):
        _, path = _saved_model(tmp_path)
        cfg = json.load(open(path + ".pdconfig"))
        cfg["compat"]["op_versions"]["flash_attention"] = 99
        json.dump(cfg, open(path + ".pdconfig", "w"))
        with pytest.raises(opv.OpVersionError, match="flash_attention"):
            paddle.jit.load(path)

    def test_older_op_version_warns_with_notes(self, tmp_path):
        _, path = _saved_model(tmp_path)
        cfg = json.load(open(path + ".pdconfig"))
        cfg["compat"]["op_versions"]["flash_attention"] = 1
        json.dump(cfg, open(path + ".pdconfig", "w"))
        with pytest.warns(UserWarning, match="LSE layout"):
            paddle.jit.load(path)

    def test_preversioning_artifact_warns(self, tmp_path):
        _, path = _saved_model(tmp_path)
        cfg = json.load(open(path + ".pdconfig"))
        del cfg["compat"]
        json.dump(cfg, open(path + ".pdconfig", "w"))
        with pytest.warns(UserWarning, match="pre-versioning"):
            paddle.jit.load(path)
