"""SparseTable optimizer-slot math vs the dense paddle optimizers
(ISSUE 19 satellites 2+3): sgd/adagrad/adam parity at 1e-6 including
adam bias correction and first-touch init, duplicate-id coalescing in
the DistributedEmbedding backward tape hook (one optimizer step per
unique id per batch — the dense scatter-add equivalence), and
eviction/re-admission round-trips that preserve slots and per-row adam
step counts."""

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import to_tensor
from paddle1_tpu.distributed import (DistributedEmbedding,
                                     EmbeddingService, SparseTable)
from paddle1_tpu.distributed.ps import _coalesce

VOCAB, DIM = 6, 4

_DENSE_OPT = {
    "sgd": lambda ps: paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=ps),
    "adagrad": lambda ps: paddle.optimizer.Adagrad(learning_rate=0.1,
                                                   parameters=ps),
    "adam": lambda ps: paddle.optimizer.Adam(learning_rate=0.1,
                                             parameters=ps),
}


class TestCoalesce:
    def test_sums_duplicates(self):
        ids = np.array([3, 1, 3, 3], np.int64)
        g = np.arange(16, dtype=np.float32).reshape(4, 4)
        u, s = _coalesce(ids, g)
        np.testing.assert_array_equal(u, [1, 3])
        np.testing.assert_allclose(s[0], g[1])
        np.testing.assert_allclose(s[1], g[0] + g[2] + g[3])

    def test_no_duplicates_is_passthrough(self):
        ids = np.array([2, 0, 5], np.int64)
        g = np.ones((3, 4), np.float32)
        u, s = _coalesce(ids, g)
        np.testing.assert_array_equal(u, ids)
        assert s is g or np.shares_memory(s, g)


def _seeded_pair(optimizer):
    """A dense nn.Embedding + paddle optimizer and an EmbeddingService
    whose tables start from the SAME rows with fresh slots."""
    paddle.seed(0)
    dense = paddle.nn.Embedding(VOCAB, DIM)
    w0 = np.asarray(dense.weight.numpy())
    opt = _DENSE_OPT[optimizer](dense.parameters())
    svc = EmbeddingService(DIM, num_shards=2, optimizer=optimizer,
                           lr=0.1)
    svc.admit(np.arange(VOCAB), w0)   # rows installed, slots zeroed
    return dense, opt, svc


def _ids_batches():
    """Every batch touches EVERY id (so dense/sparse adam agree on the
    per-row step schedule) and repeats some (the coalescing surface)."""
    return [np.array([[0, 1, 2, 3, 4, 5], [0, 0, 1, 3, 5, 5]], np.int64),
            np.array([[5, 4, 3, 2, 1, 0], [2, 2, 2, 4, 1, 0]], np.int64),
            np.array([[1, 1, 0, 2, 3, 4], [5, 0, 4, 3, 2, 5]], np.int64)]


class TestDenseParity:
    @pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
    def test_matches_dense_embedding_training(self, optimizer):
        """The satellite acceptance: duplicate-heavy batches through a
        DistributedEmbedding land on the table as ONE coalesced step
        per unique id — matching dense scatter-add + optimizer at 1e-6
        (bias correction included for adam)."""
        dense, opt, svc = _seeded_pair(optimizer)
        demb = DistributedEmbedding(svc)
        rng = np.random.default_rng(7)
        for ids in _ids_batches():
            coef = rng.standard_normal(ids.shape + (DIM,)) \
                .astype(np.float32)
            # dense side
            out = dense(to_tensor(ids))
            (out * to_tensor(coef)).sum().backward()
            opt.step()
            opt.clear_grad()
            # sparse side — same loss, tape hook pushes on backward
            out_s = demb(to_tensor(ids))
            (out_s * to_tensor(coef)).sum().backward()
            np.testing.assert_allclose(
                svc.pull(np.arange(VOCAB)),
                np.asarray(dense.weight.numpy()),
                rtol=1e-6, atol=1e-6)

    def test_two_forwards_one_coalesced_push(self):
        """A model embedding two id features through one shared table:
        the flush must fire ONCE, after the last outstanding backward,
        with duplicates across the two forwards summed."""
        dense, opt, svc = _seeded_pair("adam")
        pushes = []
        orig = svc.push
        svc.push = lambda ids, g: (pushes.append(np.asarray(ids)),
                                   orig(ids, g))[-1]
        demb = DistributedEmbedding(svc)
        ids_a = np.array([[0, 1, 2, 3, 4, 5]], np.int64)
        ids_b = np.array([[5, 4, 3, 2, 1, 0]], np.int64)
        rng = np.random.default_rng(3)
        ca = rng.standard_normal(ids_a.shape + (DIM,)).astype(np.float32)
        cb = rng.standard_normal(ids_b.shape + (DIM,)).astype(np.float32)
        # dense reference: both features share the weight
        loss_d = (dense(to_tensor(ids_a)) * to_tensor(ca)).sum() \
            + (dense(to_tensor(ids_b)) * to_tensor(cb)).sum()
        loss_d.backward()
        opt.step()
        loss_s = (demb(to_tensor(ids_a)) * to_tensor(ca)).sum() \
            + (demb(to_tensor(ids_b)) * to_tensor(cb)).sum()
        loss_s.backward()
        assert len(pushes) == 1                 # one wire push
        assert len(np.unique(pushes[0])) == len(pushes[0])
        np.testing.assert_allclose(svc.pull(np.arange(VOCAB)),
                                   np.asarray(dense.weight.numpy()),
                                   rtol=1e-6, atol=1e-6)

    def test_eval_forward_without_backward_is_harmless(self):
        _, _, svc = _seeded_pair("sgd")
        before = svc.pull(np.arange(VOCAB)).copy()
        demb = DistributedEmbedding(svc)
        demb(to_tensor(np.array([[1, 2]], np.int64)))   # no backward
        out = demb(to_tensor(np.array([[3, 3]], np.int64)))
        np.testing.assert_allclose(svc.pull(np.arange(VOCAB)), before)
        out.sum().backward()    # only the live forward's grads land
        after = svc.pull(np.arange(VOCAB))
        assert not np.allclose(after[3], before[3])
        np.testing.assert_allclose(after[1], before[1])


class TestSlotMath:
    def test_first_touch_init_adam(self):
        t = SparseTable(DIM, optimizer="adam", lr=0.1)
        row0 = t.pull([9])[0].copy()            # materializes id 9
        g = np.full(DIM, 0.5, np.float32)
        t.push([9], g[None])
        # hand-rolled first adam step from zero moments, t=1
        m1 = 0.1 * g            # (1-beta1)*g
        m2 = 0.001 * g * g      # (1-beta2)*g²
        upd = (m1 / (1 - 0.9)) / (np.sqrt(m2 / (1 - 0.999)) + 1e-8)
        np.testing.assert_allclose(t.pull([9])[0], row0 - 0.1 * upd,
                                   rtol=1e-6)
        got = t.evict([9])
        assert got["steps"][0] == 1
        np.testing.assert_allclose(got["slots"][0, 0], m1, rtol=1e-6)
        np.testing.assert_allclose(got["slots"][0, 1], m2, rtol=1e-6)

    def test_adagrad_accumulator(self):
        t = SparseTable(DIM, optimizer="adagrad", lr=0.1)
        row0 = t.pull([2])[0].copy()
        g = np.full(DIM, 2.0, np.float32)
        t.push([2], g[None])
        t.push([2], g[None])
        acc = g * g * 2
        expect = row0 - 0.1 * g / (np.sqrt(g * g) + 1e-6) \
            - 0.1 * g / (np.sqrt(acc) + 1e-6)
        np.testing.assert_allclose(t.pull([2])[0], expect, rtol=1e-6)
        np.testing.assert_allclose(t.evict([2])["slots"][0, 0], acc,
                                   rtol=1e-6)

    def test_push_coalesces_within_one_call(self):
        """Duplicate ids inside one push are ONE optimizer step on the
        summed gradient — not N steps (adam would diverge otherwise)."""
        a = SparseTable(DIM, optimizer="adam", lr=0.1, seed=1)
        b = SparseTable(DIM, optimizer="adam", lr=0.1, seed=1)
        g = np.random.default_rng(0).standard_normal(
            (3, DIM)).astype(np.float32)
        a.push([4, 4, 4], g)
        b.push([4], g.sum(axis=0, keepdims=True))
        np.testing.assert_allclose(a.pull([4]), b.pull([4]), rtol=1e-6)
        assert a.evict([4])["steps"][0] == 1


class TestEvictAdmitRoundTrip:
    def test_adam_resumes_bias_correction_exactly(self):
        """A row that leaves the tier and comes back must continue its
        adam schedule exactly where it stopped — same moments, same
        per-row step count — matching a row that never moved."""
        moved = SparseTable(DIM, optimizer="adam", lr=0.1, seed=2)
        stayed = SparseTable(DIM, optimizer="adam", lr=0.1, seed=2)
        rng = np.random.default_rng(1)
        g1 = rng.standard_normal((1, DIM)).astype(np.float32)
        g2 = rng.standard_normal((1, DIM)).astype(np.float32)
        for t in (moved, stayed):
            t.pull([7])
            t.push([7], g1)
            t.push([7], g1)
        got = moved.evict([7])
        assert not moved.has([7])[0]
        assert got["steps"][0] == 2
        other = SparseTable(DIM, optimizer="adam", lr=0.1, seed=99)
        other.admit(got["ids"], got["rows"], got["slots"], got["steps"])
        other.push([7], g2)
        stayed.push([7], g2)
        np.testing.assert_allclose(other.pull([7]), stayed.pull([7]),
                                   rtol=1e-7)
        np.testing.assert_array_equal(other.evict([7])["steps"], [3])

    def test_admit_without_slots_reinitializes(self):
        t = SparseTable(DIM, optimizer="adam")
        t.admit([3], np.ones((1, DIM), np.float32))
        got = t.evict([3])
        np.testing.assert_allclose(got["slots"], 0.0)
        assert got["steps"][0] == 0

    def test_evict_missing_skipped_unless_created(self):
        t = SparseTable(DIM)
        assert t.evict([5])["ids"].shape == (0,)
        got = t.evict([5], create=True)
        np.testing.assert_array_equal(got["ids"], [5])
        assert not t.has([5])[0]     # moved out, not copied

    def test_service_round_trip_restores_caller_order(self):
        svc = EmbeddingService(DIM, num_shards=3, optimizer="adagrad")
        ids = np.array([7, 2, 9, 4], np.int64)
        rows = svc.pull(ids).copy()
        svc.push(ids, np.ones((4, DIM), np.float32))
        trained = svc.pull(ids).copy()
        got = svc.evict(ids)
        np.testing.assert_array_equal(got["ids"], ids)   # caller order
        np.testing.assert_allclose(got["rows"], trained)
        assert len(svc) == 0
        svc.admit(got["ids"], got["rows"], got["slots"], got["steps"])
        np.testing.assert_allclose(svc.pull(ids), trained)
        assert rows.shape == trained.shape
