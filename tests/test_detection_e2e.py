"""End-to-end two-stage detection training (the RCNN-family
composition VERDICT r4 asked the training ops for): backbone → RPN
(rpn_target_assign loss + generate_proposals) → proposal sampling
(generate_proposal_labels) → ROI head (prroi_pool + cls/reg losses)
→ mask head (generate_mask_labels + per-class mask loss). The whole
pipeline trains with decreasing loss on synthetic data — every
gradient flows through the traced gathers/pools while the
data-dependent assignment stays host-side, the reference's own
split."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.fluid.layers as L
import paddle1_tpu.nn.functional as F
from paddle1_tpu.core.tensor import to_tensor


def _np(t):
    return np.asarray(t.numpy())


class TinyTwoStage(paddle.nn.Layer):
    """8x8-anchor two-stage detector over a 32x32 image."""

    def __init__(self, num_classes=3):
        super().__init__()
        self.backbone = paddle.nn.Sequential(
            paddle.nn.Conv2D(3, 8, 3, stride=2, padding=1),
            paddle.nn.ReLU(),
            paddle.nn.Conv2D(8, 16, 3, stride=2, padding=1),
            paddle.nn.ReLU())                     # [N, 16, 8, 8]
        self.rpn_head = paddle.nn.Conv2D(16, 5, 1)  # 4 loc + 1 score
        self.roi_fc = paddle.nn.Linear(16 * 2 * 2, 32)
        self.cls_head = paddle.nn.Linear(32, num_classes)
        self.reg_head = paddle.nn.Linear(32, 4 * num_classes)
        self.mask_head = paddle.nn.Linear(16 * 2 * 2,
                                          num_classes * 4 * 4)
        self.num_classes = num_classes


def _anchors_8x8():
    ys, xs = np.meshgrid(np.arange(8), np.arange(8), indexing="ij")
    return np.stack([xs.ravel() * 4, ys.ravel() * 4,
                     xs.ravel() * 4 + 7, ys.ravel() * 4 + 7],
                    axis=1).astype(np.float32)


@pytest.fixture(scope="module")
def scene():
    gt = np.array([[[2, 2, 13, 13], [18, 16, 29, 30]]], np.float32)
    gtc = np.array([[1, 2]], np.int64)
    info = np.array([[32, 32, 1.0]], np.float32)
    m1 = np.zeros((32, 32), np.uint8)
    m1[2:14, 2:14] = 1
    m2 = np.zeros((32, 32), np.uint8)
    m2[16:31, 18:30] = 1
    rng = np.random.default_rng(0)
    img = rng.standard_normal((1, 3, 32, 32)).astype(np.float32) * 0.1
    img[:, 0, 2:14, 2:14] += 2.0     # class-1 object signal
    img[:, 1, 16:31, 18:30] += 2.0   # class-2 object signal
    return img, gt, gtc, info, [m1, m2]


def _train_step(model, img, gt, gtc, info, masks, anchors):
    feat = model.backbone(to_tensor(img))          # [1, 16, 8, 8]
    rpn_out = model.rpn_head(feat)                 # [1, 5, 8, 8]
    from paddle1_tpu.ops import manip_ops
    M = anchors.shape[0]
    rpn_flat = manip_ops.reshape(
        manip_ops.transpose(rpn_out, [0, 2, 3, 1]), [1, M, 5])
    bbox_pred = rpn_flat[:, :, :4]
    cls_logits = rpn_flat[:, :, 4:5]

    # --- RPN loss ---
    ps, pl, tl, tb, iw = L.rpn_target_assign(
        bbox_pred, cls_logits, to_tensor(anchors), None,
        to_tensor(gt), None, to_tensor(info),
        gt_lengths=np.array([2], np.int64),
        rpn_batch_size_per_im=32, rpn_positive_overlap=0.5,
        rpn_negative_overlap=0.3, use_random=False)
    lbl = to_tensor(_np(tl).astype(np.float32))
    rpn_cls_loss = F.binary_cross_entropy_with_logits(ps, lbl)
    rpn_reg_loss = (L.smooth_l1(pl, tb, inside_weight=iw,
                                outside_weight=iw)).mean()

    # --- proposals (host) + second-stage sampling ---
    sc_map = _np(cls_logits).reshape(1, 8, 8, 1).transpose(0, 3, 1, 2)
    bd_map = _np(bbox_pred).reshape(1, 8, 8, 4).transpose(0, 3, 1, 2)
    rois, probs, rlens = L.generate_proposals(
        to_tensor(sc_map), to_tensor(bd_map), to_tensor(info),
        to_tensor(anchors.reshape(8, 8, 1, 4)),
        to_tensor(np.ones((8, 8, 1, 4), np.float32)),
        pre_nms_top_n=64, post_nms_top_n=12, nms_thresh=0.7,
        min_size=2.0)
    srois, slabels, stgt, siw, sow, slens = L.generate_proposal_labels(
        rois, to_tensor(gtc), None, to_tensor(gt), to_tensor(info),
        rois_lengths=np.asarray(rlens.numpy()), batch_size_per_im=16,
        fg_thresh=0.5, bg_thresh_hi=0.5, class_nums=model.num_classes,
        use_random=False)

    # --- ROI head over prroi-pooled features ---
    pooled = L.prroi_pool(feat, srois, spatial_scale=8.0 / 32.0,
                          pooled_height=2, pooled_width=2)
    R = pooled.shape[0]
    flat = manip_ops.reshape(pooled, [R, -1])
    hid = F.relu(model.roi_fc(flat))
    cls_logit = model.cls_head(hid)
    reg_pred = model.reg_head(hid)
    cls_loss = F.softmax_with_cross_entropy(
        cls_logit, to_tensor(_np(slabels).astype(np.int64))).mean()
    reg_loss = ((reg_pred - stgt) ** 2 * siw).sum() / max(R, 1)

    # --- mask head on fg rois ---
    mrois, has, mtgt, mlens = L.generate_mask_labels(
        to_tensor(info), None, None, [masks], srois, slabels,
        num_classes=model.num_classes, resolution=4,
        rois_lengths=np.asarray(slens.numpy()))
    mask_loss = to_tensor(np.float32(0.0))
    if _np(mtgt).shape[0]:
        mp = L.prroi_pool(feat, mrois, spatial_scale=8.0 / 32.0,
                          pooled_height=2, pooled_width=2)
        mlogits = model.mask_head(
            manip_ops.reshape(mp, [mp.shape[0], -1]))
        tgt = _np(mtgt).astype(np.float32)
        w = (tgt >= 0).astype(np.float32)
        mask_loss = (F.binary_cross_entropy_with_logits(
            mlogits, to_tensor(np.clip(tgt, 0, 1)), reduction="none")
            * to_tensor(w)).sum() / max(w.sum(), 1)

    return rpn_cls_loss + rpn_reg_loss + cls_loss + 0.1 * reg_loss \
        + mask_loss


@pytest.mark.slow  # ~55s of convergence soaks; the per-op detection
# suites (test_detection_ops/test_detection_train) keep the stage math
# covered in-tier (CI heavy step)
class TestTwoStageE2E:
    def test_pipeline_trains(self, scene):
        img, gt, gtc, info, masks = scene
        paddle.seed(11)
        model = TinyTwoStage()
        anchors = _anchors_8x8()
        opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                    parameters=model.parameters())
        losses = []
        for step in range(12):
            loss = _train_step(model, img, gt, gtc, info, masks,
                               anchors)
            loss.backward()
            if step == 0:
                # gradients reached every stage (checked BEFORE the
                # clear: a stage silently detached would show zeros)
                for p, name in [(model.backbone[0].weight, "backbone"),
                                (model.rpn_head.weight, "rpn"),
                                (model.roi_fc.weight, "roi_fc"),
                                (model.cls_head.weight, "cls"),
                                (model.reg_head.weight, "reg"),
                                (model.mask_head.weight, "mask")]:
                    assert p.grad is not None, name
                    assert np.abs(_np(p.grad)).sum() > 0, name
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss.numpy())))
        assert losses[-1] < losses[0] * 0.8, losses

    def test_proposals_converge_toward_gt(self, scene):
        """After training, the RPN's top proposal overlaps a gt box."""
        img, gt, gtc, info, masks = scene
        paddle.seed(12)
        model = TinyTwoStage()
        anchors = _anchors_8x8()
        opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                    parameters=model.parameters())
        for _ in range(40):
            loss = _train_step(model, img, gt, gtc, info, masks,
                               anchors)
            loss.backward()
            opt.step()
            opt.clear_grad()
        feat = model.backbone(to_tensor(img))
        rpn_out = model.rpn_head(feat)
        sc_map = _np(rpn_out)[:, 4:5]
        bd_map = _np(rpn_out)[:, :4]
        rois, probs, _ = L.generate_proposals(
            to_tensor(sc_map), to_tensor(bd_map), to_tensor(info),
            to_tensor(anchors.reshape(8, 8, 1, 4)),
            to_tensor(np.ones((8, 8, 1, 4), np.float32)),
            pre_nms_top_n=64, post_nms_top_n=3, nms_thresh=0.7,
            min_size=2.0)
        tops = _np(rois)

        def iou(a, b):
            ix = max(0, min(a[2], b[2]) - max(a[0], b[0]) + 1)
            iy = max(0, min(a[3], b[3]) - max(a[1], b[1]) + 1)
            inter = ix * iy
            aa = (a[2] - a[0] + 1) * (a[3] - a[1] + 1)
            bb = (b[2] - b[0] + 1) * (b[3] - b[1] + 1)
            return inter / (aa + bb - inter)
        best = max(iou(t, g) for t in tops for g in gt[0])
        assert best > 0.2, (tops, best)
