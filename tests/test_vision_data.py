"""vision.transforms + datasets + DataLoader integration (reference
test strategy: test_transforms.py, test_datasets.py)."""

import unittest

import numpy as np

import paddle1_tpu as paddle
from paddle1_tpu.vision import transforms as T
from paddle1_tpu.vision.datasets import FakeData


class TestTransforms(unittest.TestCase):
    def setUp(self):
        self.img = np.random.randint(0, 256, (40, 60, 3), np.uint8)

    def test_to_tensor_chw_scale(self):
        t = T.functional.to_tensor(self.img)
        self.assertEqual(t.shape, [3, 40, 60])
        self.assertLessEqual(float(t.numpy().max()), 1.0)

    def test_resize_shapes(self):
        self.assertEqual(T.functional.resize(self.img, (20, 30)).shape,
                         (20, 30, 3))
        # int size resizes the short side
        out = T.functional.resize(self.img, 20)
        self.assertEqual(out.shape[0], 20)

    def test_resize_identity(self):
        out = T.functional.resize(self.img, (40, 60))
        np.testing.assert_array_equal(out, self.img)

    def test_crop_flip_pad(self):
        self.assertEqual(T.functional.center_crop(self.img, 24).shape,
                         (24, 24, 3))
        np.testing.assert_array_equal(T.functional.hflip(self.img),
                                      self.img[:, ::-1])
        self.assertEqual(T.functional.pad(self.img, 2).shape, (44, 64, 3))

    def test_normalize(self):
        t = T.functional.to_tensor(self.img)
        out = T.functional.normalize(t, [0.5, 0.5, 0.5], [0.5, 0.5, 0.5])
        self.assertAlmostEqual(
            float(out.numpy().mean()),
            float((t.numpy() - 0.5).mean() / 0.5), places=5)

    def test_compose_pipeline(self):
        pipe = T.Compose([
            T.Resize(32), T.RandomCrop(28), T.RandomHorizontalFlip(),
            T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3)])
        out = pipe(self.img)
        self.assertEqual(out.shape, [3, 28, 28])

    def test_color_ops_preserve_dtype(self):
        for fn in (lambda i: T.functional.adjust_brightness(i, 1.2),
                   lambda i: T.functional.adjust_contrast(i, 0.8),
                   lambda i: T.functional.adjust_saturation(i, 1.5),
                   lambda i: T.functional.adjust_hue(i, 0.1)):
            out = fn(self.img)
            self.assertEqual(out.dtype, np.uint8)
            self.assertEqual(out.shape, self.img.shape)

    def test_hue_identity(self):
        out = T.functional.adjust_hue(self.img, 0.0)
        self.assertLessEqual(
            np.abs(out.astype(int) - self.img.astype(int)).max(), 2)


class TestDatasets(unittest.TestCase):
    def test_fake_data_loader(self):
        ds = FakeData(num_samples=32, image_shape=(3, 16, 16), num_classes=4,
                      transform=T.Compose([T.ToTensor()]))
        loader = paddle.io.DataLoader(ds, batch_size=8, shuffle=True)
        batches = list(loader)
        self.assertEqual(len(batches), 4)
        x, y = batches[0]
        self.assertEqual(list(x.shape), [8, 3, 16, 16])
        self.assertEqual(list(y.shape), [8, 1])

    def test_download_raises(self):
        from paddle1_tpu.vision.datasets import MNIST
        with self.assertRaises(RuntimeError):
            MNIST()

    def test_mnist_parser(self):
        """Round-trip the IDX format through a generated file."""
        import gzip, struct, tempfile, os
        imgs = np.random.randint(0, 256, (10, 28, 28), np.uint8)
        labels = np.random.randint(0, 10, 10).astype(np.uint8)
        with tempfile.TemporaryDirectory() as d:
            ip = os.path.join(d, "img.gz")
            lp = os.path.join(d, "lab.gz")
            with gzip.open(ip, "wb") as f:
                f.write(struct.pack(">IIII", 2051, 10, 28, 28))
                f.write(imgs.tobytes())
            with gzip.open(lp, "wb") as f:
                f.write(struct.pack(">II", 2049, 10))
                f.write(labels.tobytes())
            from paddle1_tpu.vision.datasets import MNIST
            ds = MNIST(image_path=ip, label_path=lp)
            self.assertEqual(len(ds), 10)
            img, lab = ds[3]
            np.testing.assert_array_equal(img, imgs[3])
            self.assertEqual(int(lab[0]), int(labels[3]))
