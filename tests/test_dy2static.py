"""dy2static: the AST fallback for tensor-dependent control flow under
jit.to_static (paddle1_tpu/jit/dy2static.py).

Reference analog: the dygraph_to_static unit tests
(python/paddle/fluid/tests/unittests/dygraph_to_static/test_ifelse.py,
test_loop.py, test_logical_op.py) — same behaviors, trace-native design.
"""

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.core.tensor import Tensor, to_tensor
from paddle1_tpu.jit import not_to_static, to_static
from paddle1_tpu.jit.dy2static import convert_control_flow


def _t(x, dtype="float32"):
    return to_tensor(np.asarray(x, dtype))


class TestIfElse:
    def test_tensor_condition_both_values(self):
        @to_static
        def f(x):
            if (x.sum() > 0):
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        pos = _t([1.0, 2.0])
        neg = _t([-1.0, -2.0])
        np.testing.assert_allclose(f(pos).numpy(), [2.0, 4.0])
        np.testing.assert_allclose(f(neg).numpy(), [-2.0, -3.0])

    def test_python_condition_untouched(self):
        @to_static
        def f(x, flag=True):
            if flag:
                return x + 1.0
            return x - 1.0

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [2.0])

    def test_modifies_existing_variable(self):
        @to_static
        def f(x):
            y = x + 1.0
            if (x.mean() > 0):
                y = y * 3.0
            return y

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [6.0])
        np.testing.assert_allclose(f(_t([-1.0])).numpy(), [0.0])

    def test_one_sided_assignment_teaches(self):
        @to_static
        def f(x):
            if (x.sum() > 0):
                z = x * 2.0
            return z + 1.0

        with pytest.raises(UnboundLocalError, match="BOTH branches"):
            f(_t([1.0]))

    def test_one_sided_dead_temp_is_fine(self):
        # a temporary used only inside its branch must not block conversion
        @to_static
        def f(x):
            if (x.sum() > 0):
                t = x * 2.0
                y = t + 1.0
            else:
                y = x
            return y

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [3.0])
        np.testing.assert_allclose(f(_t([-1.0])).numpy(), [-1.0])

    def test_only_taken_branch_executes(self):
        # the lax.cond must be a real cond, not a select: put an assert
        # on shapes that only holds when XLA doesn't need the false branch
        # value — here we check numerically that each predicate picks the
        # right branch (behavioral proxy; HLO-level check is the kernel's)
        @to_static
        def f(x):
            if (x.sum() > 0):
                y = x / x.sum()
            else:
                y = x * 0.0
            return y

        np.testing.assert_allclose(f(_t([2.0, 2.0])).numpy(), [0.5, 0.5])
        np.testing.assert_allclose(f(_t([-2.0])).numpy(), [0.0])

    def test_gradients_flow_through_cond(self):
        lin = paddle.nn.Linear(2, 2)

        @to_static
        def f(x):
            h = lin(x)
            if (h.sum() > 0):
                out = h * h
            else:
                out = h * 3.0
            return out.sum()

        x = _t([[0.5, -0.25]])
        x.stop_gradient = False
        loss = f(x)
        loss.backward()
        assert x.grad is not None
        assert np.isfinite(x.grad.numpy()).all()
        # eager reference (same params, taken branch)
        h = lin(x)
        ref = (h * h).sum() if float(h.sum().numpy()) > 0 \
            else (h * 3.0).sum()
        np.testing.assert_allclose(float(loss.numpy()),
                                   float(ref.numpy()), rtol=1e-5)

    def test_nested_if(self):
        @to_static
        def f(x):
            y = x
            if (x.sum() > 0):
                if (x.sum() > 10):
                    y = x * 100.0
                else:
                    y = x * 10.0
            else:
                y = -x
            return y

        np.testing.assert_allclose(f(_t([20.0])).numpy(), [2000.0])
        np.testing.assert_allclose(f(_t([1.0])).numpy(), [10.0])
        np.testing.assert_allclose(f(_t([-3.0])).numpy(), [3.0])


class TestLoops:
    def test_tensor_while(self):
        @to_static
        def f(n):
            i = to_tensor(np.float32(0.0))
            acc = to_tensor(np.float32(0.0))
            while (i < n):
                acc = acc + i
                i = i + 1.0
            return acc

        assert float(f(_t(5.0)).numpy()) == 10.0  # 0+1+2+3+4

    def test_python_while_still_python(self):
        @to_static
        def f(x):
            k = 0
            while k < 3:
                x = x + 1.0
                k = k + 1
            return x

        np.testing.assert_allclose(f(_t([0.0])).numpy(), [3.0])

    def test_for_range_tensor_bound(self):
        @to_static
        def f(x, n):
            acc = x * 0.0
            for i in range(n):
                acc = acc + x
            return acc

        np.testing.assert_allclose(
            f(_t([2.0]), to_tensor(np.int32(4))).numpy(), [8.0])

    def test_for_range_python_bound(self):
        @to_static
        def f(x):
            acc = x * 0.0
            for i in range(3):
                acc = acc + x * float(i)
            return acc

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [3.0])

    def test_while_write_first_temp_allowed(self):
        # a per-iteration temporary (written before read) needs no init
        @to_static
        def f(n):
            i = to_tensor(np.float32(0.0))
            acc = to_tensor(np.float32(0.0))
            while (i < n):
                s = i * 2.0
                acc = acc + s
                i = i + 1.0
            return acc

        assert float(f(_t(3.0)).numpy()) == 6.0  # 0+2+4

    def test_while_read_first_uninitialized_teaches(self):
        @to_static
        def f(n):
            i = to_tensor(np.float32(0.0))
            while (i < n):
                acc = acc + i  # reads acc before ever assigning it
                i = i + 1.0
            return i

        with pytest.raises(InvalidArgumentError,
                           match="unbound at loop entry"):
            f(_t(3.0))

    def test_loop_with_break_stays_python(self):
        # break → untransformed; python bounds still work
        @to_static
        def f(x):
            acc = x * 0.0
            for i in range(10):
                if i >= 2:
                    break
                acc = acc + x
            return acc

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [2.0])

    def test_grad_through_unrolled_loop(self):
        # concrete bound → the loop unrolls under the trace and stays
        # reverse-differentiable (traced-bound while_loop is forward-only,
        # an XLA limitation documented in dy2static.py)
        @to_static
        def f(x):
            y = x
            i = 0
            while i < 2:
                y = y * x
                i = i + 1
            return y.sum()

        x = _t([2.0])
        x.stop_gradient = False
        loss = f(x)  # y = x^3
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-5)


class TestLogicalOps:
    def test_python_value_semantics_kept(self):
        from paddle1_tpu.jit.dy2static import (convert_logical_and,
                                               convert_logical_not,
                                               convert_logical_or)

        # python operands keep python `and`/`or`/`not` VALUE semantics,
        # including short-circuit (the rhs lambda must not run)
        assert convert_logical_or(0, lambda: "fallback") == "fallback"
        assert convert_logical_or("first", lambda: 1 / 0) == "first"
        assert convert_logical_and(0, lambda: 1 / 0) == 0
        assert convert_logical_and(2, lambda: "rhs") == "rhs"
        assert convert_logical_not(0) is True
        assert convert_logical_not("x") is False

    def test_tensor_logical(self):
        @to_static
        def f(x):
            cond = (x.sum() > 0) and (x.max() < 10)
            if cond:
                out = x + 1.0
            else:
                out = x - 1.0
            return out

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [2.0])
        np.testing.assert_allclose(f(_t([-1.0])).numpy(), [-2.0])
        np.testing.assert_allclose(f(_t([100.0])).numpy(), [99.0])

    def test_tensor_not(self):
        @to_static
        def f(x):
            if not (x.sum() > 0):
                out = x * -1.0
            else:
                out = x
            return out

        np.testing.assert_allclose(f(_t([-4.0])).numpy(), [4.0])
        np.testing.assert_allclose(f(_t([4.0])).numpy(), [4.0])


class TestOptOutAndFallback:
    def test_not_to_static_keeps_teaching_error(self):
        @to_static
        @not_to_static
        def f(x):
            if (x.sum() > 0):
                y = x * 2.0
            else:
                y = x
            return y

        with pytest.raises(InvalidArgumentError, match="static.nn.cond"):
            f(_t([1.0]))

    def test_flag_disables_conversion(self):
        from paddle1_tpu.core.flags import flags_guard

        with flags_guard(dy2static=False):
            @to_static
            def f(x):
                if (x.sum() > 0):
                    y = x * 2.0
                else:
                    y = x
                return y

            with pytest.raises(InvalidArgumentError,
                               match="static.nn.cond"):
                f(_t([1.0]))

    def test_source_unavailable_falls_back(self):
        ns = {}
        exec("def g(x):\n    return x + 1.0\n", ns)
        converted = convert_control_flow(ns["g"])
        assert converted is ns["g"]

    def test_no_control_flow_untouched(self):
        def g(x):
            return x * 2.0

        assert convert_control_flow(g) is g

    def test_closure_snapshot(self):
        scale = _t([3.0])

        @to_static
        def f(x):
            if (x.sum() > 0):
                y = x * scale
            else:
                y = x
            return y

        np.testing.assert_allclose(f(_t([2.0])).numpy(), [6.0])


class TestInsideLayer:
    def test_layer_forward_with_tensor_if(self):
        class Gate(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = paddle.nn.Linear(2, 2)

            def forward(self, x):
                h = self.lin(x)
                if (h.sum() > 0):
                    out = h * 2.0
                else:
                    out = h * 0.5
                return out

        m = to_static(Gate())
        x = _t([[1.0, 1.0]])
        out = m(x)
        h = m.lin(x)
        factor = 2.0 if float(h.sum().numpy()) > 0 else 0.5
        np.testing.assert_allclose(out.numpy(), (h * factor).numpy(),
                                   rtol=1e-5)


class TestPythonSemanticsParity:
    """r3 review findings: the rewrite must not change plain-Python
    behavior of converted functions."""

    def test_for_loop_var_post_loop_value(self):
        @to_static
        def f(x):
            s = x * 0.0
            for i in range(3):
                s = s + x
            return s, i

        s, i = f(_t([1.0]))
        assert i == 2  # python: last executed value, not one-past

    def test_for_empty_range_leaves_var_unbound(self):
        @to_static
        def f(x):
            s = x * 0.0
            for i in range(0):
                s = s + x
            return s, i

        with pytest.raises(UnboundLocalError, match="'i'"):
            f(_t([1.0]))

    def test_skipped_branch_use_raises_unbound(self):
        @to_static
        def f(x, flag=False):
            if flag:
                y = x * 2.0
            return y + 1.0

        with pytest.raises(UnboundLocalError, match="'y'"):
            f(_t([1.0]))

    def test_mm_rejects_broadcast(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        a = _t(np.zeros((4, 2, 3), np.float32))
        b = _t(np.zeros((3, 2), np.float32))
        with pytest.raises(InvalidArgumentError, match="broadcast"):
            paddle.mm(a, b)
        ok = paddle.mm(_t(np.ones((2, 3), np.float32)),
                       _t(np.ones((3, 2), np.float32)))
        assert ok.shape == [2, 2]


class TestReviewRegressions:
    def test_walrus_in_test_stays_python(self):
        @to_static
        def f(x):
            k = 0
            acc = x * 0.0
            while (m := k * 2) < 6:
                acc = acc + x + float(m)
                k = k + 1
            return acc

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [9.0])  # 1+3+5

    def test_side_effecting_test_runs_once_per_state(self):
        calls = []

        def noisy_lt(k):
            calls.append(k)
            return k < 3

        @to_static
        def f(x):
            k = 0
            while noisy_lt(k):
                x = x + 1.0
                k = k + 1
            return x

        f(_t([0.0]))
        assert calls == [0, 1, 2, 3]  # exactly once per state

    def test_orelse_read_counts_as_read_first(self):
        # acc is read only inside a for/else in the traced-while body —
        # still an observable pre-iteration read, must teach, not zero-seed
        @to_static
        def f(n):
            i = to_tensor(np.float32(0.0))
            while (i < n):
                for _k in [1]:
                    pass
                else:
                    acc = acc + 1.0
                i = i + 1.0
            return i

        with pytest.raises(InvalidArgumentError,
                           match="unbound at loop entry"):
            f(_t(3.0))

    def test_user_type_error_not_masked(self):
        @to_static
        def f(x):
            if (x.sum() > 0):
                y = x * None
            else:
                y = x
            return y

        with pytest.raises(TypeError) as e:
            f(_t([1.0]))
        assert "mismatched shapes" not in str(e.value)


class TestConvertCall:
    """Recursive callee conversion (reference convert_call_func.py):
    tensor control flow inside HELPERS converts too."""

    def test_helper_with_tensor_if_converts(self):
        def helper(x):
            if (x.sum() > 0):
                y = x * 2.0
            else:
                y = -x
            return y

        @to_static
        def f(x):
            return helper(x) + 1.0

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [3.0])
        np.testing.assert_allclose(f(_t([-2.0])).numpy(), [3.0])

    def test_two_level_nesting(self):
        def inner(x):
            acc = x * 0.0
            for i in range(3):
                acc = acc + x
            return acc

        def outer(x):
            if (x.sum() > 0):
                out = inner(x)
            else:
                out = x
            return out

        @to_static
        def f(x):
            return outer(x)

        np.testing.assert_allclose(f(_t([2.0])).numpy(), [6.0])
        np.testing.assert_allclose(f(_t([-2.0])).numpy(), [-2.0])

    def test_not_to_static_helper_untouched(self):
        @not_to_static
        def helper(x):
            if (x.sum() > 0):  # would raise if traced
                return x * 2.0
            return x

        @to_static
        def f(x, use_helper=False):
            if use_helper:
                return helper(x)
            return x + 1.0

        # helper never converted; calling it with a concrete pred works
        np.testing.assert_allclose(f(_t([1.0])).numpy(), [2.0])

    def test_library_calls_pass_through(self):
        @to_static
        def f(x):
            z = np.float32(2.0)  # numpy: untouched by convert_call
            if (x.sum() > 0):
                y = x * float(z)
            else:
                y = x
            return y

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [2.0])

    def test_user_method_converts(self):
        class Scaler:
            def pick(self, x):
                if (x.sum() > 0):
                    s = x * 10.0
                else:
                    s = x * 0.1
                return s

        sc = Scaler()

        @to_static
        def f(x):
            return sc.pick(x)

        np.testing.assert_allclose(f(_t([1.0])).numpy(), [10.0])
        np.testing.assert_allclose(f(_t([-1.0])).numpy(), [-0.1])

    def test_conversion_cached(self):
        from paddle1_tpu.jit.dy2static import _call_cache, convert_call

        def helper(x):
            if (x.sum() > 0):
                y = x
            else:
                y = -x
            return y

        c1 = convert_call(helper)
        c2 = convert_call(helper)
        assert c1 is c2 and c1 is not helper

    def test_stdlib_functions_never_converted(self):
        import re as _re
        from paddle1_tpu.jit.dy2static import convert_call
        assert convert_call(_re.sub) is _re.sub
        assert convert_call(_re.sub)("a", "b", "banana") == "bbnbnb"
        import json
        assert convert_call(json.dumps) is json.dumps

    def test_super_method_bails_safely(self):
        from paddle1_tpu.jit.dy2static import convert_call

        class Base:
            def forward(self, x):
                return x + 1

        class Child(Base):
            def forward(self, x):
                return super().forward(x) * 2

        c = Child()
        assert convert_call(c.forward)(10) == 22  # no __class__ crash

    def test_private_name_mangling_bails(self):
        from paddle1_tpu.jit.dy2static import convert_control_flow

        class Secretive:
            def __init__(self):
                self.__hidden = 5

            def peek(self):
                if True:
                    v = self.__hidden
                return v

        s = Secretive()
        conv = convert_control_flow(s.peek)
        assert conv() == 5  # mangled attr still resolves

    def test_live_globals_no_module_clobber(self, tmp_path):
        import sys
        mod_file = tmp_path / "dy2s_usermod.py"
        mod_file.write_text(
            "SCALE = 2.0\n"
            "def noop(v):\n    return v\n"
            "def helper(x):\n    return noop(x) * SCALE\n")
        sys.path.insert(0, str(tmp_path))
        try:
            import dy2s_usermod as um
            from paddle1_tpu.jit.dy2static import convert_call
            orig = um.helper
            conv = convert_call(um.helper)
            assert conv is not orig
            assert um.helper is orig          # module binding untouched
            assert conv(1.0) == 2.0
            um.SCALE = 3.0
            assert conv(1.0) == 3.0           # live module globals
        finally:
            sys.path.remove(str(tmp_path))
            sys.modules.pop("dy2s_usermod", None)


class TestEarlyReturn:
    """RETURN transformer (r4): an `if` whose paths all return becomes a
    lax.cond over the return values (reference
    dygraph_to_static/return_transformer.py)."""

    def test_tensor_condition_early_return(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                return x * 2.0
            return x - 1.0

        pos = np.ones(3, np.float32)
        neg = -np.ones(3, np.float32)
        np.testing.assert_allclose(np.asarray(f(to_tensor(pos)).numpy()),
                                   pos * 2)
        np.testing.assert_allclose(np.asarray(f(to_tensor(neg)).numpy()),
                                   neg - 1)

    def test_early_return_with_statements_after(self):
        @to_static
        def f(x):
            y = x + 1.0
            if y.sum() > 10.0:
                z = y * 3.0
                return z
            w = y * 2.0
            w = w + 0.5
            return w

        small = np.zeros(3, np.float32)
        big = np.full(3, 10.0, np.float32)
        np.testing.assert_allclose(
            np.asarray(f(to_tensor(small)).numpy()), 2.5)
        np.testing.assert_allclose(
            np.asarray(f(to_tensor(big)).numpy()), 33.0)

    def test_elif_chain_returns(self):
        @to_static
        def f(x):
            if x.sum() > 10.0:
                return x * 0.0 + 3.0
            elif x.sum() > 0.0:
                return x * 0.0 + 2.0
            return x * 0.0 + 1.0

        for fill, expect in ((20.0, 3.0), (1.0, 2.0), (-5.0, 1.0)):
            out = f(to_tensor(np.full(2, fill, np.float32)))
            np.testing.assert_allclose(np.asarray(out.numpy()), expect)

    def test_early_return_differentiable(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                return (x * 3.0).sum()
            return (x * 5.0).sum()

        x = to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        f(x).backward()
        np.testing.assert_allclose(np.asarray(x.grad.numpy()), 3.0)

    def test_tuple_returns_match(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                return x * 2.0, x + 1.0
            return x * 4.0, x - 1.0

        a, b = f(to_tensor(-np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(a.numpy()), -4.0)
        np.testing.assert_allclose(np.asarray(b.numpy()), -2.0)

    def test_mismatched_structures_teach(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                return x, x
            return x

        with pytest.raises(InvalidArgumentError, match="same structure"):
            f(to_tensor(np.ones(2, np.float32)))

    def test_implicit_none_fallthrough_teaches(self):
        # `if t: return x` with nothing after: the implicit fall-off
        # returns None — a structure mismatch under a traced condition,
        # surfaced as the teaching error (not silent wrong values)
        @to_static
        def f(x):
            if x.sum() > 0:
                return x

        with pytest.raises(InvalidArgumentError, match="same structure"):
            f(to_tensor(np.ones(2, np.float32)))

    def test_plain_python_unchanged(self):
        # a CONCRETE condition (closure constant — an argument bool
        # would be traced by jit) keeps exact Python semantics incl.
        # side effects only on the taken path
        calls = []
        flag = True

        @to_static
        def f(x):
            if flag:
                calls.append("t")
                return x + 1
            calls.append("f")
            return x - 1

        assert float(f(to_tensor(np.float32(1.0))).numpy()) == 2.0
        assert calls == ["t"]

    def test_treedef_mismatch_with_equal_leaves_teaches(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                return x, (x, x)
            return (x, x), x

        with pytest.raises(InvalidArgumentError, match="same structure"):
            f(to_tensor(np.ones(2, np.float32)))

    def test_early_return_before_loop_with_break_converts(self):
        # the break belongs to the inner for-loop; absorbing the loop
        # into the else branch is safe and must not block conversion
        @to_static
        def f(x):
            if x.sum() > 100.0:
                return x * 0.0
            acc = x * 0.0
            for i in range(3):
                acc = acc + x
                if i == 1:
                    break
            return acc

        out = f(to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), 2.0)
        big = f(to_tensor(np.full(2, 100.0, np.float32)))
        np.testing.assert_allclose(np.asarray(big.numpy()), 0.0)
