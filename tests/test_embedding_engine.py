"""Sharded embedding engine (ISSUE 19 tentpole): the LFU/TTL
admission–eviction bridge between the HBM tier and the host/remote
table tiers — routing, budgets, exactly-once move accounting, census
integration, optimizer-slot fidelity across tier transfers, and the
in-graph one-dispatch-per-step training contract."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle1_tpu as paddle
from paddle1_tpu.core.errors import PreconditionNotMetError
from paddle1_tpu.core.tensor import Tensor
from paddle1_tpu.distributed import (EmbeddingService, HBMShardedEmbedding,
                                     ParallelEngine, ShardedEmbeddingEngine,
                                     SparseTable, TableServer, build_mesh,
                                     hash_bucket, remote_service)
from paddle1_tpu.nn import TieredEmbedding
from paddle1_tpu.nn.layer_base import Layer
from paddle1_tpu.obs import MetricsRegistry
from paddle1_tpu.obs import hbm as obs_hbm


@pytest.fixture(autouse=True)
def _census_isolation():
    yield
    obs_hbm.reset()


def _make(capacity=8, dim=4, budget=None, ttl_s=None, optimizer="sgd",
          metrics=None, num_shards=2):
    hbm = HBMShardedEmbedding(capacity, dim)
    host = EmbeddingService(dim, num_shards=num_shards,
                            optimizer=optimizer)
    eng = ShardedEmbeddingEngine(hbm, host, hbm_row_budget=budget,
                                 ttl_s=ttl_s, metrics=metrics)
    return eng, hbm, host


class TestHashBucket:
    def test_np_jnp_agree_and_in_range(self):
        ids = np.array([0, 1, 7, 12345, 2**33 + 17, 2**40 - 1], np.int64)
        a = np.asarray(hash_bucket(ids, 1024, xp=np))
        b = np.asarray(hash_bucket(jnp.asarray(ids), 1024))
        np.testing.assert_array_equal(a, b)
        assert a.min() >= 0 and a.max() < 1024
        # the finalizer actually mixes: consecutive ids scatter
        assert len(set(np.asarray(
            hash_bucket(np.arange(64), 1024, xp=np)).tolist())) > 32

    def test_hashed_layer_folds_ids_in_graph_and_on_host(self):
        emb = HBMShardedEmbedding(16, 4, hashed=True)
        big = np.array([[2**35 + 3, 7], [99991, 0]], np.int64)
        out = np.asarray(emb(Tensor(jnp.asarray(big))).numpy())
        w = np.asarray(emb.weight.numpy())
        np.testing.assert_allclose(out, w[emb.bucketize(big)])
        # pull accepts out-of-range raw ids in hashed mode
        assert emb.pull([2**40]).shape == (1, 4)

    def test_unhashed_bucketize_is_identity(self):
        emb = HBMShardedEmbedding(16, 4)
        np.testing.assert_array_equal(emb.bucketize([3, 5]),
                                      np.array([3, 5]))


class TestRouting:
    def test_admit_on_miss_and_shape(self):
        eng, _, host = _make()
        slots = eng.route(np.array([[1, 2], [3, 1]], np.int64))
        assert slots.shape == (2, 2)
        assert slots[0, 0] == slots[1, 1]          # same id, same slot
        assert len({int(s) for s in slots.reshape(-1)}) == 3
        acc = eng.accounting()
        assert acc["admit_total"] == 3 and acc["resident"] == 3
        assert acc["balanced"]

    def test_hits_are_stable_and_counted(self):
        eng, _, _ = _make()
        s1 = eng.route([1, 2, 3])
        s2 = eng.route([3, 2, 1])
        np.testing.assert_array_equal(np.sort(s1), np.sort(s2))
        acc = eng.accounting()
        assert acc["miss_total"] == 3 and acc["hit_total"] == 3
        assert acc["admit_total"] == 3   # no re-admission on hit

    def test_promotion_moves_row_value_and_empties_host(self):
        eng, _, host = _make()
        v = host.pull([5])[0]            # materialize in the host tier
        assert eng.tier_of(5) == "host"
        slot = int(eng.route([5])[0])
        np.testing.assert_allclose(eng.read_rows(np.array([slot])),
                                   v[None], rtol=1e-6)
        # move semantics: exactly one tier holds the row now
        assert eng.tier_of(5) == "hbm"
        assert len(host) == 0

    def test_over_budget_batch_raises_typed(self):
        eng, _, _ = _make(budget=3)
        with pytest.raises(PreconditionNotMetError, match="budget"):
            eng.route([0, 1, 2, 3])

    def test_lfu_demotes_cold_not_hot(self):
        eng, _, host = _make(budget=4)
        eng.route([0, 0, 0, 1, 2, 3])    # 0 is hot (freq 3)
        eng.route([4])                    # budget pressure: demote one
        assert eng.tier_of(0) == "hbm"
        demoted = [i for i in (1, 2, 3) if eng.tier_of(i) == "host"]
        assert len(demoted) == 1
        acc = eng.accounting()
        assert acc["demote_total"] == 1 and acc["balanced"]
        assert acc["resident"] == 4

    def test_ttl_demotes_idle_rows(self):
        eng, _, _ = _make(ttl_s=10.0)
        eng.route([1, 2], now=0.0)
        eng.route([3], now=100.0)        # 1, 2 idle past the TTL
        assert eng.tier_of(1) == "host" and eng.tier_of(2) == "host"
        assert eng.tier_of(3) == "hbm"
        assert eng.accounting()["ttl_evict_total"] == 2

    def test_sweep_ttl_explicit(self):
        eng, _, _ = _make(ttl_s=5.0)
        eng.route([7], now=0.0)
        assert eng.sweep_ttl(now=1.0) == 0
        assert eng.sweep_ttl(now=6.5) == 1
        assert eng.tier_of(7) == "host"

    def test_demote_all_preserves_values(self):
        eng, _, host = _make()
        slots = eng.route([1, 2, 3])
        rows = eng.read_rows(slots)
        assert eng.demote_all() == 3
        acc = eng.accounting()
        assert acc["resident"] == 0 and acc["balanced"]
        np.testing.assert_allclose(host.pull([1, 2, 3]), rows, rtol=1e-6)

    def test_exactly_once_under_churn(self):
        rng = np.random.default_rng(0)
        eng, _, _ = _make(capacity=8, budget=5)
        for _ in range(40):
            ids = rng.integers(0, 30, rng.integers(1, 5))
            eng.route(ids.astype(np.int64))
            acc = eng.accounting()
            assert acc["balanced"], acc
            assert acc["resident"] <= 5
        # every id lives in exactly one tier
        for i in range(30):
            tiers = [eng.tier_of(i)]
            assert tiers[0] in ("hbm", "host", "absent")

    def test_dim_mismatch_refused_at_construction(self):
        hbm = HBMShardedEmbedding(8, 4)
        with pytest.raises(ValueError, match="dim"):
            ShardedEmbeddingEngine(hbm, EmbeddingService(6))


class TestCensusAndGauges:
    def test_embed_bytes_track_logical_occupancy(self):
        eng, _, _ = _make(capacity=8, dim=4)
        assert obs_hbm.registered_bytes()["embed"] == 0
        eng.route([1, 2, 3])
        assert obs_hbm.registered_bytes()["embed"] == 3 * 4 * 4
        eng.demote_all()
        assert obs_hbm.registered_bytes()["embed"] == 0

    def test_embed_is_logical_not_physical(self):
        """The embed bucket must NOT inflate census totals/coverage —
        the backing weight allocation already counts under params."""
        eng, _, _ = _make()
        eng.route([1, 2])
        c = obs_hbm.census()
        assert c["subsystems"]["embed"] == 2 * 4 * 4
        assert c["census_bytes"] == obs_hbm._physical_total(
            c["subsystems"])
        assert "embed" not in {"params"} and \
            c["census_bytes"] == sum(
                b for s, b in c["subsystems"].items() if s != "embed")

    def test_publish_gauges_and_counters(self):
        m = MetricsRegistry()
        eng, _, host = _make(budget=4, metrics=m)
        eng.route([0, 1, 2, 3])
        eng.route([4])
        eng.publish_gauges()
        snap = m.snapshot()
        assert snap["gauges"]["embed_hbm_rows"] == 4
        assert snap["gauges"]["embed_hbm_budget_rows"] == 4
        assert snap["gauges"]["embed_hbm_bytes"] == 4 * 4 * 4
        assert snap["gauges"]["embed_host_rows"] == len(host)
        assert snap["counters"]["embed_admit_total"] == 5
        assert snap["counters"]["embed_demote_total"] == 1
        assert snap["counters"]["embed_miss_total"] == 5


class TestRemoteTier:
    def test_demotion_crosses_the_wire(self):
        servers = [TableServer(SparseTable(4, seed=s)).start()
                   for s in range(2)]
        try:
            svc = remote_service(4, [s.endpoint for s in servers])
            hbm = HBMShardedEmbedding(8, 4)
            eng = ShardedEmbeddingEngine(hbm, svc, hbm_row_budget=2)
            eng.route([1, 2])
            rows = eng.read_rows(eng.route([1, 2]))
            eng.route([3])               # demotes one over TCP
            acc = eng.accounting()
            assert acc["demote_total"] == 1 and acc["balanced"]
            demoted = [i for i in (1, 2) if eng.tier_of(i) == "host"]
            assert len(demoted) == 1
            # promoted back: the remote round trip preserved the value
            idx = 0 if demoted[0] == 1 else 1
            back = eng.route([demoted[0]])
            np.testing.assert_allclose(eng.read_rows(back), rows[[idx]],
                                       rtol=1e-6)
        finally:
            for s in servers:
                s.stop()


class TestStateDict:
    def test_mapping_round_trip_is_arrays_only(self):
        eng, _, _ = _make(budget=4)
        eng.route([3, 1, 4, 1, 5])       # 5 evicted? no: 4 uniq fits
        sd = eng.state_dict()
        for v in sd.values():
            assert isinstance(v, np.ndarray)   # PR 2 manifest-friendly
        before = dict(eng._slot_of)
        acc_before = eng.accounting()
        eng.route([9, 10])               # perturb
        eng.load_state_dict(sd)
        assert dict(eng._slot_of) == before
        acc = eng.accounting()
        assert acc["resident"] == acc_before["resident"]
        assert acc["admit_total"] == acc_before["admit_total"]
        assert acc["balanced"]


class _TieredModel(Layer):
    def __init__(self, engine):
        super().__init__()
        self.emb = TieredEmbedding(engine)
        self.head = paddle.nn.Linear(engine.dim, 1)

    def forward(self, slots):
        return self.head(self.emb(slots).mean(axis=1))


class TestInGraphTraining:
    def test_one_dispatch_per_step_with_admission_churn(self):
        """The tentpole contract: admission/eviction happen host-side
        in route(); the jitted step sees only fixed-shape slot gathers
        over the fixed-capacity table — one dispatch per step, one
        trace total, despite rows moving between tiers every step."""
        paddle.seed(0)
        hbm = HBMShardedEmbedding(16, 4)
        host = EmbeddingService(4, num_shards=2)
        eng = ShardedEmbeddingEngine(hbm, host, hbm_row_budget=8)
        model = _TieredModel(eng)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        peng = ParallelEngine(
            model, opt,
            lambda m, b: ((m(Tensor(b["slots"])) - Tensor(b["y"])) ** 2
                          ).mean(),
            mesh=build_mesh(dp=1, devices=jax.devices()[:1]),
            zero_stage=0)
        eng.bind_engine(peng)
        rng = np.random.default_rng(1)
        steps = 6
        for k in range(steps):
            ids = rng.integers(k * 3, k * 3 + 40, (4, 2)).astype(np.int64)
            slots = eng.route(ids)       # churn: fresh ids every step
            y = rng.standard_normal((4, 1)).astype(np.float32)
            peng.step({"slots": slots, "y": y})
            assert eng.accounting()["balanced"]
        assert peng.dispatch_count == steps
        assert peng.trace_count == 1     # no retrace on admission
        assert eng.accounting()["demote_total"] > 0   # churn was real

    def test_adam_slots_survive_demote_and_readmit(self):
        """Tier transfers move optimizer state with the row: a trained
        row's adam moments demote to the host tier intact and come back
        into the device slot arrays on re-admission."""
        paddle.seed(1)
        hbm = HBMShardedEmbedding(8, 4)
        host = EmbeddingService(4, num_shards=1, optimizer="adam")
        eng = ShardedEmbeddingEngine(hbm, host)
        model = _TieredModel(eng)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=model.parameters())
        peng = ParallelEngine(
            model, opt,
            lambda m, b: ((m(Tensor(b["slots"])) - Tensor(b["y"])) ** 2
                          ).mean(),
            mesh=build_mesh(dp=1, devices=jax.devices()[:1]),
            zero_stage=0)
        key = eng.bind_engine(peng)
        ids = np.array([[2, 5, 7]], np.int64)
        y = np.ones((1, 1), np.float32)
        for _ in range(3):
            slots = eng.route(ids)
            peng.step({"slots": slots, "y": y})
        slot_arrays = {n: np.asarray(jax.device_get(a))
                       for n, a in eng._slot_arrays().items()}
        assert sorted(slot_arrays) == ["moment1", "moment2"]
        s2 = int(eng.slot_of(2))
        m1_before = slot_arrays["moment1"][s2].copy()
        m2_before = slot_arrays["moment2"][s2].copy()
        assert np.abs(m1_before).max() > 0
        eng.demote_all()
        # host tier holds the moments now
        got = host.shards[0].evict([2])
        np.testing.assert_allclose(got["slots"][0, 0], m1_before,
                                   rtol=1e-6)
        np.testing.assert_allclose(got["slots"][0, 1], m2_before,
                                   rtol=1e-6)
        host.shards[0].admit(got["ids"], got["rows"], got["slots"],
                             got["steps"])
        # re-admission restores them into the device slot arrays
        new_slot = int(eng.route([2])[0])
        fresh = {n: np.asarray(jax.device_get(a))
                 for n, a in eng._slot_arrays().items()}
        np.testing.assert_allclose(fresh["moment1"][new_slot], m1_before,
                                   rtol=1e-6)
        np.testing.assert_allclose(fresh["moment2"][new_slot], m2_before,
                                   rtol=1e-6)
        assert key in peng.params

    def test_eager_lookup_matches_host_row(self):
        eng, _, host = _make()
        v = host.pull([9])[0]
        emb = TieredEmbedding(eng)
        out = np.asarray(emb.lookup(np.array([[9]])).numpy())
        np.testing.assert_allclose(out[0, 0], v, rtol=1e-6)
