"""Model-zoo smoke + training tests (reference test strategy §4:
test_imperative_resnet.py, book/ e2e tests)."""

import unittest

import numpy as np
import pytest

import paddle1_tpu as paddle

# tier-1 wall-time: the heaviest zoo builds/convergence cases are
# slow-marked (they ride the CI heavy-model step); the in-tier set keeps
# one forward per family (resnet18) + bert so the zoo stays covered.


class TestVisionModels(unittest.TestCase):
    def _fwd(self, model, size=32):
        model.eval()
        x = paddle.to_tensor(
            np.random.randn(2, 3, size, size).astype(np.float32))
        return model(x)

    def test_resnet18_forward(self):
        from paddle1_tpu.vision.models import resnet18
        y = self._fwd(resnet18(num_classes=10), 64)
        self.assertEqual(y.shape, [2, 10])

    @pytest.mark.slow  # ~12s build; resnet18_forward covers the family
    def test_resnet50_forward(self):
        from paddle1_tpu.vision.models import resnet50
        y = self._fwd(resnet50(num_classes=10), 64)
        self.assertEqual(y.shape, [2, 10])

    @pytest.mark.slow  # ~60s (two full builds + forwards); CI heavy step
    def test_mobilenets(self):
        from paddle1_tpu.vision.models import mobilenet_v1, mobilenet_v2
        self.assertEqual(self._fwd(mobilenet_v1(num_classes=7), 64).shape,
                         [2, 7])
        self.assertEqual(self._fwd(mobilenet_v2(num_classes=7), 64).shape,
                         [2, 7])

    @pytest.mark.slow  # ~28s; eager train-step mechanics are covered by
    # test_training_e2e's in-tier cases and the engine suites
    def test_resnet_train_step(self):
        from paddle1_tpu.vision.models import resnet18
        m = resnet18(num_classes=4)
        m.train()
        opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                        parameters=m.parameters())
        x = paddle.to_tensor(
            np.random.randn(2, 3, 32, 32).astype(np.float32))
        label = paddle.to_tensor(np.array([1, 3], np.int64))
        out = m(x)
        loss = paddle.nn.functional.cross_entropy(out, label)
        loss.backward()
        g = m.conv1.weight.grad
        self.assertIsNotNone(g)
        self.assertGreater(float(np.abs(g.numpy()).sum()), 0.0)
        opt.step()


class TestYolo(unittest.TestCase):
    @pytest.mark.slow  # ~68s, the single heaviest in-tier test; the
    # yolo_loss op parity cases in test_api_parity stay in-tier
    def test_forward_postprocess_loss_grad(self):
        from paddle1_tpu.vision.models import YOLOv3, yolov3_loss
        m = YOLOv3(num_classes=4)
        m.eval()
        x = paddle.to_tensor(
            np.random.randn(1, 3, 64, 64).astype(np.float32))
        outs = m(x)
        self.assertEqual([list(o.shape) for o in outs],
                         [[1, 27, 2, 2], [1, 27, 4, 4], [1, 27, 8, 8]])
        res = m.postprocess(outs, paddle.to_tensor(
            np.array([[64, 64]], np.int32)), conf_thresh=0.05)
        self.assertEqual(res[0].shape[1], 6)
        m.train()
        gtb = np.array([[[0.5, 0.5, 0.4, 0.4], [0, 0, 0, 0]]], np.float32)
        gtl = np.array([[1, -1]], np.int64)
        loss = yolov3_loss(m(x), gtb, gtl, num_classes=4)
        self.assertTrue(np.isfinite(float(loss)))
        loss.backward()
        g = m.backbone.stem.conv.weight.grad
        self.assertIsNotNone(g)
        self.assertGreater(float(np.abs(g.numpy()).sum()), 0.0)


class TestBert(unittest.TestCase):
    def _tiny(self):
        from paddle1_tpu.text.models import (BertForPretraining, BertModel,
                                             BertPretrainingCriterion)
        model = BertForPretraining(BertModel(
            vocab_size=99, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=16))
        return model, BertPretrainingCriterion(99)

    def test_pretrain_forward_backward(self):
        model, crit = self._tiny()
        ids = paddle.to_tensor(
            np.random.randint(1, 99, (2, 8)).astype(np.int32))
        mlm = paddle.to_tensor(
            np.random.randint(0, 99, (2, 8)).astype(np.int32))
        nsp = paddle.to_tensor(np.random.randint(0, 2, (2,)).astype(np.int32))
        scores, rel = model(ids)
        self.assertEqual(scores.shape, [2, 8, 99])
        self.assertEqual(rel.shape, [2, 2])
        loss = crit(scores, rel, mlm, nsp)
        loss.backward()
        g = model.bert.embeddings.word_embeddings.weight.grad
        self.assertIsNotNone(g)

    def test_tied_decoder_gets_both_grads(self):
        """MLM decoder is tied to the word embedding: its grad must include
        both the lookup path and the output-projection path."""
        model, crit = self._tiny()
        w = model.bert.embeddings.word_embeddings.weight
        self.assertIs(model.cls.decoder_weight, w)

    def test_sequence_classification(self):
        from paddle1_tpu.text.models import (BertForSequenceClassification,
                                             BertModel)
        m = BertForSequenceClassification(BertModel(
            vocab_size=50, hidden_size=16, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=32,
            max_position_embeddings=16), num_classes=3)
        m.eval()
        out = m(paddle.to_tensor(
            np.random.randint(1, 50, (2, 8)).astype(np.int32)))
        self.assertEqual(out.shape, [2, 3])

    def test_megatron_sharding_tags(self):
        from paddle1_tpu.text.models import apply_megatron_sharding
        model, _ = self._tiny()
        apply_megatron_sharding(model)
        params = dict(model.named_parameters())
        self.assertEqual(
            params["bert.encoder.layers.0.self_attn.q_proj.weight"]
            .sharding_axes, (None, "mp"))
        self.assertEqual(
            params["bert.encoder.layers.0.self_attn.out_proj.weight"]
            .sharding_axes, ("mp", None))
        self.assertEqual(
            params["bert.embeddings.word_embeddings.weight"].sharding_axes,
            ("mp", None))
