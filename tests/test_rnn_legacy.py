"""Fluid RNN-era ops (VERDICT r4 missing #2): dynamic_lstm(p) /
dynamic_gru / gru_unit / lstm vs numpy references with the kernel's
gate orders (lstm: old-api [c,i,f,o], gru: [u,r,c])."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.fluid as fluid
import paddle1_tpu.fluid.layers as L
from paddle1_tpu.core.tensor import to_tensor

B, T, H, D = 3, 6, 5, 4


def _sig(v):
    return 1.0 / (1.0 + np.exp(-v))


def np_dynamic_lstm(x, w, b, lens, use_peep, reverse=False):
    Hh = x.shape[-1] // 4
    gb = b[0, :4 * Hh]
    if use_peep:
        cki, ckf, cko = (b[0, 4 * Hh:5 * Hh], b[0, 5 * Hh:6 * Hh],
                         b[0, 6 * Hh:7 * Hh])
    else:
        cki = ckf = cko = np.zeros(Hh, np.float32)
    hs = np.zeros(x.shape[:2] + (Hh,), np.float32)
    cs = np.zeros_like(hs)
    for bi in range(x.shape[0]):
        h = np.zeros(Hh, np.float32)
        c = np.zeros(Hh, np.float32)
        order = range(lens[bi])
        if reverse:
            order = reversed(list(order))
        for t in order:
            g = x[bi, t] + h @ w + gb
            gc, gi, gf, go = np.split(g, 4)
            i = _sig(gi + c * cki)
            f = _sig(gf + c * ckf)
            cn = f * c + i * np.tanh(gc)
            o = _sig(go + cn * cko)
            hn = o * np.tanh(cn)
            hs[bi, t], cs[bi, t] = hn, cn
            h, c = hn, cn
    return hs, cs


def np_dynamic_gru(x, w, b, lens, origin_mode, reverse=False):
    Dd = x.shape[-1] // 3
    hs = np.zeros(x.shape[:2] + (Dd,), np.float32)
    w_ur, w_c = w[:, :2 * Dd], w[:, 2 * Dd:]
    for bi in range(x.shape[0]):
        h = np.zeros(Dd, np.float32)
        order = range(lens[bi])
        if reverse:
            order = reversed(list(order))
        for t in order:
            g = x[bi, t] + b[0]
            ur = g[:2 * Dd] + h @ w_ur
            u, r = _sig(ur[:Dd]), _sig(ur[Dd:])
            c = np.tanh(g[2 * Dd:] + (r * h) @ w_c)
            h = u * h + (1 - u) * c if origin_mode \
                else (1 - u) * h + u * c
            hs[bi, t] = h
    return hs


def _set_params(rng, scale=0.4):
    """Fetch the just-created implicit (weight, bias) pair — the last
    two implicit parameters — and overwrite with known values."""
    ps = fluid.layers.implicit_parameters()[-2:]
    vals = []
    for p in ps:
        v = (rng.standard_normal(p.shape) * scale).astype(np.float32)
        p.set_value(v)
        vals.append(v)
    return vals


class TestDynamicLSTM:
    @pytest.mark.parametrize("peep", [False, True])
    @pytest.mark.parametrize("reverse", [False, True])
    def test_matches_numpy(self, peep, reverse):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((B, T, 4 * H)).astype(np.float32)
        lens = np.array([6, 3, 5], np.int64)
        nm = f"dl_{peep}_{reverse}"
        L.dynamic_lstm(to_tensor(x), 4 * H, lengths=lens, name=nm,
                       use_peepholes=peep, is_reverse=reverse)
        w, b = _set_params(rng)
        hid, cell = L.dynamic_lstm(to_tensor(x), 4 * H, lengths=lens,
                                   name=nm, use_peepholes=peep,
                                   is_reverse=reverse)
        ref_h, ref_c = np_dynamic_lstm(x, w, b, lens, peep,
                                       reverse=reverse)
        np.testing.assert_allclose(np.asarray(hid.numpy()), ref_h,
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(cell.numpy()), ref_c,
                                   rtol=2e-4, atol=2e-5)
        # padded positions are exactly zero
        assert np.abs(np.asarray(hid.numpy())[1, 3:]).max() == 0

    def test_bad_shape_teaches(self):
        with pytest.raises(Exception, match="4\\*hidden"):
            L.dynamic_lstm(to_tensor(np.zeros((B, 4 * H),
                                              np.float32)), 4 * H)

    def test_gradients_flow(self):
        rng = np.random.default_rng(1)
        x = to_tensor(rng.standard_normal((B, T, 4 * H)).astype(
            np.float32))
        x.stop_gradient = False
        hid, cell = L.dynamic_lstm(x, 4 * H, name="dl_grad",
                                   use_peepholes=True)
        (hid.sum() + cell.sum()).backward()
        assert np.abs(np.asarray(x.grad.numpy())).sum() > 0


class TestDynamicLSTMP:
    def test_projection_shapes_and_numpy(self):
        rng = np.random.default_rng(2)
        P = 3
        x = rng.standard_normal((B, T, 4 * H)).astype(np.float32)
        lens = np.array([6, 4, 2], np.int64)
        L.dynamic_lstmp(to_tensor(x), 4 * H, P, lengths=lens,
                        name="dlp", use_peepholes=False)
        ps = fluid.layers.implicit_parameters()[-3:]
        w = (rng.standard_normal((P, 4 * H)) * 0.4).astype(np.float32)
        b = (rng.standard_normal((1, 4 * H)) * 0.4).astype(np.float32)
        pw = (rng.standard_normal((H, P)) * 0.4).astype(np.float32)
        # creation order: weight, bias, proj_weight
        ps[0].set_value(w)
        ps[1].set_value(b)
        ps[2].set_value(pw)
        proj, cell = L.dynamic_lstmp(to_tensor(x), 4 * H, P,
                                     lengths=lens, name="dlp",
                                     use_peepholes=False)
        assert tuple(proj.shape) == (B, T, P)
        assert tuple(cell.shape) == (B, T, H)
        # numpy twin with projection recurrence
        ref_p = np.zeros((B, T, P), np.float32)
        ref_c = np.zeros((B, T, H), np.float32)
        for bi in range(B):
            r = np.zeros(P, np.float32)
            c = np.zeros(H, np.float32)
            for t in range(lens[bi]):
                g = x[bi, t] + r @ w + b[0]
                gc, gi, gf, go = np.split(g, 4)
                i, f = _sig(gi), _sig(gf)
                cn = f * c + i * np.tanh(gc)
                o = _sig(go)
                hn = o * np.tanh(cn)
                rn = np.tanh(hn @ pw)
                ref_p[bi, t], ref_c[bi, t] = rn, cn
                r, c = rn, cn
        np.testing.assert_allclose(np.asarray(proj.numpy()), ref_p,
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(cell.numpy()), ref_c,
                                   rtol=2e-4, atol=2e-5)


class TestDynamicGRU:
    @pytest.mark.parametrize("origin", [False, True])
    def test_matches_numpy(self, origin):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((B, T, 3 * D)).astype(np.float32)
        lens = np.array([6, 2, 4], np.int64)
        nm = f"dg_{origin}"
        L.dynamic_gru(to_tensor(x), D, lengths=lens, name=nm,
                      origin_mode=origin)
        w, b = _set_params(rng)
        hid = L.dynamic_gru(to_tensor(x), D, lengths=lens, name=nm,
                            origin_mode=origin)
        ref = np_dynamic_gru(x, w, b, lens, origin)
        np.testing.assert_allclose(np.asarray(hid.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)
        assert np.abs(np.asarray(hid.numpy())[1, 2:]).max() == 0

    def test_reverse(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((B, T, 3 * D)).astype(np.float32)
        lens = np.array([5, 6, 3], np.int64)
        L.dynamic_gru(to_tensor(x), D, lengths=lens, name="dgr",
                      is_reverse=True)
        w, b = _set_params(rng)
        hid = L.dynamic_gru(to_tensor(x), D, lengths=lens, name="dgr",
                            is_reverse=True)
        ref = np_dynamic_gru(x, w, b, lens, False, reverse=True)
        np.testing.assert_allclose(np.asarray(hid.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)


class TestBiasAttr:
    def test_dynamic_lstm_rejects_bias_false(self):
        # reference rnn.py:2383 asserts the same
        with pytest.raises(Exception, match="bias_attr"):
            L.dynamic_lstm(to_tensor(np.zeros((B, T, 4 * H),
                                              np.float32)), 4 * H,
                           bias_attr=False)

    def test_dynamic_gru_without_bias(self):
        rng = np.random.default_rng(11)
        x = rng.standard_normal((B, T, 3 * D)).astype(np.float32)
        lens = np.array([6, 4, 5], np.int64)
        L.dynamic_gru(to_tensor(x), D, lengths=lens, name="dg_nb",
                      bias_attr=False)
        ps = fluid.layers.implicit_parameters()[-1:]
        w = (rng.standard_normal((D, 3 * D)) * 0.4).astype(np.float32)
        ps[0].set_value(w)
        hid = L.dynamic_gru(to_tensor(x), D, lengths=lens,
                            name="dg_nb", bias_attr=False)
        ref = np_dynamic_gru(x, w, np.zeros((1, 3 * D), np.float32),
                             lens, False)
        np.testing.assert_allclose(np.asarray(hid.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)

    def test_gru_unit_without_bias(self):
        rng = np.random.default_rng(12)
        xt = rng.standard_normal((B, 3 * D)).astype(np.float32)
        h0 = rng.standard_normal((B, D)).astype(np.float32)
        L.gru_unit(to_tensor(xt), to_tensor(h0), 3 * D, name="gu_nb",
                   bias_attr=False)
        w = (rng.standard_normal((D, 3 * D)) * 0.4).astype(np.float32)
        fluid.layers.implicit_parameters()[-1].set_value(w)
        hn, rh, gate = L.gru_unit(to_tensor(xt), to_tensor(h0), 3 * D,
                                  name="gu_nb", bias_attr=False)
        ur = xt[:, :2 * D] + h0 @ w[:, :2 * D]
        u, r = _sig(ur[:, :D]), _sig(ur[:, D:])
        c = np.tanh(xt[:, 2 * D:] + (r * h0) @ w[:, 2 * D:])
        np.testing.assert_allclose(np.asarray(hn.numpy()),
                                   (1 - u) * h0 + u * c,
                                   rtol=2e-4, atol=2e-5)


class TestGRUUnit:
    def test_single_step_matches_numpy(self):
        rng = np.random.default_rng(5)
        xt = rng.standard_normal((B, 3 * D)).astype(np.float32)
        h0 = rng.standard_normal((B, D)).astype(np.float32)
        L.gru_unit(to_tensor(xt), to_tensor(h0), 3 * D, name="gu")
        w, b = _set_params(rng)
        hn, rh, gate = L.gru_unit(to_tensor(xt), to_tensor(h0), 3 * D,
                                  name="gu")
        g = xt + b[0]
        ur = g[:, :2 * D] + h0 @ w[:, :2 * D]
        u, r = _sig(ur[:, :D]), _sig(ur[:, D:])
        c = np.tanh(g[:, 2 * D:] + (r * h0) @ w[:, 2 * D:])
        ref_h = (1 - u) * h0 + u * c
        np.testing.assert_allclose(np.asarray(hn.numpy()), ref_h,
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(rh.numpy()), r * h0,
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(gate.numpy()),
            np.concatenate([u, r, c], axis=-1), rtol=2e-4, atol=2e-5)


class TestCudnnStyleLSTM:
    def test_shapes_and_determinism(self):
        rng = np.random.default_rng(6)
        x = rng.standard_normal((T, B, D)).astype(np.float32)
        nl = 2
        h0 = np.zeros((nl, B, H), np.float32)
        c0 = np.zeros((nl, B, H), np.float32)
        out, h, c = L.lstm(to_tensor(x), to_tensor(h0), to_tensor(c0),
                           T, H, nl, is_test=True, name="cu1")
        assert tuple(out.shape) == (T, B, H)
        assert tuple(h.shape) == (nl, B, H)
        out2, _, _ = L.lstm(to_tensor(x), to_tensor(h0), to_tensor(c0),
                            T, H, nl, is_test=True, name="cu1")
        np.testing.assert_array_equal(np.asarray(out.numpy()),
                                      np.asarray(out2.numpy()))

    def test_bidirec_doubles_width(self):
        x = np.zeros((T, B, D), np.float32)
        h0 = np.zeros((2, B, H), np.float32)
        c0 = np.zeros((2, B, H), np.float32)
        out, h, c = L.lstm(to_tensor(x), to_tensor(h0), to_tensor(c0),
                           T, H, 1, is_bidirec=True, is_test=True,
                           name="cu2")
        assert tuple(out.shape) == (T, B, 2 * H)
