"""Ring/Ulysses sequence parallelism vs dense attention on the virtual
mesh (capability extension — no reference counterpart, SURVEY §5)."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _sm

    def shard_map(f, mesh, in_specs, out_specs):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
except ImportError:
    from jax.experimental.shard_map import shard_map as _sm_old

    def shard_map(f, mesh, in_specs, out_specs):
        return _sm_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

from paddle1_tpu.distributed.sequence_parallel import (ring_attention,
                                                       ulysses_attention)
from paddle1_tpu.nn.functional.attention import attention_ref


def _data(B=2, N=64, H=4, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, N, H, D)).astype(
        np.float32))
    return mk(), mk(), mk()


class TestSequenceParallel(unittest.TestCase):
    def setUp(self):
        self.mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        self.spec = P(None, "sp")

    def _sp(self, fn, *args):
        return shard_map(fn, self.mesh, tuple(self.spec for _ in args),
                         self.spec)(*args)

    def test_ring_matches_dense(self):
        q, k, v = _data()
        for causal in (False, True):
            out = self._sp(lambda q, k, v, c=causal: ring_attention(
                q, k, v, "sp", causal=c), q, k, v)
            ref = attention_ref(q, k, v, is_causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5)

    def test_ulysses_matches_dense(self):
        q, k, v = _data()
        for causal in (False, True):
            out = self._sp(lambda q, k, v, c=causal: ulysses_attention(
                q, k, v, "sp", causal=c), q, k, v)
            ref = attention_ref(q, k, v, is_causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5)

    def test_ring_gradients(self):
        q, k, v = _data(N=32)

        def loss_sp(q, k, v):
            out = self._sp(lambda q, k, v: ring_attention(
                q, k, v, "sp", causal=True), q, k, v)
            return jnp.sum(out ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_ref(q, k, v, is_causal=True) ** 2)

        gs = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gs, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_ulysses_head_divisibility(self):
        q, k, v = _data(H=3)
        with self.assertRaises(Exception):
            self._sp(lambda q, k, v: ulysses_attention(q, k, v, "sp"),
                     q, k, v)


class TestFlashKernel(unittest.TestCase):
    def test_flash_vs_ref(self):
        from paddle1_tpu.ops.pallas import flash_attention as fa
        rng = np.random.default_rng(1)
        shape = (2, 256, 2, 64)
        q, k, v = (jnp.asarray(rng.standard_normal(shape, np.float32))
                   for _ in range(3))
        self.assertTrue(fa.supported(q.shape, k.shape))
        for causal in (False, True):
            out = fa.flash_attention(q, k, v, causal=causal)
            ref = attention_ref(q, k, v, is_causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5)

    def test_flash_grads(self):
        from paddle1_tpu.ops.pallas import flash_attention as fa
        rng = np.random.default_rng(2)
        shape = (1, 128, 2, 32)
        q, k, v = (jnp.asarray(rng.standard_normal(shape, np.float32))
                   for _ in range(3))
        gf = jax.grad(lambda q, k, v: jnp.sum(
            fa.flash_attention(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            attention_ref(q, k, v, is_causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_unsupported_shapes_gated(self):
        from paddle1_tpu.ops.pallas import flash_attention as fa
        self.assertFalse(fa.supported((2, 100, 4, 64), (2, 100, 4, 64)))
        self.assertFalse(fa.supported((2, 128, 4, 257), (2, 128, 4, 257)))
