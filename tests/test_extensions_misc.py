"""Optimizer wrappers (EMA / ModelAverage / LookAhead), the to_static
control-flow teaching error, the fs abstraction with checkpoint-to-remote,
and the custom-op extension API. VERDICT r2 missing items 7/8/9/10."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor, to_tensor


def _linear_and_data(seed=0):
    rng = np.random.default_rng(seed)
    lin = paddle.nn.Linear(4, 4)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    y = rng.standard_normal((8, 4)).astype(np.float32)
    return lin, x, y


def _step(lin, opt, x, y):
    loss = ((lin(to_tensor(x)) - to_tensor(y)) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


class TestEMA:
    def test_ema_tracks_and_applies(self):
        from paddle1_tpu.incubate import ExponentialMovingAverage
        lin, x, y = _linear_and_data()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        ema = ExponentialMovingAverage(lin.parameters(), decay=0.5)
        for _ in range(5):
            _step(lin, opt, x, y)
            ema.update()
        train_w = np.asarray(lin.weight.data).copy()
        with ema.apply():
            ema_w = np.asarray(lin.weight.data).copy()
            assert not np.allclose(ema_w, train_w)
        np.testing.assert_array_equal(np.asarray(lin.weight.data), train_w)

    def test_ema_bias_correction_first_step(self):
        from paddle1_tpu.incubate import ExponentialMovingAverage
        lin, _, _ = _linear_and_data()
        ema = ExponentialMovingAverage(lin.parameters(), decay=0.9)
        ema.update()
        w = np.asarray(lin.weight.data)
        with ema.apply():
            # after 1 update, corrected EMA == current params exactly
            np.testing.assert_allclose(np.asarray(lin.weight.data), w,
                                       rtol=1e-6)

    def test_apply_before_update_raises(self):
        """Review finding: apply() with zeroed EMA buffers must not
        silently wipe the parameters."""
        from paddle1_tpu.incubate import ExponentialMovingAverage
        from paddle1_tpu.core.errors import InvalidArgumentError
        lin, _, _ = _linear_and_data()
        ema = ExponentialMovingAverage(lin.parameters())
        with pytest.raises(InvalidArgumentError):
            ema.apply()

    def test_lookahead_state_roundtrip(self):
        """Review finding: set_state_dict must restore inner + slow
        weights, not delegate a wrong-shaped dict to the inner opt."""
        from paddle1_tpu.incubate import LookAhead
        lin, x, y = _linear_and_data(4)
        opt = LookAhead(paddle.optimizer.Adam(
            learning_rate=0.01, parameters=lin.parameters()), k=3)
        for _ in range(4):
            _step(lin, opt, x, y)
        state = opt.state_dict()
        params_snap = {k: np.asarray(v.data).copy()
                       for k, v in lin.state_dict().items()}

        # continue 3 steps from the snapshot
        l1 = [_step(lin, opt, x, y) for _ in range(3)]

        # rewind the SAME model+optimizer via the state dict and replay
        # (param names must match — the reference's state_dict contract)
        for k, v in lin.state_dict().items():
            v._data = jnp.asarray(params_snap[k])
        opt.set_state_dict(state)
        assert opt._step_count == 4
        l2 = [_step(lin, opt, x, y) for _ in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-5)

    def test_double_apply_raises(self):
        from paddle1_tpu.incubate import ExponentialMovingAverage
        from paddle1_tpu.core.errors import InvalidArgumentError
        lin, _, _ = _linear_and_data()
        ema = ExponentialMovingAverage(lin.parameters())
        ema.update()
        ema.apply(need_restore=False)
        with pytest.raises(InvalidArgumentError):
            ema.apply()
        ema.restore()


class TestModelAverage:
    def test_average_applies_and_restores(self):
        from paddle1_tpu.incubate import ModelAverage
        lin, x, y = _linear_and_data(1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        ma = ModelAverage(0.5, parameters=lin.parameters(),
                          min_average_window=2, max_average_window=10)
        snaps = []
        for _ in range(4):
            _step(lin, opt, x, y)
            ma.update()
            snaps.append(np.asarray(lin.weight.data).copy())
        cur = np.asarray(lin.weight.data).copy()
        with ma.apply():
            avg = np.asarray(lin.weight.data)
            np.testing.assert_allclose(avg, np.mean(snaps[-ma._n:], axis=0),
                                       rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(lin.weight.data), cur)


class TestLookAhead:
    def test_slow_weights_interpolate(self):
        from paddle1_tpu.incubate import LookAhead
        lin, x, y = _linear_and_data(2)
        w0 = np.asarray(lin.weight.data).copy()
        inner = paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=lin.parameters())
        opt = LookAhead(inner, alpha=0.5, k=2)
        # one fast step: slow not applied yet
        _step(lin, opt, x, y)
        w1 = np.asarray(lin.weight.data)
        assert not np.allclose(w1, w0)
        # second step hits k: w = slow + 0.5*(fast - slow)
        lin_ref, _, _ = _linear_and_data(2)
        lin_ref.load_dict({k: v for k, v in lin.state_dict().items()})
        _step(lin, opt, x, y)
        w2 = np.asarray(lin.weight.data)
        # slow was w0; fast after 2 steps unknown, but w2 must lie midway
        # between w0 and the pure-fast trajectory — check pullback happened
        assert np.linalg.norm(w2 - w0) < np.linalg.norm(w1 - w0) * 2
        losses = [_step(lin, opt, x, y) for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_validation(self):
        from paddle1_tpu.incubate import LookAhead
        from paddle1_tpu.core.errors import InvalidArgumentError
        lin, _, _ = _linear_and_data()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=lin.parameters())
        with pytest.raises(InvalidArgumentError):
            LookAhead(inner, alpha=2.0)
        with pytest.raises(InvalidArgumentError):
            LookAhead(inner, k=0)
        with pytest.raises(InvalidArgumentError):
            LookAhead(None)


class TestToStaticTeachingError:
    def test_early_return_tensor_if_now_converts(self):
        # r4: this exact pattern used to raise the teaching error; the
        # RETURN transformer now lowers it to lax.cond
        @paddle.jit.to_static
        def f(x):
            if (x > 0).all():        # tensor-dependent python branch
                return x + 1
            return x - 1

        np.testing.assert_allclose(
            np.asarray(f(to_tensor(np.ones(4, np.float32))).numpy()), 2.0)
        np.testing.assert_allclose(
            np.asarray(f(to_tensor(-np.ones(4, np.float32))).numpy()),
            -2.0)

    def test_unconvertible_loop_still_teaches(self):
        from paddle1_tpu.core.errors import InvalidArgumentError

        @paddle.jit.to_static
        def g(x):
            # break keeps the loop unconverted; the traced condition
            # then hits the actionable teaching error
            while (x > 0).all():
                x = x - 1
                if float(x.sum()) < -100:
                    break
            return x

        with pytest.raises((InvalidArgumentError, Exception)) as ei:
            g(to_tensor(np.ones(4, np.float32)))
        msg = str(ei.value)
        assert ("static.nn" in msg or "while_loop" in msg
                or "traced" in msg.lower() or "Tracer" in msg)

    def test_graph_native_cond_still_works(self):
        @paddle.jit.to_static
        def f(x):
            return paddle.static.nn.cond(
                (x.sum() > 0), lambda: x + 1, lambda: x - 1)

        out = f(to_tensor(np.ones(4, np.float32)))
        np.testing.assert_allclose(np.asarray(out.numpy()), 2.0)


class TestFS:
    def test_localfs_surface(self, tmp_path):
        from paddle1_tpu.distributed.fleet.utils import LocalFS
        fs = LocalFS()
        d = tmp_path / "a"
        fs.mkdirs(str(d))
        assert fs.is_dir(str(d)) and not fs.is_file(str(d))
        f = d / "x.txt"
        fs.touch(str(f))
        assert fs.is_file(str(f))
        dirs, files = fs.ls_dir(str(d))
        assert files == ["x.txt"] and dirs == []
        fs.mv(str(f), str(d / "y.txt"))
        assert fs.is_exist(str(d / "y.txt"))
        from paddle1_tpu.distributed.fleet.utils.fs import FSFileExistsError
        fs.touch(str(d / "z.txt"))
        with pytest.raises(FSFileExistsError):
            fs.mv(str(d / "z.txt"), str(d / "y.txt"))
        assert not fs.need_upload_download()
        fs.delete(str(d))
        assert not fs.is_exist(str(d))

    def test_hdfs_requires_cli(self):
        from paddle1_tpu.distributed.fleet.utils import HDFSClient
        from paddle1_tpu.core.errors import PreconditionNotMetError
        with pytest.raises(PreconditionNotMetError):
            HDFSClient(hadoop_home="/nonexistent")

    def test_checkpoint_to_remote_roundtrip(self, tmp_path):
        """Local training checkpoints replicate through the fs layer; a
        cold host restores from the remote copy (reference HDFS flow)."""
        from paddle1_tpu.distributed.fleet.utils import LocalFS
        from paddle1_tpu.incubate import train_epoch_range
        remote = tmp_path / "remote"
        fs = LocalFS()

        def run(local_dir, epochs_to_do):
            lin, x, y = _linear_and_data(3)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=lin.parameters())
            done = []
            for ep in train_epoch_range(
                    4, lin, opt, name="t", checkpoint_dir=str(local_dir),
                    fs=fs, remote_dir=str(remote)):
                _step(lin, opt, x, y)
                done.append(ep)
                if len(done) >= epochs_to_do:
                    break
            return done, lin

        done1, _ = run(tmp_path / "host1", 2)
        assert done1 == [0, 1]
        assert fs.is_exist(str(remote))
        # "new host": fresh local dir. Breaking out of the epoch loop
        # suspends the generator before epoch 1's save, so the durable
        # snapshot is epoch 0 → the cold host resumes at epoch 1.
        done2, _ = run(tmp_path / "host2", 10)
        assert done2 == [1, 2, 3], done2


class TestCustomOps:
    def test_register_and_run_eager_and_jit(self):
        from paddle1_tpu.utils import register_op, get_op

        @register_op("test_swish")
        def swish(x):
            return x * jax.nn.sigmoid(x)

        op = get_op("test_swish")
        x = np.random.default_rng(0).standard_normal(8).astype(np.float32)
        out = op(to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   x / (1 + np.exp(-x)), rtol=1e-5)
        # under jit
        f = jax.jit(lambda a: op(Tensor(a)).data)
        np.testing.assert_allclose(np.asarray(f(jnp.asarray(x))),
                                   x / (1 + np.exp(-x)), rtol=1e-5)

    def test_autograd_through_custom_op(self):
        from paddle1_tpu.utils import register_op
        op = register_op("test_square3", lambda x: 3.0 * x * x)
        t = to_tensor(np.array([2.0], np.float32))
        t.stop_gradient = False
        op(t).sum().backward()
        np.testing.assert_allclose(np.asarray(t.grad.data), [12.0],
                                   rtol=1e-6)

    def test_custom_bwd(self):
        from paddle1_tpu.utils import register_op

        def fwd(x):
            return x * 2.0, x.shape

        def bwd(res, g):
            return (jnp.full(res, 100.0),)  # deliberately wrong grad

        op = register_op("test_custom_bwd", fwd, bwd)
        t = to_tensor(np.ones(3, np.float32))
        t.stop_gradient = False
        op(t).sum().backward()
        np.testing.assert_allclose(np.asarray(t.grad.data), 100.0)

    def test_duplicate_registration_rejected(self):
        from paddle1_tpu.utils import register_op
        from paddle1_tpu.core.errors import InvalidArgumentError
        register_op("test_dup", lambda x: x)
        with pytest.raises(InvalidArgumentError):
            register_op("test_dup", lambda x: x)

    def test_cpp_extension_teaches(self):
        from paddle1_tpu.utils import cpp_extension
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError) as ei:
            cpp_extension.load(name="x", sources=["x.cc"])
        assert "Pallas" in str(ei.value)

    def test_load_c_op_library(self, tmp_path):
        """Host C kernel through jax.pure_callback (works under jit)."""
        src = tmp_path / "op.c"
        src.write_text(textwrap.dedent("""
            #include <stdint.h>
            void scale7(const float* in, float* out, int64_t n) {
              for (int64_t i = 0; i < n; ++i) out[i] = 7.0f * in[i];
            }
        """))
        so = tmp_path / "libop.so"
        r = subprocess.run(["gcc", "-O2", "-shared", "-fPIC", str(src),
                            "-o", str(so)], capture_output=True)
        if r.returncode != 0:
            pytest.skip("no C toolchain")
        from paddle1_tpu.utils import load_op_library
        op = load_op_library(str(so), "test_scale7", "scale7")
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        out = op(to_tensor(x))
        np.testing.assert_allclose(np.asarray(out.numpy()), 7 * x)
        f = jax.jit(lambda a: op(Tensor(a)).data)
        np.testing.assert_allclose(np.asarray(f(jnp.asarray(x))), 7 * x)
