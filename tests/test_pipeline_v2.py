"""Pipeline parallelism v2 (VERDICT r2 task 5).

* In-graph path: a real BERT (embeddings + blocks + tied MLM head) trains
  through ParallelEngine at pp=4 on the virtual mesh and matches pp=1
  numerically, reached via the fleet DistributedStrategy compiler.
* Eager path: the 1F1B scheduler runs heterogeneous PipelineLayer stages
  (embedding / blocks / head — different param shapes per stage) with the
  per-stage in-flight bound of the reference's SectionWorker, and matches
  plain sequential grad accumulation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor, to_tensor
from paddle1_tpu.distributed import ParallelEngine, build_mesh
from paddle1_tpu.text.models import (BertForPretraining, BertModel,
                                     BertPretrainingCriterion)


def _tiny_bert():
    m = BertForPretraining(BertModel(
        vocab_size=128, hidden_size=32, num_hidden_layers=4,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=16, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0))
    return m, BertPretrainingCriterion(128)


def _batch(rng, b=8, s=16, v=128):
    return {"ids": rng.integers(1, v, (b, s)).astype(np.int32),
            "mlm": rng.integers(0, v, (b, s)).astype(np.int32),
            "nsp": rng.integers(0, 2, (b,)).astype(np.int32)}


class TestInGraphPipelineEngine:
    def _run(self, sd0, batch, pp, steps=3, via_fleet=False,
             n_micro=4):
        m, crit = _tiny_bert()
        for k, t in m.state_dict().items():
            t._data = jnp.asarray(sd0[k])
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())

        def loss_fn(mm, bb):
            s, r = mm(Tensor(bb["ids"]))
            return crit(s, r, Tensor(bb["mlm"]), Tensor(bb["nsp"]))

        if via_fleet:
            from paddle1_tpu.distributed.fleet.meta_optimizers import \
                compile_strategy
            from paddle1_tpu.distributed.fleet.strategy import \
                DistributedStrategy
            strat = DistributedStrategy()
            strat.hybrid_configs = {"pp_degree": pp, "dp_degree": 1,
                                    "mp_degree": 1}
            strat.pipeline = True
            strat.pipeline_configs = {"accumulate_steps": n_micro,
                                      "micro_batch_size": 2}
            kwargs = compile_strategy(strat, n_devices=pp)
            assert kwargs["degrees"]["pp"] == pp
            assert kwargs["pp_microbatches"] == n_micro
            mesh = build_mesh(**kwargs["degrees"],
                              devices=jax.devices()[:pp])
            engine = ParallelEngine(
                m, opt, loss_fn, mesh=mesh,
                zero_stage=kwargs["zero_stage"],
                grad_accum=kwargs["grad_accum"],
                amp_dtype=kwargs["amp_dtype"],
                pp_microbatches=kwargs["pp_microbatches"])
        else:
            mesh = build_mesh(pp=pp, dp=1, devices=jax.devices()[:pp])
            engine = ParallelEngine(
                m, opt, loss_fn, mesh=mesh,
                pp_microbatches=n_micro if pp > 1 else None)
        return [float(engine.step(batch)) for _ in range(steps)]

    def test_pp4_matches_pp1_via_fleet_strategy(self):
        m0, _ = _tiny_bert()
        sd0 = {k: np.asarray(t.data) for k, t in m0.state_dict().items()}
        batch = _batch(np.random.default_rng(0))
        l1 = self._run(sd0, batch, pp=1)
        l4 = self._run(sd0, batch, pp=4, via_fleet=True)
        np.testing.assert_allclose(l1, l4, rtol=2e-4)

    def test_pp2_with_dp2_composes(self):
        """pp manual axis + dp auto axis in one step function."""
        m0, _ = _tiny_bert()
        sd0 = {k: np.asarray(t.data) for k, t in m0.state_dict().items()}
        batch = _batch(np.random.default_rng(1))
        l1 = self._run(sd0, batch, pp=1)
        m, crit = _tiny_bert()
        for k, t in m.state_dict().items():
            t._data = jnp.asarray(sd0[k])
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())

        def loss_fn(mm, bb):
            s, r = mm(Tensor(bb["ids"]))
            return crit(s, r, Tensor(bb["mlm"]), Tensor(bb["nsp"]))

        mesh = build_mesh(pp=2, dp=2, devices=jax.devices()[:4])
        engine = ParallelEngine(m, opt, loss_fn, mesh=mesh,
                                pp_microbatches=2)
        l = [float(engine.step(batch)) for _ in range(3)]
        np.testing.assert_allclose(l1, l, rtol=2e-4)

    def test_pp_without_pipelined_body_raises(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        lin = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        mesh = build_mesh(pp=2, dp=1, devices=jax.devices()[:2])
        with pytest.raises(InvalidArgumentError):
            ParallelEngine(lin, opt, lambda m, b: (m(Tensor(b)) ** 2).sum(),
                           mesh=mesh)


class TestEager1F1B:
    """Heterogeneous stages through the eager SectionWorker-analog."""

    def _model_descs(self, vocab=64, hidden=16, n_blocks=4, classes=4):
        from paddle1_tpu.nn.layer_common import Embedding, Linear

        def mean_pool(x):
            from paddle1_tpu.ops import math_ops
            return math_ops.mean(x, axis=1)

        descs = [Embedding(vocab, hidden)]          # stage with [V,H] param
        for _ in range(n_blocks):
            descs.append(Linear(hidden, hidden))    # mid blocks
        descs.append(mean_pool)                     # fn layer
        descs.append(Linear(hidden, classes))       # head, [H,C]
        return descs

    def _loss_fn(self):
        def f(out, y):
            return paddle.nn.functional.cross_entropy(out, to_tensor(y))
        return f

    def _make(self, num_stages, seed=0):
        from paddle1_tpu.distributed.meta_parallel.pp_layers import \
            PipelineLayer
        np.random.seed(seed)
        descs = self._model_descs()
        model = PipelineLayer(descs, num_stages=num_stages,
                              loss_fn=self._loss_fn(),
                              seg_method="uniform")
        return model

    def _sync_weights(self, src, dst):
        s1, s2 = src.state_dict(), dst.state_dict()
        for k in s1:
            s2[k]._data = s1[k].data

    def test_1f1b_matches_sequential_accumulation(self):
        from paddle1_tpu.distributed.meta_parallel.pipeline_parallel import \
            PipelineParallel
        from paddle1_tpu.distributed import fleet
        from paddle1_tpu.distributed.fleet.strategy import \
            DistributedStrategy

        rng = np.random.default_rng(0)
        x = rng.integers(0, 64, (8, 6)).astype(np.int64)
        y = rng.integers(0, 4, (8,)).astype(np.int64)

        pp_model = self._make(num_stages=4)
        seq_model = self._make(num_stages=4)
        self._sync_weights(pp_model, seq_model)

        # reference: plain sequential micro-batch grad accumulation
        opt_r = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=seq_model.parameters())
        tl = None
        for i in range(4):
            out = seq_model(to_tensor(x[i * 2:(i + 1) * 2]))
            l = self._loss_fn()(out, y[i * 2:(i + 1) * 2])
            (l / 4.0).backward()
            tl = l if tl is None else tl + l
        opt_r.step()
        opt_r.clear_grad()

        # 1F1B scheduled
        strat = DistributedStrategy()
        strat.pipeline_configs = {"accumulate_steps": 4,
                                  "micro_batch_size": 2}

        class _HCG:
            def get_data_parallel_group(self):
                from paddle1_tpu.distributed.collective import Group
                return Group(0, 1)

        runner = PipelineParallel(pp_model, _HCG(), strategy=strat)
        opt_p = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=pp_model.parameters())
        loss = runner.train_batch([to_tensor(x), y], opt_p)

        np.testing.assert_allclose(float(loss.numpy()),
                                   float((tl / 4.0).numpy()), rtol=1e-5)
        for k, t in pp_model.state_dict().items():
            np.testing.assert_allclose(
                np.asarray(t.data),
                np.asarray(seq_model.state_dict()[k].data),
                rtol=1e-5, atol=1e-6,
                err_msg=f"param {k} diverged between 1F1B and sequential")

    def test_in_flight_bound(self):
        from paddle1_tpu.distributed.meta_parallel.pipeline_parallel import \
            PipelineParallel
        from paddle1_tpu.distributed.fleet.strategy import \
            DistributedStrategy

        rng = np.random.default_rng(1)
        x = rng.integers(0, 64, (16, 6)).astype(np.int64)
        y = rng.integers(0, 4, (16,)).astype(np.int64)
        model = self._make(num_stages=4, seed=1)
        strat = DistributedStrategy()
        strat.pipeline_configs = {"accumulate_steps": 8,
                                  "micro_batch_size": 2}

        class _HCG:
            def get_data_parallel_group(self):
                from paddle1_tpu.distributed.collective import Group
                return Group(0, 1)

        runner = PipelineParallel(model, _HCG(), strategy=strat)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        runner.train_batch([to_tensor(x), y], opt)
        S = 4
        for s in range(S):
            # SectionWorker bound: stage s holds at most S - s microbatches
            assert runner.last_max_in_flight[s] <= S - s, (
                s, runner.last_max_in_flight)
        # the schedule genuinely pipelined (stage 0 reached its bound)
        assert runner.last_max_in_flight[0] == S

    def test_int_boundary_no_deadlock(self):
        """Review finding: a non-differentiable (int) stage boundary must
        not starve the upstream grad queue."""
        from paddle1_tpu.distributed.meta_parallel.pp_layers import \
            PipelineLayer
        from paddle1_tpu.distributed.meta_parallel.pipeline_parallel import \
            PipelineParallel
        from paddle1_tpu.distributed.fleet.strategy import \
            DistributedStrategy
        from paddle1_tpu.nn.layer_common import Embedding, Linear

        def mean_pool(x):
            from paddle1_tpu.ops import math_ops
            return math_ops.mean(x, axis=1)

        # stage 0 = identity over INT ids; embedding only in stage 1
        model = PipelineLayer(
            [lambda x: x, Embedding(32, 8), mean_pool, Linear(8, 4)],
            num_stages=2, loss_fn=self._loss_fn(), seg_method="uniform")
        strat = DistributedStrategy()
        strat.pipeline_configs = {"accumulate_steps": 2,
                                  "micro_batch_size": 2}

        class _HCG:
            def get_data_parallel_group(self):
                from paddle1_tpu.distributed.collective import Group
                return Group(0, 1)

        runner = PipelineParallel(model, _HCG(), strategy=strat)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        rng = np.random.default_rng(3)
        x = rng.integers(0, 32, (4, 5)).astype(np.int64)
        y = rng.integers(0, 4, (4,)).astype(np.int64)
        loss = runner.train_batch([to_tensor(x), y], opt)  # must not hang
        assert np.isfinite(float(loss.numpy()))
        # embedding DID train (grad flowed within stage 1)
        emb = model.run_function[1]
        assert any(np.abs(np.asarray(p.data)).sum() > 0
                   for p in emb.parameters())

    def test_broadcast_mask_pipelined_encoder(self):
        """Review finding: a broadcastable ([1,1,S,S]) mask must work on
        the pipelined encoder path, as it does sequentially."""
        from paddle1_tpu.nn.layer_transformer import (TransformerEncoder,
                                                      TransformerEncoderLayer)
        from paddle1_tpu.distributed.topology import build_mesh as bm
        enc_layer = TransformerEncoderLayer(16, 2, 32, dropout=0.0)
        enc = TransformerEncoder(enc_layer, 4)
        rng = np.random.default_rng(4)
        x = rng.standard_normal((4, 8, 16)).astype(np.float32)
        causal = np.tril(np.ones((8, 8), bool))[None, None]

        seq = enc(to_tensor(x), to_tensor(causal))

        enc.pipeline_axis = "pp"
        enc.pipeline_mesh = bm(pp=4, dp=1, devices=jax.devices()[:4])
        enc.pipeline_microbatches = 2

        def fwd(xa):
            return enc(Tensor(xa), to_tensor(causal)).data

        piped = jax.jit(fwd)(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(seq.data), np.asarray(piped),
                                   rtol=2e-4, atol=2e-5)
        enc.pipeline_axis = None

    def test_tuple_activation_boundary(self):
        """Review finding: tuple activations crossing a stage boundary."""
        from paddle1_tpu.distributed.meta_parallel.pp_layers import \
            PipelineLayer
        from paddle1_tpu.distributed.meta_parallel.pipeline_parallel import \
            PipelineParallel
        from paddle1_tpu.distributed.fleet.strategy import \
            DistributedStrategy
        from paddle1_tpu.nn.layer_common import Embedding, Linear

        def split2(x):
            return x, x * 2.0

        def join2(a, b):
            from paddle1_tpu.ops import math_ops
            return math_ops.mean(a + b, axis=1)

        model = PipelineLayer(
            [Embedding(32, 8), split2, join2, Linear(8, 4)],
            num_stages=2, loss_fn=self._loss_fn(), seg_method="uniform")
        strat = DistributedStrategy()
        strat.pipeline_configs = {"accumulate_steps": 2,
                                  "micro_batch_size": 2}

        class _HCG:
            def get_data_parallel_group(self):
                from paddle1_tpu.distributed.collective import Group
                return Group(0, 1)

        runner = PipelineParallel(model, _HCG(), strategy=strat)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        rng = np.random.default_rng(5)
        x = rng.integers(0, 32, (4, 5)).astype(np.int64)
        y = rng.integers(0, 4, (4,)).astype(np.int64)
        loss = runner.train_batch([to_tensor(x), y], opt)
        assert np.isfinite(float(loss.numpy()))
        # grads crossed the tuple boundary into the embedding
        emb = model.run_function[0]
        assert emb.weight.grad is None  # cleared by clear_grad
        w_before = np.asarray(emb.weight.data).copy()
        runner.train_batch([to_tensor(x), y], opt)
        assert np.abs(np.asarray(emb.weight.data) - w_before).max() > 0

    def test_heterogeneous_partition_shapes(self):
        model = self._make(num_stages=4, seed=2)
        shapes = []
        for s in range(4):
            shapes.append(sorted(tuple(p.shape)
                                 for l in model.stage_layers(s)
                                 for p in l.parameters()))
        # embedding stage differs from block stages and head stage
        assert shapes[0] != shapes[1]
        assert shapes[-1] != shapes[1]
