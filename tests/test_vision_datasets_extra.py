"""Vision datasets added by the r3 parity sweep (DatasetFolder,
ImageFolder, Flowers, VOC2012) against miniature archives in the
official formats (reference vision/datasets/{folder,flowers,voc2012})."""

import io
import os
import tarfile

import numpy as np
import pytest

from paddle1_tpu.vision.datasets import (DatasetFolder, Flowers,
                                         ImageFolder, VOC2012)


def _png_bytes(w=6, h=6, value=128, mode="RGB"):
    from PIL import Image
    arr = np.full((h, w, 3) if mode == "RGB" else (h, w), value, np.uint8)
    img = Image.fromarray(arr, mode=mode)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


def _jpg_bytes(w=6, h=6, value=128):
    from PIL import Image
    arr = np.full((h, w, 3), value, np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    return buf.getvalue()


def _tar_add(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


class TestFolders:
    def test_dataset_folder(self, tmp_path):
        for cls, n in (("ants", 2), ("bees", 3)):
            d = tmp_path / cls
            d.mkdir()
            for i in range(n):
                (d / f"{i}.png").write_bytes(_png_bytes())
        (tmp_path / "notes.txt").write_text("ignored")
        ds = DatasetFolder(str(tmp_path))
        assert ds.classes == ["ants", "bees"]
        assert len(ds) == 5
        img, target = ds[0]
        assert target == 0
        assert np.asarray(img).shape == (6, 6, 3)
        assert ds.samples[-1][1] == 1

    def test_dataset_folder_empty_raises(self, tmp_path):
        (tmp_path / "empty_class").mkdir()
        with pytest.raises(RuntimeError, match="0 files"):
            DatasetFolder(str(tmp_path))

    def test_image_folder_flat(self, tmp_path):
        for i in range(3):
            (tmp_path / f"{i}.png").write_bytes(_png_bytes(value=i * 10))
        ds = ImageFolder(str(tmp_path))
        assert len(ds) == 3
        [img] = ds[1]
        assert np.asarray(img)[0, 0, 0] == 10


class TestFlowers:
    def test_split_and_labels(self, tmp_path):
        import scipy.io as sio
        data_p = tmp_path / "102flowers.tgz"
        with tarfile.open(data_p, "w:gz") as tf:
            for i in range(1, 5):
                _tar_add(tf, f"jpg/image_{i:05d}.jpg",
                         _jpg_bytes(value=i * 20))
        sio.savemat(tmp_path / "imagelabels.mat",
                    {"labels": np.array([[5, 6, 7, 8]])})
        sio.savemat(tmp_path / "setid.mat",
                    {"trnid": np.array([[1, 3]]),
                     "valid": np.array([[2]]),
                     "tstid": np.array([[4]])})
        tr = Flowers(str(data_p), str(tmp_path / "imagelabels.mat"),
                     str(tmp_path / "setid.mat"), mode="train")
        assert len(tr) == 2
        img, label = tr[0]
        assert label[0] == 5  # image 1 → label 5
        assert np.asarray(img).shape == (6, 6, 3)
        te = Flowers(str(data_p), str(tmp_path / "imagelabels.mat"),
                     str(tmp_path / "setid.mat"), mode="test")
        assert len(te) == 1 and te[0][1][0] == 8


class TestVOC2012:
    def test_pairs_from_listing(self, tmp_path):
        p = tmp_path / "voctrainval.tar"
        root = "VOCdevkit/VOC2012"
        with tarfile.open(p, "w") as tf:
            _tar_add(tf, f"{root}/ImageSets/Segmentation/train.txt",
                     b"img_a\n")
            _tar_add(tf, f"{root}/ImageSets/Segmentation/val.txt",
                     b"img_b\n")
            for n, v in (("img_a", 30), ("img_b", 60)):
                _tar_add(tf, f"{root}/JPEGImages/{n}.jpg",
                         _jpg_bytes(value=v))
                _tar_add(tf, f"{root}/SegmentationClass/{n}.png",
                         _png_bytes(value=v // 10, mode="L"))
        tr = VOC2012(str(p), mode="train")
        assert len(tr) == 1
        image, label = tr[0]
        assert image.shape == (6, 6, 3) and label.shape == (6, 6)
        assert int(label[0, 0]) == 3
        va = VOC2012(str(p), mode="val")
        assert len(va) == 1 and int(va[0][1][0, 0]) == 6
        with pytest.raises(ValueError, match="mode"):
            VOC2012(str(p), mode="bogus")

    def test_list_extensions_accepted(self, tmp_path):
        d = tmp_path / "cls"
        d.mkdir()
        (d / "a.png").write_bytes(_png_bytes())
        (d / "b.jpg").write_bytes(_jpg_bytes())
        ds = DatasetFolder(str(tmp_path), extensions=[".png"])
        assert len(ds) == 1  # list filter works, jpg excluded

    def test_string_extension_not_exploded(self, tmp_path):
        d = tmp_path / "cls"
        d.mkdir()
        (d / "a.png").write_bytes(_png_bytes())
        (d / "b.jpg").write_bytes(_jpg_bytes())
        ds = DatasetFolder(str(tmp_path), extensions=".png")
        assert len(ds) == 1  # str must behave as one suffix, not chars
