"""JIT-discipline suite (ISSUE 12): the three static passes
(donation-safety, retrace-hazard, host-sync) with seeded violation
matrices hitting exact lines per rule, the runtime jit sanitizer
(structural zero cost off; typed use-after-donate, retrace-storm and
host-sync accounting on), the PR 1 donation-aliasing regression made
deterministic, and the CLI satellites (--select teaching error,
--budget-s timing gate, same-PR flag liveness)."""

import os
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import lint as tl  # noqa: E402 — path bootstrap first
from tools.lint import UnknownPassError  # noqa: E402
from paddle1_tpu.core import flags as core_flags  # noqa: E402
from paddle1_tpu.core import jit_sanitizer as js  # noqa: E402
from paddle1_tpu.core.jit_sanitizer import (  # noqa: E402
    RetraceStormError, UseAfterDonateError)


def _run(tmp_path, src, select, name="seed.py"):
    p = tmp_path / name
    p.write_text(src)
    return tl.run(paths=[str(p)], select=select).findings


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# -- donation-safety: violation matrix ---------------------------------------

class TestDonationSafetyMatrix:
    def test_use_after_donate_exact_line(self, tmp_path):
        src = (
            "import jax\n"                                    # 1
            "def step(p, b):\n"                               # 2
            "    return p\n"                                  # 3
            "fn = jax.jit(step, donate_argnums=(0,))\n"       # 4
            "def train(params, batch):\n"                     # 5
            "    out = fn(params, batch)\n"                   # 6: donated
            "    print(params)\n"                             # 7: USE
            "    return out\n"                                # 8
        )
        fs = _by_rule(_run(tmp_path, src, ["donation-safety"]),
                      "use-after-donate")
        assert [(f.line) for f in fs] == [7]
        assert "donated position" in fs[0].message

    def test_reassign_from_result_is_clean(self, tmp_path):
        # the engine idiom: the donated name is rebound by the same
        # statement that dispatches
        src = (
            "import jax\n"
            "def step(p, s, b):\n"
            "    return 0.0, p, s\n"
            "fn = jax.jit(step, donate_argnums=(0, 1))\n"
            "def train(self, batch):\n"
            "    loss, self.params, self.opt = fn(\n"
            "        self.params, self.opt, batch)\n"
            "    return loss, self.params\n"  # rebound: fine
        )
        assert not _run(tmp_path, src, ["donation-safety"])

    def test_conditional_donate_argnums_counts(self, tmp_path):
        # the engine's `(0, 1) if donate else ()` shape: the donating
        # configuration is what gets checked
        src = (
            "import jax\n"                                     # 1
            "donate = True\n"                                  # 2
            "def step(p, b):\n"                                # 3
            "    return p\n"                                   # 4
            "fn = jax.jit(step,\n"                             # 5
            "             donate_argnums=(0,) if donate else ())\n"
            "def train(params, batch):\n"                      # 7
            "    out = fn(params, batch)\n"                    # 8
            "    params.keys()\n"                              # 9: USE
        )
        fs = _by_rule(_run(tmp_path, src, ["donation-safety"]),
                      "use-after-donate")
        assert [f.line for f in fs] == [9]

    def test_donated_alias_device_put(self, tmp_path):
        src = (
            "import jax\n"                                     # 1
            "import jax.numpy as jnp\n"                        # 2
            "fn = jax.jit(lambda p: p, donate_argnums=(0,))\n"  # 3
            "def place(v, sh):\n"                              # 4
            "    a = jax.device_put(v, sh)\n"                  # 5: alias
            "    b = jax.device_put(jnp.array(v, copy=True), sh)\n"
            "    return a, b\n"                                # 7
        )
        fs = _by_rule(_run(tmp_path, src, ["donation-safety"]),
                      "donated-alias")
        assert [f.line for f in fs] == [5]
        assert "ALIAS" in fs[0].message

    def test_loop_target_rebind_is_not_a_read(self, tmp_path):
        # `for x in items:` REBINDS x (Store ctx) — disposing of the
        # donated name, not reading it; later reads of the loop var
        # are reads of the fresh binding
        src = (
            "import jax\n"
            "f = jax.jit(lambda x: x, donate_argnums=(0,))\n"
            "def h(x, items):\n"
            "    f(x)\n"
            "    for x in items:\n"
            "        print(x)\n"
        )
        assert not _run(tmp_path, src, ["donation-safety"])

    def test_non_donating_file_device_put_is_clean(self, tmp_path):
        src = (
            "import jax\n"
            "def place(v, sh):\n"
            "    return jax.device_put(v, sh)\n"  # nothing donates here
        )
        assert not _run(tmp_path, src, ["donation-safety"])

    def test_noqa_with_reason_suppresses(self, tmp_path):
        src = (
            "import jax\n"
            "fn = jax.jit(lambda p: p, donate_argnums=(0,))\n"
            "def place(v, sh):\n"
            "    return jax.device_put(v, sh)"
            "  # noqa: donated-alias — v is freshly built here\n"
        )
        assert not _run(tmp_path, src, ["donation-safety"])


# -- retrace-hazard: violation matrix ----------------------------------------

class TestRetraceHazardMatrix:
    def test_module_level_array_capture(self, tmp_path):
        src = (
            "import jax\n"                                     # 1
            "import numpy as np\n"                             # 2
            "TABLE = np.arange(1000)\n"                        # 3
            "@jax.jit\n"                                       # 4
            "def embed(ids):\n"                                # 5
            "    return TABLE[ids]\n"                          # 6: capture
        )
        fs = _by_rule(_run(tmp_path, src, ["retrace-hazard"]),
                      "retrace-closure")
        assert [f.line for f in fs] == [6]
        assert "TABLE" in fs[0].message

    def test_threaded_array_is_clean(self, tmp_path):
        src = (
            "import jax\n"
            "import numpy as np\n"
            "TABLE = np.arange(1000)\n"
            "@jax.jit\n"
            "def embed(table, ids):\n"
            "    return table[ids]\n"      # through the signature: fine
            "out = embed(TABLE, 3)\n"      # call-site use is host-side
        )
        assert not _run(tmp_path, src, ["retrace-hazard"])

    def test_nonhashable_static_args(self, tmp_path):
        src = (
            "import jax\n"                                     # 1
            "import numpy as np\n"                             # 2
            "def f(x, cfg):\n"                                 # 3
            "    return x\n"                                   # 4
            "g = jax.jit(f, static_argnums=(1,))\n"            # 5
            "g(1, [2, 3])\n"                                   # 6: list
            "g(1, {'a': 1})\n"                                 # 7: dict
            "g(1, np.array([1]))\n"                            # 8: array
            "g(1, (2, 3))\n"                                   # tuple: ok
            "h = jax.jit(f, static_argnames=('cfg',))\n"       # 10
            "h(1, cfg={'a'})\n"                                # 11: set
        )
        fs = _by_rule(_run(tmp_path, src, ["retrace-hazard"]),
                      "retrace-static-arg")
        assert sorted(f.line for f in fs) == [6, 7, 8, 11]

    def test_scalar_feedback_loop(self, tmp_path):
        src = (
            "import jax\n"                                     # 1
            "def f(x):\n"                                      # 2
            "    return x * 2\n"                               # 3
            "step = jax.jit(f)\n"                              # 4
            "x = 1.0\n"                                        # 5
            "for _ in range(10):\n"                            # 6
            "    out = step(x)\n"                              # 7
            "    x = float(out)\n"                             # 8
            "    y = step(x)\n"                                # 9: feedback
        )
        fs = _by_rule(_run(tmp_path, src, ["retrace-hazard"]),
                      "retrace-scalar-feedback")
        # BOTH calls feed the scalar on the next iteration: line 7
        # consumes the float assigned at 8 when the loop comes around
        assert [f.line for f in fs] == [7, 9]

    def test_device_carry_is_clean(self, tmp_path):
        src = (
            "import jax\n"
            "def f(x):\n"
            "    return x * 2\n"
            "step = jax.jit(f)\n"
            "x = 1.0\n"
            "for _ in range(10):\n"
            "    x = step(x)\n"       # stays on device: fine
            "print(float(x))\n"       # one readback after the loop
        )
        assert not _run(tmp_path, src, ["retrace-hazard"])


# -- host-sync: violation matrix ---------------------------------------------

class TestHostSyncMatrix:
    def test_traced_body_syncs(self, tmp_path):
        src = (
            "import jax\n"                                     # 1
            "import numpy as np\n"                             # 2
            "@jax.jit\n"                                       # 3
            "def f(x):\n"                                      # 4
            "    a = float(x)\n"                               # 5
            "    b = x.item()\n"                               # 6
            "    c = np.asarray(x)\n"                          # 7
            "    d = int(np.shape(x)[0])\n"                    # 8: static
            "    return a + b + d\n"                           # 9
        )
        fs = _by_rule(_run(tmp_path, src, ["host-sync"]),
                      "hidden-host-sync")
        assert sorted(f.line for f in fs) == [5, 6, 7]

    def test_hot_path_marker_on_def_line(self, tmp_path):
        src = (
            "import numpy as np\n"                             # 1
            "class Loop:\n"                                    # 2
            "    def run(self):  # hot-path: decode\n"         # 3
            "        t = self.buf.item()\n"                    # 4
            "        a = np.asarray(self._tokens)\n"           # 5
            "        f = float(t)\n"                           # 6
            "        n = int(t)\n"                      # int ok on host
        )
        fs = _by_rule(_run(tmp_path, src, ["host-sync"]),
                      "hidden-host-sync")
        assert sorted(f.line for f in fs) == [4, 5, 6]

    def test_hot_path_marker_above_loop(self, tmp_path):
        src = (
            "import numpy as np\n"                             # 1
            "def run(q):\n"                                    # 2
            "    # hot-path\n"                                 # 3
            "    while True:\n"                                # 4
            "        v = q.result.numpy()\n"                   # 5
        )
        fs = _by_rule(_run(tmp_path, src, ["host-sync"]),
                      "hidden-host-sync")
        assert [f.line for f in fs] == [5]

    def test_unmarked_code_is_clean(self, tmp_path):
        src = (
            "import numpy as np\n"
            "def report(loss):\n"
            "    return float(loss), np.asarray(loss)\n"  # cold path
        )
        assert not _run(tmp_path, src, ["host-sync"])

    def test_jnp_asarray_not_flagged(self, tmp_path):
        # host→device transfer, not a readback
        src = (
            "import jax.numpy as jnp\n"
            "def run(self):  # hot-path\n"
            "    return jnp.asarray(self.mask, bool)\n"
        )
        assert not _run(tmp_path, src, ["host-sync"])

    def test_noqa_documents_intended_sync(self, tmp_path):
        src = (
            "import numpy as np\n"
            "def decode(self):  # hot-path\n"
            "    return np.asarray(self._tokens)"
            "  # noqa: hidden-host-sync — the one intended readback\n"
        )
        assert not _run(tmp_path, src, ["host-sync"])


# -- satellite: --select teaching error --------------------------------------

class TestSelectTeachingError:
    def test_unknown_pass_is_typed_and_lists_registry(self):
        with pytest.raises(UnknownPassError) as ei:
            tl.make_passes(["no-such-pass"])
        e = ei.value
        assert e.unknown == ["no-such-pass"]
        teach = e.teach()
        for c in tl.ALL_PASSES:
            assert c.name in teach
        assert "donation-safety" in teach and "--select" in teach

    def test_cli_exit_2_with_teaching_message(self, capsys):
        from tools.lint.__main__ import main
        rc = main(["--select", "no-such-pass"])
        assert rc == 2
        err = capsys.readouterr().err
        assert "unknown pass" in err and "host-sync" in err
        assert "Traceback" not in err

    def test_cli_valid_select_still_runs(self, tmp_path, capsys):
        p = tmp_path / "ok.py"
        p.write_text("x = 1\n")
        from tools.lint.__main__ import main
        assert main(["--select", "donation-safety", str(p)]) == 0

    def test_cli_budget_exceeded_fails(self, tmp_path, capsys):
        p = tmp_path / "ok.py"
        p.write_text("x = 1\n")
        from tools.lint.__main__ import main
        rc = main(["--select", "bare-except", "--budget-s", "1e-9",
                   str(p)])
        assert rc == 1
        assert "budget" in capsys.readouterr().err

    def test_cli_budget_generous_passes(self, tmp_path):
        p = tmp_path / "ok.py"
        p.write_text("x = 1\n")
        from tools.lint.__main__ import main
        assert main(["--select", "bare-except", "--budget-s", "600",
                     str(p)]) == 0


# -- satellite: flag-liveness same-PR hygiene --------------------------------

class TestFlagLivenessSamePR:
    def test_same_pr_define_and_read_needs_no_allowlist(self, tmp_path):
        """A flag defined in one file and read in another of the same
        walk passes with an EMPTY allowlist — wiring a flag in the PR
        that defines it must never require FORWARD_COMPAT."""
        (tmp_path / "flags.py").write_text(
            "def define_flag(n, d, h=''):\n    pass\n"
            "define_flag('debug_seeded_sanitizer', False, 'help')\n")
        (tmp_path / "sanitizer.py").write_text(
            "def sanitizing():\n"
            "    return bool(flag('debug_seeded_sanitizer'))\n")
        fs = tl.run(paths=[str(tmp_path)],
                    select=["flag-liveness"]).findings
        assert not [f for f in fs if f.rule == "dead-flag"]

    def test_debug_jit_sanitizer_not_allowlisted(self):
        from tools.lint import flag_liveness as fl
        assert "debug_jit_sanitizer" not in fl.FORWARD_COMPAT
        # and the repo-wide pass holds it live (core/jit_sanitizer.py
        # reads it) — covered by TestCleanRepo, pinned here explicitly
        res = tl.run(select=["flag-liveness"])
        assert not [f for f in res.findings
                    if "debug_jit_sanitizer" in f.message]


# -- runtime sanitizer --------------------------------------------------------

class TestJitSanitizer:
    def setup_method(self):
        js.reset()

    def test_structurally_free_when_off(self):
        # force OFF explicitly: must also hold inside the CI
        # debug-sanitizers lane where the env flag is exported
        with core_flags.flags_guard(debug_jit_sanitizer=False):
            fn = lambda x: x
            assert js.wrap_donating(fn, (0,), "t") is fn  # PASS-THROUGH
            assert js.site("t") is None
            # shared no-op section object, no allocation per entry
            assert js.hot_section("a") is js.hot_section("b")

    def test_seeded_use_after_donate_typed(self):
        import jax
        import jax.numpy as jnp
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            g = jax.jit(lambda x: x * 2, donate_argnums=(0,))
            w = js.wrap_donating(g, (0,), "seed.step")
            a = jnp.arange(4.0)
            out = w(a)
            assert float(np.asarray(out)[1]) == 2.0
            with pytest.raises(UseAfterDonateError,
                               match="seed.step"):
                w(a)

    def test_poison_makes_any_use_fail(self):
        """Even a use NOT reaching a guarded entry fails
        deterministically (jax's deleted-buffer error) instead of
        silently reading XLA-owned storage."""
        import jax
        import jax.numpy as jnp
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            w = js.wrap_donating(
                jax.jit(lambda x: x + 1, donate_argnums=(0,)),
                (0,), "seed.step")
            a = jnp.arange(4.0)
            w(a)
            with pytest.raises(RuntimeError, match="deleted"):
                np.asarray(a)

    def test_seeded_three_retrace_storm_typed(self):
        import jax
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            s = js.site("seed.engine", retrace_limit=3)
            fn = jax.jit(lambda x: x.sum())
            seen = set()
            with pytest.raises(RetraceStormError, match="retrace storm"):
                for n in range(1, 8):  # 3 retraces past the first is
                    x = np.zeros([n], np.float32)  # the storm
                    seen.add(x.shape)
                    s.note_signatures(len(seen))
                    fn(x)
            assert len(seen) == 4  # raised at the 4th distinct sig

    def test_engine_retrace_storm_enforced(self):
        """ParallelEngine._guard_retrace upgraded: distinct batch
        shapes past the limit raise typed instead of warning once."""
        import paddle1_tpu as paddle
        from paddle1_tpu import nn, optimizer
        from paddle1_tpu.distributed.parallel_engine import ParallelEngine
        with core_flags.flags_guard(debug_jit_sanitizer=True,
                                    jit_retrace_warn=False):
            m = nn.Linear(4, 2)
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=m.parameters())
            eng = ParallelEngine(m, opt,
                                 lambda mod, b: mod(b[0]).mean(),
                                 donate=False)
            assert eng._jsan is not None
            with pytest.raises(RetraceStormError):
                for i in range(js.RETRACE_LIMIT + 2):
                    # distinct batch shape per step (multiples of the
                    # 8-way dp mesh): every one is a fresh signature
                    x = np.random.rand(8 * (i + 1),
                                       4).astype(np.float32)
                    eng.step((paddle.to_tensor(x),))

    def test_engine_use_after_donate_typed(self):
        """Stale donated params fed back into the engine raise typed,
        naming the donation site."""
        import paddle1_tpu as paddle
        from paddle1_tpu import nn, optimizer
        from paddle1_tpu.distributed.parallel_engine import ParallelEngine
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            m = nn.Linear(4, 2)
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=m.parameters())
            eng = ParallelEngine(m, opt,
                                 lambda mod, b: mod(b[0]).mean(),
                                 donate=True)
            x = paddle.to_tensor(
                np.random.rand(8, 4).astype(np.float32))
            eng.step((x,))
            stale = eng.params          # about to be donated
            eng.step((x,))              # stale poisoned here
            eng.params = stale
            with pytest.raises(UseAfterDonateError,
                               match="ParallelEngine"):
                eng.step((x,))

    def test_host_sync_counting_in_hot_section(self):
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            with js.hot_section("seed_loop"):
                js.note_host_sync("loss_readback")
                js.note_host_sync("loss_readback")
            js.note_host_sync("loss_readback")  # outside: section ''
            ev = js.host_sync_events()
            assert ev[("seed_loop", "loss_readback")] == 2
            assert ev[("", "loss_readback")] == 1
            assert js.host_sync_count("seed_loop") == 2
            assert js.host_sync_count() == 3

    def test_loss_readback_attributed_to_step_loop(self):
        """async_loss materialization events attribute to the
        engine_step_loop section held by step_stream's consumer."""
        import paddle1_tpu as paddle
        from paddle1_tpu import nn, optimizer
        from paddle1_tpu.distributed.parallel_engine import ParallelEngine
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            m = nn.Linear(4, 2)
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=m.parameters())
            eng = ParallelEngine(m, opt,
                                 lambda mod, b: mod(b[0]).mean(),
                                 donate=False)
            x = paddle.to_tensor(
                np.random.rand(8, 4).astype(np.float32))
            for fut in eng.step_stream([(x,)] * 3):
                float(fut)  # the per-step readback the loop pays
            assert js.host_sync_count("engine_step_loop") >= 3

    def test_reset_disarms_when_flag_off(self):
        """An armed test must not leave flag-off code counting (or
        paying the counter lock) for the rest of the process: reset()
        re-derives the armed latch from the current flag. The off half
        forces the flag explicitly so this also holds inside the CI
        debug-sanitizers lane (FLAGS_debug_jit_sanitizer=1 in env)."""
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            js.hot_section("arming")  # arms the module
            js.note_host_sync("x")
            assert js.host_sync_count() == 1
        with core_flags.flags_guard(debug_jit_sanitizer=False):
            js.reset()  # flag is off HERE: must disarm
            js.note_host_sync("x")  # must NOT count
            assert js.host_sync_count() == 0

    def test_hot_section_exit_is_name_keyed(self):
        """A generator-held section finalized out of order must not pop
        another section's marker."""
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            outer = js.hot_section("outer")
            inner = js.hot_section("inner")
            outer.__enter__()
            inner.__enter__()
            outer.__exit__(None, None, None)  # out of order
            js.note_host_sync("x")
            assert js.host_sync_count("inner") == 1
            inner.__exit__(None, None, None)


# -- the PR 1 donation-aliasing regression, deterministic --------------------

class TestDonationAliasingRegression:
    def setup_method(self):
        js.reset()

    def test_pr1_shape_fails_deterministically(self):
        """The exact PR 1 bug shape: device_put on the same device
        ELIDES the copy — the placed array IS the layer's buffer — and
        the first donating dispatch hands the layer's storage to XLA.
        On CPU donation no-ops, so pre-sanitizer this read back the
        stale value silently (the corruption that deleted a live
        BertModel embedding on TPU). Under the sanitizer the layer
        read fails deterministically on every backend."""
        import jax
        import jax.numpy as jnp
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            layer_buf = jnp.arange(8.0)          # the live layer buffer
            placed = jax.device_put(layer_buf)    # elided copy: ALIAS
            assert placed is layer_buf            # the PR 1 trap itself
            step = js.wrap_donating(
                jax.jit(lambda p: p * 2, donate_argnums=(0,)),
                (0,), "regress.engine")
            step(placed)                          # donates the alias
            with pytest.raises(RuntimeError, match="deleted"):
                np.asarray(layer_buf)             # the layer read: LOUD
            # re-entering a guarded dispatch names the donation site
            with pytest.raises(UseAfterDonateError,
                               match="regress.engine"):
                step(layer_buf)

    def test_copy_first_fix_is_immune(self):
        """The PR 1 fix (copy before placement) under the same drive:
        the layer buffer survives the donating dispatch."""
        import jax
        import jax.numpy as jnp
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            layer_buf = jnp.arange(8.0)
            placed = jax.device_put(jnp.array(layer_buf, copy=True))
            assert placed is not layer_buf
            step = js.wrap_donating(
                jax.jit(lambda p: p * 2, donate_argnums=(0,)),
                (0,), "regress.engine")
            step(placed)
            np.testing.assert_allclose(np.asarray(layer_buf),
                                       np.arange(8.0))


# -- generation engine under the sanitizer ------------------------------------

class TestGenerationUnderSanitizer:
    def setup_method(self):
        js.reset()

    @pytest.mark.slow
    def test_decode_compile_once_and_kv_poisoning(self):
        from paddle1_tpu.serving import CausalLM
        from paddle1_tpu.serving.generate import GenerationEngine
        with core_flags.flags_guard(debug_jit_sanitizer=True):
            lm = CausalLM(vocab_size=64, d_model=32, nhead=2,
                          num_layers=1, max_seq=32)
            eng = GenerationEngine(lm, slots=2, max_seq=32,
                                   prefill_buckets=[8])
            eng.prefill(0, np.arange(4, dtype=np.int32), 0.0, 0, 1)
            for _ in range(3):
                eng.decode(np.array([True, False]))
            assert eng.decode_compile_count == 1
            # per-token readbacks counted
            assert js.host_sync_count() >= 3
