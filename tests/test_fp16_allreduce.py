"""fp16_allreduce: the dedicated gradient-communication cast.

Reference analog: fleet/meta_optimizers/fp16_allreduce_optimizer.py (cast
grads to fp16 for the allreduce, recast after). Compiled-engine path is
covered by bf16 autocast (the backward graph — hence GSPMD's collectives —
is already bf16); this tests the EAGER DataParallel hook path where the
cast is explicit (distributed/parallel.py DataParallel comm_dtype).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor
from paddle1_tpu.distributed import DataParallel, spmd_axes
from paddle1_tpu.distributed.fleet.strategy import DistributedStrategy


def _dp_grads(comm_dtype, x_local):
    """Grad of a 1-param linear under a 4-way dp shard_map; returns the
    synced parameter gradient."""
    lin = paddle.nn.Linear(2, 1)
    lin.weight._data = jnp.asarray([[0.5], [-0.25]], jnp.float32)
    lin.bias._data = jnp.zeros((1,), jnp.float32)
    model = DataParallel(lin, comm_dtype=comm_dtype)

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))

    def step(xl):
        with spmd_axes(dp="data"):
            out = model(Tensor(xl))
            loss = (out * out).mean()
            loss.backward()
            g = lin.weight.grad.data
            for p in lin.parameters():
                p.clear_grad()
            return g

    return shard_map(step, mesh=mesh, in_specs=P("data"),
                     out_specs=P())(x_local)


class TestFp16Allreduce:
    def test_cast_path_matches_f32_within_bf16_tolerance(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((8, 2)).astype(np.float32)
        g32 = np.asarray(_dp_grads(None, x))
        g16 = np.asarray(_dp_grads("bfloat16", x))
        assert g16.dtype == np.float32  # recast after comm
        np.testing.assert_allclose(g16, g32, rtol=2e-2, atol=2e-2)
        # and the cast actually changed the bits (bf16 rounding happened)
        assert not np.array_equal(g16, g32)

    def test_strategy_wires_comm_dtype(self):
        s = DistributedStrategy()
        s.fp16_allreduce = True
        assert s.fp16_allreduce is True
        # wiring check without a live fleet: the DataParallel kwarg exists
        lin = paddle.nn.Linear(2, 1)
        dp = DataParallel(lin, comm_dtype="bfloat16")
        assert dp._comm_dtype == jnp.bfloat16

    def test_integer_grads_never_cast(self):
        # non-floating leaves must pass through the hook untouched
        lin = paddle.nn.Linear(2, 1)
        dp = DataParallel(lin, comm_dtype="bfloat16")
        hook = dp._make_grad_sync_hook()
        g = Tensor(jnp.asarray([1, 2, 3], jnp.int32))
        out = hook(g)
        assert out.dtype == g.dtype
