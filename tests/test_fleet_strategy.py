"""Strategy compiler + meta-optimizer wrappers (reference
test_fleet_*_meta_optimizer.py pattern: configure strategy, assert on the
compiled result)."""

import unittest

import numpy as np

import paddle1_tpu as paddle
import paddle1_tpu.distributed.fleet as fleet
from paddle1_tpu.distributed.fleet import (DGCMomentumOptimizer,
                                           DistributedStrategy,
                                           LocalSGDOptimizer,
                                           compile_strategy)


class TestStrategyCompiler(unittest.TestCase):
    def test_default_all_dp(self):
        cfg = compile_strategy(DistributedStrategy(), n_devices=8)
        self.assertEqual(cfg["degrees"], {"dp": 8, "mp": 1, "pp": 1,
                                          "sharding": 1})
        self.assertEqual(cfg["zero_stage"], 0)

    def test_sharding_absorbs_devices(self):
        s = DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {"stage": 2}
        cfg = compile_strategy(s, n_devices=8)
        self.assertEqual(cfg["zero_stage"], 2)
        self.assertEqual(cfg["degrees"]["sharding"], 8)
        self.assertEqual(cfg["degrees"]["dp"], 1)

    def test_sharding_respects_explicit_dp(self):
        s = DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {"stage": 2}
        s.hybrid_configs = {"dp_degree": 2}
        cfg = compile_strategy(s, n_devices=8)
        self.assertEqual(cfg["degrees"]["dp"], 2)
        self.assertEqual(cfg["degrees"]["sharding"], 4)

    def test_indivisible_raises(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        s = DistributedStrategy()
        s.hybrid_configs = {"mp_degree": 3}
        with self.assertRaises(InvalidArgumentError):
            compile_strategy(s, n_devices=8)

    def test_recompute_flag_flips_encoder(self):
        from paddle1_tpu.text.models import BertModel
        from paddle1_tpu.distributed import ParallelEngine, build_mesh
        import jax
        m = BertModel(vocab_size=32, hidden_size=16, num_hidden_layers=1,
                      num_attention_heads=2, intermediate_size=32,
                      max_position_embeddings=8)
        opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
        eng = ParallelEngine(
            m, opt, lambda mm, b: mm(paddle.to_tensor(b["ids"]))[1].sum(),
            mesh=build_mesh(dp=1, devices=jax.devices()[:1]),
            recompute=True)
        self.assertTrue(getattr(m.encoder, "enable_recompute", False))
        m.train()
        l = eng.step({"ids": np.random.randint(
            1, 32, (2, 8)).astype(np.int32)})
        self.assertTrue(np.isfinite(float(l)))

    def test_hybrid_tp_dp(self):
        s = DistributedStrategy()
        s.hybrid_configs = {"mp_degree": 2}
        cfg = compile_strategy(s, n_devices=8)
        self.assertEqual(cfg["degrees"]["mp"], 2)
        self.assertEqual(cfg["degrees"]["dp"], 4)

    def test_gradient_merge_and_amp(self):
        s = DistributedStrategy()
        s.gradient_merge = True
        s.gradient_merge_configs = {"k_steps": 4}
        s.amp = True
        cfg = compile_strategy(s, n_devices=1)
        self.assertEqual(cfg["grad_accum"], 4)
        self.assertEqual(cfg["amp_dtype"], "bfloat16")

    def test_fleet_parallel_engine_end_to_end(self):
        from paddle1_tpu.text.models import (BertForPretraining, BertModel,
                                             BertPretrainingCriterion,
                                             apply_megatron_sharding)
        s = DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {"stage": 2, "sharding_degree": 2}
        s.hybrid_configs = {"mp_degree": 2}
        fleet.init(is_collective=True, strategy=s)
        model = BertForPretraining(BertModel(
            vocab_size=64, hidden_size=32, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=16, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0))
        apply_megatron_sharding(model)
        crit = BertPretrainingCriterion(64)
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())

        def loss_fn(m, b):
            sc, rel = m(paddle.to_tensor(b["ids"]))
            return crit(sc, rel, paddle.to_tensor(b["mlm"]),
                        paddle.to_tensor(b["nsp"]))

        eng = fleet.parallel_engine(model, opt, loss_fn)
        self.assertEqual(dict(eng.mesh.shape)["mp"], 2)
        self.assertEqual(dict(eng.mesh.shape)["sharding"], 2)
        rng = np.random.default_rng(0)
        batch = {"ids": rng.integers(1, 64, (8, 16)).astype(np.int32),
                 "mlm": rng.integers(0, 64, (8, 16)).astype(np.int32),
                 "nsp": rng.integers(0, 2, (8,)).astype(np.int32)}
        l0 = float(eng.step(batch))
        l1 = float(eng.step(batch))
        self.assertTrue(np.isfinite(l0) and np.isfinite(l1))
        self.assertLess(l1, l0)


class TestMetaOptimizers(unittest.TestCase):
    def _model_opt(self):
        m = paddle.nn.Linear(4, 2)
        opt = paddle.optimizer.Momentum(learning_rate=0.05,
                                        parameters=m.parameters())
        return m, opt

    def test_localsgd_counts_steps(self):
        m, opt = self._model_opt()
        lopt = LocalSGDOptimizer(opt, k_steps=2)
        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(4, 2).astype(np.float32))
        for i in range(4):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            lopt.step()
            lopt.clear_grad()
        self.assertEqual(lopt._step_count, 4)

    def test_dgc_sparsifies_grads(self):
        m, opt = self._model_opt()
        dopt = DGCMomentumOptimizer(opt, sparsity=0.25)
        x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.randn(8, 2).astype(np.float32))
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        dopt.step()
        g = m.weight.grad.numpy()
        nz = (np.abs(g) > 0).sum()
        self.assertLessEqual(nz, max(1, int(g.size * 0.25)) + 1)
        # residual kept for error feedback
        self.assertTrue(any(np.abs(v).sum() > 0
                            for v in dopt._v.values()))

    def test_dgc_training_converges(self):
        m, opt = self._model_opt()
        dopt = DGCMomentumOptimizer(opt, sparsity=0.5)
        x = paddle.to_tensor(np.random.randn(16, 4).astype(np.float32))
        y = paddle.to_tensor(
            (np.random.randn(16, 2) * 0.1).astype(np.float32))
        losses = []
        for _ in range(30):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            dopt.step()
            dopt.clear_grad()
            losses.append(float(loss))
        self.assertLess(losses[-1], losses[0])
