"""Network table service (distributed/ps_server.py) — the scoped brpc-PS
transport: TableServer/RemoteTable must be drop-in equivalent to local
SparseTable shards (reference brpc_ps_server.cc / brpc_ps_client.cc
pull_sparse/push_sparse)."""

import os
import threading
import time

import numpy as np
import pytest

from paddle1_tpu.distributed.ps import (DistributedEmbedding,
                                        EmbeddingService, SparseTable)
from paddle1_tpu.distributed.ps_server import (RemoteTable, TableServer,
                                               remote_service)


@pytest.fixture()
def server():
    srv = TableServer(SparseTable(8, optimizer="sgd", lr=0.5)).start()
    yield srv
    srv.stop()


class TestRemoteTable:
    def test_pull_push_matches_local(self, server):
        local = SparseTable(8, optimizer="sgd", lr=0.5)
        remote = RemoteTable(server.endpoint)
        assert remote.ping()

        ids = [3, 7, 3]
        g = np.ones((3, 8), np.float32)
        r0 = remote.pull(ids)
        l0 = local.pull(ids)
        # same init distribution (same seed default) → identical rows
        np.testing.assert_allclose(r0, l0)
        remote.push(ids, g)
        local.push(ids, g)
        np.testing.assert_allclose(remote.pull(ids), local.pull(ids))
        assert len(remote) == len(local) == 2
        remote.close()

    def test_state_roundtrip(self, server):
        remote = RemoteTable(server.endpoint)
        remote.pull([1, 2, 3])
        state = remote.state_dict()
        fresh = SparseTable(8)
        fresh.load_state_dict(state)
        np.testing.assert_allclose(fresh.pull([1, 2, 3]),
                                   remote.pull([1, 2, 3]))
        remote.close()

    def test_error_propagates_not_kills_server(self, server):
        remote = RemoteTable(server.endpoint)
        from paddle1_tpu.core.errors import PreconditionNotMetError
        with pytest.raises(PreconditionNotMetError):
            remote.push([1], np.ones((1, 999), np.float32))  # bad dim
        # server still alive and serving
        assert remote.ping()
        remote.close()

    def test_concurrent_workers(self, server):
        n_workers, n_pushes = 4, 25
        errs = []

        def worker(seed):
            try:
                t = RemoteTable(server.endpoint)
                rng = np.random.default_rng(seed)
                for _ in range(n_pushes):
                    ids = rng.integers(0, 50, 8)
                    t.pull(ids)
                    t.push(ids, np.full((8, 8), 0.01, np.float32))
                t.close()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in range(n_workers)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert not errs
        assert len(server.table) <= 50


class TestRemoteService:
    def test_sharded_remote_service_trains(self):
        ones = lambda rng, dim: np.ones(dim, np.float32)  # O(1) start loss
        servers = [TableServer(SparseTable(4, optimizer="sgd", lr=0.2,
                                           seed=s, initializer=ones)).start()
                   for s in range(2)]
        try:
            svc = remote_service(4, [s.endpoint for s in servers])
            emb = DistributedEmbedding(svc)
            import paddle1_tpu as paddle

            ids = np.array([0, 1, 2, 3, 4, 5])
            target = np.zeros((6, 4), np.float32)
            first = None
            for _ in range(50):
                vecs = emb(ids)
                loss = ((vecs - paddle.to_tensor(target)) ** 2).mean()
                loss.backward()
                first = first if first is not None else float(loss.numpy())
            assert float(loss.numpy()) < first * 0.3
            # rows landed on the right shards (id % 2)
            assert len(servers[0].table) == 3
            assert len(servers[1].table) == 3
        finally:
            [s.stop() for s in servers]

    def test_routing_matches_local_service(self):
        servers = [TableServer(SparseTable(4, seed=s)).start()
                   for s in range(2)]
        try:
            svc_r = remote_service(4, [s.endpoint for s in servers])
            svc_l = EmbeddingService(4, num_shards=2)
            ids = np.array([0, 1, 2, 3, 7, 8])
            np.testing.assert_allclose(svc_r.pull(ids), svc_l.pull(ids))
        finally:
            [s.stop() for s in servers]


class TestFleetServerEntry:
    def test_init_server_requires_dim(self):
        import paddle1_tpu.distributed.fleet as fleet
        from paddle1_tpu.core.errors import PreconditionNotMetError
        fleet.init()
        os.environ.pop("PADDLE_PS_TABLE_DIM", None)
        with pytest.raises(PreconditionNotMetError, match="dim"):
            fleet.fleet.init_server()

    def test_server_lifecycle_via_fleet(self, monkeypatch):
        import paddle1_tpu.distributed.fleet as fleet
        fleet.init()
        fleet.fleet.init_server(dim=4)
        monkeypatch.setenv("PADDLE_PORT", "0")
        th = threading.Thread(target=fleet.fleet.run_server, daemon=True)
        th.start()
        # wait for the server object to bind
        import time
        for _ in range(100):
            srv = getattr(fleet.fleet, "_table_server", None)
            if srv is not None:
                break
            time.sleep(0.05)
        assert srv is not None
        t = RemoteTable(srv.endpoint)
        assert t.ping()
        t.pull([1, 2])
        assert len(t) == 2
        t.close()
        srv.stop()


class TestReviewRegressions:
    def test_remote_service_empty_endpoints_raises(self):
        with pytest.raises(ValueError, match="non-empty"):
            remote_service(4, [])

    def test_run_server_requires_port(self, monkeypatch):
        import paddle1_tpu.distributed.fleet as fleet
        from paddle1_tpu.core.errors import PreconditionNotMetError
        fleet.init()
        fleet.fleet.init_server(dim=4)
        monkeypatch.delenv("PADDLE_PORT", raising=False)
        with pytest.raises(PreconditionNotMetError, match="PADDLE_PORT"):
            fleet.fleet.run_server()

    def test_dim_mismatch_teaches(self, server):  # server table dim=8
        with pytest.raises(ValueError, match="dim=8"):
            remote_service(4, [server.endpoint])

    def test_closed_server_raises_connection_error(self):
        srv = TableServer(SparseTable(4)).start()
        t = RemoteTable(srv.endpoint)
        t.shutdown_server()
        with pytest.raises(ConnectionError):
            t.ping()
        t.close()

    def test_checkpoint_manager_rejects_zero_keep(self, tmp_path):
        from paddle1_tpu.distributed import CheckpointManager
        with pytest.raises(ValueError, match="max_to_keep"):
            CheckpointManager(str(tmp_path / "x"), max_to_keep=0)


class TestDownpourComposition:
    """The reference's DistMultiTrainer + DownpourWorker shape
    (trainer.h:57, downpour_worker.cc): worker threads pull sparse rows
    from the parameter server around each step and push gradients back,
    with the optimizer living IN the table. Here: MultiTrainer Hogwild
    workers x DistributedEmbedding over the TCP TableServer."""

    def test_hogwild_workers_train_through_remote_tables(self):
        import paddle1_tpu as paddle
        from paddle1_tpu.distributed.fleet.trainer import MultiTrainer

        ones = lambda rng, dim: np.ones(dim, np.float32)
        servers = [TableServer(SparseTable(4, optimizer="adagrad",
                                           lr=0.5, seed=s,
                                           initializer=ones)).start()
                   for s in range(2)]
        try:
            svc = remote_service(4, [s.endpoint for s in servers])
            emb = DistributedEmbedding(svc)
            dense = paddle.nn.Linear(4, 1)
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=dense.parameters())

            def loss_fn(batch):
                ids = batch[:, :3]
                y = paddle.to_tensor(
                    batch[:, 3:].astype(np.float32))
                vecs = emb(ids)                      # pull over TCP
                pooled = vecs.sum(axis=1)
                return ((dense(pooled) - y) ** 2).mean()

            rng = np.random.default_rng(0)
            samples = [np.concatenate([rng.integers(0, 20, 3),
                                       [rng.integers(0, 2)]])
                       for _ in range(96)]
            trainer = MultiTrainer(thread_num=3)
            stats = trainer.train_from_dataset(samples, loss_fn, opt,
                                               batch_size=8)
            assert stats["batches"] == 12  # 96 / 8
            assert stats["workers"] == 3
            # sparse rows materialized on the right shards, updated by
            # the in-table optimizer (adagrad slots advanced)
            assert len(servers[0].table) + len(servers[1].table) <= 20
            assert len(servers[0].table) > 0 and len(servers[1].table) > 0
            assert np.isfinite(stats["loss_mean"])
            # rows moved away from the all-ones init
            row = servers[0].table.pull(
                [next(iter(servers[0].table._rows))])
            assert not np.allclose(row, 1.0)
        finally:
            [s.stop() for s in servers]


class TestDurability:
    """PS failover surface: restartable TableServer (restore from its
    own checkpoint), push-epoch fence idempotence over a byte-identical
    replay, and RemoteTable's bounded typed retry/reconnect."""

    def test_stop_is_idempotent(self):
        srv = TableServer(SparseTable(4)).start()
        srv.stop()
        srv.stop()
        srv.stop()  # documented: safe to call repeatedly

    def test_restart_resumes_from_own_checkpoint(self, tmp_path):
        srv = TableServer(SparseTable(8, optimizer="sgd", lr=0.5),
                          ckpt_dir=str(tmp_path), save_every=1).start()
        remote = RemoteTable(srv.endpoint)
        ids = [3, 7]
        before = remote.pull(ids)
        remote.push(ids, np.ones((2, 8), np.float32))
        trained = remote.pull(ids)
        remote.close()
        srv.stop()
        # a restarted PS process constructs a FRESH table; the
        # checkpoint written on the mutation brings the rows back
        srv2 = TableServer(SparseTable(8, optimizer="sgd", lr=0.5),
                           ckpt_dir=str(tmp_path)).start()
        r2 = RemoteTable(srv2.endpoint)
        try:
            np.testing.assert_array_equal(r2.pull(ids), trained)
            assert not np.allclose(trained, before)
        finally:
            r2.close()
            srv2.stop()

    def test_fence_dedups_byte_identical_replay(self):
        import socket as socket_mod
        from paddle1_tpu.distributed import ps_server as psm
        srv = TableServer(SparseTable(4, optimizer="sgd", lr=0.5)).start()
        try:
            ids = np.asarray([5], np.int64)
            v0 = srv.table.pull(ids).copy()
            envelope = ("x", ("client-a", 1, "push",
                              (ids, np.ones((1, 4), np.float32))))

            def roundtrip():
                s = socket_mod.create_connection((srv.host, srv.port))
                try:
                    psm._send(s, envelope)
                    return psm._recv(s)
                finally:
                    s.close()

            r1 = roundtrip()
            after_first = srv.table.pull(ids).copy()
            # retry past a lost ack: same client id, same sequence
            r2 = roundtrip()
            assert r1 == r2 == ("ok", None)  # cached reply, no redispatch
            np.testing.assert_array_equal(srv.table.pull(ids), after_first)
            np.testing.assert_allclose(after_first, v0 - 0.5)  # ONCE
        finally:
            srv.stop()

    def test_retry_reconnects_across_server_restart(self, tmp_path):
        srv = TableServer(SparseTable(8, optimizer="sgd", lr=0.5),
                          ckpt_dir=str(tmp_path), save_every=1).start()
        port = srv.port
        remote = RemoteTable(srv.endpoint, max_retries=60,
                             backoff_base_s=0.01, backoff_max_s=0.05)
        remote.push([1], np.ones((1, 8), np.float32))
        expect = remote.pull([1])
        srv.stop()
        srv2_box = []

        def relaunch():
            time.sleep(0.3)
            srv2_box.append(TableServer(
                SparseTable(8, optimizer="sgd", lr=0.5), port=port,
                ckpt_dir=str(tmp_path)).start())

        t = threading.Thread(target=relaunch)
        t.start()
        try:
            out = remote.pull([1])   # retries until the restart lands
            np.testing.assert_array_equal(out, expect)
        finally:
            t.join()
            remote.close()
            if srv2_box:
                srv2_box[0].stop()

    def test_exhausted_retries_raise_typed_unavailable(self):
        from paddle1_tpu.core.errors import UnavailableError
        from paddle1_tpu.distributed.ps_server import PsUnavailableError
        srv = TableServer(SparseTable(4)).start()
        ep = srv.endpoint
        srv.stop()
        with pytest.raises(PsUnavailableError) as ei:
            RemoteTable(ep, max_retries=2, backoff_base_s=0.0,
                        backoff_max_s=0.0)
        # typed for callers AND still a ConnectionError for old handlers
        assert isinstance(ei.value, UnavailableError)
        assert isinstance(ei.value, ConnectionError)
        assert "Supervisor" in str(ei.value)
