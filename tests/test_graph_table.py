"""GraphTable (distributed/graph_table.py) — the graph-learning PS table
(reference distributed/table/common_graph_table.h: weighted neighbor
sampling + node features, served over the PS transport)."""

import numpy as np
import pytest

from paddle1_tpu.distributed import GraphTable
from paddle1_tpu.distributed.ps_server import RemoteTable, TableServer


def _chain_graph():
    g = GraphTable(seed=0)
    # 0 -> 1 (w=1), 0 -> 2 (w=3); 1 -> 2; 2 is a sink
    g.add_edges([0, 0, 1], [1, 2, 2], weights=[1.0, 3.0, 1.0])
    return g


class TestGraphTable:
    def test_degree_counts(self):
        g = _chain_graph()
        np.testing.assert_array_equal(g.node_degree([0, 1, 2, 9]),
                                      [2, 1, 0, 0])
        assert g.num_edges() == 3
        assert g.num_nodes() == 2  # nodes with outgoing edges or feats

    def test_weighted_sampling_distribution(self):
        g = _chain_graph()
        s = g.sample_neighbors([0], 8000, seed=7)[0]
        frac2 = float(np.mean(s == 2))
        assert abs(frac2 - 0.75) < 0.03  # weight 3:1 toward node 2

    def test_sink_pads_minus_one(self):
        g = _chain_graph()
        np.testing.assert_array_equal(g.sample_neighbors([2], 4),
                                      [[-1, -1, -1, -1]])

    def test_random_walk_respects_sinks(self):
        g = _chain_graph()
        w = g.random_walk([0, 2], 3, seed=1)
        assert w.shape == (2, 4)
        assert w[0, 0] == 0 and w[1, 0] == 2
        assert w[1, 1] == -1  # sink stays terminated
        row = w[0]
        ended = False
        for v in row[1:]:
            if v == -1:
                ended = True
            assert not (ended and v != -1), "walk resumed after sink"

    def test_node_features_roundtrip(self):
        g = _chain_graph()
        g.set_node_feat([0, 2], np.arange(8, dtype=np.float32)
                        .reshape(2, 4))
        f = g.get_node_feat([0, 1, 2])
        np.testing.assert_allclose(f[0], [0, 1, 2, 3])
        np.testing.assert_allclose(f[1], 0)  # unknown node → zeros
        np.testing.assert_allclose(f[2], [4, 5, 6, 7])

    def test_state_roundtrip(self):
        g = _chain_graph()
        g.set_node_feat([0], np.ones((1, 2), np.float32))
        g2 = GraphTable()
        g2.load_state_dict(g.state_dict())
        assert g2.num_edges() == 3
        np.testing.assert_array_equal(g2.node_degree([0]), [2])
        np.testing.assert_allclose(g2.get_node_feat([0]), [[1.0, 1.0]])

    def test_validation(self):
        g = GraphTable()
        with pytest.raises(ValueError, match="same length"):
            g.add_edges([1, 2], [3])
        with pytest.raises(ValueError, match="positive"):
            g.add_edges([1], [2], weights=[0.0])


class TestGraphTableOverWire:
    def test_remote_sampling_and_feats(self):
        srv = TableServer(_chain_graph()).start()
        try:
            t = RemoteTable(srv.endpoint)
            assert t.dim == 0  # graph tables have no embedding width
            np.testing.assert_array_equal(
                t.call("node_degree", [0, 1, 2]), [2, 1, 0])
            s = t.call("sample_neighbors", [0], 2000, seed=3)
            assert abs(float(np.mean(s == 2)) - 0.75) < 0.05
            t.call("set_node_feat", [1],
                   np.full((1, 3), 2.0, np.float32))
            np.testing.assert_allclose(t.call("get_node_feat", [1]),
                                       [[2.0, 2.0, 2.0]])
            # non-whitelisted method refused
            from paddle1_tpu.core.errors import PreconditionNotMetError
            with pytest.raises(PreconditionNotMetError,
                               match="RPC_METHODS"):
                t.call("load_state_dict", {})
            t.close()
        finally:
            srv.stop()
