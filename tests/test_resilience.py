"""Fault-tolerance runtime: chaos injection (core/chaos), hardened
checkpoints (distributed/checkpoint), device-side bad-step detection
(ParallelEngine check_finite), bad-step policies + resume
(distributed/resilience.ResilientTrainer), GradScaler dynamic scaling,
DataLoader error propagation, hapi fit(resume=), and the bare-except
lint.

Budget note: tier-1 runs ~850s of an 870s cap, so every engine build
here is shared/tiny (Linear(8,16,4) @ batch 4, dp=1) and the long soak
is @slow.
"""

import os
import shutil

import numpy as np
import pytest
import jax

import paddle1_tpu as paddle
from paddle1_tpu.core import chaos
from paddle1_tpu.core.tensor import Tensor
from paddle1_tpu.core.flags import flags_guard
from paddle1_tpu.distributed import (BadStepError, CheckpointManager,
                                     ParallelEngine, ResilientTrainer,
                                     build_mesh)
from paddle1_tpu.distributed import checkpoint as dckpt


@pytest.fixture(autouse=True)
def _chaos_isolation():
    chaos.reset()
    yield
    chaos.reset()


# -- tiny deterministic engine factory ---------------------------------------

N_BATCHES = 24
_rng = np.random.default_rng(0)
BATCHES = [{"x": _rng.standard_normal((4, 8)).astype(np.float32),
            "y": _rng.standard_normal((4, 4)).astype(np.float32)}
           for _ in range(N_BATCHES)]
NAN_BATCH = {"x": np.full((4, 8), np.nan, np.float32),
             "y": np.zeros((4, 4), np.float32)}


def _mk_engine(**kw):
    paddle.seed(0)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
    for i, p in enumerate(model.parameters()):
        p._data = jax.numpy.asarray(
            np.random.default_rng(100 + i)
            .standard_normal(p.shape).astype(np.float32) * 0.1)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    loss_fn = lambda m, b: ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()
    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    kw.setdefault("check_finite", True)
    return ParallelEngine(model, opt, loss_fn, mesh=mesh, **kw)


@pytest.fixture(scope="module")
def shared_engine():
    """One compiled engine reused by the policy/detection tests (each
    restores or tolerates prior state; compile once, not per test)."""
    return _mk_engine()


def _params(engine):
    return {k: np.asarray(v) for k, v in engine.params.items()}


def _assert_params_close(a, b, tol=1e-6):
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=tol, atol=tol,
                                   err_msg=f"param {k}")


# -- chaos spec --------------------------------------------------------------

class TestChaosSpec:
    def test_parse_fire_once(self):
        chaos.configure("nan_batch@2, ckpt_fail@1")
        assert chaos.enabled()
        assert not chaos.fire(chaos.POISON_BATCH)   # occurrence 1
        assert chaos.fire(chaos.POISON_BATCH)       # occurrence 2: armed
        assert not chaos.fire(chaos.POISON_BATCH)   # fires exactly once
        assert chaos.fire(chaos.CKPT_FAIL)
        assert chaos.counts() == {"nan_batch": 3, "ckpt_fail": 1}

    def test_bad_specs(self):
        with pytest.raises(ValueError):
            chaos.configure("not_a_point@1")
        with pytest.raises(ValueError):
            chaos.configure("nan_batch@0")
        with pytest.raises(ValueError):
            chaos.configure("nan_batch")

    def test_poison_first_float_leaf(self):
        chaos.configure("nan_batch@1")
        out = chaos.maybe_poison({"i": np.arange(3),
                                  "x": np.ones(3, np.float32)})
        assert np.all(np.isnan(out["x"])) and out["i"].dtype.kind == "i"
        # disarmed occurrence: batch passes through untouched
        out2 = chaos.maybe_poison({"x": np.ones(3, np.float32)})
        assert not np.any(np.isnan(out2["x"]))

    def test_recommender_points_parse_and_count(self):
        # ISSUE 20: PS + delta chaos points ride the same spec grammar.
        # ps_kill/ps_hang share ONE per-request counter; ``:R``
        # qualifies to a PS rank, unqualified matches any rank.
        chaos.configure("ps_kill@2:0, ps_hang@3, delta_corrupt@1, "
                        "delta_gap@2")
        assert chaos.check_ps(rank=0) is None          # request 1
        assert chaos.check_ps(rank=1) is None          # request 2, rank≠0
        assert chaos.check_ps(rank=1) == chaos.PS_HANG  # request 3, any
        assert chaos.check_delta_corrupt()             # publish 1: armed
        assert not chaos.check_delta_corrupt()         # fires once
        assert not chaos.check_delta_gap()             # own counter: occ 1
        assert chaos.check_delta_gap()                 # occurrence 2
        chaos.reset()
        chaos.configure("ps_kill@1")
        assert chaos.check_ps(rank=7) == chaos.PS_KILL

    def test_preemption_request(self):
        chaos.configure("preempt@3")
        chaos.check_preempt()
        chaos.request_preemption()
        with pytest.raises(chaos.SimulatedPreemption) as ei:
            chaos.check_preempt()
        assert ei.value.graceful  # an advance notice: time to save
        chaos.check_preempt()  # request was consumed; occurrence 3 next
        with pytest.raises(chaos.SimulatedPreemption) as ei:
            chaos.check_preempt()
        assert not ei.value.graceful  # armed chaos = ungraceful kill
        assert issubclass(chaos.SimulatedPreemption, BaseException) \
            and not issubclass(chaos.SimulatedPreemption, Exception)


# -- hardened checkpoints (no engine: plain jnp trees) -----------------------

def _tree(val=1.0):
    return {"params": {"w": jax.numpy.full((3, 2), val, "float32"),
                       "b": jax.numpy.full((2,), val, "float32")}}


class TestCheckpointHardening:
    def test_latest_step_skips_junk(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(3, _tree(3.0))
        # junk that used to crash/confuse latest_step: non-numeric dirs,
        # unicode digits int() rejects, stray files, partial step dirs
        os.makedirs(tmp_path / "notastep")
        os.makedirs(tmp_path / "²")
        (tmp_path / "12").write_text("a FILE named like a step")
        os.makedirs(tmp_path / "99")  # numeric but no manifest: partial
        assert mgr.latest_step() == 3
        assert dckpt.latest_step(str(tmp_path)) == 3

    def test_atomic_commit_and_injected_failure(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, _tree(1.0), meta={"step": 1, "note": "ok"})
        chaos.configure("ckpt_fail@1")
        with pytest.raises(IOError):
            mgr.save(2, _tree(2.0))
        # the failed write left no committed step-2 — and whatever debris
        # it left is ignored by latest_step and swept by the next GC
        assert mgr.latest_step() == 1
        restored, step = mgr.restore(_tree())
        assert step == 1
        np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 1.0)
        assert mgr.read_meta(1)["note"] == "ok"
        mgr.save(2, _tree(2.0))  # chaos disarmed after firing once
        assert mgr.latest_step() == 2
        assert not any(".tmp-" in d for d in os.listdir(tmp_path))

    def test_corrupt_latest_falls_back(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        for s, v in ((1, 1.0), (2, 2.0), (3, 3.0)):
            mgr.save(s, _tree(v))
        # corrupt newest: orbax payload gone, manifest still claims valid
        for d in os.listdir(tmp_path / "3"):
            p = tmp_path / "3" / d
            if d != dckpt.MANIFEST_NAME:
                shutil.rmtree(p) if p.is_dir() else p.unlink()
        with pytest.warns(UserWarning, match="falling back"):
            restored, step = mgr.restore(_tree())
        assert step == 2
        np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 2.0)

    def test_manifest_mismatch_and_all_corrupt(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, _tree())
        wrong = {"params": {"w": jax.numpy.zeros((5, 5), "float32")}}
        with pytest.raises(dckpt.CheckpointCorruptError):
            dckpt.verify_manifest(str(tmp_path / "1"), wrong)
        with pytest.warns(UserWarning), \
                pytest.raises(dckpt.CheckpointCorruptError):
            mgr.restore(wrong)
        with pytest.raises(FileNotFoundError):
            CheckpointManager(str(tmp_path / "empty")).restore(_tree())

    def test_gc_counts_only_committed(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
        mgr.save(1, _tree(1.0))
        mgr.save(2, _tree(2.0))
        os.makedirs(tmp_path / "9")  # manifest-less (legacy/foreign) —
        mgr.save(3, _tree(3.0))      # must NOT push 2 out of retention
        assert mgr.all_steps() == [2, 3]
        # ...and must NOT be deleted either: a pre-manifest checkpoint
        # from an older run is preserved, just never restored/counted
        assert (tmp_path / "9").exists()
        assert mgr.latest_step() == 3


# -- GradScaler dynamic scaling ---------------------------------------------

class TestGradScaler:
    def test_record_step_halve_and_regrow(self):
        s = paddle.amp.GradScaler(init_loss_scaling=64.0,
                                  incr_every_n_steps=3)
        assert s.record_step(found_inf=True) == 32.0   # halve on bad
        for _ in range(2):
            assert s.record_step(found_inf=False) == 32.0
        assert s.record_step(found_inf=False) == 64.0  # regrow after 3
        # a bad step resets the good-step streak
        s.record_step(found_inf=False)
        s.record_step(found_inf=True)
        for _ in range(2):
            s.record_step(found_inf=False)
        assert s.get_loss_scaling() == 32.0
        assert s.record_step(found_inf=False) == 64.0

    def test_nonfinite_skips_update_and_halves(self):
        lin = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        s = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        before = np.asarray(lin.weight.data).copy()
        bad = (lin(x) * paddle.to_tensor(np.float32(np.nan))).mean()
        scaled = s.scale(bad)
        scaled.backward()
        s.minimize(opt, scaled)
        assert s.last_step_skipped()
        assert s.get_loss_scaling() == 512.0
        np.testing.assert_allclose(np.asarray(lin.weight.data), before)
        opt.clear_grad()
        good = lin(x).mean()
        scaled = s.scale(good)
        scaled.backward()
        s.minimize(opt, scaled)
        assert not s.last_step_skipped()
        assert not np.allclose(np.asarray(lin.weight.data), before)

    def test_double_unscale_refused_and_update_consumes_flag(self):
        lin = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        s = paddle.amp.GradScaler(init_loss_scaling=16.0)
        loss = lin(paddle.to_tensor(np.ones((1, 2), np.float32))).mean()
        s.scale(loss).backward()
        s.unscale_(opt)
        with pytest.raises(Exception):
            s.unscale_(opt)
        s._found_inf = True   # white-box: a detected overflow...
        s._pending_update = True
        s.update()
        assert s.get_loss_scaling() == 8.0
        s.update()  # outcome was consumed: no second halving
        assert s.get_loss_scaling() == 8.0

    def test_reference_step_then_update_pattern(self):
        # paddle/torch idiom: scaler.step(opt); scaler.update() — the
        # external update() must not register a phantom good step, or
        # decr_every_n_nan_or_inf=2 could never trip
        lin = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        s = paddle.amp.GradScaler(init_loss_scaling=64.0,
                                  decr_every_n_nan_or_inf=2)
        nan = paddle.to_tensor(np.float32(np.nan))
        for _ in range(2):
            loss = (lin(paddle.to_tensor(np.ones((1, 2), np.float32)))
                    * nan).mean()
            s.scale(loss).backward()
            s.step(opt)
            s.update()  # reference pattern: external update after step
            opt.clear_grad()
        assert s.get_loss_scaling() == 32.0  # 2 bad steps -> one halve


# -- DataLoader worker-error propagation -------------------------------------

class _FailingDS(paddle.io.Dataset):
    def __init__(self, exc):
        self.exc = exc

    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i >= 4:
            raise self.exc
        return np.ones(3, np.float32)


class TestDataLoaderErrors:
    def test_worker_error_reraises_and_sticks(self):
        it = iter(paddle.io.DataLoader(_FailingDS(ValueError("boom")),
                                       batch_size=2, num_workers=0))
        next(it), next(it)
        with pytest.raises(ValueError, match="boom"):
            next(it)
        with pytest.raises(ValueError, match="boom"):
            next(it)  # sticky: NOT a clean StopIteration after the error

    def test_leaked_stopiteration_is_an_error(self):
        # PEP 479: a dataset leaking StopIteration must not read as a
        # silently shorter epoch
        dl = paddle.io.DataLoader(_FailingDS(StopIteration()),
                                  batch_size=2, num_workers=0)
        with pytest.raises(RuntimeError, match="StopIteration"):
            for _ in dl:
                pass

    def test_chaos_loader_injection(self):
        chaos.configure("loader_raise@2")
        dl = paddle.io.DataLoader(_FailingDS(ValueError("unused")),
                                  batch_size=1, num_workers=0)
        seen = 0
        with pytest.raises(IOError, match="injected dataloader"):
            for _ in dl:
                seen += 1
        assert seen == 1


# -- device-side bad-step detection ------------------------------------------

class TestBadStepDetection:
    def test_flag_rides_loss_readback_and_update_skipped(self,
                                                         shared_engine):
        from paddle1_tpu.core import async_loss
        eng = shared_engine
        fut = eng.step(BATCHES[0])
        assert not fut.bad and np.isfinite(float(fut))
        good = _params(eng)
        async_loss.reset_readback_count()
        fut = eng.step(NAN_BATCH)
        assert fut.bad and not np.isfinite(float(fut))
        assert async_loss.readback_count() == 1  # loss+flag: ONE readback
        _assert_params_close(_params(eng), good)  # skipped on device
        fut = eng.step(BATCHES[1])  # trains straight through afterwards
        assert not fut.bad

    def test_step_many_scan_body_flags(self, shared_engine):
        eng = shared_engine
        before = _params(eng)
        fut = eng.step_many([BATCHES[2], NAN_BATCH, BATCHES[3]])
        assert fut.bad and list(fut.bad_mask()) == [False, True, False]
        assert fut.bad_count() == 1
        losses = np.asarray(fut)
        assert losses.shape == (3,) and np.isnan(losses[1])
        after = _params(eng)  # 2 good updates applied, NaN one skipped
        assert any(not np.allclose(before[k], after[k]) for k in before)
        assert all(np.all(np.isfinite(v)) for v in after.values())


class TestDonationOwnership:
    def test_layer_buffers_survive_donated_training(self):
        """Single-device Layer params placed onto a MULTI-device mesh:
        device_put elides the origin-device shard copy, so without the
        engine's unconditional ownership copy the first donated step
        deletes the model's live tensors (surfaced by registry-wide
        fluid.io saves, PR 2). sync_model must also hand the Layer
        copies, or resume-then-continue training re-breaks it."""
        paddle.seed(0)
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                     paddle.nn.Linear(16, 4))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        loss_fn = lambda m, b: ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2
                                ).mean()
        mesh = build_mesh(dp=2, devices=jax.devices()[:2])
        eng = ParallelEngine(model, opt, loss_fn, mesh=mesh,
                             check_finite=True)  # donate defaults True
        eng.step(BATCHES[0])
        for name, t in model.state_dict().items():
            np.asarray(t._data)  # raises "Array has been deleted" on alias
        eng.sync_model()
        eng.step(BATCHES[1])  # donates engine buffers again
        eng.drain()
        for name, t in model.state_dict().items():
            np.asarray(t._data)


# -- ResilientTrainer --------------------------------------------------------

def _trainer(engine, directory, **kw):
    kw.setdefault("save_freq", 2)
    kw.setdefault("backoff_base_s", 0.0)
    return ResilientTrainer(engine, str(directory), **kw)


class TestResilientTrainer:
    def test_policy_raise(self, shared_engine, tmp_path):
        chaos.configure("nan_batch@2")
        tr = _trainer(shared_engine, tmp_path / "r", bad_step_policy="raise")
        with pytest.raises(BadStepError):
            tr.fit(lambda: BATCHES, steps=6)
        good = _params(shared_engine)
        assert all(np.all(np.isfinite(v)) for v in good.values())

    def test_policy_skip_counters(self, shared_engine, tmp_path):
        chaos.configure("nan_batch@3")
        tr = _trainer(shared_engine, tmp_path / "s", bad_step_policy="skip")
        scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
        tr.scaler = scaler
        rep = tr.fit(lambda: BATCHES, steps=6)
        assert rep.final_step == 6
        assert rep.bad_steps == 1 and rep.steps_skipped == 1
        assert rep.steps_done == 5  # 6 slots, one consumed by the skip
        assert scaler.get_loss_scaling() == 32.0  # bad step fed the scaler
        assert rep.restores == 0

    def test_graceful_preemption_saves_instead_of_rollback(
            self, shared_engine, tmp_path):
        chaos.configure("nan_batch@999")  # arm chaos (no point fires)
        chaos.request_preemption()
        tr = _trainer(shared_engine, tmp_path / "g", bad_step_policy="skip",
                      save_freq=100)
        rep = tr.fit(lambda: BATCHES, steps=4)
        assert rep.preemptions == 1
        assert rep.restores == 0          # notice ≠ rollback
        assert rep.final_step == 4
        # the notice landed before any step, so the grace-window save
        # committed step 0 (on top of the baseline), and training went on
        assert tr.manager.latest_step() == 4

    def test_divergence_watchdog(self, tmp_path):
        # host-side unit: the watchdog warms up on the first 5 losses,
        # then flags a loss > factor * running-mean as a bad step
        import types
        tr = ResilientTrainer(
            types.SimpleNamespace(check_finite=True), str(tmp_path / "d"),
            divergence_factor=3.0, bad_step_policy="skip")
        for loss in (1.0, 1.1, 0.9, 1.0, 1.05):
            assert not tr._diverged(loss)   # warmup window
        assert not tr._diverged(1.2)
        assert tr._diverged(50.0)           # explosion: > 3x the mean
        assert not tr._diverged(1.0)        # and the EMA was not polluted
        off = ResilientTrainer(
            types.SimpleNamespace(check_finite=True), str(tmp_path / "o"),
            divergence_factor=0.0, bad_step_policy="skip")
        assert all(not off._diverged(v) for v in (1.0, 1.0, 1.0, 1.0,
                                                  1.0, 1e9))

    def test_persistent_bad_data_breaks_restore_loop(self, shared_engine,
                                                     tmp_path):
        tr = _trainer(shared_engine, tmp_path / "p",
                      bad_step_policy="restore_last_good", max_retries=1)
        with pytest.warns(UserWarning), pytest.raises(BadStepError,
                                                      match="deterministic"):
            tr.fit(lambda: [NAN_BATCH] * 8, steps=8)

    def test_chaos_matrix_parity_and_hard_kill_resume(self, tmp_path):
        """The acceptance matrix: NaN batch + failed checkpoint write +
        simulated preemption recover to the uninterrupted run's params
        (1e-6), with accurate counters; then a hard kill (corrupt newest
        checkpoint, fresh trainer) resumes through fallback and still
        matches the straight run."""
        steps1, steps2 = 8, 12
        clean_eng = _mk_engine()
        clean = _trainer(clean_eng, tmp_path / "clean",
                         bad_step_policy="restore_last_good")
        rep_clean = clean.fit(lambda: BATCHES, steps=steps1)
        assert rep_clean.bad_steps == 0 and rep_clean.restores == 0
        clean_mid = _params(clean_eng)
        clean.fit(lambda: BATCHES, steps=steps2)  # resumes from 8 → 12
        clean_final = _params(clean_eng)

        # chaos leg: poison batch idx 4 (occurrence 5), fail the 3rd
        # checkpoint write, preempt on the 7th loop iteration
        chaos.configure("nan_batch@5,ckpt_fail@3,preempt@7")
        eng = _mk_engine()
        tr = _trainer(eng, tmp_path / "chaos",
                      bad_step_policy="restore_last_good")
        rep = tr.fit(lambda: BATCHES, steps=steps1)
        chaos.reset()
        assert rep.final_step == steps1
        assert rep.bad_steps == 1      # the poisoned batch
        assert rep.retries >= 1        # the failed checkpoint write
        assert rep.preemptions == 1
        assert rep.restores == 2       # NaN rollback + preemption restore
        _assert_params_close(_params(eng), clean_mid)

        # hard kill: newest checkpoint corrupt (write died mid-commit),
        # fresh trainer on the same dir falls back, replays, catches up
        mgr = tr.manager
        latest = mgr.latest_step()
        os.remove(os.path.join(mgr.directory, str(latest),
                               dckpt.MANIFEST_NAME))
        tr2 = _trainer(eng, tmp_path / "chaos",
                       bad_step_policy="restore_last_good")
        rep2 = tr2.fit(lambda: BATCHES, steps=steps2)
        assert rep2.resumed_from is not None and rep2.resumed_from < latest
        _assert_params_close(_params(eng), clean_final)


# -- hapi Model.fit resume ---------------------------------------------------

class TestHapiResume:
    def _model(self):
        paddle.seed(7)
        net = paddle.nn.Linear(4, 2)
        net.weight._data = jax.numpy.asarray(
            np.random.default_rng(5).standard_normal((4, 2))
            .astype(np.float32))
        net.bias._data = jax.numpy.zeros((2,), "float32")
        m = paddle.Model(net)
        m.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()),
            loss=paddle.nn.MSELoss())
        return m

    def test_resume_continues_from_latest_epoch(self, tmp_path):
        rng = np.random.default_rng(3)
        data = [(rng.standard_normal((4,)).astype(np.float32),
                 rng.standard_normal((2,)).astype(np.float32))
                for _ in range(8)]
        straight = self._model()
        straight.fit(data, epochs=3, batch_size=4, verbose=0, shuffle=False)

        resumed = self._model()
        resumed.fit(data, epochs=1, batch_size=4, verbose=0, shuffle=False,
                    save_dir=str(tmp_path))
        (tmp_path / "junk.txt").write_text("not a checkpoint")
        (tmp_path / "nan.pdparams").write_text("non-numeric name")
        fresh = self._model()  # new process analog: re-built, then resumed
        fresh.fit(data, epochs=3, batch_size=4, verbose=0, shuffle=False,
                  save_dir=str(tmp_path), resume=True)
        np.testing.assert_allclose(
            np.asarray(fresh.network.weight.data),
            np.asarray(straight.network.weight.data), rtol=1e-6, atol=1e-6)

    def test_resume_requires_save_dir(self):
        with pytest.raises(Exception, match="save_dir"):
            self._model().fit([(np.zeros(4, np.float32),
                                np.zeros(2, np.float32))],
                              epochs=1, verbose=0, resume=True)


# -- supervisor health channel (PR 3) ----------------------------------------

class TestHealthChannel:
    @pytest.fixture(autouse=True)
    def _isolate(self):
        import signal as _signal
        from paddle1_tpu.core import health
        old = _signal.getsignal(_signal.SIGTERM)
        health.reset()
        yield
        health.reset()
        _signal.signal(_signal.SIGTERM, old)
        for k in (health.HEARTBEAT_ENV, health.STACKDUMP_ENV,
                  health.INCARNATION_ENV):
            os.environ.pop(k, None)

    def test_beat_unsupervised_is_noop(self):
        from paddle1_tpu.core import health
        health.beat()  # no env, no error
        assert not health.supervised()

    def test_beat_touches_heartbeat_and_pops_env(self, tmp_path):
        import time
        from paddle1_tpu.core import health
        hb = tmp_path / "hb.0"
        hb.write_text("")
        before = hb.stat().st_mtime
        os.environ[health.HEARTBEAT_ENV] = str(hb)
        time.sleep(0.05)
        health.beat()
        assert hb.stat().st_mtime > before
        # env consumed at install: grandchildren (e.g. the fleet mp
        # workers forwarding PADDLE_*) must not adopt this channel
        assert health.HEARTBEAT_ENV not in os.environ
        assert health.supervised()

    def test_worker_unhealthy_chaos_writes_marker(self, tmp_path):
        from paddle1_tpu.core import health
        hb = tmp_path / "hb.0"
        hb.write_text("")
        os.environ[health.HEARTBEAT_ENV] = str(hb)
        chaos.configure("worker_unhealthy@2")
        health.beat()
        marker = tmp_path / ("hb.0" + health.UNHEALTHY_SUFFIX)
        assert not marker.exists()
        health.beat()  # 2nd beat: armed occurrence fires
        assert marker.exists() and "chaos" in marker.read_text()

    def test_worker_chaos_gated_to_incarnation_zero(self, tmp_path):
        from paddle1_tpu.core import health
        hb = tmp_path / "hb.0"
        hb.write_text("")
        os.environ[health.HEARTBEAT_ENV] = str(hb)
        os.environ[health.INCARNATION_ENV] = "1"  # a restarted worker
        chaos.configure("worker_unhealthy@1")
        health.beat()
        # armed but gated: restarts must replay clean (fire-once)
        assert not (tmp_path / ("hb.0" + health.UNHEALTHY_SUFFIX)).exists()

    def test_reinstall_does_not_self_chain_sigterm(self, tmp_path):
        """reset() + reinstall must not capture our own handler as
        'previous' — the drain SIGTERM would chain into itself until
        RecursionError inside the signal handler."""
        import signal as _signal
        from paddle1_tpu.core import health
        hb = tmp_path / "hb.0"
        hb.write_text("")
        os.environ[health.HEARTBEAT_ENV] = str(hb)
        health.beat()
        health.reset()
        os.environ[health.HEARTBEAT_ENV] = str(hb)
        health.beat()
        assert health._prev_sigterm is not health._on_sigterm
        health._on_sigterm(_signal.SIGTERM, None)  # must not recurse
        assert health.drain_requested()

    def test_drain_request_checkpoints_then_stops_fit(self, tmp_path):
        """The drain policy's worker half: request_drain (what the
        supervisor's SIGTERM triggers) makes ResilientTrainer.fit
        checkpoint its current good state and STOP, not keep training
        like an ordinary graceful preemption."""
        from paddle1_tpu.core import health
        tr = ResilientTrainer(_mk_engine(), str(tmp_path / "ck"),
                              save_freq=100, backoff_base_s=0.0)

        def data():
            def gen():
                for i, b in enumerate(BATCHES):
                    if i == 4:
                        health.request_drain()
                    yield b
            return gen()

        rep = tr.fit(data, steps=12)
        assert rep.preemptions == 1
        assert rep.final_step == 5      # batch 4 applied, then stopped
        assert tr.manager.latest_step() == 5  # ... with state committed


# -- bare-except lint --------------------------------------------------------

class TestBareExceptLint:
    def test_rules(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "chk", os.path.join(os.path.dirname(__file__), "..", "tools",
                                "check_no_bare_except.py"))
        chk = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(chk)
        bad = "try:\n    x()\nexcept:\n    pass\n"
        assert chk.check_source(bad)
        swallow = "try:\n    x()\nexcept BaseException:\n    pass\n"
        assert chk.check_source(swallow)
        ok = "try:\n    x()\nexcept Exception:\n    pass\n"
        assert not chk.check_source(ok)
        reraise = ("try:\n    x()\nexcept BaseException:\n"
                   "    log()\n    raise\n")
        assert not chk.check_source(reraise)
        marked = ("try:\n    x()\n"
                  "except BaseException as e:  # noqa: broad-except — q\n"
                  "    q.put(e)\n")
        assert not chk.check_source(marked)
        # PR 3 extensions: the marker needs a REASON, and absorbing the
        # preemption notice is allowlisted to the resilient loop only
        bare_marker = ("try:\n    x()\n"
                       "except BaseException:  # noqa: broad-except\n"
                       "    pass\n")
        assert chk.check_source(bare_marker)
        preempt = ("try:\n    x()\nexcept SimulatedPreemption:\n"
                   "    pass\n")
        assert chk.check_source(
            preempt, "paddle1_tpu/distributed/supervisor.py")
        assert not chk.check_source(
            preempt, "paddle1_tpu/distributed/resilience.py")
        # the package tree itself is clean (CI lints the full default
        # path set; here the package only, for tier-1 time budget)
        pkg = os.path.join(os.path.dirname(__file__), "..", "paddle1_tpu")
        assert chk.main([pkg]) == 0


# -- embed sidecar -----------------------------------------------------------

def _assert_table_state_equal(a, b):
    assert set(a["rows"]) == set(b["rows"])
    for i in a["rows"]:
        np.testing.assert_array_equal(a["rows"][i], b["rows"][i],
                                      err_msg=f"row {i}")
        for k, (sa, sb) in enumerate(zip(a["slots"].get(i, []),
                                         b["slots"].get(i, []))):
            np.testing.assert_array_equal(sa, sb,
                                          err_msg=f"slot {k} of {i}")
        assert a["steps"].get(i) == b["steps"].get(i)


class TestEmbedSidecar:
    """The embed sidecar rides the manifest checkpoint: engine
    admission/placement/ledger state plus host-tier rows and optimizer
    slots restore bit-identically, so post-crash evict/re-admit traffic
    replays the clean run exactly."""

    def test_save_restore_round_trip_bit_identical(self, tmp_path):
        from paddle1_tpu.distributed import (EmbeddingService,
                                             HBMShardedEmbedding,
                                             ShardedEmbeddingEngine)
        from paddle1_tpu.nn import TieredEmbedding
        DIM, CAP, BUDGET = 4, 16, 12
        paddle.seed(0)
        hbm = HBMShardedEmbedding(CAP, DIM)
        host = EmbeddingService(DIM, num_shards=2, optimizer="adam")
        eng = ShardedEmbeddingEngine(hbm, host, hbm_row_budget=BUDGET)

        class _CTR(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = TieredEmbedding(eng)
                self.head = paddle.nn.Linear(DIM, 1)

            def forward(self, slots):
                return self.head(self.emb(slots).mean(axis=1))

        model = _CTR()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        peng = ParallelEngine(
            model, opt,
            lambda m, b: ((m(Tensor(b["slots"])) - Tensor(b["y"])) ** 2
                          ).mean(),
            mesh=build_mesh(dp=1, devices=jax.devices()[:1]),
            check_finite=True)
        eng.bind_engine(peng)
        tr = ResilientTrainer(peng, str(tmp_path), save_freq=100,
                              backoff_base_s=0.0)
        tr.attach_embedding(eng)
        rng = np.random.default_rng(0)

        def drive(lo, hi, steps):
            for step in steps:
                ids = rng.integers(lo, hi, (4, 3))
                y = rng.standard_normal((4, 1)).astype(np.float32)
                peng.step({"slots": eng.route(ids, now=float(step)),
                           "y": y})

        drive(0, 40, range(3))
        assert tr.save(3)
        peng.drain()
        want_engine = eng.state_dict()
        want_host = host.state_dict()
        # perturb AFTER the save: fresh admissions, evictions, pushes
        drive(20, 64, range(3, 6))
        assert tr.restore_latest() == 3
        got_engine = eng.state_dict()
        assert set(got_engine) == set(want_engine)
        for k in want_engine:
            np.testing.assert_array_equal(got_engine[k], want_engine[k],
                                          err_msg=f"engine[{k}]")
        # placement-determinism state travels too (free-list order and
        # last-route times drive future victim choice)
        for key in ("free", "touch", "touch_ids", "dirty"):
            assert key in got_engine
        got_host = host.state_dict()
        for ws, gs in zip(want_host["shards"], got_host["shards"]):
            _assert_table_state_equal(ws, gs)
        # the sidecar is digest-verified npz next to the manifest
        arrays = tr.manager.read_sidecar("embed")
        assert any(k.startswith("engine/") for k in arrays)
        assert any(k.startswith("host/") for k in arrays)


# -- chaos soak (slow: excluded from tier-1) ---------------------------------

@pytest.mark.slow
def test_chaos_soak_bench():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    bench.bench_chaos_soak(on_tpu=False, steps_override=40)


@pytest.mark.slow
def test_recommender_chaos_bench():
    """CI recommender-chaos lane: the full durable-recommender soak
    (PS SIGKILL mid-epoch + trainer preemption + delta corruption +
    delta gap vs a clean run) at reduced steps."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    bench.bench_recommender_chaos(on_tpu=False, steps_override=12)
