"""Dense tables + async Communicator + geo-async SGD (VERDICT r3
missing #1): the reference PS trains DENSE params asynchronously through
send/recv gradient queues (communicator.cc, common_dense_table.h) and
supports geo-async staleness (sparse_geo_table.h)."""

import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle1_tpu.distributed.communicator import (AsyncCommunicator,
                                                  DenseEndpoint,
                                                  GeoCommunicator)
from paddle1_tpu.distributed.ps import DenseTable, SparseTable
from paddle1_tpu.distributed.ps_server import RemoteTable, TableServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestDenseTable:
    def test_sgd_update_math(self):
        t = DenseTable((3, 2), optimizer="sgd", lr=0.5, seed=1)
        v0 = t.pull_dense()
        g = np.ones((3, 2), np.float32)
        t.push_dense_grad(g)
        np.testing.assert_allclose(t.pull_dense(), v0 - 0.5, rtol=1e-6)
        assert t.get_version() == 1

    def test_adam_update_moves_against_grad(self):
        t = DenseTable((4,), optimizer="adam", lr=0.1, seed=2)
        v0 = t.pull_dense()
        for _ in range(3):
            t.push_dense_grad(np.ones(4, np.float32))
        assert (t.pull_dense() < v0).all()
        assert t.get_version() == 3

    def test_delta_merge_and_state_roundtrip(self):
        t = DenseTable((2, 2), seed=3)
        v0 = t.pull_dense()
        t.push_dense_delta(np.full((2, 2), 0.25, np.float32))
        np.testing.assert_allclose(t.pull_dense(), v0 + 0.25, rtol=1e-6)
        sd = t.state_dict()
        t2 = DenseTable((2, 2), seed=99)
        t2.load_state_dict(sd)
        np.testing.assert_allclose(t2.pull_dense(), t.pull_dense())
        assert t2.get_version() == t.get_version()

    def test_shape_mismatch_raises(self):
        t = DenseTable((2, 2))
        with pytest.raises(ValueError, match="shape"):
            t.push_dense_grad(np.ones((3, 3), np.float32))


class TestServedDense:
    def test_named_dense_tables_over_the_wire(self):
        dense = {"w": DenseTable((4, 3), lr=0.1, seed=0),
                 "b": DenseTable((3,), lr=0.1, seed=1)}
        srv = TableServer(SparseTable(dim=8), aux_tables=dense).start()
        try:
            rt = RemoteTable(srv.endpoint)
            assert rt.list_tables() == ["b", "w"]
            w0 = rt.table_call("w", "pull_dense")
            rt.table_call("w", "push_dense_grad", np.ones((4, 3),
                                                          np.float32))
            np.testing.assert_allclose(
                rt.table_call("w", "pull_dense"), w0 - 0.1, rtol=1e-6)
            # primary sparse table still serves on the same port
            assert rt.pull([1, 2]).shape == (2, 8)
            # unknown table / non-whitelisted method are loud errors
            from paddle1_tpu.core.errors import PreconditionNotMetError
            with pytest.raises(PreconditionNotMetError, match="no table"):
                rt.table_call("nope", "pull_dense")
            with pytest.raises(PreconditionNotMetError,
                               match="RPC_METHODS"):
                rt.table_call("w", "load_state_dict", {})
        finally:
            srv.stop()


class TestAsyncCommunicator:
    def test_merge_mean_applies_once(self):
        t = DenseTable((2,), optimizer="sgd", lr=1.0, seed=0)
        v0 = t.pull_dense()
        comm = AsyncCommunicator({"w": t}, merge_num=4,
                                 merge_mode="mean").start()
        try:
            for g in ([2.0, 0.0], [0.0, 2.0], [2.0, 2.0], [0.0, 0.0]):
                comm.send("w", np.asarray(g, np.float32))
            comm.flush()
            # mean of the four grads = [1, 1] applied with lr=1
            np.testing.assert_allclose(t.pull_dense(), v0 - 1.0,
                                       rtol=1e-5)
            np.testing.assert_allclose(comm.recv("w"), t.pull_dense())
        finally:
            comm.stop()

    def test_async_linear_regression_converges_two_threads(self):
        rng = np.random.default_rng(0)
        W_true = rng.standard_normal((5, 1)).astype(np.float32)
        # async SGD stability: staleness (steps between cache refreshes)
        # x lr must stay inside the contraction region, so small lr and a
        # fast pull interval
        t = DenseTable((5, 1), optimizer="sgd", lr=0.01, seed=1)
        comm = AsyncCommunicator({"w": t}, merge_num=2,
                                 pull_interval=0.005).start()

        def worker(seed):
            r = np.random.default_rng(seed)
            for _ in range(400):
                X = r.standard_normal((16, 5)).astype(np.float32)
                y = X @ W_true
                w = comm.recv("w")
                grad = 2.0 * X.T @ (X @ w - y) / len(X)
                comm.send("w", grad)
                time.sleep(0.001)

        try:
            ts = [threading.Thread(target=worker, args=(s,))
                  for s in (1, 2)]
            [th.start() for th in ts]
            [th.join() for th in ts]
            comm.flush()
            err = float(np.abs(t.pull_dense() - W_true).max())
            assert err < 0.05, err
            assert t.get_version() > 100  # many merged async updates
        finally:
            comm.stop()

    def test_send_before_start_raises(self):
        from paddle1_tpu.core.errors import PreconditionNotMetError
        comm = AsyncCommunicator({"w": DenseTable((2,))})
        with pytest.raises(PreconditionNotMetError):
            comm.send("w", np.zeros(2, np.float32))


class TestGeoAsync:
    def test_staleness_bounded_and_converges(self):
        rng = np.random.default_rng(0)
        W_true = rng.standard_normal((4,)).astype(np.float32) * 0.5
        table = DenseTable((4,), seed=1)
        geo = GeoCommunicator({"w": table}, geo_k=5)
        w = geo.register("w")
        versions_at_sync = []
        max_lag = 0
        for step in range(100):
            X = rng.standard_normal((8, 4)).astype(np.float32)
            y = X @ W_true
            grad = 2.0 * X.T @ (X @ w - y) / len(X)
            w = w - 0.05 * grad          # LOCAL update (no PS traffic)
            lag_before = geo.steps_since_sync("w")
            w = geo.step("w", w)
            max_lag = max(max_lag, lag_before + 1)
            if geo.steps_since_sync("w") == 0:
                versions_at_sync.append(table.get_version())
        assert max_lag <= 5               # bounded staleness: geo_k
        # the PS only heard from us every geo_k steps
        assert len(versions_at_sync) == 100 // 5
        assert float(np.abs(w - W_true).max()) < 0.05

    def test_two_workers_deltas_compose(self):
        table = DenseTable((2,), seed=0)
        v0 = table.pull_dense()
        a = GeoCommunicator({"w": table}, geo_k=1)
        b = GeoCommunicator({"w": table}, geo_k=1)
        wa, wb = a.register("w"), b.register("w")
        a.step("w", wa + np.float32(1.0))
        b.step("w", wb + np.float32(2.0))  # pushes vs its OWN base
        np.testing.assert_allclose(table.pull_dense(), v0 + 3.0,
                                   rtol=1e-6)


WORKER = textwrap.dedent("""
    import os, sys, time
    import numpy as np
    sys.path.insert(0, os.environ["REPO"])
    from paddle1_tpu.distributed.communicator import AsyncCommunicator
    from paddle1_tpu.distributed.ps_server import RemoteTable

    seed = int(sys.argv[1])
    rt = RemoteTable(os.environ["PS_ENDPOINT"])
    comm = AsyncCommunicator({"w": (rt, "w")}, merge_num=2,
                             pull_interval=0.01).start()
    rng = np.random.default_rng(seed)
    W_true = np.arange(1, 6, dtype=np.float32).reshape(5, 1) / 5.0
    emb_ids = [seed * 10 + 1, seed * 10 + 2]
    for step in range(300):
        X = rng.standard_normal((16, 5)).astype(np.float32)
        y = X @ W_true
        w = comm.recv("w")
        grad = 2.0 * X.T @ (X @ w - y) / len(X)
        comm.send("w", grad)
        rows = rt.pull(emb_ids)              # sparse path on same port
        rt.push(emb_ids, 0.1 * rows)         # in-table sgd step
        time.sleep(0.001)
    comm.stop()
    w = comm.recv("w")
    print("FINAL_ERR", float(np.abs(w - W_true).max()))
""")


class TestTwoProcessDownpourDense:
    def test_two_worker_processes_train_dense_and_sparse(self):
        """VERDICT r4 item 5 'done' criterion: two real worker PROCESSES
        training dense (async Communicator) + sparse (pull/push) params
        through one PS endpoint, converging."""
        dense = {"w": DenseTable((5, 1), optimizer="sgd", lr=0.02,
                                 seed=1)}
        sparse = SparseTable(dim=3, optimizer="sgd", lr=1.0)
        srv = TableServer(sparse, aux_tables=dense).start()
        env = {k: v for k, v in os.environ.items()}
        env.update({"REPO": REPO, "PS_ENDPOINT": srv.endpoint,
                    "JAX_PLATFORMS": "cpu"})
        try:
            procs = [subprocess.Popen([sys.executable, "-c", WORKER,
                                       str(s)],
                                      env=env, stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE)
                     for s in (1, 2)]
            outs = [p.communicate(timeout=240) for p in procs]
            for p, (out, errtxt) in zip(procs, outs):
                assert p.returncode == 0, (out.decode(), errtxt.decode())
                err = float(out.decode().split("FINAL_ERR")[1])
                assert err < 0.1, (err, out.decode())
            W_true = np.arange(1, 6, dtype=np.float32).reshape(5, 1) / 5.0
            assert float(np.abs(dense["w"].pull_dense()
                                - W_true).max()) < 0.1
            # both workers' sparse rows were trained in-table
            assert len(sparse) == 4
            # gradient-ascent-by-0.1 rows moved away from init
            assert dense["w"].get_version() > 50
        finally:
            srv.stop()


class TestReviewRegressions:
    def test_load_state_dict_validates_shape_and_optimizer(self):
        src = DenseTable((4, 2), optimizer="adam")
        sd = src.state_dict()
        with pytest.raises(ValueError, match="shape"):
            DenseTable((2, 2), optimizer="adam").load_state_dict(sd)
        with pytest.raises(ValueError, match="optimizer"):
            DenseTable((4, 2), optimizer="sgd").load_state_dict(sd)

    def test_send_surfaces_dead_send_thread(self):
        from paddle1_tpu.core.errors import PreconditionNotMetError

        class Broken:
            RPC_METHODS = DenseTable.RPC_METHODS

            def pull_dense(self):
                return np.zeros(2, np.float32)

            def push_dense_grad(self, g):
                raise ConnectionError("ps is gone")

            def get_version(self):
                return 0

        comm = AsyncCommunicator({"w": Broken()}, send_queue_size=1,
                                 send_interval=0.001)
        comm._max_retries = 2
        comm.start()
        try:
            deadline = time.time() + 10
            with pytest.raises(PreconditionNotMetError, match="down"):
                while time.time() < deadline:
                    comm.send("w", np.zeros(2, np.float32))
                    time.sleep(0.01)
                raise TimeoutError("send never surfaced the dead thread")
        finally:
            comm._stop.set()
            for t in comm._threads:
                t.join(timeout=5)


class TestFleetPersistables:
    """fleet.save/load_persistables + save_inference_model parity."""

    def test_roundtrip_dense_and_tables(self, tmp_path):
        import paddle1_tpu as paddle
        import paddle1_tpu.distributed.fleet as fleet
        fleet.init()
        fleet.fleet.init_server(dim=4, dense_tables={"w": (2, 2)})
        tbl = fleet.fleet._server_table
        tbl.pull([1, 2, 3])
        fleet.fleet._server_dense["w"].push_dense_grad(
            np.ones((2, 2), np.float32))
        model = paddle.nn.Linear(3, 2)
        d = str(tmp_path / "ckpt")
        fleet.fleet.save_persistables(dirname=d, model=model)

        # mutate, then restore
        w_after = fleet.fleet._server_dense["w"].pull_dense().copy()
        fleet.fleet._server_dense["w"].push_dense_grad(
            np.ones((2, 2), np.float32))
        tbl.push([1], np.ones((1, 4), np.float32))
        fleet.fleet.load_persistables(dirname=d, model=model)
        np.testing.assert_allclose(
            fleet.fleet._server_dense["w"].pull_dense(), w_after)
        assert len(fleet.fleet._server_table) == 3

    def test_save_inference_model_gates_and_writes(self, tmp_path):
        import os
        import paddle1_tpu as paddle
        import paddle1_tpu.distributed.fleet as fleet
        from paddle1_tpu.jit import InputSpec
        from paddle1_tpu.core.errors import PreconditionNotMetError
        fleet.init()
        with pytest.raises(PreconditionNotMetError, match="input_spec"):
            fleet.fleet.save_inference_model(dirname=str(tmp_path))
        m = paddle.nn.Linear(4, 2)
        fleet.fleet.save_inference_model(
            dirname=str(tmp_path / "sim"), model=m,
            input_spec=[InputSpec([1, 4], "float32", "x")])
        assert os.path.exists(str(tmp_path / "sim" / "model.pdmodel"))
