"""Out-of-core file datasets (VERDICT r2 missing item 3): InMemoryDataset
load/shuffle semantics, shared-filesystem global shuffle covering all
trainers disjointly, QueueDataset streaming with bounded memory, and the
pipe_command filter. Reference fluid/dataset.py + data_feed.cc roles."""

import os
import threading
import time

import numpy as np
import pytest

from paddle1_tpu.io import (DataLoader, DatasetFactory, InMemoryDataset,
                            QueueDataset)


@pytest.fixture()
def files(tmp_path):
    paths = []
    v = 0
    for i in range(4):
        p = tmp_path / f"part-{i}.txt"
        lines = []
        for _ in range(25):
            lines.append(f"{v} {v + 0.5}")
            v += 1
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))
    return paths  # 100 samples total, sample j = [j, j+0.5]


class TestInMemoryDataset:
    def test_factory_and_load(self, files):
        ds = DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        ds.load_into_memory()
        assert len(ds) == 100
        assert ds.get_memory_data_size() == 100
        np.testing.assert_allclose(ds[7], [7.0, 7.5])
        ds.release_memory()
        assert len(ds) == 0

    def test_file_sharding_two_trainers(self, files):
        sizes = []
        for rank in range(2):
            ds = InMemoryDataset()
            ds.set_filelist(files)
            ds.set_rank_world(rank, 2)
            ds.load_into_memory()
            sizes.append(len(ds))
        assert sizes == [50, 50]

    def test_local_shuffle(self, files):
        ds = InMemoryDataset()
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        ds.load_into_memory()
        before = [float(ds[i][0]) for i in range(100)]
        ds.local_shuffle(seed=0)
        after = [float(ds[i][0]) for i in range(100)]
        assert sorted(after) == sorted(before) and after != before

    def test_global_shuffle_disjoint_cover(self, files):
        """Every trainer's shard after global_shuffle: union = corpus,
        pairwise disjoint, and genuinely shuffled."""
        shards = []
        for rank in range(4):
            ds = InMemoryDataset()
            ds.set_filelist(files)
            ds.set_rank_world(rank, 4)
            ds.global_shuffle(seed=7)
            assert ds.get_shuffle_data_size() == len(ds) == 25
            shards.append({float(s[0]) for s in ds._samples})
        union = set().union(*shards)
        assert union == {float(i) for i in range(100)}
        for a in range(4):
            for b in range(a + 1, 4):
                assert not (shards[a] & shards[b])

    def test_dataloader_integration(self, files):
        ds = InMemoryDataset()
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        ds.load_into_memory()
        loader = DataLoader(ds, batch_size=10, shuffle=False)
        batches = list(loader)
        assert len(batches) == 10
        assert list(batches[0].shape) == [10, 2]

    def test_pipe_command_filter(self, files):
        ds = InMemoryDataset()
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        ds.set_pipe_command("grep -v '^1 '")   # drop sample 1
        ds.load_into_memory()
        vals = {float(s[0]) for s in ds._samples}
        assert 1.0 not in vals and len(ds) == 99

    def test_pipe_command_failure_raises(self, files):
        from paddle1_tpu.core.errors import PreconditionNotMetError
        ds = InMemoryDataset()
        ds.set_filelist(files[:1])
        ds.set_rank_world(0, 1)
        ds.set_pipe_command("false")
        with pytest.raises(PreconditionNotMetError):
            ds.load_into_memory()


class TestQueueDataset:
    def test_streams_all_samples(self, files):
        ds = QueueDataset()
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        got = [float(s[0]) for s in ds]
        assert got == [float(i) for i in range(100)]

    def test_bounded_memory(self, files):
        """The reader must BLOCK at queue capacity — out-of-core, not a
        hidden load_into_memory."""
        parsed = []

        def counting_parse(line):
            parsed.append(1)
            parts = line.split()
            return np.asarray([float(p) for p in parts], np.float32)

        ds = QueueDataset(capacity=8)
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        ds.set_parse_fn(counting_parse)
        it = iter(ds)
        next(it)
        time.sleep(0.3)  # give the reader thread time to run ahead
        # reader can be at most capacity + in-flight ahead of the consumer
        assert len(parsed) <= 8 + 2, len(parsed)
        rest = sum(1 for _ in it)
        assert rest == 99 and len(parsed) == 100

    def test_parse_error_propagates(self, files):
        ds = QueueDataset()
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)

        def bad_parse(line):
            raise ValueError("boom")

        ds.set_parse_fn(bad_parse)
        with pytest.raises(ValueError):
            for _ in ds:
                pass

    def test_custom_parse_drops_none(self, files):
        ds = QueueDataset()
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        ds.set_parse_fn(lambda l: None if l.startswith("2 ")
                        else np.float32(l.split()[0]))
        got = [float(s) for s in ds]
        assert 2.0 not in got and len(got) == 99

    def test_early_break_releases_reader(self, files):
        """Review finding: breaking out of iteration must not leave the
        reader thread blocked on a full queue forever."""
        before = threading.active_count()
        for _ in range(5):
            ds = QueueDataset(capacity=4)
            ds.set_filelist(files)
            ds.set_rank_world(0, 1)
            for i, _s in enumerate(ds):
                if i == 2:
                    break   # abandons the iterator mid-stream
        time.sleep(0.5)
        assert threading.active_count() <= before + 1, (
            "reader threads leaked after early break")

    def test_factory_unknown_raises(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError):
            DatasetFactory().create_dataset("NopeDataset")
