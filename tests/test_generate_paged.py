"""Decode economics (ISSUE 16): block-paged KV cache with
copy-on-write prefix sharing, speculative decoding, and the int8
decode path.

The acceptance contracts pinned here:

* paged decode is BIT-identical to the dense slot cache (greedy and
  sampled), over one compiled decode signature (page faults, ragged
  arrivals, and speculative steps never retrace);
* prefix-shared prompts store their prefill pages once, cohabitants
  stay bit-identical through wedges/cancels/releases, and refcounts
  prove who holds what;
* cancel and mid-stream deadline release KV pages in the SAME
  scheduler tick (drain reports ``kv_pages_owed == 0`` under load);
* speculation is pure upside: greedy AND sampled output bit-identical
  to non-speculative decode whatever the drafts, with the acceptance
  ratio/counters exposed;
* the int8 artifact pass quantizes decode matmul weights per channel
  with bounded reconstruction error, inside the same single decode
  executable.
"""

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core import chaos, health
from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.core.flags import flags_guard
from paddle1_tpu.serving import (PARKING_PAGE, CausalLM, GenerationEngine,
                                 GenerationServer, KVPageAccountingError,
                                 KVPoolExhausted, NGramSpeculator,
                                 PagePool, SlotWedged)
from paddle1_tpu.serving.speculate import DraftModelSpeculator

VOCAB, MAX_SEQ, SLOTS, PS = 32, 64, 4, 8


@pytest.fixture(autouse=True)
def _isolate():
    health.reset()
    chaos.reset()
    yield
    health.reset()
    chaos.reset()


@pytest.fixture(scope="module")
def lm():
    paddle.seed(7)
    return CausalLM(vocab_size=VOCAB, d_model=16, nhead=2,
                    dim_feedforward=32, num_layers=2, max_seq=MAX_SEQ)


@pytest.fixture(scope="module")
def dense(lm):
    return GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                            prefill_buckets=(8, 24))


@pytest.fixture(scope="module")
def paged(lm):
    return GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                            prefill_buckets=(8, 24), paged=True,
                            page_size=PS, prefix_cache=8)


def _run(eng, slot, prompt, steps, temperature=0.0, top_k=0, seed=1):
    """prefill + ``steps`` single-slot decode steps -> token list."""
    out = [eng.prefill(slot, np.asarray(prompt, np.int32),
                       temperature, top_k, seed)]
    active = np.zeros([eng.slots], bool)
    active[slot] = True
    for _ in range(steps):
        toks, flags = eng.decode(active)
        out.append(int(toks[slot, 0]))
    eng.release(slot)
    return out


# ---------------------------------------------------------------------------
# page pool (host accounting unit)


class TestPagePool:
    def test_parking_page_reserved(self):
        pool = PagePool(4, PS)
        assert PARKING_PAGE not in pool.alloc(3)
        with pytest.raises(KVPoolExhausted, match="exhausted"):
            pool.alloc(1)

    def test_refcount_release_roundtrip(self):
        pool = PagePool(5, PS)
        pages = pool.alloc(2)
        pool.retain(pages)
        pool.release(pages)
        assert pool.pages_in_use == 2      # still held once
        pool.release(pages)
        assert pool.pages_in_use == 0 and pool.free_pages == 4

    def test_over_release_is_an_accounting_bug(self):
        # the double-release guard raises TYPED, and BEFORE mutating:
        # a page appended to the free list twice would be handed to two
        # holders and silently cross-write their KV
        pool = PagePool(3, PS)
        [p] = pool.alloc(1)
        pool.release([p])
        with pytest.raises(KVPageAccountingError, match="over-released"):
            pool.release([p])
        # the failed release corrupted nothing: the free list still
        # holds the page exactly once and the invariants all pass
        assert pool.free_pages == 2 and pool.pages_in_use == 0
        pool.check_invariants()

    def test_prefix_registry_hit_and_refs(self):
        pool = PagePool(8, 4, prefix_entries=4)
        prompt = np.arange(9, dtype=np.int32)     # 2 full pages + 1
        chain = pool.alloc(3)
        pool.register_prefix(prompt, chain)
        hit = pool.lookup_prefix(np.concatenate(
            [prompt[:8], [30, 31]]).astype(np.int32))
        assert hit == chain[:2]                    # full pages only
        # holders now: slot(1) + registry(len-1 and len-2 chains) + hit
        assert pool.refcount(chain[0]) == 4
        assert pool.refcount(chain[2]) == 1        # tail never shared

    def test_lru_eviction_under_pressure(self):
        pool = PagePool(4, 2, prefix_entries=8)
        a = pool.alloc(2)
        pool.register_prefix(np.array([1, 2], np.int32), a[:1])
        pool.register_prefix(np.array([3, 4], np.int32), a[1:])
        pool.release(a)                            # only registry holds
        got = pool.alloc(3)                        # forces both evicted
        assert len(got) == 3 and pool.stats()["evictions"] == 2

    def test_needs_room_for_parking(self):
        with pytest.raises(ValueError, match="parking"):
            PagePool(1, PS)


class TestInvariantChecker:
    """``check_invariants`` (FLAGS_debug_kv_refcount's engine): the
    refcount ledger must equal registry + holder chains exactly, and
    every way it can lie raises typed."""

    def test_clean_pool_passes(self):
        pool = PagePool(8, 4, prefix_entries=4)
        pool.check_invariants()
        chain = pool.alloc(3)
        prompt = np.arange(9, dtype=np.int32)
        pool.register_prefix(prompt, chain)
        pool.check_invariants(holders=[chain])
        pool.release(chain)                 # slot's refs gone
        pool.check_invariants()             # registry still holds 1..2

    def test_unreported_holder_raises(self):
        # pages held by a slot the caller didn't report = the ledger
        # and reality disagree — typed, with the page named
        pool = PagePool(6, 4)
        chain = pool.alloc(2)
        with pytest.raises(KVPageAccountingError, match="refcount"):
            pool.check_invariants()         # holders omitted
        pool.check_invariants(holders=[chain])

    def test_corrupt_free_list_raises(self):
        pool = PagePool(6, 4)
        pool.alloc(2)
        pool._free.append(pool._free[0])    # simulate a double-free
        with pytest.raises(KVPageAccountingError, match="duplicate"):
            pool.check_invariants()

    def test_parking_page_leak_raises(self):
        pool = PagePool(6, 4)
        pool._free.append(PARKING_PAGE)
        with pytest.raises(KVPageAccountingError, match="parking"):
            pool.check_invariants()


class TestCOWRegistryLifecycle:
    """Eviction vs live holders — the copy-on-write registry's whole
    lifecycle matrix: an entry evicted while its pages are SHARED must
    keep them alive for the current holders, and only the LAST release
    returns them to the free list."""

    def test_evicted_while_shared_keeps_pages_for_holders(self):
        pool = PagePool(8, 4, prefix_entries=2)
        prompt = np.arange(8, dtype=np.int32)      # 2 full pages
        chain = pool.alloc(2)
        pool.register_prefix(prompt, chain)
        # a second "request" comes in over the same prefix
        held = pool.lookup_prefix(prompt)
        assert held == chain
        # evict everything the registry holds (pressure simulation)
        while pool._evict_one():
            pass
        assert pool.stats()["prefix_entries"] == 0
        # the holder's pages survived the eviction: refcounts are the
        # holder chains only (original alloc + lookup retain)
        for p in chain:
            assert pool.refcount(p) == 2
        pool.check_invariants(holders=[chain, held])
        # a NEW lookup misses (the registry forgot the prefix)...
        assert pool.lookup_prefix(prompt) == []
        # ...but the live streams keep decoding on their pages
        pool.release(held)
        for p in chain:
            assert pool.refcount(p) == 1           # still alive
        assert pool.free_pages == 5
        pool.release(chain)                        # LAST holder out
        assert pool.free_pages == 7                # only now reaped
        pool.check_invariants()

    def test_release_order_is_irrelevant(self):
        # same matrix, releases interleaved the other way round:
        # registry evicts LAST, after both holders released
        pool = PagePool(8, 4, prefix_entries=2)
        prompt = np.arange(8, dtype=np.int32)
        chain = pool.alloc(2)
        pool.register_prefix(prompt, chain)
        held = pool.lookup_prefix(prompt)
        pool.release(chain)
        pool.release(held)
        # only the registry holds the pages now — they are CACHED, not
        # free, and a hit revives them without allocation
        assert pool.free_pages == 5
        assert pool.stats()["pages_cached"] == 2
        revived = pool.lookup_prefix(prompt)
        assert revived == chain
        pool.release(revived)
        while pool._evict_one():
            pass
        assert pool.free_pages == 7                # reaped on last ref
        pool.check_invariants()


# ---------------------------------------------------------------------------
# paged <-> dense parity (the tentpole gate)


class TestPagedParity:
    # prompt lengths straddle the page boundary: P % page_size == 0 is
    # the all-pages-full edge where the first decode write must land in
    # a freshly faulted page
    @pytest.mark.parametrize("plen", [3, PS - 1, PS, PS + 3, 2 * PS])
    def test_greedy_bit_identical(self, dense, paged, plen):
        prompt = (np.arange(plen) % VOCAB).astype(np.int32)
        assert _run(dense, 0, prompt, 12) == _run(paged, 0, prompt, 12)

    @pytest.mark.parametrize("temp,top_k", [(0.8, 5), (1.3, 0)])
    def test_sampled_bit_identical(self, dense, paged, temp, top_k):
        prompt = np.array([5, 1, 9, 2, 7], np.int32)
        a = _run(dense, 1, prompt, 10, temp, top_k, seed=11)
        b = _run(paged, 1, prompt, 10, temp, top_k, seed=11)
        assert a == b

    def test_one_decode_compile_across_faults_and_ragged(self, paged):
        before = paged.decode_compile_count
        # long decode crosses page boundaries (faults), then a second
        # ragged arrival joins mid-flight — same executable throughout
        p1 = paged.prefill(0, np.array([1, 2, 3], np.int32), 0.0, 0, 1)
        active = np.array([True, False, False, False])
        for _ in range(PS + 2):
            paged.decode(active)
        paged.prefill(2, (np.arange(17) % VOCAB).astype(np.int32),
                      0.7, 4, 5)
        active[2] = True
        for _ in range(4):
            paged.decode(active)
        paged.release(0)
        paged.release(2)
        assert paged.decode_compile_count == max(before, 1) == 1
        assert p1 is not None

    def test_kernel_vs_ref_routing(self, lm):
        # the Pallas gather (interpret mode on CPU) and the XLA take
        # composition agree numerically on the same pools
        import jax
        from paddle1_tpu.ops.pallas import paged_attention as pa
        k = jax.random.split(jax.random.key(0), 4)
        S, W, H, D, NP = 3, 1, 2, 8, 5
        q = jax.random.normal(k[0], (S, W, H, D), "float32")
        kp = jax.random.normal(k[1], (NP, PS, H, D), "float32")
        vp = jax.random.normal(k[2], (NP, PS, H, D), "float32")
        table = np.array([[1, 2], [3, 0], [4, 1]], np.int32)
        base = np.array([9, 5, 12], np.int32)
        ref = pa.paged_attention_ref(q, kp, vp, table, base)
        assert pa.supported(q.shape, kp.shape)
        out = pa.paged_attention(q, kp, vp, table, base)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_paged_needs_paged_cache_contract(self):
        class NoPaged:
            def gen_slot_cache(self, *a, **k):
                raise NotImplementedError
        with pytest.raises(InvalidArgumentError, match="gen_paged_cache"):
            GenerationEngine(NoPaged(), slots=2, max_seq=8, paged=True)


# ---------------------------------------------------------------------------
# copy-on-write prefix sharing


class TestPrefixSharing:
    PREFIX = (np.arange(2 * PS) % VOCAB).astype(np.int32)

    def test_shared_prefill_pages_stored_once(self, lm, dense):
        eng = GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                               prefill_buckets=(24,), paged=True,
                               page_size=PS, prefix_cache=8)
        pA = np.concatenate([self.PREFIX, [7, 9]]).astype(np.int32)
        pB = np.concatenate([self.PREFIX, [11, 3]]).astype(np.int32)
        tA = eng.prefill(0, pA, 0.0, 0, 1)
        in_use_after_A = eng.pool.stats()["pages_in_use"]
        tB = eng.prefill(1, pB, 0.0, 0, 2)
        st = eng.pool.stats()
        # B reused both full prefix pages; only its private tail page
        # is new
        assert st["prefix_hit_pages"] == 2
        assert st["pages_in_use"] == in_use_after_A + 1
        shared = eng._slot_pages[0][:2]
        assert eng._slot_pages[1][:2] == shared
        assert eng._slot_pages[1][2] != eng._slot_pages[0][2]
        # both cohabitants bit-identical to the dense oracle
        seq = {0: [tA], 1: [tB]}
        for _ in range(6):
            toks, _ = eng.decode(np.array([True, True, False, False]))
            seq[0].append(int(toks[0, 0]))
            seq[1].append(int(toks[1, 0]))
        assert seq[0] == _run(dense, 0, pA, 6)
        assert seq[1] == _run(dense, 1, pB, 6)
        # releasing A leaves B + the registry holding the prefix
        eng.release(0)
        for p in shared:
            assert eng.pool.refcount(p) >= 2
        before = seq[1][-1]
        toks, _ = eng.decode(np.array([False, True, False, False]))
        assert toks.shape[0] == SLOTS and before is not None
        eng.release(1)

    def test_wedge_during_shared_prefix_decode(self, lm):
        # satellite: chaos wedge while two requests share prefix pages
        # — the survivor stays bit-identical AND the wedged slot's page
        # refs drop the same tick
        eng = GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                               prefill_buckets=(24,), paged=True,
                               page_size=PS, prefix_cache=8)
        prompt = list(self.PREFIX[:12])
        srv = GenerationServer(eng, token_budget=12).start()
        ref = srv.submit(prompt + [7], max_new_tokens=10).result(
            timeout=120)
        srv.drain()
        chaos.configure("gen_slot_wedge@3:1")
        srv = GenerationServer(eng, token_budget=12).start()
        a = srv.submit(prompt + [7], max_new_tokens=10)   # slot 0
        b = srv.submit(prompt + [9], max_new_tokens=10)   # slot 1: wedged
        got_a = a.result(timeout=120)
        with pytest.raises(SlotWedged):
            b.result(timeout=120)
        rep = srv.drain()
        assert got_a == ref                 # cohabitant bit-identical
        assert eng._slot_pages[1] == []     # wedged slot's pages gone
        assert rep["kv_pages_owed"] == 0
        assert rep["unaccounted"] == 0

    def test_warmup_does_not_pollute_prefix_registry(self, lm):
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), paged=True,
                               page_size=PS, prefix_cache=8)
        eng.warm_up()
        st = eng.pool.stats()
        assert st["prefix_entries"] == 0 and st["pages_in_use"] == 0


# ---------------------------------------------------------------------------
# page lifecycle: cancel / deadline / exhaustion / drain


class TestPageLifecycle:
    def test_cancel_releases_pages_same_tick(self, lm):
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), paged=True,
                               page_size=PS, prefix_cache=0)
        srv = GenerationServer(eng, token_budget=60).start()
        st = srv.submit([1, 2, 3], max_new_tokens=60)
        it = iter(st)
        next(it)
        assert eng.pool.stats()["pages_in_use"] > 0
        st.cancel()
        with pytest.raises(Exception):
            st.result(timeout=120)
        rep = srv.drain()
        # release happened in the tick that retired the stream — by
        # drain time nothing is owed and the slot chain is empty
        assert eng._slot_pages[0] == []
        assert eng.pool.stats()["pages_in_use"] == 0
        assert rep["kv_pages_owed"] == 0

    def test_deadline_midstream_releases_pages(self, lm):
        from paddle1_tpu.serving import DeadlineExceeded
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), paged=True,
                               page_size=PS, prefix_cache=0)
        chaos.configure("gen_slow_step@2")
        with flags_guard(serve_chaos_slow_s=0.4):
            srv = GenerationServer(eng, token_budget=100).start()
            st = srv.submit([1, 2], max_new_tokens=100, deadline_ms=150)
            with pytest.raises(DeadlineExceeded, match="mid-stream"):
                st.result(timeout=120)
            rep = srv.drain()
        assert eng._slot_pages[0] == []
        assert rep["kv_pages_owed"] == 0
        assert rep["deadline_failed"] == 1

    def test_prefill_pool_exhaustion_typed(self, lm):
        # 3 usable pages, prompts need 2 each: the second admit fails
        # typed and the first request is untouched
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(16,), paged=True,
                               page_size=PS, pages=4, prefix_cache=0)
        p = (np.arange(2 * PS - 2) % VOCAB).astype(np.int32)
        eng.prefill(0, p, 0.0, 0, 1)
        with pytest.raises(KVPoolExhausted, match="exhausted"):
            eng.prefill(1, (p + 1) % VOCAB, 0.0, 0, 2)
        assert eng._slot_pages[1] == []    # nothing half-claimed
        eng.release(0)
        assert eng.pool.stats()["pages_in_use"] == 0

    def test_decode_page_fault_exhaustion_fails_only_that_slot(self, lm):
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), paged=True,
                               page_size=PS, pages=4, prefix_cache=0)
        # slot 0: 6 prompt tokens (1 page); slot 1: 7 (1 page); one
        # spare page — the first slot to fault claims it, the next
        # fault finds the pool dry
        t0 = eng.prefill(0, np.arange(6, dtype=np.int32), 0.0, 0, 1)
        t1 = eng.prefill(1, np.arange(7, dtype=np.int32), 0.0, 0, 2)
        active = np.array([True, True])
        faulted = None
        for _ in range(2 * PS):
            toks, flags = eng.decode(active)
            if eng.last_page_faults:
                faulted = dict(eng.last_page_faults)
                break
        assert faulted is not None
        (slot, exc), = faulted.items()
        assert isinstance(exc, KVPoolExhausted)
        # the faulted slot produced nothing that step; the other did
        assert not flags[slot].any()
        other = 1 - slot
        assert flags[other].any()
        assert t0 is not None and t1 is not None
        eng.release(0)
        eng.release(1)

    def test_prefill_failure_releases_shared_prefix_refs(self, lm):
        # exception-path audit: _alloc_prefill_pages retains shared
        # prefix pages BEFORE allocating private ones — when the
        # private alloc raises, the retained refs must be handed back
        # (exactly what was taken), or the prefix pages leak a ref per
        # failed admission forever
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(8, 40), paged=True,
                               page_size=PS, pages=4, prefix_cache=4)
        shared = (np.arange(PS) % VOCAB).astype(np.int32)  # 1 full page
        eng.prefill(0, shared, 0.0, 0, 1)
        eng.release(0)                     # page survives in registry
        assert eng.pool.stats()["pages_cached"] == 1
        # same prefix + a long tail: hits the cached page (one ref
        # RETAINED for the slot), then needs 3 private pages from a
        # pool with 2 free — the private alloc raises, and the retained
        # prefix ref must be handed back
        big = np.concatenate([shared,
                              (np.arange(3 * PS) + 3) % VOCAB]
                             ).astype(np.int32)
        with pytest.raises(KVPoolExhausted):
            eng.prefill(1, big, 0.0, 0, 2)
        assert eng._slot_pages[1] == []    # nothing half-claimed
        # every ref the failed admission took was released — a leaked
        # retain would leave pages_in_use > 0 with no holder, which the
        # invariant sweep (refcounts == registry + slot chains) catches
        assert eng.pool.stats()["pages_in_use"] == 0
        eng.check_kv_invariants()

    def test_debug_refcount_asserted_every_scheduler_tick(self, lm):
        # FLAGS_debug_kv_refcount: the scheduler sweeps the invariant
        # checker after EVERY tick — admissions, releases, prefix hits
        # and drains all run under it without tripping
        with flags_guard(debug_kv_refcount=True):
            eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                                   prefill_buckets=(8,), paged=True,
                                   page_size=PS, prefix_cache=4)
            srv = GenerationServer(eng, queue_depth=16, token_budget=6)
            srv.start()
            streams = [srv.submit([1 + i % 3, 2, 3], max_new_tokens=6)
                       for i in range(6)]
            rep = srv.drain(timeout=120)
        assert all(s.done() for s in streams)
        assert rep["fatal"] is None        # a checker trip kills the loop
        assert rep["unaccounted"] == 0 and rep["kv_pages_owed"] == 0

    def test_drain_under_load_owes_no_pages(self, lm):
        eng = GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), paged=True,
                               page_size=PS, prefix_cache=4)
        srv = GenerationServer(eng, queue_depth=64, token_budget=5)
        srv.start()
        streams = [srv.submit([1 + i % 5, 2], max_new_tokens=5)
                   for i in range(10)]
        rep = srv.drain(timeout=120)
        assert all(s.done() for s in streams)
        assert rep["kv_pages_owed"] == 0
        assert rep["unaccounted"] == 0 and rep["tokens_owed"] == 0

    def test_oversize_prompt_margin_typed(self, lm):
        eng = GenerationEngine(lm, slots=2, max_seq=16, spec_tokens=3)
        with pytest.raises(InvalidArgumentError, match="margin"):
            eng.prefill(0, np.arange(13, dtype=np.int32), 0.0, 0, 1)


# ---------------------------------------------------------------------------
# HBM census coverage (satellite: the page pool is accounted)


class TestCensusCoverage:
    def test_kv_subsystem_covers_page_pool(self, lm):
        from paddle1_tpu.obs import hbm as obs_hbm
        obs_hbm.reset()
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), paged=True,
                               page_size=PS, prefix_cache=0)
        per = obs_hbm.registered_bytes()
        pool_bytes = sum(
            k.size * k.dtype.itemsize + v.size * v.dtype.itemsize
            for k, v in eng._kv)
        assert per["kv_cache"] >= pool_bytes
        assert per["params"] > 0
        obs_hbm.reset()

    def test_census_coverage_with_paged_engine_subprocess(self, tmp_path):
        # a clean process where the ONLY device state is the paged
        # engine: census coverage must be complete (the page pools and
        # table are registered, not leaked into unaccounted bytes)
        import os
        import subprocess
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        code = (
            "import sys\n"
            f"sys.path.insert(0, {root!r})\n"
            "import numpy as np\n"
            "import paddle1_tpu as paddle\n"
            "from paddle1_tpu.obs import hbm\n"
            "from paddle1_tpu.serving import CausalLM, GenerationEngine\n"
            "paddle.seed(0)\n"
            "lm = CausalLM(vocab_size=32, d_model=16, nhead=2,\n"
            "              num_layers=2, max_seq=64)\n"
            "eng = GenerationEngine(lm, slots=2, max_seq=64,\n"
            "                       prefill_buckets=(8,), paged=True,\n"
            "                       page_size=8)\n"
            "eng.prefill(0, np.arange(5, dtype=np.int32), 0.0, 0, 1)\n"
            "eng.decode(np.array([True, False]))\n"
            "c = hbm.census()\n"
            "print('COVERAGE', c['coverage_ratio'])\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr
        cov = float(r.stdout.split("COVERAGE")[1].split()[0])
        assert cov >= 0.95, (cov, r.stdout)


# ---------------------------------------------------------------------------
# speculative decoding


def _spec_run(eng, prompt, steps, temperature=0.0, top_k=0, seed=1):
    """prefill + n-gram speculative decode on slot 0 until ``steps``
    generated tokens -> (token list, dispatch count)."""
    out = [eng.prefill(0, prompt, temperature, top_k, seed)]
    sp = NGramSpeculator(prompt, eng.spec_tokens, n=3)
    sp.observe(out[0])
    active = np.array([True, False])
    dispatches = 0
    while len(out) < steps + 1:
        d = sp.propose()
        drafts = np.zeros([2, eng.spec_tokens], np.int32)
        nd = np.zeros([2], np.int32)
        nd[0] = d.size
        drafts[0, :d.size] = d
        toks, flags = eng.decode(active, drafts, nd)
        dispatches += 1
        for i in range(int(flags[0].sum())):
            sp.observe(int(toks[0, i]))
            out.append(int(toks[0, i]))
    eng.release(0)
    return out[:steps + 1], dispatches


class TestSpeculation:
    PROMPT = np.array([1, 2, 3, 4] * 3, np.int32)

    @pytest.fixture(scope="class")
    def spec(self, lm):
        return GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                                prefill_buckets=(16,), spec_tokens=4)

    def _spec_run(self, eng, prompt, steps, temperature=0.0, top_k=0,
                  seed=1):
        return _spec_run(eng, prompt, steps, temperature, top_k, seed)

    def test_greedy_bit_identical_to_nonspec(self, dense, spec):
        ref = _run(dense, 0, self.PROMPT, 15)
        got, _ = self._spec_run(spec, self.PROMPT, 15)
        assert got == ref

    @pytest.mark.parametrize("temp,top_k", [(0.8, 5), (1.2, 0)])
    def test_sampled_bit_identical_to_nonspec(self, dense, spec, temp,
                                              top_k):
        # stronger than a distribution test: the per-request key
        # schedule advances per ACCEPTED token, so even sampled output
        # is bit-equal whatever the speculator proposed
        ref = _run(dense, 0, self.PROMPT, 12, temp, top_k, seed=9)
        got, _ = self._spec_run(spec, self.PROMPT, 12, temp, top_k,
                                seed=9)
        assert got == ref

    def test_wrong_drafts_cost_nothing_but_width(self, dense, spec):
        # adversarial speculator: propose garbage every step — output
        # must STILL match non-speculative decode exactly
        ref = _run(dense, 0, self.PROMPT, 8)
        out = [spec.prefill(0, self.PROMPT, 0.0, 0, 1)]
        drafts = np.full([2, 4], VOCAB - 1, np.int32)
        nd = np.array([4, 0], np.int32)
        while len(out) < 9:
            toks, flags = spec.decode(np.array([True, False]),
                                      drafts, nd)
            for i in range(int(flags[0].sum())):
                out.append(int(toks[0, i]))
        spec.release(0)
        assert out[:9] == ref

    def test_repetitive_arm_accepts_and_compresses_dispatches(self):
        # the economics arm: on cyclic text the n-gram speculator's
        # acceptance clears 70% and dispatches collapse by > 1.8x
        paddle.seed(7)
        lm = CausalLM(vocab_size=VOCAB, d_model=16, nhead=2,
                      num_layers=2, max_seq=256)
        for _, t in lm.state_dict().items():
            t._data = t.data * 0          # degenerate fixed point:
        eng = GenerationEngine(lm, slots=2, max_seq=256,  # cyclic output
                               prefill_buckets=(16,), spec_tokens=4)
        prompt = np.array([1, 2, 3, 4] * 3, np.int32)
        out, dispatches = self._spec_run(eng, prompt, 60)
        # 60 tokens in far fewer than 60 dispatches
        assert dispatches <= 60 / 1.8
        assert len(set(out[4:])) == 1      # the cycle the drafts rode
        assert eng.decode_compile_count == 1

    def test_spec_metrics_via_server(self, lm):
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(16,), spec_tokens=4)
        srv = GenerationServer(eng, token_budget=12).start()
        got = srv.submit(list(self.PROMPT),
                         max_new_tokens=12).result(timeout=120)
        snap = srv.metrics.snapshot()
        rep = srv.drain()
        assert len(got) == 12
        c = snap["counters"]
        assert c.get("gen_spec_proposed_total", 0) > 0
        assert "gen_spec_accept_ratio" in snap["gauges"]
        assert rep["decode_compiles"] == 1

    def test_draft_model_speculator_protocol(self):
        sp = DraftModelSpeculator([1, 2, 3], 3,
                                  lambda hist, k: hist[-1:] * k)
        sp.observe(9)
        assert list(sp.propose()) == [9, 9, 9]

    def test_ngram_prefers_full_window(self):
        sp = NGramSpeculator([7, 7, 7, 7, 7, 7, 7, 7], 4, n=3)
        assert list(sp.propose()) == [7, 7, 7, 7]
        fresh = NGramSpeculator([1, 2, 3], 4, n=3)
        assert fresh.propose().size == 0

    def test_window_margin_validated(self, lm):
        with pytest.raises(InvalidArgumentError, match="window"):
            GenerationEngine(lm, slots=2, max_seq=4, spec_tokens=4)


@pytest.mark.slow
class TestSpeculationParityMatrix:
    """CI generate-lane matrix (ISSUE 16 satellite): speculation is
    pure upside across every sampling mode x window width — greedy
    EXACT, and sampled exact too (the per-request key schedule advances
    per ACCEPTED token, so even temperature/top-k chains are bit-equal
    to non-speculative decode), all over one compiled signature."""

    PROMPT = np.array([1, 2, 3, 4] * 3, np.int32)
    CASES = [(0.0, 0, 1), (0.0, 0, 7), (0.7, 4, 3), (0.7, 0, 11),
             (1.0, 8, 5), (1.3, 3, 2)]

    @pytest.fixture(scope="class")
    def engines(self, lm):
        return {k: GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                                    prefill_buckets=(16,),
                                    spec_tokens=k) for k in (2, 4)}

    @pytest.mark.parametrize("k", [2, 4])
    @pytest.mark.parametrize("temp,top_k,seed", CASES)
    def test_parity(self, dense, engines, k, temp, top_k, seed):
        ref = _run(dense, 0, self.PROMPT, 14, temp, top_k, seed)
        got, _ = _spec_run(engines[k], self.PROMPT, 14, temp, top_k,
                           seed)
        assert got == ref
        assert engines[k].decode_compile_count == 1

    @pytest.mark.parametrize("temp,top_k,seed", [(0.0, 0, 1),
                                                 (0.9, 6, 4)])
    def test_parity_with_paged_kv(self, lm, dense, temp, top_k, seed):
        # the full economics stack: speculation over the paged cache
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(16,), paged=True,
                               page_size=PS, spec_tokens=4)
        ref = _run(dense, 0, self.PROMPT, 14, temp, top_k, seed)
        got, _ = _spec_run(eng, self.PROMPT, 14, temp, top_k, seed)
        assert got == ref
        assert eng.decode_compile_count == 1
        st = eng.pool.stats()     # owed == 0 (prefix cache stays warm)
        assert st["pages_in_use"] == st["pages_cached"]


# ---------------------------------------------------------------------------
# int8 decode path


class TestInt8Decode:
    def test_quantize_reconstruction_bounded(self):
        from paddle1_tpu.quantization import (dequantize_weights,
                                              quantize_weights_int8)
        rng = np.random.default_rng(0)
        w = rng.standard_normal((32, 16)).astype(np.float32)
        params = {"layers.0.fc.weight": w,
                  "embed.weight": rng.standard_normal(
                      (8, 4)).astype(np.float32),
                  "layers.0.fc.bias": np.zeros(16, np.float32)}
        q = quantize_weights_int8(params)
        from paddle1_tpu.quantization import QuantTensor
        assert isinstance(q["layers.0.fc.weight"], QuantTensor)
        assert not isinstance(q["embed.weight"], QuantTensor)  # skipped
        assert not isinstance(q["layers.0.fc.bias"], QuantTensor)
        deq = dequantize_weights(q)
        scale = np.asarray(q["layers.0.fc.weight"].scale)
        err = np.abs(np.asarray(deq["layers.0.fc.weight"]) - w)
        # per-channel rounding bound: half a quantization step
        assert (err <= 0.5 * scale[None, :] + 1e-7).all()

    def test_int8_engine_greedy_matches_f32(self, lm, dense):
        eng = GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), int8=True)
        prompt = np.array([3, 1, 4, 1, 5], np.int32)
        assert _run(eng, 0, prompt, 10) == _run(dense, 0, prompt, 10)
        assert eng.decode_compile_count == 1

    def test_int8_halves_weight_bytes(self, lm):
        from paddle1_tpu.quantization import QuantTensor
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(8,), int8=True)
        quant = [v for v in eng._params.values()
                 if isinstance(v, QuantTensor)]
        assert quant, "no decode matmul weights were quantized"
        q_bytes = sum(v.q.size + v.scale.size * 4 for v in quant)
        f_bytes = sum(v.q.size * 4 for v in quant)
        assert q_bytes < 0.5 * f_bytes

    def test_int8_with_paging_and_spec_composes(self, lm, dense):
        # the full decode-economics stack in ONE signature
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(16,), paged=True,
                               page_size=PS, spec_tokens=2, int8=True)
        prompt = np.array([1, 2, 3, 4] * 3, np.int32)
        ref = _run(dense, 0, prompt, 10)
        out = [eng.prefill(0, prompt, 0.0, 0, 1)]
        while len(out) < 11:
            toks, flags = eng.decode(np.array([True, False]))
            for i in range(int(flags[0].sum())):
                out.append(int(toks[0, i]))
        assert out[:11] == ref
        assert eng.decode_compile_count == 1

    def test_quant_tensor_is_a_pytree(self):
        import jax
        from paddle1_tpu.quantization import QuantTensor
        import jax.numpy as jnp
        qt = QuantTensor(jnp.zeros((4, 2), jnp.int8),
                         jnp.ones((2,), jnp.float32))
        leaves, treedef = jax.tree_util.tree_flatten(qt)
        assert len(leaves) == 2
        back = jax.tree_util.tree_unflatten(treedef, leaves)
        assert isinstance(back, QuantTensor)

    def test_int8_linear_module_pass(self):
        from paddle1_tpu import nn
        from paddle1_tpu.core.tensor import to_tensor
        from paddle1_tpu.quantization import Int8Linear, quantize_decode

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.embed_fc = nn.Linear(4, 8)
                self.head = nn.Linear(8, 4)

            def forward(self, x):
                return self.head(self.embed_fc(x))

        m = M()
        x = to_tensor(np.random.default_rng(1).standard_normal(
            (2, 4)).astype(np.float32))
        ref = m(x).numpy()
        quantize_decode(m, skip=("embed",))
        assert isinstance(m.head, Int8Linear)
        assert not isinstance(m.embed_fc, Int8Linear)
        got = m(x).numpy()
        np.testing.assert_allclose(got, ref, atol=0.1)
