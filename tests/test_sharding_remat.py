"""The hybrid dp x mp x ZeRO-2 step must lower without GSPMD's
"involuntary full rematerialization" fallback (VERDICT r3 weak #3):
grads reduce-scatter into the slot layout instead of replicate-and-
repartition. Reference intent: sharding_optimizer.py:146 "reduce rather
than allreduce"."""

import os
import re
import tempfile
import unittest

import numpy as np
import jax

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor
from paddle1_tpu.distributed import ParallelEngine, build_mesh
from paddle1_tpu.text.models import apply_megatron_sharding


def _tiny_bert():
    from paddle1_tpu.text.models import (BertForPretraining, BertModel,
                                         BertPretrainingCriterion)
    model = BertForPretraining(BertModel(
        vocab_size=128, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=16, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0))
    return model, BertPretrainingCriterion(128)


class _CaptureFd2:
    """Capture EVERYTHING written to fd 2 (XLA's C++ glog warnings bypass
    sys.stderr) for the duration of the with-block."""

    def __enter__(self):
        self._saved = os.dup(2)
        self._tmp = tempfile.TemporaryFile()
        os.dup2(self._tmp.fileno(), 2)
        return self

    def __exit__(self, *exc):
        os.dup2(self._saved, 2)
        os.close(self._saved)
        self._tmp.seek(0)
        self.text = self._tmp.read().decode(errors="replace")
        self._tmp.close()
        return False


@unittest.skipIf(len(jax.devices()) < 8, "needs the 8-device CPU mesh")
class TestHybridZero2Lowering(unittest.TestCase):
    def test_no_involuntary_remat_and_reduce_scatter_present(self):
        model, crit = _tiny_bert()
        apply_megatron_sharding(model)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        def loss_fn(m, batch):
            scores, rel = m(Tensor(batch["ids"]))
            return crit(scores, rel, Tensor(batch["mlm"]),
                        Tensor(batch["nsp"]))

        mesh = build_mesh(dp=2, mp=2, sharding=2, devices=jax.devices()[:8])
        engine = ParallelEngine(model, opt, loss_fn, mesh=mesh,
                                zero_stage=2, clip_global_norm=1.0)
        rng = np.random.default_rng(0)
        batch = {
            "ids": rng.integers(1, 128, (8, 16)).astype(np.int32),
            "mlm": rng.integers(0, 128, (8, 16)).astype(np.int32),
            "nsp": rng.integers(0, 2, (8,)).astype(np.int32),
        }
        placed = engine.shard_batch(batch)
        lowered = engine._jit.lower(engine.params, engine.opt_state, placed,
                                    jax.random.PRNGKey(0),
                                    np.float32(1e-4))
        with _CaptureFd2() as cap:
            compiled = lowered.compile()
        self.assertNotIn("Involuntary full rematerialization", cap.text,
                         "GSPMD fell back to replicate-then-repartition:\n"
                         + cap.text[-2000:])

        hlo = compiled.as_text()
        # no all-to-all fallback in the grad path. (reduce-scatter itself
        # is not asserted: XLA:CPU never forms it — the
        # allreduce+slice→reduce-scatter reassociation is a TPU/GPU pass;
        # on CPU the grads lower to all-reduce + local slice.)
        self.assertNotIn("all-to-all", hlo)
        self.assertIn("all-reduce", hlo)  # the batch-axis grad reduction

        # and the step still trains
        loss = engine.step(batch)
        self.assertTrue(np.isfinite(float(loss)))
