"""ProcessMultiTrainer: real process Hogwild workers over the shm arena
(VERDICT r3 weak #6 — thread workers are GIL-bound; the reference
HogwildWorker is a parallel C++ thread, device_worker.h:150)."""

import time

import numpy as np
import pytest

from paddle1_tpu.core import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="needs the native shm arena")


# -- module-level factories (spawn-picklable) --------------------------------

def _model_fn():
    import paddle1_tpu as paddle
    return paddle.nn.Linear(16, 1)


def _optimizer_fn(model):
    import paddle1_tpu as paddle
    return paddle.optimizer.SGD(learning_rate=0.05,
                                parameters=model.parameters())


def _mse_loss(model, batch):
    from paddle1_tpu.core.tensor import to_tensor
    pred = model(to_tensor(batch["x"]))
    y = to_tensor(batch["y"])
    return ((pred - y) * (pred - y)).mean()


def _slot_loss(model, batch):
    """CPU-bound slot-file workload: GIL-heavy python feature hashing
    before the tiny model math (the work profile process workers exist
    for)."""
    import numpy as _np
    from paddle1_tpu.core.tensor import to_tensor
    feats = _np.zeros((len(batch["slots"]), 16), _np.float32)
    for i, line in enumerate(batch["slots"]):          # pure-Python parse
        for tok in line.split():
            h = 0
            for ch in tok:                              # GIL-bound hash
                h = (h * 131 + ord(ch)) & 0xFFFFFFFF
            feats[i, h % 16] += 1.0
    pred = model(to_tensor(feats))
    return (pred * pred).mean()


def _make_xy_batches(n_batches, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    W = rng.standard_normal((16, 1)).astype(np.float32)
    out = []
    for _ in range(n_batches):
        X = rng.standard_normal((batch, 16)).astype(np.float32)
        out.append({"x": X, "y": X @ W})
    return out, W


def _make_slot_batches(n_batches, rows=512, tokens=120, seed=0):
    # one shared line pool: generation stays cheap, parse cost per batch
    # is rows*tokens*chars of pure-Python work (~130 ms)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 99999, (rows, tokens))
    lines = [" ".join(f"f{ids[r, j]}:{j}" for j in range(tokens))
             for r in range(rows)]
    return [{"slots": lines} for _ in range(n_batches)]


class TestProcessTrainerCorrectness:
    @pytest.mark.slow  # ~17s convergence soak; worker-error/arena/dead-
    # worker cases keep the mp machinery covered in-tier (CI heavy step)
    def test_two_process_regression_converges(self):
        from paddle1_tpu.distributed.fleet.process_trainer import (
            ProcessMultiTrainer)
        batches, W = _make_xy_batches(120)
        tr = ProcessMultiTrainer(process_num=2, publish_interval=2)
        out = tr.train_from_dataset(batches, _model_fn, _mse_loss,
                                    _optimizer_fn, batch_size=None)
        assert out["batches"] == 120
        assert out["updates"] == 120         # every grad applied once
        assert out["workers"] == 2
        # both workers actually trained
        assert all(s["batches"] > 0 for s in out["per_worker"].values())
        # the MASTER model converged to the generating weights
        from paddle1_tpu.core.tensor import to_tensor
        master = out["model"]
        X = np.random.default_rng(9).standard_normal(
            (64, 16)).astype(np.float32)
        pred = np.asarray(master(to_tensor(X)).numpy())
        mse = float(np.mean((pred - X @ W) ** 2))
        assert mse < 0.05, mse

    def test_worker_error_propagates(self):
        from paddle1_tpu.distributed.fleet.process_trainer import (
            ProcessMultiTrainer)
        batches, _ = _make_xy_batches(4)
        bad = [{"x": b["x"][:, :7], "y": b["y"]} for b in batches]  # shape
        tr = ProcessMultiTrainer(process_num=2)
        with pytest.raises(RuntimeError, match="hogwild worker"):
            tr.train_from_dataset(bad, _model_fn, _mse_loss,
                                  _optimizer_fn, batch_size=None)

    def test_arena_reset_barrier_under_pressure(self):
        """A small arena forces the drain-reset-republish path."""
        from paddle1_tpu.distributed.fleet.process_trainer import (
            ProcessMultiTrainer)
        batches, _ = _make_xy_batches(40, batch=64)
        tr = ProcessMultiTrainer(process_num=2, arena_size=1 << 18,
                                 publish_interval=2,
                                 arena_reset_fraction=0.4)
        out = tr.train_from_dataset(batches, _model_fn, _mse_loss,
                                    _optimizer_fn, batch_size=None)
        assert out["batches"] == 40
        assert out["updates"] == 40


class TestProcessTrainerThroughput:
    @pytest.mark.slow  # ~90s (3 interleaved rounds) and load-sensitive;
    # the scaling assertion runs on the CI heavy step where the box is
    # dedicated
    @pytest.mark.skipif(
        len(__import__("os").sched_getaffinity(0)) < 2,
        reason="throughput scaling needs >=2 CPU cores (this host has 1; "
               "the mechanism is exercised by the correctness tests, the "
               "scaling assertion runs on multi-core CI)")
    def test_two_processes_beat_one_on_slot_workload(self):
        """The point of process workers: GIL-bound slot parsing scales
        with processes (VERDICT r4 item 6 'done' criterion).

        Scored as a best-of-N RATIO via ``bench_utils.best_of`` — this
        was the tier-1 suite's one chronic flake as a single-run
        wall-clock comparison: one multi-second scheduler stall landing
        on the 2-process run flipped the ratio. Interleaved rounds make
        both arms sample the same noise windows and the fastest round
        of each is the scaling signal."""
        from bench_utils import best_of
        from paddle1_tpu.distributed.fleet.process_trainer import (
            ProcessMultiTrainer)
        batches = _make_slot_batches(40)

        def run(n):
            def phase():
                tr = ProcessMultiTrainer(process_num=n)
                out = tr.train_from_dataset(batches, _model_fn,
                                            _slot_loss, _optimizer_fn,
                                            batch_size=None)
                assert out["batches"] == 40
            return phase

        one, two = best_of(3, run(1), run(2))
        speedup = one.best_s / two.best_s
        assert speedup > 1.2, (one.times, two.times, speedup)


def _exit_model_fn():
    import os
    if os.environ.get("P1T_HOGWILD_WORKER"):
        os._exit(3)  # dies before any error can be reported
    import paddle1_tpu as paddle
    return paddle.nn.Linear(16, 1)  # parent master builds fine


class TestDeadWorkerDetection:
    def test_silently_dead_worker_raises_not_hangs(self):
        from paddle1_tpu.distributed.fleet.process_trainer import (
            ProcessMultiTrainer)
        batches, _ = _make_xy_batches(4)
        tr = ProcessMultiTrainer(process_num=2)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="died without reporting"):
            tr.train_from_dataset(batches, _exit_model_fn, _mse_loss,
                                  _optimizer_fn, batch_size=None)
        assert time.monotonic() - t0 < 120


class TestExecutorEntry:
    """exe.train_from_dataset parity (reference executor.py:1113)."""

    def test_thread_route(self):
        import paddle1_tpu as paddle
        from paddle1_tpu.core.tensor import to_tensor
        from paddle1_tpu.static import Executor
        m = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        rng = np.random.default_rng(0)
        W = rng.standard_normal((4, 1)).astype(np.float32)
        data = []
        for _ in range(30):
            X = rng.standard_normal((8, 4)).astype(np.float32)
            data.append({"x": X, "y": X @ W})

        def loss_fn(b):
            d = m(to_tensor(b["x"])) - to_tensor(b["y"])
            return (d * d).mean()

        out = Executor().train_from_dataset(
            dataset=data, thread=2, loss_fn=loss_fn, optimizer=opt,
            batch_size=None)
        assert out["batches"] == 30

    def test_process_route(self):
        from paddle1_tpu.static import Executor
        batches, _ = _make_xy_batches(10)
        out = Executor().train_from_dataset(
            dataset=batches, process_num=2, model_fn=_model_fn,
            loss_fn=_mse_loss, optimizer_fn=_optimizer_fn,
            batch_size=None)
        assert out["batches"] == 10 and out["workers"] == 2

    def test_missing_args_teach(self):
        import pytest as _pytest
        from paddle1_tpu.core.errors import InvalidArgumentError
        from paddle1_tpu.static import Executor
        with _pytest.raises(InvalidArgumentError, match="loss_fn"):
            Executor().train_from_dataset(dataset=[1, 2])
        with _pytest.raises(InvalidArgumentError, match="picklable"):
            Executor().train_from_dataset(dataset=[1], process_num=2)


class TestTrainerDesc:
    """TrainerDesc/DeviceWorkerDesc factory parity (reference
    trainer_desc.proto + trainer_factory.cc)."""

    def test_routes_by_desc(self):
        import paddle1_tpu.distributed.fleet as fleet
        t = fleet.create_trainer(fleet.TrainerDesc(thread_num=3))
        assert isinstance(t, fleet.MultiTrainer) and t.thread_num == 3
        p = fleet.create_trainer(fleet.TrainerDesc(process_num=2))
        assert isinstance(p, fleet.ProcessMultiTrainer)
        assert p.process_num == 2

    def test_bad_worker_kind_teaches(self):
        import paddle1_tpu.distributed.fleet as fleet
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="hogwild"):
            fleet.DeviceWorkerDesc("heter")
        with pytest.raises(InvalidArgumentError, match="PipelineParallel"):
            fleet.create_trainer(fleet.TrainerDesc(
                device_worker=fleet.DeviceWorkerDesc("section")))


class TestOrphanDetection:
    """PR 3 satellite: a worker whose leader died must exit promptly
    with a clear error instead of hanging on its queue gets (120s on
    the initial-param get, forever in the task loop)."""

    def test_dead_parent_raises_promptly(self, monkeypatch):
        import queue

        from paddle1_tpu.distributed.fleet import process_trainer as pt

        class _DeadParent:
            def is_alive(self):
                return False

        monkeypatch.setattr(pt.mp, "parent_process", lambda: _DeadParent())
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="orphaned"):
            # timeout=None = the task-loop get that used to block forever
            pt._orphan_checked_get(queue.Queue(), None, "the next task")
        assert time.monotonic() - t0 < 10

    def test_finite_timeout_still_raises_empty(self):
        import queue

        from paddle1_tpu.distributed.fleet import process_trainer as pt

        # in the MAIN process parent_process() is None: no orphan check
        # applies and the plain-get timeout contract is preserved
        t0 = time.monotonic()
        with pytest.raises(queue.Empty):
            pt._orphan_checked_get(queue.Queue(), 0.2, "the initial params")
        dt = time.monotonic() - t0
        assert 0.15 < dt < 5

    def test_live_parent_delivers(self):
        import queue

        from paddle1_tpu.distributed.fleet import process_trainer as pt

        q = queue.Queue()
        q.put("payload")
        assert pt._orphan_checked_get(q, 5, "x") == "payload"
