"""Math-op correctness + gradient checks (OpTest-style, SURVEY §4)."""

import numpy as np

import paddle1_tpu as paddle
from op_test import OpTest


class TestElementwise(OpTest):
    def test_add_broadcast(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4).astype(np.float32)
        self.check_output(paddle.add, np.add, [a, b])
        self.check_grad(paddle.add, [a, b], grad_input_idx=(0, 1))

    def test_mul_div(self):
        a = np.random.rand(2, 3).astype(np.float32) + 0.5
        b = np.random.rand(2, 3).astype(np.float32) + 0.5
        self.check_output(paddle.multiply, np.multiply, [a, b])
        self.check_output(paddle.divide, np.divide, [a, b])
        self.check_grad(paddle.divide, [a, b], grad_input_idx=(0, 1))

    def test_scalar_ops(self):
        a = np.random.randn(5).astype(np.float32)
        t = paddle.to_tensor(a)
        np.testing.assert_allclose((t + 2.0).numpy(), a + 2.0, rtol=1e-6)
        np.testing.assert_allclose((2.0 * t).numpy(), 2.0 * a, rtol=1e-6)
        np.testing.assert_allclose((1.0 / (t + 10)).numpy(), 1.0 / (a + 10),
                                   rtol=1e-6)
        np.testing.assert_allclose((t ** 2).numpy(), a ** 2, rtol=1e-6)

    def test_unary(self):
        a = np.random.rand(4, 4).astype(np.float32) + 0.1
        self.check_output(paddle.exp, np.exp, [a])
        self.check_output(paddle.log, np.log, [a], rtol=5e-4, atol=1e-5)
        self.check_output(paddle.sqrt, np.sqrt, [a])
        # XLA's f32 tanh is a rational approximation ~3e-5 off np.tanh
        self.check_output(paddle.tanh, np.tanh, [a], rtol=2e-4, atol=1e-4)
        self.check_grad(paddle.tanh, [a])
        self.check_grad(paddle.exp, [a])

    def test_matmul(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        self.check_output(paddle.matmul, np.matmul, [a, b], rtol=1e-4)
        # matmul is linear, so central differences have zero truncation error;
        # a large delta minimises f32 cancellation noise in the sum-loss.
        self.check_grad(paddle.matmul, [a, b], grad_input_idx=(0, 1),
                        delta=1e-1)

    def test_matmul_transpose(self):
        a = np.random.randn(4, 3).astype(np.float32)
        b = np.random.randn(5, 4).astype(np.float32)
        got = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_x=True, transpose_y=True)
        np.testing.assert_allclose(got.numpy(), a.T @ b.T, rtol=1e-4)


class TestReduce(OpTest):
    def test_sum_mean(self):
        a = np.random.randn(3, 4, 5).astype(np.float32)
        self.check_output(paddle.sum, lambda x: x.sum(), [a], rtol=1e-4)
        t = paddle.to_tensor(a)
        np.testing.assert_allclose(paddle.sum(t, axis=1).numpy(),
                                   a.sum(1), rtol=1e-4)
        np.testing.assert_allclose(
            paddle.mean(t, axis=[0, 2], keepdim=True).numpy(),
            a.mean((0, 2), keepdims=True), rtol=1e-4)

    def test_max_min_grad(self):
        a = np.random.randn(4, 4).astype(np.float32)
        self.check_grad(paddle.max, [a])
        self.check_output(paddle.min, lambda x: x.min(), [a])

    def test_logsumexp(self):
        a = np.random.randn(3, 4).astype(np.float32)
        from scipy.special import logsumexp as np_lse
        t = paddle.to_tensor(a)
        np.testing.assert_allclose(
            paddle.logsumexp(t, axis=1).numpy(), np_lse(a, axis=1),
            rtol=1e-5)


class TestCompareLogic(OpTest):
    def test_compare(self):
        a = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([2.0, 2.0, 2.0], np.float32)
        ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
        np.testing.assert_array_equal((ta < tb).numpy(), a < b)
        np.testing.assert_array_equal((ta >= tb).numpy(), a >= b)
        np.testing.assert_array_equal(
            paddle.equal_all(ta, ta).numpy(), True)

    def test_where(self):
        c = np.array([True, False, True])
        a = np.ones(3, np.float32)
        b = np.zeros(3, np.float32)
        out = paddle.where(paddle.to_tensor(c), paddle.to_tensor(a),
                           paddle.to_tensor(b))
        np.testing.assert_array_equal(out.numpy(), np.where(c, a, b))


class TestSearchSort(OpTest):
    def test_argmax_topk(self):
        a = np.random.randn(4, 6).astype(np.float32)
        t = paddle.to_tensor(a)
        np.testing.assert_array_equal(paddle.argmax(t, axis=1).numpy(),
                                      a.argmax(1))
        vals, idx = paddle.topk(t, k=3, axis=1)
        ref = np.sort(a, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)

    def test_sort(self):
        a = np.random.randn(5, 5).astype(np.float32)
        t = paddle.to_tensor(a)
        np.testing.assert_allclose(paddle.sort(t, axis=0).numpy(),
                                   np.sort(a, 0), rtol=1e-6)


class TestLinalg(OpTest):
    def test_cholesky_inv(self):
        a = np.random.randn(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        t = paddle.to_tensor(spd)
        np.testing.assert_allclose(paddle.linalg.cholesky(t).numpy(),
                                   np.linalg.cholesky(spd), rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(paddle.linalg.inv(t).numpy(),
                                   np.linalg.inv(spd), rtol=1e-3, atol=1e-4)

    def test_norm(self):
        a = np.random.randn(3, 4).astype(np.float32)
        t = paddle.to_tensor(a)
        np.testing.assert_allclose(paddle.linalg.norm(t).numpy(),
                                   np.linalg.norm(a), rtol=1e-5)


class TestHooksAndEngine(OpTest):
    def test_hook_scales_grad(self):
        w = paddle.Parameter(np.ones(3, np.float32))
        w.register_hook(lambda g: g * 2.0)
        (w.sum() * 3.0).backward()
        np.testing.assert_allclose(w.grad.numpy(), np.full(3, 6.0),
                                   rtol=1e-6)

    def test_grad_accumulation(self):
        w = paddle.Parameter(np.ones(2, np.float32))
        (w.sum()).backward()
        (w.sum() * 2).backward()
        np.testing.assert_allclose(w.grad.numpy(), np.full(2, 3.0))

    def test_no_grad(self):
        w = paddle.Parameter(np.ones(2, np.float32))
        with paddle.no_grad():
            y = w * 5
        assert y.stop_gradient

    def test_paddle_grad_api(self):
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = x * x * x
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-5)
        assert x.grad is None  # PartialGradEngine must not touch .grad

    def test_detach_breaks_graph(self):
        w = paddle.Parameter(np.ones(2, np.float32))
        y = (w * 2).detach()
        z = y * 3
        assert z.stop_gradient

    def test_multi_output_op_grad(self):
        a = np.random.randn(6).astype(np.float32)
        t = paddle.to_tensor(a, stop_gradient=False)
        parts = paddle.split(t, 2)
        (parts[0].sum() + 2 * parts[1].sum()).backward()
        expect = np.concatenate([np.ones(3), 2 * np.ones(3)])
        np.testing.assert_allclose(t.grad.numpy(), expect)


class TestPyLayer(OpTest):
    def test_custom_vjp(self):
        from paddle1_tpu.autograd import PyLayer

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, g):
                return g * 2

        x = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        y = Double.apply(x)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])


class TestParitySweepOps(OpTest):
    """Ops added by the r3 API-parity sweep vs the reference's
    python/paddle/tensor surface (mm, increment, is_tensor,
    broadcast_shape, gaussian, flatten_, tanh_)."""

    def test_mm(self):
        a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        b = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
        np.testing.assert_allclose(paddle.mm(a, b).numpy(),
                                   a.numpy() @ b.numpy())

    def test_increment_inplace(self):
        x = paddle.to_tensor(np.float32(4.0))
        out = paddle.increment(x, 1.5)
        assert out is x
        np.testing.assert_allclose(float(x.numpy()), 5.5)

    def test_is_tensor(self):
        assert paddle.is_tensor(paddle.to_tensor(np.float32(1.0)))
        assert not paddle.is_tensor(np.float32(1.0))

    def test_broadcast_shape(self):
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]

    def test_gaussian_moments(self):
        g = paddle.gaussian([20000], mean=2.0, std=0.5)
        assert abs(float(g.numpy().mean()) - 2.0) < 0.05
        assert abs(float(g.numpy().std()) - 0.5) < 0.05

    def test_flatten_inplace(self):
        x = paddle.to_tensor(np.zeros((2, 3, 4), np.float32))
        out = paddle.flatten_(x, start_axis=1)
        assert out is x and x.shape == [2, 12]

    def test_tanh_inplace_grad_safe(self):
        x = paddle.to_tensor(np.float32(0.5))
        paddle.tanh_(x)
        np.testing.assert_allclose(float(x.numpy()), np.tanh(0.5),
                                   rtol=1e-6)
