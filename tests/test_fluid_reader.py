"""py_reader / create_py_reader_by_data + the fluid doc/codegen
decorators (r5): real queue-backed readers (reference
fluid/layers/io.py:418,629) and generate_*_fn over the functional
registry (layer_function_generator.py analogs)."""

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu import fluid

L = fluid.layers


def _gen(n_batches=4, bs=8):
    # one fixed learnable batch repeated: the training test needs the
    # loss to be comparable across steps
    rng = np.random.default_rng(0)
    x = rng.standard_normal((bs, 4)).astype(np.float32)
    y = rng.integers(0, 3, (bs, 1)).astype(np.int64)

    def gen():
        for _ in range(n_batches):
            yield (x, y)
    return gen


class TestPyReader:
    def test_reference_idiom_epoch_and_reset(self):
        reader = L.py_reader(capacity=4, shapes=[(-1, 4), (-1, 1)],
                             dtypes=["float32", "int64"])
        reader.decorate_batch_generator(_gen(3))
        reader.start()
        seen = 0
        try:
            while True:
                img, label = L.read_file(reader)
                assert list(img.shape) == [8, 4]
                # x64 is disabled platform-wide: int64 feeds
                # canonicalize to int32 (same as to_tensor everywhere)
                assert "int" in str(label.dtype)
                seen += 1
        except fluid.core.EOFException:
            reader.reset()
        assert seen == 3
        # second epoch after reset
        reader.start()
        img, _ = L.read_file(reader)
        assert list(img.shape) == [8, 4]
        reader.reset()

    def test_iterable_mode_trains(self):
        paddle.seed(0)
        lin = paddle.nn.Linear(4, 3)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        reader = L.py_reader(capacity=2, shapes=[(-1, 4), (-1, 1)],
                             dtypes=["float32", "int64"])
        reader.decorate_batch_generator(_gen(5))
        losses = []
        for img, label in reader:
            loss = paddle.nn.functional.cross_entropy(
                lin(img), label.reshape([-1]))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss.data)))
        assert len(losses) == 5
        assert losses[-1] < losses[0]

    def test_sample_list_collation(self):
        # decorate_paddle_reader consumes paddle.batch-style items: a
        # LIST of (img, label) sample tuples, collated field-wise
        rng = np.random.default_rng(1)
        samples = [(rng.standard_normal(4).astype(np.float32),
                    np.int64(i % 3)) for i in range(8)]

        def gen():
            yield samples          # one batch of 8 samples

        reader = L.py_reader(capacity=2)
        reader.decorate_paddle_reader(gen)
        img, label = next(iter(reader))
        assert list(img.shape) == [8, 4]
        assert list(label.shape) == [8]

    def test_generator_error_surfaces(self):
        def gen():
            yield (np.zeros((2, 4), np.float32),)
            raise IOError("corrupt shard")
        reader = L.py_reader(capacity=2)
        reader.decorate_batch_generator(gen)
        reader.start()
        reader.read()              # batch 1 fine
        with pytest.raises(IOError, match="corrupt shard"):
            reader.read()          # the pipeline failure, not EOF
        # and after exhaustion, further reads keep raising (no hang)
        reader.reset()
        reader.decorate_batch_generator(lambda: iter(()))
        reader.start()
        with pytest.raises(fluid.core.EOFException):
            reader.read()
        with pytest.raises(fluid.core.EOFException):
            reader.read()

    def test_unstarted_read_teaches(self):
        from paddle1_tpu.core.errors import PreconditionNotMetError
        r = L.py_reader(capacity=2)
        with pytest.raises(PreconditionNotMetError, match="start"):
            r.read()
        with pytest.raises(PreconditionNotMetError, match="decorate"):
            r.start()

    def test_create_by_data_derives_shapes(self):
        x = fluid.data("x", shape=[8, 4], dtype="float32")
        y = fluid.data("y", shape=[8, 1], dtype="int64")
        r = L.create_py_reader_by_data(capacity=2, feed_list=[x, y])
        r.decorate_batch_generator(_gen(1))
        out = list(r)
        assert len(out) == 1 and len(out[0]) == 2


class TestDocCodegen:
    def test_templatedoc_fills_comment(self):
        @L.templatedoc()
        def myop(x):
            """Sum of x.

            ${comment} — details follow.
            """
        assert "${comment}" not in myop.__doc__
        assert "Sum of x. — details follow." in myop.__doc__

    def test_autodoc_prefixes(self):
        @L.autodoc("PREFIX. ")
        def op2(x):
            """body"""
        assert op2.__doc__.startswith("PREFIX. ")

    def test_generate_layer_fn_resolves_registry(self):
        relu = L.generate_layer_fn("relu")
        x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
        np.testing.assert_allclose(relu(x).numpy(), [0.0, 2.0])
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="no op named"):
            L.generate_layer_fn("definitely_not_an_op")

    def test_generate_activation_and_inplace(self):
        sigmoid = L.generate_activation_fn("sigmoid")
        x = paddle.to_tensor(np.zeros((3,), np.float32))
        np.testing.assert_allclose(sigmoid(x).numpy(), 0.5, rtol=1e-6)
        relu_ = L.generate_inplace_fn("relu_")
        t = paddle.to_tensor(np.array([-2.0, 3.0], np.float32))
        out = relu_(t)
        assert out is t  # write-back contract
        np.testing.assert_allclose(t.numpy(), [0.0, 3.0])


class TestIterationProtocol:
    """ADVICE r5: iterable-mode PyReader must speak the Python iteration
    protocol — StopIteration (not EOFException) from __next__, and a
    fresh for-loop over a partially-consumed epoch restarts it."""

    def test_next_raises_stopiteration_at_epoch_end(self):
        r = L.py_reader(capacity=2)
        r.decorate_batch_generator(_gen(2))
        it = iter(r)
        next(it)
        next(it)
        with pytest.raises(StopIteration):
            next(it)
        # and the protocol-level contract: zip() terminates cleanly
        r.decorate_batch_generator(_gen(3))
        pairs = list(zip(r, range(10)))
        assert len(pairs) == 3

    def test_partially_consumed_epoch_restarts(self):
        def gen():
            for i in range(4):
                yield (np.full((1, 1), i, np.float32),)

        r = L.py_reader(capacity=2)
        r.decorate_batch_generator(gen)
        it = iter(r)
        next(it)
        next(it)                      # 2 of 4 consumed, then abandon it
        vals = [int(x[0].numpy()[0, 0]) for x in r]   # fresh loop
        assert vals == [0, 1, 2, 3]   # restarted, not resumed mid-epoch

    def test_started_but_untouched_epoch_is_consumed_not_restarted(self):
        consumed = {"n": 0}

        def gen():
            for i in range(3):
                consumed["n"] += 1
                yield (np.full((1, 1), i, np.float32),)

        r = L.py_reader(capacity=2)
        r.decorate_batch_generator(gen)
        r.start()                     # the reference start-then-iterate idiom
        out = list(r)
        assert len(out) == 3
        assert consumed["n"] == 3     # generator ran exactly one epoch

    def test_read_keeps_legacy_eof_contract(self):
        r = L.py_reader(capacity=2)
        r.decorate_batch_generator(_gen(1))
        r.start()
        r.read()
        with pytest.raises(fluid.core.EOFException):
            r.read()

    def test_noniterable_for_loop_terminates_cleanly(self):
        r = fluid.io.PyReader(capacity=2, iterable=False)
        r.decorate_batch_generator(_gen(2))
        n = 0
        for _ in r:
            n += 1
        assert n == 2
