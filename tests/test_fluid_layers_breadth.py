"""fluid.layers breadth tier 2 (VERDICT r4 item 7): namespace sweep
pinning coverage counts against the reference surface, plus functional
spot-checks of the newly mapped groups and the transpiler teaching
error (VERDICT r3 missing #3)."""

import os
import re

import numpy as np
import pytest

import paddle1_tpu.fluid as fluid
import paddle1_tpu.fluid.layers as L
from paddle1_tpu.core.tensor import to_tensor

REF = "/root/reference/python/paddle/fluid/layers"


def _reference_names():
    names = set()
    if not os.path.isdir(REF):
        return names
    for f in os.listdir(REF):
        if not f.endswith(".py") or f == "__init__.py":
            continue
        txt = open(os.path.join(REF, f), encoding="utf-8",
                   errors="replace").read()
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", txt, re.S)
        if m:
            names.update(re.findall(r"['\"]([A-Za-z_0-9]+)['\"]",
                                    m.group(1)))
    return names


class TestNamespaceSweep:
    def test_coverage_counts(self):
        """Pin the classification like the 242-name top-level sweep:
        every reference name either resolves (mapped) or raises the
        teaching AttributeError — and the mapped share stays >= 200."""
        ref = _reference_names()
        if not ref:
            pytest.skip("reference tree unavailable")
        mapped, teaching = [], []
        for n in sorted(ref):
            try:
                getattr(L, n)
                mapped.append(n)
            except AttributeError as e:
                teaching.append(n)
                assert n in str(e), f"teaching error must name {n}"
        assert len(ref) >= 300            # surface didn't shrink
        assert len(mapped) >= 300, (len(mapped),
                                    "r5 mapping floor regressed")
        # the tier-2 groups are all mapped
        for n in """elementwise_max logical_and reduce_prod ones eye
                 linspace argsort gather_nd scatter squeeze stack split
                 where triu expand pad flatten transpose relu6
                 leaky_relu elu swish hard_sigmoid maxout prelu scale
                 l2_normalize label_smooth mse_loss huber_loss log_loss
                 kldiv_loss cos_sim sigmoid_cross_entropy_with_logits
                 dice_loss layer_norm group_norm instance_norm lrn
                 conv2d_transpose conv3d pool3d adaptive_pool2d
                 image_resize resize_bilinear pixel_shuffle grid_sampler
                 unfold yolo_box multiclass_nms prior_box box_coder
                 roi_align iou_similarity sequence_pad sequence_pool
                 sequence_softmax sequence_enumerate exponential_decay
                 piecewise_decay cosine_decay noam_decay linear_lr_warmup
                 rnn birnn GRUCell LSTMCell array_write array_read
                 tensor_array_to_tensor edit_distance""".split():
            assert n in mapped, n

    def test_still_teaching_by_design(self):
        """Block-based program-construction APIs stay loud teaching
        errors (py_reader became a real queue-backed reader in r5 —
        tests/test_fluid_reader.py)."""
        for n in ("StaticRNN", "DynamicRNN", "While", "Switch",
                  "IfElse"):
            with pytest.raises(AttributeError):
                getattr(L, n)


class TestMappedGroupsFunctional:
    def test_elementwise_compare_reduce(self):
        a = to_tensor(np.array([[1.0, 5.0], [3.0, 2.0]], np.float32))
        b = to_tensor(np.array([[2.0, 4.0], [3.0, 1.0]], np.float32))
        np.testing.assert_allclose(L.elementwise_max(a, b).numpy(),
                                   [[2, 5], [3, 2]])
        assert L.less_than(a, b).numpy().tolist() == [[True, False],
                                                      [False, False]]
        np.testing.assert_allclose(L.reduce_prod(a).numpy(), 30.0)
        assert bool(L.reduce_any(L.equal(a, b)).numpy())

    def test_creation_and_manipulation(self):
        e = L.eye(3)
        np.testing.assert_allclose(e.numpy(), np.eye(3, dtype=np.float32))
        r = L.range(0, 6, 2, "int64")
        assert r.numpy().tolist() == [0, 2, 4]
        x = to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        f = L.fill_constant_batch_size_like(x, [1, 2], "float32", 7.0)
        assert f.shape == [3, 2] and float(f.numpy()[0, 0]) == 7.0
        s = L.split(x, 2, dim=1)
        assert len(s) == 2 and s[0].shape == [3, 2]
        st = L.stack([x, x], axis=0)
        assert st.shape == [2, 3, 4]
        assert L.flatten(x, axis=2).shape == [12, 1]
        assert L.size(x).numpy() == 12

    def test_activations_and_scale(self):
        x = to_tensor(np.array([-2.0, 0.5, 9.0], np.float32))
        np.testing.assert_allclose(L.relu6(x).numpy(), [0, 0.5, 6.0])
        np.testing.assert_allclose(L.brelu(x, 0.0, 1.0).numpy(),
                                   [0, 0.5, 1.0])
        np.testing.assert_allclose(
            L.hard_sigmoid(x).numpy(),
            np.clip(np.array([-2, 0.5, 9]) * 0.2 + 0.5, 0, 1), rtol=1e-6)
        np.testing.assert_allclose(
            L.scale(x, scale=2.0, bias=1.0).numpy(), [-3, 2, 19])
        np.testing.assert_allclose(
            L.scale(x, scale=2.0, bias=1.0,
                    bias_after_scale=False).numpy(), [-2, 3, 20])

    def test_losses(self):
        p = to_tensor(np.array([[0.2], [0.8]], np.float32))
        y = to_tensor(np.array([[0.0], [1.0]], np.float32))
        ll = L.log_loss(p, y).numpy()
        np.testing.assert_allclose(
            ll, [[-np.log(0.8)], [-np.log(0.8)]], atol=2e-4)
        h = L.huber_loss(to_tensor(np.array([0.0, 3.0], np.float32)),
                         to_tensor(np.array([0.5, 0.0], np.float32)),
                         delta=1.0)
        np.testing.assert_allclose(h.numpy(), [0.125, 2.5], rtol=1e-6)
        d = L.edit_distance(
            to_tensor(np.array([[1, 2, 3]], np.int64)),
            to_tensor(np.array([[1, 3, 3]], np.int64)),
            normalized=False)
        assert float(d[0].numpy()[0, 0]) == 1.0

    def test_param_bearing_norm_layers_train(self):
        x = to_tensor(np.random.default_rng(0).standard_normal(
            (2, 4, 8)).astype(np.float32))
        out = L.layer_norm(x, begin_norm_axis=2)
        assert out.shape == [2, 4, 8]
        # normalized over the trailing axis
        np.testing.assert_allclose(np.asarray(out.numpy()).mean(-1),
                                   np.zeros((2, 4)), atol=1e-5)
        img = to_tensor(np.random.default_rng(1).standard_normal(
            (2, 6, 8, 8)).astype(np.float32))
        assert L.group_norm(img, groups=3).shape == [2, 6, 8, 8]
        assert L.instance_norm(img).shape == [2, 6, 8, 8]
        assert L.conv2d_transpose(img, 4, filter_size=3).shape[1] == 4

    def test_lr_decays_are_schedulers(self):
        from paddle1_tpu.optimizer.lr import LRScheduler
        import paddle1_tpu as paddle
        sched = L.exponential_decay(0.1, decay_steps=10, decay_rate=0.5)
        assert isinstance(sched, LRScheduler)
        m = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=L.piecewise_decay(
            [2], [0.1, 0.01]), parameters=m.parameters())
        assert abs(opt.get_lr() - 0.1) < 1e-9

    def test_rnn_runner(self):
        import paddle1_tpu as paddle
        cell = L.GRUCell(hidden_size=8)
        x = to_tensor(np.random.default_rng(0).standard_normal(
            (2, 5, 8)).astype(np.float32))
        out, state = L.rnn(cell, x)
        assert out.shape == [2, 5, 8]

    def test_tensor_array_ops(self):
        arr = L.create_array("float32")
        L.array_write(to_tensor(np.ones((2, 3), np.float32)), 0, arr)
        L.array_write(to_tensor(np.zeros((2, 3), np.float32)), 1, arr)
        assert int(L.array_length(arr).numpy()[0]) == 2
        assert L.array_read(arr, 1).numpy().sum() == 0
        t, sizes = L.tensor_array_to_tensor(arr, axis=0, use_stack=True)
        assert t.shape == [2, 2, 3]

    def test_detection_spotcheck(self):
        iou = L.iou_similarity(
            to_tensor(np.array([[0, 0, 10, 10]], np.float32)),
            to_tensor(np.array([[0, 0, 10, 10], [20, 20, 30, 30]],
                               np.float32)))
        np.testing.assert_allclose(iou.numpy(), [[1.0, 0.0]], atol=1e-6)

    def test_space_to_depth_and_shuffle_channel(self):
        x = to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        y = L.space_to_depth(x, 2)
        assert y.shape == [1, 4, 2, 2]
        c = to_tensor(np.arange(8, dtype=np.float32).reshape(1, 8, 1, 1))
        s = L.shuffle_channel(c, group=2)
        assert s.numpy().reshape(-1).tolist() == [0, 4, 1, 5, 2, 6, 3, 7]


class TestTranspilerSurface:
    # r5: the transpiler became a REAL mapping onto the PS runtime —
    # the e2e train flow is tests/test_transpiler_ps.py; here the
    # surface-level contracts
    def test_transpile_without_net_teaches(self, monkeypatch):
        from paddle1_tpu.core.errors import PreconditionNotMetError
        from paddle1_tpu.fluid import layers as fl
        # other tests in this file create implicit params; an empty
        # registry is the condition under test
        monkeypatch.setattr(fl, "_implicit_registry", {})
        t = fluid.DistributeTranspiler()
        with pytest.raises(PreconditionNotMetError, match="parameters"):
            t.transpile(trainer_id=0, pservers="127.0.0.1:6174",
                        trainers=2)

    def test_programs_require_transpile_first(self):
        from paddle1_tpu.core.errors import PreconditionNotMetError
        t = fluid.DistributeTranspiler()
        with pytest.raises(PreconditionNotMetError, match="transpile"):
            t.get_trainer_program()
        with pytest.raises(PreconditionNotMetError, match="transpile"):
            t.get_pserver_program("127.0.0.1:6174")

    def test_memory_optimize_noop(self):
        assert fluid.transpiler.memory_optimize() is None


class TestReviewRegressions:
    def test_elementwise_max_mid_axis_broadcast(self):
        x = to_tensor(np.zeros((2, 3, 4), np.float32))
        y = to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        out = L.elementwise_max(x, y, axis=1)  # [C] broadcasts on dim 1
        assert out.shape == [2, 3, 4]
        np.testing.assert_allclose(np.asarray(out.numpy())[0, :, 0],
                                   [1, 2, 3])

    def test_unique_returns_real_index_mapping(self):
        u, idx = L.unique(to_tensor(np.array([2, 3, 2], np.int64)))
        uv = np.asarray(u.numpy())
        iv = np.asarray(idx.numpy())
        np.testing.assert_array_equal(uv[iv],
                                      np.array([2, 3, 2]))
        u2, idx2, counts = L.unique_with_counts(
            to_tensor(np.array([5, 5, 7], np.int64)))
        assert np.asarray(counts.numpy()).tolist() == [2, 1]
        np.testing.assert_array_equal(np.asarray(u2.numpy())[
            np.asarray(idx2.numpy())], np.array([5, 5, 7]))

    def test_bpr_loss_excludes_self_term(self):
        # two classes, logits equal => only the self term and one
        # diff=0 term... construct: pos=class0, score diff pos-other = 1
        x = to_tensor(np.array([[2.0, 1.0]], np.float32))
        y = to_tensor(np.array([[0]], np.int64))
        loss = float(np.asarray(L.bpr_loss(x, y).numpy())[0, 0])
        expect = -np.log(1.0 / (1.0 + np.exp(-1.0)))  # only pos-vs-other
        assert abs(loss - expect) < 1e-5, (loss, expect)

    def test_sigmoid_still_layers_version(self):
        # the star import must not shadow layers.py's own definitions
        import paddle1_tpu.fluid.layers as LL
        import inspect
        assert "layers_ext" not in inspect.getsourcefile(LL.sigmoid)

    def test_lr_decay_staircase_semantics(self):
        sched = L.natural_exp_decay(0.1, decay_steps=1000,
                                    decay_rate=0.5, staircase=True)
        for _ in range(5):
            sched.step()
        assert abs(sched() - 0.1) < 1e-9  # still inside the first stair
        sched2 = L.inverse_time_decay(0.1, decay_steps=2,
                                      decay_rate=1.0, staircase=True)
        sched2.step(); sched2.step()  # step=2 -> floor(2/2)=1 -> lr/2
        assert abs(sched2() - 0.05) < 1e-9

    def test_cumsum_reverse_exclusive(self):
        x = to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_allclose(L.cumsum(x, reverse=True).numpy(),
                                   [6, 5, 3])
        np.testing.assert_allclose(L.cumsum(x, exclusive=True).numpy(),
                                   [0, 1, 3])
        np.testing.assert_allclose(
            L.cumsum(x, exclusive=True, reverse=True).numpy(), [5, 3, 0])

    def test_sum_single_tensor_passes_through(self):
        x = to_tensor(np.ones((2, 3), np.float32))
        assert L.sum(x).shape == [2, 3]
        assert float(L.sum([x, x]).numpy()[0, 0]) == 2.0

    def test_sequence_expand_as_needs_lengths(self):
        from paddle1_tpu.core.errors import InvalidArgumentError
        x = to_tensor(np.ones((2, 3), np.float32))
        with pytest.raises(InvalidArgumentError, match="lengths"):
            L.sequence_expand_as(x, x)

    def test_prelu_element_mode_teaches(self):
        from paddle1_tpu.core.errors import UnimplementedError
        x = to_tensor(np.ones((1, 2, 3), np.float32))
        with pytest.raises(UnimplementedError, match="element"):
            L.prelu(x, mode="element")


class TestTier3:
    def test_mean_iou_counts(self):
        pred = to_tensor(np.array([0, 0, 1, 1], np.int64))
        lab = to_tensor(np.array([0, 1, 1, 1], np.int64))
        miou, wrong, correct = L.mean_iou(pred, lab, 2)
        # class0: corr 1, union 2 -> 0.5; class1: corr 2, union 3 -> 2/3
        np.testing.assert_allclose(float(miou.numpy()),
                                   (0.5 + 2 / 3) / 2, rtol=1e-6)
        assert np.asarray(correct.numpy()).tolist() == [1, 2]
        assert np.asarray(wrong.numpy()).tolist() == [1, 0]

    def test_case_and_switch_case(self):
        t, f = to_tensor(np.array(True)), to_tensor(np.array(False))
        out = L.case([(f, lambda: 1), (t, lambda: 2)],
                     default=lambda: 3)
        assert out == 2
        assert L.switch_case(to_tensor(np.array(1)),
                             {0: lambda: "a", 1: lambda: "b"}) == "b"
        assert L.switch_case(to_tensor(np.array(9)),
                             {0: lambda: "a"},
                             default=lambda: "d") == "d"

    def test_assert_and_print(self):
        x = to_tensor(np.ones(3, np.float32))
        assert L.Print(x, message="dbg") is x
        L.Assert(to_tensor(np.array(True)))
        with pytest.raises(AssertionError):
            L.Assert(to_tensor(np.array(False)),
                     data=[to_tensor(np.arange(3))])

    def test_distributions(self):
        n = L.Normal(0.0, 1.0)
        s = n.sample([4])
        assert list(s.shape)[:1] == [4]
        u = L.Uniform(0.0, 2.0)
        vals = np.asarray(u.sample([100]).numpy())
        assert (vals >= 0).all() and (vals <= 2).all()
        c = L.Categorical(to_tensor(np.array([1.0, 1.0, 1.0],
                                             np.float32)))
        assert c is not None

    def test_auc_functional(self):
        scores = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7],
                           [0.6, 0.4]], np.float32)
        labels = np.array([1, 0, 1, 0], np.int64)
        v, stat = L.auc(to_tensor(scores), to_tensor(labels))
        assert float(v.numpy()) == 1.0  # perfectly separable


class TestTier4:
    def test_hsigmoid_trains(self):
        x = to_tensor(np.random.default_rng(0).standard_normal(
            (4, 8)).astype(np.float32))
        y = to_tensor(np.array([0, 1, 2, 3], np.int64))
        loss = L.hsigmoid(x, y, num_classes=6)
        assert loss.shape[0] == 4
        loss.sum().backward()

    def test_bilinear_tensor_product(self):
        x = to_tensor(np.ones((2, 3), np.float32))
        y = to_tensor(np.ones((2, 5), np.float32))
        out = L.bilinear_tensor_product(x, y, size=4)
        assert out.shape == [2, 4]

    def test_fsp_matrix(self):
        a = to_tensor(np.ones((2, 3, 4, 4), np.float32))
        b = to_tensor(np.full((2, 5, 4, 4), 2.0, np.float32))
        out = L.fsp_matrix(a, b)
        assert out.shape == [2, 3, 5]
        np.testing.assert_allclose(np.asarray(out.numpy()), 2.0)

    def test_row_conv_lookahead(self):
        x = to_tensor(np.eye(4, dtype=np.float32).reshape(1, 4, 4))
        out = L.row_conv(x, future_context_size=1)
        assert out.shape == [1, 4, 4]

    def test_im2sequence_patches(self):
        x = to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        out = L.im2sequence(x, filter_size=2, stride=2)
        assert out.shape == [1, 4, 4]  # 4 patches of 1*2*2

    def test_center_loss_updates_centers(self):
        feats = to_tensor(np.ones((4, 3), np.float32))
        labels = to_tensor(np.zeros((4,), np.int64))
        losses = []
        for _ in range(2):  # same site across two passes
            l = L.center_loss(feats, labels, num_classes=2, alpha=0.5)
            losses.append(float(l.numpy().sum()))
            L.reset_parameter_pass()  # end of pass (no backward here)
        # centers moved toward the features: loss decreased
        assert losses[1] < losses[0], losses

    def test_sampling_id_range(self):
        probs = to_tensor(np.array([[0.0, 1.0, 0.0]] * 8, np.float32))
        ids = np.asarray(L.sampling_id(probs).numpy())
        assert (ids == 1).all()

    def test_anchor_generator_shapes(self):
        fmap = to_tensor(np.zeros((1, 8, 4, 6), np.float32))
        anchors, var = L.anchor_generator(
            fmap, anchor_sizes=[64.0], aspect_ratios=[1.0],
            stride=[16.0, 16.0])
        assert anchors.shape == [4, 6, 1, 4]
        a = np.asarray(anchors.numpy())
        # reference convention: center offset*(stride-1)=7.5, corners
        # +-(w-1)/2 with w = round(sqrt(256/1)) * 64/16 = 64
        np.testing.assert_allclose(a[0, 0, 0], [-24, -24, 39, 39])
        assert var.shape == [4, 6, 1, 4]

    def test_bipartite_match_greedy(self):
        d = to_tensor(np.array([[0.9, 0.1],
                                [0.8, 0.7]], np.float32))
        idx, dist = L.bipartite_match(d)
        iv = np.asarray(idx.numpy())[0]
        assert iv[0] == 0 and iv[1] == 1   # mutual-best then next-best
        np.testing.assert_allclose(np.asarray(dist.numpy())[0],
                                   [0.9, 0.7])

    def test_density_prior_box_counts(self):
        fmap = to_tensor(np.zeros((1, 3, 2, 2), np.float32))
        boxes, var = L.density_prior_box(
            fmap, densities=[2], fixed_sizes=[32.0],
            fixed_ratios=[1.0], steps=[16.0, 16.0], clip=True,
            flatten_to_2d=True)
        # 2x2 cells x density^2(4) boxes = 16
        assert boxes.shape == [16, 4]
        b = np.asarray(boxes.numpy())
        assert (b >= 0).all() and (b <= 1).all()

    def test_teacher_student_loss_runs(self):
        x = to_tensor(np.array([0.5, -0.5], np.float32))
        y = to_tensor(np.array([1.0, 0.0], np.float32))
        out = L.teacher_student_sigmoid_loss(x, y)
        assert out.shape == [2]

    def test_teacher_student_piecewise_values(self):
        x = np.array([1.0, 1.0, 1.0, 1.0], np.float32)
        y = np.array([-2.0, -0.5, 0.5, 2.0], np.float32)
        out = np.asarray(L.teacher_student_sigmoid_loss(
            to_tensor(x), to_tensor(y)).numpy())
        l1p = np.log1p(np.exp(1.0))
        np.testing.assert_allclose(
            out, [l1p, l1p - 1.0, 2 * l1p - 0.5, 2 * l1p - 2.0],
            rtol=1e-5)

    def test_row_conv_truncates_at_sequence_end(self):
        x = np.zeros((1, 4, 2), np.float32)
        x[0, 3] = 99.0                      # padding content
        out = np.asarray(L.row_conv(
            to_tensor(x), future_context_size=2,
            lengths=to_tensor(np.array([3], np.int64))).numpy())
        # valid positions must not see the padding frame at t=3
        assert np.isfinite(out).all() and (np.abs(out[0, :3]) < 50).all()

    def test_density_prior_box_clamps_unconditionally(self):
        fmap = to_tensor(np.zeros((1, 3, 2, 2), np.float32))
        boxes, _ = L.density_prior_box(
            fmap, densities=[1], fixed_sizes=[64.0],
            fixed_ratios=[1.0], steps=[16.0, 16.0], clip=False,
            flatten_to_2d=True)
        b = np.asarray(boxes.numpy())
        assert (b >= 0).all() and (b <= 1).all()

    def test_sampling_id_seeded_reproducible(self):
        probs = to_tensor(np.full((4, 3), 1 / 3, np.float32))
        a = np.asarray(L.sampling_id(probs, seed=7).numpy())
        b = np.asarray(L.sampling_id(probs, seed=7).numpy())
        np.testing.assert_array_equal(a, b)

    def test_center_loss_centers_not_in_autograd(self):
        feats = to_tensor(np.ones((2, 3), np.float32))
        feats.stop_gradient = False
        labels = to_tensor(np.zeros((2,), np.int64))
        loss = L.center_loss(feats, labels, num_classes=2, alpha=0.0,
                             update_center=False)
        loss.sum().backward()
        assert feats.grad is not None
        # the centers parameter got NO autograd gradient
        from paddle1_tpu.fluid.layers import _implicit_registry
        for st in _implicit_registry.values():
            for lay in st.layers:
                for pp in lay.parameters():
                    if tuple(pp.shape) == (2, 3):
                        assert pp.grad is None


class TestTier5:
    def test_gather_tree_backtrace(self):
        # T=3, B=1, beam=2; parent pointers trace the winning path
        ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
        parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int64)
        out = np.asarray(L.gather_tree(to_tensor(ids),
                                       to_tensor(parents)).numpy())
        # beam 0 at t=2 came from parent 1 at t=1 (which came from 0)
        np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])
        np.testing.assert_array_equal(out[:, 0, 1], [1, 3, 6])

    def test_add_position_encoding(self):
        x = np.zeros((1, 4, 6), np.float32)
        out = np.asarray(L.add_position_encoding(
            to_tensor(x), alpha=1.0, beta=1.0).numpy())
        np.testing.assert_allclose(out[0, 0, :3], 0.0, atol=1e-6)  # sin 0
        np.testing.assert_allclose(out[0, 0, 3:], 1.0, atol=1e-6)  # cos 0
        assert abs(out[0, 1, 0] - np.sin(1.0)) < 1e-5

    def test_affine_channel(self):
        x = to_tensor(np.ones((1, 2, 2, 2), np.float32))
        out = L.affine_channel(x, scale=np.array([2.0, 3.0], np.float32),
                               bias=np.array([1.0, 0.0], np.float32))
        o = np.asarray(out.numpy())
        assert o[0, 0, 0, 0] == 3.0 and o[0, 1, 0, 0] == 3.0

    def test_step_counter_increments(self):
        a = int(L.autoincreased_step_counter("t5c").numpy()[0])
        b = int(L.autoincreased_step_counter("t5c").numpy()[0])
        assert b == a + 1

    def test_selected_rows_bridges(self):
        from paddle1_tpu.core.indexed_slices import IndexedSlices
        import jax.numpy as jnp
        s = IndexedSlices(jnp.asarray([0, 0], jnp.int32),
                          jnp.ones((2, 3)), (4, 3))
        merged = L.merge_selected_rows(s)
        rows = L.get_tensor_from_selected_rows(merged)
        vals = np.asarray(rows.numpy())
        # reference semantics: the VALUES tensor [n_rows, dim], not a
        # zero-filled dense scatter
        assert vals.shape == (1, 3)
        np.testing.assert_allclose(vals[0], 2.0)  # duplicate rows merged

    def test_chunk_eval_iob(self):
        # 2 chunk types, IOB: tags B0=0 I0=1 B1=2 I1=3 O=4
        label = np.array([[0, 1, 4, 2, 3]], np.int64)
        pred = np.array([[0, 1, 4, 2, 4]], np.int64)
        p, r, f1, ni, nl, nc = L.chunk_eval(
            to_tensor(pred), to_tensor(label), "IOB", 2)
        assert int(nl.numpy()[0]) == 2
        assert int(ni.numpy()[0]) == 2
        assert int(nc.numpy()[0]) == 1          # chunk (0,2,type0) only
        assert abs(float(p.numpy()[0]) - 0.5) < 1e-6
        assert abs(float(f1.numpy()[0]) - 0.5) < 1e-6

    def test_polygon_box_transform_offsets(self):
        x = to_tensor(np.zeros((1, 2, 2, 2), np.float32))
        out = np.asarray(L.polygon_box_transform(x).numpy())
        # zero offsets -> absolute grid coords (x: 4*col, y: 4*row)
        np.testing.assert_allclose(out[0, 0], [[0, 4], [0, 4]])
        np.testing.assert_allclose(out[0, 1], [[0, 0], [4, 4]])

    def test_chunk_eval_iobes_malformed(self):
        # B0 E0 I0: E closes (0..2); the dangling I opens (2..3)
        # (reference ChunkEnd on E even for non-canonical sequences)
        # tags: B0=0 I0=1 E0=2 S0=3, O=8 (2 types x 4)
        pred = np.array([[0, 2, 1]], np.int64)
        label = np.array([[0, 2, 1]], np.int64)
        p, r, f1, ni, nl, nc = L.chunk_eval(
            to_tensor(pred), to_tensor(label), "IOBES", 2)
        assert int(ni.numpy()[0]) == 2
        assert int(nc.numpy()[0]) == 2
        assert float(f1.numpy()[0]) == 1.0

    def test_add_position_encoding_reference_divisor(self):
        x = np.zeros((1, 2, 6), np.float32)
        out = np.asarray(L.add_position_encoding(to_tensor(x)).numpy())
        # k=1 divisor is 10000^(1/(half-1)) = 10000^0.5 for half=3
        assert abs(out[0, 1, 1] - np.sin(1.0 / 10000 ** 0.5)) < 1e-6

    def test_rnncell_teaches_on_subclass(self):
        from paddle1_tpu.core.errors import UnimplementedError
        with pytest.raises(UnimplementedError, match="RNNCellBase"):
            class _C(L.RNNCell):
                pass

    def test_resize_short_and_linear_and_lod(self):
        img = to_tensor(np.zeros((1, 3, 8, 16), np.float32))
        out = L.image_resize_short(img, 4)
        assert out.shape == [1, 3, 4, 8]
        seq = to_tensor(np.zeros((1, 3, 6), np.float32))
        assert L.resize_linear(seq, out_shape=[12]).shape == [1, 3, 12]
        x, lens = L.lod_reset(to_tensor(np.zeros((2, 3), np.float32)),
                              target_lod=[2, 1])
        assert np.asarray(lens.numpy()).tolist() == [2, 1]

    def test_beam_search_step_and_decode(self):
        B, beam, V, end = 1, 2, 5, 0
        pre_ids = np.array([[3], [4]], np.int64)      # both alive
        pre_sc = np.array([[-0.5], [-1.0]], np.float32)
        # beam 0 strongly prefers token 2; beam 1 prefers token 1
        acc = np.full((2, V), -10.0, np.float32)
        acc[0, 2] = -0.6
        acc[0, 1] = -0.9
        acc[1, 1] = -1.1
        ids, sc, par = L.beam_search(pre_ids, pre_sc, None, acc,
                                     beam_size=beam, end_id=end,
                                     return_parent_idx=True)
        iv = np.asarray(ids.numpy()).reshape(-1)
        pv = np.asarray(par.numpy()).reshape(-1)
        assert iv.tolist() == [2, 1] and pv.tolist() == [0, 0]

        # finished beam keeps exactly its end candidate
        pre_ids2 = np.array([[0], [4]], np.int64)     # beam 0 finished
        ids2, sc2 = L.beam_search(pre_ids2, pre_sc, None, acc,
                                  beam_size=beam, end_id=end)
        i2 = np.asarray(ids2.numpy()).reshape(-1)
        s2 = np.asarray(sc2.numpy()).reshape(-1)
        assert 0 in i2.tolist()
        assert abs(s2[i2.tolist().index(0)] - (-0.5)) < 1e-6

        # decode: T=2 steps of (ids, parents)
        step_ids = np.array([[[2, 1]], [[0, 3]]], np.int64)
        step_par = np.array([[[0, 0]], [[0, 1]]], np.int64)
        seqs, _ = L.beam_search_decode(step_ids, None, beam, end,
                                       parents=step_par)
        sq = np.asarray(seqs.numpy())
        assert sq[:, 0, 0].tolist() == [2, 0]   # ends at end_id
        assert sq[:, 0, 1].tolist() == [1, 3]

    def test_beam_search_pruned_ids_path(self):
        # topk-pruned usage: scores [B*beam, K] with candidate vocab
        # ids in `ids` — selected tokens must be VOCAB ids
        pre_ids = np.array([[3], [4]], np.int64)
        pre_sc = np.array([[-0.5], [-1.0]], np.float32)
        cand_ids = np.array([[7, 9], [11, 13]], np.int64)   # K=2
        cand_sc = np.array([[-0.6, -0.9], [-1.1, -5.0]], np.float32)
        ids, sc, par = L.beam_search(pre_ids, pre_sc, cand_ids, cand_sc,
                                     beam_size=2, end_id=0,
                                     return_parent_idx=True)
        assert np.asarray(ids.numpy()).reshape(-1).tolist() == [7, 9]
        # finished beam in pruned mode: token forced to end_id
        pre_ids2 = np.array([[0], [4]], np.int64)
        ids2, _ = L.beam_search(pre_ids2, pre_sc, cand_ids, cand_sc,
                                beam_size=2, end_id=0)
        assert 0 in np.asarray(ids2.numpy()).reshape(-1).tolist()

    def test_beam_decode_fills_after_end(self):
        step_ids = np.array([[[5, 1]], [[0, 3]], [[7, 4]]], np.int64)
        step_par = np.array([[[0, 0]], [[0, 1]], [[0, 1]]], np.int64)
        seqs, _ = L.beam_search_decode(step_ids, None, 2, 0,
                                       parents=step_par)
        sq = np.asarray(seqs.numpy())
        assert sq[:, 0, 0].tolist() == [5, 0, 0]  # 7 after end -> end

    def test_image_resize_short_rounds(self):
        img = to_tensor(np.zeros((1, 1, 4, 6), np.float32))
        out = L.image_resize_short(img, 3)
        assert out.shape == [1, 1, 3, 5]  # 6*3/4=4.5 -> rounds to 5

    def test_lod_reset_y_dtype(self):
        x = to_tensor(np.zeros((2, 3), np.float32))
        _, l1 = L.lod_reset(x, y=[2, 1])
        _, l2 = L.lod_reset(x, target_lod=[2, 1])
        assert str(l1.dtype) == str(l2.dtype)


class TestTier6:
    def test_spectral_norm_unit_sigma(self):
        w = np.random.default_rng(0).standard_normal(
            (4, 6)).astype(np.float32) * 3.0
        out = L.spectral_norm(to_tensor(w), power_iters=20)
        o = np.asarray(out.numpy())
        s = np.linalg.svd(o, compute_uv=False)[0]
        assert abs(s - 1.0) < 0.05  # spectral radius normalized to ~1

    def test_batch_size_like_randoms(self):
        x = to_tensor(np.zeros((5, 2), np.float32))
        u = L.uniform_random_batch_size_like(x, [1, 3])
        assert u.shape == [5, 3]
        g = L.gaussian_random_batch_size_like(x, [1, 4])
        assert g.shape == [5, 4]

    def test_lstm_unit_step(self):
        x = to_tensor(np.ones((2, 3), np.float32))
        h = to_tensor(np.zeros((2, 4), np.float32))
        c = to_tensor(np.zeros((2, 4), np.float32))
        h2, c2 = L.lstm_unit(x, h, c)
        assert h2.shape == [2, 4] and c2.shape == [2, 4]
        # |h| = |tanh(c)*o| < 1 strictly
        assert np.abs(np.asarray(h2.numpy())).max() < 1.0

    def test_hash_buckets_stable(self):
        ids = to_tensor(np.array([[1], [2], [1]], np.int64))
        a = np.asarray(L.hash(ids, hash_size=1000, num_hash=2).numpy())
        b = np.asarray(L.hash(ids, hash_size=1000, num_hash=2).numpy())
        np.testing.assert_array_equal(a, b)       # deterministic
        # reference HashOutputSize: (..., num_hash, 1); the whole
        # last-dim row is ONE key
        assert a.shape == (3, 2, 1)
        assert (a >= 0).all() and (a < 1000).all()
        np.testing.assert_array_equal(a[0], a[2])  # same id same bucket
        bi = np.array([[1, 2]], np.int64)          # bigram row = one key
        hb = np.asarray(L.hash(to_tensor(bi), hash_size=1000).numpy())
        assert hb.shape == (1, 1, 1)
        assert hb.reshape(-1)[0] != a[0, 0, 0]     # row-key, not elementwise

    def test_target_assign(self):
        ent = np.arange(12, dtype=np.float32).reshape(1, 3, 4)
        matched = np.array([[2, -1, 0]], np.int64)
        out, w = L.target_assign(to_tensor(ent), to_tensor(matched),
                                 mismatch_value=-5.0)
        o = np.asarray(out.numpy())
        np.testing.assert_allclose(o[0, 0], ent[0, 2])
        np.testing.assert_allclose(o[0, 1], -5.0)
        np.testing.assert_allclose(np.asarray(w.numpy())[0, :, 0],
                                   [1, 0, 1])

    def test_target_assign_negatives_per_row(self):
        ent = np.ones((2, 2, 1), np.float32)
        matched = np.array([[0, 1], [1, 0]], np.int64)
        neg = np.array([[1], [0]], np.int64)   # DIFFERENT prior per row
        out, w = L.target_assign(to_tensor(ent), to_tensor(matched),
                                 negative_indices=to_tensor(neg),
                                 mismatch_value=0.0)
        o = np.asarray(out.numpy())
        wv = np.asarray(w.numpy())
        # row 0: negative at prior 1 only; row 1: at prior 0 only
        assert o[0, 1, 0] == 0.0 and o[1, 0, 0] == 0.0
        assert o[0, 0, 0] == 1.0 and o[1, 1, 0] == 1.0
        assert wv[0, 1, 0] == 1.0 and wv[1, 0, 0] == 1.0

    def test_lstm_unit_reference_gate_order_and_bias_attr(self):
        import paddle1_tpu as paddle
        x = to_tensor(np.ones((1, 2), np.float32))
        h = to_tensor(np.zeros((1, 3), np.float32))
        c = to_tensor(np.full((1, 3), 2.0, np.float32))
        L.reset_parameter_pass()
        h2, c2 = L.lstm_unit(x, h, c, forget_bias=100.0,
                             bias_attr=False)
        # forget gate (slot 1) saturated at 1: c2 = c + i*g in (1, 3)
        assert (np.asarray(c2.numpy()) > 1.0).all()
        L.reset_parameter_pass()
        _, c3 = L.lstm_unit(x, h, c, forget_bias=-100.0,
                            bias_attr=False)
        # forget gate saturated at 0: c3 = i*g in (-1, 1)
        assert (np.abs(np.asarray(c3.numpy())) < 1.0).all()

    def test_gaussian_batch_size_like_seeded(self):
        x = to_tensor(np.zeros((3, 2), np.float32))
        a = np.asarray(L.gaussian_random_batch_size_like(
            x, [1, 4], seed=11).numpy())
        b = np.asarray(L.gaussian_random_batch_size_like(
            x, [1, 4], seed=11).numpy())
        np.testing.assert_array_equal(a, b)

    def test_continuous_value_model(self):
        x = np.ones((2, 4), np.float32)
        sc = np.array([[3.0, 1.0], [0.0, 0.0]], np.float32)
        out = np.asarray(L.continuous_value_model(
            to_tensor(x), to_tensor(sc)).numpy())
        assert out.shape == (2, 4)
        np.testing.assert_allclose(out[0, 0], np.log(4.0), rtol=1e-6)
        np.testing.assert_allclose(out[0, 1],
                                   np.log(2.0) - np.log(4.0), rtol=1e-6)
        np.testing.assert_allclose(out[:, 2:], 1.0)
        out2 = np.asarray(L.continuous_value_model(
            to_tensor(x), to_tensor(sc), use_cvm=False).numpy())
        assert out2.shape == (2, 2)

    def test_data_norm_reference_formula(self):
        import jax.numpy as jnp
        from paddle1_tpu.fluid.layers import _implicit_registry
        L.reset_parameter_pass()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((8, 3)).astype(np.float32)

        xt = to_tensor(x)
        xt.stop_gradient = False  # so backward() can drive the commit

        def dn(**kw):  # ONE call site -> one implicit stat holder
            return L.data_norm(xt, **kw)

        before = np.asarray(dn().numpy())
        assert before.shape == (8, 3)
        # locate the stat holder and pin known summaries: the output
        # must follow the reference math out = (x - sum/size) *
        # sqrt(size / square_sum) (data_norm_op.cc:302-303)
        holder = None
        for st in _implicit_registry.values():
            for lay in st.layers:
                if hasattr(lay, "batch_square_sum") and \
                        tuple(lay.batch_sum.shape) == (3,):
                    holder = lay
        assert holder is not None
        holder.batch_size._data = jnp.full((3,), 10.0)
        holder.batch_sum._data = jnp.full((3,), 20.0)     # mean 2
        holder.batch_square_sum._data = jnp.full((3,), 40.0)  # scale 0.5
        L.reset_parameter_pass()
        out = np.asarray(dn(update=False).numpy())
        np.testing.assert_allclose(out, (x - 2.0) * 0.5, rtol=1e-5)
        # updates are STAGED at forward and committed on backward-end
        # (the reference updates in the grad op): eval forwards leave
        # the stats untouched
        L.reset_parameter_pass()
        dn()
        np.testing.assert_allclose(
            np.asarray(holder.batch_size.numpy()), 10.0)
        L.reset_parameter_pass()
        y = dn()
        y.sum().backward()      # commit fires here
        assert float(np.asarray(holder.batch_size.numpy())[0]) > 10.0
