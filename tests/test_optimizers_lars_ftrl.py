"""LARS / Ftrl / AdaDelta numeric checks vs the reference kernel
formulas (VERDICT r4 missing #6: lars_momentum_op.h, ftrl_op.h,
adadelta_op.h) + the fleet lars/lamb meta-optimizer toggles."""

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import to_tensor


def _param(shape, val, name_suffix=""):
    lay = paddle.nn.Layer()
    p = lay.create_parameter(list(shape))
    p.set_value(val)
    return p


def _step(opt, p, grad):
    p.clear_grad() if p.grad is not None else None
    (p * to_tensor(grad)).sum().backward()
    opt.step()
    opt.clear_grad()


class TestLars:
    def test_matches_kernel_formula(self):
        rng = np.random.default_rng(0)
        v0 = rng.standard_normal((4, 3)).astype(np.float32)
        g = rng.standard_normal((4, 3)).astype(np.float32)
        p = _param((4, 3), v0)
        lr, mu, coeff, wd, eps = 0.1, 0.9, 0.001, 0.0005, 1e-9
        opt = paddle.optimizer.Lars(learning_rate=lr, momentum=mu,
                                    parameters=[p], lars_coeff=coeff,
                                    lars_weight_decay=wd, epsilon=eps)
        vel = np.zeros_like(v0)
        pv = v0.copy()
        for _ in range(3):
            pn = np.sqrt((pv ** 2).sum())
            gn = np.sqrt((g ** 2).sum())
            local_lr = lr * coeff * pn / (gn + wd * pn + eps)
            vel = mu * vel + local_lr * (g + wd * pv)
            pv = pv - vel
            _step(opt, p, g)
        np.testing.assert_allclose(np.asarray(p.numpy()), pv,
                                   rtol=2e-5, atol=1e-6)

    def test_user_regularization_applies_before_lars(self):
        rng = np.random.default_rng(4)
        v0 = rng.standard_normal((4,)).astype(np.float32)
        g = rng.standard_normal((4,)).astype(np.float32)
        p = _param((4,), v0)
        lr, mu, coeff, wd, l2 = 0.1, 0.9, 0.001, 0.0005, 0.01
        opt = paddle.optimizer.Lars(learning_rate=lr, momentum=mu,
                                    parameters=[p], lars_coeff=coeff,
                                    lars_weight_decay=wd,
                                    weight_decay=l2, epsilon=1e-9)
        vel = np.zeros_like(v0)
        pv = v0.copy()
        for _ in range(2):
            greg = g + l2 * pv           # user L2 first
            pn = np.sqrt((pv ** 2).sum())
            gn = np.sqrt((greg ** 2).sum())
            local_lr = lr * coeff * pn / (gn + wd * pn + 1e-9)
            vel = mu * vel + local_lr * (greg + wd * pv)
            pv = pv - vel
            _step(opt, p, g)
        np.testing.assert_allclose(np.asarray(p.numpy()), pv,
                                   rtol=2e-5, atol=1e-6)

    def test_zero_weight_decay_degrades_to_momentum(self):
        rng = np.random.default_rng(1)
        v0 = rng.standard_normal((5,)).astype(np.float32)
        g = rng.standard_normal((5,)).astype(np.float32)
        p1 = _param((5,), v0)
        p2 = _param((5,), v0)
        lars = paddle.optimizer.Lars(learning_rate=0.1, momentum=0.9,
                                     parameters=[p1],
                                     lars_weight_decay=0.0)
        mom = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=[p2])
        for _ in range(2):
            _step(lars, p1, g)
            _step(mom, p2, g)
        np.testing.assert_allclose(np.asarray(p1.numpy()),
                                   np.asarray(p2.numpy()), rtol=1e-6)


class TestFtrl:
    @pytest.mark.parametrize("lr_power", [-0.5, -0.3])
    def test_matches_kernel_formula(self, lr_power):
        rng = np.random.default_rng(2)
        v0 = (rng.standard_normal((6,)) * 0.5).astype(np.float32)
        p = _param((6,), v0)
        lr, l1, l2 = 0.05, 0.1, 0.2
        opt = paddle.optimizer.Ftrl(learning_rate=lr, l1=l1, l2=l2,
                                    lr_power=lr_power, parameters=[p])
        l1k, l2k = l1 + 1e-10, l2 + 1e-10
        sq = np.zeros_like(v0)
        lin = np.zeros_like(v0)
        pv = v0.copy()
        for i in range(4):
            g = (rng.standard_normal(6) * 0.3).astype(np.float32)
            new_sq = sq + g * g
            if lr_power == -0.5:
                sigma = (np.sqrt(new_sq) - np.sqrt(sq)) / lr
                y = np.sqrt(new_sq) / lr + 2 * l2k
            else:
                sigma = (new_sq ** (-lr_power) - sq ** (-lr_power)) / lr
                y = new_sq ** (-lr_power) / lr + 2 * l2k
            lin = lin + g - sigma * pv
            x = l1k * np.sign(lin) - lin
            pv = np.where(np.abs(lin) > l1k, x / y, 0.0).astype(
                np.float32)
            sq = new_sq
            _step(opt, p, g)
        np.testing.assert_allclose(np.asarray(p.numpy()), pv,
                                   rtol=2e-4, atol=1e-6)

    def test_l1_shrinkage_produces_exact_zeros(self):
        p = _param((8,), np.full(8, 0.01, np.float32))
        opt = paddle.optimizer.Ftrl(learning_rate=0.1, l1=10.0, l2=0.0,
                                    parameters=[p])
        _step(opt, p, np.full(8, 0.001, np.float32))
        assert (np.asarray(p.numpy()) == 0.0).all()


class TestAdaDelta:
    def test_matches_kernel_formula(self):
        rng = np.random.default_rng(3)
        v0 = rng.standard_normal((5,)).astype(np.float32)
        p = _param((5,), v0)
        rho, eps = 0.95, 1e-6
        opt = paddle.optimizer.AdaDelta(learning_rate=1.0, rho=rho,
                                        epsilon=eps, parameters=[p])
        Eg = np.zeros_like(v0)
        Ex = np.zeros_like(v0)
        pv = v0.copy()
        for i in range(3):
            g = rng.standard_normal(5).astype(np.float32)
            Eg = rho * Eg + (1 - rho) * g * g
            upd = -np.sqrt((Ex + eps) / (Eg + eps)) * g
            Ex = rho * Ex + (1 - rho) * upd * upd
            pv = pv + upd
            _step(opt, p, g)
        np.testing.assert_allclose(np.asarray(p.numpy()), pv,
                                   rtol=2e-4, atol=1e-6)


class TestFleetToggles:
    def test_lars_swaps_momentum(self):
        from paddle1_tpu.distributed.fleet import DistributedStrategy
        from paddle1_tpu.distributed.fleet.meta_optimizers import \
            apply_optimizer_meta
        from paddle1_tpu.optimizer import Ftrl, Lamb, Lars
        p = _param((3,), np.zeros(3, np.float32))
        st = DistributedStrategy()
        st.lars = True
        st.lars_configs = {"lars_coeff": 0.002,
                           "lars_weight_decay": 0.001}
        mom = paddle.optimizer.Momentum(learning_rate=0.1,
                                        momentum=0.8, parameters=[p])
        out = apply_optimizer_meta(mom, st)
        assert isinstance(out, Lars)
        assert out._lars_coeff == 0.002
        assert out._momentum == 0.8
        assert out._parameter_list == [p]
        # a non-Momentum optimizer passes through
        adam = paddle.optimizer.Adam(parameters=[p])
        assert apply_optimizer_meta(adam, st) is adam

    def test_lamb_swaps_adam(self):
        from paddle1_tpu.distributed.fleet import DistributedStrategy
        from paddle1_tpu.distributed.fleet.meta_optimizers import \
            apply_optimizer_meta
        from paddle1_tpu.optimizer import Lamb
        p = _param((3,), np.zeros(3, np.float32))
        st = DistributedStrategy()
        st.lamb = True
        st.lamb_configs = {"lamb_weight_decay": 0.02}
        adam = paddle.optimizer.Adam(learning_rate=0.01,
                                     parameters=[p])
        out = apply_optimizer_meta(adam, st)
        assert isinstance(out, Lamb)
        assert out._lamb_wd == 0.02

    def test_fluid_legacy_spellings(self):
        import paddle1_tpu.fluid as fluid
        assert fluid.optimizer.LarsMomentumOptimizer \
            is paddle.optimizer.Lars
        assert fluid.optimizer.FtrlOptimizer is paddle.optimizer.Ftrl
        assert fluid.optimizer.AdadeltaOptimizer \
            is paddle.optimizer.AdaDelta
