"""Generative serving (ISSUE 9): device-resident KV-cache decode with
slot-based continuous batching and per-token streaming.

Fast cases ride tier-1 around ONE module-scoped model+engine (the XLA
compiles are paid once); the continuous-batching soak matrix and the
staggered-load drain soak are slow-marked (CI's generate lane and
``bench.py --generate`` run them)."""

import threading
import time

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core import chaos, health
from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.core.flags import flags_guard
from paddle1_tpu.serving import (CausalLM, DeadlineExceeded,
                                 GenerationEngine, GenerationServer,
                                 ServerClosed, ServerOverloaded,
                                 SlotWedged, StreamCancelled)
from paddle1_tpu.serving.generate import eager_generate

VOCAB, MAX_SEQ, SLOTS = 32, 32, 4


@pytest.fixture(autouse=True)
def _isolate():
    health.reset()
    chaos.reset()
    yield
    health.reset()
    chaos.reset()


@pytest.fixture(scope="module")
def lm():
    paddle.seed(7)
    return CausalLM(vocab_size=VOCAB, d_model=16, nhead=2,
                    dim_feedforward=32, num_layers=2, max_seq=MAX_SEQ)


@pytest.fixture(scope="module")
def engine(lm):
    # one engine for the whole module: its jit caches make every test
    # after the first nearly free, and the decode-compile-count gate
    # gets to assert "still exactly one" ACROSS the whole module
    return GenerationEngine(lm, slots=SLOTS, max_seq=MAX_SEQ,
                            prefill_buckets=(4, 8))


def _serve(engine, **kw):
    kw.setdefault("token_budget", 10)
    return GenerationServer(engine, **kw).start()


# ---------------------------------------------------------------------------
# slot cache (tentpole unit)


class TestSlotCache:
    def test_static_cache_matches_concat_cache(self, lm):
        """The masked [slots, max_seq] write path must compute the same
        attention as the growing concat Cache for the same tokens."""
        from paddle1_tpu.core.tensor import to_tensor
        ids = np.array([[3, 9, 1, 4]], np.int64)
        # concat path: prefill then 2 incremental steps
        cache = lm.empty_cache(1)
        lg_a, cache = lm(to_tensor(ids[:, :2]), cache=cache)
        steps_a = [np.asarray(lg_a.numpy())[0, -1]]
        for t in (2, 3):
            lg_a, cache = lm(to_tensor(ids[:, t:t + 1]), cache=cache)
            steps_a.append(np.asarray(lg_a.numpy())[0, -1])
        # slot path: same tokens through a GenCache at slot 0
        import jax.numpy as jnp
        from paddle1_tpu.nn import MultiHeadAttention
        slot_cache = lm.gen_slot_cache(1, 8)
        pos = to_tensor(np.zeros([1], np.int32))
        caches = [MultiHeadAttention.GenCache(c.k, c.v, pos)
                  for c in slot_cache]
        mask = to_tensor(
            (np.arange(8)[None, None, None, :]
             <= np.arange(2)[None, None, :, None]).copy())
        positions = to_tensor(np.arange(2, dtype=np.int64)[None])
        lg_b, caches = lm(to_tensor(ids[:, :2]), cache=caches,
                          positions=positions, attn_mask=mask)
        steps_b = [np.asarray(lg_b.numpy())[0, -1]]
        for t in (2, 3):
            mask = to_tensor(
                (np.arange(8)[None, None, None, :] <= t).copy()
                .reshape(1, 1, 1, 8))
            positions = to_tensor(np.array([[t]], np.int64))
            lg_b, caches = lm(to_tensor(ids[:, t:t + 1]), cache=caches,
                              positions=positions, attn_mask=mask)
            steps_b.append(np.asarray(lg_b.numpy())[0, -1])
        for a, b in zip(steps_a, steps_b):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)

    def test_gen_cache_write_advances_cursor_in_place_shape(self):
        import paddle1_tpu.nn as nn
        mha = nn.MultiHeadAttention(8, 2)
        c = mha.gen_slot_cache(3, 6)
        assert list(c.k.shape) == [3, 6, 2, 4]
        from paddle1_tpu.core.tensor import to_tensor
        x = to_tensor(np.random.default_rng(0).standard_normal(
            (3, 1, 8)).astype(np.float32))
        mask = to_tensor(np.ones((3, 1, 1, 6), bool))
        _, c2 = mha(x, x, x, attn_mask=mask, cache=c)
        assert list(c2.k.shape) == [3, 6, 2, 4]  # shape NEVER grows
        np.testing.assert_array_equal(np.asarray(c2.pos.numpy()),
                                      [1, 1, 1])


# ---------------------------------------------------------------------------
# engine: parity + the one-compile contract


class TestGenerationEngine:
    def test_greedy_parity_with_eager_decode(self, lm, engine):
        srv = _serve(engine)
        prompt = [1, 5, 3]
        got = srv.submit(prompt, max_new_tokens=8).result(timeout=120)
        srv.drain()
        assert got == eager_generate(lm, prompt, 8)

    def test_sampled_parity_with_eager_decode(self, lm, engine):
        """The per-request key schedule (fold 0 = draw, fold 1 = carry,
        chained from fold_in(key(seed), 0)) is shared by the jitted
        slot decode and the eager reference — same seed, same tokens,
        bit-exact."""
        srv = _serve(engine)
        kw = dict(max_new_tokens=8, temperature=0.8, top_k=6, seed=77)
        got = srv.submit([1, 5, 3], **kw).result(timeout=120)
        srv.drain()
        assert got == eager_generate(lm, [1, 5, 3], 8, temperature=0.8,
                                     top_k=6, seed=77)

    def test_one_decode_compile_across_ragged_lengths(self, lm, engine):
        srv = _serve(engine)
        outs = [srv.submit(p, max_new_tokens=4).result(timeout=120)
                for p in ([2], [1, 2, 3], [4, 4, 4, 4, 4, 4, 7])]
        rep = srv.drain()
        assert all(len(o) == 4 for o in outs)
        # ragged prompt lengths hit different PREFILL buckets but the
        # decode executable — pinned to [slots, max_seq] — is ONE
        assert engine.decode_compile_count == 1
        assert set(engine.prefill_compile_counts) <= {4, 8}
        assert all(v == 1 for v in engine.prefill_compile_counts.values())
        assert rep["unaccounted"] == 0 and rep["tokens_owed"] == 0

    def test_eos_finishes_stream(self, lm):
        # an eos_id that the greedy argmax actually emits: probe one
        # eager decode and use its 3rd token as the "eos"
        probe = eager_generate(lm, [1, 5, 3], 6)
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(4,), eos_id=probe[2])
        srv = _serve(eng)
        st = srv.submit([1, 5, 3], max_new_tokens=10)
        got = st.result(timeout=120)
        srv.drain()
        assert st.finish_reason == "eos"
        assert got == probe[:3]

    def test_alone_vs_batched_bit_identical(self, lm, engine):
        """A request's tokens must not depend on who shares the batch
        (greedy AND seeded sampling) — the slot-isolation contract."""
        prompts = [[1, 5, 3], [2, 2], [7, 1, 4, 9, 6]]
        kw = [dict(max_new_tokens=6),
              dict(max_new_tokens=6, temperature=0.9, top_k=5, seed=11),
              dict(max_new_tokens=6, temperature=0.7, seed=12)]
        srv = _serve(engine)
        batched = [srv.submit(p, **k).result(timeout=120)
                   for p in prompts for k in [kw[prompts.index(p)]]]
        srv.drain()
        alone = []
        for p, k in zip(prompts, kw):
            srv = _serve(engine)
            alone.append(srv.submit(p, **k).result(timeout=120))
            srv.drain()
        assert batched == alone

    def test_needs_generation_contract(self):
        m = paddle.nn.Linear(4, 4)
        with pytest.raises(InvalidArgumentError, match="gen_slot_cache"):
            GenerationEngine(m, slots=2, max_seq=8)

    def test_model_positional_capacity_validated(self, lm):
        # an engine max_seq past the model's embedding table would
        # CLAMP positions under jit (silent degradation) — typed now
        with pytest.raises(InvalidArgumentError,
                           match="positional capacity"):
            GenerationEngine(lm, slots=2, max_seq=MAX_SEQ * 4)

    def test_prompt_too_long_typed(self, engine):
        srv = _serve(engine)
        with pytest.raises(InvalidArgumentError, match="bucket"):
            srv.submit(list(range(MAX_SEQ + 4)))
        with pytest.raises(InvalidArgumentError, match="room"):
            srv.submit(list(range(MAX_SEQ)))
        with pytest.raises(InvalidArgumentError):
            srv.submit([])
        rep = srv.drain()
        assert rep["accepted"] == 0


# ---------------------------------------------------------------------------
# streaming front end


class TestGenerationServer:
    def test_tokens_stream_incrementally(self, lm, engine):
        srv = _serve(engine)
        st = srv.submit([1, 2], max_new_tokens=6)
        seen = list(st)  # iterator consumes per token
        srv.drain()
        assert len(seen) == 6 and st.finish_reason == "length"
        assert st.tokens == seen

    def test_budget_truncation_typed_midstream(self, lm, engine):
        srv = _serve(engine, token_budget=3)
        st = srv.submit([1, 2], max_new_tokens=50)
        with pytest.raises(DeadlineExceeded, match="budget"):
            st.result(timeout=120)
        assert st.finish_reason == "budget"
        assert len(st.tokens) == 3  # everything generated still arrived
        rep = srv.drain()
        assert rep["unaccounted"] == 0 and rep["tokens_owed"] == 0

    def test_cancel_releases_slot(self, lm, engine):
        srv = _serve(engine, token_budget=200)
        st = srv.submit([1, 2], max_new_tokens=200)
        while len(st.tokens) < 2:
            time.sleep(0.005)
        st.cancel()
        with pytest.raises(StreamCancelled):
            st.result(timeout=60)
        # iteration just stops (no raise) after a cancel
        assert isinstance(list(st), list)
        # the slot is free again: another request completes
        out = srv.submit([3], max_new_tokens=3).result(timeout=120)
        rep = srv.drain()
        assert len(out) == 3
        assert rep["cancelled"] == 1 and rep["unaccounted"] == 0

    def test_overload_sheds_typed(self, lm, engine):
        srv = _serve(engine, queue_depth=2, token_budget=3)
        shed = 0
        for _ in range(SLOTS + 8):
            try:
                srv.submit([1], max_new_tokens=3)
            except ServerOverloaded:
                shed += 1
        rep = srv.drain(timeout=120)
        assert shed > 0 and rep["shed"] == shed
        assert rep["unaccounted"] == 0 and rep["tokens_owed"] == 0

    def test_submit_after_drain_typed(self, lm, engine):
        srv = _serve(engine)
        srv.drain()
        with pytest.raises(ServerClosed):
            srv.submit([1])

    def test_backpressure_parks_slot_without_changing_tokens(
            self, lm, engine):
        srv = _serve(engine, stream_buffer=2, token_budget=12)
        st = srv.submit([1, 2], max_new_tokens=12)
        # don't consume: the slot parks at the buffer bound
        deadline = time.monotonic() + 30
        while not st.done() and len(st.tokens) < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        time.sleep(0.1)  # give the loop time to (wrongly) overrun
        assert len(st.tokens) <= 3  # parked: bound + at most one step
        got = st.result(timeout=120)  # result() consumes → unparks
        rep = srv.drain()
        assert len(got) == 12 and rep["tokens_owed"] == 0
        # parity: parking must not change WHAT is generated
        srv = _serve(engine, stream_buffer=64, token_budget=12)
        ref = srv.submit([1, 2], max_new_tokens=12).result(timeout=120)
        srv.drain()
        assert got == ref

    def test_wall_deadline_midstream_via_slow_step_chaos(
            self, lm, engine):
        chaos.configure("gen_slow_step@2")
        with flags_guard(serve_chaos_slow_s=0.4):
            srv = _serve(engine, token_budget=100)
            st = srv.submit([1, 2], max_new_tokens=100, deadline_ms=150)
            with pytest.raises(DeadlineExceeded, match="mid-stream"):
                st.result(timeout=120)
            rep = srv.drain()
        assert st.finish_reason == "deadline"
        assert rep["deadline_failed"] == 1 and rep["unaccounted"] == 0

    def test_drain_under_load_token_accounting(self, lm, engine):
        srv = _serve(engine, queue_depth=64, token_budget=4)
        streams = [srv.submit([1 + i % 5], max_new_tokens=4)
                   for i in range(10)]
        rep = srv.drain(timeout=120)
        assert all(s.done() for s in streams)
        assert rep["accepted"] == 10
        assert rep["unaccounted"] == 0 and rep["tokens_owed"] == 0
        assert rep["tokens_generated"] == rep["tokens_streamed"]

    def test_metrics_surface(self, lm, engine):
        srv = _serve(engine)
        srv.submit([1, 2], max_new_tokens=4).result(timeout=120)
        snap = srv.metrics.snapshot()
        srv.drain()
        assert snap["counters"]["tokens_generated_total"] >= 4
        assert "tokens_per_s" in srv.metrics.snapshot()["histograms"]
        assert "slot_occupancy" in snap["gauges"]
        text = srv.metrics.render_text()
        assert "p1t_serving_tokens_generated_total" in text
        assert "# TYPE p1t_serving_slot_occupancy gauge" in text


# ---------------------------------------------------------------------------
# chaos: slot isolation


class TestSlotWedgeChaos:
    def test_wedge_fails_only_that_stream_and_releases_slot(
            self, lm, engine):
        srv = _serve(engine, token_budget=12)
        ref = srv.submit([1, 5, 3], max_new_tokens=12).result(timeout=120)
        srv.drain()
        chaos.configure("gen_slot_wedge@4:1")
        srv = _serve(engine, token_budget=12)
        a = srv.submit([1, 5, 3], max_new_tokens=12)  # slot 0
        b = srv.submit([2, 2], max_new_tokens=12)     # slot 1: wedged
        got_a = a.result(timeout=120)
        with pytest.raises(SlotWedged):
            b.result(timeout=120)
        assert 0 < len(b.tokens) < 12  # typed MID-stream, tokens kept
        # the wedged slot is released: a follow-up request completes
        c = srv.submit([4, 4], max_new_tokens=3).result(timeout=120)
        rep = srv.drain()
        # cohabitant is BIT-identical to the uncontended run (pad-leak
        # analog: the wedge never touches a neighbor's cache rows)
        assert got_a == ref
        assert len(c) == 3
        assert rep["errors"] == 1 and rep["unaccounted"] == 0
        assert rep["tokens_owed"] == 0


# ---------------------------------------------------------------------------
# sampling parity (satellite): eager vs inside a jitted scan


class TestSamplingParity:
    def test_helpers_identical_eager_vs_jitted_scan(self):
        import jax
        import jax.numpy as jnp
        from paddle1_tpu.nn.decode import sample_logits_array
        rng = np.random.default_rng(3)
        seq = jnp.asarray(rng.standard_normal((6, 16)).astype(np.float32))
        base = jax.random.key(42)
        for temp, top_k in ((0.0, 0), (0.8, 0), (0.7, 4), (1.3, 1)):
            eager = [np.asarray(sample_logits_array(
                seq[t], jax.random.fold_in(base, t), temp, top_k))
                for t in range(6)]

            @jax.jit
            def scan_run(seq):
                def body(t, lg):
                    return t + 1, sample_logits_array(
                        lg, jax.random.fold_in(base, t), temp, top_k)
                _, toks = jax.lax.scan(body, 0, seq)
                return toks
            np.testing.assert_array_equal(np.asarray(scan_run(seq)),
                                          np.stack(eager))

    def test_per_slot_keys_split_independent(self):
        """vmapped per-slot sampling must equal each slot sampled alone
        with its own key — the per-slot RNG split the engine relies on
        (the easy thing to get wrong)."""
        import jax
        import jax.numpy as jnp
        from paddle1_tpu.nn.decode import sample_logits_array
        rng = np.random.default_rng(5)
        lg = jnp.asarray(rng.standard_normal((4, 16)).astype(np.float32))
        keys = jax.vmap(jax.random.key)(jnp.arange(100, 104))
        temps = jnp.asarray([0.0, 0.9, 0.7, 1.2], jnp.float32)
        topks = jnp.asarray([0, 3, 0, 5], jnp.int32)
        batched = np.asarray(jax.jit(jax.vmap(sample_logits_array))(
            lg, keys, temps, topks))
        alone = [np.asarray(sample_logits_array(
            lg[i], jax.random.key(100 + i),
            float(temps[i]), int(topks[i]))) for i in range(4)]
        np.testing.assert_array_equal(batched, np.asarray(alone))

    def test_top_k_masks_to_top_candidates(self):
        import jax
        from paddle1_tpu.nn.decode import sample_logits_array
        lg = np.zeros((256, 8), np.float32)
        lg[:, 2], lg[:, 5] = 5.0, 4.0  # top-2 candidates
        toks = np.asarray(sample_logits_array(
            lg, jax.random.key(0), 1.0, 2))
        assert set(toks.tolist()) <= {2, 5}
        assert len(set(toks.tolist())) == 2  # temperature still samples

    def test_sample_helper_rewired_through_shared_op(self):
        # SampleEmbeddingHelper must keep its exact draw schedule after
        # the rewire: same seed → same ids as raw categorical
        import jax
        from paddle1_tpu.core.tensor import to_tensor
        from paddle1_tpu.nn.decode import SampleEmbeddingHelper
        h = SampleEmbeddingHelper(lambda x: x, np.zeros(3, np.int64), 1,
                                  softmax_temperature=0.7, seed=9)
        lg = np.random.default_rng(0).standard_normal(
            (3, 16)).astype(np.float32)
        got = np.asarray(h.sample(2, to_tensor(lg), None).numpy())
        ref = np.asarray(jax.random.categorical(
            jax.random.key(9 + 2), lg / 0.7, axis=-1))
        np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# slow soak matrix (CI's generate lane; bench.py --generate is the gate)


@pytest.mark.slow
class TestContinuousBatchingSoak:
    def test_staggered_arrivals_bit_identical_and_one_compile(self, lm):
        paddle.seed(7)
        eng = GenerationEngine(lm, slots=4, max_seq=MAX_SEQ,
                               prefill_buckets=(4, 8))
        prompts = [[1, 5, 3], [2, 2], [7, 1, 4, 9, 6], [3], [6, 6],
                   [9, 9, 9, 9, 9, 9, 9], [2, 4], [1]]
        kws = [dict(max_new_tokens=8) if i % 2 else
               dict(max_new_tokens=8, temperature=0.9, top_k=6,
                    seed=50 + i) for i in range(len(prompts))]

        def run(stagger):
            srv = _serve(eng, queue_depth=64, token_budget=8)
            streams = []
            for i, (p, k) in enumerate(zip(prompts, kws)):
                streams.append(srv.submit(p, **k))
                if stagger and i % 3 == 2:
                    # let the running batch advance before more join
                    while len(streams[0].tokens) < min(2 + i, 8):
                        time.sleep(0.002)
            outs = [s.result(timeout=120) for s in streams]
            rep = srv.drain(timeout=120)
            return outs, rep

        burst, rep1 = run(stagger=False)
        staggered, rep2 = run(stagger=True)
        assert staggered == burst
        assert eng.decode_compile_count == 1
        for rep in (rep1, rep2):
            assert rep["unaccounted"] == 0 and rep["tokens_owed"] == 0

    def test_slot_reuse_waves_with_ragged_lengths(self, lm):
        paddle.seed(7)
        eng = GenerationEngine(lm, slots=2, max_seq=MAX_SEQ,
                               prefill_buckets=(4, 8))
        srv = _serve(eng, queue_depth=64, token_budget=6)
        rng = np.random.default_rng(0)
        streams = []
        for i in range(12):  # 6 waves over 2 slots
            n = int(rng.integers(1, 7))
            streams.append(srv.submit(
                rng.integers(0, VOCAB, size=n).tolist(),
                max_new_tokens=int(rng.integers(2, 7))))
        outs = [s.result(timeout=120) for s in streams]
        rep = srv.drain(timeout=120)
        assert all(len(o) >= 2 for o in outs)
        assert eng.decode_compile_count == 1
        assert rep["unaccounted"] == 0 and rep["tokens_owed"] == 0
        # every request alone reproduces its batched tokens exactly
        for i in (0, 5, 11):
            srv = _serve(eng, token_budget=6)
            rng2 = np.random.default_rng(0)
            reqs = []
            for j in range(12):
                n = int(rng2.integers(1, 7))
                reqs.append((rng2.integers(0, VOCAB, size=n).tolist(),
                             int(rng2.integers(2, 7))))
            p, m = reqs[i]
            assert srv.submit(p, max_new_tokens=m).result(
                timeout=120) == outs[i]
            srv.drain()
