"""TP dropout RNG tracker (VERDICT r4 missing #5): per-rank streams
via meta_parallel.model_parallel_random_seed +
get_rng_state_tracker().rng_state(), eager and jit."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.nn.functional as F
from paddle1_tpu.core.generator import (rng_scope, get_rng_tracker,
                                        MODEL_PARALLEL_RNG)
from paddle1_tpu.core.tensor import to_tensor
from paddle1_tpu.distributed.meta_parallel import (
    get_rng_state_tracker, model_parallel_random_seed)


class _FakeHcg:
    def __init__(self, rank):
        self._r = rank

    def get_model_parallel_rank(self):
        return self._r


def _mask(x):
    out = np.asarray(F.dropout(to_tensor(x), p=0.5,
                               training=True).numpy())
    return out != 0


def _seed_as_rank(monkeypatch, rank, seed=2048):
    from paddle1_tpu.distributed import topology
    monkeypatch.setattr(topology, "get_hybrid_communicate_group",
                        lambda: _FakeHcg(rank))
    model_parallel_random_seed(seed)


class TestEagerStreams:
    def test_mp_ranks_draw_distinct_masks_in_tracked_region(
            self, monkeypatch):
        x = np.ones((64, 64), np.float32)
        tr = get_rng_state_tracker()
        _seed_as_rank(monkeypatch, 0)
        with tr.rng_state(MODEL_PARALLEL_RNG):
            m0 = _mask(x)
        _seed_as_rank(monkeypatch, 1)
        with tr.rng_state(MODEL_PARALLEL_RNG):
            m1 = _mask(x)
        assert (m0 != m1).any()

    def test_replicated_stream_identical_across_ranks(
            self, monkeypatch):
        x = np.ones((64, 64), np.float32)
        _seed_as_rank(monkeypatch, 0)
        a = _mask(x)
        _seed_as_rank(monkeypatch, 1)
        b = _mask(x)
        np.testing.assert_array_equal(a, b)

    def test_tracked_region_restores_default_stream(self, monkeypatch):
        x = np.ones((32, 32), np.float32)
        _seed_as_rank(monkeypatch, 0)
        ref = _mask(x)
        _seed_as_rank(monkeypatch, 0)
        with get_rng_state_tracker().rng_state():
            _mask(x)  # consumes the TRACKED stream only
        after = _mask(x)
        np.testing.assert_array_equal(ref, after)

    def test_duplicate_seed_rejected(self):
        tr = get_rng_tracker()
        tr.reset()
        tr.add("a", 7)
        with pytest.raises(Exception, match="already"):
            tr.add("b", 7)
        with pytest.raises(Exception, match="already"):
            tr.add("a", 8)
        tr.reset()

    def test_unknown_state_teaches(self):
        tr = get_rng_tracker()
        tr.reset()
        with pytest.raises(Exception, match="add"):
            with tr.rng_state("never_added"):
                pass


class TestJitPath:
    def test_scope_reproducible_and_per_name_distinct(
            self, monkeypatch):
        import jax
        x = np.ones((64, 64), np.float32)
        _seed_as_rank(monkeypatch, 0)
        tr = get_rng_state_tracker()
        key = jax.random.key(5)

        def tracked_mask():
            with tr.rng_state(MODEL_PARALLEL_RNG):
                return _mask(x)
        with rng_scope(key):
            a = tracked_mask()
        with rng_scope(key):
            b = tracked_mask()
        np.testing.assert_array_equal(a, b)  # deterministic in the key
        with rng_scope(key):
            plain = _mask(x)
        assert (a != plain).any()            # tracked != default stream

    def test_repeated_regions_draw_distinct_masks(self, monkeypatch):
        """The per-layer dropout pattern: two tracked regions in one
        trace must NOT restart the same stream."""
        import jax
        x = np.ones((64, 64), np.float32)
        _seed_as_rank(monkeypatch, 0)
        tr = get_rng_state_tracker()
        key = jax.random.key(21)
        with rng_scope(key):
            with tr.rng_state(MODEL_PARALLEL_RNG):
                m1 = _mask(x)
            with tr.rng_state(MODEL_PARALLEL_RNG):
                m2 = _mask(x)
        assert (m1 != m2).any()
        # and the pair is still reproducible under the same key
        with rng_scope(key):
            with tr.rng_state(MODEL_PARALLEL_RNG):
                n1 = _mask(x)
            with tr.rng_state(MODEL_PARALLEL_RNG):
                n2 = _mask(x)
        np.testing.assert_array_equal(m1, n1)
        np.testing.assert_array_equal(m2, n2)

    def test_scope_ranks_differ(self, monkeypatch):
        import jax
        x = np.ones((64, 64), np.float32)
        key = jax.random.key(9)
        tr = get_rng_state_tracker()
        _seed_as_rank(monkeypatch, 0)
        with rng_scope(key):
            with tr.rng_state(MODEL_PARALLEL_RNG):
                m0 = _mask(x)
        _seed_as_rank(monkeypatch, 1)
        with rng_scope(key):
            with tr.rng_state(MODEL_PARALLEL_RNG):
                m1 = _mask(x)
        assert (m0 != m1).any()
