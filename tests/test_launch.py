"""Multi-node launch path (VERDICT r2 task 10): Cluster/Pod/Trainer model,
2-process rendezvous through jax.distributed, cross-process allreduce, and
fail-fast watch semantics. Reference launch_utils.py:58,141,452,559.

PR 3 adds the elastic supervision layer (distributed/supervisor): worker
heartbeats, hang detection with SIGABRT stack dumps, restart-from-
checkpoint and drain policies, worker-level chaos. The fast cases below
use plain-stdlib worker scripts (the heartbeat protocol is just a file
mtime) so they cost subprocess startup, not a jax import; the full
kill/restart training-parity soak is @slow (also `bench.py --elastic`).
"""

import os
import socket
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _clean_env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_",
                                "PADDLE_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO
    return env


class TestClusterModel:
    def test_get_cluster_two_hosts(self):
        from paddle1_tpu.distributed.launch_utils import get_cluster
        c = get_cluster(["10.0.0.1", "10.0.0.2"], 2, base_port=7000)
        assert c.world_size() == 4
        assert c.trainers_endpoints() == [
            "10.0.0.1:7000", "10.0.0.1:7001",
            "10.0.0.2:7000", "10.0.0.2:7001"]
        assert c.pod(1).trainers[0].rank == 2
        assert c.pod(1).addr == "10.0.0.2"

    def test_local_simulation_unique_ports(self):
        from paddle1_tpu.distributed.launch_utils import get_cluster
        c = get_cluster(["127.0.0.1", "127.0.0.1"], 2, base_port=7000)
        eps = c.trainers_endpoints()
        assert len(set(eps)) == 4  # every local rank gets its own port


WORKER_ALLREDUCE = textwrap.dedent("""
    import os, sys
    import numpy as np
    import paddle1_tpu.distributed as dist

    pe = dist.init_parallel_env()   # dials jax.distributed
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 2, devs     # 1 CPU device per process, global view

    rank = dist.get_rank()
    mesh = Mesh(np.array(devs), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    local = np.full((1, 4), float(rank + 1), np.float32)
    garr = jax.make_array_from_process_local_data(sharding, local, (2, 4))
    summed = jax.jit(lambda a: jnp.sum(a, axis=0),
                     out_shardings=NamedSharding(mesh, P()))(garr)
    val = float(np.asarray(summed.addressable_shards[0].data)[0])
    print(f"RESULT rank={rank} endpoint="
          f"{os.environ['PADDLE_CURRENT_ENDPOINT']} sum={val}", flush=True)
    assert val == 3.0, val
""")

WORKER_ENGINE_DP = textwrap.dedent("""
    import os
    import numpy as np
    import paddle1_tpu.distributed as dist

    pe = dist.init_parallel_env()
    import jax
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh

    rank = dist.get_rank()
    devs = jax.devices()
    assert len(devs) == 2

    # identical init on both ranks (fixed weights)
    lin = paddle.nn.Linear(4, 1)
    lin.weight._data = jax.numpy.asarray(
        np.arange(4, dtype=np.float32).reshape(4, 1) * 0.1)
    lin.bias._data = jax.numpy.zeros((1,), np.float32)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())

    def loss_fn(m, b):
        return ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()

    mesh = build_mesh(dp=2, devices=devs)
    engine = ParallelEngine(lin, opt, loss_fn, mesh=mesh, donate=False)

    # deterministic global batch [4, ...]; THIS process feeds rows
    # [2*rank : 2*rank+2] — its local data-parallel shard
    rng = np.random.default_rng(7)
    gx = rng.standard_normal((4, 4)).astype(np.float32)
    gy = rng.standard_normal((4, 1)).astype(np.float32)
    b = {"x": gx[2 * rank:2 * rank + 2], "y": gy[2 * rank:2 * rank + 2]}

    losses = [float(engine.step(b)) for _ in range(3)]
    print(f"ENGINE rank={rank} losses=" +
          ",".join(f"{l:.6f}" for l in losses), flush=True)
""")

WORKER_FAILFAST = textwrap.dedent("""
    import os, sys, time
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    if rank == 1:
        sys.exit(7)
    time.sleep(300)   # rank 0 must be killed by the watcher
""")


class TestLauncher:
    def test_two_node_rendezvous_allreduce(self, tmp_path):
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_ALLREDUCE)
        logdir = tmp_path / "logs"
        port = _free_port()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--nnodes", "2", "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}",
             "--log_dir", str(logdir), str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=300)
        logs = {i: (logdir / f"workerlog.{i}").read_text()
                for i in range(2)}
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode(),
                                   logs)
        for i in range(2):
            assert f"RESULT rank={i}" in logs[i], logs
            assert "sum=3.0" in logs[i], logs
        # distinct endpoints per rank
        assert f":{port}" in logs[0] and f":{port + 1}" in logs[1]

    @pytest.mark.slow  # ~18s of double jax.distributed rendezvous; the
    # allreduce rendezvous test above keeps the two-node path in-tier
    # (CI heavy step runs this full training variant)
    def test_engine_dp_training_across_processes(self, tmp_path):
        """Full multi-host TRAINING path: 2 processes, each feeding its
        local dp shard into one ParallelEngine step over the global mesh;
        losses must agree across ranks AND match the single-process run
        on the concatenated batch."""
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_ENGINE_DP)
        logdir = tmp_path / "logs"
        port = _free_port()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--nnodes", "2", "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}",
             "--log_dir", str(logdir), str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=300)
        logs = {i: (logdir / f"workerlog.{i}").read_text()
                for i in range(2)}
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode(),
                                   logs)
        import re as _re
        per_rank = {}
        for i in range(2):
            m = _re.search(r"ENGINE rank=%d losses=([\d.,-]+)" % i,
                           logs[i])
            assert m, logs[i]
            per_rank[i] = [float(v) for v in m.group(1).split(",")]
        assert per_rank[0] == per_rank[1], per_rank  # replicated loss

        # single-process reference on the concatenated batch
        import numpy as np
        import jax.numpy as jnp
        import paddle1_tpu as paddle
        from paddle1_tpu.core.tensor import Tensor
        from paddle1_tpu.distributed import ParallelEngine, build_mesh
        import jax
        lin = paddle.nn.Linear(4, 1)
        lin.weight._data = jnp.asarray(
            np.arange(4, dtype=np.float32).reshape(4, 1) * 0.1)
        lin.bias._data = jnp.zeros((1,), np.float32)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        rng = np.random.default_rng(7)
        gx = rng.standard_normal((4, 4)).astype(np.float32)
        gy = rng.standard_normal((4, 1)).astype(np.float32)
        engine = ParallelEngine(
            lin, opt, lambda m, b: ((m(Tensor(b["x"])) - Tensor(b["y"]))
                                    ** 2).mean(),
            mesh=build_mesh(dp=1, devices=jax.devices()[:1]), donate=False)
        ref = [float(engine.step({"x": gx, "y": gy})) for _ in range(3)]
        np.testing.assert_allclose(per_rank[0], ref, rtol=2e-4)

    def test_fail_fast_kills_pod(self, tmp_path):
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_FAILFAST)
        port = _free_port()
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--nnodes", "2", "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}", str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=120)
        dt = time.time() - t0
        assert r.returncode == 7, (r.returncode, r.stderr.decode())
        assert dt < 60, f"watcher failed to kill the sleeping rank ({dt}s)"


# -- elastic supervision (PR 3) ---------------------------------------------
# plain-stdlib workers: the heartbeat protocol is file mtime + the
# PADDLE_FT_* env vars, so supervision logic tests don't pay a jax import

BEATER = textwrap.dedent("""
    import os, sys, time
    hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
    rank = int(os.environ.get("RANK", "0"))
    if rank == 1 and os.environ.get("RANK1_EXIT"):
        sys.exit(int(os.environ["RANK1_EXIT"]))
    for _ in range(3000):
        os.utime(hb, None)
        time.sleep(0.02)
""")

RESTART_RESUME = textwrap.dedent("""
    import os, sys, time
    hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
    inc = int(os.environ["PADDLE_FT_WORKER_INCARNATION"])
    state = os.environ["STATE_FILE"]  # stands in for a checkpoint
    start = int(open(state).read()) if os.path.exists(state) else 0
    for step in range(start, 10):
        os.utime(hb, None)
        open(state, "w").write(str(step + 1))
        if inc == 0 and step == 4 and not os.environ.get("ALWAYS_DIE"):
            sys.exit(3)
        if os.environ.get("ALWAYS_DIE") and step == start + 2:
            sys.exit(3)   # deterministic fault: dies in EVERY life
        time.sleep(0.02)
""")

HANG_AFTER_3 = textwrap.dedent("""
    import faulthandler, os, time
    hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
    faulthandler.enable(
        file=open(os.environ["PADDLE_FT_STACKDUMP_FILE"], "w"),
        all_threads=True)
    for _ in range(3):
        os.utime(hb, None)
        time.sleep(0.05)
    time.sleep(600)   # the wedge: stops beating, never exits
""")

DRAINER = textwrap.dedent("""
    import os, signal, sys, time
    hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
    rank = int(os.environ.get("RANK", "0"))
    def on_term(s, f):   # "checkpoint" on the drain SIGTERM, exit clean
        open(os.environ["DRAIN_FILE"] + str(rank), "w").write("saved")
        sys.exit(0)
    signal.signal(signal.SIGTERM, on_term)
    for i in range(3000):
        os.utime(hb, None)
        if rank == 0 and i == 5:
            with open(hb + ".unhealthy", "w") as f:
                f.write("simulated sick worker")
        time.sleep(0.02)
""")


def _sup(tmp_path, **kw):
    from paddle1_tpu.distributed import Supervisor
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("grace_s", 3.0)
    kw.setdefault("hang_timeout", 5.0)
    kw.setdefault("heartbeat_dir", str(tmp_path / "hb"))
    return Supervisor(**kw)


def _worker_file(tmp_path, body, name="worker.py"):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


class TestSupervisor:
    def test_fail_fast_kills_pod_on_worker_exit(self, tmp_path):
        """Supervised fail_fast preserves watch_local_trainers
        semantics: rank 1 exits 7, rank 0 (alive and beating) is
        killed, the pod returns 7."""
        w = _worker_file(tmp_path, BEATER)
        sup = _sup(tmp_path, policy="fail_fast")
        for r in range(2):
            env = dict(os.environ, RANK=str(r), RANK1_EXIT="7")
            sup.add_worker(r, [sys.executable, "-u", w], env=env)
        t0 = time.time()
        assert sup.run() == 7
        assert time.time() - t0 < 30
        assert sup.report.failures[0].kind == "exit"

    def test_restart_policy_resumes_and_converges(self, tmp_path):
        """A rank SIGKILL-able worker dies mid-run (incarnation 0);
        restart relaunches it with the same env and it RESUMES from
        its persisted state (the checkpoint stand-in) and finishes."""
        w = _worker_file(tmp_path, RESTART_RESUME)
        state = tmp_path / "state"
        sup = _sup(tmp_path, policy="restart", max_restarts=2)
        sup.add_worker(0, [sys.executable, "-u", w],
                       env=dict(os.environ, STATE_FILE=str(state)),
                       log_path=str(tmp_path / "log.0"))
        assert sup.run() == 0
        assert sup.report.total_restarts == 1
        assert int(state.read_text()) == 10  # resumed 5..10, not 0..10

    @pytest.mark.slow  # tier-1 time budget: the core restart/hang/
    # drain/CLI cases above cover the policy matrix; these variants
    # ride the CI launcher-smoke step instead
    def test_restart_budget_exhausted_fails_pod(self, tmp_path):
        w = _worker_file(tmp_path, RESTART_RESUME)
        sup = _sup(tmp_path, policy="restart", max_restarts=1)
        sup.add_worker(0, [sys.executable, "-u", w],
                       env=dict(os.environ, ALWAYS_DIE="1",
                                STATE_FILE=str(tmp_path / "state")))
        assert sup.run() == 3      # deterministic fault: budget runs out
        assert sup.report.total_restarts == 1

    def test_hang_detected_within_timeout_and_stack_dumped(self, tmp_path):
        """A worker that stops beating is declared hung within
        ft_hang_timeout, SIGABRT'd for a faulthandler stack dump, and
        the pod fails instead of blocking forever."""
        w = _worker_file(tmp_path, HANG_AFTER_3)
        sup = _sup(tmp_path, policy="fail_fast", hang_timeout=1.0,
                   startup_grace_s=3.0, dump_wait_s=3.0)
        sup.add_worker(0, [sys.executable, "-u", w])
        t0 = time.time()
        assert sup.run() != 0
        assert time.time() - t0 < 20  # NOT the 600s the worker sleeps
        assert sup.report.hangs_detected == 1
        assert sup.report.failures[0].kind == "hang"
        assert sup.report.stack_dumps
        dump = open(sup.report.stack_dumps[0]).read()
        assert "time.sleep" in dump or "File" in dump, dump[:300]

    @pytest.mark.slow  # see test_restart_budget_exhausted_fails_pod
    def test_hung_rank_restarts(self, tmp_path):
        """restart policy also covers hangs: kill the wedged rank,
        relaunch, finish (second incarnation = RESTART_RESUME path)."""
        w = _worker_file(tmp_path, textwrap.dedent("""
            import os, sys, time
            hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
            if int(os.environ["PADDLE_FT_WORKER_INCARNATION"]) == 0:
                os.utime(hb, None)
                time.sleep(600)   # wedge in the first life
            for _ in range(3):
                os.utime(hb, None)
                time.sleep(0.02)
        """))
        sup = _sup(tmp_path, policy="restart", max_restarts=1,
                   hang_timeout=0.8, startup_grace_s=2.0, dump_wait_s=2.0)
        sup.add_worker(0, [sys.executable, "-u", w])
        assert sup.run() == 0
        assert sup.report.hangs_detected == 1
        assert sup.report.total_restarts == 1

    def test_drain_checkpoints_every_worker(self, tmp_path):
        """An unhealthy report under drain: every rank gets the
        graceful SIGTERM, "checkpoints" (drain file), exits clean; the
        pod stops with rc 0 and report.drained."""
        w = _worker_file(tmp_path, DRAINER)
        sup = _sup(tmp_path, policy="drain")
        for r in range(2):
            env = dict(os.environ, RANK=str(r),
                       DRAIN_FILE=str(tmp_path / "drained."))
            sup.add_worker(r, [sys.executable, "-u", w], env=env)
        assert sup.run() == 0
        assert sup.report.drained
        assert sup.report.unhealthy_reports == 1
        assert (tmp_path / "drained.0").exists()
        assert (tmp_path / "drained.1").exists()

    @pytest.mark.slow  # see test_restart_budget_exhausted_fails_pod
    def test_unhealthy_report_restarts_rank(self, tmp_path):
        """Explicit unhealthy report under restart policy relaunches
        just that rank (second life takes the clean path)."""
        w = _worker_file(tmp_path, textwrap.dedent("""
            import os, time
            hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
            first = int(os.environ["PADDLE_FT_WORKER_INCARNATION"]) == 0
            for i in range(4):
                os.utime(hb, None)
                if first and i == 2:
                    with open(hb + ".unhealthy", "w") as f:
                        f.write("broken")
                    time.sleep(60)   # sick: waits for the supervisor
                time.sleep(0.02)
        """))
        sup = _sup(tmp_path, policy="restart", max_restarts=1)
        sup.add_worker(0, [sys.executable, "-u", w])
        assert sup.run() == 0
        assert sup.report.unhealthy_reports == 1
        assert sup.report.total_restarts == 1


class TestSupervisedLaunchCLI:
    def test_launch_ft_supervise_restart_smoke(self, tmp_path):
        """The launcher end-to-end with --ft_supervise restart: the
        worker dies once mid-run, the supervisor relaunches it (same
        env), the relaunch resumes from its state file, rc 0. Also
        covers the no-execve single-proc supervised path."""
        worker = tmp_path / "worker.py"
        worker.write_text(RESTART_RESUME)
        env = _clean_env()
        env["STATE_FILE"] = str(tmp_path / "state")
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--ft_supervise", "restart", "--ft_max_worker_restarts", "2",
             "--log_dir", str(tmp_path / "logs"), str(worker)],
            env=env, cwd=REPO, capture_output=True, timeout=300)
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode())
        assert b"relaunched" in r.stderr
        assert (tmp_path / "state").read_text() == "10"
        # the restarted rank's log APPENDS across incarnations
        log = (tmp_path / "logs" / "workerlog.0").read_text()
        assert "supervisor restart #1" in log


@pytest.mark.slow
class TestElasticTrainingParity:
    def test_kill_restart_final_param_parity(self):
        """The acceptance gate: a run whose worker is SIGKILLed
        mid-training (worker_kill chaos) and auto-restarted by the
        Supervisor produces final params equal to the uninterrupted
        run at 1e-6 (resume via ResilientTrainer.restore_latest)."""
        sys.path.insert(0, REPO)
        from bench import bench_elastic_soak
        bench_elastic_soak(on_tpu=False)  # raises unless parity holds


WORKER_PS = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle1_tpu as paddle
    import paddle1_tpu.distributed.fleet as fleet

    role = os.environ["TRAINING_ROLE"]
    if role == "PSERVER":
        fleet.init()
        fleet.fleet.init_server(dim=4)
        print("SERVER UP", os.environ["PADDLE_PORT"], flush=True)
        fleet.fleet.run_server()
    else:
        import time
        from paddle1_tpu.distributed import DistributedEmbedding, ps_server
        eps = os.environ["PADDLE_PSERVERS_IP_PORT_LIST"].split(",")
        svc = None
        for _ in range(60):   # wait for servers to bind
            try:
                svc = ps_server.remote_service(4, eps)
                break
            except OSError:
                time.sleep(0.5)
        assert svc is not None, "servers never came up"
        emb = DistributedEmbedding(svc)
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        ids = np.array([0, 1, 2, 3]) + 4 * rank
        first = None
        for _ in range(20):
            v = emb(ids)
            loss = (v * v).mean()
            loss.backward()
            first = first if first is not None else float(loss.numpy())
        print(f"PSTRAIN rank={rank} first={first:.8f} "
              f"last={float(loss.numpy()):.8f}", flush=True)
        assert float(loss.numpy()) <= first
""")


class TestLauncherPSMode:
    def test_ps_job_one_server_two_trainers(self, tmp_path):
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_PS)
        logdir = tmp_path / "logs"
        port = _free_port()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--server_num", "1", "--trainer_num", "2",
             "--master", f"127.0.0.1:{port}",
             "--log_dir", str(logdir), str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=300)
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode())
        slog = (logdir / "serverlog.0").read_text()
        tlogs = {i: (logdir / f"workerlog.{i}").read_text()
                 for i in range(2)}
        assert "SERVER UP" in slog
        for i in range(2):
            assert f"PSTRAIN rank={i}" in tlogs[i], tlogs
