"""Multi-node launch path (VERDICT r2 task 10): Cluster/Pod/Trainer model,
2-process rendezvous through jax.distributed, cross-process allreduce, and
fail-fast watch semantics. Reference launch_utils.py:58,141,452,559."""

import os
import socket
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _clean_env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_",
                                "PADDLE_"))}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO
    return env


class TestClusterModel:
    def test_get_cluster_two_hosts(self):
        from paddle1_tpu.distributed.launch_utils import get_cluster
        c = get_cluster(["10.0.0.1", "10.0.0.2"], 2, base_port=7000)
        assert c.world_size() == 4
        assert c.trainers_endpoints() == [
            "10.0.0.1:7000", "10.0.0.1:7001",
            "10.0.0.2:7000", "10.0.0.2:7001"]
        assert c.pod(1).trainers[0].rank == 2
        assert c.pod(1).addr == "10.0.0.2"

    def test_local_simulation_unique_ports(self):
        from paddle1_tpu.distributed.launch_utils import get_cluster
        c = get_cluster(["127.0.0.1", "127.0.0.1"], 2, base_port=7000)
        eps = c.trainers_endpoints()
        assert len(set(eps)) == 4  # every local rank gets its own port


WORKER_ALLREDUCE = textwrap.dedent("""
    import os, sys
    import numpy as np
    import paddle1_tpu.distributed as dist

    pe = dist.init_parallel_env()   # dials jax.distributed
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 2, devs     # 1 CPU device per process, global view

    rank = dist.get_rank()
    mesh = Mesh(np.array(devs), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    local = np.full((1, 4), float(rank + 1), np.float32)
    garr = jax.make_array_from_process_local_data(sharding, local, (2, 4))
    summed = jax.jit(lambda a: jnp.sum(a, axis=0),
                     out_shardings=NamedSharding(mesh, P()))(garr)
    val = float(np.asarray(summed.addressable_shards[0].data)[0])
    print(f"RESULT rank={rank} endpoint="
          f"{os.environ['PADDLE_CURRENT_ENDPOINT']} sum={val}", flush=True)
    assert val == 3.0, val
""")

WORKER_ENGINE_DP = textwrap.dedent("""
    import os
    import numpy as np
    import paddle1_tpu.distributed as dist

    pe = dist.init_parallel_env()
    import jax
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh

    rank = dist.get_rank()
    devs = jax.devices()
    assert len(devs) == 2

    # identical init on both ranks (fixed weights)
    lin = paddle.nn.Linear(4, 1)
    lin.weight._data = jax.numpy.asarray(
        np.arange(4, dtype=np.float32).reshape(4, 1) * 0.1)
    lin.bias._data = jax.numpy.zeros((1,), np.float32)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())

    def loss_fn(m, b):
        return ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()

    mesh = build_mesh(dp=2, devices=devs)
    engine = ParallelEngine(lin, opt, loss_fn, mesh=mesh, donate=False)

    # deterministic global batch [4, ...]; THIS process feeds rows
    # [2*rank : 2*rank+2] — its local data-parallel shard
    rng = np.random.default_rng(7)
    gx = rng.standard_normal((4, 4)).astype(np.float32)
    gy = rng.standard_normal((4, 1)).astype(np.float32)
    b = {"x": gx[2 * rank:2 * rank + 2], "y": gy[2 * rank:2 * rank + 2]}

    losses = [float(engine.step(b)) for _ in range(3)]
    print(f"ENGINE rank={rank} losses=" +
          ",".join(f"{l:.6f}" for l in losses), flush=True)
""")

WORKER_FAILFAST = textwrap.dedent("""
    import os, sys, time
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    if rank == 1:
        sys.exit(7)
    time.sleep(300)   # rank 0 must be killed by the watcher
""")


class TestLauncher:
    def test_two_node_rendezvous_allreduce(self, tmp_path):
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_ALLREDUCE)
        logdir = tmp_path / "logs"
        port = _free_port()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--nnodes", "2", "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}",
             "--log_dir", str(logdir), str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=300)
        logs = {i: (logdir / f"workerlog.{i}").read_text()
                for i in range(2)}
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode(),
                                   logs)
        for i in range(2):
            assert f"RESULT rank={i}" in logs[i], logs
            assert "sum=3.0" in logs[i], logs
        # distinct endpoints per rank
        assert f":{port}" in logs[0] and f":{port + 1}" in logs[1]

    def test_engine_dp_training_across_processes(self, tmp_path):
        """Full multi-host TRAINING path: 2 processes, each feeding its
        local dp shard into one ParallelEngine step over the global mesh;
        losses must agree across ranks AND match the single-process run
        on the concatenated batch."""
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_ENGINE_DP)
        logdir = tmp_path / "logs"
        port = _free_port()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--nnodes", "2", "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}",
             "--log_dir", str(logdir), str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=300)
        logs = {i: (logdir / f"workerlog.{i}").read_text()
                for i in range(2)}
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode(),
                                   logs)
        import re as _re
        per_rank = {}
        for i in range(2):
            m = _re.search(r"ENGINE rank=%d losses=([\d.,-]+)" % i,
                           logs[i])
            assert m, logs[i]
            per_rank[i] = [float(v) for v in m.group(1).split(",")]
        assert per_rank[0] == per_rank[1], per_rank  # replicated loss

        # single-process reference on the concatenated batch
        import numpy as np
        import jax.numpy as jnp
        import paddle1_tpu as paddle
        from paddle1_tpu.core.tensor import Tensor
        from paddle1_tpu.distributed import ParallelEngine, build_mesh
        import jax
        lin = paddle.nn.Linear(4, 1)
        lin.weight._data = jnp.asarray(
            np.arange(4, dtype=np.float32).reshape(4, 1) * 0.1)
        lin.bias._data = jnp.zeros((1,), np.float32)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        rng = np.random.default_rng(7)
        gx = rng.standard_normal((4, 4)).astype(np.float32)
        gy = rng.standard_normal((4, 1)).astype(np.float32)
        engine = ParallelEngine(
            lin, opt, lambda m, b: ((m(Tensor(b["x"])) - Tensor(b["y"]))
                                    ** 2).mean(),
            mesh=build_mesh(dp=1, devices=jax.devices()[:1]), donate=False)
        ref = [float(engine.step({"x": gx, "y": gy})) for _ in range(3)]
        np.testing.assert_allclose(per_rank[0], ref, rtol=2e-4)

    def test_fail_fast_kills_pod(self, tmp_path):
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_FAILFAST)
        port = _free_port()
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--nnodes", "2", "--nproc_per_node", "1",
             "--master", f"127.0.0.1:{port}", str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=120)
        dt = time.time() - t0
        assert r.returncode == 7, (r.returncode, r.stderr.decode())
        assert dt < 60, f"watcher failed to kill the sleeping rank ({dt}s)"


WORKER_PS = textwrap.dedent("""
    import os
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle1_tpu as paddle
    import paddle1_tpu.distributed.fleet as fleet

    role = os.environ["TRAINING_ROLE"]
    if role == "PSERVER":
        fleet.init()
        fleet.fleet.init_server(dim=4)
        print("SERVER UP", os.environ["PADDLE_PORT"], flush=True)
        fleet.fleet.run_server()
    else:
        import time
        from paddle1_tpu.distributed import DistributedEmbedding, ps_server
        eps = os.environ["PADDLE_PSERVERS_IP_PORT_LIST"].split(",")
        svc = None
        for _ in range(60):   # wait for servers to bind
            try:
                svc = ps_server.remote_service(4, eps)
                break
            except OSError:
                time.sleep(0.5)
        assert svc is not None, "servers never came up"
        emb = DistributedEmbedding(svc)
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        ids = np.array([0, 1, 2, 3]) + 4 * rank
        first = None
        for _ in range(20):
            v = emb(ids)
            loss = (v * v).mean()
            loss.backward()
            first = first if first is not None else float(loss.numpy())
        print(f"PSTRAIN rank={rank} first={first:.8f} "
              f"last={float(loss.numpy()):.8f}", flush=True)
        assert float(loss.numpy()) <= first
""")


class TestLauncherPSMode:
    def test_ps_job_one_server_two_trainers(self, tmp_path):
        worker = tmp_path / "worker.py"
        worker.write_text(WORKER_PS)
        logdir = tmp_path / "logs"
        port = _free_port()
        r = subprocess.run(
            [sys.executable, "-m", "paddle1_tpu.distributed.launch",
             "--server_num", "1", "--trainer_num", "2",
             "--master", f"127.0.0.1:{port}",
             "--log_dir", str(logdir), str(worker)],
            env=_clean_env(), cwd=REPO, capture_output=True, timeout=300)
        assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode())
        slog = (logdir / "serverlog.0").read_text()
        tlogs = {i: (logdir / f"workerlog.{i}").read_text()
                 for i in range(2)}
        assert "SERVER UP" in slog
        for i in range(2):
            assert f"PSTRAIN rank={i}" in tlogs[i], tlogs
