"""Production-day traffic simulator (ISSUE 18): grammar, schedule
determinism, rate composition, and the open-loop runner's accounting
identities. Everything here is fast — the runner is driven with
in-process fake futures at high ``speed`` so no replica ever spawns.
"""

import time

import pytest

from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.serving import ServerOverloaded, TrafficModel, parse_traffic
from paddle1_tpu.serving.errors import DeadlineExceeded
from paddle1_tpu.serving.traffic import FlashCrowd, run, schedule


class TestGrammar:
    def test_empty_spec_is_defaults(self):
        assert parse_traffic("") == TrafficModel()

    def test_full_grammar_roundtrip(self):
        m = parse_traffic("rps=40;dur=30;diurnal=0.3;"
                          "flash=10x@12+6,8x@20+2;tail=1.5;len=8:512;"
                          "prio=0:0.7,1:0.2,2:0.1;deadline=250;seed=7")
        assert m.rps == 40 and m.duration_s == 30 and m.diurnal == 0.3
        assert m.flash == (FlashCrowd(12, 6, 10), FlashCrowd(20, 2, 8))
        assert m.tail_alpha == 1.5
        assert (m.len_min, m.len_max) == (8, 512)
        assert m.priorities == ((0, 0.7), (1, 0.2), (2, 0.1))
        assert m.deadline_ms == 250 and m.seed == 7

    def test_unknown_key_typed(self):
        with pytest.raises(InvalidArgumentError, match="qps"):
            parse_traffic("qps=40")

    def test_bad_flash_clause_typed(self):
        with pytest.raises(InvalidArgumentError, match="flash"):
            parse_traffic("flash=10x12")

    def test_bad_value_typed(self):
        with pytest.raises(InvalidArgumentError, match="rps=fast"):
            parse_traffic("rps=fast")

    def test_full_amplitude_diurnal_typed(self):
        with pytest.raises(InvalidArgumentError, match="diurnal"):
            TrafficModel(diurnal=1.0)

    def test_degenerate_lengths_typed(self):
        with pytest.raises(InvalidArgumentError, match="len_min"):
            TrafficModel(len_min=10, len_max=2)

    def test_nonpositive_priority_weight_typed(self):
        with pytest.raises(InvalidArgumentError, match="priorities"):
            TrafficModel(priorities=((0, 0.0),))


class TestRateComposition:
    def test_flash_multiplies_inside_window_only(self):
        m = TrafficModel(rps=10, duration_s=100,
                         flash=(FlashCrowd(40, 10, 10),))
        assert m.rate_at(39.9) == pytest.approx(10.0)
        assert m.rate_at(45.0) == pytest.approx(100.0)
        assert m.rate_at(50.0) == pytest.approx(10.0)  # half-open end

    def test_diurnal_peaks_mid_day(self):
        m = TrafficModel(rps=10, duration_s=100, diurnal=0.4)
        assert m.rate_at(25.0) == pytest.approx(14.0)  # sin peak
        assert m.rate_at(75.0) == pytest.approx(6.0)   # sin trough
        assert m.peak_rate() == pytest.approx(14.0)

    def test_peak_rate_bounds_every_instant(self):
        m = parse_traffic("rps=20;dur=60;diurnal=0.3;"
                          "flash=10x@12+6,4x@40+5")
        peak = m.peak_rate()
        assert all(m.rate_at(t / 10.0) <= peak + 1e-9
                   for t in range(600))


class TestSchedule:
    def test_same_seed_same_day(self):
        m = parse_traffic("rps=50;dur=10;diurnal=0.2;flash=5x@4+2;"
                          "len=4:64;prio=0:0.5,1:0.5;seed=11")
        assert schedule(m) == schedule(m)

    def test_different_seed_different_day(self):
        a = schedule(TrafficModel(rps=50, duration_s=10, seed=1))
        b = schedule(TrafficModel(rps=50, duration_s=10, seed=2))
        assert a != b

    def test_arrival_fields_in_bounds(self):
        m = parse_traffic("rps=100;dur=10;len=4:64;"
                          "prio=1:0.5,2:0.5;deadline=250;seed=3")
        arrivals = schedule(m)
        assert arrivals, "a 100rps/10s day produced no arrivals"
        assert all(0 <= a.t < 10 for a in arrivals)
        assert all(4 <= a.length <= 64 for a in arrivals)
        assert all(a.priority in (1, 2) for a in arrivals)
        assert all(a.deadline_ms == 250 for a in arrivals)
        assert {a.priority for a in arrivals} == {1, 2}
        # arrivals come out time-ordered (one thinned Poisson pass)
        assert all(x.t <= y.t
                   for x, y in zip(arrivals, arrivals[1:]))

    def test_volume_tracks_offered_rate(self):
        n = len(schedule(TrafficModel(rps=200, duration_s=5, seed=5)))
        # Poisson(1000): +/-5 sigma ~ 158 — generous, deterministic
        assert 840 <= n <= 1160, n

    def test_flash_concentrates_volume(self):
        m = TrafficModel(rps=40, duration_s=20,
                         flash=(FlashCrowd(8, 4, 10),), seed=9)
        arrivals = schedule(m)
        in_flash = sum(1 for a in arrivals if 8 <= a.t < 12)
        # the 20% flash window carries ~71% of the day at 10x
        assert in_flash / len(arrivals) > 0.5

    def test_heavy_tail_is_heavy(self):
        m = TrafficModel(rps=400, duration_s=5, tail_alpha=1.1,
                         len_min=8, len_max=512, seed=13)
        lengths = [a.length for a in schedule(m)]
        # Pareto(1.1) from 8: most mass near the floor, a real tail
        assert sum(1 for v in lengths if v < 32) > len(lengths) * 0.5
        assert max(lengths) > 128


class _Future:
    def __init__(self, fail=None, delay_s=0.0):
        self._fail = fail
        self._delay = delay_s

    def result(self, timeout=None):
        if self._delay:
            time.sleep(self._delay)
        if self._fail is not None:
            raise self._fail
        return object()


class TestRunner:
    def _day(self, rps=400, dur=2.0, seed=0):
        return schedule(TrafficModel(rps=rps, duration_s=dur,
                                     seed=seed))

    def test_accounting_identities(self):
        arrivals = self._day()
        state = {"n": 0}

        def submit(a):
            state["n"] += 1
            if state["n"] % 7 == 0:
                raise ServerOverloaded("shed (test)")
            if state["n"] % 13 == 0:
                raise RuntimeError("router crashed (test)")
            if state["n"] % 11 == 0:
                return _Future(fail=DeadlineExceeded("late (test)"))
            return _Future()
        stats = run(arrivals, submit, speed=50.0)
        assert stats["offered"] == len(arrivals)
        assert stats["offered"] == (stats["admitted"] + stats["shed"]
                                    + stats["submit_failed"])
        assert stats["admitted"] == stats["completed"] + stats["errors"]
        assert stats["shed"] >= 1 and stats["submit_failed"] >= 1
        assert stats["error_types"] == {
            "DeadlineExceeded": stats["errors"]}
        assert stats["latency_ms"]["n"] == stats["completed"]

    def test_clean_run_all_complete(self):
        arrivals = self._day(rps=200, dur=1.0)
        stats = run(arrivals, lambda a: _Future(), speed=50.0)
        assert stats["completed"] == stats["offered"] == len(arrivals)
        assert stats["shed"] == stats["errors"] == 0
        assert stats["latency_ms"]["p99"] >= stats["latency_ms"]["p50"]

    def test_open_loop_keeps_offering_through_failures(self):
        # every submit raises: an open-loop generator must offer the
        # WHOLE day anyway (closed-loop would stall on the first)
        arrivals = self._day(rps=200, dur=1.0)
        stats = run(arrivals, lambda a: (_ for _ in ()).throw(
            RuntimeError("fleet is gone")), speed=50.0)
        assert stats["submit_failed"] == stats["offered"]
        assert stats["completed"] == 0

    def test_on_tick_fires_through_the_day(self):
        arrivals = self._day(rps=400, dur=2.0)
        ticks = []
        run(arrivals, lambda a: _Future(), speed=4.0,
            on_tick=ticks.append, tick_s=0.05)
        assert len(ticks) >= 5
        assert ticks == sorted(ticks)

    def test_slow_completions_do_not_block_submission(self):
        # completions take 50ms each; at speed 50 the whole day's
        # submissions finish LONG before the collectors drain — the
        # submit thread must never wait on a result
        arrivals = self._day(rps=100, dur=1.0)
        t0 = time.monotonic()
        stats = run(arrivals, lambda a: _Future(delay_s=0.05),
                    speed=50.0, collectors=32)
        assert stats["completed"] == stats["offered"]
        assert stats["lateness_p99_ms"] < 5000
        assert time.monotonic() - t0 < 60
