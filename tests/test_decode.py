"""Seq2seq decode stack (VERDICT r4 missing #1): dynamic_decode +
BeamSearchDecoder + BasicDecoder/helpers vs numpy references.

Reference: /root/reference/python/paddle/fluid/layers/rnn.py
(Decoder:753, BeamSearchDecoder:866, dynamic_decode:1581,
helpers:1673-2127)."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.nn as nn
from paddle1_tpu.core.tensor import to_tensor

B, H, V, EMB = 3, 8, 11, 6
START, END = 1, 2


def _np(t):
    return np.asarray(t.numpy())


class _Seq2SeqFixture:
    """A tiny decoder: GRU cell + embedding + vocab projection with
    fixed weights, plus a pure-numpy twin of the step function."""

    def __init__(self, seed=0):
        rng = np.random.default_rng(seed)
        self.emb_w = rng.standard_normal((V, EMB)).astype(np.float32)
        self.proj_w = rng.standard_normal((H, V)).astype(np.float32) * 2.0
        self.proj_b = rng.standard_normal(V).astype(np.float32)
        self.cell = nn.GRUCell(EMB, H)
        # freeze cell weights to known values
        self.wi = rng.standard_normal((3 * H, EMB)).astype(np.float32) * 0.5
        self.wh = rng.standard_normal((3 * H, H)).astype(np.float32) * 0.5
        self.bi = rng.standard_normal(3 * H).astype(np.float32) * 0.1
        self.bh = rng.standard_normal(3 * H).astype(np.float32) * 0.1
        self.cell.weight_ih.set_value(self.wi)
        self.cell.weight_hh.set_value(self.wh)
        self.cell.bias_ih.set_value(self.bi)
        self.cell.bias_hh.set_value(self.bh)
        self.h0 = rng.standard_normal((B, H)).astype(np.float32)

    def embedding_fn(self, ids):
        w = to_tensor(self.emb_w)
        import paddle1_tpu.nn.functional as F
        return F.embedding(ids, w)

    def output_fn(self, h):
        return paddle.matmul(h, to_tensor(self.proj_w)) \
            + to_tensor(self.proj_b)

    # -- numpy twin --
    def np_step(self, x, h):
        def sigmoid(v):
            return 1.0 / (1.0 + np.exp(-v))
        xg = x @ self.wi.T + self.bi
        hg = h @ self.wh.T + self.bh
        xr, xz, xn = np.split(xg, 3, axis=-1)
        hr, hz, hn = np.split(hg, 3, axis=-1)
        r, z = sigmoid(xr + hr), sigmoid(xz + hz)
        n = np.tanh(xn + r * hn)
        return (1 - z) * n + z * h

    def np_logits(self, h):
        return h @ self.proj_w + self.proj_b


def _np_log_softmax(x):
    m = x - x.max(axis=-1, keepdims=True)
    return m - np.log(np.exp(m).sum(axis=-1, keepdims=True))


def _np_greedy_decode(fx, max_steps):
    """Numpy greedy decode loop (GreedyEmbeddingHelper semantics)."""
    h = fx.h0.copy()
    ids = np.full(B, START, np.int64)
    finished = np.zeros(B, bool)
    all_ids, lengths = [], np.zeros(B, np.int64)
    for _ in range(max_steps + 1):
        if finished.all():
            break
        x = fx.emb_w[ids]
        h = fx.np_step(x, h)
        samp = fx.np_logits(h).argmax(-1).astype(np.int64)
        all_ids.append(samp)
        lengths += (~finished).astype(np.int64)
        finished = finished | (samp == END)
        ids = samp
    return np.stack(all_ids, axis=1), lengths


class TestGreedyDecode:
    def test_matches_numpy(self):
        fx = _Seq2SeqFixture()
        helper = nn.GreedyEmbeddingHelper(
            fx.embedding_fn, np.full(B, START, np.int64), END)
        dec = nn.BasicDecoder(fx.cell, helper, output_fn=fx.output_fn)
        outs, final_states, lens = nn.dynamic_decode(
            dec, inits=to_tensor(fx.h0), max_step_num=15,
            return_length=True)
        ref_ids, ref_lens = _np_greedy_decode(fx, 15)
        got = _np(outs.sample_ids)
        assert got.shape[0] == B
        # compare up to each row's decode length (positions past
        # finished keep sampling in both implementations)
        np.testing.assert_array_equal(got[:, :ref_ids.shape[1]], ref_ids)
        np.testing.assert_array_equal(_np(lens), ref_lens)

    def test_cell_outputs_match_states(self):
        fx = _Seq2SeqFixture(seed=5)
        helper = nn.GreedyEmbeddingHelper(
            fx.embedding_fn, np.full(B, START, np.int64), END)
        dec = nn.BasicDecoder(fx.cell, helper, output_fn=fx.output_fn)
        outs, final_states = nn.dynamic_decode(
            dec, inits=to_tensor(fx.h0), max_step_num=4)
        # logits at step 0 = proj(np_step(emb[START], h0))
        h1 = fx.np_step(fx.emb_w[np.full(B, START)], fx.h0)
        np.testing.assert_allclose(_np(outs.cell_outputs)[:, 0],
                                   fx.np_logits(h1), rtol=2e-4,
                                   atol=2e-4)


class TestSampleDecode:
    def test_temperature_and_reproducible_seed(self):
        fx = _Seq2SeqFixture(seed=2)

        def run(seed):
            helper = nn.SampleEmbeddingHelper(
                fx.embedding_fn, np.full(B, START, np.int64), END,
                softmax_temperature=0.7, seed=seed)
            dec = nn.BasicDecoder(fx.cell, helper,
                                  output_fn=fx.output_fn)
            outs, _ = nn.dynamic_decode(dec, inits=to_tensor(fx.h0),
                                        max_step_num=6)
            return _np(outs.sample_ids)
        a, b2 = run(seed=7), run(seed=7)
        np.testing.assert_array_equal(a, b2)
        assert a.min() >= 0 and a.max() < V


class TestTrainingHelper:
    def test_teacher_forcing_matches_rnn(self):
        fx = _Seq2SeqFixture(seed=3)
        rng = np.random.default_rng(4)
        T = 5
        gt = rng.standard_normal((B, T, EMB)).astype(np.float32)
        seq_len = np.array([5, 3, 4], np.int64)
        helper = nn.TrainingHelper(to_tensor(gt), seq_len)
        dec = nn.BasicDecoder(fx.cell, helper, output_fn=fx.output_fn)
        outs, _, lens = nn.dynamic_decode(dec, inits=to_tensor(fx.h0),
                                          return_length=True)
        # numpy: run the cell over ground-truth inputs
        h = fx.h0.copy()
        ref = []
        for t in range(int(seq_len.max())):
            h = fx.np_step(gt[:, t], h)
            ref.append(fx.np_logits(h))
        ref = np.stack(ref, axis=1)
        got = _np(outs.cell_outputs)
        assert got.shape[1] == int(seq_len.max())
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
        np.testing.assert_array_equal(_np(lens), seq_len)

    def test_gradients_flow_to_cell(self):
        fx = _Seq2SeqFixture(seed=6)
        gt = np.random.default_rng(1).standard_normal(
            (B, 4, EMB)).astype(np.float32)
        helper = nn.TrainingHelper(to_tensor(gt), np.full(B, 4, np.int64))
        dec = nn.BasicDecoder(fx.cell, helper, output_fn=fx.output_fn)
        outs, _ = nn.dynamic_decode(dec, inits=to_tensor(fx.h0))
        loss = (outs.cell_outputs ** 2).mean()
        loss.backward()
        g = _np(fx.cell.weight_ih.grad)
        assert np.abs(g).sum() > 0


def _np_beam_decode(fx, beam_size, max_steps):
    """Independent numpy beam search (batch loop, per-beam lists)."""
    K = beam_size
    results = []
    for b in range(B):
        h = np.repeat(fx.h0[b:b + 1], K, axis=0)  # [K, H]
        log_probs = np.array([0.0] + [-1e9] * (K - 1), np.float32)
        tokens = np.full(K, START, np.int64)
        finished = np.zeros(K, bool)
        lengths = np.zeros(K, np.int64)
        step_tokens, step_parents = [], []
        for _ in range(max_steps + 1):
            if finished.all():
                break
            x = fx.emb_w[tokens]
            h_new = fx.np_step(x, h)
            step_lp = _np_log_softmax(fx.np_logits(h_new))  # [K, V]
            noend = np.full(V, -1e9, np.float32)
            noend[END] = 0.0
            step_lp = np.where(finished[:, None], noend[None], step_lp)
            scores = (log_probs[:, None] + step_lp).reshape(-1)
            top = np.argsort(-scores, kind="stable")[:K]
            parents, toks = top // V, (top % V).astype(np.int64)
            log_probs = scores[top]
            finished_new = finished[parents] | (toks == END)
            lengths = lengths[parents] + (~finished[parents]).astype(
                np.int64)
            h = h_new[parents]
            finished = finished_new
            tokens = toks
            step_tokens.append(toks)
            step_parents.append(parents)
        # gather_tree back-trace
        Tn = len(step_tokens)
        seqs = np.zeros((Tn, K), np.int64)
        beam = np.arange(K)
        for t in range(Tn - 1, -1, -1):
            seqs[t] = step_tokens[t][beam]
            beam = step_parents[t][beam]
        results.append((seqs, log_probs, lengths))
    return results


class TestBeamSearchDecode:
    def test_matches_numpy_beam_search(self):
        fx = _Seq2SeqFixture(seed=8)
        K = 4
        dec = nn.BeamSearchDecoder(fx.cell, START, END, K,
                                   embedding_fn=fx.embedding_fn,
                                   output_fn=fx.output_fn)
        ids, final_states, lens = nn.dynamic_decode(
            dec, inits=to_tensor(fx.h0), max_step_num=12,
            output_time_major=True, return_length=True)
        got_ids = _np(ids)            # [T, B, K]
        got_scores = _np(final_states.log_probs)
        got_lens = _np(lens)
        ref = _np_beam_decode(fx, K, 12)
        for b in range(B):
            seqs, scores, lengths = ref[b]
            Tn = seqs.shape[0]
            np.testing.assert_array_equal(got_ids[:Tn, b], seqs)
            np.testing.assert_allclose(got_scores[b], scores,
                                       rtol=2e-4, atol=2e-4)
            np.testing.assert_array_equal(got_lens[b], lengths)

    def test_beam1_equals_greedy(self):
        fx = _Seq2SeqFixture(seed=9)
        dec = nn.BeamSearchDecoder(fx.cell, START, END, 1,
                                   embedding_fn=fx.embedding_fn,
                                   output_fn=fx.output_fn)
        ids, _ = nn.dynamic_decode(dec, inits=to_tensor(fx.h0),
                                   max_step_num=10)
        ref_ids, ref_lens = _np_greedy_decode(fx, 10)
        got = _np(ids)[:, :, 0]       # [B, T]
        for b in range(B):
            L = int(ref_lens[b])
            np.testing.assert_array_equal(got[b, :L], ref_ids[b, :L])

    def test_batch_major_default_and_tile_helper(self):
        fx = _Seq2SeqFixture(seed=10)
        K = 3
        dec = nn.BeamSearchDecoder(fx.cell, START, END, K,
                                   embedding_fn=fx.embedding_fn,
                                   output_fn=fx.output_fn)
        ids, _ = nn.dynamic_decode(dec, inits=to_tensor(fx.h0),
                                   max_step_num=5)
        assert _np(ids).shape[0] == B and _np(ids).shape[2] == K
        enc = to_tensor(np.arange(B * 2, dtype=np.float32).reshape(B, 2))
        tiled = nn.BeamSearchDecoder.tile_beam_merge_with_batch(enc, K)
        tn = _np(tiled)
        assert tn.shape == (B * K, 2)
        np.testing.assert_array_equal(tn[:K], np.repeat(_np(enc)[:1], K,
                                                        axis=0))

    def test_finished_beams_emit_end_fill(self):
        """After a beam finishes, back-traced positions keep sampling
        end tokens: every position at/after the first END is END."""
        fx = _Seq2SeqFixture(seed=11)
        dec = nn.BeamSearchDecoder(fx.cell, START, END, 4,
                                   embedding_fn=fx.embedding_fn,
                                   output_fn=fx.output_fn)
        ids, st, lens = nn.dynamic_decode(
            dec, inits=to_tensor(fx.h0), max_step_num=12,
            output_time_major=True, return_length=True)
        got, ln = _np(ids), _np(lens)
        fin = _np(st.finished)
        for b in range(B):
            for k in range(4):
                if fin[b, k]:
                    seq = got[:, b, k]
                    ends = np.where(seq == END)[0]
                    assert ends.size, seq
                    assert (seq[ends[0]:] == END).all()
                    assert ln[b, k] >= 1


class TestDecodeAccumulationLinear:
    """ISSUE 9 satellite: dynamic_decode's output accumulation must be
    O(steps) — per-step outputs buffered in a host list, ONE stack at
    finalize — never re-concatenated per step (O(steps²) copy work and
    a growing-shape retrace per step). Pinned by an op-count regression
    plus a bit-parity check against the per-step-concat formulation."""

    def _run(self, T, fx=None):
        fx = fx or _Seq2SeqFixture(seed=12)
        gt = np.random.default_rng(7).standard_normal(
            (B, T, EMB)).astype(np.float32)
        helper = nn.TrainingHelper(to_tensor(gt),
                                   np.full(B, T, np.int64))
        dec = nn.BasicDecoder(fx.cell, helper, output_fn=fx.output_fn)
        return nn.dynamic_decode(dec, inits=to_tensor(fx.h0))

    def test_stack_once_and_no_per_step_concat(self, monkeypatch):
        from paddle1_tpu.ops import manip_ops
        counts = {"stack": 0, "concat": 0}
        real_stack, real_concat = manip_ops.stack, manip_ops.concat

        def stack(x, axis=0, name=None):
            counts["stack"] += 1
            return real_stack(x, axis=axis)

        def concat(x, axis=0, name=None):
            counts["concat"] += 1
            return real_concat(x, axis=axis)
        import paddle1_tpu.nn.decode as D
        monkeypatch.setattr(D.manip_ops, "stack", stack)
        monkeypatch.setattr(D.manip_ops, "concat", concat)
        per_T = {}
        for T in (4, 8):
            counts["stack"] = counts["concat"] = 0
            self._run(T)
            per_T[T] = dict(counts)
        # one stack per OUTPUT LEAF (cell_outputs + sample_ids), no
        # driver-side concats — and neither grows with the step count
        assert per_T[4]["stack"] == per_T[8]["stack"] == 2
        assert per_T[4]["concat"] == per_T[8]["concat"] == 0

    def test_parity_with_per_step_concat_accumulation(self, monkeypatch):
        """The finalize-time single stack must be BIT-identical to the
        O(steps²) formulation it replaces (re-concatenating the
        accumulator every step)."""
        fx = _Seq2SeqFixture(seed=12)
        outs, _ = self._run(6, fx)
        ref = _np(outs.cell_outputs)

        from paddle1_tpu.ops import manip_ops
        real_stack = manip_ops.stack

        def stack_via_per_step_concat(x, axis=0, name=None):
            from paddle1_tpu.ops.manip_ops import concat, unsqueeze
            acc = unsqueeze(x[0], axis)
            for t in x[1:]:  # the quadratic re-concat, on purpose
                acc = concat([acc, unsqueeze(t, axis)], axis=axis)
            return acc
        import paddle1_tpu.nn.decode as D
        monkeypatch.setattr(D.manip_ops, "stack",
                            stack_via_per_step_concat)
        outs2, _ = self._run(6, fx)
        monkeypatch.setattr(D.manip_ops, "stack", real_stack)
        np.testing.assert_array_equal(ref, _np(outs2.cell_outputs))

    def test_repeat_runs_bit_identical(self):
        fx = _Seq2SeqFixture(seed=12)
        a, _ = self._run(5, fx)
        b, _ = self._run(5, fx)
        np.testing.assert_array_equal(_np(a.cell_outputs),
                                      _np(b.cell_outputs))
        np.testing.assert_array_equal(_np(a.sample_ids),
                                      _np(b.sample_ids))


class TestFluidSpellings:
    def test_names_resolve(self):
        import paddle1_tpu.fluid.layers as L
        for n in ("dynamic_decode", "BeamSearchDecoder", "BasicDecoder",
                  "TrainingHelper", "GreedyEmbeddingHelper",
                  "SampleEmbeddingHelper", "DecodeHelper", "Decoder"):
            assert getattr(L, n) is getattr(nn, n)
