"""Fluid tier 7 (VERDICT r4 item 4c): py_func, random_crop,
conv3d_transpose, adaptive_pool3d, scatter_nd."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.fluid.layers as L
from paddle1_tpu.core.tensor import to_tensor


class TestPyFunc:
    def test_forward_numpy_roundtrip(self):
        x = to_tensor(np.arange(6, np.float32).reshape(2, 3)
                      if False else
                      np.arange(6, dtype=np.float32).reshape(2, 3))
        out = L.py_func(lambda a: a * 2 + 1, x)
        np.testing.assert_allclose(
            np.asarray(out.numpy()),
            np.arange(6, dtype=np.float32).reshape(2, 3) * 2 + 1)

    def test_multiple_inputs_outputs(self):
        a = to_tensor(np.ones((2, 2), np.float32))
        b = to_tensor(np.full((2, 2), 3.0, np.float32))
        s, p = L.py_func(lambda u, v: (u + v, u * v), [a, b])
        np.testing.assert_allclose(np.asarray(s.numpy()), 4.0)
        np.testing.assert_allclose(np.asarray(p.numpy()), 3.0)

    def test_backward_func_supplies_grad(self):
        x = to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        x.stop_gradient = False

        def fwd(a):
            return np.tanh(a)

        def bwd(a, out, gout):
            return gout * (1 - out ** 2)
        y = L.py_func(fwd, x, backward_func=bwd)
        y.sum().backward()
        ref = 1 - np.tanh(np.asarray([[1, 2], [3, 4]], np.float32)) ** 2
        np.testing.assert_allclose(np.asarray(x.grad.numpy()), ref,
                                   rtol=1e-5)

    def test_skip_input_var(self):
        x = to_tensor(np.array([2.0, 3.0], np.float32))
        x.stop_gradient = False
        argc = {}

        def fwd(a):
            return a * a

        def bwd(*args):
            argc["n"] = len(args)
            return args[-1]
        y = L.py_func(fwd, x, backward_func=bwd,
                      skip_vars_in_backward_input=[x])
        y.sum().backward()
        # backward saw (out, gout) only — x was skipped
        assert argc["n"] == 2


class TestRandomCrop:
    def test_shapes_and_content(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 8, 9)).astype(np.float32)
        out = L.random_crop(to_tensor(x), [5, 6], seed=3)
        o = np.asarray(out.numpy())
        assert o.shape == (4, 5, 6)
        # every cropped instance is a contiguous window of its source
        for b in range(4):
            found = False
            for i in range(8 - 5 + 1):
                for j in range(9 - 6 + 1):
                    if np.allclose(o[b], x[b, i:i + 5, j:j + 6]):
                        found = True
            assert found, b

    def test_instances_draw_distinct_offsets(self):
        # identical content per instance: crops differ iff offsets do
        base = np.arange(100, dtype=np.float32).reshape(10, 10)
        x = np.tile(base, (16, 1, 1))
        out = np.asarray(L.random_crop(to_tensor(x), [4, 4],
                                       seed=11).numpy())
        assert not all(np.array_equal(out[0], out[b])
                       for b in range(1, 16))

    def test_bad_shape(self):
        with pytest.raises(Exception, match="non-batch"):
            L.random_crop(to_tensor(np.zeros((2, 4, 4), np.float32)),
                          [2])


class TestConv3DTranspose:
    def test_shape_and_grad(self):
        x = to_tensor(np.random.default_rng(1).standard_normal(
            (2, 3, 4, 4, 4)).astype(np.float32))
        out = L.conv3d_transpose(x, 5, filter_size=3, stride=2,
                                 name="c3t")
        assert tuple(out.shape) == (2, 5, 9, 9, 9)
        out.sum().backward()

    def test_needs_filter_size(self):
        with pytest.raises(Exception, match="filter_size"):
            L.conv3d_transpose(
                to_tensor(np.zeros((1, 2, 4, 4, 4), np.float32)), 3)


class TestAdaptivePool3D:
    def test_avg_matches_numpy(self):
        x = np.arange(2 * 2 * 4 * 4 * 4, dtype=np.float32).reshape(
            2, 2, 4, 4, 4)
        out = L.adaptive_pool3d(to_tensor(x), [2, 2, 2],
                                pool_type="avg")
        ref = x.reshape(2, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7))
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   rtol=1e-5)

    def test_max(self):
        x = np.random.default_rng(2).standard_normal(
            (1, 1, 6, 6, 6)).astype(np.float32)
        out = L.adaptive_pool3d(to_tensor(x), [3, 3, 3],
                                pool_type="max")
        ref = x.reshape(1, 1, 3, 2, 3, 2, 3, 2).max(axis=(3, 5, 7))
        np.testing.assert_allclose(np.asarray(out.numpy()), ref)


class TestScatterNd:
    def test_matches_numpy(self):
        idx = np.array([[1, 1], [0, 1], [1, 1]], np.int64)
        upd = np.array([9.0, 10.0, 11.0], np.float32)
        out = L.scatter_nd(to_tensor(idx), to_tensor(upd), [2, 3])
        ref = np.zeros((2, 3), np.float32)
        for i, u in zip(idx, upd):
            ref[tuple(i)] += u
        np.testing.assert_allclose(np.asarray(out.numpy()), ref)
