"""Native (C++) runtime: bounded queue, shm arena, stats, multiprocess
DataLoader (reference buffered_reader / mmap_allocator / monitor tests)."""

import pickle
import threading
import unittest

import numpy as np

import paddle1_tpu as paddle
from paddle1_tpu.core import native


class TestNative(unittest.TestCase):
    def test_available(self):
        self.assertTrue(native.available())

    def test_queue_fifo_and_close(self):
        q = native.BoundedQueue(4)
        for i in range(4):
            q.put(pickle.dumps(i))
        got = [pickle.loads(q.get()) for _ in range(4)]
        self.assertEqual(got, [0, 1, 2, 3])
        q.close()
        self.assertIsNone(q.get(timeout_ms=100))

    def test_queue_blocks_when_full(self):
        q = native.BoundedQueue(1)
        q.put(b"a")
        self.assertEqual(q.put(b"b", timeout_ms=50), False)  # timeout

    def test_queue_threaded(self):
        q = native.BoundedQueue(2)
        out = []

        def prod():
            for i in range(20):
                q.put(pickle.dumps(i))
            q.close()

        t = threading.Thread(target=prod)
        t.start()
        while True:
            b = q.get(timeout_ms=2000)
            if b is None:
                break
            out.append(pickle.loads(b))
        t.join()
        self.assertEqual(out, list(range(20)))

    def test_shm_roundtrip(self):
        a = native.ShmArena("/p1t_ut", 1 << 20)
        try:
            x = np.random.randn(17, 5).astype(np.float32)
            d = a.put_array(x)
            np.testing.assert_array_equal(a.get_array(d), x)
            used = a.used()
            self.assertGreater(used, x.nbytes)
            a.reset()
            self.assertLess(a.used(), used)
        finally:
            a.close(unlink=True)

    def test_shm_full_raises(self):
        a = native.ShmArena("/p1t_ut2", 1 << 12)
        try:
            with self.assertRaises(MemoryError):
                for _ in range(10):
                    a.put_array(np.zeros(1024, np.float32))
        finally:
            a.close(unlink=True)

    def test_stats(self):
        native.stat_set("ut_gauge", 7)
        native.stat_add("ut_gauge", 3)
        self.assertEqual(native.stat_get("ut_gauge"), 10)
        self.assertIn("ut_gauge", native.stat_dump())


class TestMultiProcessLoader(unittest.TestCase):
    def test_order_and_parity(self):
        from paddle1_tpu.vision import transforms as T
        from paddle1_tpu.vision.datasets import FakeData
        ds = FakeData(num_samples=48, image_shape=(3, 8, 8), num_classes=3,
                      transform=T.Compose([T.ToTensor()]))
        sp = [b[0].numpy() for b in paddle.io.DataLoader(
            ds, batch_size=8, shuffle=False, num_workers=0)]
        mp_batches = [b[0].numpy() for b in paddle.io.DataLoader(
            ds, batch_size=8, shuffle=False, num_workers=2)]
        self.assertEqual(len(sp), len(mp_batches))
        for a, b in zip(sp, mp_batches):
            np.testing.assert_array_equal(a, b)

    def test_dict_batches(self):
        class DictDs:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return {"x": np.full(4, i, np.float32),
                        "y": np.array([i], np.int64)}

        loader = paddle.io.DataLoader(DictDs(), batch_size=4, shuffle=False,
                                      num_workers=2)
        batches = list(loader)
        self.assertEqual(len(batches), 4)
        b0 = batches[0]
        self.assertIsInstance(b0, dict)
        np.testing.assert_array_equal(b0["y"].numpy().ravel(),
                                      [0, 1, 2, 3])

    def test_arena_recycles_small_arena(self):
        """Total epoch bytes exceed the arena: backpressure + reset must
        keep the pipeline alive instead of raising MemoryError."""
        import os
        os.environ["FLAGS_dataloader_shm_mb"] = "1"
        try:
            class Big:
                def __len__(self):
                    return 64

                def __getitem__(self, i):
                    return (np.full((64, 64), i, np.float32),
                            np.array([i], np.int64))

            loader = paddle.io.DataLoader(Big(), batch_size=4,
                                          shuffle=False, num_workers=1)
            n = 0
            for x, y in loader:
                self.assertEqual(float(x.numpy()[0, 0, 0]), float(n * 4))
                n += 1
            self.assertEqual(n, 16)
        finally:
            os.environ.pop("FLAGS_dataloader_shm_mb", None)

    def test_worker_exception_propagates(self):
        class Bad:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom")
                return np.zeros(4, np.float32), np.array([0], np.int64)

        loader = paddle.io.DataLoader(Bad(), batch_size=4, shuffle=False,
                                      num_workers=1)
        with self.assertRaises(RuntimeError):
            list(loader)
