"""2.0 API parity surface (api_diff tool as a CI gate) + functional
checks for the pieces added to reach it: vision.ops deform_conv2d /
yolo_loss / decode_jpeg, fleet data generators, io.get_worker_info,
static/jit shims."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import to_tensor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _np(t):
    return np.asarray(t.numpy())


class TestApiDiffGate:
    def test_sweep_meets_floors(self):
        """tools/api_diff.py is the api-compat CI check (reference
        tools/check_api_compatible.py role): every namespace must meet
        its pinned floor."""
        env = dict(os.environ,
                   XLA_FLAGS="--xla_force_host_platform_device_count=1",
                   JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable,
                            os.path.join(REPO, "tools", "api_diff.py")],
                           capture_output=True, text=True, env=env,
                           timeout=280)
        assert r.returncode == 0, r.stdout + r.stderr


class TestVisionOps2:
    def test_deform_conv2d_matches_fluid_spelling(self):
        import paddle1_tpu.fluid.layers as L
        from paddle1_tpu.vision.ops import deform_conv2d
        rng = np.random.default_rng(0)
        x = rng.standard_normal((1, 4, 8, 8)).astype(np.float32)
        off = np.zeros((1, 18, 6, 6), np.float32)
        mask = np.ones((1, 9, 6, 6), np.float32)
        # fluid implicit spelling creates the weights; reuse them
        out_fluid = L.deformable_conv(to_tensor(x), to_tensor(off),
                                      to_tensor(mask), 5, 3,
                                      name="parity_dcn")
        import paddle1_tpu.fluid as fluid
        w, b = fluid.layers.implicit_parameters()[-2:]
        out_fn = deform_conv2d(to_tensor(x), to_tensor(off), w, b,
                               mask=to_tensor(mask))
        np.testing.assert_allclose(_np(out_fn), _np(out_fluid),
                                   rtol=1e-5, atol=1e-6)

    def test_DeformConv2D_layer_trains(self):
        from paddle1_tpu.vision.ops import DeformConv2D
        rng = np.random.default_rng(1)
        layer = DeformConv2D(2, 3, 3)
        x = to_tensor(rng.standard_normal((1, 2, 6, 6)).astype(
            np.float32))
        off = to_tensor(np.zeros((1, 18, 4, 4), np.float32))
        out = layer(x, off)
        assert tuple(out.shape) == (1, 3, 4, 4)
        out.sum().backward()
        assert np.abs(_np(layer.weight.grad)).sum() > 0

    def test_DeformConv2D_registers_in_enclosing_layer(self):
        from paddle1_tpu.vision.ops import DeformConv2D

        class Net(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.dcn = DeformConv2D(2, 3, 3)

            def forward(self, x, off):
                return self.dcn(x, off)
        net = Net()
        names = set()
        params = list(net.parameters())
        assert len(params) >= 2  # dcn weight + bias visible
        sd = net.state_dict()
        assert any("dcn" in k for k in sd)

    def test_yolo_loss_smooth_score_scale(self):
        from paddle1_tpu.vision.ops import yolo_loss
        rng = np.random.default_rng(5)
        B, na, C = 1, 3, 4
        x = to_tensor(rng.standard_normal(
            (B, na * (5 + C), 4, 4)).astype(np.float32) * 0.1)
        gt = np.array([[[0.5, 0.5, 0.3, 0.3]]], np.float32)
        gl = np.array([[1]], np.int64)
        kw = dict(anchors=[10, 13, 16, 30, 33, 23],
                  anchor_mask=[0, 1, 2], class_num=C,
                  ignore_thresh=0.7, downsample_ratio=32)
        base = float(np.asarray(yolo_loss(
            x, to_tensor(gt), to_tensor(gl),
            use_label_smooth=False, **kw).numpy()))
        smoothed = float(np.asarray(yolo_loss(
            x, to_tensor(gt), to_tensor(gl),
            use_label_smooth=True, **kw).numpy()))
        assert smoothed != base          # smoothing changes the target
        # gt_score = 0 removes that gt's box/cls contribution
        zeroed = float(np.asarray(yolo_loss(
            x, to_tensor(gt), to_tensor(gl),
            gt_score=to_tensor(np.zeros((1, 1), np.float32)),
            use_label_smooth=False, **kw).numpy()))
        assert zeroed < base
        scaled = float(np.asarray(yolo_loss(
            x, to_tensor(gt), to_tensor(gl), scale_x_y=1.2,
            use_label_smooth=False, **kw).numpy()))
        assert scaled != base            # decode scale shifts targets

    def test_yolo_loss_single_level(self):
        from paddle1_tpu.vision.ops import yolo_loss
        rng = np.random.default_rng(2)
        B, na, C = 2, 3, 4
        x = to_tensor(rng.standard_normal(
            (B, na * (5 + C), 4, 4)).astype(np.float32) * 0.1)
        x.stop_gradient = False
        gt = np.array([[[0.5, 0.5, 0.3, 0.3]],
                       [[0.25, 0.25, 0.2, 0.2]]], np.float32)
        gl = np.array([[1], [2]], np.int64)
        loss = yolo_loss(x, to_tensor(gt), to_tensor(gl),
                         anchors=[10, 13, 16, 30, 33, 23],
                         anchor_mask=[0, 1, 2], class_num=C,
                         ignore_thresh=0.7, downsample_ratio=32)
        v = float(np.asarray(loss.numpy()))
        assert v > 0
        loss.backward()
        assert np.abs(_np(x.grad)).sum() > 0

    def test_decode_jpeg_roundtrip(self, tmp_path):
        from paddle1_tpu.core.jpeg import encode_jpeg_bytes
        from paddle1_tpu.vision.ops import decode_jpeg, read_file
        y, xg = np.mgrid[0:24, 0:32]
        img = np.stack([(xg * 5) % 256, (y * 7) % 256,
                        ((xg + y) * 3) % 256], -1).astype(np.uint8)
        img = img // 8 * 8
        p = tmp_path / "t.jpg"
        p.write_bytes(encode_jpeg_bytes(img, quality=92))
        raw = read_file(str(p))
        out = _np(decode_jpeg(raw))
        assert out.shape == (3, 24, 32)  # CHW like the reference
        err = np.abs(out.transpose(1, 2, 0).astype(float)
                     - img.astype(float))
        assert err.mean() < 8, err.mean()

    def test_decode_jpeg_rejects_progressive(self):
        from paddle1_tpu.vision.ops import decode_jpeg
        # minimal stream with a progressive SOF2 marker
        bad = (b"\xff\xd8\xff\xc2\x00\x0b\x08\x00\x08\x00\x08\x01"
               b"\x01\x11\x00\xff\xd9")
        with pytest.raises(Exception, match="progressive|baseline"):
            decode_jpeg(to_tensor(np.frombuffer(bad, np.uint8).copy()))


class TestDataGenerators:
    def test_multislot_lines(self):
        from paddle1_tpu.distributed.fleet import MultiSlotDataGenerator

        class G(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("ids", [1, 2, 3]), ("label", [0])]
                    yield [("ids", [7]), ("label", [1])]
                return it
        lines = G().run_from_memory()
        assert lines == ["3 1 2 3 1 0\n", "1 7 1 1\n"]

    def test_generate_batch_hook_applies(self):
        from paddle1_tpu.distributed.fleet import MultiSlotDataGenerator

        class G(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    for i in range(5):
                        yield [("v", [i])]
                return it

            def generate_batch(self, samples):
                def it():
                    # batch-level transform: offset every value by 100
                    for s in samples:
                        yield [(n, [v + 100 for v in vals])
                               for n, vals in s]
                return it
        g = G()
        g.set_batch(2)
        lines = g.run_from_memory()
        assert lines == ["1 100\n", "1 101\n", "1 102\n", "1 103\n",
                         "1 104\n"]

    def test_multislot_validates_slot_order(self):
        from paddle1_tpu.distributed.fleet import MultiSlotDataGenerator

        class Bad(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("a", [1])]
                    yield [("b", [1])]
                return it
        with pytest.raises(ValueError, match="slot"):
            Bad().run_from_memory()

    def test_string_generator_and_dataset_roundtrip(self, tmp_path):
        from paddle1_tpu.distributed.fleet import \
            MultiSlotStringDataGenerator

        class G(MultiSlotStringDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("f", ["0.5", "1.5"]), ("lbl", ["1"])]
                return it
        lines = G().run_from_memory()
        assert lines == ["2 0.5 1.5 1 1\n"]
        # the emitted protocol parses through the dataset reader
        p = tmp_path / "gen.txt"
        p.write_text("".join(lines))
        ds = paddle.io.QueueDataset()
        ds.set_filelist([str(p)])
        ds.set_rank_world(0, 1)
        rows = [r for r in iter(ds)]
        assert len(rows) == 1


class TestWorkerInfo:
    def test_main_process_none(self):
        assert paddle.io.get_worker_info() is None

    def test_worker_sees_info(self):
        seen = {}

        class DS(paddle.io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                wi = paddle.io.get_worker_info()
                return np.asarray(
                    [i, -1 if wi is None else wi.id,
                     -1 if wi is None else wi.num_workers],
                    np.int64)
        dl = paddle.io.DataLoader(DS(), batch_size=4, num_workers=2,
                                  shuffle=False)
        batches = [np.asarray(b.numpy()) for b in dl]
        got = np.concatenate(batches)
        assert (got[:, 1] >= 0).all()      # worker id visible
        assert (got[:, 2] == 2).all()      # num_workers visible


class TestStaticJitShims:
    def test_append_backward_returns_param_grads(self):
        import paddle1_tpu.fluid as fluid
        import paddle1_tpu.static as S
        fluid.layers.reset_parameter_pass()
        x = to_tensor(np.ones((2, 3), np.float32))
        out = fluid.layers.fc(x, 4, name="ab_fc")
        pairs = S.append_backward(out.sum())
        assert pairs and all(g is not None for _, g in pairs)

    def test_program_state_roundtrip(self, tmp_path):
        import paddle1_tpu.fluid as fluid
        import paddle1_tpu.static as S
        fluid.layers.reset_parameter_pass()
        x = to_tensor(np.ones((1, 2), np.float32))
        fluid.layers.fc(x, 2, name="ps_fc")
        path = str(tmp_path / "m")
        S.save(None, path)
        st = S.load_program_state(path)
        assert st
        S.set_program_state(None, st)

    def test_traced_layer(self):
        from paddle1_tpu.jit import TracedLayer
        lin = paddle.nn.Linear(3, 2)
        x = to_tensor(np.ones((1, 3), np.float32))
        outs, traced = TracedLayer.trace(lin, [x])
        np.testing.assert_allclose(_np(traced(x)), _np(lin(x)),
                                   rtol=1e-6)
