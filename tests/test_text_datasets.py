"""Text dataset parsers (paddle1_tpu/text/datasets.py) against
miniature archives synthesized in the OFFICIAL formats (no network
egress; reference parsers: python/paddle/text/datasets/)."""

import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle1_tpu.text import (Conll05st, Imikolov, Movielens, WMT14,
                              WMT16)


def _tar_add(tf, name, data: bytes):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture()
def ptb_tgz(tmp_path):
    p = tmp_path / "simple-examples.tgz"
    train = "the cat sat\nthe dog sat\nthe cat ran\n" * 20
    valid = "the cat sat\n" * 5
    with tarfile.open(p, "w:gz") as tf:
        _tar_add(tf, "./simple-examples/data/ptb.train.txt",
                 train.encode())
        _tar_add(tf, "./simple-examples/data/ptb.valid.txt",
                 valid.encode())
    return str(p)


class TestImikolov:
    def test_ngram_windows(self, ptb_tgz):
        ds = Imikolov(ptb_tgz, data_type="NGRAM", window_size=3,
                      min_word_freq=1)
        assert len(ds) > 0
        sample = ds[0]
        assert sample.shape == (3,)
        # dict: frequency-sorted, <unk> last
        assert ds.word_idx["<unk>"] == len(ds.word_idx) - 1
        assert ds.word_idx["the"] < ds.word_idx["dog"]

    def test_seq_mode_shifted_pair(self, ptb_tgz):
        ds = Imikolov(ptb_tgz, data_type="SEQ", min_word_freq=1)
        src, trg = ds[0]
        assert len(src) == len(trg)
        np.testing.assert_array_equal(src[1:], trg[:-1])

    def test_cutoff_drops_rare_words(self, ptb_tgz):
        ds = Imikolov(ptb_tgz, data_type="NGRAM", window_size=2,
                      min_word_freq=30)
        assert "dog" not in ds.word_idx  # appears 20x <= 30


@pytest.fixture()
def ml1m_zip(tmp_path):
    p = tmp_path / "ml-1m.zip"
    movies = "1::Toy Story (1995)::Animation|Children's\n" \
             "2::Heat (1995)::Action|Crime\n"
    users = "1::M::25::4::55455\n2::F::35::7::55117\n"
    ratings = "1::1::5::978300760\n1::2::3::978302109\n" \
              "2::1::4::978301968\n"
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("ml-1m/movies.dat", movies)
        zf.writestr("ml-1m/users.dat", users)
        zf.writestr("ml-1m/ratings.dat", ratings)
    return str(p)


class TestMovielens:
    def test_parse_and_fields(self, ml1m_zip):
        ds = Movielens(ml1m_zip, mode="train", test_ratio=0.0)
        assert len(ds) == 3
        mid, cids, tids, uid, g, age, job, r = ds[0]
        assert mid[0] == 1 and uid[0] == 1
        assert g[0] == 0 and age[0] == 25 and job[0] == 4
        assert r[0] == 5.0
        assert len(ds.categories_dict) == 4  # Animation,Children's,Action,Crime
        # female user mapped to 1
        _, _, _, uid2, g2, _, _, _ = ds[2]
        assert uid2[0] == 2 and g2[0] == 1

    def test_split_disjoint(self, ml1m_zip):
        tr = Movielens(ml1m_zip, mode="train", test_ratio=0.5,
                       rand_seed=3)
        te = Movielens(ml1m_zip, mode="test", test_ratio=0.5, rand_seed=3)
        assert len(tr) + len(te) == 3


@pytest.fixture()
def conll_tgz(tmp_path):
    p = tmp_path / "conll05st-tests.tar.gz"
    # two sentences; first has 2 predicates (2 prop columns)
    words = "The\ncat\nsat\n\nDogs\nbark\n\n"
    props = ("-\t(A0*\t(A0*\n"
             "-\t*)\t*)\n"
             "sit\t(V*)\t(V*)\n"
             "\n"
             "-\t(A0*)\n"
             "bark\t(V*)\n"
             "\n")
    with tarfile.open(p, "w:gz") as tf:
        _tar_add(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                 gzip.compress(words.encode()))
        _tar_add(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                 gzip.compress(props.encode()))
    return str(p)


class TestConll05st:
    def test_one_sample_per_predicate(self, conll_tgz):
        ds = Conll05st(conll_tgz)
        assert len(ds) == 3  # 2 predicates + 1 predicate
        words, pred, labels = ds[0]
        assert words.shape == labels.shape == (3,)
        inv_label = {v: k for k, v in ds.label_dict.items()}
        tags = [inv_label[i] for i in labels]
        assert tags == ["B-A0", "I-A0", "B-V"]
        inv_pred = {v: k for k, v in ds.predicate_dict.items()}
        assert inv_pred[int(pred[0])] == "sit"

    def test_single_token_span_closes(self, conll_tgz):
        ds = Conll05st(conll_tgz)
        words, pred, labels = ds[2]  # "Dogs bark"
        inv = {v: k for k, v in ds.label_dict.items()}
        assert [inv[i] for i in labels] == ["B-A0", "B-V"]


@pytest.fixture()
def wmt14_tgz(tmp_path):
    p = tmp_path / "wmt14.tgz"
    src_dict = "<s>\n<e>\n<unk>\nle\nchat\nnoir\n"
    trg_dict = "<s>\n<e>\n<unk>\nthe\ncat\nblack\n"
    train = "le chat\tthe cat\nle noir\tthe black\n"
    test = "le chat noir\tthe black cat\n"
    with tarfile.open(p, "w:gz") as tf:
        _tar_add(tf, "wmt14/src.dict", src_dict.encode())
        _tar_add(tf, "wmt14/trg.dict", trg_dict.encode())
        _tar_add(tf, "wmt14/train/train", train.encode())
        _tar_add(tf, "wmt14/test/test", test.encode())
    return str(p)


class TestWMT14:
    def test_triplets(self, wmt14_tgz):
        ds = WMT14(wmt14_tgz, mode="train", dict_size=6)
        assert len(ds) == 2
        src, trg_in, trg_out = ds[0]
        np.testing.assert_array_equal(src, [3, 4])       # le chat
        assert trg_in[0] == ds.trg_ids["<s>"]
        assert trg_out[-1] == ds.trg_ids["<e>"]
        np.testing.assert_array_equal(trg_in[1:], trg_out[:-1])

    def test_unk_and_dict_cap(self, wmt14_tgz):
        ds = WMT14(wmt14_tgz, mode="test", dict_size=4)  # drops chat/noir
        src, _, _ = ds[0]
        unk = ds.src_ids["<unk>"]
        np.testing.assert_array_equal(src, [3, unk, unk])

    def test_requires_dict_size(self, wmt14_tgz):
        with pytest.raises(ValueError, match="dict_size"):
            WMT14(wmt14_tgz)


@pytest.fixture()
def wmt16_tar(tmp_path):
    p = tmp_path / "wmt16.tar"
    train = "the cat\tdie katze\nthe dog\tder hund\n"
    val = "the cat\tdie katze\n"
    with tarfile.open(p, "w") as tf:
        _tar_add(tf, "wmt16/train", train.encode())
        _tar_add(tf, "wmt16/val", val.encode())
        _tar_add(tf, "wmt16/test", val.encode())
    return str(p)


class TestWMT16:
    def test_dict_built_from_train(self, wmt16_tar):
        ds = WMT16(wmt16_tar, mode="val", src_dict_size=10,
                   trg_dict_size=10)
        assert len(ds) == 1
        src, trg_in, trg_out = ds[0]
        assert ds.src_ids["<s>"] == 0 and ds.src_ids["<unk>"] == 2
        assert "the" in ds.src_ids and "katze" in ds.trg_ids
        assert trg_in[0] == 0 and trg_out[-1] == 1

    def test_lang_swap(self, wmt16_tar):
        en = WMT16(wmt16_tar, mode="train", src_dict_size=10,
                   trg_dict_size=10, lang="en")
        de = WMT16(wmt16_tar, mode="train", src_dict_size=10,
                   trg_dict_size=10, lang="de")
        assert "the" in en.src_ids and "the" in de.trg_ids

    def test_tiny_dict_teaches(self, wmt14_tgz):
        with pytest.raises(ValueError, match="special tokens"):
            WMT14(wmt14_tgz, mode="train", dict_size=2)
