"""Test configuration: force a virtual 8-device CPU mesh so distributed/
sharding logic is exercised without a TPU pod (SURVEY §4: the reference has
no simulated-topology backend — we make one a first-class test fixture)."""

import os

# Under axon the JAX_PLATFORMS env var is pinned to the tunnel TPU; the
# config knob below still wins, so set both.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The XLA default runs matmul/conv at bf16 (MXU semantics) even in the CPU
# sim; pin f32 so finite-difference gradient checks are meaningful.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "float32")
