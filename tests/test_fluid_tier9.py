"""Fluid tier 9: psroi/prroi/deformable roi pooling,
roi_perspective_transform, retinanet target/output, RCNN
proposal/mask label generators — numpy references from the C++
kernels (psroi_pool_op.h, prroi_pool_op.h,
deformable_psroi_pooling_op.h, rpn_target_assign_op.cc retinanet
branch, generate_proposal_labels_op.cc)."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.fluid.layers as L
from paddle1_tpu.core.tensor import to_tensor


def _np(t):
    return np.asarray(t.numpy())


class TestPsroiPool:
    def test_matches_kernel_loop(self):
        rng = np.random.default_rng(0)
        oc, ph, pw = 2, 2, 2
        C = oc * ph * pw
        x = rng.standard_normal((1, C, 8, 8)).astype(np.float32)
        rois = np.array([[0, 0, 7, 7], [2, 2, 5, 6]], np.float32)
        out = _np(L.psroi_pool(to_tensor(x), to_tensor(rois), oc, 1.0,
                               ph, pw))
        assert out.shape == (2, oc, ph, pw)
        # numpy twin of the kernel loop
        for n, roi in enumerate(rois):
            sw, sh = round(roi[0]) * 1.0, round(roi[1]) * 1.0
            ew, eh = (round(roi[2]) + 1), (round(roi[3]) + 1)
            bh = max(eh - sh, 0.1) / ph
            bw = max(ew - sw, 0.1) / pw
            for c in range(oc):
                for i in range(ph):
                    for j in range(pw):
                        hs = int(np.floor(i * bh + sh))
                        he = int(np.ceil((i + 1) * bh + sh))
                        ws = int(np.floor(j * bw + sw))
                        we = int(np.ceil((j + 1) * bw + sw))
                        hs, he = max(hs, 0), min(he, 8)
                        ws, we = max(ws, 0), min(we, 8)
                        ch = (c * ph + i) * pw + j
                        ref = x[0, ch, hs:he, ws:we].mean() \
                            if he > hs and we > ws else 0.0
                        np.testing.assert_allclose(
                            out[n, c, i, j], ref, rtol=2e-5,
                            atol=1e-6)

    def test_channel_check(self):
        with pytest.raises(Exception, match="channels"):
            L.psroi_pool(to_tensor(np.zeros((1, 7, 4, 4), np.float32)),
                         to_tensor(np.zeros((1, 4), np.float32)),
                         2, 1.0, 2, 2)


class TestPrroiPool:
    def test_constant_map_gives_constant(self):
        x = np.full((1, 1, 6, 6), 3.0, np.float32)
        rois = np.array([[0.7, 0.9, 4.3, 4.9]], np.float32)
        out = _np(L.prroi_pool(to_tensor(x), to_tensor(rois), pooled_height=2,
                               pooled_width=2))
        np.testing.assert_allclose(out, 3.0, rtol=1e-5)

    def test_linear_ramp_integral(self):
        # f(x, y) = x (bilinear of a ramp is the ramp): bin average
        # over [a, b] must be the midpoint (a+b)/2
        W = 8
        x = np.tile(np.arange(W, dtype=np.float32), (1, 1, W, 1))
        rois = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
        out = _np(L.prroi_pool(to_tensor(x), to_tensor(rois), pooled_height=1,
                               pooled_width=2))
        # two bins along x: [1,3] and [3,5] -> means 2 and 4
        np.testing.assert_allclose(out[0, 0, 0], [2.0, 4.0],
                                   rtol=1e-5)

    def test_roi_coordinate_gradients(self):
        rng = np.random.default_rng(1)
        x = to_tensor(rng.standard_normal((1, 2, 6, 6)).astype(
            np.float32))
        rois = to_tensor(np.array([[1.2, 1.1, 4.4, 4.6]], np.float32))
        x.stop_gradient = False
        rois.stop_gradient = False
        out = L.prroi_pool(x, rois, pooled_height=2, pooled_width=2)
        out.sum().backward()
        assert np.abs(_np(x.grad)).sum() > 0
        assert np.abs(_np(rois.grad)).sum() > 0   # coordinate grads


class TestDeformableRoiPooling:
    def test_zero_trans_equals_average_of_samples(self):
        x = np.full((1, 3, 8, 8), 2.5, np.float32)
        rois = np.array([[1, 1, 6, 6]], np.float32)
        trans = np.zeros((1, 2, 2, 2), np.float32)
        out = _np(L.deformable_roi_pooling(
            to_tensor(x), to_tensor(rois), to_tensor(trans),
            no_trans=True, pooled_height=2, pooled_width=2,
            sample_per_part=2))
        assert out.shape == (1, 3, 2, 2)
        np.testing.assert_allclose(out, 2.5, rtol=1e-5)

    def test_trans_shifts_sampling(self):
        # left half 0, right half 10; positive x-offset moves bins
        # toward the larger values
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[:, :, :, 4:] = 10.0
        rois = np.array([[0, 0, 5, 5]], np.float32)
        z = np.zeros((1, 2, 1, 1), np.float32)
        t = np.zeros((1, 2, 1, 1), np.float32)
        t[0, 0] = 3.0  # x-offset * trans_std(0.1) * roi_w
        base = _np(L.deformable_roi_pooling(
            to_tensor(x), to_tensor(rois), to_tensor(z),
            pooled_height=2, pooled_width=2, sample_per_part=2,
            part_size=(1, 1)))
        shifted = _np(L.deformable_roi_pooling(
            to_tensor(x), to_tensor(rois), to_tensor(t),
            pooled_height=2, pooled_width=2, sample_per_part=2,
            part_size=(1, 1)))
        assert shifted.sum() > base.sum()

    def test_position_sensitive_channel_map(self):
        # C=4, group 2x2, out_dim=1: each bin reads its own channel
        x = np.zeros((1, 4, 4, 4), np.float32)
        for c in range(4):
            x[0, c] = c + 1
        rois = np.array([[0, 0, 3, 3]], np.float32)
        z = np.zeros((1, 2, 1, 1), np.float32)
        out = _np(L.deformable_roi_pooling(
            to_tensor(x), to_tensor(rois), to_tensor(z),
            no_trans=True, group_size=(2, 2), pooled_height=2,
            pooled_width=2, sample_per_part=2,
            position_sensitive=True))
        assert out.shape == (1, 1, 2, 2)
        np.testing.assert_allclose(out[0, 0],
                                   [[1.0, 2.0], [3.0, 4.0]],
                                   rtol=1e-5)


class TestRoiPerspective:
    def test_axis_aligned_quad_equals_resize(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((1, 1, 8, 8)).astype(np.float32)
        # quad covering [1,1]..[5,5] axis-aligned (clockwise)
        quad = np.array([[1, 1, 5, 1, 5, 5, 1, 5]], np.float32)
        out, mask, mat = L.roi_perspective_transform(
            to_tensor(x), to_tensor(quad), 5, 5)
        o = _np(out)
        assert o.shape == (1, 1, 5, 5)
        assert _np(mask).min() == 1.0  # fully inside
        # corners map exactly onto the quad corners
        np.testing.assert_allclose(o[0, 0, 0, 0], x[0, 0, 1, 1],
                                   rtol=1e-5)
        np.testing.assert_allclose(o[0, 0, 4, 4], x[0, 0, 5, 5],
                                   rtol=1e-5)

    def test_grad_flows(self):
        x = to_tensor(np.random.default_rng(3).standard_normal(
            (1, 2, 6, 6)).astype(np.float32))
        x.stop_gradient = False
        quad = np.array([[0, 0, 4, 1, 5, 5, 1, 4]], np.float32)
        out, _, _ = L.roi_perspective_transform(x, to_tensor(quad),
                                                4, 4)
        out.sum().backward()
        assert np.abs(_np(x.grad)).sum() > 0


class TestRetinanetTargetAssign:
    def test_all_anchors_used_class_labels(self):
        ys, xs = np.meshgrid(np.arange(0, 16, 8), np.arange(0, 16, 8),
                             indexing="ij")
        a = np.stack([xs.ravel(), ys.ravel(), xs.ravel() + 7,
                      ys.ravel() + 7], 1).astype(np.float32)
        M, C = a.shape[0], 3
        rng = np.random.default_rng(4)
        bp = rng.standard_normal((1, M, 4)).astype(np.float32)
        cl = rng.standard_normal((1, M, C)).astype(np.float32)
        gt = np.array([[[0, 0, 7, 7]]], np.float32)
        gtl = np.array([[2]], np.int64)
        info = np.array([[16, 16, 1.0]], np.float32)
        (ps, pl, tl, tb, iw,
         fg_num) = L.retinanet_target_assign(
            to_tensor(bp), to_tensor(cl), to_tensor(a), None,
            to_tensor(gt), to_tensor(gtl), None, to_tensor(info),
            num_classes=C)
        lab = _np(tl).ravel()
        # the matching anchor carries class 2; others are bg 0; NO
        # subsampling: all anchors scored
        assert lab.shape[0] == M
        assert (lab == 2).sum() == 1
        assert _np(fg_num).ravel()[0] == 2  # fg + 1
        assert _np(ps).shape == (M, C)
        # perfect-match anchor encodes to zero deltas
        assert np.abs(_np(tb)).max() < 1e-5

    def test_detection_output_decodes(self):
        a = np.array([[0, 0, 7, 7], [8, 8, 15, 15]], np.float32)
        d = np.zeros((2, 4), np.float32)
        s = np.array([[0.9, 0.01], [0.02, 0.8]], np.float32)
        info = np.array([[16, 16, 1.0]], np.float32)
        out = _np(L.retinanet_detection_output(
            [to_tensor(d)], [to_tensor(s)], [to_tensor(a)],
            to_tensor(info), score_threshold=0.5))
        assert out.shape[0] == 2
        row0 = out[out[:, 0] == 0][0]
        np.testing.assert_allclose(row0[2:], a[0], atol=1e-4)


class TestGenerateProposalLabels:
    def test_sampling_and_class_slot_targets(self):
        rois = np.array([[0, 0, 7, 7], [20, 20, 27, 27],
                         [1, 1, 8, 8], [40, 40, 47, 47]], np.float32)
        gt = np.array([[[0, 0, 7, 7], [20, 20, 27, 27]]], np.float32)
        gtc = np.array([[1, 2]], np.int64)
        info = np.array([[64, 64, 1.0]], np.float32)
        (out_rois, labels, tgts, inw, outw,
         lens) = L.generate_proposal_labels(
            to_tensor(rois), to_tensor(gtc), None, to_tensor(gt),
            to_tensor(info), rois_lengths=np.array([4], np.int64),
            batch_size_per_im=8, fg_thresh=0.5, class_nums=3,
            use_random=False)
        lab = _np(labels).ravel()
        t = _np(tgts)
        assert t.shape[1] == 12
        # fg rois carry their class in the right 4-col slot
        for k, c in enumerate(lab):
            if c > 0:
                assert np.abs(t[k, 4 * c:4 * c + 4]).sum() >= 0
                assert _np(inw)[k, 4 * c:4 * c + 4].sum() == 4
            else:
                assert _np(inw)[k].sum() == 0
        assert (lab > 0).sum() >= 2  # both gt matched (gt appended)
        assert int(_np(lens)[0]) == lab.shape[0]


class TestGenerateMaskLabels:
    def test_bitmap_masks_cropped_to_class_slot(self):
        info = np.array([[8, 8, 1.0]], np.float32)
        m = np.zeros((8, 8), np.uint8)
        m[2:6, 2:6] = 1
        rois = np.array([[2, 2, 5, 5]], np.float32)
        labels = np.array([[1]], np.int32)
        res = 4
        mrois, has, targets, lens = L.generate_mask_labels(
            to_tensor(info), None, None, [[m]], to_tensor(rois),
            to_tensor(labels), num_classes=3, resolution=res,
            rois_lengths=np.array([1], np.int64))
        t = _np(targets)
        assert t.shape == (1, 3 * res * res)
        cls1 = t[0, res * res:2 * res * res].reshape(res, res)
        assert (cls1 == 1).all()          # roi fully inside the mask
        assert (t[0, :res * res] == -1).all()  # other classes ignored
        assert int(_np(lens)[0]) == 1

    def test_empty_segms_image_contributes_nothing(self):
        info = np.array([[8, 8, 1.0]], np.float32)
        rois = np.array([[1, 1, 4, 4]], np.float32)
        labels = np.array([[1]], np.int32)
        mrois, has, targets, lens = L.generate_mask_labels(
            to_tensor(info), None, None, [[]], to_tensor(rois),
            to_tensor(labels), num_classes=2, resolution=2,
            rois_lengths=np.array([1], np.int64))
        assert _np(targets).shape[0] == 0
        assert _np(lens).tolist() == [0]

    def test_polygon_rasterization(self):
        info = np.array([[10, 10, 1.0]], np.float32)
        poly = [[2.0, 2.0, 8.0, 2.0, 8.0, 8.0, 2.0, 8.0]]  # square
        rois = np.array([[3, 3, 7, 7]], np.float32)
        labels = np.array([[2]], np.int32)
        mrois, has, targets, lens = L.generate_mask_labels(
            to_tensor(info), None, None, [[poly]], to_tensor(rois),
            to_tensor(labels), num_classes=3, resolution=2,
            rois_lengths=np.array([1], np.int64))
        t = _np(targets)[0, 2 * 4:3 * 4]
        assert (t == 1).all()  # roi interior of the square
