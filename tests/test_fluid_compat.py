"""fluid compat shim (paddle1_tpu/fluid/): pre-2.0 scripts written
against `import paddle.fluid as fluid` run on the modern surface
(reference python/paddle/fluid/)."""

import numpy as np
import pytest

import paddle1_tpu.fluid as fluid


class TestFluidDygraphScript:
    def test_classic_training_script_shape(self):
        """The canonical fluid dygraph idiom: guard + to_variable +
        layers.fc + cross_entropy + backward + SGDOptimizer."""
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 8)).astype(np.float32)
        Y = (X[:, 0] > 0).astype(np.int64)
        with fluid.dygraph.guard():
            losses = []
            params = None
            opt = None
            for step in range(25):
                x = fluid.dygraph.to_variable(X)
                label = fluid.dygraph.to_variable(Y)
                h = fluid.layers.fc(x, 16, act="relu")
                logits = fluid.layers.fc(h, 2, name="head")
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(
                        logits, fluid.layers.reshape(label, [-1, 1])))
                loss.backward()
                if opt is None:
                    params = fluid.layers.implicit_parameters()
                    opt = fluid.optimizer.SGDOptimizer(
                        learning_rate=0.5, parameters=params)
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.numpy()))
            assert losses[-1] < losses[0] * 0.8

    def test_layer_cache_reuses_weights(self):
        with fluid.dygraph.guard():
            x = fluid.dygraph.to_variable(
                np.ones((2, 4), np.float32))
            a = fluid.layers.fc(x, 3, name="shared")
            b = fluid.layers.fc(x, 3, name="shared")
            np.testing.assert_allclose(a.numpy(), b.numpy())

    def test_ops_subset(self):
        x = fluid.dygraph.to_variable(
            np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_allclose(
            fluid.layers.reduce_sum(x).numpy(), 15.0)
        assert fluid.layers.mul(
            x, fluid.dygraph.to_variable(
                np.ones((3, 2), np.float32))).shape == [2, 2]
        assert fluid.layers.elementwise_add(x, x).shape == [2, 3]
        assert fluid.layers.cast(x, "int32").dtype == "int32"
        assert fluid.layers.fill_constant([2], "float32", 3.0).shape == [2]
        oh = fluid.layers.one_hot(
            fluid.dygraph.to_variable(np.array([0, 2])), 3)
        np.testing.assert_allclose(oh.numpy(),
                                   [[1, 0, 0], [0, 0, 1]])

    def test_cross_entropy_is_prob_space(self):
        # fluid.layers.cross_entropy takes POST-softmax probabilities
        probs = fluid.dygraph.to_variable(
            np.array([[0.9, 0.1], [0.2, 0.8]], np.float32))
        label = fluid.dygraph.to_variable(np.array([0, 1]))
        ce = fluid.layers.cross_entropy(probs, label)
        np.testing.assert_allclose(
            ce.numpy().reshape(-1), [-np.log(0.9), -np.log(0.8)],
            rtol=1e-5)


class TestTeachingErrors:
    def test_moved_op_names_destination(self):
        # r5: the former teaching names are now real implementations
        assert callable(fluid.layers.dynamic_lstm)
        assert callable(fluid.layers.py_func)
        # r4 breadth tier 2: multiclass_nms is now MAPPED (vision.ops)
        assert callable(fluid.layers.multiclass_nms)

    def test_unknown_op_points_at_modern_namespace(self):
        with pytest.raises(AttributeError, match="MIGRATING"):
            fluid.layers.this_never_existed

    def test_disable_dygraph_teaches(self):
        with pytest.raises(RuntimeError, match="to_static"):
            fluid.disable_dygraph()

    def test_global_scope_is_real(self):
        # r5: the scope tree is real — find_var sees live parameters
        # and get_tensor() reads/writes them (reference scope.h idiom)
        import numpy as np
        import paddle1_tpu as paddle
        paddle.seed(0)
        lin = paddle.nn.Linear(3, 2)
        v = fluid.global_scope().find_var(lin.weight.name)
        assert v is not None
        t = v.get_tensor()
        assert np.array(t).shape == (3, 2)
        t.set(np.full((3, 2), 2.0, np.float32))
        np.testing.assert_allclose(np.asarray(lin.weight.numpy()), 2.0)
        # persistable buffers (BN stats) are scope-visible too
        bn = paddle.nn.BatchNorm1D(4)
        assert fluid.global_scope().find_var(bn._mean.name) is not None
        # scope TREE: child lookup falls through to the root
        kid = fluid.global_scope().new_scope()
        kid.var("local").get_tensor().set(
            np.float32(1.0).reshape(()))
        assert kid.find_var(lin.weight.name) is not None
        assert fluid.global_scope().find_var("local") is None
        assert "local" in kid.local_var_names()
        kid2 = kid.new_scope()
        assert kid2.find_var("local") is not None
        fluid.global_scope().drop_kids()
        # shape-mismatched writes are loud
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="shape"):
            fluid.global_scope().find_var(lin.weight.name) \
                 .get_tensor().set(np.zeros((5, 5), np.float32))

    def test_scope_guard_switches_global(self):
        s = fluid.Scope()
        assert isinstance(s, fluid.Scope)   # the real class, lazily
        with fluid.scope_guard(s):
            assert fluid.global_scope() is s
        assert fluid.global_scope() is not s

    def test_fresh_scope_is_isolated(self):
        # review finding: only the global ROOT carries the live-model
        # bridge — a user Scope must be empty (scope_guard isolation)
        import numpy as np
        import paddle1_tpu as paddle
        lin = paddle.nn.Linear(2, 2)
        s = fluid.Scope()
        assert s.find_var(lin.weight.name) is None
        assert s.local_var_names() == []
        # and a fresh variable's first set() DEFINES shape/dtype
        # (reference LoDTensor.set on a new Variable)
        t = s.var("img").get_tensor()
        t.set(np.ones((3, 4), np.float32))
        assert np.array(t).shape == (3, 4)
        # subsequent sets enforce the established shape
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="shape"):
            t.set(np.ones((2, 2), np.float32))

    def test_root_var_does_not_pin_params(self):
        # review finding: var() on a live param must not cache a strong
        # reference (GC pinning / staleness)
        import paddle1_tpu as paddle
        lin = paddle.nn.Linear(2, 2)
        name = lin.weight.name
        fluid.global_scope().var(name)
        assert name not in fluid.global_scope()._vars


class TestAliases:
    def test_optimizer_spellings(self):
        assert fluid.optimizer.SGDOptimizer is fluid.optimizer.SGD
        assert fluid.optimizer.AdamOptimizer is fluid.optimizer.Adam

    def test_places_and_static_shell(self):
        assert fluid.CUDAPlace is fluid.TPUPlace  # "the accelerator"
        assert fluid.Executor is not None
        spec = fluid.data("x", [None, 8])
        assert list(spec.shape) == [None, 8] or list(spec.shape) == [-1, 8]

    def test_initializer_spellings(self):
        assert fluid.initializer.ConstantInitializer \
            is fluid.initializer.Constant
        assert fluid.initializer.MSRAInitializer is not None

    def test_batch_norm_and_pool(self):
        x = fluid.dygraph.to_variable(
            np.random.default_rng(0).standard_normal(
                (2, 3, 8, 8)).astype(np.float32))
        y = fluid.layers.batch_norm(x, act="relu")
        assert y.shape == [2, 3, 8, 8]
        assert float(y.numpy().min()) >= 0.0
        p = fluid.layers.pool2d(x, pool_size=2, pool_type="max",
                                pool_stride=2)
        assert p.shape == [2, 3, 4, 4]
        g = fluid.layers.pool2d(x, global_pooling=True, pool_type="avg")
        assert g.shape == [2, 3, 1, 1]


class TestReviewRegressions:
    def test_distinct_fc_call_sites_do_not_weight_tie(self):
        x = fluid.dygraph.to_variable(
            np.random.default_rng(0).standard_normal(
                (2, 64)).astype(np.float32))
        h1 = fluid.layers.fc(x, 64)
        h2 = fluid.layers.fc(x, 64)  # different line: different weights
        assert not np.allclose(h1.numpy(), h2.numpy())

    def test_loop_call_site_reuses_weights(self):
        # training-shaped loop: backward() ends the pass, so the next
        # iteration reuses the same implicit parameters
        x = fluid.dygraph.to_variable(
            np.ones((1, 4), np.float32))
        outs = []
        for _ in range(2):
            y = fluid.layers.fc(x, 3)
            outs.append(y.numpy())
            y.sum().backward()
        np.testing.assert_allclose(outs[0], outs[1])

    def test_same_line_two_creations_train_distinct_params(self):
        # reference per-creation semantics (VERDICT r3 weak #7): two
        # textual calls on ONE line are two parameter sets
        x = fluid.dygraph.to_variable(
            np.random.default_rng(3).standard_normal(
                (2, 16)).astype(np.float32))
        outs = []
        for _ in range(2):
            a = fluid.layers.fc(x, 16); b = fluid.layers.fc(x, 16)  # noqa: E702,E501
            outs.append((a.numpy(), b.numpy()))
            (a.sum() + b.sum()).backward()
        a1, b1 = outs[0]
        a2, b2 = outs[1]
        assert not np.allclose(a1, b1)  # two creations, distinct weights
        # second pass reuses both, in creation order
        np.testing.assert_allclose(a1, a2)
        np.testing.assert_allclose(b1, b2)

    def test_helper_called_for_two_branches_distinct(self):
        x = fluid.dygraph.to_variable(
            np.random.default_rng(4).standard_normal(
                (2, 8)).astype(np.float32))

        def branch():
            return fluid.layers.fc(x, 8)

        l, r = branch(), branch()
        assert not np.allclose(l.numpy(), r.numpy())
        (l.sum() + r.sum()).backward()
        l2, r2 = branch(), branch()
        np.testing.assert_allclose(l.numpy(), l2.numpy())
        np.testing.assert_allclose(r.numpy(), r2.numpy())

    def test_frozen_overrun_warns_and_reuses(self):
        import warnings as w
        x = fluid.dygraph.to_variable(np.ones((1, 4), np.float32))

        def call():
            return fluid.layers.fc(x, 5)

        y = call()
        y.sum().backward()  # freeze: one creation in the first pass
        with w.catch_warnings(record=True) as rec:
            w.simplefilter("always")
            y1 = call()
            y2 = call()  # overrun: collapses onto y1's weights
        np.testing.assert_allclose(y1.numpy(), y2.numpy())
        assert any("reuse existing weights" in str(r.message) for r in rec)

    def test_conv2d_dilation_not_shared(self):
        x = fluid.dygraph.to_variable(
            np.random.default_rng(0).standard_normal(
                (1, 2, 8, 8)).astype(np.float32))
        a = fluid.layers.conv2d(x, 4, 3, padding=1, dilation=1)
        b = fluid.layers.conv2d(x, 4, 3, padding=2, dilation=2)
        assert a.shape == b.shape == [1, 4, 8, 8]

    def test_elementwise_axis_broadcast(self):
        x = fluid.dygraph.to_variable(
            np.zeros((2, 3, 4, 5), np.float32))
        bias = fluid.dygraph.to_variable(
            np.arange(3, dtype=np.float32))
        out = fluid.layers.elementwise_add(x, bias, axis=1)
        assert out.shape == [2, 3, 4, 5]
        np.testing.assert_allclose(out.numpy()[0, :, 0, 0], [0, 1, 2])

    def test_cross_entropy_rank2_label(self):
        probs = fluid.dygraph.to_variable(
            np.array([[0.9, 0.1], [0.2, 0.8]], np.float32))
        label = fluid.dygraph.to_variable(
            np.array([[0], [1]]))  # the old mandatory [N, 1]
        ce = fluid.layers.cross_entropy(probs, label)
        np.testing.assert_allclose(ce.numpy().reshape(-1),
                                   [-np.log(0.9), -np.log(0.8)],
                                   rtol=1e-5)

    def test_accuracy_topk(self):
        probs = fluid.dygraph.to_variable(np.array(
            [[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]], np.float32))
        label = fluid.dygraph.to_variable(np.array([[1], [0]]))
        acc5 = fluid.layers.accuracy(probs, label, k=3)
        np.testing.assert_allclose(float(acc5.numpy()), 1.0)

    def test_crf_cost_sign(self):
        # fluid's linear_chain_crf is a COST (negative log-likelihood)
        rng = np.random.default_rng(0)
        x = fluid.dygraph.to_variable(
            rng.standard_normal((2, 4, 3)).astype(np.float32))
        y = fluid.dygraph.to_variable(rng.integers(0, 3, (2, 4)))
        cost = fluid.layers.linear_chain_crf(x, y)
        assert float(cost.numpy().mean()) > 0  # -log p >= 0

    def test_rank3_input_rank2_label_cross_entropy(self):
        # sequence probs [B, T, C] with [B, T] labels keep working
        probs = fluid.dygraph.to_variable(
            np.full((2, 1, 2), 0.5, np.float32))
        label = fluid.dygraph.to_variable(np.array([[0], [1]]))
        ce = fluid.layers.cross_entropy(probs, label)
        np.testing.assert_allclose(ce.numpy().reshape(-1),
                                   [np.log(2.0)] * 2, rtol=1e-6)

    def test_same_line_fc_distinct_creations(self):
        # r4: per-creation semantics — one line, two creations, two
        # parameter sets (was a documented weight-tie before)
        x = fluid.dygraph.to_variable(np.ones((1, 4), np.float32))
        a, b = fluid.layers.fc(x, 3), fluid.layers.fc(x, 3)  # one line
        assert not np.allclose(a.numpy(), b.numpy())
        c = fluid.layers.fc(x, 3, name="other")
        assert not np.allclose(a.numpy(), c.numpy())

    def test_crf_heads_separable_by_name(self):
        rng = np.random.default_rng(0)
        x = fluid.dygraph.to_variable(
            rng.standard_normal((1, 3, 4)).astype(np.float32))
        y = fluid.dygraph.to_variable(rng.integers(0, 4, (1, 3)))
        fluid.layers.linear_chain_crf(x, y, param_attr="head_a")
        fluid.layers.linear_chain_crf(x, y, param_attr="head_b")
        from paddle1_tpu.fluid.layers import _crf_param
        assert ("named", "head_a") in _crf_param._params
        assert ("named", "head_b") in _crf_param._params

    def test_rank3_input_rank3_label_cross_entropy(self):
        # fluid's trailing-1 label applies at any rank: [B,T,1] labels
        probs = fluid.dygraph.to_variable(
            np.full((2, 2, 2), 0.5, np.float32))
        label = fluid.dygraph.to_variable(
            np.zeros((2, 2, 1), np.int64))
        ce = fluid.layers.cross_entropy(probs, label)
        np.testing.assert_allclose(np.asarray(ce.numpy()).reshape(-1),
                                   [np.log(2.0)] * 4, rtol=1e-6)
