"""Sampled large-vocab losses (VERDICT r4 missing #3): nce +
sampled_softmax_with_cross_entropy vs numpy references built from the
kernel formulas (nce_op.h cost loop; sample_logits_op + math/sampler.cc
probabilities)."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.fluid as fluid
import paddle1_tpu.fluid.layers as L
from paddle1_tpu.core.tensor import to_tensor

B, DIM, K = 4, 6, 20


def _sig(v):
    return 1.0 / (1.0 + np.exp(-v))


def np_nce_cost(x, w, bias, samples, num_true, q, n_neg, sw=None):
    o = _sig(np.einsum("bd,bsd->bs", x, w[samples])
             + (bias[samples, 0] if bias is not None else 0.0))
    bq = q * n_neg
    cost = np.where(np.arange(samples.shape[1])[None, :] < num_true,
                    -np.log(o / (o + bq)), -np.log(bq / (o + bq)))
    out = cost.sum(axis=1)
    if sw is not None:
        out = out * sw
    return out[:, None]


class TestNCE:
    def _setup(self, name, with_bias=True, num_true=1):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((B, DIM)).astype(np.float32)
        lab = rng.integers(0, K, (B, num_true)).astype(np.int64)
        negs = [1, 3, 5, 7, 11]
        L.nce(to_tensor(x), to_tensor(lab), K, name=name,
              custom_neg_classes=negs,
              bias_attr=True if with_bias else False)
        ps = fluid.layers.implicit_parameters()[-(2 if with_bias else 1):]
        w = (rng.standard_normal((K, DIM)) * 0.5).astype(np.float32)
        ps[0].set_value(w)
        bias = None
        if with_bias:
            bias = (rng.standard_normal((K, 1)) * 0.5).astype(np.float32)
            ps[1].set_value(bias)
        return x, lab, negs, w, bias

    def test_uniform_custom_negs_matches_numpy(self):
        x, lab, negs, w, bias = self._setup("nce_u")
        cost = L.nce(to_tensor(x), to_tensor(lab), K, name="nce_u",
                     custom_neg_classes=negs, bias_attr=True)
        samples = np.concatenate(
            [lab, np.tile(negs, (B, 1))], axis=1)
        q = np.full(samples.shape, 1.0 / K, np.float32)
        ref = np_nce_cost(x, w, bias, samples, 1, q, len(negs))
        np.testing.assert_allclose(np.asarray(cost.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)

    def test_log_uniform_probability_formula(self):
        x, lab, negs, w, bias = self._setup("nce_lu")
        cost = L.nce(to_tensor(x), to_tensor(lab), K, name="nce_lu",
                     custom_neg_classes=negs, sampler="log_uniform",
                     bias_attr=True)
        samples = np.concatenate([lab, np.tile(negs, (B, 1))], axis=1)
        q = (np.log((samples + 2.0) / (samples + 1.0))
             / np.log(K + 1.0)).astype(np.float32)
        ref = np_nce_cost(x, w, bias, samples, 1, q, len(negs))
        np.testing.assert_allclose(np.asarray(cost.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)

    def test_sample_weight_and_no_bias(self):
        x, lab, negs, w, bias = self._setup("nce_sw", with_bias=False)
        sw = np.array([0.5, 1.0, 2.0, 0.0], np.float32)
        cost = L.nce(to_tensor(x), to_tensor(lab), K, name="nce_sw",
                     custom_neg_classes=negs, bias_attr=False,
                     sample_weight=to_tensor(sw[:, None]))
        samples = np.concatenate([lab, np.tile(negs, (B, 1))], axis=1)
        q = np.full(samples.shape, 1.0 / K, np.float32)
        ref = np_nce_cost(x, w, None, samples, 1, q, len(negs), sw=sw)
        np.testing.assert_allclose(np.asarray(cost.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)
        assert float(np.asarray(cost.numpy())[3, 0]) == 0.0

    @pytest.mark.slow  # ~22s convergence soak; the NCE cost-parity
    # cases above stay in-tier (CI heavy step)
    def test_trains_word2vec_style(self):
        """The defining use: large-vocab binary logistic training —
        loss decreases and the gradient reaches input and weight."""
        paddle.seed(7)  # Embedding init draws from the global RNG
        rng = np.random.default_rng(7)
        emb = paddle.nn.Embedding(K, DIM)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=list(emb.parameters())
                                    + fluid.layers.implicit_parameters())
        ctx = rng.integers(0, K, (16,)).astype(np.int64)
        tgt = ((ctx + 1) % K)[:, None]
        losses = []
        for i in range(12):
            vec = emb(to_tensor(ctx))
            cost = L.nce(vec, to_tensor(tgt), K, name="nce_train",
                         num_neg_samples=5, seed=13 + i)
            loss = cost.mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses

    def test_sampler_validation(self):
        with pytest.raises(Exception, match="custom_dist"):
            L.nce(to_tensor(np.zeros((2, DIM), np.float32)),
                  to_tensor(np.zeros((2, 1), np.int64)), K,
                  name="nce_bad", sampler="custom_dist")
        # same teaching error through the custom_neg_classes branch
        with pytest.raises(Exception, match="custom_dist"):
            L.nce(to_tensor(np.zeros((2, DIM), np.float32)),
                  to_tensor(np.zeros((2, 1), np.int64)), K,
                  name="nce_bad2", sampler="custom_dist",
                  custom_neg_classes=[1, 2])


class TestSampledSoftmax:
    def test_customized_samples_match_numpy(self):
        rng = np.random.default_rng(1)
        logits = rng.standard_normal((B, K)).astype(np.float32)
        lab = rng.integers(0, K, (B, 1)).astype(np.int64)
        S = 6
        neg = rng.integers(0, K, (B, S)).astype(np.int64)
        samples = np.concatenate([lab, neg], axis=1)
        probs = rng.random((B, S + 1)).astype(np.float32) * 0.1 + 0.01
        loss = L.sampled_softmax_with_cross_entropy(
            to_tensor(logits), to_tensor(lab), S,
            use_customized_samples=True,
            customized_samples=to_tensor(samples),
            customized_probabilities=to_tensor(probs),
            remove_accidental_hits=False)
        g = np.take_along_axis(logits, samples, axis=1) - np.log(probs)
        m = g - g.max(axis=1, keepdims=True)
        logp = m - np.log(np.exp(m).sum(axis=1, keepdims=True))
        ref = -logp[:, :1]
        np.testing.assert_allclose(np.asarray(loss.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)

    def test_accidental_hits_are_masked(self):
        rng = np.random.default_rng(2)
        logits = rng.standard_normal((2, K)).astype(np.float32)
        lab = np.array([[4], [9]], np.int64)
        # negative column 0 hits the true label of row 0
        neg = np.array([[4, 5, 6], [1, 2, 3]], np.int64)
        samples = np.concatenate([lab, neg], axis=1)
        probs = np.full((2, 4), 0.1, np.float32)
        with_mask = L.sampled_softmax_with_cross_entropy(
            to_tensor(logits), to_tensor(lab), 3,
            use_customized_samples=True,
            customized_samples=to_tensor(samples),
            customized_probabilities=to_tensor(probs),
            remove_accidental_hits=True)
        without = L.sampled_softmax_with_cross_entropy(
            to_tensor(logits), to_tensor(lab), 3,
            use_customized_samples=True,
            customized_samples=to_tensor(samples),
            customized_probabilities=to_tensor(probs),
            remove_accidental_hits=False)
        wm = np.asarray(with_mask.numpy())
        wo = np.asarray(without.numpy())
        assert wm[0, 0] < wo[0, 0]          # hit removed -> lower loss
        np.testing.assert_allclose(wm[1], wo[1], rtol=1e-5)

    def test_num_true_soft_target(self):
        rng = np.random.default_rng(3)
        logits = rng.standard_normal((2, K)).astype(np.float32)
        lab = np.array([[1, 2], [3, 4]], np.int64)
        S = 4
        neg = rng.integers(10, K, (2, S)).astype(np.int64)
        samples = np.concatenate([lab, neg], axis=1)
        probs = np.full((2, S + 2), 0.05, np.float32)
        loss = L.sampled_softmax_with_cross_entropy(
            to_tensor(logits), to_tensor(lab), S, num_true=2,
            use_customized_samples=True,
            customized_samples=to_tensor(samples),
            customized_probabilities=to_tensor(probs),
            remove_accidental_hits=False)
        g = np.take_along_axis(logits, samples, axis=1) - np.log(probs)
        m = g - g.max(axis=1, keepdims=True)
        logp = m - np.log(np.exp(m).sum(axis=1, keepdims=True))
        ref = -(logp[:, :2].sum(axis=1) / 2)[:, None]
        np.testing.assert_allclose(np.asarray(loss.numpy()), ref,
                                   rtol=2e-4, atol=2e-5)

    def test_sampled_path_runs_and_backprops(self):
        rng = np.random.default_rng(4)
        logits = to_tensor(rng.standard_normal((B, K)).astype(
            np.float32))
        logits.stop_gradient = False
        lab = to_tensor(rng.integers(0, K, (B, 1)).astype(np.int64))
        loss = L.sampled_softmax_with_cross_entropy(
            logits, lab, num_samples=5, seed=11)
        assert tuple(loss.shape) == (B, 1)
        loss.sum().backward()
        assert np.abs(np.asarray(logits.grad.numpy())).sum() > 0
