"""HBM-resident sharded embedding (heter_ps analog, VERDICT r4 item 9):
table row-sharded over the mesh in device memory, trained under jit,
matching the host-table result."""

import numpy as np
import jax
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor, to_tensor
from paddle1_tpu.distributed import (HBMShardedEmbedding, ParallelEngine,
                                     build_mesh)
from paddle1_tpu.nn.layer_base import Layer


class _Model(Layer):
    def __init__(self, vocab, dim, axis_size):
        super().__init__()
        self.emb = HBMShardedEmbedding(vocab, dim, axis="sharding",
                                       axis_size=axis_size)
        self.head = paddle.nn.Linear(dim, 1)

    def forward(self, ids):
        return self.head(self.emb(ids).mean(axis=1))


class TestHBMShardedEmbedding:
    def test_eager_lookup_matches_plain_gather(self):
        emb = HBMShardedEmbedding(16, 4)
        ids = to_tensor(np.array([[1, 3], [15, 0]], np.int64))
        out = emb(ids)
        w = np.asarray(emb.weight.numpy())
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   w[np.array([[1, 3], [15, 0]])])

    def test_vocab_pads_to_shard_multiple(self):
        emb = HBMShardedEmbedding(10, 4, axis_size=4)
        assert emb.vocab_size == 12

    def test_sharded_training_matches_single_device(self):
        """The engine trains the row-sharded table in-graph; values must
        match the SAME model trained dp=1 (a host-table/dense-equivalent
        reference)."""
        n = len(jax.devices())
        if n < 8:
            pytest.skip("needs the 8-device CPU mesh")
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (16, 6)).astype(np.int64)
        y = rng.standard_normal((16, 1)).astype(np.float32)

        def run(degrees):
            paddle.seed(7)
            model = _Model(64, 8, axis_size=degrees.get("sharding", 1))
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=model.parameters())
            eng = ParallelEngine(
                model, opt,
                lambda m, b: ((m(Tensor(b["ids"])) - Tensor(b["y"])) ** 2
                              ).mean(),
                mesh=build_mesh(**degrees,
                                devices=jax.devices()[:int(np.prod(
                                    list(degrees.values())))]),
                zero_stage=0)
            for _ in range(3):
                loss = eng.step({"ids": ids, "y": y})
            eng.sync_model()
            return (float(loss),
                    np.asarray(model.emb.weight.numpy()))

        loss1, w1 = run({"dp": 1})
        loss8, w8 = run({"dp": 2, "sharding": 4})
        assert abs(loss1 - loss8) < 1e-4, (loss1, loss8)
        np.testing.assert_allclose(w1, w8, rtol=2e-4, atol=1e-5)

    def test_service_surface_pull_push(self):
        emb = HBMShardedEmbedding(16, 4)
        rows = emb.pull([2, 5])
        assert rows.shape == (2, 4)
        g = np.ones((2, 4), np.float32)
        emb.push_grad([2, 5], g, lr=0.5)
        np.testing.assert_allclose(emb.pull([2, 5]), rows - 0.5,
                                   rtol=1e-6)
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="host tier"):
            emb.pull([99])
        with pytest.raises(InvalidArgumentError, match="-1"):
            emb.pull([-1])  # negative ids must not wrap around
