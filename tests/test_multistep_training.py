"""Device-resident multi-step training (ISSUE 1 tentpole): step_many
fuses k optimizer steps into ONE jitted executable; losses come back as
lazy LossFutures so the host loop never pays a per-step device→host
readback. Acceptance: step_many(k) parity with k sequential step()
calls (params + losses, atol 1e-6, CPU) with exactly one dispatch per
call; Model.fit completes an epoch with zero per-batch readbacks;
DataLoader prefetch threads shut down cleanly after a broken-out loop;
bench.py parses its own JSON line."""

import threading
import time
import unittest
import warnings

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu import nn
from paddle1_tpu.core import async_loss
from paddle1_tpu.core.async_loss import LossFuture
from paddle1_tpu.distributed import ParallelEngine, build_mesh


def _mlp(seed=0):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _clone_into(src, dst):
    dst.set_state_dict({k: paddle.to_tensor(v.numpy().copy())
                        for k, v in src.state_dict().items()})


def _mse_loss(m, b):
    out = m(paddle.to_tensor(b["x"]))
    return ((out - paddle.to_tensor(b["y"])) ** 2).mean()


def _batches(n, bs=4, accum=1, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        b = {"x": rng.standard_normal((bs * accum, 8)).astype(np.float32),
             "y": rng.standard_normal((bs * accum, 4)).astype(np.float32)}
        if accum > 1:
            b = {k: v.reshape((accum, bs) + v.shape[1:])
                 for k, v in b.items()}
        out.append(b)
    return out


def _single_dev_mesh():
    import jax
    return build_mesh(dp=1, devices=jax.devices()[:1])


def _engines(opt_factory, grad_accum=1, **kw):
    net_a, net_b = _mlp(0), _mlp(1)
    _clone_into(net_a, net_b)
    ea = ParallelEngine(net_a, opt_factory(net_a), _mse_loss,
                        mesh=_single_dev_mesh(), grad_accum=grad_accum,
                        **kw)
    eb = ParallelEngine(net_b, opt_factory(net_b), _mse_loss,
                        mesh=_single_dev_mesh(), grad_accum=grad_accum,
                        **kw)
    return (net_a, ea), (net_b, eb)


class TestStepManyParity(unittest.TestCase):
    def _assert_parity(self, opt_factory, k=5, grad_accum=1):
        (net_a, ea), (net_b, eb) = _engines(opt_factory,
                                            grad_accum=grad_accum)
        batches = _batches(k, accum=grad_accum)
        paddle.seed(42)
        seq = [float(ea.step(b)) for b in batches]
        paddle.seed(42)
        fut = eb.step_many(batches)
        self.assertIsInstance(fut, LossFuture)
        many = np.asarray(fut)
        self.assertEqual(many.shape, (k,))
        np.testing.assert_allclose(seq, many, atol=1e-6)
        ea.sync_model()
        eb.sync_model()
        sa, sb = net_a.state_dict(), net_b.state_dict()
        for key in sa:
            np.testing.assert_allclose(np.asarray(sa[key].numpy()),
                                       np.asarray(sb[key].numpy()),
                                       atol=1e-6, err_msg=key)
        return ea, eb

    def test_adamw_parity(self):
        self._assert_parity(lambda m: paddle.optimizer.AdamW(
            learning_rate=1e-2, parameters=m.parameters()))

    def test_grad_accum_composes_with_step_scan(self):
        # outer scan over steps wraps the existing grad-accum inner scan
        self._assert_parity(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()), grad_accum=2)

    def test_lr_schedule_advances_k_times(self):
        from paddle1_tpu.optimizer.lr import StepDecay
        scheds = []

        def factory(m):
            s = StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
            scheds.append(s)
            return paddle.optimizer.SGD(learning_rate=s,
                                        parameters=m.parameters())

        self._assert_parity(factory, k=5)
        # both schedulers saw exactly 5 steps
        self.assertEqual(scheds[0].last_epoch, scheds[1].last_epoch)
        self.assertEqual(scheds[0].last_lr, scheds[1].last_lr)

    def test_exactly_one_dispatch_per_step_many(self):
        (_, ea), (_, eb) = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        batches = _batches(4)
        for b in batches:
            ea.step(b)
        self.assertEqual(ea.dispatch_count, 4)
        eb.step_many(batches)
        self.assertEqual(eb.dispatch_count, 1)
        self.assertEqual(eb.trace_count, 1)
        # second step_many(k=4) reuses the compiled executable
        paddle.seed(7)
        eb.step_many(batches)
        self.assertEqual(eb.dispatch_count, 2)
        self.assertEqual(eb.trace_count, 1)
        self.assertEqual(eb.cache_stats(), {"hits": 1, "misses": 1})

    def test_step_many_of_one_delegates_to_step(self):
        (_, ea), _ = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        fut = ea.step_many(_batches(1))
        self.assertTrue(np.isfinite(float(fut)))
        self.assertEqual(ea.dispatch_count, 1)


class TestRetraceGuard(unittest.TestCase):
    def test_new_batch_shape_warns_once(self):
        (_, ea), _ = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        ea.step(_batches(1, bs=4)[0])
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ea.step(_batches(1, bs=6)[0])   # new shape → retrace warning
            ea.step(_batches(1, bs=2)[0])   # warned once already
        msgs = [str(x.message) for x in w if "retracing" in str(x.message)]
        self.assertEqual(len(msgs), 1)

    def test_guard_respects_flag(self):
        from paddle1_tpu.core.flags import flags_guard
        (_, ea), _ = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        ea.step(_batches(1, bs=4)[0])
        with flags_guard(jit_retrace_warn=False), \
                warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ea.step(_batches(1, bs=6)[0])
        self.assertFalse([x for x in w
                          if "retracing" in str(x.message)])


class TestAsyncLoss(unittest.TestCase):
    def test_handle_matches_eager_readback(self):
        (net_a, ea), (net_b, eb) = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        b = _batches(1)[0]
        paddle.seed(3)
        eager = float(np.asarray(ea.step(b).data))  # direct device fetch
        paddle.seed(3)
        fut = eb.step(b)
        self.assertFalse(fut.materialized)
        self.assertEqual(float(fut), eager)
        self.assertTrue(fut.materialized)
        self.assertEqual(fut.item(), eager)  # cached, same value

    def test_readback_counted_once_per_handle(self):
        async_loss.reset_readback_count()
        (_, ea), _ = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        fut = ea.step(_batches(1)[0])
        self.assertEqual(async_loss.readback_count(), 0)
        fut.block()                       # sync is NOT a readback
        self.assertEqual(async_loss.readback_count(), 0)
        float(fut)
        fut.item()
        np.asarray(fut)
        self.assertEqual(async_loss.readback_count(), 1)

    def test_inflight_window_bounds_queue(self):
        (_, ea), _ = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        self.assertEqual(ea.inflight_window, 2)
        for b in _batches(6):
            ea.step(b)
        self.assertLessEqual(len(ea._inflight), 2)
        ea.drain()
        self.assertEqual(len(ea._inflight), 0)

    def test_numeric_protocol_matches_old_float_returns(self):
        (_, ea), _ = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        fut = ea.step(_batches(1)[0])
        v = fut.item()
        self.assertTrue(fut < v + 1 and fut > v - 1)
        self.assertTrue(v - 1 < fut <= v)
        self.assertEqual(fut + 1.0, v + 1.0)
        self.assertEqual(1.0 + fut, 1.0 + v)
        self.assertEqual(min([fut, v + 5]), v)
        self.assertAlmostEqual(2.0 / fut, 2.0 / v)
        self.assertEqual(-fut, -v)

    def test_formatting_materializes(self):
        (_, ea), _ = _engines(lambda m: paddle.optimizer.SGD(
            learning_rate=0.05, parameters=m.parameters()))
        fut = ea.step(_batches(1)[0])
        s = f"{fut:.4f}"
        self.assertRegex(s, r"^\d+\.\d{4}$")


class _SyntheticDS(paddle.io.Dataset):
    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        return (rng.standard_normal(8).astype(np.float32),
                np.int64(i % 3))


class TestModelFitNoPerBatchReadback(unittest.TestCase):
    def test_silent_epoch_has_zero_readbacks(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 3))
        model = paddle.Model(net)
        model.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        async_loss.reset_readback_count()
        model.fit(_SyntheticDS(), epochs=1, batch_size=8, verbose=0)
        self.assertEqual(async_loss.readback_count(), 0)

    def test_train_batch_returns_lazy_handles(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 3))
        model = paddle.Model(net)
        model.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        out = model.train_batch([np.zeros((4, 8), np.float32)],
                                [np.zeros((4,), np.int64)])
        self.assertIsInstance(out[0], LossFuture)
        self.assertTrue(np.isfinite(float(out[0])))

    def test_verbose_epoch_end_materializes(self):
        # formatting the epoch-end log line IS the materialization point
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 3))
        model = paddle.Model(net)
        model.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        async_loss.reset_readback_count()
        model.fit(_SyntheticDS(64), epochs=1, batch_size=8, verbose=2,
                  log_freq=100)   # one step-0 line + the epoch-end line
        n_batches = 8
        self.assertLessEqual(async_loss.readback_count(), 2)
        self.assertLess(async_loss.readback_count(), n_batches)


class TestDataLoaderMultiStepFeed(unittest.TestCase):
    def test_peek_many_pops_chunks(self):
        loader = paddle.io.DataLoader(_SyntheticDS(32), batch_size=4)
        it = iter(loader)
        chunk = it.peek_many(3)
        self.assertEqual(len(chunk), 3)
        rest = it.peek_many(100)   # truncates at epoch end
        self.assertEqual(len(rest), 5)
        with self.assertRaises(StopIteration):
            it.peek_many(2)

    def test_prefetch_thread_shuts_down_after_break(self):
        loader = paddle.io.DataLoader(_SyntheticDS(64), batch_size=2,
                                      prefetch_factor=2)
        it = iter(loader)
        for i, _ in enumerate(it):
            if i == 1:
                break                      # queue still full, producer live
        it.shutdown()
        deadline = time.time() + 5
        while it._thread.is_alive() and time.time() < deadline:
            time.sleep(0.01)
        self.assertFalse(it._thread.is_alive())

    def test_step_stream_uses_chunk_size(self):
        net = _mlp(0)
        eng = ParallelEngine(
            net, paddle.optimizer.SGD(learning_rate=0.05,
                                      parameters=net.parameters()),
            _mse_loss, mesh=_single_dev_mesh(), train_steps_per_sync=3)
        batches = _batches(7)
        futs = list(eng.step_stream(batches))
        # 7 batches at k=3 → two fused dispatches + 1 sequential
        # remainder step (the tail never compiles a fresh scan)
        self.assertEqual(eng.dispatch_count, 3)
        self.assertEqual(np.asarray(futs[0]).shape, (3,))
        total = sum(np.asarray(f).size for f in futs)
        self.assertEqual(total, 7)

    def test_strategy_knob_reaches_engine(self):
        from paddle1_tpu.distributed.fleet import (DistributedStrategy,
                                                   compile_strategy)
        s = DistributedStrategy()
        s.train_steps_per_sync = 8
        cfg = compile_strategy(s, n_devices=8)
        self.assertEqual(cfg["train_steps_per_sync"], 8)


class TestBenchJson(unittest.TestCase):
    def test_bench_parses_its_own_json_line(self, capsys=None):
        import io
        import sys
        sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
        import bench
        buf = io.StringIO()
        stdout, sys.stdout = sys.stdout, buf
        try:
            rec = bench._emit(
                "bert_base_pretrain_samples_per_sec_per_chip", 123.4,
                "samples/s", 0.5,
                {"steps_per_dispatch": 8, "steps_per_readback": 24,
                 "compile_cache": {"hits": 2, "misses": 1}})
        finally:
            sys.stdout = stdout
        line = buf.getvalue().strip()
        parsed = bench.parse_result_line(line)
        self.assertEqual(parsed, rec)
        self.assertEqual(parsed["detail"]["steps_per_readback"], 24)
        self.assertEqual(parsed["detail"]["compile_cache"],
                         {"hits": 2, "misses": 1})
        with self.assertRaises(ValueError):
            bench.parse_result_line('{"metric": "x"}')
        with self.assertRaises(ValueError):
            bench.parse_result_line("not json at all")


class TestMeshIdentityPassThrough(unittest.TestCase):
    def test_prestaged_same_mesh_passes_other_mesh_replaces(self):
        import jax
        net = _mlp(0)
        eng = ParallelEngine(
            net, paddle.optimizer.SGD(learning_rate=0.05,
                                      parameters=net.parameters()),
            _mse_loss, mesh=build_mesh(dp=2, devices=jax.devices()[:2]))
        b = _batches(1)[0]
        staged = eng.shard_batch(b)
        # same mesh: leaves pass through untouched (no re-placement)
        again = eng.shard_batch(staged)
        for l1, l2 in zip(jax.tree_util.tree_leaves(staged),
                          jax.tree_util.tree_leaves(again)):
            self.assertIs(l1, l2)
        # same axis sizes, DIFFERENT devices: must be re-placed, not
        # passed through (ADVICE r5 mesh-identity fix)
        other = build_mesh(dp=2, devices=jax.devices()[2:4])
        net2 = _mlp(1)
        eng2 = ParallelEngine(
            net2, paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=net2.parameters()),
            _mse_loss, mesh=other)
        self.assertEqual(dict(other.shape), dict(eng.mesh.shape))
        replaced = eng2.shard_batch(staged)
        for leaf in jax.tree_util.tree_leaves(replaced):
            self.assertTrue(set(leaf.sharding.device_set)
                            <= set(np.ravel(other.devices).tolist()))
        # and the re-placed batch still trains
        self.assertTrue(np.isfinite(float(eng2.step(replaced))))


if __name__ == "__main__":
    unittest.main()
