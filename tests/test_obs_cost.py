"""ISSUE 13: the cost observatory — per-executable FLOPs/bytes
attribution (obs.costmodel), live HBM census + leak detector
(obs.hbm), declarative SLOs (obs.slo), the crash flight recorder
(obs.flight), the scrape-vs-drain staleness fix (obs.http), and the
bench trajectory tool (tools/bench_history)."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle1_tpu import obs
from paddle1_tpu.core import flags as core_flags
from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.obs import costmodel, flight as obs_flight
from paddle1_tpu.obs import hbm as obs_hbm
from paddle1_tpu.obs import slo as obs_slo
from paddle1_tpu.obs import trace as obs_trace

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_process_registry()
    obs_hbm.reset()
    obs_flight.reset()
    obs_slo.set_process_slos(None)
    yield
    obs.reset_process_registry()
    obs_hbm.reset()
    obs_flight.reset()
    obs_slo.set_process_slos(None)


def _mlp_engine():
    import jax
    import paddle1_tpu as paddle
    from paddle1_tpu.core.tensor import Tensor
    from paddle1_tpu.distributed import ParallelEngine, build_mesh
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                 paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters())
    loss_fn = lambda m, b: \
        ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()
    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    return ParallelEngine(model, opt, loss_fn, mesh=mesh)


def _batch(rows=4):
    rng = np.random.default_rng(0)
    return {"x": rng.standard_normal((rows, 8)).astype(np.float32),
            "y": rng.standard_normal((rows, 4)).astype(np.float32)}


class TestCostModel:
    def test_analyze_exact_matmul(self):
        import jax
        import jax.numpy as jnp
        x = jnp.ones((32, 32))
        cost = costmodel.analyze(
            lambda: jax.jit(lambda a, b: a @ b).lower(x, x))
        assert cost.exact and cost.source == "xla_cost_analysis"
        # 2*M*N*K MACs-as-2-flops, give or take fusion bookkeeping
        assert cost.flops == pytest.approx(2 * 32 ** 3, rel=0.2)
        assert cost.bytes_accessed > 0

    def test_analyze_failure_degrades_to_labeled_fallback(self):
        fb = costmodel.tree_size_cost({"w": np.zeros((4, 4))},
                                      batch=np.zeros((8, 4)))
        cost = costmodel.analyze(
            lambda: (_ for _ in ()).throw(RuntimeError("no backend")),
            fallback=fb)
        assert cost is fb
        assert not cost.exact
        assert cost.source == "tree_size_heuristic"

    def test_tree_size_heuristic_formula(self):
        params = {"w": np.zeros((4, 4), np.float32)}
        cost = costmodel.tree_size_cost(
            params, batch=np.zeros((8, 4), np.float32))
        assert cost.flops == 2.0 * 16 * 8   # 2 * param elems * rows
        # one read of params+batch, one param-sized write
        assert cost.bytes_accessed == (16 * 4) * 2 + 8 * 4 * 4

    def test_site_cost_memoizes(self):
        costmodel.clear_cache()
        calls = []

        def thunk():
            calls.append(1)
            raise RuntimeError("forces the fallback, still cached")

        fb = costmodel.tree_size_cost({"w": np.zeros((2, 2))})
        a = costmodel.site_cost("site", ("sig",), thunk, fallback=fb)
        b = costmodel.site_cost("site", ("sig",), thunk, fallback=fb)
        assert a is b and len(calls) == 1
        costmodel.clear_cache()

    def test_forward_cost_exact_for_layer(self):
        import paddle1_tpu as paddle
        net = paddle.nn.Sequential(paddle.nn.Linear(16, 32),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(32, 4))
        cost = costmodel.forward_cost(net, (8, 16))
        assert cost.exact
        # dominated by the two matmuls: 2*8*(16*32 + 32*4)
        assert cost.flops == pytest.approx(2 * 8 * (16 * 32 + 32 * 4),
                                           rel=0.3)

    def test_peak_tables(self):
        import jax
        dev = jax.devices()[0]
        assert costmodel.device_peak_flops(dev) > 0
        assert costmodel.device_peak_hbm_bw(dev) > 0

    def test_summary_gains_flops_column(self, capsys):
        import paddle1_tpu as paddle
        net = paddle.nn.Sequential(paddle.nn.Linear(8, 8))
        out = paddle.summary(net, input_size=(4, 8))
        text = capsys.readouterr().out
        assert "FLOPs" in text
        assert out["flops_source"] == "xla_cost_analysis"
        assert out["total_flops"] > 0
        # without an input size the table stays the legacy shape
        out2 = paddle.summary(net)
        assert "total_flops" not in out2


class TestEngineCost:
    def test_step_cost_exact_and_cached(self):
        eng = _mlp_engine()
        b = _batch()
        c1 = eng.step_cost(b)
        c2 = eng.step_cost(b)
        assert c1.exact and c1 is c2
        n_params = 8 * 16 + 16 + 16 * 4 + 4
        # fwd+bwd+opt of a dense MLP: >= the 2*params*rows forward floor
        assert c1.flops >= 2 * n_params * 4

    def test_step_cost_does_not_touch_compile_accounting(self):
        # the acceptance gates read trace_count — the cost lowering
        # must trace the UNCOUNTED body
        eng = _mlp_engine()
        b = _batch()
        float(eng.step(b))
        before = eng.cache_stats()
        eng.step_cost(b)
        assert eng.cache_stats() == before

    def test_mfu_and_cost_gauges_published(self):
        eng = _mlp_engine()
        b = _batch()
        with core_flags.flags_guard(obs_metrics=True):
            for _ in range(3):
                float(eng.step(b))
        g = obs.process_registry().snapshot()["gauges"]
        assert g["train_step_flops"] > 0
        assert g["train_step_bytes"] > 0
        assert g["train_cost_exact"] == 1.0
        assert 0 < g["train_mfu"] < 1.0
        assert 0 < g["train_hbm_bw_util"]
        assert g["hbm_params_bytes"] > 0
        assert g["hbm_census_bytes"] > 0

    def test_disabled_still_structurally_zero(self):
        eng = _mlp_engine()
        float(eng.step(_batch()))
        assert obs.process_registry().empty()
        assert obs_flight.recorder() is None


class TestServingCost:
    def test_bucket_cost_gauges_and_compile_counts(self):
        import paddle1_tpu as paddle
        from paddle1_tpu.serving import InferenceEngine, ServingMetrics
        paddle.seed(0)
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 8))
        model.eval()
        m = ServingMetrics()
        eng = InferenceEngine(model, buckets=(1, 4), metrics=m)
        x = np.ones((1, 8), np.float32)
        with core_flags.flags_guard(obs_metrics=True):
            eng.infer([x])
        cost = eng.bucket_cost([x])
        assert cost.exact
        g = m.snapshot()["gauges"]
        assert g["cost_bucket_1_flops"] > 0
        assert g["cost_bucket_1_bytes"] > 0
        # the uncounted cost lowering left compile accounting intact
        assert eng.compile_counts == {1: 1}

    def test_generation_decode_cost_uncounted(self):
        from paddle1_tpu.serving import CausalLM, GenerationEngine
        lm = CausalLM(vocab_size=16, d_model=8, nhead=2,
                      dim_feedforward=16, num_layers=1, max_seq=16)
        eng = GenerationEngine(lm, slots=2, max_seq=16,
                               prefill_buckets=(4,))
        cost = eng.decode_cost()
        assert cost.exact and cost.flops > 0
        # the compile-ONCE contract untouched: no decode compile ran
        assert eng.decode_compile_count == 0
        pc = eng.prefill_cost(4)
        assert pc.exact and pc.flops > 0
        assert eng.prefill_compile_counts == {}


class TestHbmCensus:
    def test_register_census_and_weakref_death(self):
        class Owner:
            tree = {"a": np.zeros((10,), np.float32)}
        o = Owner()
        obs_hbm.register("params", o, lambda x: x.tree)
        per = obs_hbm.registered_bytes()
        assert per["params"] == 40
        del o
        import gc
        gc.collect()
        assert obs_hbm.registered_bytes()["params"] == 0

    def test_alias_dedup_counts_once(self):
        shared = np.zeros((10,), np.float32)

        class A:
            pass
        a, b = A(), A()
        obs_hbm.register("params", a, lambda x: [shared])
        obs_hbm.register("other", b, lambda x: [shared])
        per = obs_hbm.registered_bytes()
        assert per["params"] == 40 and per["other"] == 0

    def test_unknown_subsystem_folds_into_other(self):
        class A:
            pass
        a = A()
        obs_hbm.register("weird", a, lambda x: [np.zeros(4, np.int8)])
        assert obs_hbm.registered_bytes()["other"] == 4

    def test_census_device_side(self):
        eng = _mlp_engine()
        c = obs_hbm.census()
        assert c["subsystems"]["params"] > 0
        assert c["subsystems"]["opt_state"] > 0
        assert c["device_bytes_in_use"] > 0
        assert 0 < c["coverage_ratio"] <= 1.01
        assert eng is not None  # keep the engine (and weakrefs) alive

    def test_leak_detector_flag_gated(self):
        # disarmed: monotone growth never raises
        for i in range(10):
            obs_hbm.leak_note(1000 + i)
        with core_flags.flags_guard(obs_hbm_leak_steps=3):
            obs_hbm.reset()
            obs_hbm.leak_note(100)
            obs_hbm.leak_note(200)
            obs_hbm.leak_note(300)
            with pytest.raises(obs.HbmLeakSuspected) as ei:
                obs_hbm.leak_note(400)
            assert "consecutive" in str(ei.value)
            # a plateau resets the streak
            obs_hbm.leak_note(100)
            obs_hbm.leak_note(200)
            obs_hbm.leak_note(200)
            obs_hbm.leak_note(300)
            obs_hbm.leak_note(400)
            with pytest.raises(obs.HbmLeakSuspected):
                obs_hbm.leak_note(500)

    def test_publish_gauges(self):
        class A:
            pass
        a = A()
        obs_hbm.register("kv_cache", a,
                         lambda x: [np.zeros((8,), np.float32)])
        m = obs.MetricsRegistry(namespace="p1t")
        total = obs_hbm.publish(m, full=True)
        g = m.snapshot()["gauges"]
        assert g["hbm_kv_cache_bytes"] == 32 and total == 32
        assert "hbm_census_coverage_ratio" in g
        assert "hbm_device_bytes_in_use" in g


class TestSlo:
    def test_parse_grammar(self):
        s = obs_slo.parse_slos(
            "lat=p99(e2e_ms)<50;err=rate(errors_total/requests_total)"
            "<0.01;fresh=stale(age_seconds)<600")
        kinds = [sp.kind for sp in s.specs]
        assert kinds == ["latency_quantile", "error_rate", "staleness"]
        assert s.specs[0].quantile == 99.0

    def test_parse_teaching_errors(self):
        with pytest.raises(InvalidArgumentError) as ei:
            obs_slo.parse_slos("lat=p42(e2e_ms)<50")
        assert "grammar" in str(ei.value)
        with pytest.raises(InvalidArgumentError):
            obs_slo.parse_slos("err=rate(only_one)<0.01")
        with pytest.raises(InvalidArgumentError):
            obs_slo.parse_slos("dup=stale(a)<1;dup=stale(b)<1")

    def test_evaluate_publishes_burn_gauges(self):
        m = obs.MetricsRegistry(namespace="p1t")
        h = m.histogram("e2e_ms")
        for _ in range(10):
            h.observe(80.0)
        s = obs_slo.parse_slos("lat=p99(e2e_ms)<50")
        v = s.evaluate(m)
        assert v["lat"]["ok"] is False
        assert v["lat"]["burn_rate"] == pytest.approx(1.6)
        g = m.snapshot()["gauges"]
        assert g["slo_lat_burn_rate_ratio"] == pytest.approx(1.6)
        assert g["slo_lat_ok"] == 0.0

    def test_evaluate_peek_only_no_family_creation(self):
        m = obs.MetricsRegistry(namespace="p1t")
        s = obs_slo.parse_slos("lat=p99(never_fired_ms)<50")
        v = s.evaluate(m, publish=False)
        assert v["lat"]["ok"] is True and v["lat"]["observed"] is None
        assert m.empty()  # evaluating must not create empty families

    def test_healthz_verdicts(self):
        m = obs.process_registry()
        h = m.histogram("e2e_ms")
        for _ in range(5):
            h.observe(10.0)
        with core_flags.flags_guard(obs_slos="lat=p99(e2e_ms)<50"):
            srv = obs.TelemetryServer(port=0).start()
            try:
                hz = json.loads(urllib.request.urlopen(
                    srv.url + "/healthz", timeout=10).read())
            finally:
                srv.stop()
        assert hz["slo_ok"] is True
        assert hz["slo"]["lat"]["ok"] is True


class TestFlightRecorder:
    def test_disarmed_is_none(self):
        assert obs_flight.recorder() is None

    def test_ring_keeps_last_n_and_dump_atomic(self, tmp_path):
        with core_flags.flags_guard(obs_flight_steps=5,
                                    obs_flight_dir=str(tmp_path)):
            r = obs_flight.recorder()
            assert r is not None
            for i in range(12):
                r.note_step(step=i)
            path = r.dump(reason="unit")
        recs = obs_flight.read_bundle(path)
        hdr = recs[0]
        assert hdr["kind"] == "flight_header" and hdr["reason"] == "unit"
        steps = [x["step"] for x in recs if x.get("kind") == "step"]
        assert steps == [7, 8, 9, 10, 11]
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]

    def test_event_and_span_taps(self, tmp_path):
        from paddle1_tpu.obs import events as obs_events
        with core_flags.flags_guard(obs_flight_steps=4,
                                    obs_flight_dir=str(tmp_path)):
            r = obs_flight.recorder()
            # no events file, no trace dir — the ring still sees both
            obs_events.emit("worker_restart", rank=3)
            with obs_trace.span("train/step", cat="Engine"):
                pass
            text = r.dump_text()
        assert '"worker_restart"' in text
        assert '"train/step"' in text

    def test_debug_flight_route(self, tmp_path):
        srv = obs.TelemetryServer(port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(srv.url + "/debug/flight",
                                       timeout=10)
            with core_flags.flags_guard(obs_flight_steps=4,
                                        obs_flight_dir=str(tmp_path)):
                obs_flight.recorder().note_step(step=1)
                body = urllib.request.urlopen(
                    srv.url + "/debug/flight", timeout=10).read()
                assert b"flight_header" in body
                # the route also wrote the on-demand disk dump
                assert [f for f in os.listdir(tmp_path)
                        if f.startswith("flight-")]
        finally:
            srv.stop()

    def test_export_chrome_trace_merges_flight(self, tmp_path):
        d = str(tmp_path / "tr")
        with core_flags.flags_guard(obs_trace_dir=d,
                                    obs_flight_steps=4,
                                    obs_flight_dir=d):
            with obs_trace.span("train/step", cat="Engine"):
                pass
            r = obs_flight.recorder()
            r.note_step(step=7)
            r.dump(reason="unit")
        stats = obs_trace.export_chrome_trace(
            d, str(tmp_path / "chrome.json"))
        assert "flight/step" in stats["names"]
        assert "flight/dump" in stats["names"]
        # the span flushed to the live sink is not duplicated by its
        # shadow copy in the flight bundle
        ev = json.load(open(tmp_path / "chrome.json"))["traceEvents"]
        assert len([e for e in ev if e["name"] == "train/step"]) == 1

    def test_crash_dump_via_excepthook_subprocess(self, tmp_path):
        code = (
            "import sys\n"
            "sys.path.insert(0, %r)\n"
            "from paddle1_tpu.core import flags as core_flags\n"
            "from paddle1_tpu.obs import flight\n"
            "core_flags.set_flags({'obs_flight_steps': 3,\n"
            "                      'obs_flight_dir': %r})\n"
            "r = flight.recorder()\n"
            "for i in range(9):\n"
            "    r.note_step(step=i)\n"
            "raise RuntimeError('injected')\n"
        ) % (_ROOT, str(tmp_path))
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, timeout=120)
        assert r.returncode != 0
        bundles = [f for f in os.listdir(tmp_path)
                   if f.startswith("flight-")]
        assert bundles, r.stderr.decode()[-2000:]
        recs = obs_flight.read_bundle(str(tmp_path / bundles[0]))
        assert recs[0]["reason"] == "crash"
        assert "injected" in recs[0]["error"]
        assert [x["step"] for x in recs
                if x.get("kind") == "step"] == [6, 7, 8]


class TestTelemetryStaleProviders:
    def _get(self, url):
        return urllib.request.urlopen(url, timeout=10).read().decode()

    def test_stale_page_served_after_provider_breaks(self):
        state = {"broken": False}

        def provider():
            if state["broken"]:
                raise RuntimeError("drained")
            return "good_page 1\n"

        srv = obs.TelemetryServer(port=0, registry=False,
                                  providers=[provider])
        srv.start()
        try:
            page = self._get(srv.url + "/metrics")
            assert "good_page 1" in page
            state["broken"] = True
            page = self._get(srv.url + "/metrics")
            assert "good_page 1" in page
            assert "# provider stale" in page
            assert "# provider error" not in page
        finally:
            srv.stop()

    def test_never_succeeded_provider_keeps_error_comment(self):
        def boom():
            raise RuntimeError("never worked")
        srv = obs.TelemetryServer(port=0, registry=False,
                                  providers=[boom])
        srv.start()
        try:
            assert "# provider error" in self._get(srv.url + "/metrics")
        finally:
            srv.stop()

    def test_scrape_vs_drain_hammer(self):
        """Concurrent scrapes racing a provider being torn down and
        revived: every response must carry the data page (fresh or
        stale), never the provider-error hole."""
        state = {"broken": False}

        def provider():
            if state["broken"]:
                raise RuntimeError("torn down")
            return "hammer_page 1\n"

        srv = obs.TelemetryServer(port=0, registry=False,
                                  providers=[provider])
        srv.start()
        pages, errors = [], []
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    pages.append(self._get(srv.url + "/metrics"))
                except Exception as e:  # noqa: broad-except — any
                    # scrape failure fails the hammer below
                    errors.append(repr(e))

        def toggler():
            while not stop.is_set():
                state["broken"] = not state["broken"]
                time.sleep(0.002)

        try:
            self._get(srv.url + "/metrics")  # seed the good page
            threads = [threading.Thread(target=scraper)
                       for _ in range(6)]
            threads.append(threading.Thread(target=toggler))
            for t in threads:
                t.start()
            time.sleep(1.0)
            stop.set()
            for t in threads:
                t.join(timeout=10)
        finally:
            srv.stop()
        assert not errors
        assert len(pages) > 20
        assert all("hammer_page 1" in p for p in pages)
        assert not any("# provider error" in p for p in pages)
        assert any("# provider stale" in p for p in pages)


class TestBenchHistory:
    def _tool(self):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        try:
            import bench_history
        finally:
            sys.path.pop(0)
        return bench_history

    def _rec(self, metric, value, unit="req/s", vs=1.0):
        return {"metric": metric, "value": value, "unit": unit,
                "vs_baseline": vs, "detail": {}}

    def test_regression_ratchet(self):
        bh = self._tool()
        prior = [self._rec("qps", v) for v in (90, 100, 95)]
        assert bh.check_regressions(prior, [self._rec("qps", 91)]) == []
        probs = bh.check_regressions(prior, [self._rec("qps", 80)])
        assert probs and "down more than" in probs[0]

    def test_first_run_seeds_the_bar(self):
        bh = self._tool()
        assert bh.check_regressions([], [self._rec("new", 1.0)]) == []

    def test_lower_is_better_with_absolute_floor(self):
        bh = self._tool()
        prior = [self._rec("obs_overhead_frac", 0.005,
                           unit="fraction")]
        # 2x relative but noise-level absolute: not a regression
        assert bh.check_regressions(
            prior, [self._rec("obs_overhead_frac", 0.01,
                              unit="fraction")]) == []
        probs = bh.check_regressions(
            prior, [self._rec("obs_overhead_frac", 0.04,
                              unit="fraction")])
        assert probs and "up more than" in probs[0]

    def test_vs_baseline_contract_break(self):
        bh = self._tool()
        prior = [self._rec("soak", 10.0, unit="steps/s", vs=1.0)]
        probs = bh.check_regressions(
            prior, [self._rec("soak", 10.0, unit="steps/s", vs=0.0)])
        assert probs and "contract broke" in probs[0]

    def test_append_roundtrip_and_window(self, tmp_path):
        bh = self._tool()
        path = str(tmp_path / "hist.jsonl")
        for v in (100, 101, 102, 103, 104, 105, 40):
            bh.append_records(path, [self._rec("qps", v)])
        hist = bh.read_history(path)
        assert len(hist) == 7
        # the window is the LAST 5 priors: an ancient best outside it
        # does not gate
        prior, fresh = hist[:-1], [hist[-1]]
        probs = bh.check_regressions(prior, fresh)
        assert probs  # 40 vs best-of-last-5 (105)


class TestExpositionConformanceCostFamilies:
    def test_cost_hbm_slo_gauge_families_conform(self):
        from tests.test_obs import parse_exposition
        m = obs.MetricsRegistry(namespace="p1t")
        m.gauge("train_mfu").set(0.41)
        m.gauge("train_hbm_bw_util").set(0.6)
        m.gauge("train_step_flops").set(1e12)
        m.gauge("train_step_bytes").set(2e9)
        m.gauge("hbm_params_bytes").set(4.4e8)
        m.gauge("hbm_census_coverage_ratio").set(0.98)
        m.gauge("slo_lat_burn_rate_ratio").set(0.5)
        m.gauge("slo_lat_ok").set(1.0)
        m.histogram("train_readback_seconds").observe(0.01)
        types, samples = parse_exposition(m.render_text())
        for fam in ("p1t_train_mfu", "p1t_train_hbm_bw_util",
                    "p1t_hbm_params_bytes",
                    "p1t_hbm_census_coverage_ratio",
                    "p1t_slo_lat_burn_rate_ratio"):
            assert types[fam] == "gauge"
        assert types["p1t_train_readback_seconds"] == "summary"
