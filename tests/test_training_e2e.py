"""End-to-end training tests: capability config 1 (MNIST-shaped LeNet,
eager, single chip) with DataLoader, optimizer, checkpoint — the "one model
milestone" of SURVEY §7 stage 3."""

import os
import tempfile

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu import nn
from paddle1_tpu.io import DataLoader, Dataset


class SyntheticMNIST(Dataset):
    """Deterministic separable 28x28 problem (stands in for MNIST; the image
    has no network egress)."""

    def __init__(self, n=256, num_classes=10, seed=0):
        rng = np.random.RandomState(seed)
        self.labels = rng.randint(0, num_classes, n).astype(np.int64)
        base = rng.randn(num_classes, 1, 28, 28).astype(np.float32)
        self.images = (base[self.labels] +
                       0.3 * rng.randn(n, 1, 28, 28).astype(np.float32))

    def __getitem__(self, i):
        return self.images[i], self.labels[i]

    def __len__(self):
        return len(self.labels)


@pytest.mark.slow  # ~28s convergence soak (CI heavy step); the fit/
# engine mechanics stay covered in-tier by test_hapi_model_fit and the
# parallel-engine suites
def test_lenet_learns():
    paddle.seed(0)
    net = paddle.vision.models.LeNet()
    opt = paddle.optimizer.Adam(parameters=net.parameters(),
                                learning_rate=1e-3)
    loader = DataLoader(SyntheticMNIST(128), batch_size=32, shuffle=True)
    loss_fn = nn.CrossEntropyLoss()
    first = last = None
    for epoch in range(3):
        for img, label in loader:
            logits = net(img)
            loss = loss_fn(logits, label)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = loss.item()
            last = loss.item()
    assert last < first * 0.7, (first, last)


def test_sgd_momentum_converges_quadratic():
    paddle.seed(0)
    w = paddle.Parameter(np.array([5.0, -3.0], np.float32))
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=[w])
    for _ in range(100):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float((w * w).sum().item()) < 1e-3


def test_checkpoint_roundtrip():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    net(x).sum().backward()
    opt.step()
    with tempfile.TemporaryDirectory() as d:
        paddle.save(net.state_dict(), os.path.join(d, "model.pdparams"))
        paddle.save(opt.state_dict(), os.path.join(d, "opt.pdopt"))
        net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        opt2 = paddle.optimizer.Adam(parameters=net2.parameters())
        net2.set_state_dict(paddle.load(os.path.join(d, "model.pdparams")))
        opt2.set_state_dict(paddle.load(os.path.join(d, "opt.pdopt")))
        y1 = net(x).numpy()
        y2 = net2(x).numpy()
    np.testing.assert_allclose(y1, y2, rtol=1e-6)
    assert opt2._step_count == opt._step_count


def test_lr_scheduler_with_optimizer():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2,
                                          gamma=0.5)
    w = paddle.Parameter(np.ones(1, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w])
    lrs = []
    for i in range(6):
        (w.sum()).backward()
        opt.step()
        opt.clear_grad()
        lrs.append(opt.get_lr())
        sched.step()
    assert lrs[0] == 0.1 and abs(lrs[2] - 0.05) < 1e-9, lrs


def test_grad_clip_global_norm():
    w = paddle.Parameter(np.array([10.0, 0.0], np.float32))
    clip = nn.ClipGradByGlobalNorm(1.0)
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w],
                               grad_clip=clip)
    (w * w).sum().backward()   # grad = [20, 0], norm 20
    opt.step()
    # update should be clipped to norm 1 → w ≈ [10-1, 0]
    np.testing.assert_allclose(w.numpy(), [9.0, 0.0], atol=1e-4)


def test_amp_autocast_and_scaler():
    paddle.seed(0)
    net = nn.Linear(8, 8)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    with paddle.amp.auto_cast(dtype="bfloat16"):
        out = net(x)
        assert str(out.dtype) == "bfloat16"
        loss = out.astype("float32").mean()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    assert net.weight.grad is not None


def test_hapi_model_fit():
    paddle.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(784, 10))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())
    ds = SyntheticMNIST(64)
    model.fit(ds, epochs=1, batch_size=16, verbose=0)
    res = model.evaluate(ds, batch_size=16, verbose=0)
    assert "acc" in res
