"""ISSUE 10: unified metrics registry, cross-process tracing, live
telemetry endpoint, lifecycle journal — and the exposition-format
conformance lock (one # TYPE per family, _total counters, raw
_sum/_count) plus the profiler span-stack-leak regression."""

import json
import os
import re
import socket
import tempfile
import threading
import urllib.request

import numpy as np
import pytest

from paddle1_tpu import obs, profiler
from paddle1_tpu.core import flags as core_flags
from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.obs import events as obs_events
from paddle1_tpu.obs import trace as obs_trace


@pytest.fixture(autouse=True)
def _fresh_registry():
    obs.reset_process_registry()
    yield
    obs.reset_process_registry()


class TestUnifiedRegistry:
    def test_namespace_rendering(self):
        m = obs.MetricsRegistry(namespace="p1t")
        m.counter("train_steps_total").inc(3)
        text = m.render_text()
        assert "# TYPE p1t_train_steps_total counter" in text.splitlines()
        assert "p1t_train_steps_total 3" in text.splitlines()

    def test_serving_shim_unchanged(self):
        # zero API break: serving imports resolve to the same objects,
        # default namespace still p1t_serving
        from paddle1_tpu.serving.metrics import (MetricsRegistry,
                                                 ServingMetrics)
        assert ServingMetrics is MetricsRegistry
        m = ServingMetrics()
        m.counter("requests_total").inc()
        assert "p1t_serving_requests_total 1" in m.render_text()

    def test_kind_conflict_guard(self):
        m = obs.MetricsRegistry()
        m.counter("x_total")
        with pytest.raises(InvalidArgumentError):
            m.gauge("x_total")
        with pytest.raises(InvalidArgumentError):
            m.histogram("x_total")

    def test_process_registry_singleton_and_reset(self):
        a = obs.process_registry()
        assert obs.process_registry() is a
        a.counter("x_total").inc()
        b = obs.reset_process_registry()
        assert obs.process_registry() is b
        assert b.empty()

    def test_step_registry_flag_gate(self):
        assert obs.step_registry() is None
        with core_flags.flags_guard(obs_metrics=True):
            assert obs.step_registry() is obs.process_registry()

    def test_snapshot_file_roundtrip(self, tmp_path):
        m = obs.process_registry()
        m.counter("x_total").inc(7)
        path = str(tmp_path / "snap.json")
        from paddle1_tpu.obs.registry import write_snapshot_file
        write_snapshot_file(path)
        snap = json.load(open(path))
        assert snap["counters"]["x_total"] == 7


# -- exposition conformance (ISSUE 10 satellite) ---------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'            # family/sample name
    r'(\{[a-zA-Z0-9_]+="[^"]*"'               # optional label set
    r'(,[a-zA-Z0-9_]+="[^"]*")*\})?'
    r' (-?[0-9.e+-]+|NaN)$')                  # value
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge|summary|histogram|untyped)$")


def parse_exposition(text):
    """Minimal Prometheus text-format parser: returns (types, samples)
    and asserts structural validity — every line is a TYPE header, a
    comment, or a well-formed sample; one TYPE per family per page."""
    types = {}
    samples = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            m = _TYPE_RE.match(line)
            assert m, f"malformed TYPE line: {line!r}"
            fam = m.group(1)
            assert fam not in types, f"duplicate # TYPE for {fam}"
            types[fam] = m.group(2)
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        samples.append((m.group(1), line))
    # conformance rules the PR 7/8 fixes locked in:
    for fam, kind in types.items():
        if kind == "counter":
            assert fam.endswith("_total"), \
                f"counter family {fam} must end _total"
        if kind == "summary":
            names = {n for n, _ in samples}
            assert f"{fam}_sum" in names and f"{fam}_count" in names, \
                f"summary {fam} missing raw _sum/_count"
    return types, samples


class TestExpositionConformance:
    def _populated(self, m):
        m.counter("requests_total").inc(7)
        m.gauge("slot_occupancy").set(0.75)
        h = m.histogram("e2e_ms")
        for _ in range(3):
            h.observe(0.1)
        return m

    def test_serving_page(self):
        m = self._populated(obs.MetricsRegistry())
        types, samples = parse_exposition(m.render_text())
        assert types["p1t_serving_requests_total"] == "counter"
        assert types["p1t_serving_slot_occupancy"] == "gauge"
        assert types["p1t_serving_e2e_ms"] == "summary"
        # RAW unrounded _sum (repr of the float accumulation, not the
        # 4-digit-rounded summary value)
        line = next(l for n, l in samples
                    if n == "p1t_serving_e2e_ms_sum")
        assert line.split()[-1] == repr(0.1 + 0.1 + 0.1)

    def test_process_page(self):
        m = self._populated(obs.process_registry())
        m.histogram("train_dispatch_seconds").observe(0.001)
        types, _ = parse_exposition(m.render_text())
        assert types["p1t_train_dispatch_seconds"] == "summary"

    def test_autoscale_families_conform(self):
        """ISSUE 18: the control loop's families — the queue-EWMA
        gauge the fleet sweep publishes plus every autoscale_* name —
        render as conformant exposition with the right kinds."""
        m = obs.MetricsRegistry()
        m.gauge("serve_queue_depth_ewma").set(3.2)
        m.gauge("serve_replicas_live").set(3)
        m.counter("autoscale_decisions_total").inc(4)
        m.counter("autoscale_scale_out_total").inc()
        m.counter("autoscale_refusals_total").inc()
        m.gauge("autoscale_queue_ratio").set(0.4)
        m.gauge("autoscale_burn_max_ratio").set(0.8)
        m.gauge("autoscale_target_replicas").set(3)
        m.histogram("autoscale_decision_seconds").observe(0.0004)
        types, samples = parse_exposition(m.render_text())
        assert types["p1t_serving_serve_queue_depth_ewma"] == "gauge"
        assert types["p1t_serving_serve_replicas_live"] == "gauge"
        assert types["p1t_serving_autoscale_decisions_total"] \
            == "counter"
        assert types["p1t_serving_autoscale_queue_ratio"] == "gauge"
        assert types["p1t_serving_autoscale_target_replicas"] \
            == "gauge"
        assert types["p1t_serving_autoscale_decision_seconds"] \
            == "summary"
        names = {n for n, _ in samples}
        assert "p1t_serving_autoscale_decision_seconds_sum" in names

    def test_embedding_families_conform(self):
        """ISSUE 19: the sharded-embedding tier families — per-tier row
        gauges, admission/eviction counters, and the delta-loop names —
        render as conformant exposition with the right kinds."""
        m = obs.MetricsRegistry()
        m.gauge("embed_hbm_rows").set(4096)
        m.gauge("embed_hbm_budget_rows").set(4096)
        m.gauge("embed_hbm_bytes").set(4096 * 64)
        m.gauge("embed_host_rows").set(150_000)
        m.counter("embed_admit_total").inc(7)
        m.counter("embed_demote_total").inc(3)
        m.counter("embed_ttl_evict_total").inc()
        m.counter("embed_hit_total").inc(90)
        m.counter("embed_miss_total").inc(10)
        m.counter("embed_delta_applied_total").inc(2)
        m.counter("embed_delta_rows_total").inc(128)
        m.counter("embed_delta_errors_total").inc()
        m.gauge("embed_delta_version").set(2)
        types, samples = parse_exposition(m.render_text())
        assert types["p1t_serving_embed_hbm_rows"] == "gauge"
        assert types["p1t_serving_embed_hbm_budget_rows"] == "gauge"
        assert types["p1t_serving_embed_hbm_bytes"] == "gauge"
        assert types["p1t_serving_embed_host_rows"] == "gauge"
        assert types["p1t_serving_embed_admit_total"] == "counter"
        assert types["p1t_serving_embed_demote_total"] == "counter"
        assert types["p1t_serving_embed_delta_rows_total"] == "counter"
        assert types["p1t_serving_embed_delta_version"] == "gauge"
        names = {n for n, _ in samples}
        assert "p1t_serving_embed_miss_total" in names

    def test_recommender_reliability_families_conform(self):
        """ISSUE 20: the durable-recommender families — PS
        retry/reconnect counters, delta durability counters, and the
        staleness gauge a ``stale(...)`` SLO clause watches — render as
        conformant exposition with the right kinds."""
        m = obs.MetricsRegistry()
        m.counter("ft_ps_retries_total").inc(5)
        m.counter("ft_ps_reconnects_total").inc(2)
        m.counter("ft_ps_unavailable_total").inc()
        m.counter("delta_skipped_files_total").inc(3)
        m.counter("delta_corrupt_total").inc()
        m.counter("delta_gaps_total").inc()
        m.counter("delta_resyncs_total").inc()
        m.gauge("embed_delta_staleness_seconds").set(0.25)
        types, samples = parse_exposition(m.render_text())
        assert types["p1t_serving_ft_ps_retries_total"] == "counter"
        assert types["p1t_serving_ft_ps_reconnects_total"] == "counter"
        assert types["p1t_serving_ft_ps_unavailable_total"] == "counter"
        assert types["p1t_serving_delta_skipped_files_total"] \
            == "counter"
        assert types["p1t_serving_delta_corrupt_total"] == "counter"
        assert types["p1t_serving_delta_gaps_total"] == "counter"
        assert types["p1t_serving_delta_resyncs_total"] == "counter"
        assert types["p1t_serving_embed_delta_staleness_seconds"] \
            == "gauge"
        names = {n for n, _ in samples}
        assert "p1t_serving_delta_resyncs_total" in names

    def test_staleness_slo_clause_watches_the_gauge(self):
        """FLAGS_obs_slos='fresh=stale(embed_delta_staleness_seconds)<N'
        goes red exactly when the subscriber has been behind the log
        head for more than N seconds."""
        from paddle1_tpu.obs.slo import parse_slos
        m = obs.MetricsRegistry()
        slos = parse_slos("fresh=stale(embed_delta_staleness_seconds)<2")
        assert slos.evaluate(m)["fresh"]["ok"]   # no data: vacuously ok
        m.gauge("embed_delta_staleness_seconds").set(0.5)
        assert slos.evaluate(m)["fresh"]["ok"]
        m.gauge("embed_delta_staleness_seconds").set(10.0)
        assert not slos.evaluate(m)["fresh"]["ok"]

    def test_group_page_untyped_labeled(self):
        g = obs.MetricsGroup("version")
        self._populated(g.child("v1"))
        self._populated(g.child("v2"))
        text = g.render_text()
        types, samples = parse_exposition(text)
        assert not types  # labeled multi-child pages drop TYPE headers
        assert any('version="v2"' in l for _, l in samples)

    def test_merged_snapshot_page(self):
        a = self._populated(obs.MetricsRegistry())
        b = self._populated(obs.MetricsRegistry())
        merged = obs.merge_snapshots([a.snapshot(), b.snapshot()])
        assert merged["counters"]["requests_total"] == 14
        text = obs.render_snapshot_text(merged, namespace="p1t_serving",
                                        label=("scope", "agg"))
        types, samples = parse_exposition(text)
        assert not types
        line = next(l for n, l in samples
                    if n == "p1t_serving_requests_total")
        assert 'scope="agg"' in line and line.endswith(" 14")

    def test_decode_economics_families(self):
        # ISSUE 16: the paged-KV / speculation metric families must
        # render as conformant exposition (gauges unit-suffixed per the
        # lint table, counters _total) exactly as the engine emits them
        m = obs.MetricsRegistry()
        m.gauge("gen_kv_pages_in_use").set(5)
        m.gauge("gen_kv_pages_free").set(3)
        m.gauge("gen_kv_pages_cached").set(2)
        m.gauge("gen_kv_page_bytes").set(4096)
        m.gauge("gen_spec_accept_ratio").set(0.75)
        m.counter("gen_kv_page_faults_total").inc(4)
        m.counter("gen_kv_page_evictions_total").inc()
        m.counter("gen_kv_prefix_hits_total").inc(2)
        m.counter("gen_spec_proposed_total").inc(8)
        m.counter("gen_spec_accepted_total").inc(6)
        types, samples = parse_exposition(m.render_text())
        for fam, kind in {
                "gen_kv_pages_in_use": "gauge",
                "gen_kv_pages_free": "gauge",
                "gen_kv_pages_cached": "gauge",
                "gen_kv_page_bytes": "gauge",
                "gen_spec_accept_ratio": "gauge",
                "gen_kv_page_faults_total": "counter",
                "gen_kv_page_evictions_total": "counter",
                "gen_kv_prefix_hits_total": "counter",
                "gen_spec_proposed_total": "counter",
                "gen_spec_accepted_total": "counter"}.items():
            assert types[f"p1t_serving_{fam}"] == kind, fam
        line = next(l for n, l in samples
                    if n == "p1t_serving_gen_spec_accept_ratio")
        assert line.endswith(" 0.75")

    def test_generation_fleet_families(self):
        # ISSUE 17: the GenerationFleet's reliability metric families
        # (failover / preemption / deploy plane) must render as
        # conformant exposition exactly as the fleet emits them —
        # counters _total, gauges bare, the stream-latency histogram
        # unit-suffixed
        m = obs.MetricsRegistry()
        for c in ("gen_fleet_streams_total",
                  "gen_fleet_streams_completed_total",
                  "gen_fleet_tokens_total",
                  "gen_fleet_dup_tokens_total",
                  "gen_fleet_failovers_total",
                  "gen_fleet_retries_total",
                  "gen_fleet_migrations_total",
                  "gen_fleet_shed_total",
                  "gen_fleet_cancelled_total",
                  "gen_fleet_deadline_expired_total",
                  "gen_fleet_errors_total",
                  "gen_fleet_stream_failed_total",
                  "gen_fleet_pressure_deferrals_total",
                  "gen_fleet_replica_restarts_total",
                  "gen_fleet_replica_wedged_total",
                  "gen_fleet_replica_exhausted_total",
                  "gen_fleet_deploys_total",
                  "gen_fleet_rollbacks_total"):
            m.counter(c).inc()
        m.gauge("gen_fleet_streams_active").set(3)
        m.gauge("gen_fleet_replicas_ready").set(2)
        m.gauge("gen_fleet_kv_pages_free").set(9)
        m.histogram("gen_fleet_stream_ms").observe(120.0)
        types, _ = parse_exposition(m.render_text())
        for fam, kind in {
                "gen_fleet_streams_active": "gauge",
                "gen_fleet_replicas_ready": "gauge",
                "gen_fleet_kv_pages_free": "gauge",
                "gen_fleet_failovers_total": "counter",
                "gen_fleet_dup_tokens_total": "counter",
                # histograms render as quantile summaries (the
                # registry's exposition choice, see render_text)
                "gen_fleet_stream_ms": "summary"}.items():
            assert types[f"p1t_serving_{fam}"] == kind, fam
        # the dedup plane's counters must be distinct families: a
        # failover that re-sends tokens increments dup_tokens, never
        # tokens — dashboards difference them for exactly-once audit
        assert "p1t_serving_gen_fleet_tokens_total" in types
        assert "p1t_serving_gen_fleet_dup_tokens_total" in types

    def test_composite_fleet_style_page(self):
        # a typed page followed by labeled group pages — the fleet's
        # /metrics composition — must still parse with unique TYPEs
        m = self._populated(obs.MetricsRegistry())
        g = obs.MetricsGroup("replica")
        self._populated(g.child(0))
        parse_exposition(m.render_text() + g.render_text())


class TestTrace:
    def test_span_nesting_and_export(self, tmp_path):
        d = str(tmp_path / "tr")
        with core_flags.flags_guard(obs_trace_dir=d):
            with obs_trace.span("outer", args={"k": 1}):
                with obs_trace.span("inner"):
                    pass
                ctx = obs_trace.current()
                # a cross-thread child (the replica resolver pattern):
                # this is the hop that earns a flow arrow
                t = threading.Thread(
                    target=lambda: obs_trace.record_span(
                        "other_thread", 0.001, ctx=ctx))
                t.start()
                t.join()
            obs_trace.instant("mark")
        recs = obs_trace.read_spans(d)
        by = {r["name"]: r for r in recs}
        assert by["inner"]["parent"] == by["outer"]["span"]
        assert by["outer"]["args"] == {"k": 1}
        assert by["inner"]["trace"] == by["outer"]["trace"]
        out = str(tmp_path / "chrome.json")
        stats = obs_trace.export_chrome_trace(d, out)
        # same-thread nesting renders as stacked slices (no arrow);
        # the cross-thread hop is exactly one flow
        assert stats["flows"] == 1
        trace = json.load(open(out))
        phases = {e["ph"] for e in trace["traceEvents"]}
        assert {"X", "s", "f"} <= phases

    def test_instant_flushed_immediately(self, tmp_path):
        # instants survive a SIGKILL a microsecond later: the record
        # must be on disk BEFORE any explicit flush
        d = str(tmp_path / "tr")
        with core_flags.flags_guard(obs_trace_dir=d):
            obs_trace.instant("recv", ctx=("t" * 16, "s" * 16))
            fn = os.path.join(d, f"spans-{os.getpid()}.jsonl")
            raw = open(fn).read()
        assert '"recv"' in raw

    def test_wire_header_roundtrip(self):
        ctx = (obs_trace.new_trace_id(), obs_trace.new_span_id())
        h = obs_trace.wire_header(ctx)
        assert obs_trace.adopt_header(h) == ctx
        assert obs_trace.adopt_header({"t": 'bad"id', "s": "x"}) is None
        assert obs_trace.adopt_header("nope") is None
        assert obs_trace.adopt_header({}) is None

    def test_env_ctx_parsing(self, monkeypatch):
        monkeypatch.setenv(obs_trace.TRACE_CTX_ENV, "abc123:def456")
        assert obs_trace._env_ctx() == ("abc123", "def456")
        monkeypatch.setenv(obs_trace.TRACE_CTX_ENV, "garbage")
        assert obs_trace._env_ctx() is None

    def test_context_manager_sets_current(self):
        with obs_trace.context("t1", "s1"):
            assert obs_trace.current() == ("t1", "s1")

    def test_disabled_is_noop(self, tmp_path):
        assert not obs_trace.sink_active()
        with obs_trace.span("x"):
            pass
        obs_trace.instant("y")
        # nothing written anywhere, and span() returned the shared
        # null object (the hot-path zero-cost contract)
        assert obs_trace.span("z") is obs_trace.span("w")


class TestExportEdgeCases:
    """export_chrome_trace must survive every shape a crashed or idle
    fleet leaves behind (ISSUE 12 satellite): an empty/missing trace
    dir, a pid that opened its sink but completed zero spans, and a
    torn final JSONL line from a SIGKILLed process."""

    def test_empty_trace_dir(self, tmp_path):
        d = tmp_path / "tr"
        d.mkdir()
        out = str(tmp_path / "chrome.json")
        stats = obs_trace.export_chrome_trace(str(d), out)
        assert stats["events"] == 0 and stats["flows"] == 0
        assert json.load(open(out)) == {"traceEvents": []}

    def test_missing_trace_dir(self, tmp_path):
        # never created (tracing was configured but nothing recorded)
        out = str(tmp_path / "chrome.json")
        stats = obs_trace.export_chrome_trace(
            str(tmp_path / "never_made"), out)
        assert stats["events"] == 0
        assert json.load(open(out)) == {"traceEvents": []}

    def test_zero_span_pid_file(self, tmp_path):
        # a process that armed its sink and died before any span
        # completed leaves an empty spans-<pid>.jsonl
        d = tmp_path / "tr"
        d.mkdir()
        (d / "spans-12345.jsonl").write_text("")
        (d / "spans-12346.jsonl").write_text("\n\n")  # blank lines only
        out = str(tmp_path / "chrome.json")
        stats = obs_trace.export_chrome_trace(str(d), out)
        assert stats["events"] == 0 and stats["pids"] == []

    def test_truncated_last_line_is_skipped(self, tmp_path):
        # a SIGKILL mid-write tears the final line; every complete
        # record before it must still export
        d = tmp_path / "tr"
        d.mkdir()
        good = json.dumps({"ph": "X", "name": "step", "cat": "Engine",
                           "ts": 1.0, "dur": 2.0, "pid": 7, "tid": 1,
                           "trace": "t1", "span": "s1",
                           "parent": None})
        (d / "spans-7.jsonl").write_text(
            good + "\n" + '{"ph": "X", "name": "torn", "ts": 3')
        out = str(tmp_path / "chrome.json")
        stats = obs_trace.export_chrome_trace(str(d), out)
        assert stats["events"] == 1
        assert stats["names"] == ["step"]
        assert stats["pids"] == [7]
        ev = json.load(open(out))["traceEvents"]
        assert [e["name"] for e in ev] == ["step"]

    def test_mixed_torn_and_foreign_files(self, tmp_path):
        # non-span files in the dir are ignored; torn lines in one pid
        # file don't poison another pid's records
        d = tmp_path / "tr"
        d.mkdir()
        (d / "notes.txt").write_text("not a span file")
        (d / "spans-1.jsonl").write_text('{"broken...')
        rec = json.dumps({"ph": "i", "name": "mark", "ts": 5.0,
                          "pid": 2, "tid": 9, "s": "p",
                          "trace": "t2", "span": "s2"})
        (d / "spans-2.jsonl").write_text(rec + "\n")
        stats = obs_trace.export_chrome_trace(
            str(d), str(tmp_path / "chrome.json"))
        assert stats["events"] == 1 and stats["pids"] == [2]


class TestProfilerSpanLeak:
    def test_stop_mid_span_does_not_leak_stack(self):
        # the satellite regression: stop_profiler flipping _enabled
        # mid-span used to make end() early-return with the span still
        # on _tls.stack, mis-nesting every later span on the thread
        profiler.start_profiler()
        ev = profiler.RecordEvent("outer").begin()
        profiler.stop_profiler()
        ev.end()
        assert not getattr(profiler._tls, "stack", [])
        # and a following profiled span records at depth 0
        profiler.start_profiler()
        with profiler.RecordEvent("next"):
            pass
        profiler._enabled = False
        with profiler._lock:
            evs = [e for e in profiler._events if e["name"] == "next"]
        profiler.stop_profiler()
        assert evs and evs[0]["depth"] == 0

    def test_record_event_writes_trace_sink_without_profiler(
            self, tmp_path):
        profiler.reset_profiler()  # drop the previous test's events
        d = str(tmp_path / "tr")
        with core_flags.flags_guard(obs_trace_dir=d):
            with profiler.RecordEvent("serving_op", args={"rows": 4}):
                pass
        recs = obs_trace.read_spans(d)
        assert recs and recs[0]["name"] == "serving_op"
        assert recs[0]["args"] == {"rows": 4}
        # profiler tables stayed off: nothing aggregated
        assert profiler.stop_profiler() == []


class TestEvents:
    def test_emit_and_read(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with core_flags.flags_guard(obs_events_file=path):
            obs_events.emit("checkpoint_commit", step=7, seconds=0.5)
            obs_events.emit("worker_restart", rank=2)
        recs = obs_events.read_events(path)
        assert [r["event"] for r in recs] == ["checkpoint_commit",
                                             "worker_restart"]
        assert recs[0]["step"] == 7 and recs[0]["pid"] == os.getpid()

    def test_disabled_noop(self, tmp_path):
        assert core_flags.flag("obs_events_file") == ""
        obs_events.emit("x")  # must not raise, must not create files

    def test_unserializable_fields_degrade(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with core_flags.flags_guard(obs_events_file=path):
            obs_events.emit("weird", obj=object())
        recs = obs_events.read_events(path)
        assert recs and recs[0]["event"] == "weird"


class TestTelemetryEndpoint:
    def _get(self, url):
        return urllib.request.urlopen(url, timeout=10)

    def test_metrics_and_healthz(self):
        m = obs.process_registry()
        m.counter("train_steps_total").inc(5)
        srv = obs.TelemetryServer(port=0).start()
        try:
            page = self._get(srv.url + "/metrics").read().decode()
            types, _ = parse_exposition(page)
            assert types["p1t_train_steps_total"] == "counter"
            hz = json.loads(self._get(srv.url + "/healthz").read())
            assert hz["ok"] is True and hz["pid"] == os.getpid()
            with pytest.raises(urllib.error.HTTPError):
                self._get(srv.url + "/nope")
        finally:
            srv.stop()

    def test_provider_error_never_kills_page(self):
        def boom():
            raise RuntimeError("broken provider")
        srv = obs.TelemetryServer(port=0, registry=False,
                                  providers=[boom, lambda: "ok 1\n"])
        srv.start()
        try:
            page = self._get(srv.url + "/metrics").read().decode()
            assert "# provider error" in page and "ok 1" in page
        finally:
            srv.stop()

    def test_flag_disabled(self):
        assert obs.start_telemetry_from_flags() is None


class TestEngineInstrumentation:
    def _engine(self):
        import jax
        import paddle1_tpu as paddle
        from paddle1_tpu.core.tensor import Tensor
        from paddle1_tpu.distributed import ParallelEngine, build_mesh
        paddle.seed(0)
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 8))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        loss_fn = lambda m, b: \
            ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()
        mesh = build_mesh(dp=1, devices=jax.devices()[:1])
        return ParallelEngine(model, opt, loss_fn, mesh=mesh)

    def test_step_phases_and_gauges(self):
        eng = self._engine()
        rng = np.random.default_rng(0)
        b = {"x": rng.standard_normal((4, 8)).astype(np.float32),
             "y": rng.standard_normal((4, 8)).astype(np.float32)}
        float(eng.step(b))  # disabled: registry stays untouched
        assert obs.process_registry().empty()
        with core_flags.flags_guard(obs_metrics=True):
            for _ in range(3):
                float(eng.step(b))
            list(eng.step_stream([b] * 2))
            eng.drain()
        snap = obs.process_registry().snapshot()
        h = snap["histograms"]
        assert h["train_shard_seconds"]["count"] >= 5
        assert h["train_dispatch_seconds"]["count"] >= 5
        assert h["train_readback_seconds"]["count"] >= 3
        assert h["train_data_wait_seconds"]["count"] >= 1
        assert snap["counters"]["train_steps_total"] >= 5
        assert snap["gauges"]["train_samples_per_s"] > 0
        assert snap["gauges"]["train_steps_per_readback"] > 0

    def test_step_trace_spans(self, tmp_path):
        eng = self._engine()
        rng = np.random.default_rng(0)
        b = {"x": rng.standard_normal((4, 8)).astype(np.float32),
             "y": rng.standard_normal((4, 8)).astype(np.float32)}
        d = str(tmp_path / "tr")
        with core_flags.flags_guard(obs_trace_dir=d):
            float(eng.step(b))
        names = {r["name"] for r in obs_trace.read_spans(d)}
        assert {"train/step", "train/shard", "train/dispatch"} <= names


class TestMetricsCallback:
    def test_publishes_into_registry(self):
        from paddle1_tpu.hapi.callbacks import MetricsCallback
        cb = MetricsCallback(batch_size=32, log_freq=2)
        cb.on_epoch_begin(0)
        for step in range(4):
            cb.on_train_batch_end(step, {"loss": [0.5 - 0.1 * step]})
        cb.on_epoch_end(0)
        cb.on_eval_end({"loss": [0.25], "acc@Top-1": 0.9})
        m = obs.process_registry()
        snap = m.snapshot()
        assert snap["counters"]["hapi_steps_total"] == 4
        assert snap["counters"]["hapi_epochs_total"] == 1
        assert snap["histograms"]["hapi_step_seconds"]["count"] == 4
        # log_freq=2: steps 0 and 2 updated the loss gauge (readback
        # bounded); last write was step 2's 0.3
        assert abs(snap["gauges"]["hapi_loss"] - 0.3) < 1e-6
        assert snap["gauges"]["hapi_samples_per_s"] > 0
        assert abs(snap["gauges"]["hapi_eval_acc_top_1"] - 0.9) < 1e-9
        # the slugged eval gauge passes the lint's naming contract
        assert re.match(r"^[a-z][a-z0-9_]*$", "hapi_eval_acc_top_1")


class TestSupervisorObsPlumbing:
    def test_worker_env_stamping(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import Supervisor
        from paddle1_tpu.obs.registry import SNAPSHOT_ENV
        sup = Supervisor(policy="fail_fast",
                         heartbeat_dir=str(tmp_path / "hb"),
                         world_size=1)
        sup.add_worker(0, ["true"])
        w = sup._workers[0]
        d = str(tmp_path / "tr")
        ev_file = str(tmp_path / "events.jsonl")
        with core_flags.flags_guard(obs_trace_dir=d,
                                    obs_events_file=ev_file,
                                    obs_metrics=True):
            env = {}
            sup._obs_worker_env(w, env)
        assert env["FLAGS_obs_trace_dir"] == d
        assert env["FLAGS_obs_events_file"] == ev_file
        assert env["FLAGS_obs_metrics"] == "1"
        assert env[SNAPSHOT_ENV].endswith("metrics.0.json")
        tid, sid = env[obs_trace.TRACE_CTX_ENV].split(":")
        assert obs_trace._ID_RE.match(tid) and obs_trace._ID_RE.match(sid)
        # disabled: nothing stamped
        env = {}
        sup._obs_worker_env(w, env)
        assert not env

    def test_worker_snapshot_aggregation_page(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import Supervisor
        sup = Supervisor(policy="fail_fast",
                         heartbeat_dir=str(tmp_path / "hb"),
                         world_size=1)
        sup.add_worker(0, ["true"])
        sup.add_worker(1, ["true"])
        os.makedirs(sup._heartbeat_dir(), exist_ok=True)
        for rank in (0, 1):
            reg = obs.MetricsRegistry(namespace="p1t")
            reg.counter("train_steps_total").inc(10 + rank)
            from paddle1_tpu.obs.registry import write_snapshot_file
            write_snapshot_file(os.path.join(
                sup._heartbeat_dir(), f"metrics.{rank}.json"), reg)
        page = sup._worker_metrics_page()
        types, samples = parse_exposition(page)
        line = next(l for n, l in samples
                    if n == "p1t_train_steps_total")
        assert 'scope="workers"' in line and line.endswith(" 21")

    def test_supervisor_telemetry_endpoint(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import Supervisor
        sup = Supervisor(policy="fail_fast",
                         heartbeat_dir=str(tmp_path / "hb"),
                         world_size=1)
        sup.add_worker(0, ["true"])
        srv = sup.start_telemetry(port=0)
        try:
            hz = json.loads(urllib.request.urlopen(
                srv.url + "/healthz", timeout=10).read())
            assert hz["policy"] == "fail_fast"
            assert hz["workers"] == {0: "down"} or \
                hz["workers"] == {"0": "down"}
        finally:
            sup.stop_telemetry()


class TestWireTracePropagation:
    def test_trace_header_rides_frames(self):
        from paddle1_tpu.serving import wire
        a, b = socket.socketpair()
        try:
            ctx = (obs_trace.new_trace_id(), obs_trace.new_span_id())
            hdr = {"kind": "infer", "id": 7,
                   "trace": obs_trace.wire_header(ctx)}
            wire.send_msg(a, hdr, [np.ones((2, 3), np.float32)])
            got, arrays = wire.recv_msg(b)
            assert obs_trace.adopt_header(got["trace"]) == ctx
            assert arrays[0].shape == (2, 3)
        finally:
            a.close()
            b.close()

    def test_server_stamps_request_trace(self, tmp_path):
        # a replica submits under the wire context; the batcher request
        # must carry it so the dispatch span can flow-link back
        from paddle1_tpu.serving.batcher import _Request
        d = str(tmp_path / "tr")
        with core_flags.flags_guard(obs_trace_dir=d):
            with obs_trace.context("t" * 16, "s" * 16):
                # the Server.submit stamping path, isolated
                req = _Request([np.ones((1, 4), np.float32)],
                               ("sig",), None)
                req.trace = obs_trace.current()
        assert req.trace == ("t" * 16, "s" * 16)


class TestMetricNameLint:
    def test_repo_is_clean(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_metric_names",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "tools", "check_metric_names.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main() == 0

    def test_rules_catch_violations(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_metric_names",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "tools", "check_metric_names.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        bad = tmp_path / "bad.py"
        bad.write_text(
            "m.counter('requests')\n"          # counter without _total
            "m.histogram('latency')\n"         # histogram without unit
            "m.gauge('CamelCase')\n"           # not snake_case
            "m.gauge('dual_ms')\n"
            "m.histogram('dual_ms')\n"         # kind conflict
            "m.gauge('used_mb')\n"             # non-canonical: _bytes
            "m.gauge('wait_secs')\n"           # non-canonical: _seconds
            "m.counter('io_kb_total')\n"       # bad unit under _total
            "m.histogram('load_frac')\n"       # non-canonical: _ratio
            "m.gauge('gen_kv_used_pg')\n"      # non-canonical: _pages
            "m.counter('kv_fault_page_total')\n")  # singular _page
        problems = mod.check([str(bad)])
        text = "\n".join(problems)
        assert "'requests' must end in '_total'" in text
        assert "needs a unit suffix" in text
        assert "not snake_case" in text
        assert "multiple kinds" in text
        # ISSUE 13: the canonical-unit-spelling table
        assert "'used_mb' uses non-canonical unit suffix '_mb'" in text
        assert "spell it '_seconds'" in text
        assert "'io_kb_total' uses non-canonical unit suffix " \
               "'_kb'" in text
        assert "'load_frac' uses non-canonical unit suffix " \
               "'_frac'" in text
        # ISSUE 16: the KV paging unit family
        assert "'gen_kv_used_pg' uses non-canonical unit suffix " \
               "'_pg'" in text
        assert "'kv_fault_page_total' uses non-canonical unit suffix " \
               "'_page'" in text

    def test_canonical_suffixes_pass(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_metric_names",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "tools", "check_metric_names.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        good = tmp_path / "good.py"
        good.write_text(
            "m.gauge('hbm_params_bytes')\n"
            "m.gauge('hbm_census_coverage_ratio')\n"
            "m.gauge('slo_lat_burn_rate_ratio')\n"
            "m.histogram('ckpt_write_bytes')\n"
            "m.histogram('train_readback_seconds')\n"
            "m.gauge('gen_kv_pages_in_use')\n"
            "m.gauge('gen_kv_page_bytes')\n"
            "m.gauge('gen_spec_accept_ratio')\n"
            "m.counter('gen_kv_page_faults_total')\n"
            "m.counter('gen_spec_accepted_total')\n")
        assert mod.check([str(good)]) == []
