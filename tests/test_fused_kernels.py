"""Fused Pallas kernel tests (flash padding mask, fused LayerNorm, fused
Adam) — run in interpreter mode on the CPU sim, exercising the same kernel
code the TPU executes. Mirrors the reference's fused-op unit tests
(test_fused_* over operators/fused/)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle1_tpu.core.flags import flags_guard


class TestFlashPaddingMask:
    def _qkv(self, b=2, n=128, h=2, d=32, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(
            rng.standard_normal((b, n, h, d)).astype(np.float32) * 0.5)
        return mk(), mk(), mk()

    def test_masked_matches_ref(self):
        from paddle1_tpu.nn.functional.attention import attention_ref
        from paddle1_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._qkv()
        b, n = q.shape[0], k.shape[1]
        rng = np.random.default_rng(1)
        keep = np.ones((b, n), np.float32)
        keep[:, n // 2:] = 0.0  # second half = padding
        out = fa.flash_attention(q, k, v, padding_mask=jnp.asarray(keep))
        add = jnp.where(jnp.asarray(keep)[:, None, None, :] > 0, 0.0,
                        -1e9).astype(jnp.float32)
        ref = attention_ref(q, k, v, mask=add)
        # only non-padded query rows are meaningful downstream
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_masked_grads_finite_and_match(self):
        from paddle1_tpu.nn.functional.attention import attention_ref
        from paddle1_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._qkv(b=1, n=128, h=1, d=16)
        keep = np.ones((1, 128), np.float32)
        keep[:, 100:] = 0.0
        keepj = jnp.asarray(keep)

        def loss_flash(q, k, v):
            return jnp.sum(fa.flash_attention(
                q, k, v, padding_mask=keepj) ** 2)

        def loss_ref(q, k, v):
            add = jnp.where(keepj[:, None, None, :] > 0, 0.0, -1e9)
            return jnp.sum(attention_ref(q, k, v, mask=add) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            assert np.isfinite(np.asarray(a)).all()
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-4)

    def test_fully_padded_row_zero_output_and_grads(self):
        """Review finding: an all-padding batch entry must produce zero
        output and exactly zero gradients, not exp(0)=1 garbage."""
        from paddle1_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._qkv(b=2, n=128, h=1, d=16)
        keep = np.ones((2, 128), np.float32)
        keep[1, :] = 0.0  # batch entry 1 fully padded
        keepj = jnp.asarray(keep)

        out = fa.flash_attention(q, k, v, padding_mask=keepj)
        np.testing.assert_allclose(np.asarray(out)[1], 0.0)

        def loss(q, k, v):
            return jnp.sum(fa.flash_attention(
                q, k, v, padding_mask=keepj) ** 2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g in (gq, gk, gv):
            ga = np.asarray(g)
            assert np.isfinite(ga).all()
            np.testing.assert_allclose(ga[1], 0.0)

    def test_soft_bias_mask_falls_back_to_ref(self):
        """Review finding: a finite additive bias (not a padding mask) must
        NOT route to the flash kernel, which would drop it."""
        from paddle1_tpu.ops.pallas import flash_attention as fa
        from paddle1_tpu.nn import functional as F
        from paddle1_tpu.core.tensor import to_tensor
        q = np.random.default_rng(3).standard_normal(
            (2, 128, 2, 32)).astype(np.float32)
        bias = np.full((2, 1, 1, 128), -5.0, np.float32)  # soft penalty
        called = {}
        orig = fa.flash_attention

        def spy(*a, **kw):
            called["yes"] = True
            return orig(*a, **kw)

        fa.flash_attention = spy
        try:
            with flags_guard({"flash_attention": "always"}):
                out = F.scaled_dot_product_attention(
                    to_tensor(q), to_tensor(q), to_tensor(q),
                    attn_mask=to_tensor(bias), dropout_p=0.0)
        finally:
            fa.flash_attention = orig
        assert "yes" not in called, "soft bias was dropped by flash routing"
        # and the bias genuinely shifted nothing (uniform): output finite
        assert np.isfinite(np.asarray(out.data)).all()

    def test_bool_mask_routes_flash_under_trace(self):
        """BERT's bool keep-mask must stay flash-routable inside jit."""
        from paddle1_tpu.ops.pallas import flash_attention as fa
        from paddle1_tpu.nn import functional as F
        from paddle1_tpu.core.tensor import to_tensor
        q = np.random.default_rng(4).standard_normal(
            (2, 128, 2, 32)).astype(np.float32)
        keep = np.ones((2, 1, 1, 128), bool)
        keep[:, :, :, 100:] = False
        called = {}
        orig = fa.flash_attention

        def spy(*a, **kw):
            called["yes"] = True
            return orig(*a, **kw)

        fa.flash_attention = spy
        try:
            with flags_guard({"flash_attention": "always"}):
                def fwd(qa):
                    return F.scaled_dot_product_attention(
                        to_tensor(qa), to_tensor(qa), to_tensor(qa),
                        attn_mask=to_tensor(jnp.asarray(keep)),
                        dropout_p=0.0).data
                out = jax.jit(fwd)(jnp.asarray(q))
        finally:
            fa.flash_attention = orig
        assert called.get("yes"), "bool mask fell off the flash path in jit"
        assert np.isfinite(np.asarray(out)).all()

    def test_bert_routes_flash_for_bench_shapes(self):
        """The flagship-path regression VERDICT r2 flagged: BERT's padding
        mask must not knock attention off the flash path."""
        from paddle1_tpu.ops.pallas import flash_attention as fa
        from paddle1_tpu.nn import functional as F
        from paddle1_tpu.core.tensor import to_tensor
        q = np.random.default_rng(0).standard_normal(
            (2, 128, 2, 32)).astype(np.float32)
        mask = np.zeros((2, 1, 1, 128), np.float32)  # additive, no padding
        mask[:, :, :, 120:] = -1e9
        called = {}
        orig = fa.flash_attention

        def spy(*a, **kw):
            called["yes"] = True
            return orig(*a, **kw)

        fa.flash_attention = spy
        try:
            with flags_guard({"flash_attention": "always"}):
                out = F.scaled_dot_product_attention(
                    to_tensor(q), to_tensor(q), to_tensor(q),
                    attn_mask=to_tensor(mask), dropout_p=0.0)
        finally:
            fa.flash_attention = orig
        assert called.get("yes"), (
            "padding-shaped mask did not route to the flash kernel")
        assert np.isfinite(np.asarray(out.data)).all()


class TestFusedLayerNorm:
    @pytest.mark.parametrize("shape", [(16, 128), (4, 32, 256)])
    def test_matches_plain(self, shape):
        from paddle1_tpu.ops.pallas import layer_norm as pln
        rng = np.random.default_rng(0)
        h = shape[-1]
        x = jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 3 + 1)
        w = jnp.asarray(rng.standard_normal((h,)).astype(np.float32))
        b = jnp.asarray(rng.standard_normal((h,)).astype(np.float32))
        assert pln.supported(shape, 1)
        y = pln.fused_layer_norm(x, w, b, 1e-5)
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        ref = (x - mean) * jax.lax.rsqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_plain(self):
        from paddle1_tpu.ops.pallas import layer_norm as pln
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((8, 128)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((128,)).astype(np.float32))
        b = jnp.asarray(rng.standard_normal((128,)).astype(np.float32))

        def plain(x, w, b):
            mean = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            return jnp.sum(((x - mean) * jax.lax.rsqrt(var + 1e-5) * w + b)
                           ** 2)

        def fused(x, w, b):
            return jnp.sum(pln.fused_layer_norm(x, w, b, 1e-5) ** 2)

        gp = jax.grad(plain, argnums=(0, 1, 2))(x, w, b)
        gf = jax.grad(fused, argnums=(0, 1, 2))(x, w, b)
        for a, bb in zip(gf, gp):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=1e-4, atol=1e-4)

    def test_functional_routes_fused(self):
        from paddle1_tpu.ops.pallas import layer_norm as pln
        from paddle1_tpu.nn import functional as F
        from paddle1_tpu.core.tensor import to_tensor
        x = np.random.default_rng(2).standard_normal(
            (16, 128)).astype(np.float32)
        w = np.ones(128, np.float32)
        b = np.zeros(128, np.float32)
        called = {}
        orig = pln.fused_layer_norm

        def spy(*a, **kw):
            called["yes"] = True
            return orig(*a, **kw)

        pln.fused_layer_norm = spy
        try:
            with flags_guard({"fused_layer_norm": "always"}):
                y = F.layer_norm(to_tensor(x), 128, to_tensor(w),
                                 to_tensor(b))
        finally:
            pln.fused_layer_norm = orig
        assert called.get("yes")
        np.testing.assert_allclose(np.asarray(y.data).mean(), 0.0, atol=1e-5)


class TestFusedSoftmax:
    @pytest.mark.parametrize("shape", [(16, 128), (2, 8, 256)])
    def test_matches_jax(self, shape):
        from paddle1_tpu.ops.pallas import softmax as psm
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 4)
        assert psm.supported(shape, -1)
        y = psm.fused_softmax(x)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(jax.nn.softmax(x, axis=-1)),
                                   rtol=1e-5, atol=1e-6)

    def test_grads_match(self):
        from paddle1_tpu.ops.pallas import softmax as psm
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((8, 128)).astype(np.float32))
        gf = jax.grad(lambda a: jnp.sum(psm.fused_softmax(a) ** 2))(x)
        gr = jax.grad(lambda a: jnp.sum(jax.nn.softmax(a, -1) ** 2))(x)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5)

    def test_functional_routes(self):
        from paddle1_tpu.ops.pallas import softmax as psm
        from paddle1_tpu.nn import functional as F
        from paddle1_tpu.core.tensor import to_tensor
        x = np.random.default_rng(2).standard_normal(
            (16, 128)).astype(np.float32)
        called = {}
        orig = psm.fused_softmax

        def spy(*a, **kw):
            called["yes"] = True
            return orig(*a, **kw)

        psm.fused_softmax = spy
        try:
            with flags_guard({"fused_softmax": "always"}):
                y = F.softmax(to_tensor(x))
        finally:
            psm.fused_softmax = orig
        assert called.get("yes")
        np.testing.assert_allclose(np.asarray(y.data).sum(-1), 1.0,
                                   rtol=1e-5)

    def test_non_last_axis_falls_back(self):
        from paddle1_tpu.nn import functional as F
        from paddle1_tpu.core.tensor import to_tensor
        x = np.random.default_rng(3).standard_normal(
            (16, 128)).astype(np.float32)
        with flags_guard({"fused_softmax": "always"}):
            y = F.softmax(to_tensor(x), axis=0)   # not kernel-shaped
        np.testing.assert_allclose(np.asarray(y.data).sum(0), 1.0,
                                   rtol=1e-5)


class TestFusedAdam:
    def test_matches_plain_adamw(self):
        from paddle1_tpu.ops.pallas import fused_adam as fadam
        rng = np.random.default_rng(0)
        n = fadam._CHUNK + 123  # force padding path
        p = jnp.asarray(rng.standard_normal((n,)).astype(np.float32))
        g = jnp.asarray(rng.standard_normal((n,)).astype(np.float32))
        m1 = jnp.asarray(rng.standard_normal((n,)).astype(np.float32) * 0.01)
        m2 = jnp.abs(jnp.asarray(
            rng.standard_normal((n,)).astype(np.float32) * 0.01))
        beta1, beta2, eps, decay, lr = 0.9, 0.999, 1e-8, 0.01, 1e-3
        step = jnp.asarray(3, jnp.int32)

        np_, nm1, nm2 = fadam.fused_adam_update(
            p, g, m1, m2, lr, step, beta1, beta2, eps, decay)

        em1 = beta1 * m1 + (1 - beta1) * g
        em2 = beta2 * m2 + (1 - beta2) * g * g
        bc1 = 1 - beta1 ** 3
        bc2 = 1 - beta2 ** 3
        upd = (em1 / bc1) / (jnp.sqrt(em2 / bc2) + eps)
        ep = p * (1 - lr * decay) - lr * upd
        np.testing.assert_allclose(np.asarray(np_), np.asarray(ep),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(nm1), np.asarray(em1),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(nm2), np.asarray(em2),
                                   rtol=1e-6, atol=1e-7)

    def test_optimizer_fused_equals_unfused(self):
        """AdamW.functional_update with the flag on vs off is bit-close."""
        import paddle1_tpu as paddle
        from paddle1_tpu.ops.pallas import fused_adam as fadam
        from paddle1_tpu.nn.layer_common import Linear
        rng = np.random.default_rng(3)
        lin = Linear(128, 128)  # 16k params >= _CHUNK? ensure threshold
        n = int(np.prod(lin.weight.shape))
        params = {k: t.data for k, t in lin.state_dict().items()}
        grads = {k: jnp.asarray(
            rng.standard_normal(v.shape).astype(np.float32) * 0.01)
            for k, v in params.items()}

        def run(flag_val):
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=lin.parameters())
            state = opt.functional_init(params)
            with flags_guard({"fused_adam": flag_val}):
                newp, _ = opt.functional_update(params, grads, state,
                                                jnp.float32(1e-3))
            return newp

        p_plain = run("never")
        p_fused = run("always")
        for k in params:
            np.testing.assert_allclose(np.asarray(p_fused[k]),
                                       np.asarray(p_plain[k]),
                                       rtol=1e-6, atol=1e-7)
        assert n >= fadam._CHUNK  # the weight actually took the fused path


class TestFlashBackwardKernels:
    """Pallas flash BACKWARD (ops/pallas/flash_attention_bwd.py) vs the
    XLA recompute backward and vs autodiff of the dense reference —
    interpret mode (flag default stays 'never' until the chip smoke)."""

    def _problem(self, causal=False, masked=False, nq=256, nk=256):
        rng = np.random.default_rng(0)
        B, H, D = 2, 4, 64
        q, k, v = (jnp.asarray(rng.standard_normal((B, n, H, D))
                               .astype(np.float32))
                   for n in (nq, nk, nk))
        pm = jnp.asarray((rng.random((B, nk)) > 0.25)
                         .astype(np.float32)) if masked else None
        dout = jnp.asarray(rng.standard_normal((B, nq, H, D))
                           .astype(np.float32))
        return q, k, v, pm, dout

    def _grads(self, q, k, v, pm, dout, causal):
        from paddle1_tpu.ops.pallas import flash_attention as fa
        from paddle1_tpu.ops.pallas.flash_attention_bwd import \
            flash_attention_bwd
        scale = 1.0 / (q.shape[-1] ** 0.5)
        out, lse = fa._flash_fwd(q, k, v, scale, causal,
                                 padding_mask=pm)
        got = flash_attention_bwd(q, k, v, out, lse, dout, scale,
                                  causal, padding_mask=pm)
        want = fa._bwd_xla(q, k, v, out, lse, dout, scale, causal,
                           padding_mask=pm)
        return got, want

    def _check(self, got, want):
        for g, w, name in zip(got, want, "q k v".split()):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-3, atol=2e-3,
                                       err_msg=f"d{name}")

    def test_plain(self):
        q, k, v, pm, dout = self._problem()
        got, want = self._grads(q, k, v, None, dout, causal=False)
        self._check(got, want)

    def test_causal(self):
        q, k, v, pm, dout = self._problem(causal=True)
        got, want = self._grads(q, k, v, None, dout, causal=True)
        self._check(got, want)

    def test_padding_mask(self):
        q, k, v, pm, dout = self._problem(masked=True)
        got, want = self._grads(q, k, v, pm, dout, causal=False)
        self._check(got, want)

    def test_causal_rectangular(self):
        # nq < nk (bottom-right alignment)
        q, k, v, pm, dout = self._problem(causal=True, nq=128, nk=256)
        got, want = self._grads(q, k, v, None, dout, causal=True)
        self._check(got, want)

    def test_matches_dense_autodiff_end_to_end(self):
        from paddle1_tpu.core.flags import flags_guard
        from paddle1_tpu.nn.functional.attention import attention_ref
        from paddle1_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v, pm, dout = self._problem(masked=True)

        with flags_guard(flash_backward="always"):
            dq_p = jax.grad(lambda q: jnp.sum(
                flash_attention(q, k, v, padding_mask=pm) * dout))(q)
        dq_ref = jax.grad(lambda q: jnp.sum(attention_ref(
            q, k, v, mask=(pm[:, None, None, :] > 0.5)) * dout))(q)
        np.testing.assert_allclose(np.asarray(dq_p), np.asarray(dq_ref),
                                   rtol=5e-3, atol=5e-3)

    def test_flag_default_is_auto(self):
        # flipped never -> auto after the r5 on-chip smoke passed
        # (chip_results/kernel_smoke.txt: all bwd variants max_err=0)
        from paddle1_tpu.core.flags import flag
        assert flag("flash_backward") == "auto"

    def test_fully_padded_row_zero_grads(self):
        # one batch entry entirely padded: all three grads must be EXACT
        # zeros for it (the sentinel-LSE remap; review r3 finding)
        q, k, v, pm, dout = self._problem(masked=True)
        pm = pm.at[1].set(0.0)
        got, want = self._grads(q, k, v, pm, dout, causal=False)
        for g, name in zip(got, "q k v".split()):
            np.testing.assert_array_equal(
                np.asarray(g)[1], 0.0,
                err_msg=f"d{name} row 1 must be exactly zero")
        self._check(got, want)

    def test_supported_bounds_full_sequence_residency(self):
        from paddle1_tpu.ops.pallas.flash_attention_bwd import supported
        assert supported((2, 256, 4, 64), (2, 256, 4, 64))
        # 65536 q rows x 128 head dim: full q+do residency > VMEM budget
        assert not supported((1, 65536, 1, 128), (1, 1024, 1, 128))


class TestFlashAutoDispatch:
    """r5: flash_attention=auto is memory-adaptive — XLA dense attention
    below flash_auto_score_mb, Pallas flash above (the on-chip crossover
    sweep showed dense is faster at every compute-bound length;
    chip_results/flash_crossover.txt)."""

    def _route(self, monkeypatch, b, s, h=4, d=64, threshold_mb=4,
               mode="auto"):
        import jax
        import numpy as np
        from paddle1_tpu.core import flags as core_flags
        from paddle1_tpu.core.tensor import Tensor
        from paddle1_tpu.nn.functional.attention import \
            scaled_dot_product_attention as sdpa
        from paddle1_tpu.ops.pallas import flash_attention as fa

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        hit = {"flash": False}

        def spy(*a, **k):
            hit["flash"] = True
            raise RuntimeError("stop-at-dispatch")
        monkeypatch.setattr(fa, "flash_attention", spy)
        x = Tensor(np.zeros((b, s, h, d), np.float32))
        with core_flags.flags_guard(flash_attention=mode,
                                    flash_auto_score_mb=threshold_mb):
            try:
                sdpa(x, x, x)
            except RuntimeError as e:
                assert "stop-at-dispatch" in str(e)
        return hit["flash"]

    def test_small_seq_routes_dense(self, monkeypatch):
        # est = 2*4*128*128*(2*4+8)B = 2 MiB < 4 MiB -> dense
        assert self._route(monkeypatch, b=2, s=128) is False

    def test_large_seq_routes_flash(self, monkeypatch):
        # est = 2*4*1024*1024*(2*4+8)B = 128 MiB >= 4 MiB -> flash
        assert self._route(monkeypatch, b=2, s=1024) is True

    def test_always_ignores_threshold(self, monkeypatch):
        assert self._route(monkeypatch, b=2, s=128, threshold_mb=10**6,
                           mode="always") is True

    def test_bad_threshold_rejected(self):
        import pytest
        from paddle1_tpu.core import flags as core_flags
        from paddle1_tpu.core.errors import InvalidArgumentError
        for bad in (0, -5):
            with pytest.raises(InvalidArgumentError):
                core_flags.set_flags({"flash_auto_score_mb": bad})
        # fractional thresholds are legal (float flag, not int)
        with core_flags.flags_guard(flash_auto_score_mb=0.5):
            assert core_flags.flag("flash_auto_score_mb") == 0.5


class TestChunkedXlaBackward:
    """r5: _bwd_xla scans over query chunks for long sequences (the
    memory-escape backward when the Pallas kernels' VMEM model rejects
    the shape). Chunked must equal dense exactly."""

    def _problem(self, b=2, nq=256, nk=256, h=2, d=32, masked=False):
        rng = np.random.default_rng(0)
        mk = lambda *s: jnp.asarray(
            rng.standard_normal(s).astype(np.float32) * 0.3)
        q, k, v = mk(b, nq, h, d), mk(b, nk, h, d), mk(b, nk, h, d)
        dout = mk(b, nq, h, d)
        pm = None
        if masked:
            keep = np.ones((b, nk), np.float32)
            keep[:, nk - 40:] = 0.0
            pm = jnp.asarray(keep)
        return q, k, v, pm, dout

    @pytest.mark.parametrize("causal,masked,nq,nk", [
        (False, False, 256, 256),
        (True, False, 256, 256),
        (True, False, 128, 256),     # rectangular bottom-right causal
        (False, True, 256, 256),
    ])
    def test_chunked_equals_dense(self, causal, masked, nq, nk):
        from paddle1_tpu.ops.pallas import flash_attention as fa
        q, k, v, pm, dout = self._problem(nq=nq, nk=nk, masked=masked)
        scale = 1.0 / (q.shape[-1] ** 0.5)
        out, lse = fa._flash_fwd(q, k, v, scale, causal,
                                 padding_mask=pm)
        dense = fa._bwd_xla(q, k, v, out, lse, dout, scale, causal,
                            padding_mask=pm, q_chunk=nq)
        chunked = fa._bwd_xla(q, k, v, out, lse, dout, scale, causal,
                              padding_mask=pm, q_chunk=64)
        for g1, g2, name in zip(dense, chunked, "dq dk dv".split()):
            np.testing.assert_allclose(
                np.asarray(g1), np.asarray(g2), rtol=1e-5, atol=1e-5,
                err_msg=f"{name} causal={causal} masked={masked}")

    def test_vmem_model_rejects_long_seq(self):
        from paddle1_tpu.ops.pallas.flash_attention_bwd import supported
        assert supported((1, 4096, 12, 64), (1, 4096, 12, 64))
        # 32 * 16384 * 64 = 32 MiB > the 14 MiB budget (measured OOM
        # at 32.25 MiB scoped vmem on chip)
        assert not supported((1, 16384, 12, 64), (1, 16384, 12, 64))
        assert not supported((1, 8192, 12, 64), (1, 8192, 12, 64))
