"""Sharded checkpointing (distributed/checkpoint.py + engine methods):
save shard-by-shard from a live mesh, restore into the same — or a
DIFFERENT — sharding layout (reference save_persistables sliced-vars
role, fluid/io.py)."""

import numpy as np
import pytest
import jax

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor
from paddle1_tpu.distributed import (CheckpointManager, ParallelEngine,
                                     build_mesh)


def _make_engine(degrees, zero_stage=2, seed=0):
    rng = np.random.default_rng(seed)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
    # deterministic init across engines
    for i, p in enumerate(model.parameters()):
        p._data = jax.numpy.asarray(
            np.random.default_rng(100 + i)
            .standard_normal(p.shape).astype(np.float32) * 0.1)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())

    def loss_fn(m, b):
        return ((m(Tensor(b["x"])) - Tensor(b["y"])) ** 2).mean()

    n = int(np.prod(list(degrees.values())))
    mesh = build_mesh(**degrees, devices=jax.devices()[:n])
    eng = ParallelEngine(model, opt, loss_fn, mesh=mesh,
                         zero_stage=zero_stage, donate=False)
    batch = {"x": rng.standard_normal((8, 8)).astype(np.float32),
             "y": rng.standard_normal((8, 4)).astype(np.float32)}
    return eng, batch


def _trees_close(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-6)


class TestShardedCheckpoint:
    def test_save_restore_same_topology(self, tmp_path):
        eng, batch = _make_engine({"dp": 2, "sharding": 2})
        for _ in range(2):
            eng.step(batch)
        path = eng.save_checkpoint(str(tmp_path / "ck"))

        eng2, _ = _make_engine({"dp": 2, "sharding": 2}, seed=1)
        eng2.load_checkpoint(path)
        _trees_close(eng.params, eng2.params)
        _trees_close(eng.opt_state, eng2.opt_state)
        # training continues identically
        l1 = float(eng.step(batch))
        l2 = float(eng2.step(batch))
        np.testing.assert_allclose(l1, l2, rtol=1e-6)

    def test_restore_into_different_topology(self, tmp_path):
        # ZeRO-2 over (dp=2, sharding=2) → restore into (dp=4) — orbax
        # reshards on load; values identical, layout per target engine
        eng, batch = _make_engine({"dp": 2, "sharding": 2})
        eng.step(batch)
        path = eng.save_checkpoint(str(tmp_path / "ck"))

        eng2, _ = _make_engine({"dp": 4}, zero_stage=0, seed=2)
        eng2.load_checkpoint(path)
        _trees_close(eng.params, eng2.params)
        l1 = float(eng.step(batch))
        l2 = float(eng2.step(batch))
        np.testing.assert_allclose(l1, l2, rtol=1e-5)

    def test_sync_model_after_load(self, tmp_path):
        eng, batch = _make_engine({"dp": 2})
        eng.step(batch)
        path = eng.save_checkpoint(str(tmp_path / "ck"))
        eng2, _ = _make_engine({"dp": 2}, seed=3)
        eng2.load_checkpoint(path)
        # the Layer itself carries the restored weights (save/eval path)
        for k, arr in eng2.params.items():
            sd = eng2.model.state_dict()
            if k in sd:
                np.testing.assert_allclose(np.asarray(sd[k]._data),
                                           np.asarray(arr))

    def test_manager_retention_and_latest(self, tmp_path):
        eng, batch = _make_engine({"dp": 2})
        mgr = CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=2)
        for s in (1, 2, 3):
            eng.step(batch)
            mgr.save(s, {"params": eng.params})
        assert mgr.latest_step() == 3
        import os
        kept = sorted(int(d) for d in os.listdir(mgr.directory)
                      if d.isdigit())
        assert kept == [2, 3]
        restored, step = mgr.restore({"params": eng.params})
        assert step == 3
        _trees_close(restored["params"], eng.params)
        with pytest.raises(FileNotFoundError):
            CheckpointManager(str(tmp_path / "empty")).restore(
                {"params": eng.params})
