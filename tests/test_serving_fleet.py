"""Serving fleet (ISSUE 7): supervised replicas, health-gated routing,
hot-swap, overload degradation.

Fast cases (in-tier) exercise the pure logic — adaptive admission, the
wire protocol, FleetFuture first-wins, metrics groups/merging, replica
chaos points, and Supervisor non-trainer adoption with plain-stdlib
workers (no jax import). The full replica-subprocess matrix (kill
failover, hot-swap, canary rollback, hang breaker, overload soak) is
slow-marked — each spawns real ``paddle1_tpu.serving.replica``
processes (~10s of jax import + warmup apiece) and runs in the CI
serving-fleet step; ``bench.py --serving-fleet`` is the acceptance
soak.
"""

import json
import os
import socket
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle1_tpu.core import chaos
from paddle1_tpu.serving import (AdaptiveAdmission, DeadlineExceeded,
                                 DeployFailed, FleetFuture, MetricsGroup,
                                 ReplicaFailed, ServerOverloaded,
                                 ServingFleet, ServingMetrics,
                                 merge_snapshots)
from paddle1_tpu.serving import wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FACTORY = textwrap.dedent('''
    def make_model(arg):
        import numpy as np
        import jax.numpy as jnp
        if arg == "boom":
            raise RuntimeError("broken artifact")
        rng = np.random.default_rng(0)
        W1 = (rng.standard_normal((8, 16)) * 0.1).astype(np.float32)
        b1 = np.zeros(16, np.float32)
        W2 = (rng.standard_normal((16, 4)) * 0.1).astype(np.float32)
        b2 = np.zeros(4, np.float32)
        scale = 2.0 if arg == "v2" else 1.0

        def fwd(x):
            h = jnp.maximum(x @ W1 + b1, 0)
            return (h @ W2 + b2) * scale
        return fwd
''')


# -- fast: adaptive admission -------------------------------------------------

class TestAdaptiveAdmission:
    def test_overload_ramp(self):
        a = AdaptiveAdmission(100, shed_start=0.5, levels=4, alpha=1.0)
        a.observe(10)
        assert a.overload() == 0.0
        a.observe(50)
        assert a.overload() == 0.0  # exactly at the start: no shedding
        a.observe(75)
        assert abs(a.overload() - 0.5) < 1e-9
        a.observe(100)
        assert a.overload() == 1.0
        a.observe(500)
        assert a.overload() == 1.0  # clamped

    def test_priority_zero_never_adaptively_shed(self):
        a = AdaptiveAdmission(10, shed_start=0.5, levels=4, alpha=1.0)
        a.observe(1000)  # fully overloaded
        assert not a.should_shed(0, None)
        assert not a.should_shed(0, 50.0)

    def test_lowest_priority_sheds_first(self):
        a = AdaptiveAdmission(100, shed_start=0.5, levels=4, alpha=1.0)
        a.observe(75)  # overload 0.5 -> cutoff score 0.5
        # p3 (rank 1.0): score >= 0.75 -> shed regardless of deadline
        assert a.should_shed(3, None)
        assert a.should_shed(3, 100.0)
        # p1 (rank 1/3): score 0.25 + 0.25*dl_rank <= 0.5 -> admitted
        assert not a.should_shed(1, 100.0, 30000.0)

    def test_longest_deadline_breaks_ties(self):
        a = AdaptiveAdmission(100, shed_start=0.5, levels=4, alpha=1.0)
        a.observe(80)  # overload 0.6 -> cutoff score 0.4
        # the marginal class p1 (priority score 0.25): a tight deadline
        # stays under the cutoff (0.25 + ~0.001 < 0.4), while a long or
        # absent deadline — the most shed-tolerant work — goes over
        # (0.25 + 0.25 = 0.5 > 0.4)
        assert not a.should_shed(1, 100.0, 30000.0)
        assert a.should_shed(1, None)
        assert a.should_shed(1, 30000.0, 30000.0)

    def test_ewma_decays_back_to_admitting(self):
        a = AdaptiveAdmission(10, shed_start=0.5, levels=4, alpha=0.5)
        a.observe(100)
        assert a.should_shed(3, None)
        for _ in range(20):
            a.observe(0)  # the sweep feeds the EWMA when idle
        assert a.overload() == 0.0
        assert not a.should_shed(3, None)


# -- fast: wire protocol ------------------------------------------------------

class TestWireProtocol:
    def test_round_trip_header_and_arrays(self):
        s1, s2 = socket.socketpair()
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        b = np.array([[1, 2]], dtype=np.int64)
        wire.send_msg(s1, {"kind": "infer", "id": 9,
                           "deadline_ms": 12.5}, [a, b])
        h, arrs = wire.recv_msg(s2)
        assert h["kind"] == "infer" and h["id"] == 9
        assert h["deadline_ms"] == 12.5 and h["n"] == 2
        np.testing.assert_array_equal(arrs[0], a)
        np.testing.assert_array_equal(arrs[1], b)
        assert arrs[0].dtype == np.float32 and arrs[1].dtype == np.int64

    def test_peer_close_is_connection_error(self):
        s1, s2 = socket.socketpair()
        s1.close()
        with pytest.raises(ConnectionError):
            wire.recv_msg(s2)

    def test_mid_frame_close_is_connection_error(self):
        s1, s2 = socket.socketpair()
        s1.sendall(b"\x40\x00\x00\x00{")  # claims 64 bytes, sends 1
        s1.close()
        with pytest.raises(ConnectionError, match="mid-frame"):
            wire.recv_msg(s2)

    def test_object_arrays_refused(self):
        # the no-pickle contract: an object array must fail at SEND
        s1, _ = socket.socketpair()
        with pytest.raises(Exception):
            wire.send_msg(s1, {"kind": "infer", "id": 1},
                          [np.array([object()])])

    def test_idle_hook_can_abort(self):
        s1, s2 = socket.socketpair()
        s2.settimeout(0.01)

        class Abort(Exception):
            pass

        def idle():
            raise Abort

        with pytest.raises(Abort):
            wire.recv_msg(s2, idle=idle)

    def test_idle_timeout_preserves_partial_frame(self):
        # a timeout mid-frame must not desynchronize the stream
        s1, s2 = socket.socketpair()
        s2.settimeout(0.02)
        a = np.ones((2, 2), np.float32)
        done = threading.Event()

        def slow_send():
            import io as _io
            buf = _io.BytesIO()
            np.lib.format.write_array(buf, a, allow_pickle=False)
            blob = buf.getvalue()
            hb = json.dumps({"kind": "result", "id": 1, "n": 1}).encode()
            import struct as _struct
            frame = (_struct.pack("<I", len(hb)) + hb
                     + _struct.pack("<I", len(blob)) + blob)
            for i in range(0, len(frame), 7):
                s1.sendall(frame[i:i + 7])
                time.sleep(0.005)  # forces timeouts between chunks
            done.set()

        t = threading.Thread(target=slow_send)
        t.start()
        h, arrs = wire.recv_msg(s2, idle=lambda: None)
        t.join()
        assert h["id"] == 1
        np.testing.assert_array_equal(arrs[0], a)


# -- fast: FleetFuture --------------------------------------------------------

class TestFleetFuture:
    def test_first_wins_value_then_exception(self):
        f = FleetFuture()
        assert f._set_value([np.ones(3)], "v1")
        assert not f._set_exception(RuntimeError("late"))
        assert f.version == "v1"
        np.testing.assert_array_equal(f.result(), np.ones(3))

    def test_first_wins_exception_then_value(self):
        f = FleetFuture()
        assert f._set_exception(ReplicaFailed("gone"))
        assert not f._set_value([np.ones(3)], "v1")
        with pytest.raises(ReplicaFailed):
            f.result()

    def test_multi_output_list(self):
        f = FleetFuture()
        f._set_value([np.ones(2), np.zeros(2)], "v1")
        outs = f.result()
        assert isinstance(outs, list) and len(outs) == 2

    def test_result_timeout_typed(self):
        f = FleetFuture()
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded, match="still in flight"):
            f.result(timeout=0.05)
        assert time.monotonic() - t0 < 5
        # the request later resolves: first-wins, reader can come back
        f._set_value([np.ones(1)], "v2")
        assert f.result().shape == (1,)


# -- fast: metrics groups -----------------------------------------------------

class TestMetricsGroups:
    def test_per_label_isolation_and_aggregate(self):
        g = MetricsGroup("version")
        g.child("v1").counter("responses_total").inc(3)
        g.child("v2").counter("responses_total").inc(4)
        g.child("v1").histogram("e2e_ms").observe(10.0)
        g.child("v2").histogram("e2e_ms").observe(50.0)
        snap = g.snapshot()
        assert snap["v1"]["counters"]["responses_total"] == 3
        agg = g.aggregate()
        assert agg["counters"]["responses_total"] == 7
        h = agg["histograms"]["e2e_ms"]
        assert h["count"] == 2 and h["sum"] == 60.0
        assert h["max"] == 50.0  # conservative: worst child

    def test_group_render_text_labels(self):
        g = MetricsGroup("replica")
        g.child(0).counter("responses_total").inc()
        g.child(1).counter("responses_total").inc(2)
        text = g.render_text()
        assert 'p1t_serving_responses_total{replica="0"} 1' in text
        assert 'p1t_serving_responses_total{replica="1"} 2' in text

    def test_merge_snapshots_cross_process_shape(self):
        # exactly what fleet_snapshot(include_replicas=True) merges:
        # plain dicts that rode the wire as JSON
        m = ServingMetrics()
        m.counter("requests_total").inc(5)
        m.histogram("queue_ms").observe(2.0)
        s1 = json.loads(json.dumps(m.snapshot()))
        s2 = json.loads(json.dumps(m.snapshot()))
        agg = merge_snapshots([s1, s2])
        assert agg["counters"]["requests_total"] == 10
        assert agg["histograms"]["queue_ms"]["count"] == 2


# -- fast: replica chaos points ----------------------------------------------

class TestReplicaChaosPoints:
    def teardown_method(self):
        chaos.reset()

    def test_shared_counter_and_qualifier(self):
        chaos.configure("replica_kill@3:1,replica_slow@2:0")
        assert chaos.check_replica(0) is None       # req 1
        assert chaos.check_replica(0) == "replica_slow"   # req 2
        assert chaos.check_replica(0) is None       # req 3: wrong rank
        chaos.reset()
        chaos.configure("replica_kill@3:1")
        assert chaos.check_replica(1) is None
        assert chaos.check_replica(1) is None
        assert chaos.check_replica(1) == "replica_kill"

    def test_kill_beats_hang_beats_slow(self):
        chaos.configure("replica_kill@1,replica_hang@1,replica_slow@1")
        assert chaos.check_replica(0) == "replica_kill"

    def test_spec_round_trips_active_spec(self):
        chaos.configure("replica_hang@4:2")
        assert chaos.active_spec() == "replica_hang@4:2"


# -- fast: Supervisor non-trainer adoption (plain-stdlib workers) -------------

BEATER = textwrap.dedent("""
    import os, sys, time
    hb = os.environ["PADDLE_FT_HEARTBEAT_FILE"]
    if os.environ.get("EXIT_RC"):
        sys.exit(int(os.environ["EXIT_RC"]))
    n = int(os.environ.get("BEATS", "3000"))
    for _ in range(n):
        os.utime(hb, None)
        time.sleep(0.02)
""")

GRANDCHILD_ENV = textwrap.dedent("""
    import importlib.util, json, os, subprocess, sys, time
    spec = importlib.util.spec_from_file_location(
        "health", os.environ["HEALTH_PY"])
    health = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(health)
    health.beat()   # adopts + POPS the PADDLE_FT_* env (replica.py
                    # calls this before anything else for this reason)
    out = subprocess.run(
        [sys.executable, "-c",
         "import os, json; print(json.dumps(sorted("
         "k for k in os.environ if k.startswith('PADDLE_FT_'))))"],
        capture_output=True, text=True)
    with open(os.environ["RESULT_FILE"], "w") as f:
        f.write(out.stdout.strip())
    for _ in range(100):
        health.beat()
        time.sleep(0.02)
""")


def _sup(tmp_path, **kw):
    from paddle1_tpu.distributed.supervisor import Supervisor
    kw.setdefault("policy", "restart")
    kw.setdefault("elastic", False)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("grace_s", 3.0)
    kw.setdefault("hang_timeout", 5.0)
    kw.setdefault("heartbeat_dir", str(tmp_path / "hb"))
    return Supervisor(**kw)


def _worker(tmp_path, body, name="worker.py"):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


class TestSupervisorAdoption:
    def test_clean_exit_is_done_not_failure(self, tmp_path):
        """An essential=False replica exiting 0 (a retire/drain) is
        role-complete — supervise_once reports nothing."""
        w = _worker(tmp_path, BEATER)
        sup = _sup(tmp_path)
        sup.add_worker(0, [sys.executable, "-u", w],
                       env=dict(os.environ, BEATS="1"), role="replica")
        sup.start()
        t0 = time.monotonic()
        events = []
        while time.monotonic() - t0 < 30:
            events += sup.supervise_once()
            if sup.worker_done(0):
                break
            time.sleep(0.05)
        assert sup.worker_done(0)
        assert events == []
        assert sup.report.failures == []

    def test_restart_then_budget_exhaustion(self, tmp_path):
        """A crashing replica is relaunched within budget; exhaustion
        surfaces as a restart_exhausted event ONCE (the corpse must not
        re-report every sweep)."""
        w = _worker(tmp_path, BEATER)
        sup = _sup(tmp_path, max_restarts=1)
        sup.add_worker(0, [sys.executable, "-u", w],
                       env=dict(os.environ, EXIT_RC="3"), role="replica")
        sup.start()
        actions = []
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            for ev in sup.supervise_once():
                actions.append(ev.action)
            if "restart_exhausted" in actions:
                break
            time.sleep(0.05)
        assert actions == ["restarted", "restart_exhausted"]
        assert sup.incarnation(0) == 1
        assert sup.restarts_used(0) == 1
        # abandoned: further sweeps stay quiet
        for _ in range(5):
            assert sup.supervise_once() == []
            time.sleep(0.02)

    def test_per_worker_zero_budget(self, tmp_path):
        """max_restarts=0 (the deploy-canary setting): first failure is
        immediately terminal, no relaunch."""
        w = _worker(tmp_path, BEATER)
        sup = _sup(tmp_path, max_restarts=5)
        sup.add_worker(0, [sys.executable, "-u", w],
                       env=dict(os.environ, EXIT_RC="3"),
                       role="replica", max_restarts=0)
        sup.start()
        t0 = time.monotonic()
        actions = []
        while time.monotonic() - t0 < 30 and not actions:
            actions = [ev.action for ev in sup.supervise_once()]
            time.sleep(0.05)
        assert actions == ["restart_exhausted"]
        assert sup.restarts_used(0) == 0

    def test_retire_exit_never_classified(self, tmp_path):
        """retire() SIGTERMs and removes the rank — the exit must not
        appear as a failure (the hot-swap old-replica path)."""
        w = _worker(tmp_path, BEATER)
        sup = _sup(tmp_path)
        sup.add_worker(0, [sys.executable, "-u", w],
                       env=dict(os.environ), role="replica")
        sup.start()
        time.sleep(0.3)
        sup.retire(0, grace_s=5.0)
        assert sup.worker_ranks() == []
        assert sup.supervise_once() == []
        assert sup.report.failures == []

    def test_heartbeat_env_not_leaked_to_grandchildren(self, tmp_path):
        """The PR 3 gotcha, replica flavor: the worker adopts the
        channel (health.beat first), so its grandchildren see NO
        PADDLE_FT_* vars — a grandchild beating the replica's file
        would mask a real replica hang."""
        w = _worker(tmp_path, GRANDCHILD_ENV)
        result = tmp_path / "grandchild_env.json"
        health_py = os.path.join(REPO, "paddle1_tpu", "core",
                                 "health.py")
        sup = _sup(tmp_path)
        sup.add_worker(0, [sys.executable, "-u", w],
                       env=dict(os.environ, HEALTH_PY=health_py,
                                RESULT_FILE=str(result)),
                       role="replica")
        sup.start()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30 and not result.exists():
            sup.supervise_once()
            time.sleep(0.05)
        time.sleep(0.2)
        assert result.exists(), "worker never wrote the probe result"
        assert json.loads(result.read_text()) == []
        sup.retire(0, grace_s=2.0)

    def test_fleet_latches_unhealthy_on_exhaustion(self, tmp_path):
        """Budget exhaustion marks the FLEET unhealthy (the outer
        supervisor's signal) — but a probation canary's death does
        not."""
        from paddle1_tpu.serving.fleet import _ReplicaClient
        fleet = ServingFleet("x.py:f", replicas=1,
                             work_dir=str(tmp_path))
        standing = _ReplicaClient(fleet, 0, "v1",
                                  str(tmp_path / "ep0.json"))
        canary = _ReplicaClient(fleet, 1, "v2",
                                str(tmp_path / "ep1.json"),
                                probation=True)
        assert fleet.healthy
        fleet._on_replica_exhausted(canary, None)
        assert fleet.healthy  # deploy failure, not fleet degradation
        fleet._on_replica_exhausted(standing, None)
        assert not fleet.healthy
        assert standing.state == "failed"


# -- fast: replica model loading ---------------------------------------------

class TestReplicaModelLoading:
    def test_file_factory(self, tmp_path):
        from paddle1_tpu.serving.replica import load_model
        p = tmp_path / "factory.py"
        p.write_text(FACTORY)
        fwd = load_model(f"{p}:make_model", "v1")
        out = np.asarray(fwd(np.zeros((1, 8), np.float32)))
        assert out.shape == (1, 4)

    def test_factory_error_propagates(self, tmp_path):
        from paddle1_tpu.serving.replica import load_model
        p = tmp_path / "factory.py"
        p.write_text(FACTORY)
        with pytest.raises(RuntimeError, match="broken artifact"):
            load_model(f"{p}:make_model", "boom")

    def test_bad_spec_typed(self):
        from paddle1_tpu.serving.replica import load_model
        with pytest.raises(ValueError, match="model spec"):
            load_model("no-colon-here")


# -- slow: the real replica-subprocess matrix ---------------------------------

def _make_fleet(tmp_path, n=2, chaos_spec=None, **kw):
    factory = tmp_path / "factory.py"
    factory.write_text(FACTORY)
    kw.setdefault("max_batch", 8)
    kw.setdefault("buckets", (1, 8))
    kw.setdefault("batch_timeout_ms", 2)
    kw.setdefault("input_specs", [((8,), "float32")])
    kw.setdefault("warmup", True)
    kw.setdefault("hang_timeout", 30.0)
    kw.setdefault("poll_s", 0.1)
    kw.setdefault("version", "v1")
    kw.setdefault("model_arg", "v1")
    # small in-flight cap: a request burst must SPREAD across replicas
    # (with a large cap the first-connected replica can hoover a whole
    # burst and a rank-qualified chaos point never sees its Nth request)
    kw.setdefault("inflight_per_replica", 4)
    env = kw.pop("env", {})
    env.setdefault("JAX_PLATFORMS", "cpu")
    return ServingFleet(f"{factory}:make_model", replicas=n, env=env,
                        work_dir=str(tmp_path / "fleet"),
                        chaos_spec=chaos_spec, **kw)


def _reference(version="v1"):
    """The single-process engine answer for the FACTORY model."""
    rng = np.random.default_rng(0)
    W1 = (rng.standard_normal((8, 16)) * 0.1).astype(np.float32)
    b1 = np.zeros(16, np.float32)
    W2 = (rng.standard_normal((16, 4)) * 0.1).astype(np.float32)
    b2 = np.zeros(4, np.float32)
    scale = 2.0 if version == "v2" else 1.0

    def fwd(x):
        h = np.maximum(x @ W1 + b1, 0)
        return (h @ W2 + b2) * scale
    return fwd


@pytest.mark.slow
class TestFleetSubprocessMatrix:
    def test_kill_failover_every_request_resolves(self, tmp_path):
        """replica_kill mid-load: in-flight work fails over to the
        survivor, the Supervisor relaunches the rank, zero
        client-visible failures, unaccounted == 0."""
        fleet = _make_fleet(tmp_path, n=2, retry_max=3,
                            replica_timeout_ms=60000,
                            chaos_spec="replica_kill@5:1")
        fleet.start()
        try:
            rng = np.random.default_rng(1)
            xs = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(60)]
            futs = [fleet.submit(x) for x in xs]
            outs = [f.result(timeout=300) for f in futs]
            ref = _reference("v1")
            err = max(float(np.max(np.abs(ref(x) - o)))
                      for x, o in zip(xs, outs))
            assert err <= 1e-6, err
        finally:
            rep = fleet.drain()
        assert rep["unaccounted"] == 0, rep
        assert rep["completed"] == 60
        assert rep["errors"] == 0
        assert rep["replica_restarts"] >= 1, rep

    def test_hot_swap_zero_drops_and_version_split(self, tmp_path):
        """deploy under load: zero dropped requests, responses tagged
        per version, each matching its own reference at 1e-6, metrics
        split by version."""
        fleet = _make_fleet(tmp_path, n=2)
        fleet.start()
        stop = threading.Event()
        got, failures = [], []
        rng = np.random.default_rng(2)
        xs = [rng.standard_normal((1, 8)).astype(np.float32)
              for _ in range(16)]

        def pump():
            i = 0
            while not stop.is_set():
                i = (i + 1) % len(xs)
                try:
                    f = fleet.submit(xs[i])
                    got.append((i, f, f.result(timeout=300)))
                except Exception as e:  # noqa: broad-except — ANY
                    # failure during the swap fails the zero-drop gate
                    failures.append(repr(e))
        t = threading.Thread(target=pump)
        t.start()
        try:
            res = fleet.deploy(fleet.model_spec, "v2", model_arg="v2",
                               canary=[np.zeros((1, 8), np.float32)])
        finally:
            stop.set()
            t.join(timeout=300)
        assert res["rolled"] == 2
        assert not failures, failures[:3]
        refs = {"v1": _reference("v1"), "v2": _reference("v2")}
        err = max(float(np.max(np.abs(refs[f.version](xs[i]) - o)))
                  for i, f, o in got)
        assert err <= 1e-6, err
        # tail of the pump ran on v2
        assert got[-1][1].version == "v2"
        by_version = fleet.version_metrics.snapshot()
        assert "v2" in by_version
        try:
            assert by_version["v2"]["counters"]["responses_total"] >= 1
        finally:
            rep = fleet.drain()
        assert rep["unaccounted"] == 0, rep
        assert rep["deploys"] == 1

    def test_failed_canary_rolls_back_still_serving(self, tmp_path):
        fleet = _make_fleet(tmp_path, n=2)
        fleet.start()
        try:
            with pytest.raises(DeployFailed, match="canary"):
                fleet.deploy(fleet.model_spec, "v2", model_arg="boom",
                             ready_timeout_s=60)
            assert fleet.healthy  # canary death is not fleet sickness
            x = np.zeros((1, 8), np.float32)
            f = fleet.submit(x)
            out = f.result(timeout=120)
            assert f.version == "v1"
            assert float(np.max(np.abs(_reference("v1")(x) - out))) \
                <= 1e-6
        finally:
            rep = fleet.drain()
        assert rep["unaccounted"] == 0, rep
        assert rep["rollbacks"] == 1

    def test_hang_breaker_failover(self, tmp_path):
        """replica_hang: the replica stops reading but keeps
        heartbeating — only the fleet's transport deadline can see it.
        In-flight work fails over, the rank is force-restarted, every
        request resolves."""
        fleet = _make_fleet(tmp_path, n=2, retry_max=3,
                            replica_timeout_ms=4000,
                            chaos_spec="replica_hang@4:1")
        fleet.start()
        try:
            rng = np.random.default_rng(3)
            xs = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(40)]
            futs = [fleet.submit(x) for x in xs]
            outs = [f.result(timeout=300) for f in futs]
            assert len(outs) == 40
        finally:
            rep = fleet.drain()
        assert rep["unaccounted"] == 0, rep
        assert rep["completed"] == 40
        assert rep["errors"] == 0
        assert rep["failovers"] >= 1, rep

    def test_overload_sheds_low_priority_typed(self, tmp_path):
        """Sustained overload (a wedged replica + a flood): adaptive
        admission sheds low-priority work typed; priority 0 is never
        adaptively shed; everything admitted resolves and the books
        balance."""
        fleet = _make_fleet(tmp_path, n=1, retry_max=3,
                            replica_timeout_ms=60000,
                            fleet_queue_depth=64, shed_start=0.5,
                            chaos_spec="replica_slow@1:0",
                            env={"JAX_PLATFORMS": "cpu",
                                 "FLAGS_serve_chaos_slow_s": "2.0"})
        fleet.start()
        try:
            x = np.zeros((1, 8), np.float32)
            futs, sheds = [], []
            for i in range(400):
                prio = i % 4
                try:
                    futs.append(fleet.submit(x, priority=prio))
                except ServerOverloaded as e:
                    sheds.append((prio, "adaptive" in str(e)))
            for f in futs:
                f.result(timeout=300)
        finally:
            rep = fleet.drain()
        assert rep["unaccounted"] == 0, rep
        assert rep["shed"] == len(sheds)
        assert rep["shed_adaptive"] >= 1, rep
        counters = fleet.metrics.snapshot()["counters"]
        # per-priority shed counters grew the _total suffix (ISSUE 10
        # metric-name lint); priority 0 must never be adaptively shed
        assert "shed_priority_0_total" not in counters, counters
        assert "shed_priority_0" not in counters, counters
        adaptive_prios = {p for p, adaptive in sheds if adaptive}
        assert adaptive_prios and 0 not in adaptive_prios

    def test_scale_to_zero_downtime_both_directions(self, tmp_path):
        """ISSUE 18: scale_to grows then shrinks the fleet under load
        with zero client-visible failures; scale counters, the
        serve_replicas_* / serve_queue_depth_ewma gauges, and the
        fleet_scale event journal all record the transitions."""
        from paddle1_tpu.obs import events as obs_events
        journal = str(tmp_path / "events.jsonl")
        os.environ[obs_events.EVENTS_ENV] = journal
        fleet = _make_fleet(tmp_path, n=1, retry_max=3,
                            replica_timeout_ms=60000)
        try:
            fleet.start()
            rng = np.random.default_rng(5)
            xs = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(16)]
            stop = threading.Event()
            failures, ok = [], [0]

            def pump():
                i = 0
                while not stop.is_set():
                    i += 1
                    try:
                        fleet.submit(xs[i % 16]).result(timeout=300)
                        ok[0] += 1
                    except Exception as e:  # noqa: broad-except — ANY
                        # failure during either transition fails the
                        # zero-downtime gate below
                        failures.append(repr(e))
            threads = [threading.Thread(target=pump) for _ in range(4)]
            for t in threads:
                t.start()
            try:
                up = fleet.scale_to(3, reason="test scale-out")
                down = fleet.scale_to(2, reason="test scale-in")
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=300)
            assert up["from"] == 1 and up["to"] == 3
            assert len(up["added"]) == 2 and not up["retired"]
            assert down["from"] == 3 and down["to"] == 2
            assert down["retired"] == [2]    # highest rank drains out
            assert fleet.live_replicas() == fleet.ready_replicas() == 2
            assert not failures, failures[:3]
            assert ok[0] >= 1
            snap = fleet.metrics.snapshot()
            assert snap["counters"]["scale_out_total"] == 1
            assert snap["counters"]["scale_in_total"] == 1
            assert snap["gauges"]["serve_replicas_live"] == 2
            # the sweep publishes the admission EWMA as a first-class
            # gauge (ISSUE 18 satellite): present and finite
            assert snap["gauges"]["serve_queue_depth_ewma"] >= 0.0
            evs = [e for e in obs_events.read_events(journal)
                   if e["event"] == "fleet_scale"]
            assert [(e["replicas_from"], e["replicas_to"])
                    for e in evs] == [(1, 3), (3, 2)]
            assert all(e["kind"] == "serving" and not e["refused"]
                       for e in evs)
        finally:
            os.environ.pop(obs_events.EVENTS_ENV, None)
            rep = fleet.drain()
        assert rep["unaccounted"] == 0, rep
        assert rep["errors"] == 0
