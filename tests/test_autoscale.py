"""SLO-driven autoscaler (ISSUE 18 tentpole): policy grammar, the
hysteresis/cooldown/dwell/backoff control discipline, typed refusal
handling, and the chaos-composed scaling edges.

Fast cases drive :class:`~paddle1_tpu.serving.autoscale.Autoscaler`
against an in-process fake target with injected clocks — every
decision path is deterministic (``decide()`` is pure in the signals
plus the loop's clocks, ``step(now=...)`` pins time). The slow class
spawns real replica subprocesses and exercises the satellite-3 edges:
scale-in racing an in-flight deploy canary, a flash-crowd burst
landing mid-scale, and an autoscaler decision while a replica's
restart budget is exhausted — each typed, each with the drain
identity ``unaccounted == 0``.
"""

import threading
import time

import numpy as np
import pytest

from paddle1_tpu.core.errors import InvalidArgumentError
from paddle1_tpu.obs import events as obs_events
from paddle1_tpu.obs import slo as obs_slo
from paddle1_tpu.obs.registry import MetricsRegistry
from paddle1_tpu.serving import (Autoscaler, ScaleFailed, ScalingPolicy,
                                 ServerOverloaded, ServingFleet,
                                 SupervisorTarget, parse_policy)
from paddle1_tpu.serving.autoscale import (HOLD, SCALE_IN, SCALE_OUT,
                                           Decision)

from test_serving_fleet import FACTORY

AUTOSCALE_FAMILIES = (
    "autoscale_decisions_total", "autoscale_scale_out_total",
    "autoscale_scale_in_total", "autoscale_refusals_total",
    "autoscale_queue_ratio", "autoscale_burn_max_ratio",
    "autoscale_target_replicas", "autoscale_decision_seconds")


class _FakeAdmission:
    def __init__(self, ewma=0.0, depth=100):
        self.ewma = ewma
        self.depth = depth

    def overload(self):
        return 0.0


class _FakeFleet:
    """Just enough surface for the Autoscaler: live/ready counts, an
    admission EWMA, a metrics registry, and a scale_to that records
    (or refuses) every transition."""

    def __init__(self, live=2, queue_ratio=0.0, fail=False):
        self.metrics = MetricsRegistry()
        self.admission = _FakeAdmission(ewma=queue_ratio * 100)
        self.fail = fail
        self.calls = []
        self._live = live

    def live_replicas(self):
        return self._live

    def ready_replicas(self):
        return self._live

    def scale_to(self, n, ready_timeout_s=None, reason="requested"):
        if self.fail:
            raise ScaleFailed("wedged transition (test)")
        start, self._live = self._live, int(n)
        self.calls.append((start, int(n), reason))
        return {"from": start, "to": int(n)}


class TestPolicyGrammar:
    def test_empty_spec_is_defaults(self):
        assert parse_policy("") == ScalingPolicy()

    def test_full_grammar_roundtrip(self):
        p = parse_policy("min=2;max=8;queue_hi=0.8;queue_lo=0.1;"
                         "burn_hi=1.5;burn_lo=0.4;occ_hi=0.95;"
                         "occ_lo=0.25;kv_free_min=16;step=2;"
                         "cooldown=5;dwell=12;backoff=7;interval=0.5")
        assert p.min_replicas == 2 and p.max_replicas == 8
        assert p.queue_hi == 0.8 and p.queue_lo == 0.1
        assert p.burn_hi == 1.5 and p.burn_lo == 0.4
        assert p.occupancy_hi == 0.95 and p.occupancy_lo == 0.25
        assert p.kv_free_min == 16 and p.step == 2
        assert (p.cooldown, p.dwell, p.backoff, p.interval) == \
            (5.0, 12.0, 7.0, 0.5)

    def test_unknown_key_typed(self):
        with pytest.raises(InvalidArgumentError, match="replicas=9"):
            parse_policy("replicas=9")

    def test_bad_value_typed(self):
        with pytest.raises(InvalidArgumentError, match="min=two"):
            parse_policy("min=two")

    def test_min_above_max_typed(self):
        with pytest.raises(InvalidArgumentError, match="min"):
            ScalingPolicy(min_replicas=5, max_replicas=2)

    def test_degenerate_band_typed(self):
        # equal bounds would flap on noise — refused, not accepted
        with pytest.raises(InvalidArgumentError, match="queue"):
            ScalingPolicy(queue_hi=0.5, queue_lo=0.5)

    def test_nonpositive_interval_typed(self):
        with pytest.raises(InvalidArgumentError, match="interval"):
            ScalingPolicy(interval=0.0)


class TestControlDiscipline:
    """decide()/step() against pinned clocks: the anti-flap toolkit."""

    def _policy(self, **kw):
        kw.setdefault("cooldown", 5.0)
        kw.setdefault("dwell", 10.0)
        kw.setdefault("backoff", 30.0)
        return ScalingPolicy(min_replicas=1, max_replicas=4, **kw)

    def test_queue_pressure_scales_out(self):
        fleet = _FakeFleet(live=2, queue_ratio=0.9)
        d = Autoscaler(fleet, self._policy()).step(now=100.0)
        assert d.action == SCALE_OUT and d.target == 3
        assert "queue_ewma" in d.reason
        assert fleet.calls == [(2, 3, d.reason)]

    def test_between_bands_holds(self):
        # 0.5 is above queue_lo (0.2) and below queue_hi (0.75):
        # the hysteresis gap neither scales out nor starts the dwell
        fleet = _FakeFleet(live=2, queue_ratio=0.5)
        d = Autoscaler(fleet, self._policy()).step(now=100.0)
        assert d.action == HOLD and not fleet.calls
        assert "hysteresis" in d.reason

    def test_cooldown_blocks_consecutive_transitions(self):
        fleet = _FakeFleet(live=1, queue_ratio=0.9)
        scaler = Autoscaler(fleet, self._policy())
        assert scaler.step(now=100.0).action == SCALE_OUT
        d = scaler.step(now=102.0)      # 2s < cooldown 5s, still hot
        assert d.action == HOLD and "cooldown" in d.reason
        assert scaler.step(now=106.0).action == SCALE_OUT
        assert [c[:2] for c in fleet.calls] == [(1, 2), (2, 3)]

    def test_at_max_holds_under_pressure(self):
        fleet = _FakeFleet(live=4, queue_ratio=0.9)
        d = Autoscaler(fleet, self._policy()).step(now=100.0)
        assert d.action == HOLD and "max_replicas" in d.reason
        assert not fleet.calls

    def test_scale_in_requires_continuous_dwell(self):
        fleet = _FakeFleet(live=3, queue_ratio=0.0)
        scaler = Autoscaler(fleet, self._policy())
        assert "dwell" in scaler.step(now=100.0).reason   # dwell arms
        assert scaler.step(now=105.0).action == HOLD      # 5s < 10s
        d = scaler.step(now=111.0)                        # 11s > 10s
        assert d.action == SCALE_IN and d.target == 2
        assert "calm" in d.reason
        assert fleet.calls == [(3, 2, d.reason)]

    def test_pressure_resets_the_dwell_clock(self):
        fleet = _FakeFleet(live=3, queue_ratio=0.0)
        scaler = Autoscaler(fleet, self._policy())
        scaler.step(now=100.0)                            # dwell arms
        fleet.admission.ewma = 90.0                       # spike
        scaler.step(now=104.0)                            # re-pressurized
        fleet.admission.ewma = 0.0
        d = scaler.step(now=111.0)   # 11s after first calm, but the
        assert d.action == HOLD      # spike reset the clock: re-arm
        assert scaler.step(now=122.0).action == SCALE_IN

    def test_never_below_min_replicas(self):
        fleet = _FakeFleet(live=1, queue_ratio=0.0)
        scaler = Autoscaler(fleet, self._policy())
        for now in (100.0, 111.0, 122.0):
            assert scaler.step(now=now).action == HOLD
        assert not fleet.calls

    def test_refused_transition_backs_off_typed(self):
        fleet = _FakeFleet(live=2, queue_ratio=0.9, fail=True)
        scaler = Autoscaler(fleet, self._policy())
        d = scaler.step(now=100.0)
        assert d.action == HOLD and "refused" in d.reason
        assert "wedged transition" in scaler.last_refusal
        counters = fleet.metrics.snapshot()["counters"]
        assert counters["autoscale_refusals_total"] == 1
        assert "autoscale_scale_out_total" not in counters
        # parked: inside the backoff window the loop never re-actuates
        d = scaler.step(now=110.0)
        assert d.action == HOLD and "backoff" in d.reason
        # backoff expires -> re-evaluate; target healed -> transition
        fleet.fail = False
        assert scaler.step(now=131.0).action == SCALE_OUT

    def test_burn_rate_triggers_scale_out(self):
        fleet = _FakeFleet(live=2, queue_ratio=0.0)
        h = fleet.metrics.histogram("e2e_ms")
        for _ in range(50):
            h.observe(80.0)          # p99 80ms against a 10ms target
        slos = obs_slo.parse_slos("lat=p99(e2e_ms)<10")
        scaler = Autoscaler(fleet, self._policy(), slos=slos)
        d = scaler.step(now=100.0)
        assert d.action == SCALE_OUT and "slo_burn" in d.reason
        assert d.signals.burn_max == pytest.approx(8.0)
        assert fleet.metrics.snapshot()["gauges"][
            "autoscale_burn_max_ratio"] == pytest.approx(8.0)

    def test_decision_journal_bounded(self):
        fleet = _FakeFleet(live=2, queue_ratio=0.5)
        scaler = Autoscaler(fleet, self._policy())
        for i in range(300):
            scaler.step(now=100.0 + i)
        assert len(scaler.decisions()) == 256
        assert all(isinstance(d, Decision) for d in scaler.decisions())

    def test_decision_metrics_published(self):
        fleet = _FakeFleet(live=2, queue_ratio=0.9)
        scaler = Autoscaler(fleet, self._policy())
        scaler.step(now=100.0)
        snap = fleet.metrics.snapshot()
        assert snap["counters"]["autoscale_decisions_total"] == 1
        assert snap["counters"]["autoscale_scale_out_total"] == 1
        assert snap["gauges"]["autoscale_target_replicas"] == 3
        assert snap["gauges"]["autoscale_queue_ratio"] == \
            pytest.approx(0.9)
        assert snap["histograms"]["autoscale_decision_seconds"][
            "count"] == 1

    def test_structurally_zero_without_autoscaler(self):
        # a fleet that never constructs an Autoscaler never pays for
        # the families — peek (never materialize) proves absence
        m = MetricsRegistry()
        m.counter("requests_total").inc()
        assert all(m.peek(n) is None for n in AUTOSCALE_FAMILIES)

    def test_events_journal_records_decisions(self, tmp_path,
                                              monkeypatch):
        journal = str(tmp_path / "events.jsonl")
        monkeypatch.setenv(obs_events.EVENTS_ENV, journal)
        fleet = _FakeFleet(live=2, queue_ratio=0.9)
        scaler = Autoscaler(fleet, self._policy())
        scaler.step(now=100.0)
        fleet.fail = True
        fleet.admission.ewma = 90.0
        scaler.step(now=106.0)
        evs = obs_events.read_events(journal)
        dec = [e for e in evs if e["event"] == "autoscale_decision"]
        ref = [e for e in evs if e["event"] == "autoscale_refused"]
        assert len(dec) == 1 and dec[0]["action"] == SCALE_OUT
        assert dec[0]["replicas_from"] == 2
        assert dec[0]["replicas_to"] == 3
        assert len(ref) == 1 and ref[0]["error"] == "ScaleFailed"
        assert ref[0]["backoff_s"] == 30.0

    def test_background_loop_start_stop(self):
        fleet = _FakeFleet(live=2, queue_ratio=0.5)
        with Autoscaler(fleet, self._policy(interval=0.01)) as scaler:
            deadline = time.monotonic() + 5.0
            while not scaler.decisions() and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
        assert scaler.decisions()
        assert not fleet.calls     # 0.5 sits in the hysteresis gap


class _BlockingFleet(_FakeFleet):
    """A fake fleet whose scale_to parks on an event — the shape of a
    real multi-second replica spawn."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.entered = threading.Event()
        self.release = threading.Event()

    def scale_to(self, n, ready_timeout_s=None, reason="requested"):
        self.entered.set()
        if not self.release.wait(10.0):
            raise ScaleFailed("test actuation never released")
        return super().scale_to(n, ready_timeout_s=ready_timeout_s,
                                reason=reason)


def _poll(pred, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


class TestAsyncActuation:
    """The background loop's non-blocking transitions: sensing
    continues through a slow spawn, single-flight is enforced, and
    the dwell earned during a scale-out spawn is not forfeited."""

    def _policy(self, **kw):
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("cooldown", 5.0)
        kw.setdefault("dwell", 10.0)
        kw.setdefault("backoff", 30.0)
        kw.setdefault("interval", 0.01)
        return ScalingPolicy(**kw)

    def test_loop_keeps_sensing_through_blocked_transition(self):
        fleet = _BlockingFleet(live=2, queue_ratio=0.9)
        scaler = Autoscaler(fleet, self._policy()).start()
        try:
            assert fleet.entered.wait(5.0)
            # the spawn is parked — ticks keep coming, each resolving
            # a typed "transition in flight" hold, none re-actuating
            assert _poll(lambda: sum(
                "transition in flight" in d.reason
                for d in scaler.decisions()) >= 3)
            assert not fleet.calls
            fleet.release.set()
            assert _poll(lambda: fleet.calls)
            assert fleet.calls[0][:2] == (2, 3)
            # single-flight + completion-stamped cooldown: pressure
            # persisted the whole time, yet exactly one transition ran
            assert _poll(lambda: fleet.metrics.snapshot()["counters"]
                         .get("autoscale_scale_out_total") == 1)
            assert len(fleet.calls) == 1
        finally:
            scaler.stop()

    def test_async_refusal_parks_loop_in_backoff(self):
        fleet = _FakeFleet(live=2, queue_ratio=0.9, fail=True)
        scaler = Autoscaler(fleet, self._policy()).start()
        try:
            assert _poll(lambda: fleet.metrics.snapshot()["counters"]
                         .get("autoscale_refusals_total", 0) >= 1)
            # the refusal resolution is journaled, then the loop parks
            assert _poll(lambda: any(
                "refused" in d.reason for d in scaler.decisions()))
            assert _poll(lambda: any(
                "backoff" in d.reason for d in scaler.decisions()))
            assert "wedged transition" in scaler.last_refusal
            assert not fleet.calls
        finally:
            scaler.stop()
        # parked exactly once: no re-actuation storm inside backoff
        assert fleet.metrics.snapshot()["counters"][
            "autoscale_refusals_total"] == 1

    def test_dwell_earned_during_scale_out_spawn_survives(self):
        """Calm observed while a scale-out spawns is valid evidence —
        capacity only increased — so the scale-in fires one cooldown
        after completion instead of re-earning the dwell from zero."""
        fleet = _BlockingFleet(live=2, queue_ratio=0.9)
        scaler = Autoscaler(fleet, self._policy(
            dwell=0.3, cooldown=0.05)).start()
        try:
            assert fleet.entered.wait(5.0)
            fleet.admission.ewma = 0.0       # flash passed mid-spawn
            time.sleep(0.5)                  # > dwell, all in flight
            assert _poll(lambda: any(
                "dwell" in d.reason for d in scaler.decisions()))
            assert not fleet.calls           # still single-flight
            fleet.release.set()
            # scale-out lands (2 -> 3), then the pre-earned dwell lets
            # the scale-in follow after only the cooldown
            assert _poll(lambda: (3, 2) in
                         [c[:2] for c in fleet.calls])
            counters = fleet.metrics.snapshot()["counters"]
            assert counters["autoscale_scale_out_total"] == 1
            assert counters["autoscale_scale_in_total"] >= 1
        finally:
            scaler.stop()

    def test_stop_joins_inflight_actuation(self):
        fleet = _BlockingFleet(live=2, queue_ratio=0.9)
        scaler = Autoscaler(fleet, self._policy()).start()
        assert fleet.entered.wait(5.0)
        fleet.release.set()
        scaler.stop()                        # joins loop AND actuator
        assert fleet.calls == [(2, 3, fleet.calls[0][2])]

    def test_sync_step_catches_untyped_wedge(self):
        """Satellite hardening: ANY exception out of scale_to — not
        just ScaleFailed — parks the loop typed instead of killing
        it."""
        class _Wedged(_FakeFleet):
            def scale_to(self, n, ready_timeout_s=None,
                         reason="requested"):
                raise RuntimeError("transport wedged mid-resize")
        fleet = _Wedged(live=2, queue_ratio=0.9)
        scaler = Autoscaler(fleet, self._policy())
        d = scaler.step(now=100.0)
        assert d.action == HOLD and "refused" in d.reason
        assert "transport wedged" in scaler.last_refusal
        assert fleet.metrics.snapshot()["counters"][
            "autoscale_refusals_total"] == 1
        assert scaler.step(now=101.0).reason.startswith("backoff")


class TestSupervisorTarget:
    def test_refusal_is_scalefailed(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import Supervisor
        sup = Supervisor(policy="resize", world_size=4, min_world=2,
                         heartbeat_dir=str(tmp_path / "hb"))
        target = SupervisorTarget(sup)
        assert target.live_replicas() == 4
        with pytest.raises(ScaleFailed, match="below_floor"):
            target.scale_to(1)

    def test_accepted_resize_queues(self, tmp_path):
        from paddle1_tpu.distributed.supervisor import Supervisor
        sup = Supervisor(policy="resize", world_size=4, min_world=2,
                         heartbeat_dir=str(tmp_path / "hb"))
        rep = SupervisorTarget(sup).scale_to(3, reason="autoscale")
        assert rep == {"from": 4, "to": 3, "queued": True}
        assert sup._resize_request == (3, "autoscale")

    def test_autoscaler_backs_off_on_refused_resize(self, tmp_path):
        """Satellite 3 edge: a decision landing while the resize
        budget is exhausted is refused TYPED and the loop parks
        instead of re-requesting every tick."""
        from paddle1_tpu.distributed.supervisor import Supervisor
        sup = Supervisor(policy="resize", world_size=2, min_world=1,
                         max_resizes=0,
                         heartbeat_dir=str(tmp_path / "hb"))
        target = SupervisorTarget(sup)
        reg = MetricsRegistry()
        reg.gauge("slot_occupancy").set(0.99)   # pressure signal
        scaler = Autoscaler(target, ScalingPolicy(
            min_replicas=1, max_replicas=4, backoff=60.0),
            registry=reg)
        d = scaler.step(now=100.0)
        assert d.action == HOLD and "budget_exhausted" in d.reason
        assert reg.snapshot()["counters"][
            "autoscale_refusals_total"] == 1
        assert sup._resize_request is None
        assert scaler.step(now=130.0).reason.startswith("backoff")


# -- slow: chaos-composed scaling edges on real replicas ---------------------

def _fleet(tmp_path, n=2, **kw):
    factory = tmp_path / "factory.py"
    factory.write_text(FACTORY)
    kw.setdefault("max_batch", 8)
    kw.setdefault("buckets", (1, 8))
    kw.setdefault("batch_timeout_ms", 2)
    kw.setdefault("input_specs", [((8,), "float32")])
    kw.setdefault("warmup", True)
    kw.setdefault("hang_timeout", 30.0)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("version", "v1")
    kw.setdefault("model_arg", "v1")
    kw.setdefault("retry_max", 3)
    kw.setdefault("replica_timeout_ms", 60000)
    kw.setdefault("inflight_per_replica", 4)
    env = kw.pop("env", {})
    env.setdefault("JAX_PLATFORMS", "cpu")
    return ServingFleet(f"{factory}:make_model", replicas=n, env=env,
                        work_dir=str(tmp_path / "fleet"), **kw)


@pytest.mark.slow
class TestChaosScalingEdges:
    def test_scale_in_races_inflight_deploy_canary(self, tmp_path):
        """Satellite 3 edge 1: a scale-in issued while a deploy canary
        is in flight serializes behind the deploy mutex — it retires
        ranks the finished roll owns, never ranks mid-swap, and the
        drain identity holds across both transitions."""
        fleet = _fleet(tmp_path, n=3)
        fleet.start()
        try:
            done = {}

            def roll():
                done["deploy"] = fleet.deploy(
                    fleet.model_spec, "v2", model_arg="v2",
                    canary=[np.zeros((1, 8), np.float32)])
            t = threading.Thread(target=roll)
            t.start()
            time.sleep(0.2)          # let the canary take the mutex
            rep = fleet.scale_to(2, reason="autoscale scale-in")
            t.join(timeout=300)
            assert done["deploy"]["rolled"] == 3
            assert rep["from"] == 3 and rep["to"] == 2
            assert fleet.live_replicas() == 2
            # the survivors serve v2: the scale-in retired rolled
            # replicas, not the mid-swap window
            fut = fleet.submit(np.zeros((1, 8), np.float32))
            fut.result(timeout=300)
            assert fut.version == "v2"
        finally:
            report = fleet.drain()
        assert report["unaccounted"] == 0, report
        assert report["errors"] == 0

    def test_flash_crowd_lands_mid_scale_out(self, tmp_path):
        """Satellite 3 edge 2: a burst arriving while scale_to is
        still spawning keeps resolving on the existing capacity (or
        sheds TYPED) — nothing is lost in the transition window."""
        fleet = _fleet(tmp_path, n=1, fleet_queue_depth=32)
        fleet.start()
        try:
            rng = np.random.default_rng(3)
            xs = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(16)]
            outcome = {"ok": 0, "shed": 0, "failures": []}
            stop = threading.Event()

            def crowd():
                i = 0
                while not stop.is_set():
                    i += 1
                    try:
                        fleet.submit(xs[i % 16]).result(timeout=300)
                        outcome["ok"] += 1
                    except ServerOverloaded:
                        outcome["shed"] += 1   # typed back-pressure
                    except Exception as e:  # noqa: broad-except — any
                        # OTHER failure during the resize window fails
                        # the zero-loss gate below
                        outcome["failures"].append(repr(e))
            threads = [threading.Thread(target=crowd)
                       for _ in range(8)]
            for t in threads:
                t.start()
            try:
                rep = fleet.scale_to(3, reason="flash crowd")
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=300)
            assert rep["to"] == 3 and len(rep["added"]) == 2
            assert fleet.ready_replicas() == 3
            assert not outcome["failures"], outcome["failures"][:3]
            assert outcome["ok"] >= 1
        finally:
            report = fleet.drain()
        assert report["unaccounted"] == 0, report

    def test_decision_during_restart_budget_exhaustion(self, tmp_path):
        """Satellite 3 edge 3: a replica dies with its restart budget
        spent (stays FAILED), the autoscaler's next decision still
        actuates — scale-out spawns a FRESH rank (new budget), live
        capacity recovers, and the whole episode drains accounted."""
        fleet = _fleet(tmp_path, n=2, max_restarts=0,
                       fleet_queue_depth=32,
                       chaos_spec="replica_kill@3:1")
        fleet.start()
        try:
            rng = np.random.default_rng(4)
            xs = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(20)]       # burst < queue cap 32
            futs = [fleet.submit(x) for x in xs]
            for f in futs:
                f.result(timeout=300)       # kill fires; failover eats it
            deadline = time.monotonic() + 60.0
            while fleet.live_replicas() > 1 and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert fleet.live_replicas() == 1   # budget spent, not back
            scaler = Autoscaler(
                fleet, ScalingPolicy(min_replicas=1, max_replicas=3,
                                     queue_hi=0.5, queue_lo=0.1))
            for _ in range(10):                  # pressure: EWMA ramps
                fleet.admission.observe(32)      # to ~0.89 of depth
            d = scaler.step(now=100.0)
            assert d.action == SCALE_OUT and d.target == 2
            assert fleet.ready_replicas() == 2
            fut = fleet.submit(xs[0])
            fut.result(timeout=300)
        finally:
            report = fleet.drain()
        assert report["unaccounted"] == 0, report
        assert report["errors"] == 0
        assert report["replica_restarts"] == 0   # budget was zero
