"""Fluid tier 8 (VERDICT r4 item 4 remainder): ctc_greedy_decoder,
similarity_focus, filter_by_instag, reorder_lod_tensor_by_rank,
load/read_file, inplace_abn, detection_output, box_decoder_and_assign,
collect_fpn_proposals, locality_aware_nms."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.fluid.layers as L
from paddle1_tpu.core.tensor import to_tensor


def _np(t):
    return np.asarray(t.numpy())


class TestCtcGreedyDecoder:
    def test_merge_repeats_drop_blanks(self):
        # logits whose argmax path is [b, 1, 1, b, 2, 2] -> [1, 2]
        path = [[0, 1, 1, 0, 2, 2], [3, 3, 0, 0, 0, 0]]
        C = 4
        x = np.full((2, 6, C), -5.0, np.float32)
        for b, row in enumerate(path):
            for t, tok in enumerate(row):
                x[b, t, tok] = 5.0
        dec, lens = L.ctc_greedy_decoder(to_tensor(x), blank=0)
        d, ln = _np(dec), _np(lens)
        assert ln.tolist() == [[2], [1]]
        assert d[0, :2].tolist() == [1, 2]
        assert d[1, :1].tolist() == [3]
        assert (d[1, 1:] == 0).all()  # padding_value default 0

    def test_input_length_truncates(self):
        x = np.full((1, 4, 3), -5.0, np.float32)
        for t, tok in enumerate([1, 2, 1, 2]):
            x[0, t, tok] = 5.0
        dec, lens = L.ctc_greedy_decoder(
            to_tensor(x), blank=0,
            input_length=np.array([2], np.int64))
        assert _np(lens).tolist() == [[2]]
        assert _np(dec)[0].tolist()[:2] == [1, 2]


class TestSimilarityFocus:
    def test_reference_docstring_example(self):
        x = np.array(
            [[[[0.8, 0.1], [0.4, 0.5]],
              [[0.9, 0.7], [0.9, 0.9]],
              [[0.8, 0.9], [0.1, 0.2]]],
             [[[0.2, 0.5], [0.3, 0.4]],
              [[0.9, 0.7], [0.8, 0.4]],
              [[0.0, 0.2], [0.4, 0.7]]]], np.float32)
        out = _np(L.similarity_focus(to_tensor(x), axis=1,
                                     indexes=[0]))
        ref0 = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
        ref1 = np.array([[0.0, 1.0], [1.0, 0.0]], np.float32)
        for c in range(3):
            np.testing.assert_array_equal(out[0, c], ref0)
            np.testing.assert_array_equal(out[1, c], ref1)


class TestFilterByInstag:
    def test_reference_example(self):
        ins = np.arange(8, dtype=np.float32).reshape(4, 2)
        tags = [[0, 1], [1, 3], [0, 3], [2, 6]]
        out, w = L.filter_by_instag(to_tensor(ins), tags,
                                    to_tensor(np.array([1], np.int64)))
        np.testing.assert_array_equal(_np(out), ins[[0, 1]])
        np.testing.assert_array_equal(_np(w), np.ones((2, 1)))

    def test_empty_result_contract(self):
        ins = np.ones((2, 3), np.float32)
        out, w = L.filter_by_instag(
            to_tensor(ins), [[5], [6]],
            to_tensor(np.array([9], np.int64)), out_val_if_empty=7)
        assert (_np(out) == 7).all() and _np(out).shape == (1, 3)
        assert _np(w).tolist() == [[0.0]]

    def test_padded_array_tags(self):
        ins = np.eye(3, dtype=np.float32)
        tags = np.array([[1, -1], [2, 3], [4, -1]], np.int64)
        out, w = L.filter_by_instag(to_tensor(ins), tags,
                                    np.array([3, 4], np.int64))
        np.testing.assert_array_equal(_np(out), ins[[1, 2]])


class TestReorderByRank:
    def test_descending_length_order(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        lens = np.array([2, 5, 3, 5], np.int64)
        out = _np(L.reorder_lod_tensor_by_rank(to_tensor(x), lens))
        np.testing.assert_array_equal(out, x[[1, 3, 2, 0]])  # stable


class TestLoadReadFile:
    def test_load_roundtrip(self, tmp_path):
        val = to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        p = str(tmp_path / "var.pd")
        paddle.save(val, p)
        out = to_tensor(np.zeros((2, 3), np.float32))
        L.load(out, p)
        np.testing.assert_array_equal(_np(out),
                                      np.arange(6).reshape(2, 3))

    def test_read_file(self, tmp_path):
        p = tmp_path / "blob.bin"
        p.write_bytes(bytes([1, 2, 250]))
        out = _np(L.read_file(str(p)))
        assert out.dtype == np.uint8
        assert out.tolist() == [1, 2, 250]


class TestInplaceAbn:
    def test_equals_bn_plus_activation(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 3, 4, 4)).astype(np.float32)
        a = L.inplace_abn(to_tensor(x), act="leaky_relu",
                          act_alpha=0.2, name="abn1")
        b = L.batch_norm(to_tensor(x), name="abn2")
        import paddle1_tpu.nn.functional as F
        ref = F.leaky_relu(b, negative_slope=0.2)
        np.testing.assert_allclose(_np(a), _np(ref), rtol=2e-5,
                                   atol=2e-6)

    def test_unsupported_act_teaches(self):
        with pytest.raises(Exception, match="leaky_relu"):
            L.inplace_abn(to_tensor(np.zeros((1, 2, 2, 2),
                                             np.float32)), act="relu")

    def test_is_test_uses_moving_stats(self):
        rng = np.random.default_rng(5)
        x1 = rng.standard_normal((4, 2, 3, 3)).astype(np.float32)
        # one training pass updates the moving stats
        L.inplace_abn(to_tensor(x1), name="abn_t")
        x2 = rng.standard_normal((4, 2, 3, 3)).astype(np.float32) + 3.0
        a = _np(L.inplace_abn(to_tensor(x2), is_test=True,
                              name="abn_t"))
        b = _np(L.inplace_abn(to_tensor(x2), is_test=False,
                              name="abn_t"))
        # eval normalizes with moving stats (mean≈0), not the shifted
        # batch stats — outputs must differ
        assert np.abs(a - b).max() > 0.1


class TestDetectionOutput:
    def test_decode_and_nms(self):
        # two priors, one clear detection per class
        pb = np.array([[0.1, 0.1, 0.3, 0.3], [0.6, 0.6, 0.9, 0.9]],
                      np.float32)
        pv = np.full((2, 4), 0.1, np.float32)
        loc = np.zeros((1, 2, 4), np.float32)  # decode to the priors
        scores = np.array([[[0.05, 0.9, 0.05], [0.05, 0.05, 0.9]]],
                          np.float32)
        outs = L.detection_output(to_tensor(loc), to_tensor(scores),
                                  to_tensor(pb), to_tensor(pv),
                                  background_label=0,
                                  score_threshold=0.5)
        assert isinstance(outs, list) and len(outs) == 1
        o = _np(outs[0])
        assert o.shape[0] == 2
        labels = set(o[:, 0].astype(int).tolist())
        assert labels == {1, 2}
        # decoded boxes equal the priors (zero deltas)
        row1 = o[o[:, 0] == 1][0]
        np.testing.assert_allclose(row1[2:], pb[0], atol=1e-5)


class TestBoxDecoderAndAssign:
    def test_assign_picks_argmax_class(self):
        pb = np.array([[0, 0, 9, 9]], np.float32)
        pv = np.ones((1, 4), np.float32)
        # class 0 deltas zero; class 1 shifts right by 1 width
        tb = np.array([[0, 0, 0, 0, 1.0, 0, 0, 0]], np.float32)
        sc = np.array([[0.2, 0.8]], np.float32)
        dec, assigned = L.box_decoder_and_assign(
            to_tensor(pb), to_tensor(pv), to_tensor(tb),
            to_tensor(sc), box_clip=4.135)
        d = _np(dec)
        np.testing.assert_allclose(d[0, :4], [0, 0, 9, 9], atol=1e-4)
        a = _np(assigned)
        np.testing.assert_allclose(a[0], d[0, 4:], atol=1e-5)


class TestCollectFpn:
    def test_topk_across_levels(self):
        r1 = np.array([[0, 0, 1, 1], [1, 1, 2, 2]], np.float32)
        r2 = np.array([[2, 2, 3, 3]], np.float32)
        s1 = np.array([[0.9], [0.1]], np.float32)
        s2 = np.array([[0.5]], np.float32)
        out = _np(L.collect_fpn_proposals([to_tensor(r1),
                                           to_tensor(r2)],
                                          [to_tensor(s1),
                                           to_tensor(s2)], 2, 3, 2))
        np.testing.assert_array_equal(out, np.stack([r1[0], r2[0]]))

    def test_batched_per_image_topk(self):
        # two images: level rows partitioned by per-level lengths —
        # the top-k must NOT mix images
        r1 = np.array([[0, 0, 1, 1], [9, 9, 10, 10]], np.float32)
        s1 = np.array([[0.9], [0.8]], np.float32)
        lens1 = np.array([1, 1], np.int64)
        r2 = np.array([[2, 2, 3, 3], [8, 8, 9, 9]], np.float32)
        s2 = np.array([[0.5], [0.95]], np.float32)
        lens2 = np.array([1, 1], np.int64)
        rois, out_lens = L.collect_fpn_proposals(
            [to_tensor(r1), to_tensor(r2)],
            [to_tensor(s1), to_tensor(s2)], 2, 3, 1,
            rois_lengths=[lens1, lens2])
        rv = _np(rois)
        assert _np(out_lens).tolist() == [1, 1]
        np.testing.assert_array_equal(rv[0], r1[0])  # img0 best: 0.9
        np.testing.assert_array_equal(rv[1], r2[1])  # img1 best: 0.95


class TestLocalityAwareNms:
    def test_adjacent_boxes_merge_weighted(self):
        b = np.array([[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                      [50, 50, 60, 60]], np.float32)
        s = np.array([[0.8, 0.4, 0.9]], np.float32)
        out = _np(L.locality_aware_nms(to_tensor(b), to_tensor(s),
                                       score_threshold=0.1,
                                       nms_top_k=10, keep_top_k=10,
                                       nms_threshold=0.3))
        assert out.shape[0] == 2  # first two merged, third separate
        merged = out[np.argmax(out[:, 1])]
        # weighted average of the two overlapping boxes
        exp = (b[0] * 0.8 + b[1] * 0.4) / 1.2
        got_box = out[(out[:, 2] < 20)][0][2:]
        np.testing.assert_allclose(got_box, exp, atol=1e-4)


class TestMultivariateNormalDiag:
    def test_entropy_and_kl_closed_form(self):
        import math
        d1 = np.array([2.0, 3.0], np.float64)
        d2 = np.array([1.0, 1.5], np.float64)
        a = L.MultivariateNormalDiag(
            np.array([0.1, 0.2], np.float32),
            np.diag(d1).astype(np.float32))
        b = L.MultivariateNormalDiag(
            np.array([0.3, -0.1], np.float32),
            np.diag(d2).astype(np.float32))
        ent = float(_np(a.entropy()))
        ref_ent = 0.5 * (2 * (1 + math.log(2 * math.pi))
                         + math.log(d1.prod()))
        assert abs(ent - ref_ent) < 1e-5
        kl = float(_np(a.kl_divergence(b)))
        mu = np.array([0.3, -0.1]) - np.array([0.1, 0.2])
        ref_kl = 0.5 * ((d1 / d2).sum() + (mu ** 2 / d2).sum() - 2
                        + math.log(d2.prod() / d1.prod()))
        assert abs(kl - ref_kl) < 1e-5
