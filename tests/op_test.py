"""OpTest-style harness.

Analog of the reference's op correctness harness
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:255):
``check_output`` compares an eager op against a numpy reference;
``check_grad`` compares tape-engine analytic gradients against central
finite differences (op_test.py:110 get_numeric_gradient).
"""

from __future__ import annotations

import unittest
from typing import Callable, Dict, Sequence

import numpy as np

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import Tensor


class OpTest(unittest.TestCase):
    rtol = 1e-5
    atol = 1e-6

    def check_output(self, op_fn: Callable, np_fn: Callable,
                     inputs: Sequence[np.ndarray], rtol=None, atol=None,
                     **attrs):
        tensors = [paddle.to_tensor(x) for x in inputs]
        got = op_fn(*tensors, **attrs)
        want = np_fn(*inputs, **attrs)
        got_list = got if isinstance(got, (tuple, list)) else [got]
        want_list = want if isinstance(want, (tuple, list)) else [want]
        for g, w in zip(got_list, want_list):
            np.testing.assert_allclose(
                np.asarray(g.numpy(), np.float64),
                np.asarray(w, np.float64),
                rtol=rtol or self.rtol, atol=atol or self.atol)
        return got

    def check_grad(self, op_fn: Callable, inputs: Sequence[np.ndarray],
                   grad_input_idx: Sequence[int] = (0,), delta=1e-3,
                   rtol=5e-3, atol=1e-4, reduce_fn=None, **attrs):
        """Compare tape gradients vs central finite differences."""
        inputs = [np.asarray(x, np.float64).astype(np.float32)
                  for x in inputs]

        def scalar_out(*arrs):
            ts = [paddle.to_tensor(a) for a in arrs]
            out = op_fn(*ts, **attrs)
            if isinstance(out, (tuple, list)):
                out = out[0]
            if reduce_fn is not None:
                return reduce_fn(out)
            return out.sum() if out.size > 1 else out

        # analytic via tape
        tensors = [paddle.to_tensor(a, stop_gradient=(i not in
                                                      grad_input_idx))
                   for i, a in enumerate(inputs)]
        out = op_fn(*tensors, **attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        loss = (reduce_fn(out) if reduce_fn is not None else
                (out.sum() if out.size > 1 else out))
        loss.backward()

        for idx in grad_input_idx:
            analytic = tensors[idx].grad.numpy().astype(np.float64)
            numeric = self._numeric_grad(scalar_out, inputs, idx, delta)
            np.testing.assert_allclose(analytic, numeric, rtol=rtol,
                                       atol=atol,
                                       err_msg=f"grad mismatch input {idx}")

    @staticmethod
    def _numeric_grad(scalar_fn, inputs, idx, delta):
        base = [np.array(a, np.float32) for a in inputs]
        flat = base[idx].reshape(-1)
        grad = np.zeros_like(flat, np.float64)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + delta
            lo_hi = []
            f_hi = float(scalar_fn(*base).item())
            flat[i] = orig - delta
            f_lo = float(scalar_fn(*base).item())
            flat[i] = orig
            grad[i] = (f_hi - f_lo) / (2 * delta)
        return grad.reshape(base[idx].shape)
