"""Detection + sequence op correctness (reference detection op tests /
sequence_ops tests; numpy references inline)."""

import unittest

import numpy as np

import paddle1_tpu as paddle
from paddle1_tpu.vision import ops as V


def _iou_np(a, b):
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    aa = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    ab = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / np.maximum(aa[:, None] + ab[None, :] - inter, 1e-10)


class TestDetectionOps(unittest.TestCase):
    def test_iou(self):
        a = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], np.float32)
        b = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
        got = V.iou(a, b).numpy()
        np.testing.assert_allclose(got, _iou_np(a, b), rtol=1e-5)

    def test_nms_suppresses_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = V.nms(boxes, 0.5, scores).numpy()
        self.assertEqual(sorted(keep.tolist()), [0, 2])

    def test_nms_category_aware(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1], np.int64)
        keep = V.nms(boxes, 0.5, scores, category_idxs=cats,
                     categories=[0, 1]).numpy()
        self.assertEqual(sorted(keep.tolist()), [0, 1])  # different classes

    def test_multiclass_nms(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [40, 40, 60, 60]],
                         np.float32)
        scores = np.array([[0.9, 0.85, 0.1],    # class 0
                           [0.1, 0.2, 0.95]],   # class 1
                          np.float32)
        out = V.multiclass_nms(boxes, scores, score_threshold=0.3,
                               nms_threshold=0.5).numpy()
        labels = out[:, 0].astype(int).tolist()
        self.assertEqual(sorted(labels), [0, 1])

    def test_matrix_nms_decays_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                         np.float32)
        scores = np.array([[0.9, 0.85, 0.8]], np.float32)
        out = V.matrix_nms(boxes, scores, score_threshold=0.1).numpy()
        self.assertEqual(out.shape[1], 6)
        # overlapping second box decayed below its raw score
        row_b = out[np.argmin(np.abs(out[:, 2] - 1.0))]
        self.assertLess(row_b[1], 0.85)

    def test_yolo_box_shapes_and_range(self):
        B, na, C, H, W = 2, 3, 4, 5, 5
        x = np.random.randn(B, na * (5 + C), H, W).astype(np.float32)
        img = np.array([[320, 320], [416, 416]], np.int32)
        boxes, scores = V.yolo_box(x, img, [10, 13, 16, 30, 33, 23], C,
                                   0.01, 32)
        self.assertEqual(list(boxes.shape), [B, na * H * W, 4])
        self.assertEqual(list(scores.shape), [B, na * H * W, C])
        bn = boxes.numpy()
        self.assertTrue((bn[0, :, 2] <= 320).all())
        self.assertTrue((bn >= 0).all())

    def test_roi_align_identity_box(self):
        # a RoI covering exactly one constant region pools to its value
        feat = np.zeros((1, 1, 8, 8), np.float32)
        feat[0, 0, :4, :4] = 1.0
        rois = np.array([[0, 0, 4, 4]], np.float32)
        out = V.roi_align(feat, rois, np.array([1]), output_size=2,
                          spatial_scale=1.0)
        np.testing.assert_allclose(out.numpy()[0, 0], np.ones((2, 2)),
                                   atol=0.3)

    def test_prior_box(self):
        inp = np.zeros((1, 8, 4, 4), np.float32)
        img = np.zeros((1, 3, 64, 64), np.float32)
        boxes, var = V.prior_box(inp, img, min_sizes=[16],
                                 aspect_ratios=[1.0, 2.0])
        self.assertEqual(boxes.shape[:2], [4, 4])
        self.assertEqual(boxes.shape[3], 4)
        self.assertEqual(var.shape, boxes.shape)

    def test_distribute_fpn(self):
        rois = np.array([[0, 0, 16, 16], [0, 0, 200, 200]], np.float32)
        outs, restore = V.distribute_fpn_proposals(rois, 2, 5, 4, 224)
        sizes = [o.shape[0] for o in outs]
        self.assertEqual(sum(sizes), 2)
        self.assertEqual(sorted(restore.numpy().tolist()), [0, 1])


class TestSequenceOps(unittest.TestCase):
    def test_mask_pad_unpad_roundtrip(self):
        from paddle1_tpu.ops import sequence_ops as S
        lengths = np.array([3, 1, 2], np.int64)
        flat = np.arange(12, dtype=np.float32).reshape(6, 2)
        padded, lens = S.sequence_pad(flat, 0.0, lengths)
        self.assertEqual(list(padded.shape), [3, 3, 2])
        self.assertEqual(float(padded.numpy()[1, 1, 0]), 0.0)
        back = S.sequence_unpad(padded, lens)
        np.testing.assert_array_equal(back.numpy(), flat)
        mask = S.sequence_mask(lengths).numpy()
        np.testing.assert_array_equal(mask,
                                      [[1, 1, 1], [1, 0, 0], [1, 1, 0]])

    def test_pool_variants(self):
        from paddle1_tpu.ops import sequence_ops as S
        x = np.array([[[1.], [2.], [3.]],
                      [[4.], [5.], [6.]]], np.float32)
        lens = np.array([2, 3], np.int64)
        self.assertEqual(S.sequence_pool(x, lens, "sum").numpy().tolist(),
                         [[3.0], [15.0]])
        self.assertEqual(S.sequence_pool(x, lens, "mean").numpy().tolist(),
                         [[1.5], [5.0]])
        self.assertEqual(S.sequence_pool(x, lens, "max").numpy().tolist(),
                         [[2.0], [6.0]])
        self.assertEqual(S.sequence_last_step(x, lens).numpy().tolist(),
                         [[2.0], [6.0]])

    def test_softmax_masked(self):
        from paddle1_tpu.ops import sequence_ops as S
        x = np.zeros((1, 3, 1), np.float32)
        lens = np.array([2], np.int64)
        out = S.sequence_softmax(x, lens).numpy()
        np.testing.assert_allclose(out[0, :, 0], [0.5, 0.5, 0.0], atol=1e-6)

    def test_reverse(self):
        from paddle1_tpu.ops import sequence_ops as S
        x = np.array([[[1.], [2.], [3.]]], np.float32)
        lens = np.array([2], np.int64)
        out = S.sequence_reverse(x, lens).numpy()
        np.testing.assert_array_equal(out[0, :, 0], [2.0, 1.0, 3.0])

    def test_grad_through_pool(self):
        from paddle1_tpu.ops import sequence_ops as S
        x = paddle.to_tensor(np.ones((2, 3, 1), np.float32),
                             stop_gradient=False)
        lens = np.array([2, 3], np.int64)
        out = S.sequence_pool(x, lens, "sum")
        out.sum().backward()
        np.testing.assert_array_equal(
            x.grad.numpy()[:, :, 0], [[1, 1, 0], [1, 1, 1]])


class TestSequenceOpsLongTail(unittest.TestCase):
    """r4 breadth: the remaining reference sequence_ops/ family on the
    dense+lengths representation (sequence_conv/enumerate/erase/
    reshape/scatter/slice/topk_avg_pooling)."""

    def test_sequence_conv_window_math(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        x = np.arange(12, dtype=np.float32).reshape(1, 4, 3)
        lens = np.array([3], np.int64)       # position 3 is padding
        # identity-ish filter: context L=1 => plain projection
        w = np.eye(3, dtype=np.float32)
        out = S.sequence_conv(to_tensor(x), to_tensor(lens),
                              to_tensor(w), context_length=1,
                              context_start=0)
        o = np.asarray(out.numpy())
        np.testing.assert_allclose(o[0, :3], x[0, :3])
        np.testing.assert_allclose(o[0, 3], 0.0)     # masked tail
        # centered L=3 window at t=0 must NOT see t=-1
        w3 = np.zeros((9, 1), np.float32)
        w3[0] = 1.0  # picks feature 0 of the t-1 context slot
        o3 = np.asarray(S.sequence_conv(to_tensor(x), to_tensor(lens),
                                        to_tensor(w3),
                                        context_length=3).numpy())
        self.assertEqual(float(o3[0, 0, 0]), 0.0)
        self.assertEqual(float(o3[0, 1, 0]), float(x[0, 0, 0]))

    def test_sequence_enumerate_windows(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        ids = np.array([[1, 2, 3, 9]], np.int64)
        lens = np.array([3], np.int64)
        out = np.asarray(S.sequence_enumerate(
            to_tensor(ids), to_tensor(lens), win_size=2,
            pad_value=0).numpy())
        np.testing.assert_array_equal(out[0, :3],
                                      [[1, 2], [2, 3], [3, 0]])
        np.testing.assert_array_equal(out[0, 3], [0, 0])

    def test_sequence_erase_compacts(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        ids = np.array([[5, 1, 5, 2], [7, 7, 3, 0]], np.int64)
        lens = np.array([4, 3], np.int64)
        out, nl = S.sequence_erase(to_tensor(ids), to_tensor(lens), [5, 7])
        np.testing.assert_array_equal(np.asarray(out.numpy()),
                                      [[1, 2, 0, 0], [3, 0, 0, 0]])
        self.assertEqual(np.asarray(nl.numpy()).tolist(), [2, 1])

    def test_sequence_reshape_rechunks(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        x = np.arange(8, dtype=np.float32).reshape(1, 2, 4)
        lens = np.array([2], np.int64)
        out, nl = S.sequence_reshape(to_tensor(x), to_tensor(lens), 2)
        self.assertEqual(list(out.shape), [1, 4, 2])
        self.assertEqual(np.asarray(nl.numpy()).tolist(), [4])
        np.testing.assert_allclose(np.asarray(out.numpy()).reshape(-1),
                                   np.arange(8))

    def test_sequence_scatter_masked_add(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        x = np.zeros((2, 5), np.float32)
        idx = np.array([[0, 2], [4, 4]], np.int64)
        upd = np.array([[1.0, 2.0], [3.0, 9.0]], np.float32)
        lens = np.array([2, 1], np.int64)   # row 1's second update masked
        out = np.asarray(S.sequence_scatter(
            to_tensor(x), to_tensor(idx), to_tensor(upd),
            to_tensor(lens)).numpy())
        np.testing.assert_allclose(out[0], [1, 0, 2, 0, 0])
        np.testing.assert_allclose(out[1], [0, 0, 0, 0, 3])

    def test_sequence_slice_per_row(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        x = np.arange(10, dtype=np.float32).reshape(2, 5)
        out, nl = S.sequence_slice(to_tensor(x), [1, 0], [2, 3])
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   [[1, 2, 0], [5, 6, 7]])
        self.assertEqual(np.asarray(nl.numpy()).tolist(), [2, 3])

    def test_sequence_topk_avg_pooling(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        x = np.array([[[1.0], [5.0], [3.0], [99.0]]], np.float32)
        lens = np.array([3], np.int64)       # 99 is padding
        out = np.asarray(S.sequence_topk_avg_pooling(
            to_tensor(x), to_tensor(lens), topks=[1, 2]).numpy())
        np.testing.assert_allclose(out, [[5.0, 4.0]])

    def test_sequence_expand_as_alias(self):
        from paddle1_tpu.ops import sequence_ops as S
        from paddle1_tpu.core.tensor import to_tensor
        x = np.array([[1.0], [2.0]], np.float32)
        out = S.sequence_expand_as(to_tensor(x), [2, 1])
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   [[1], [1], [2]])
