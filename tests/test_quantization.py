"""QAT/PTQ (reference slim quantization tests)."""

import unittest

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.quantization import QAT, PTQ, fake_quant


class TestQuant(unittest.TestCase):
    def test_fake_quant_levels(self):
        x = np.linspace(-1, 1, 11).astype(np.float32)
        out = fake_quant(paddle.to_tensor(x), 1.0, bits=3).numpy()
        # 3 bits → qmax=3 → values on k/3 grid
        np.testing.assert_allclose(out * 3, np.round(out * 3), atol=1e-6)

    def test_fake_quant_ste_grad(self):
        x = paddle.to_tensor(np.array([0.3, 2.0], np.float32),
                             stop_gradient=False)
        out = fake_quant(x, 1.0, bits=8)
        out.sum().backward()
        # inside range → grad 1; clipped (|x|>scale) → grad 0
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0], atol=1e-6)

    @pytest.mark.slow  # ~21s train soak; layer-swap + fake-quant math
    # stay covered in-tier by the ptq/fake_quant cases (CI heavy step)
    def test_qat_swaps_and_trains(self):
        from paddle1_tpu.vision.models import LeNet
        m = LeNet()
        QAT().quantize(m)
        names = [type(l).__name__ for l in m.sublayers()]
        self.assertIn("QuantizedConv2D", names)
        self.assertIn("QuantizedLinear", names)
        m.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        x = paddle.to_tensor(
            np.random.randn(4, 1, 28, 28).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
        losses = []
        for _ in range(5):
            loss = paddle.nn.functional.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        self.assertLess(losses[-1], losses[0])

    def test_ptq_calibrates(self):
        from paddle1_tpu.vision.models import LeNet
        from paddle1_tpu.quantization import FakeQuantMovingAverageAbsMax
        m = LeNet()
        data = [(paddle.to_tensor(
            np.random.randn(2, 1, 28, 28).astype(np.float32)),)
            for _ in range(3)]
        PTQ().quantize(m, data, num_batches=3)
        obs = [l for l in m.sublayers()
               if isinstance(l, FakeQuantMovingAverageAbsMax)]
        self.assertTrue(obs)
        self.assertTrue(all(int(o.inited.numpy()) == 1 for o in obs))
        self.assertFalse(m.training)
