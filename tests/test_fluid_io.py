"""fluid.io persistence + feeding surface (r5): the reference exe-first
save/load family (reference python/paddle/fluid/io.py:239-1050) working
against the live named-variable registry, plus DataLoader.from_generator
and the classic batch() decorator."""

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu import fluid


class TestSaveLoad:
    def _net(self, seed):
        paddle.seed(seed)
        return paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                    paddle.nn.BatchNorm1D(8),
                                    paddle.nn.Linear(8, 2))

    def test_persistables_roundtrip(self, tmp_path):
        m = self._net(0)
        # dirty the BN running stats so they are part of the state
        x = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((16, 4))
            .astype(np.float32))
        m.train()
        m(x)
        fluid.io.save_persistables(None, str(tmp_path))

        want = {k: np.asarray(v.numpy())
                for k, v in m.state_dict().items()}
        # scramble params AND buffers, then load back (buffers must be
        # genuinely restored, not just untouched)
        for t in m.state_dict().values():
            t._data = t.data * 0 - 7.0
        fluid.io.load_persistables(None, str(tmp_path))
        got = {k: np.asarray(v.numpy()) for k, v in m.state_dict().items()}
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-6,
                                       err_msg=k)

    def test_params_excludes_buffers(self, tmp_path):
        m = self._net(1)
        fluid.io.save_params(None, str(tmp_path), filename="p.npz")
        import os
        # the payload is np.savez — readable with allow_pickle=False,
        # i.e. the non-executable format (ADVICE r5)
        with np.load(os.path.join(tmp_path, "p.npz"),
                     allow_pickle=False) as z:
            payload = set(z.files)
        assert any(k.endswith("weight") for k in payload)
        assert not any("_mean" in k or "_variance" in k for k in payload)

    def test_save_vars_by_name_and_value_accessors(self, tmp_path):
        m = self._net(2)
        name = m[0].weight.name
        fluid.io.save_vars(None, str(tmp_path), vars=[name],
                           filename="w.pkl")
        v1 = fluid.io.get_parameter_value_by_name(name)
        np.testing.assert_allclose(
            v1, fluid.io.get_parameter_value(m[0].weight))
        from paddle1_tpu.core.errors import NotFoundError
        with pytest.raises(NotFoundError):
            fluid.io.get_parameter_value_by_name("nope_0.w")
        with pytest.raises(NotFoundError, match="exist"):
            fluid.io.load_persistables(None, str(tmp_path))  # wrong file

    def test_shape_mismatch_and_missing_are_loud(self, tmp_path):
        import os
        m = self._net(3)
        fluid.io.save_persistables(None, str(tmp_path), filename="c")
        # corrupt one entry's shape in the checkpoint
        path = os.path.join(tmp_path, "c")
        with np.load(path, allow_pickle=False) as z:
            payload = {k: z[k] for k in z.files}
        wname = m[0].weight.name
        payload[wname] = np.zeros((9, 9), np.float32)
        with open(path, "wb") as f:
            np.savez(f, **payload)
        from paddle1_tpu.core.errors import (InvalidArgumentError,
                                             NotFoundError)
        with pytest.raises(InvalidArgumentError, match="shape"):
            fluid.io.load_persistables(None, str(tmp_path),
                                       filename="c")
        # and names absent from the file are loud for load_vars
        with pytest.raises(NotFoundError, match="not in the saved"):
            fluid.io.save_vars(None, str(tmp_path),
                               vars=[m[2].weight.name], filename="one")
            fluid.io.load_vars(None, str(tmp_path),
                               vars=[m[2].weight.name, wname],
                               filename="one")
        # a checkpoint sharing no names with the model teaches
        with pytest.raises(NotFoundError, match="no parameter names"):
            with open(path, "wb") as f:
                np.savez(f, ghost=np.zeros(2, np.float32))
            fluid.io.load_params(None, str(tmp_path), filename="c")

    def test_legacy_pickle_needs_opt_in(self, tmp_path):
        """ADVICE r5: pickle executes arbitrary code from untrusted
        checkpoints, so legacy pickle payloads load only behind the
        explicit io_load_pickle flag; the current format is np.savez."""
        import os
        import pickle
        from paddle1_tpu.core.errors import InvalidArgumentError
        from paddle1_tpu.core.flags import flags_guard
        m = self._net(4)
        # registry-named payload, exactly what the old pickle writer
        # produced
        want = {v.name: np.asarray(v.numpy())
                for v in m.state_dict().values()
                if getattr(v, "name", None)}
        with open(os.path.join(tmp_path, "legacy"), "wb") as f:
            pickle.dump(want, f)  # a pre-PR-4 checkpoint
        with pytest.raises(InvalidArgumentError, match="io_load_pickle"):
            fluid.io.load_persistables(None, str(tmp_path),
                                       filename="legacy")
        for t in m.state_dict().values():
            t._data = t.data * 0 - 2.0
        with flags_guard(io_load_pickle=True):
            fluid.io.load_persistables(None, str(tmp_path),
                                       filename="legacy")
        got = {v.name: np.asarray(v.numpy())
               for v in m.state_dict().values()
               if getattr(v, "name", None)}
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-6)

    def test_corrupt_payload_fails_typed(self, tmp_path):
        """A truncated/corrupt checkpoint (save killed mid-stream)
        raises zipfile.BadZipFile from np.load — both read paths must
        convert that to their typed contract: load_* teaches about the
        format, and the clobber guard refuses to overwrite what it
        can't prove is a subset."""
        import os
        from paddle1_tpu.core.errors import InvalidArgumentError
        m = self._net(8)
        path = os.path.join(str(tmp_path), "__params__")
        with open(path, "wb") as f:
            f.write(b"PK\x03\x04 truncated garbage")
        with pytest.raises(InvalidArgumentError, match="io_load_pickle"):
            fluid.io.load_params(None, str(tmp_path), main_program=m)
        with pytest.raises(InvalidArgumentError, match="clobber"):
            fluid.io.save_params(None, str(tmp_path), main_program=m)

    def test_variable_named_file_roundtrips(self, tmp_path):
        """np.savez's **kwargs API chokes on a member literally named
        "file" (its first positional parameter) — the writer streams
        the zip members itself, so any registry name saves."""
        paddle.seed(9)
        m = paddle.nn.Linear(3, 2,
                             weight_attr=paddle.ParamAttr(name="file"))
        assert m.weight.name == "file"
        want = np.asarray(m.weight.numpy()).copy()
        fluid.io.save_params(None, str(tmp_path), main_program=m)
        m.weight._data = m.weight.data * 0
        fluid.io.load_params(None, str(tmp_path), main_program=m)
        np.testing.assert_array_equal(np.asarray(m.weight.numpy()), want)

    def test_bfloat16_roundtrips_through_npz(self, tmp_path):
        """Extension dtypes (bfloat16 etc.) have no native npz encoding
        — np.savez writes them silently but np.load hands back raw void
        bytes. The writer must sidecar-encode them so a bf16 checkpoint
        from a TPU run is loadable, bit-exact, with the live dtype
        preserved."""
        import jax.numpy as jnp
        m = self._net(6)
        w = m[0].weight
        b = m[0].bias
        w._data = w.data.astype(jnp.bfloat16)
        want_w = np.asarray(w.numpy()).copy()
        want_b = np.asarray(b.numpy()).copy()  # f32 neighbors unharmed
        fluid.io.save_persistables(None, str(tmp_path))
        w._data = (w.data * 0 - 3).astype(jnp.bfloat16)
        b._data = b.data * 0 - 3.0
        fluid.io.load_persistables(None, str(tmp_path))
        assert w.dtype == np.asarray(want_w).dtype  # still bfloat16
        np.testing.assert_array_equal(np.asarray(w.numpy()), want_w)
        np.testing.assert_array_equal(np.asarray(b.numpy()), want_b)
        # the payload is still the non-executable format
        with np.load(str(tmp_path / "__persistables__"),
                     allow_pickle=False) as z:
            assert any(k.startswith("__ext_dtype__::") for k in z.files)

    def test_saved_payload_is_not_executable(self, tmp_path):
        """The r5 threat model, asserted: the written file parses as a
        zip of .npy members under allow_pickle=False (np.load of such a
        payload cannot execute code)."""
        import os
        import zipfile
        m = self._net(5)
        fluid.io.save_params(None, str(tmp_path), main_program=m)
        path = os.path.join(tmp_path, "__params__")
        assert zipfile.is_zipfile(path)
        with np.load(path, allow_pickle=False) as z:
            assert len(z.files) == len(
                [p for p in m.parameters()])


class TestReaders:
    def test_batch_plus_pyreader_idiom(self):
        rng = np.random.default_rng(0)
        samples = [(rng.standard_normal(4).astype(np.float32),
                    np.int64(i % 3)) for i in range(10)]

        loader = fluid.io.DataLoader.from_generator(capacity=4)
        loader.decorate_sample_list_generator(
            fluid.io.batch(lambda: iter(samples), batch_size=4))
        shapes = [tuple(b[0].shape) for b in loader]
        assert shapes == [(4, 4), (4, 4), (2, 4)]  # drop_last=False

    def test_batch_drop_last(self):
        gen = fluid.io.batch(lambda: iter(range(10)), 4, drop_last=True)
        assert [len(b) for b in gen()] == [4, 4]

    def test_pyreader_alias(self):
        assert fluid.io.PyReader is fluid.layers.py_reader(
            capacity=1).__class__


class TestDistinctDefaultFilenames:
    """ADVICE r5: save_params + save_persistables into one dirname must
    coexist (distinct default filenames), and an overwrite that would
    DROP variables from an existing file errors instead of clobbering."""

    def _net(self, seed):
        paddle.seed(seed)
        return paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                    paddle.nn.BatchNorm1D(8),
                                    paddle.nn.Linear(8, 2))

    def test_params_and_persistables_coexist(self, tmp_path):
        import os
        m = self._net(10)
        x = paddle.to_tensor(np.random.default_rng(0)
                             .standard_normal((16, 4)).astype(np.float32))
        m.train()
        m(x)
        fluid.io.save_params(None, str(tmp_path), main_program=m)
        fluid.io.save_persistables(None, str(tmp_path), main_program=m)
        names = set(os.listdir(tmp_path))
        assert {"__params__", "__persistables__"} <= names
        # both load from their own defaults
        want = {k: np.asarray(v.numpy()) for k, v in m.state_dict().items()}
        for t in m.state_dict().values():
            t._data = t.data * 0 - 3.0
        fluid.io.load_persistables(None, str(tmp_path))
        got = {k: np.asarray(v.numpy()) for k, v in m.state_dict().items()}
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-6,
                                       err_msg=k)
        fluid.io.load_params(None, str(tmp_path))  # resolves __params__

    def test_dropping_overwrite_errors(self, tmp_path):
        from paddle1_tpu.core.errors import InvalidArgumentError
        m = self._net(11)
        fluid.io.save_params(None, str(tmp_path), main_program=m)
        m2 = self._net(12)
        # same default file, DIFFERENT model → would drop m's params
        with pytest.raises(InvalidArgumentError, match="refusing"):
            fluid.io.save_params(None, str(tmp_path), main_program=m2)
        # resaving the SAME var set (checkpoint-as-you-train) stays fine
        fluid.io.save_params(None, str(tmp_path), main_program=m)
        # a non-checkpoint file at the target path is never clobbered
        import os
        victim = os.path.join(tmp_path, "notes.txt")
        with open(victim, "w") as f:
            f.write("not a checkpoint")
        with pytest.raises(InvalidArgumentError, match="refusing"):
            fluid.io.save_params(None, str(tmp_path), main_program=m,
                                 filename="notes.txt")
        assert open(victim).read() == "not a checkpoint"

    def test_legacy_shared_file_still_loads(self, tmp_path):
        # pre-fix checkpoints wrote everything to __persistables__;
        # load_params/load_vars fall back to it
        m = self._net(13)
        fluid.io.save_persistables(None, str(tmp_path), main_program=m)
        for t in m.state_dict().values():
            t._data = t.data * 0 - 5.0
        fluid.io.load_params(None, str(tmp_path),
                             main_program=m)  # falls back to _FILE
        w = np.asarray(m[0].weight.numpy())
        assert not np.allclose(w, -5.0)

    def test_cross_helper_load_falls_back(self, tmp_path):
        # previously-working pairs: save_params → load_vars and
        # save_vars → load_params resolve across default filenames
        m = self._net(14)
        fluid.io.save_params(None, str(tmp_path), main_program=m)
        name = m[0].weight.name
        want = np.asarray(m[0].weight.numpy()).copy()
        m[0].weight._data = m[0].weight.data * 0 - 9.0
        fluid.io.load_vars(None, str(tmp_path), vars=[name])
        np.testing.assert_allclose(np.asarray(m[0].weight.numpy()), want,
                                   rtol=1e-6)
