"""Linear-chain CRF (nn/functional/crf.py) vs brute-force enumeration
over all tag paths (reference operators/linear_chain_crf_op.cc,
crf_decoding_op.cc)."""

import itertools

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.nn.functional as F
from paddle1_tpu.core.tensor import to_tensor


def _score(e, w, path):
    """Path score under the op's transition layout."""
    start, end, pair = w[0], w[1], w[2:]
    s = start[path[0]] + e[0, path[0]]
    for t in range(1, len(path)):
        s += pair[path[t - 1], path[t]] + e[t, path[t]]
    return s + end[path[-1]]


def _brute(e, w):
    """(logZ, best_path) by enumeration. e: [T, N]."""
    T, N = e.shape
    scores = {p: _score(e, w, p)
              for p in itertools.product(range(N), repeat=T)}
    arr = np.array(list(scores.values()))
    m = arr.max()
    logz = m + np.log(np.exp(arr - m).sum())
    best = max(scores, key=scores.get)
    return logz, list(best)


@pytest.fixture()
def problem():
    rng = np.random.default_rng(0)
    B, T, N = 3, 5, 4
    e = rng.standard_normal((B, T, N)).astype(np.float32)
    w = rng.standard_normal((N + 2, N)).astype(np.float32)
    return e, w


class TestLinearChainCRF:
    def test_log_likelihood_matches_enumeration(self, problem):
        e, w = problem
        B, T, N = e.shape
        rng = np.random.default_rng(1)
        y = rng.integers(0, N, (B, T))
        ll = F.linear_chain_crf(to_tensor(e), to_tensor(w),
                                to_tensor(y)).numpy()
        for b in range(B):
            logz, _ = _brute(e[b], w)
            want = _score(e[b], w, list(y[b])) - logz
            np.testing.assert_allclose(ll[b, 0], want, rtol=1e-4)

    def test_variable_lengths(self, problem):
        e, w = problem
        B, T, N = e.shape
        y = np.random.default_rng(2).integers(0, N, (B, T))
        lengths = np.array([5, 3, 1])
        ll = F.linear_chain_crf(to_tensor(e), to_tensor(w), to_tensor(y),
                                length=lengths).numpy()
        for b, L in enumerate(lengths):
            logz, _ = _brute(e[b, :L], w)
            want = _score(e[b, :L], w, list(y[b, :L])) - logz
            np.testing.assert_allclose(ll[b, 0], want, rtol=1e-4)

    def test_likelihood_is_normalized(self, problem):
        e, w = problem
        B, T, N = e.shape
        paths = np.asarray(list(itertools.product(range(N), repeat=T)))
        e_rep = np.tile(e[0][None], (paths.shape[0], 1, 1))
        ll = F.linear_chain_crf(to_tensor(e_rep), to_tensor(w),
                                to_tensor(paths)).numpy()
        np.testing.assert_allclose(np.exp(ll[:, 0]).sum(), 1.0,
                                   rtol=1e-3)

    @pytest.mark.slow  # ~45s convergence soak; the decode/likelihood
    # cases above keep the CRF math covered in-tier (CI heavy step)
    def test_trains_toward_labels(self, problem):
        e, w = problem
        B, T, N = e.shape
        y = np.random.default_rng(3).integers(0, N, (B, T))
        wt = to_tensor(w.copy(), stop_gradient=False)
        first = None
        for step in range(40):
            nll = -F.linear_chain_crf(to_tensor(e), wt,
                                      to_tensor(y)).mean()
            nll.backward()
            wt = to_tensor(wt.numpy() - 0.2 * wt.grad.numpy(),
                           stop_gradient=False)
            first = first if first is not None else float(nll.numpy())
        assert float(nll.numpy()) < first


class TestCRFDecoding:
    def test_viterbi_matches_enumeration(self, problem):
        e, w = problem
        B = e.shape[0]
        path = F.crf_decoding(to_tensor(e), to_tensor(w)).numpy()
        for b in range(B):
            _, best = _brute(e[b], w)
            np.testing.assert_array_equal(path[b], best)

    def test_variable_length_padding_zeroed(self, problem):
        e, w = problem
        lengths = np.array([5, 3, 1])
        path = F.crf_decoding(to_tensor(e), to_tensor(w),
                              length=lengths).numpy()
        for b, L in enumerate(lengths):
            _, best = _brute(e[b, :L], w)
            np.testing.assert_array_equal(path[b, :L], best)
            assert (path[b, L:] == 0).all()

    def test_marks_output(self, problem):
        e, w = problem
        path = F.crf_decoding(to_tensor(e), to_tensor(w)).numpy()
        marks = F.crf_decoding(to_tensor(e), to_tensor(w),
                               label=to_tensor(path)).numpy()
        assert (marks == 1).all()  # decoded vs itself: all correct


class TestFluidSpelling:
    def test_fluid_layers_crf(self):
        import paddle1_tpu.fluid as fluid
        rng = np.random.default_rng(0)
        x = fluid.dygraph.to_variable(
            rng.standard_normal((2, 4, 3)).astype(np.float32))
        y = fluid.dygraph.to_variable(rng.integers(0, 3, (2, 4)))
        ll = fluid.layers.linear_chain_crf(x, y)
        assert ll.shape == [2, 1]
        path = fluid.layers.crf_decoding(x)
        assert path.shape == [2, 4]
        # shared transition parameter between the two entries
        from paddle1_tpu.fluid.layers import _crf_param
        assert ("tags", 3) in _crf_param._params
