"""Detection training ops (VERDICT r4 missing #4): rpn_target_assign,
generate_proposals, ssd_loss, multi_box_head, deformable_conv.

Numerics pinned against numpy references built from the C++ kernels
(rpn_target_assign_op.cc, generate_proposals_op.cc bbox_util.h,
mine_hard_examples_op.cc) and invariance checks for deformable_conv
(zero offsets == plain conv; integer offsets == shifted sampling)."""

import numpy as np
import pytest

import paddle1_tpu as paddle
import paddle1_tpu.fluid as fluid
import paddle1_tpu.fluid.layers as L
from paddle1_tpu.core.tensor import to_tensor


def _np(t):
    return np.asarray(t.numpy())


class TestRpnTargetAssign:
    def _data(self, seed=0):
        rng = np.random.default_rng(seed)
        # a tiny grid of anchors
        ys, xs = np.meshgrid(np.arange(0, 32, 8), np.arange(0, 32, 8),
                             indexing="ij")
        a = np.stack([xs.ravel(), ys.ravel(), xs.ravel() + 7,
                      ys.ravel() + 7], axis=1).astype(np.float32)
        M = a.shape[0]
        N = 2
        bbox_pred = rng.standard_normal((N, M, 4)).astype(np.float32)
        cls_logits = rng.standard_normal((N, M, 1)).astype(np.float32)
        gt = np.zeros((N, 2, 4), np.float32)
        gt[0, 0] = [0, 0, 7, 7]       # exactly anchor 0
        gt[0, 1] = [8, 8, 15, 15]
        gt[1, 0] = [16, 0, 23, 7]
        gt_lens = np.array([2, 1], np.int64)
        crowd = np.zeros((N, 2), np.int64)
        im_info = np.tile(np.array([32.0, 32.0, 1.0], np.float32),
                          (N, 1))
        return a, bbox_pred, cls_logits, gt, gt_lens, crowd, im_info

    def test_perfect_anchor_is_fg_with_zero_delta(self):
        (a, bp, cl, gt, lens, crowd,
         info) = self._data()
        scores, locs, lbl, tbox, inw = L.rpn_target_assign(
            to_tensor(bp), to_tensor(cl), to_tensor(a), None,
            to_tensor(gt), to_tensor(crowd), to_tensor(info),
            gt_lengths=lens, rpn_batch_size_per_im=16,
            use_random=False)
        lbl_np, tb = _np(lbl).ravel(), _np(tbox)
        # fg targets exist and the exact-match anchors encode to 0
        n_fg = int((lbl_np == 1).sum())
        assert n_fg >= 3
        assert tb.shape[0] >= n_fg
        exact = np.abs(tb).sum(axis=1)
        assert (exact < 1e-5).sum() >= 3   # the 3 perfect anchors
        # shapes line up between scores and labels, locs and weights
        assert _np(scores).shape[0] == lbl_np.shape[0]
        assert _np(locs).shape == tb.shape == _np(inw).shape

    def test_batch_cap_and_label_balance(self):
        (a, bp, cl, gt, lens, crowd, info) = self._data(1)
        scores, locs, lbl, tbox, inw = L.rpn_target_assign(
            to_tensor(bp), to_tensor(cl), to_tensor(a), None,
            to_tensor(gt), to_tensor(crowd), to_tensor(info),
            gt_lengths=lens, rpn_batch_size_per_im=8,
            rpn_fg_fraction=0.5, use_random=False)
        lbl_np = _np(lbl).ravel()
        # per image at most batch_size samples
        assert lbl_np.shape[0] <= 2 * 8
        assert set(np.unique(lbl_np)) <= {0, 1}

    def test_gathered_predictions_carry_grad(self):
        (a, bp, cl, gt, lens, crowd, info) = self._data(2)
        bpt, clt = to_tensor(bp), to_tensor(cl)
        bpt.stop_gradient = False
        clt.stop_gradient = False
        scores, locs, lbl, tbox, inw = L.rpn_target_assign(
            bpt, clt, to_tensor(a), None, to_tensor(gt),
            to_tensor(crowd), to_tensor(info), gt_lengths=lens,
            use_random=False)
        loss = (locs * inw - tbox * inw).abs().sum() \
            + (scores ** 2).sum()
        loss.backward()
        assert np.abs(_np(bpt.grad)).sum() > 0
        assert np.abs(_np(clt.grad)).sum() > 0

    def test_zero_gt_image_is_all_background(self):
        (a, bp, cl, gt, lens, crowd, info) = self._data(4)
        lens0 = np.array([2, 0], np.int64)  # image 1 has no gt
        scores, locs, lbl, tbox, inw = L.rpn_target_assign(
            to_tensor(bp), to_tensor(cl), to_tensor(a), None,
            to_tensor(gt), to_tensor(crowd), to_tensor(info),
            gt_lengths=lens0, rpn_batch_size_per_im=8,
            use_random=False)
        lbl_np = _np(lbl).ravel()
        assert lbl_np.shape[0] > 0
        # the negative image contributed only background labels and
        # no regression targets beyond image 0's
        assert set(np.unique(lbl_np)) <= {0, 1}

    def test_crowd_gt_excluded(self):
        (a, bp, cl, gt, lens, crowd, info) = self._data(3)
        crowd2 = crowd.copy()
        crowd2[0, 0] = 1  # first gt of image 0 is crowd
        _, _, lbl_a, _, _ = L.rpn_target_assign(
            to_tensor(bp), to_tensor(cl), to_tensor(a), None,
            to_tensor(gt), to_tensor(crowd), to_tensor(info),
            gt_lengths=lens, use_random=False)
        _, _, lbl_b, _, _ = L.rpn_target_assign(
            to_tensor(bp), to_tensor(cl), to_tensor(a), None,
            to_tensor(gt), to_tensor(crowd2), to_tensor(info),
            gt_lengths=lens, use_random=False)
        assert (_np(lbl_b) == 1).sum() < (_np(lbl_a) == 1).sum()


class TestGenerateProposals:
    def test_decode_clip_nms(self):
        rng = np.random.default_rng(4)
        N, A, H, W = 1, 3, 4, 4
        anchors = np.zeros((H, W, A, 4), np.float32)
        for y in range(H):
            for x in range(W):
                for k in range(A):
                    s = 4 * (k + 1)
                    anchors[y, x, k] = [x * 8, y * 8, x * 8 + s,
                                        y * 8 + s]
        variances = np.full((H, W, A, 4), 1.0, np.float32)
        scores = rng.random((N, A, H, W)).astype(np.float32)
        deltas = (rng.standard_normal((N, 4 * A, H, W)) * 0.1).astype(
            np.float32)
        info = np.array([[32, 32, 1.0]], np.float32)
        rois, probs, lens = L.generate_proposals(
            to_tensor(scores), to_tensor(deltas), to_tensor(info),
            to_tensor(anchors), to_tensor(variances),
            pre_nms_top_n=40, post_nms_top_n=10, nms_thresh=0.7,
            min_size=1.0)
        r, p, ln = _np(rois), _np(probs), _np(lens)
        assert ln[0] == r.shape[0] <= 10
        assert p.shape == (r.shape[0], 1)
        # clipped to the image
        assert r[:, 0].min() >= 0 and r[:, 2].max() <= 31
        assert r[:, 1].min() >= 0 and r[:, 3].max() <= 31
        # scores sorted descending (NMS keeps order)
        assert (np.diff(p.ravel()) <= 1e-6).all()
        # zero-delta anchor decodes to itself
        z = np.zeros_like(deltas)
        rois2, probs2, _ = L.generate_proposals(
            to_tensor(scores), to_tensor(z), to_tensor(info),
            to_tensor(anchors), to_tensor(variances),
            pre_nms_top_n=40, post_nms_top_n=48, nms_thresh=1.01,
            min_size=1.0)
        r2 = _np(rois2)
        best = scores[0].transpose(1, 2, 0).reshape(-1).argmax()
        np.testing.assert_allclose(
            r2[0], anchors.reshape(-1, 4)[best], atol=1e-5)


class TestGenerateProposalsEdge:
    def test_all_filtered_emits_zero_box(self):
        """keep-the-graph-alive contract: an image whose proposals are
        all filtered still contributes one [0,0,0,0] roi, score 0."""
        anchors = np.zeros((1, 1, 1, 4), np.float32)
        anchors[0, 0, 0] = [0, 0, 0.5, 0.5]   # sub-min_size anchor
        variances = np.ones((1, 1, 1, 4), np.float32)
        scores = np.ones((1, 1, 1, 1), np.float32)
        deltas = np.zeros((1, 4, 1, 1), np.float32)
        info = np.array([[16, 16, 1.0]], np.float32)
        rois, probs, lens = L.generate_proposals(
            to_tensor(scores), to_tensor(deltas), to_tensor(info),
            to_tensor(anchors), to_tensor(variances), min_size=8.0)
        assert _np(lens).tolist() == [1]
        np.testing.assert_array_equal(_np(rois), [[0, 0, 0, 0]])
        np.testing.assert_array_equal(_np(probs), [[0.0]])


class TestSSDLoss:
    def _toy(self, seed=5):
        rng = np.random.default_rng(seed)
        N, P, C, G = 2, 8, 4, 2
        pb = np.zeros((P, 4), np.float32)
        for i in range(P):
            cx = (i % 4) * 0.25 + 0.125
            cy = (i // 4) * 0.5 + 0.25
            pb[i] = [cx - 0.1, cy - 0.15, cx + 0.1, cy + 0.15]
        loc = (rng.standard_normal((N, P, 4)) * 0.1).astype(np.float32)
        conf = rng.standard_normal((N, P, C)).astype(np.float32)
        gt = np.zeros((N, G, 4), np.float32)
        gt[0, 0] = pb[1] + 0.01
        gt[0, 1] = pb[6] - 0.01
        gt[1, 0] = pb[3] + 0.02
        gl = np.array([[1, 2], [3, 0]], np.int64)
        lens = np.array([2, 1], np.int64)
        return pb, loc, conf, gt, gl, lens

    def test_loss_shape_positive_and_grad(self):
        pb, loc, conf, gt, gl, lens = self._toy()
        lt, ct = to_tensor(loc), to_tensor(conf)
        lt.stop_gradient = False
        ct.stop_gradient = False
        loss = L.ssd_loss(lt, ct, to_tensor(gt), to_tensor(gl),
                          to_tensor(pb), gt_lengths=lens)
        lv = _np(loss)
        assert lv.shape == (2, 1) and (lv > 0).all()
        loss.sum().backward()
        assert np.abs(_np(lt.grad)).sum() > 0
        assert np.abs(_np(ct.grad)).sum() > 0

    def test_training_decreases_loss(self):
        pb, loc, conf, gt, gl, lens = self._toy(6)
        lay = paddle.nn.Layer()
        lt = lay.create_parameter(list(loc.shape))
        ct = lay.create_parameter(list(conf.shape))
        lt.set_value(loc)
        ct.set_value(conf)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=[lt, ct])
        losses = []
        for _ in range(15):
            loss = L.ssd_loss(lt, ct, to_tensor(gt), to_tensor(gl),
                              to_tensor(pb), gt_lengths=lens).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_perfect_predictions_loss_small(self):
        """Predictions exactly matching the encoded targets and
        confident correct classes → near-zero loc loss part."""
        pb, loc, conf, gt, gl, lens = self._toy(7)
        zero_loc = np.zeros_like(loc)
        l1 = _np(L.ssd_loss(to_tensor(loc * 10), to_tensor(conf),
                            to_tensor(gt), to_tensor(gl),
                            to_tensor(pb), gt_lengths=lens))
        l2 = _np(L.ssd_loss(to_tensor(zero_loc), to_tensor(conf),
                            to_tensor(gt), to_tensor(gl),
                            to_tensor(pb), gt_lengths=lens))
        # targets are near-zero deltas (gt ≈ prior): zero predictions
        # give a smaller localization loss than large ones
        assert l2.sum() < l1.sum()


class TestMultiBoxHead:
    def test_shapes_and_consistency(self):
        rng = np.random.default_rng(8)
        img = to_tensor(rng.standard_normal((1, 3, 64, 64)).astype(
            np.float32))
        f1 = to_tensor(rng.standard_normal((1, 8, 8, 8)).astype(
            np.float32))
        f2 = to_tensor(rng.standard_normal((1, 16, 4, 4)).astype(
            np.float32))
        loc, conf, boxes, vars_ = L.multi_box_head(
            [f1, f2], img, base_size=64, num_classes=5,
            aspect_ratios=[[2.0], [2.0, 3.0]], min_ratio=20,
            max_ratio=90, offset=0.5, flip=True, name="mbh")
        M = _np(boxes).shape[0]
        assert _np(loc).shape == (1, M, 4)
        assert _np(conf).shape == (1, M, 5)
        assert _np(vars_).shape == (M, 4)
        bx = _np(boxes)
        assert (bx[:, 2] >= bx[:, 0]).all()

    def test_feeds_ssd_loss(self):
        rng = np.random.default_rng(9)
        img = to_tensor(rng.standard_normal((2, 3, 32, 32)).astype(
            np.float32))
        f1 = to_tensor(rng.standard_normal((2, 4, 4, 4)).astype(
            np.float32))
        loc, conf, boxes, vars_ = L.multi_box_head(
            [f1], img, base_size=32, num_classes=3,
            aspect_ratios=[[2.0]], min_sizes=[10.0], max_sizes=[20.0],
            name="mbh2")
        gt = np.array([[[0.1, 0.1, 0.4, 0.4]],
                       [[0.5, 0.5, 0.9, 0.9]]], np.float32)
        gl = np.array([[1], [2]], np.int64)
        loss = L.ssd_loss(loc, conf, to_tensor(gt), to_tensor(gl),
                          boxes, prior_box_var=vars_,
                          gt_lengths=np.array([1, 1], np.int64))
        assert (_np(loss) > 0).all()
        loss.sum().backward()  # grads reach the implicit conv heads


class TestDeformableConv:
    def _conv_ref(self, x, w, stride=1):
        """Plain valid conv via jax for the zero-offset check."""
        import jax
        return np.asarray(jax.lax.conv_general_dilated(
            x, w, (stride, stride), "VALID"))

    def test_zero_offset_equals_plain_conv(self):
        rng = np.random.default_rng(10)
        x = rng.standard_normal((2, 4, 8, 8)).astype(np.float32)
        off = np.zeros((2, 2 * 9, 6, 6), np.float32)
        mask = np.ones((2, 9, 6, 6), np.float32)
        out = L.deformable_conv(to_tensor(x), to_tensor(off),
                                to_tensor(mask), 5, 3, name="dcn1")
        w = _np(fluid.layers.implicit_parameters()[-2])
        assert w.shape == (5, 4, 3, 3)
        ref = self._conv_ref(x, w)
        b = _np(fluid.layers.implicit_parameters()[-1])
        np.testing.assert_allclose(_np(out),
                                   ref + b[None, :, None, None],
                                   rtol=2e-4, atol=2e-4)

    def test_integer_offset_shifts_sampling(self):
        rng = np.random.default_rng(11)
        x = rng.standard_normal((1, 2, 10, 10)).astype(np.float32)
        # every tap shifted by (+1, +1): equals plain conv on the
        # shifted input window (out is 8x8; the shifted ref covers 7x7)
        off = np.ones((1, 2 * 9, 8, 8), np.float32)
        mask = np.ones((1, 9, 8, 8), np.float32)
        out = L.deformable_conv(to_tensor(x), to_tensor(off),
                                to_tensor(mask), 3, 3,
                                bias_attr=False, name="dcn2")
        w = _np(fluid.layers.implicit_parameters()[-1])
        ref = self._conv_ref(x[:, :, 1:, 1:], w)
        np.testing.assert_allclose(_np(out)[:, :, :7, :7], ref,
                                   rtol=2e-4, atol=2e-4)

    def test_mask_modulates(self):
        rng = np.random.default_rng(12)
        x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
        off = np.zeros((1, 18, 4, 4), np.float32)
        half = np.full((1, 9, 4, 4), 0.5, np.float32)
        full = np.ones((1, 9, 4, 4), np.float32)
        o_half = L.deformable_conv(to_tensor(x), to_tensor(off),
                                   to_tensor(half), 3, 3,
                                   bias_attr=False, name="dcn3")
        o_full = L.deformable_conv(to_tensor(x), to_tensor(off),
                                   to_tensor(full), 3, 3,
                                   bias_attr=False, name="dcn3")
        np.testing.assert_allclose(_np(o_half) * 2, _np(o_full),
                                   rtol=2e-4, atol=2e-4)

    def test_gradients_flow_to_offsets(self):
        rng = np.random.default_rng(13)
        x = to_tensor(rng.standard_normal((1, 2, 6, 6)).astype(
            np.float32))
        off = to_tensor((rng.standard_normal((1, 18, 4, 4)) * 0.3)
                        .astype(np.float32))
        mask = to_tensor(np.ones((1, 9, 4, 4), np.float32))
        x.stop_gradient = False
        off.stop_gradient = False
        out = L.deformable_conv(x, off, mask, 3, 3, name="dcn4")
        out.sum().backward()
        assert np.abs(_np(x.grad)).sum() > 0
        assert np.abs(_np(off.grad)).sum() > 0
