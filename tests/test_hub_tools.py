"""hub (hubconf protocol), program introspection (StableHLO text), op
benchmark harness, and style tooling."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import to_tensor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def hub_repo(tmp_path):
    (tmp_path / "hubconf.py").write_text(textwrap.dedent("""
        dependencies = ["numpy"]

        def tiny_mlp(hidden=8):
            \"\"\"A tiny MLP entrypoint.\"\"\"
            import paddle1_tpu as paddle
            return paddle.nn.Linear(4, hidden)

        def _private():
            pass
    """))
    return str(tmp_path)


class TestHub:
    def test_list(self, hub_repo):
        assert paddle.hub.list(hub_repo, source="local") == ["tiny_mlp"]

    def test_help(self, hub_repo):
        assert "tiny MLP" in paddle.hub.help(hub_repo, "tiny_mlp")

    def test_load(self, hub_repo):
        m = paddle.hub.load(hub_repo, "tiny_mlp", hidden=16)
        assert m.weight.shape == [4, 16]

    def test_unknown_entrypoint(self, hub_repo):
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError):
            paddle.hub.load(hub_repo, "nope")

    def test_remote_source_teaches(self, hub_repo):
        from paddle1_tpu.core.errors import PreconditionNotMetError
        with pytest.raises(PreconditionNotMetError, match="local"):
            paddle.hub.load("org/repo", "m", source="github")

    def test_missing_dependency(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "dependencies = ['not_a_real_pkg_xyz']\n"
            "def m():\n    return 1\n")
        from paddle1_tpu.core.errors import PreconditionNotMetError
        with pytest.raises(PreconditionNotMetError,
                           match="not_a_real_pkg_xyz"):
            paddle.hub.load(str(tmp_path), "m")


class TestProgramIntrospection:
    def test_to_static_program_text(self):
        @paddle.jit.to_static
        def f(x):
            return (x * 2.0 + 1.0).sum()

        txt = f.program_text(to_tensor(np.ones((4,), np.float32)))
        assert "stablehlo" in txt or "mhlo" in txt or "func" in txt
        assert "multiply" in txt  # the traced op is visible

    def test_translated_layer_program(self, tmp_path):
        from paddle1_tpu.jit import InputSpec, load, save
        lin = paddle.nn.Linear(4, 2)
        lin.eval()
        base = str(tmp_path / "m")
        save(lin, base, input_spec=[InputSpec([3, 4], "float32",
                                              name="x")])
        tl = load(base)
        txt = tl.program()
        assert "dot" in txt or "dot_general" in txt  # the matmul is there


class TestTools:
    @pytest.mark.slow  # ~14s subprocess; CI runs the op microbench
    # smoke as its own step, so in-tier duplication buys nothing
    def test_op_benchmark_single(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "op_benchmark.py"),
             "--op", "add", "--shapes", "32x32,32x32", "--repeat", "2"],
            capture_output=True, text=True, timeout=300, cwd=REPO)
        assert r.returncode == 0, r.stderr
        rec = json.loads(r.stdout.strip().splitlines()[-1])
        assert rec["op"] == "add" and rec["jit_us_median"] > 0

    def test_check_style_passes(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_style.py")],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout
