"""Model encryption (framework.crypto + encrypted Predictor), the
MultiTrainer/HogwildWorker runtime over out-of-core data + embedding
service, and Go-binding/C-ABI consistency. Reference crypto/, trainer.h,
go/paddle."""

import os
import re

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core.tensor import to_tensor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestCrypto:
    def test_roundtrip_and_auth(self, tmp_path):
        from paddle1_tpu.framework.crypto import Cipher, CipherUtils
        key = CipherUtils.gen_key()
        c = Cipher(key)
        blob = os.urandom(1000)
        enc = c.encrypt(blob)
        assert enc != blob and enc.startswith(b"P1CRYPT1")
        assert c.decrypt(enc) == blob
        # wrong key fails loudly, not garbage
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError):
            Cipher(CipherUtils.gen_key()).decrypt(enc)
        # tamper detection (GCM auth)
        bad = enc[:-1] + bytes([enc[-1] ^ 1])
        with pytest.raises(InvalidArgumentError):
            c.decrypt(bad)

    def test_key_file_roundtrip(self, tmp_path):
        from paddle1_tpu.framework.crypto import CipherUtils
        p = str(tmp_path / "key")
        k = CipherUtils.gen_key_to_file(p)
        assert CipherUtils.read_key_from_file(p) == k
        assert os.stat(p).st_mode & 0o777 == 0o600

    def test_bad_key_length(self):
        from paddle1_tpu.framework.crypto import Cipher
        from paddle1_tpu.core.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError):
            Cipher(b"short")

    def test_encrypted_predictor_end_to_end(self, tmp_path):
        from paddle1_tpu.framework.crypto import Cipher, CipherUtils
        from paddle1_tpu.inference import Config, create_predictor
        from paddle1_tpu.jit import InputSpec, save
        from paddle1_tpu.vision.models.lenet import LeNet

        base = str(tmp_path / "lenet")
        model = LeNet()
        model.eval()
        save(model, base,
             input_spec=[InputSpec([2, 1, 28, 28], "float32",
                                   name="image")])
        x = np.random.default_rng(0).standard_normal(
            (2, 1, 28, 28)).astype(np.float32)
        ref = np.asarray(model(to_tensor(x)).numpy())

        key = CipherUtils.gen_key()
        c = Cipher(key)
        ebase = str(tmp_path / "enc")
        c.encrypt_file(base + ".pdmodel", ebase + ".pdmodel")
        c.encrypt_file(base + ".pdiparams", ebase + ".pdiparams")
        import shutil
        shutil.copy(base + ".pdconfig", ebase + ".pdconfig")

        # without the key: loud error
        with pytest.raises(ValueError):
            create_predictor(Config(ebase + ".pdmodel"))

        cfg = Config(ebase + ".pdmodel")
        cfg.set_cipher_key(key)
        pred = create_predictor(cfg)
        outs = pred.run([x])
        np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-5)

        # review finding: params-only encryption (weights are the IP)
        # must decrypt that half and pass the plaintext half through
        mbase = str(tmp_path / "mixed")
        shutil.copy(base + ".pdmodel", mbase + ".pdmodel")
        c.encrypt_file(base + ".pdiparams", mbase + ".pdiparams")
        shutil.copy(base + ".pdconfig", mbase + ".pdconfig")
        with pytest.raises(ValueError):
            create_predictor(Config(mbase + ".pdmodel"))
        cfg2 = Config(mbase + ".pdmodel")
        cfg2.set_cipher_key(key)
        outs2 = create_predictor(cfg2).run([x])
        np.testing.assert_allclose(outs2[0], ref, rtol=1e-5, atol=1e-5)


class TestGoBindings:
    def test_symbols_match_c_abi(self):
        """The cgo declarations in go/paddle/*.go must name symbols
        the C ABI actually exports (toolchain-free consistency check)."""
        import glob
        go_src = "".join(
            open(p).read()
            for p in glob.glob(os.path.join(REPO, "go", "paddle",
                                            "*.go")))
        c_src = open(os.path.join(
            REPO, "paddle1_tpu", "core", "native", "src",
            "capi.cc")).read()
        go_syms = set(re.findall(r"extern [\w\s]+\**\s*(p1_\w+)\(",
                                 go_src))
        assert go_syms, "no extern declarations found in go/paddle"
        for sym in go_syms:
            assert sym in c_src, f"{sym} not exported by capi.cc"

    def test_go_api_parity_surface(self):
        """The reference's 3-file Go API (config/predictor/tensor)
        exists with its method names (toolchain-free check)."""
        base = os.path.join(REPO, "go", "paddle")
        cfg = open(os.path.join(base, "config.go")).read()
        for m in ("SetModel", "EnableUseGpu", "DisableGpu", "UseGpu",
                  "SwitchIrOptim", "EnableMemoryOptim",
                  "SetCpuMathLibraryNumThreads", "EnableProfile",
                  "DeletePass", "EnableTensorRtEngine",
                  "EnableMkldnn"):
            assert f"func (c *AnalysisConfig) {m}(" in cfg, m
        pred = open(os.path.join(base, "predictor.go")).read()
        for m in ("GetInputNum", "GetOutputNum", "GetInputNames",
                  "GetOutputNames", "GetInputTensors",
                  "GetOutputTensors", "SetZeroCopyInput",
                  "GetZeroCopyOutput", "ZeroCopyRun"):
            assert f"func (p *Predictor) {m}(" in pred, m
        ten = open(os.path.join(base, "tensor.go")).read()
        for m in ("Shape", "Name", "Rename", "Reshape", "SetValue",
                  "Value", "DataType", "Lod"):
            assert f"func (t *ZeroCopyTensor) {m}(" in ten, m
        assert "func Endian()" in ten

    def test_capi_so_exports(self):
        from paddle1_tpu.core.native import build_capi
        so = build_capi()
        if so is None:
            pytest.skip("cannot build capi")
        import subprocess
        out = subprocess.run(["nm", "-D", so], capture_output=True,
                             text=True).stdout
        for sym in ("p1_predictor_create", "p1_predictor_run_f32",
                    "p1_predictor_destroy", "p1_last_error",
                    "p1_predictor_num_inputs", "p1_predictor_num_outputs",
                    "p1_predictor_input_name",
                    "p1_predictor_output_name"):
            assert sym in out


class TestMultiTrainer:
    def _dataset(self, tmp_path, n_files=3, rows=30):
        rng = np.random.default_rng(0)
        files = []
        for i in range(n_files):
            p = tmp_path / f"f{i}.txt"
            lines = []
            for _ in range(rows):
                x = rng.standard_normal(4)
                y = float(x @ np.array([1.0, -1.0, 2.0, 0.5]))
                lines.append(" ".join(map(str, list(x) + [y])))
            p.write_text("\n".join(lines) + "\n")
            files.append(str(p))
        ds = paddle.io.QueueDataset()
        ds.set_filelist(files)
        ds.set_rank_world(0, 1)
        return ds

    def test_single_thread_trains(self, tmp_path):
        from paddle1_tpu.distributed.fleet import MultiTrainer
        ds = self._dataset(tmp_path)
        lin = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=lin.parameters())

        def loss_fn(batch):
            xb = to_tensor(batch[:, :4])
            yb = to_tensor(batch[:, 4:5])
            return ((lin(xb) - yb) ** 2).mean()

        first = MultiTrainer(thread_num=1).train_from_dataset(
            ds, loss_fn, opt, batch_size=10)
        assert first["batches"] == 9
        again = MultiTrainer(thread_num=1).train_from_dataset(
            ds, loss_fn, opt, batch_size=10)
        assert again["loss_mean"] < first["loss_mean"]

    def test_hogwild_threads_drain_and_train(self, tmp_path):
        from paddle1_tpu.distributed.fleet import MultiTrainer
        ds = self._dataset(tmp_path, n_files=4, rows=40)
        lin = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=lin.parameters())

        def loss_fn(batch):
            xb = to_tensor(batch[:, :4])
            yb = to_tensor(batch[:, 4:5])
            return ((lin(xb) - yb) ** 2).mean()

        trainer = MultiTrainer(thread_num=4)
        runs = [trainer.train_from_dataset(ds, loss_fn, opt, batch_size=8)
                for _ in range(3)]
        assert all(r["batches"] == 20 for r in runs)
        # work actually spread across workers
        active = sum(1 for s in runs[0]["per_worker"].values()
                     if s["batches"] > 0)
        assert active >= 2, runs[0]["per_worker"]
        assert runs[-1]["loss_mean"] < runs[0]["loss_mean"]

    def test_sparse_embedding_service_path(self, tmp_path):
        """The reference's defining workload: hogwild workers + host-RAM
        sparse table, device memory independent of vocab."""
        from paddle1_tpu.distributed import (DistributedEmbedding,
                                             EmbeddingService)
        from paddle1_tpu.distributed.fleet import MultiTrainer
        rng = np.random.default_rng(1)
        samples = [(rng.integers(0, 10**8, 4),
                    rng.standard_normal(8).astype(np.float32))
                   for _ in range(60)]
        svc = EmbeddingService(dim=8, num_shards=4, optimizer="adagrad",
                               lr=0.3)
        emb = DistributedEmbedding(svc)

        def loss_fn(batch):
            ids = np.stack([b[0] for b in batch])
            tgt = to_tensor(np.stack([b[1] for b in batch]))
            out = emb(to_tensor(ids))
            from paddle1_tpu.ops import math_ops
            pooled = math_ops.mean(out, axis=1)
            return ((pooled - tgt) ** 2).mean()

        trainer = MultiTrainer(thread_num=3)
        r1 = trainer.train_from_dataset(samples, loss_fn, _NoOpt(),
                                        batch_size=6,
                                        collate=lambda b: b)
        r2 = trainer.train_from_dataset(samples, loss_fn, _NoOpt(),
                                        batch_size=6,
                                        collate=lambda b: b)
        assert r2["loss_mean"] < r1["loss_mean"]
        assert len(svc) <= 240  # only touched rows exist

    def test_worker_error_propagates(self):
        from paddle1_tpu.distributed.fleet import MultiTrainer

        def bad_loss(batch):
            raise RuntimeError("worker boom")

        with pytest.raises(RuntimeError, match="worker boom"):
            MultiTrainer(thread_num=2).train_from_dataset(
                [np.zeros(2), np.zeros(2)], bad_loss, _NoOpt(),
                batch_size=1)


class TestInferFromDataset:
    def test_executor_drains_without_update(self, tmp_path):
        lin = paddle.nn.Linear(4, 1)
        w_before = np.asarray(lin.weight.numpy()).copy()
        rng = np.random.default_rng(3)
        rows = rng.standard_normal((30, 4)).astype(np.float32)
        p = tmp_path / "infer.txt"
        p.write_text("\n".join(" ".join(map(str, r)) for r in rows) + "\n")
        ds = paddle.io.QueueDataset()
        ds.set_filelist([str(p)])
        ds.set_rank_world(0, 1)

        got = []

        def infer_fn(batch):
            return np.asarray(lin(to_tensor(batch)).numpy())

        exe = paddle.static.Executor()
        out = exe.infer_from_dataset(dataset=ds, infer_fn=infer_fn,
                                     batch_size=10, thread=2,
                                     fetch_handler=got.append)
        assert out["batches"] == 3
        assert sum(len(g) for g in got) == 30
        # forward only: parameters untouched
        np.testing.assert_array_equal(np.asarray(lin.weight.numpy()),
                                      w_before)
        # outputs match a direct forward over the same rows
        direct = np.asarray(lin(to_tensor(rows)).numpy())
        np.testing.assert_allclose(
            np.sort(np.concatenate(got, axis=0), axis=0),
            np.sort(direct, axis=0), rtol=1e-5)

    def test_needs_infer_fn(self):
        exe = paddle.static.Executor()
        with pytest.raises(Exception, match="infer_fn"):
            exe.infer_from_dataset(dataset=[np.zeros(2)])


class TestExecutorRunTeaching:
    def test_startup_idiom_is_noop(self):
        exe = paddle.static.Executor()
        assert exe.run(paddle.static.default_startup_program()) == []

    def test_real_program_run_teaches_loudly(self):
        from paddle1_tpu.core.errors import UnimplementedError
        exe = paddle.static.Executor()
        with pytest.raises(UnimplementedError, match="train_from_dataset"):
            exe.run(paddle.static.default_main_program(),
                    feed={"x": np.zeros(2)}, fetch_list=["out"])

    def test_callable_program_still_runs(self):
        exe = paddle.static.Executor()
        out = exe.run(lambda x: x + 1, feed={"x": 41})
        assert out == [42]


class _NoOpt:
    def step(self):
        pass

    def clear_grad(self):
        pass
