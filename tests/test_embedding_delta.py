"""Online-learning embedding deltas + async sparse PS mode (ISSUE 19
tentpoles (c)/(d)): DeltaLog/DeltaSubscriber semantics, the serving-side
recompile-free row rewrite, the trainer→fleet latency contract, the
collective-sanitizer coverage of the sparse push/pull and delta-publish
schedules (satellite 1), and SparseAsyncCommunicator's bounded-staleness
overlap."""

import glob
import os
import threading
import time

import numpy as np
import pytest

import paddle1_tpu as paddle
from paddle1_tpu.core import collective_sanitizer as cs
from paddle1_tpu.core import flags as core_flags
from paddle1_tpu.core.collective_sanitizer import CollectiveDivergenceError
from paddle1_tpu.core.errors import (InvalidArgumentError,
                                     PreconditionNotMetError)
from paddle1_tpu.distributed import (DeltaLog, DeltaSubscriber,
                                     EmbeddingService,
                                     SparseAsyncCommunicator)
from paddle1_tpu.distributed.embedding_delta import read_since
from paddle1_tpu.obs import MetricsRegistry
from paddle1_tpu.serving import InferenceEngine, Server

DIM = 4


class TestDeltaLog:
    def test_publish_read_round_trip_in_order(self, tmp_path):
        log = DeltaLog(str(tmp_path))
        v1 = log.publish("emb.weight", [3, 1], np.ones((2, DIM)))
        v2 = log.publish("emb.weight", [7], np.full((1, DIM), 2.0))
        assert (v1, v2) == (1, 2)
        recs = read_since(str(tmp_path), 0)
        assert [r.version for r in recs] == [1, 2]
        assert recs[0].param == "emb.weight"
        np.testing.assert_array_equal(recs[1].ids, [7])
        np.testing.assert_allclose(recs[1].rows, 2.0)
        assert read_since(str(tmp_path), 1)[0].version == 2
        assert read_since(str(tmp_path), 2) == []

    def test_versions_are_monotone(self, tmp_path):
        log = DeltaLog(str(tmp_path))
        log.publish("p", [1], np.zeros((1, DIM)), version=5)
        with pytest.raises(InvalidArgumentError, match="monotone"):
            log.publish("p", [1], np.zeros((1, DIM)), version=5)
        # a new instance over the same dir resumes past the head
        assert DeltaLog(str(tmp_path)).publish(
            "p", [1], np.zeros((1, DIM))) == 6

    def test_shape_mismatch_refused(self, tmp_path):
        log = DeltaLog(str(tmp_path))
        with pytest.raises(InvalidArgumentError, match="rows"):
            log.publish("p", [1, 2], np.zeros((3, DIM)))

    def test_prune_keeps_tail_and_no_tmp_residue(self, tmp_path):
        log = DeltaLog(str(tmp_path), keep=3)
        for _ in range(7):
            log.publish("p", [0], np.zeros((1, DIM)))
        files = sorted(glob.glob(str(tmp_path / "delta-*.npz")))
        assert len(files) == 3
        assert [r.version for r in read_since(str(tmp_path), 0)] \
            == [5, 6, 7]
        assert glob.glob(str(tmp_path / "*.tmp")) == []   # atomic


class TestDeltaSubscriber:
    def test_poll_applies_in_order_exactly_once(self, tmp_path):
        log = DeltaLog(str(tmp_path))
        seen = []
        sub = DeltaSubscriber(str(tmp_path),
                              lambda p, i, r: seen.append(int(i[0])))
        log.publish("p", [10], np.zeros((1, DIM)))
        log.publish("p", [20], np.zeros((1, DIM)))
        assert sub.poll_once() == 2
        assert sub.poll_once() == 0     # nothing new: no re-apply
        assert seen == [10, 20]
        assert sub.applied_version == 2

    def test_bad_delta_is_skipped_counted_and_version_advances(
            self, tmp_path):
        log = DeltaLog(str(tmp_path))
        m = MetricsRegistry()
        applied = []

        def apply_fn(p, i, r):
            if p == "bad":
                raise InvalidArgumentError("renamed param")
            applied.append(p)

        sub = DeltaSubscriber(str(tmp_path), apply_fn, metrics=m)
        log.publish("ok", [1], np.zeros((1, DIM)))
        log.publish("bad", [2], np.zeros((1, DIM)))
        log.publish("ok", [3], np.zeros((1, DIM)))
        assert sub.poll_once() == 2
        assert applied == ["ok", "ok"]
        assert sub.applied_version == 3   # the bad version is consumed
        snap = m.snapshot()
        assert snap["counters"]["embed_delta_errors_total"] == 1
        assert snap["counters"]["embed_delta_applied_total"] == 2
        assert snap["counters"]["embed_delta_rows_total"] == 2
        assert snap["gauges"]["embed_delta_version"] == 3

    def test_threaded_wait_version(self, tmp_path):
        log = DeltaLog(str(tmp_path))
        got = []
        sub = DeltaSubscriber(str(tmp_path),
                              lambda p, i, r: got.append(p),
                              poll_s=0.01).start()
        try:
            assert not sub.wait_version(1, timeout=0.05)   # nothing yet
            log.publish("p", [1], np.zeros((1, DIM)))
            assert sub.wait_version(1, timeout=5.0)
            assert got == ["p"]
        finally:
            sub.stop()


def _emb_model(vocab=32, seed=0):
    paddle.seed(seed)

    class _M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = paddle.nn.Embedding(vocab, DIM)

        def forward(self, ids):
            return self.emb(ids)

    m = _M()
    m.eval()
    return m


class TestServingDelta:
    def test_update_param_rows_no_recompile(self):
        model = _emb_model()
        eng = InferenceEngine(model, buckets=(1, 2),
                              input_specs=[((1,), "int64")])
        ids = np.array([[5]], np.int64)
        before = np.asarray(eng.infer([ids])[0])
        compiles = dict(eng.compile_counts)
        new_row = np.arange(DIM, dtype=np.float32)[None]
        eng.update_param_rows("emb.weight", [5], new_row)
        after = np.asarray(eng.infer([ids])[0])
        np.testing.assert_allclose(after[0, 0], new_row[0], rtol=1e-6)
        assert not np.allclose(before, after)
        assert eng.compile_counts == compiles   # zero recompiles

    def test_update_param_rows_typed_errors(self):
        eng = InferenceEngine(_emb_model(), buckets=(1,),
                              input_specs=[((1,), "int64")])
        with pytest.raises(InvalidArgumentError, match="not served"):
            eng.update_param_rows("nope", [0], np.zeros((1, DIM)))
        with pytest.raises(InvalidArgumentError, match="fit"):
            eng.update_param_rows("emb.weight", [0],
                                  np.zeros((1, DIM + 1)))
        with pytest.raises(InvalidArgumentError, match="range"):
            eng.update_param_rows("emb.weight", [99],
                                  np.zeros((1, DIM)))

    def test_server_serves_published_delta_within_five_seconds(
            self, tmp_path):
        """The production-loop gate: a delta published while the server
        is live is servable in < 5s with rows matching the publisher's
        at 1e-6 — no restart, no redeploy."""
        srv = Server(_emb_model(), max_batch=1, buckets=(1,),
                     input_specs=[((1,), "int64")],
                     delta_dir=str(tmp_path), delta_poll_ms=10).start()
        try:
            ids = np.array([[7]], np.int64)
            srv.submit(ids).result(timeout=30)   # warm path
            row = np.linspace(1, 2, DIM, dtype=np.float32)[None]
            t0 = time.monotonic()
            DeltaLog(str(tmp_path)).publish("emb.weight", [7], row)
            while time.monotonic() - t0 < 5.0:
                out = np.asarray(srv.submit(ids).result(timeout=30))
                if np.allclose(out[0, 0], row[0], rtol=1e-6):
                    break
                time.sleep(0.01)
            else:
                pytest.fail("delta not served within 5s")
            assert time.monotonic() - t0 < 5.0
        finally:
            srv.drain()


class TestSanitizedSchedules:
    """Satellite 1: the sparse push/pull and delta-publish points ride
    the PR 14 collective-schedule sanitizer."""

    def test_sparse_ops_journal_into_the_schedule(self, tmp_path):
        with core_flags.flags_guard(debug_collective_sanitizer=True):
            cs.reset()
            svc = EmbeddingService(DIM, num_shards=2)
            svc.pull([1, 2])
            svc.push([1, 2], np.zeros((2, DIM), np.float32))
            DeltaLog(str(tmp_path)).publish("p", [1],
                                            np.zeros((1, DIM)))
            ops = [r["op"] for r in cs.schedule()]
            assert ops == ["ps_pull_sparse", "ps_push_sparse",
                           "delta_publish"]
            sites = [r["site"] for r in cs.schedule()]
            assert sites == ["EmbeddingService.pull",
                             "EmbeddingService.push",
                             "DeltaLog.publish"]

    def test_unarmed_is_free(self, tmp_path):
        cs.reset()
        svc = EmbeddingService(DIM)
        svc.pull([1])
        DeltaLog(str(tmp_path)).publish("p", [1], np.zeros((1, DIM)))
        assert cs.schedule() == []

    def test_misordered_push_fails_typed_across_ranks(self, tmp_path,
                                                      monkeypatch):
        """Two ranks run the same program; rank 1 skips its push (the
        classic async-PS bug: a worker silently drops a gradient). The
        cross-rank verifier names the diverging step instead of letting
        the tables drift."""
        with core_flags.flags_guard(
                debug_collective_sanitizer=True,
                collective_journal_dir=str(tmp_path)):
            g = np.ones((2, DIM), np.float32)

            def program(skip_push):
                svc = EmbeddingService(DIM)
                svc.pull([1, 2])
                if not skip_push:
                    svc.push([1, 2], g)
                svc.pull([3, 4])

            monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
            cs.reset()
            program(skip_push=False)
            assert len(cs.schedule()) == 3
            monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
            cs.reset()
            program(skip_push=True)
            with pytest.raises(CollectiveDivergenceError) as ei:
                cs.verify_dir(str(tmp_path), complete=True)
            msg = str(ei.value)
            assert "step 2" in msg and "ps_push_sparse" in msg

    def test_divergent_push_shape_fails_typed(self, tmp_path,
                                              monkeypatch):
        """Same schedule, different payload shape — the digest catches
        a rank pushing a differently-coalesced gradient."""
        with core_flags.flags_guard(
                debug_collective_sanitizer=True,
                collective_journal_dir=str(tmp_path)):
            for rank, n in ((0, 2), (1, 3)):
                monkeypatch.setenv("PADDLE_TRAINER_ID", str(rank))
                cs.reset()
                EmbeddingService(DIM).push(
                    list(range(n)), np.ones((n, DIM), np.float32))
            with pytest.raises(CollectiveDivergenceError, match="step 1"):
                cs.verify_dir(str(tmp_path), complete=True)


class TestSparseAsyncCommunicator:
    def test_async_push_matches_synchronous_sgd(self):
        """Coalescing across queued steps must be value-preserving for
        the table's sgd (sum of grads × lr == sequential steps)."""
        svc_async = EmbeddingService(DIM, num_shards=2, lr=0.1)
        svc_sync = EmbeddingService(DIM, num_shards=2, lr=0.1)
        np.testing.assert_allclose(svc_async.pull([1, 2, 3]),
                                   svc_sync.pull([1, 2, 3]))
        comm = SparseAsyncCommunicator(svc_async, merge_num=4).start()
        try:
            rng = np.random.default_rng(0)
            for _ in range(10):
                ids = rng.integers(1, 4, 5).astype(np.int64)
                g = rng.standard_normal((5, DIM)).astype(np.float32)
                comm.push(ids, g)
                svc_sync.push(ids, g)
            comm.flush()
            assert comm.applied_total == comm.pushed_total == 10
            np.testing.assert_allclose(svc_async.pull([1, 2, 3]),
                                       svc_sync.pull([1, 2, 3]),
                                       rtol=1e-5, atol=1e-6)
        finally:
            comm.stop()

    def test_staleness_stays_bounded(self):
        svc = EmbeddingService(DIM)
        slow = threading.Event()
        orig = svc.push

        def slow_push(ids, grads):
            slow.wait(0.01)
            orig(ids, grads)

        svc.push = slow_push
        comm = SparseAsyncCommunicator(svc, max_staleness=3,
                                       send_interval=0.001).start()
        try:
            for _ in range(12):
                comm.push([1], np.ones((1, DIM), np.float32))
                assert comm.staleness() <= 3
            comm.flush()
            assert comm.staleness() == 0
        finally:
            comm.stop()

    def test_push_before_start_raises(self):
        comm = SparseAsyncCommunicator(EmbeddingService(DIM))
        with pytest.raises(PreconditionNotMetError, match="start"):
            comm.push([1], np.ones((1, DIM), np.float32))

    def test_prefetch_overlaps_and_matches_direct_pull(self):
        svc = EmbeddingService(DIM)
        want = svc.pull([4, 5])
        comm = SparseAsyncCommunicator(svc).start()
        try:
            comm.prefetch([4, 5])
            np.testing.assert_allclose(comm.pulled([4, 5]), want)
            # a non-matching request falls back to a direct pull
            np.testing.assert_allclose(comm.pulled([4]), want[:1])
        finally:
            comm.stop()

    def test_flush_surfaces_push_failure(self):
        svc = EmbeddingService(DIM)

        def boom(ids, grads):
            raise RuntimeError("wire down")

        comm = SparseAsyncCommunicator(svc, send_interval=60).start()
        svc.push = boom
        try:
            comm.push([1], np.ones((1, DIM), np.float32))
            with pytest.raises(RuntimeError, match="wire down"):
                comm.flush()
            assert comm.staleness() == 0   # backpressure freed
        finally:
            comm._stop.set()

    def test_checkpoint_round_trip_is_quiesced(self):
        svc = EmbeddingService(DIM, lr=0.5)
        comm = SparseAsyncCommunicator(svc).start()
        try:
            base = svc.pull([1, 2])
            comm.push([1, 2], np.ones((2, DIM), np.float32))
            sd = comm.state_dict()        # flushes first: queue empty
            np.testing.assert_allclose(svc.pull([1, 2]), base - 0.5)
            assert sd["pushed_total"] == 1 and sd["applied_total"] == 1
        finally:
            comm.stop()
        svc2 = EmbeddingService(DIM, lr=0.5)
        comm2 = SparseAsyncCommunicator(svc2).start()
        try:
            comm2.load_state_dict(sd)
            np.testing.assert_allclose(svc2.pull([1, 2]),
                                       svc.pull([1, 2]))
            assert comm2.pushed_total == 1
        finally:
            comm2.stop()


class TestSubscriberDurability:
    """Exactly-once across subscriber restarts, CRC corruption
    skipping, typed gap detection + snapshot healing, and the
    in-stream-snapshot regression (a routine trainer snapshot is part
    of the stream, not a hole)."""

    def test_stop_restart_resumes_without_reapplying(self, tmp_path):
        log = DeltaLog(str(tmp_path))
        applied = []
        sub = DeltaSubscriber(
            str(tmp_path),
            lambda p, ids, rows: applied.append(int(ids[0])),
            poll_s=0.005).start()
        try:
            log.publish("w", [1], np.ones((1, DIM), np.float32))
            assert sub.wait_version(1, timeout=5)
            sub.stop()
            log.publish("w", [2], np.full((1, DIM), 2.0, np.float32))
            sub.start()        # same subscriber resumes in place
            assert sub.wait_version(2, timeout=5)
        finally:
            sub.stop()
        # v1 applied exactly once, never replayed after the restart
        assert applied == [1, 2]

    def test_fresh_subscriber_resumes_from_version(self, tmp_path):
        # a restarted replica process passes the version its restored
        # checkpoint corresponds to — nothing at or before it replays
        log = DeltaLog(str(tmp_path))
        log.publish("w", [1], np.ones((1, DIM), np.float32))
        log.publish("w", [2], np.ones((1, DIM), np.float32))
        applied = []
        sub = DeltaSubscriber(str(tmp_path),
                              lambda p, i, r: applied.append(int(i[0])),
                              from_version=1)
        assert sub.poll_once() == 1
        assert applied == [2]

    def test_corrupt_delta_skipped_and_counted(self, tmp_path):
        reg = MetricsRegistry()
        log = DeltaLog(str(tmp_path))
        log.publish("w", [1], np.ones((1, DIM), np.float32))
        v2 = log.publish("w", [2], np.full((1, DIM), 2.0, np.float32))
        path = os.path.join(str(tmp_path), f"delta-{v2:012d}.npz")
        blob = bytearray(open(path, "rb").read())
        # bit-flip inside the rows payload itself (not zip padding) so
        # the stored CRC can no longer match the bytes on disk
        idx = blob.find(np.full((1, DIM), 2.0, np.float32).tobytes())
        assert idx != -1
        blob[idx] ^= 0xFF
        open(path, "wb").write(bytes(blob))
        applied = []
        sub = DeltaSubscriber(str(tmp_path),
                              lambda p, i, r: applied.append(int(i[0])),
                              metrics=reg)
        sub.poll_once()
        assert applied == [1]                 # bad file never applied
        assert reg.counter("delta_corrupt_total").value >= 1
        assert reg.counter("delta_skipped_files_total").value >= 1

    def test_gap_is_typed_then_snapshot_heals(self, tmp_path):
        from paddle1_tpu.distributed.embedding_delta import \
            DeltaGapDetected
        reg = MetricsRegistry()
        log = DeltaLog(str(tmp_path))
        for i in range(3):
            log.publish("w", [i], np.ones((1, DIM), np.float32))
        sub = DeltaSubscriber(str(tmp_path), lambda p, i, r: None,
                              metrics=reg)
        assert sub.poll_once() == 3
        # prune v4 from under the reader → hole at 4, head at 5
        v4 = log.publish("w", [1], np.ones((1, DIM), np.float32))
        log.publish("w", [2], np.ones((1, DIM), np.float32))
        os.remove(os.path.join(str(tmp_path), f"delta-{v4:012d}.npz"))
        with pytest.raises(DeltaGapDetected, match="version hole"):
            sub.poll_once()
        with pytest.raises(DeltaGapDetected):
            sub.poll_once()   # still stale; counted once per episode
        assert reg.counter("delta_gaps_total").value == 1
        assert sub.applied_version == 3   # never silently jumped
        # the trainer publishes a full snapshot anchor → next poll
        # resyncs from it and streaming resumes
        log.publish_snapshot("w", np.arange(3),
                             np.full((3, DIM), 9.0, np.float32))
        sub.poll_once()
        assert sub.applied_version == 6
        assert reg.counter("delta_resyncs_total").value == 1

    def test_instream_snapshot_is_not_a_gap(self, tmp_path):
        # regression: a snapshot whose version == applied + 1 is the
        # trainer's ROUTINE anchor publish — apply silently, keep
        # streaming, no gap episode
        reg = MetricsRegistry()
        log = DeltaLog(str(tmp_path))
        log.publish("w", [0], np.ones((1, DIM), np.float32))
        got = {}
        sub = DeltaSubscriber(
            str(tmp_path),
            lambda p, i, r: got.update(zip(i.tolist(), r[:, 0].tolist())),
            metrics=reg)
        assert sub.poll_once() == 1
        log.publish_snapshot("w", [0, 1],
                             np.full((2, DIM), 3.0, np.float32))  # v2
        log.publish("w", [1], np.full((1, DIM), 4.0, np.float32))  # v3
        assert sub.poll_once() == 2
        assert sub.applied_version == 3
        assert got == {0: 3.0, 1: 4.0}   # snapshot THEN delta, in order
        assert reg.counter("delta_gaps_total").value == 0
        assert reg.counter("delta_resyncs_total").value == 0


class TestServingDurability:
    """Serving-side teaching errors + the parity-probe reader."""

    def test_server_rejects_missing_delta_dir(self, tmp_path):
        srv = Server(_emb_model(), max_batch=1, buckets=(1,),
                     input_specs=[((1,), "int64")],
                     delta_dir=str(tmp_path / "nope"))
        with pytest.raises(InvalidArgumentError, match="does not exist"):
            srv.start()

    def test_param_rows_reads_back_applied_delta(self):
        eng = InferenceEngine(_emb_model(), buckets=(1,),
                              input_specs=[((1,), "int64")])
        row = np.linspace(0, 1, DIM, dtype=np.float32)[None]
        eng.update_param_rows("emb.weight", [3], row)
        np.testing.assert_allclose(
            eng.param_rows("emb.weight", [3]), row, rtol=1e-6)
        with pytest.raises(InvalidArgumentError):
            eng.param_rows("nope", [0])
        with pytest.raises(InvalidArgumentError):
            eng.param_rows("emb.weight", [10_000])
