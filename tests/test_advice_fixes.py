"""Regression tests for the ADVICE r1 findings (VERDICT r2 task 7):
QAT-under-jit silent collapse, NMS negative-coordinate category offsets,
box_coder axis semantics, shm create/attach ftruncate discipline, profiler
cross-thread trace state."""

import threading
import unittest

import numpy as np
import jax
import jax.numpy as jnp

from paddle1_tpu.core.tensor import to_tensor


class TestQATUnderJit(unittest.TestCase):
    def test_uncalibrated_activation_quant_passes_through_under_jit(self):
        """An uninited EMA observer inside a jitted/functionalized forward
        must pass activations through, not clamp them to ~0."""
        from paddle1_tpu.nn.layer_common import Linear
        from paddle1_tpu.quantization import QAT

        lin = Linear(8, 8)
        q = QAT()
        model = q.quantize(lin)
        model.eval()

        x = np.random.default_rng(0).standard_normal((4, 8)).astype(
            np.float32)
        params = model.functional_state()

        def fwd(params, x):
            from paddle1_tpu.autograd import engine as ag
            with ag.no_grad(), model.load_functional_state(params):
                return model(to_tensor(x)).data

        out_jit = np.asarray(jax.jit(fwd)(params, x))
        out_eager = np.asarray(fwd(params, x))
        # pre-fix the jitted path quantized with scale=0 → all ~0 outputs
        self.assertGreater(np.abs(out_jit).max(), 1e-3)
        np.testing.assert_allclose(out_jit, out_eager, rtol=1e-5, atol=1e-6)

    def test_calibrated_observer_quantizes_under_jit(self):
        from paddle1_tpu.quantization import FakeQuantMovingAverageAbsMax
        obs = FakeQuantMovingAverageAbsMax(bits=8)
        x = np.linspace(-1, 1, 1000).astype(np.float32)
        obs.train()
        obs(to_tensor(x))  # calibrates scale
        obs.eval()
        params = obs.functional_state()

        def fwd(params, x):
            from paddle1_tpu.autograd import engine as ag
            with ag.no_grad(), obs.load_functional_state(params):
                return obs(to_tensor(x)).data

        out = np.asarray(jax.jit(fwd)(params, x))
        # quantized: at most 2^bits levels, but non-degenerate
        self.assertGreater(np.abs(out).max(), 0.5)
        self.assertLess(len(np.unique(np.round(out, 5))), 260)


class TestNMSNegativeCoords(unittest.TestCase):
    def test_category_offset_with_negative_boxes(self):
        """Identical overlapping boxes in different categories must BOTH
        survive even when coordinates are negative (the max+1 offset
        collapsed categories then)."""
        from paddle1_tpu.vision import ops as V
        boxes = np.array([[-50, -50, -40, -40],
                          [-50, -50, -40, -40]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1], np.int32)
        keep = V.nms(to_tensor(boxes), 0.5, to_tensor(scores),
                     category_idxs=to_tensor(cats))
        self.assertEqual(sorted(np.asarray(keep.numpy()).tolist()), [0, 1])

    def test_same_category_still_suppressed(self):
        from paddle1_tpu.vision import ops as V
        boxes = np.array([[-50, -50, -40, -40],
                          [-50, -50, -40, -40]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 0], np.int32)
        keep = V.nms(to_tensor(boxes), 0.5, to_tensor(scores),
                     category_idxs=to_tensor(cats))
        self.assertEqual(np.asarray(keep.numpy()).tolist(), [0])


class TestBoxCoderAxis(unittest.TestCase):
    def _roundtrip(self, axis):
        from paddle1_tpu.vision import ops as V
        rng = np.random.default_rng(0)
        m = 3
        prior = np.abs(rng.standard_normal((m, 4))).astype(np.float32)
        prior[:, 2:] = prior[:, :2] + 1.0 + prior[:, 2:]
        # encode m targets against m priors → [m, m, 4]; diagonal is each
        # target vs its own prior
        target = prior + 0.1
        enc = np.asarray(V.box_coder(to_tensor(prior), None,
                                     to_tensor(target),
                                     code_type="encode_center_size").numpy())
        self.assertEqual(enc.shape, (m, m, 4))
        # decode with target [N=m, M=m, 4]
        dec = np.asarray(V.box_coder(
            to_tensor(prior), None, to_tensor(enc),
            code_type="decode_center_size", axis=axis).numpy())
        return target, enc, dec

    def test_axis0_roundtrip_diagonal(self):
        target, enc, dec = self._roundtrip(axis=0)
        # axis=0: prior aligns with dim 1 → dec[i, i] recovers target[i]
        for i in range(3):
            np.testing.assert_allclose(dec[i, i], target[i], rtol=1e-5,
                                       atol=1e-5)

    def test_axis1_differs_from_axis0(self):
        from paddle1_tpu.vision import ops as V
        rng = np.random.default_rng(1)
        m = 3
        prior = np.abs(rng.standard_normal((m, 4))).astype(np.float32)
        prior[:, 2:] = prior[:, :2] + 1.0 + prior[:, 2:]
        deltas = rng.standard_normal((m, m, 4)).astype(np.float32) * 0.1
        d0 = np.asarray(V.box_coder(to_tensor(prior), None,
                                    to_tensor(deltas),
                                    code_type="decode_center_size",
                                    axis=0).numpy())
        d1 = np.asarray(V.box_coder(to_tensor(prior), None,
                                    to_tensor(deltas),
                                    code_type="decode_center_size",
                                    axis=1).numpy())
        self.assertEqual(d0.shape, d1.shape)
        self.assertFalse(np.allclose(d0, d1))
        # axis=1 on transposed deltas == transpose of axis=0
        d1t = np.asarray(V.box_coder(
            to_tensor(prior), None,
            to_tensor(np.swapaxes(deltas, 0, 1).copy()),
            code_type="decode_center_size", axis=1).numpy())
        np.testing.assert_allclose(np.swapaxes(d1t, 0, 1), d0, rtol=1e-5,
                                   atol=1e-5)


class TestShmDiscipline(unittest.TestCase):
    def test_attach_existing_does_not_resize(self):
        from paddle1_tpu.core import native
        if not native.available():
            self.skipTest("native lib unavailable")
        name = "/p1t_test_resize"
        lib = native._load()
        lib.shm_arena_unlink(name.encode())
        a = native.ShmArena(name, 1 << 16)
        try:
            # a second create must ATTACH at the existing size, never
            # ftruncate an arena another process already mapped
            b = native.ShmArena(name, 1 << 14)  # smaller request: ok
            self.assertEqual(a.size, b.size)
            off = lib.shm_alloc(a._base, 100)
            self.assertGreater(off, 0)
        finally:
            lib.shm_arena_unlink(name.encode())

    def test_concurrent_alloc_no_overlap(self):
        from paddle1_tpu.core import native
        if not native.available():
            self.skipTest("native lib unavailable")
        name = "/p1t_test_race"
        lib = native._load()
        lib.shm_arena_unlink(name.encode())
        arena = native.ShmArena(name, 1 << 20)
        offsets = []
        lock = threading.Lock()

        def worker():
            got = []
            for _ in range(200):
                off = lib.shm_alloc(arena._base, 64)
                if off:
                    got.append(off)
            with lock:
                offsets.extend(got)

        ts = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        try:
            self.assertEqual(len(offsets), len(set(offsets)))
        finally:
            lib.shm_arena_unlink(name.encode())


class TestProfilerCrossThread(unittest.TestCase):
    def test_stop_on_other_thread_sees_trace_state(self):
        import paddle1_tpu.profiler as prof
        # no real device trace (log_dir None keeps jax out of it); assert
        # the module-global state is visible across threads
        prof._trace_dir = "/tmp/fake_dir_sentinel"
        seen = {}

        def other():
            seen["dir"] = prof._trace_dir

        t = threading.Thread(target=other)
        t.start()
        t.join()
        prof._trace_dir = None
        self.assertEqual(seen["dir"], "/tmp/fake_dir_sentinel")




class TestAdviceR3Fixes(unittest.TestCase):
    """Regression tests for the ADVICE r3 findings."""

    def test_fluid_cross_entropy_soft_label(self):
        import paddle1_tpu.fluid.layers as L
        rng = np.random.default_rng(0)
        logits = rng.standard_normal((4, 5)).astype(np.float32)
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        soft = rng.random((4, 5)).astype(np.float32)
        soft /= soft.sum(-1, keepdims=True)
        out = L.cross_entropy(to_tensor(probs), to_tensor(soft),
                              soft_label=True)
        expect = -(soft * np.log(probs)).sum(-1)
        np.testing.assert_allclose(np.asarray(out.data), expect, rtol=1e-5)

    def test_fluid_cross_entropy_soft_label_shape_mismatch_raises(self):
        import paddle1_tpu.fluid.layers as L
        from paddle1_tpu.core.errors import InvalidArgumentError
        probs = np.full((4, 5), 0.2, np.float32)
        lab = np.zeros((4, 1), np.int64)
        with self.assertRaises(InvalidArgumentError):
            L.cross_entropy(to_tensor(probs), to_tensor(lab),
                            soft_label=True)

    def test_reader_compose_alignment_raises(self):
        from paddle1_tpu import reader
        r1 = lambda: iter([1, 2, 3])
        r2 = lambda: iter([10, 20])
        with self.assertRaises(reader.ComposeNotAligned):
            list(reader.compose(r1, r2)())

    def test_reader_compose_unchecked_truncates(self):
        from paddle1_tpu import reader
        r1 = lambda: iter([1, 2, 3])
        r2 = lambda: iter([10, 20])
        out = list(reader.compose(r1, r2, check_alignment=False)())
        self.assertEqual(out, [(1, 10), (2, 20)])

    def test_reader_compose_aligned_ok(self):
        from paddle1_tpu import reader
        r1 = lambda: iter([(1, 2), (3, 4)])
        r2 = lambda: iter([10, 20])
        out = list(reader.compose(r1, r2)())
        self.assertEqual(out, [(1, 2, 10), (3, 4, 20)])

    def test_ps_frame_hmac_rejects_unauthenticated(self):
        import os
        from paddle1_tpu.distributed import ps, ps_server
        os.environ["PADDLE_PS_SECRET"] = "topsecret"
        try:
            srv = ps_server.TableServer(ps.SparseTable(dim=4)).start()
            good = ps_server.RemoteTable(srv.endpoint)
            self.assertTrue(good.ping())
            # a frame with a forged tag must be dropped BEFORE the server
            # unpickles it: the connection closes with no reply
            import pickle
            import socket as socketlib
            def _drain(sock):
                out = b""
                while True:
                    b_ = sock.recv(4096)
                    if not b_:
                        return out
                    out += b_

            payload = pickle.dumps(("ping", None))
            raw = socketlib.create_connection(
                (srv.host, srv.port), timeout=5.0)
            raw.sendall(ps_server._HDR.pack(1, len(payload)) +
                        b"\x00" * ps_server._TAG_LEN + payload)
            reply = _drain(raw)  # err frame explaining, then close
            self.assertIn(b"HMAC", reply)
            self.assertNotIn(b"pong", reply)  # the op never executed
            raw.close()
            # an UNTAGGED frame against a secret-bearing server is a loud
            # drop too (flag byte prevents the read-deadlock)
            raw2 = socketlib.create_connection(
                (srv.host, srv.port), timeout=5.0)
            raw2.sendall(ps_server._HDR.pack(0, len(payload)) + payload)
            reply2 = _drain(raw2)
            self.assertIn(b"PADDLE_PS_SECRET", reply2)
            self.assertNotIn(b"pong", reply2)
            raw2.close()
            self.assertTrue(good.ping())  # authed session unaffected
            good.shutdown_server()
        finally:
            os.environ.pop("PADDLE_PS_SECRET", None)

    def test_engine_place_rejects_silent_spec_drop(self):
        """A 1-D leaf that would drop a sharded batch-spec axis under
        grad_accum errors at placement, not deep inside jit."""
        import paddle1_tpu as paddle
        from paddle1_tpu.core.errors import InvalidArgumentError
        from paddle1_tpu.distributed import ParallelEngine
        from paddle1_tpu.nn.layer_common import Linear

        model = Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        loss = lambda m, b: m(b["x"]).mean() + b["w"].mean()
        eng = ParallelEngine(model, opt, loss,
                             degrees={"dp": len(jax.devices())},
                             grad_accum=2)
        bad = {"x": np.zeros((2, 8, 4), np.float32),
               "w": np.zeros((8,), np.float32)}  # missing accum dim
        with self.assertRaises(InvalidArgumentError):
            eng.shard_batch(bad)
        # a 0-d leaf dies inside lax.scan under grad_accum — also caught
        # at placement with the friendly message
        with self.assertRaises(InvalidArgumentError):
            eng.shard_batch({"x": np.zeros((2, 8, 4), np.float32),
                             "s": np.float32(2.0)})
        ok = {"x": np.zeros((2, 8, 4), np.float32),
              "w": np.zeros((2, 8), np.float32)}
        eng.shard_batch(ok)  # placement fine; scalars still replicate

    def test_engine_place_scalar_leaf_still_replicates(self):
        import paddle1_tpu as paddle
        from paddle1_tpu.distributed import ParallelEngine
        from paddle1_tpu.nn.layer_common import Linear
        model = Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        eng = ParallelEngine(model, opt,
                             lambda m, b: m(b["x"]).mean(),
                             degrees={"dp": len(jax.devices())})
        placed = eng.shard_batch({"x": np.zeros((8, 4), np.float32),
                                  "s": np.float32(2.0)})
        self.assertEqual(placed["s"].shape, ())


if __name__ == "__main__":
    unittest.main()


class TestBatchNormTracedStatsWarning(unittest.TestCase):
    """ADVICE r6 medium (nn/functional/norm.py): the silent skip of
    running mean/var updates under jit/shard_map tracing must warn —
    once per buffer — so eval-after-compiled-training divergence has a
    signal."""

    def test_warns_once_per_buffer_under_tracing(self):
        import warnings

        import paddle1_tpu.nn.functional as F

        rm = to_tensor(np.zeros(3, np.float32))
        rv = to_tensor(np.ones(3, np.float32))
        x = np.random.default_rng(0).standard_normal((4, 3)).astype(
            np.float32)

        def f(xx):
            return F.batch_norm(to_tensor(xx), rm, rv, training=True).data

        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            jax.make_jaxpr(f)(x)
        skipped = [r for r in rec if "SKIPPED" in str(r.message)]
        self.assertEqual(len(skipped), 1, [str(r.message) for r in rec])

        # once per buffer: a second trace over the SAME buffers is quiet
        with warnings.catch_warnings(record=True) as rec2:
            warnings.simplefilter("always")
            jax.make_jaxpr(f)(x)
        self.assertFalse([r for r in rec2 if "SKIPPED" in str(r.message)])

        # the dedup contract itself (not trace caching): same buffer
        # quiet, a DIFFERENT buffer still warns
        from paddle1_tpu.nn.functional.norm import warn_traced_stats_skipped
        with warnings.catch_warnings(record=True) as rec2b:
            warnings.simplefilter("always")
            warn_traced_stats_skipped(rm, "batch_norm")
        self.assertFalse([r for r in rec2b if "SKIPPED" in str(r.message)])
        other = to_tensor(np.zeros(3, np.float32))
        with warnings.catch_warnings(record=True) as rec2c:
            warnings.simplefilter("always")
            warn_traced_stats_skipped(other, "batch_norm")
        self.assertEqual(
            1, len([r for r in rec2c if "SKIPPED" in str(r.message)]))

        # ... and eager training still updates the stats silently
        with warnings.catch_warnings(record=True) as rec3:
            warnings.simplefilter("always")
            F.batch_norm(to_tensor(x), rm, rv, training=True)
        self.assertFalse([r for r in rec3 if "SKIPPED" in str(r.message)])
        self.assertGreater(
            float(np.abs(np.asarray(rm.numpy())).max()), 0.0)
