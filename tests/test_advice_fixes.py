"""Regression tests for the ADVICE r1 findings (VERDICT r2 task 7):
QAT-under-jit silent collapse, NMS negative-coordinate category offsets,
box_coder axis semantics, shm create/attach ftruncate discipline, profiler
cross-thread trace state."""

import threading
import unittest

import numpy as np
import jax
import jax.numpy as jnp

from paddle1_tpu.core.tensor import to_tensor


class TestQATUnderJit(unittest.TestCase):
    def test_uncalibrated_activation_quant_passes_through_under_jit(self):
        """An uninited EMA observer inside a jitted/functionalized forward
        must pass activations through, not clamp them to ~0."""
        from paddle1_tpu.nn.layer_common import Linear
        from paddle1_tpu.quantization import QAT

        lin = Linear(8, 8)
        q = QAT()
        model = q.quantize(lin)
        model.eval()

        x = np.random.default_rng(0).standard_normal((4, 8)).astype(
            np.float32)
        params = model.functional_state()

        def fwd(params, x):
            from paddle1_tpu.autograd import engine as ag
            with ag.no_grad(), model.load_functional_state(params):
                return model(to_tensor(x)).data

        out_jit = np.asarray(jax.jit(fwd)(params, x))
        out_eager = np.asarray(fwd(params, x))
        # pre-fix the jitted path quantized with scale=0 → all ~0 outputs
        self.assertGreater(np.abs(out_jit).max(), 1e-3)
        np.testing.assert_allclose(out_jit, out_eager, rtol=1e-5, atol=1e-6)

    def test_calibrated_observer_quantizes_under_jit(self):
        from paddle1_tpu.quantization import FakeQuantMovingAverageAbsMax
        obs = FakeQuantMovingAverageAbsMax(bits=8)
        x = np.linspace(-1, 1, 1000).astype(np.float32)
        obs.train()
        obs(to_tensor(x))  # calibrates scale
        obs.eval()
        params = obs.functional_state()

        def fwd(params, x):
            from paddle1_tpu.autograd import engine as ag
            with ag.no_grad(), obs.load_functional_state(params):
                return obs(to_tensor(x)).data

        out = np.asarray(jax.jit(fwd)(params, x))
        # quantized: at most 2^bits levels, but non-degenerate
        self.assertGreater(np.abs(out).max(), 0.5)
        self.assertLess(len(np.unique(np.round(out, 5))), 260)


class TestNMSNegativeCoords(unittest.TestCase):
    def test_category_offset_with_negative_boxes(self):
        """Identical overlapping boxes in different categories must BOTH
        survive even when coordinates are negative (the max+1 offset
        collapsed categories then)."""
        from paddle1_tpu.vision import ops as V
        boxes = np.array([[-50, -50, -40, -40],
                          [-50, -50, -40, -40]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1], np.int32)
        keep = V.nms(to_tensor(boxes), 0.5, to_tensor(scores),
                     category_idxs=to_tensor(cats))
        self.assertEqual(sorted(np.asarray(keep.numpy()).tolist()), [0, 1])

    def test_same_category_still_suppressed(self):
        from paddle1_tpu.vision import ops as V
        boxes = np.array([[-50, -50, -40, -40],
                          [-50, -50, -40, -40]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 0], np.int32)
        keep = V.nms(to_tensor(boxes), 0.5, to_tensor(scores),
                     category_idxs=to_tensor(cats))
        self.assertEqual(np.asarray(keep.numpy()).tolist(), [0])


class TestBoxCoderAxis(unittest.TestCase):
    def _roundtrip(self, axis):
        from paddle1_tpu.vision import ops as V
        rng = np.random.default_rng(0)
        m = 3
        prior = np.abs(rng.standard_normal((m, 4))).astype(np.float32)
        prior[:, 2:] = prior[:, :2] + 1.0 + prior[:, 2:]
        # encode m targets against m priors → [m, m, 4]; diagonal is each
        # target vs its own prior
        target = prior + 0.1
        enc = np.asarray(V.box_coder(to_tensor(prior), None,
                                     to_tensor(target),
                                     code_type="encode_center_size").numpy())
        self.assertEqual(enc.shape, (m, m, 4))
        # decode with target [N=m, M=m, 4]
        dec = np.asarray(V.box_coder(
            to_tensor(prior), None, to_tensor(enc),
            code_type="decode_center_size", axis=axis).numpy())
        return target, enc, dec

    def test_axis0_roundtrip_diagonal(self):
        target, enc, dec = self._roundtrip(axis=0)
        # axis=0: prior aligns with dim 1 → dec[i, i] recovers target[i]
        for i in range(3):
            np.testing.assert_allclose(dec[i, i], target[i], rtol=1e-5,
                                       atol=1e-5)

    def test_axis1_differs_from_axis0(self):
        from paddle1_tpu.vision import ops as V
        rng = np.random.default_rng(1)
        m = 3
        prior = np.abs(rng.standard_normal((m, 4))).astype(np.float32)
        prior[:, 2:] = prior[:, :2] + 1.0 + prior[:, 2:]
        deltas = rng.standard_normal((m, m, 4)).astype(np.float32) * 0.1
        d0 = np.asarray(V.box_coder(to_tensor(prior), None,
                                    to_tensor(deltas),
                                    code_type="decode_center_size",
                                    axis=0).numpy())
        d1 = np.asarray(V.box_coder(to_tensor(prior), None,
                                    to_tensor(deltas),
                                    code_type="decode_center_size",
                                    axis=1).numpy())
        self.assertEqual(d0.shape, d1.shape)
        self.assertFalse(np.allclose(d0, d1))
        # axis=1 on transposed deltas == transpose of axis=0
        d1t = np.asarray(V.box_coder(
            to_tensor(prior), None,
            to_tensor(np.swapaxes(deltas, 0, 1).copy()),
            code_type="decode_center_size", axis=1).numpy())
        np.testing.assert_allclose(np.swapaxes(d1t, 0, 1), d0, rtol=1e-5,
                                   atol=1e-5)


class TestShmDiscipline(unittest.TestCase):
    def test_attach_existing_does_not_resize(self):
        from paddle1_tpu.core import native
        if not native.available():
            self.skipTest("native lib unavailable")
        name = "/p1t_test_resize"
        lib = native._load()
        lib.shm_arena_unlink(name.encode())
        a = native.ShmArena(name, 1 << 16)
        try:
            # a second create must ATTACH at the existing size, never
            # ftruncate an arena another process already mapped
            b = native.ShmArena(name, 1 << 14)  # smaller request: ok
            self.assertEqual(a.size, b.size)
            off = lib.shm_alloc(a._base, 100)
            self.assertGreater(off, 0)
        finally:
            lib.shm_arena_unlink(name.encode())

    def test_concurrent_alloc_no_overlap(self):
        from paddle1_tpu.core import native
        if not native.available():
            self.skipTest("native lib unavailable")
        name = "/p1t_test_race"
        lib = native._load()
        lib.shm_arena_unlink(name.encode())
        arena = native.ShmArena(name, 1 << 20)
        offsets = []
        lock = threading.Lock()

        def worker():
            got = []
            for _ in range(200):
                off = lib.shm_alloc(arena._base, 64)
                if off:
                    got.append(off)
            with lock:
                offsets.extend(got)

        ts = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        try:
            self.assertEqual(len(offsets), len(set(offsets)))
        finally:
            lib.shm_arena_unlink(name.encode())


class TestProfilerCrossThread(unittest.TestCase):
    def test_stop_on_other_thread_sees_trace_state(self):
        import paddle1_tpu.profiler as prof
        # no real device trace (log_dir None keeps jax out of it); assert
        # the module-global state is visible across threads
        prof._trace_dir = "/tmp/fake_dir_sentinel"
        seen = {}

        def other():
            seen["dir"] = prof._trace_dir

        t = threading.Thread(target=other)
        t.start()
        t.join()
        prof._trace_dir = None
        self.assertEqual(seen["dir"], "/tmp/fake_dir_sentinel")


if __name__ == "__main__":
    unittest.main()
